#!/usr/bin/env python
"""Headline benchmark: PPO env-steps/sec/chip (north-star metric #1,
BASELINE.json / SURVEY.md §6).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no number for this metric (BASELINE.json
``published = {}``), so ``vs_baseline`` is reported against the first
recorded value of OUR implementation (BENCH_BASELINE_VALUE below, set from
round 1); 1.0 means parity with that record. When the run's platform or
measurement method differs from the record's, ``vs_baseline`` is null —
the ratio would not be apples-to-apples (ADVICE r5).

Runs the config-1 workload (PPO-MLP, 64-GPU cluster, synthetic Poisson
trace — SURVEY.md §0) scaled to fill one chip: the fused rollout+update
train step is one jitted XLA program, so steps/sec measures the whole
RL loop, not just env stepping.

TPU expected; if the TPU tunnel is unhealthy (it hangs JAX init on this
machine) we detect that with a subprocess probe and fall back to CPU,
flagging the platform in the JSON line.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time

# Recorded baseline under the CURRENT method: round 5's fused-scan CPU
# number (BENCH_r05.json, 2026-07-31, median-of-7, noisy: false) — the
# first clean artifact measured the way this bench measures today, so
# BENCH_r06+ vs_baseline compares like with like (VERDICT r5 weak #1 /
# ADVICE #1). Historical record, different method AND platform — NOT
# comparable, retained for the log only: round 1 (2026-07-29) read
# 67,931,471.7 env-steps/s/chip on TPU v5 lite with method
# "per-dispatch" (k host-loop dispatches per repeat; rounds 1-4 timed
# ~3 ms bursts through the tunnel and their 8x min-max spreads were
# dispatch jitter, not chip variance). When the first fused-scan TPU
# number lands, re-baseline again to (tpu, fused-scan) the same way.
BENCH_BASELINE_VALUE: float | None = 26_099.6
BENCH_BASELINE_PLATFORM = "cpu"
BENCH_BASELINE_METHOD = "fused-scan"
BENCH_METHOD = "fused-scan"


def tpu_healthy(timeout_s: float = 75.0, attempts: int = 3) -> bool:
    """The axon TPU tunnel hangs JAX init when unhealthy — probe in a
    subprocess so we can time out and fall back. One probe can also time
    out spuriously when the host is briefly loaded (measured: a parallel
    pytest run pushed JAX init past 75s on the 1-core rig and the bench
    silently recorded a CPU number), so retry before concluding the
    tunnel is down — but with an ESCALATING timeout (short first probe),
    so a genuinely dead tunnel costs ~30s + retries, not attempts × the
    full window (ADVICE r3: 3 × 75s stalled a dead-tunnel bench ~225s)."""
    timeouts = [min(30.0, timeout_s)] + [timeout_s] * max(attempts - 1, 0)
    for t in timeouts:
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d=jax.devices(); print(d[0].platform)"],
                capture_output=True, text=True, timeout=t)
            # require the probe to actually SEE the TPU: a jax that falls
            # back to CPU exits 0 too, and treating that as healthy would
            # re-import jax under the tunnel sitecustomize with no timeout
            # guard (the exact hang the probe exists to avoid)
            if r.returncode == 0 and r.stdout.strip() == "tpu":
                return True
            if r.returncode == 0:
                # fast clean exit WITHOUT the chip: jax initialized some
                # other platform — the tunnel is conclusively down, and
                # retrying cannot change that (only hangs are ambiguous)
                return False
        except subprocess.TimeoutExpired:
            pass
    return False


def cpu_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="bench.py")
    p.add_argument("--cpu", action="store_true",
                   help="skip the TPU probe and bench the CPU backend")
    # minibatch-geometry lever (the tentpole of ISSUE 2): the update
    # phase dominates the fused step, so its geometry is part of the
    # benchmarked config. Defaults reproduce the recorded 2x8 workload;
    # --sweep points at a profile_breakdown --sweep-minibatch artifact
    # and benches its best geometry, so the headline number reflects the
    # lever. The geometry is recorded in the output JSON either way.
    p.add_argument("--n-epochs", type=int, default=2)
    p.add_argument("--n-minibatches", type=int, default=8)
    p.add_argument("--minibatch-size", type=int, default=None)
    p.add_argument("--sweep", default=None, metavar="SWEEP_JSON",
                   help="take the update geometry from this ranked "
                        "profile_breakdown --sweep-minibatch artifact "
                        "(its 'best' entry; explicit geometry flags are "
                        "refused alongside it)")
    p.add_argument("--mesh", default="off", metavar="off|auto|PxDxM",
                   help="bench the rule-sharded build (partition-rule "
                        "engine, parallel.sharding) instead of the plain "
                        "jit; the resolved mesh shape and rule-table "
                        "hash are recorded in the output JSON either "
                        "way")
    p.add_argument("--async", dest="async_run", action="store_true",
                   help="bench the overlapped actor-learner engine "
                        "against the sync per-iteration loop on the same "
                        "workload (2 forced CPU devices on the fallback "
                        "platform; reports measured speedup plus the "
                        "phase-time overlap ceiling)")
    p.add_argument("--staleness-bound", type=int, default=1,
                   help="staleness bound for the --async measurement; "
                        "bounds >= 4 want --correction vtrace")
    p.add_argument("--correction", default="none",
                   choices=["none", "vtrace"],
                   help="with --async: advantage correction for the "
                        "benched engine — 'vtrace' benches the "
                        "importance-corrected deep-staleness pipeline "
                        "(its batched ratio recompute is part of the "
                        "learner phase being measured)")
    return p


def geometry_from_sweep(path: str) -> tuple[int, int]:
    """(n_epochs, n_minibatches) of the ranked sweep artifact's best
    entry. Fails loudly on a file that is not a sweep artifact — silently
    benching the default geometry would mislabel the headline number."""
    with open(path) as f:
        art = json.load(f)
    if art.get("sweep") != "minibatch-geometry" or "best" not in art:
        raise SystemExit(
            f"{path} is not a profile_breakdown --sweep-minibatch "
            f"artifact (missing sweep/best fields)")
    best = art["best"]
    return int(best["n_epochs"]), int(best["n_minibatches"])


def bench_async(cfg, args, platform: str, iters: int) -> None:
    """--async: the overlapped actor-learner engine vs the sync
    per-iteration loop, same workload, same devices. The sync comparator
    is ``Experiment.run`` (per-iteration dispatch), NOT the fused scan —
    the async engine overlaps per-iteration programs, so that is the
    like-for-like baseline. Besides the measured ratio the line reports
    ``projected_overlap_speedup = (R+U)/max(R,U)`` from the engine's own
    phase accounting: on a host with too few cores to actually run the
    two loops in parallel (the 1-core CI rig — and XLA:CPU additionally
    forces serialized dispatch, see async_engine), the measured ratio
    reads ~1.0 and the projection is the honest overlap ceiling."""
    import tempfile

    import jax
    from rlgpuschedule_tpu.async_engine import AsyncRunner
    from rlgpuschedule_tpu.experiment import Experiment

    if args.correction != "none":
        # the deep-staleness pipeline: importance-corrected advantage
        # targets (algos.vtrace) — sync comparator stays uncorrected
        # (the sync loop is on-policy; ratios would be identically 1)
        cfg = dataclasses.replace(
            cfg, ppo=dataclasses.replace(cfg.ppo,
                                         correction=args.correction))
    n_chips = jax.device_count()

    def rate(run, k: int) -> tuple[float, float]:
        t0 = time.perf_counter()
        run(k)
        wall = time.perf_counter() - t0
        return wall, k * steps_iter / wall / n_chips

    sync_cfg = (dataclasses.replace(
        cfg, ppo=dataclasses.replace(cfg.ppo, correction="none"))
        if args.correction != "none" else cfg)
    exp_s = Experiment.build(sync_cfg)
    steps_iter = exp_s.steps_per_iteration
    exp_s.run(iterations=iters)                       # compile + warmup
    cal = min(rate(lambda k: exp_s.run(iterations=k), iters)[0]
              for _ in range(2))
    target_s = 0.5 if platform == "cpu" else 1.5
    iters_rep = max(iters, min(2_000, int(iters * target_s / max(cal, 1e-6))))

    exp_a = Experiment.build(cfg)
    runner = AsyncRunner(exp_a, staleness_bound=args.staleness_bound,
                         queue_capacity=max(2, args.staleness_bound))
    runner.run(iterations=iters)                      # compile + warmup

    repeats = 5
    sync_r = sorted(rate(lambda k: exp_s.run(iterations=k), iters_rep)[1]
                    for _ in range(repeats))
    async_r = sorted(rate(lambda k: runner.run(iterations=k), iters_rep)[1]
                     for _ in range(repeats))
    sync_v, async_v = sync_r[repeats // 2], async_r[repeats // 2]
    # measured occupancy (PR 11's flight recorder): ONE extra traced
    # repeat, untimed — span emission is file IO per iteration, so it
    # stays out of the throughput repeats above. log_every materializes
    # the importance-ratio stats the correction pipeline reports (the
    # timed repeats never sync metrics, so rho would read its 1.0
    # neutral default otherwise)
    from rlgpuschedule_tpu.obs import RunTelemetry
    from rlgpuschedule_tpu.obs.events import read_events
    from rlgpuschedule_tpu.obs.trace import async_overlap_summary
    with tempfile.TemporaryDirectory() as td:
        with RunTelemetry(td, trace=True) as tel:
            runner.run(iterations=min(iters_rep, 200), log_every=10,
                       logger=lambda i, m: None, telemetry=tel)
            events_path = tel.bus.path
        overlap = async_overlap_summary(read_events(events_path))
    info = runner.async_info()
    r_busy, u_busy = info["actor_busy_s"], info["learner_busy_s"]
    ceiling = ((r_busy + u_busy) / max(r_busy, u_busy)
               if max(r_busy, u_busy) > 0 else None)
    print(json.dumps({
        "metric": f"async_actor_learner_speedup[{platform}]",
        "method": "sync-iter-loop-vs-async-engine",
        "staleness_bound": args.staleness_bound,
        "correction": args.correction,
        "groups": runner.groups.describe(),
        "cores": os.cpu_count(),
        "iters_per_repeat": iters_rep,
        "repeats": repeats,
        "sync_env_steps_per_sec_per_chip": round(sync_v, 1),
        "async_env_steps_per_sec_per_chip": round(async_v, 1),
        "speedup": round(async_v / sync_v, 3),
        "actor_busy_s": round(r_busy, 3),
        "learner_busy_s": round(u_busy, 3),
        "projected_overlap_speedup":
            round(ceiling, 3) if ceiling else None,
        "async_overlap_measured": (overlap["async_overlap_measured"]
                                   if overlap else None),
        "overlap_window": overlap,
        "overlap_s": round(info["overlap_s"], 3),
        "staleness_max": info["staleness_max"],
        "importance_ratio_mean": info["importance_ratio_mean"],
        "importance_ratio_max": info["importance_ratio_max"],
        "note": ("projected_overlap_speedup is the phase-time ceiling "
                 "(R+U)/max(R,U); async_overlap_measured is the span-"
                 "timeline occupancy of one traced repeat (1 - idle/"
                 "window). The measured speedup needs enough host cores "
                 "to run both loops concurrently, and on XLA:CPU the "
                 "engine serializes device dispatch"),
    }))


def main() -> None:
    args = build_parser().parse_args()
    # the refusal table is the contract for flag interactions: --mesh is
    # a sync-loop layout and --correction an async-loop knob, so the
    # cross combinations refuse up front instead of silently ignoring
    # one flag (import stays lazy — the CPU re-exec path runs first)
    from rlgpuschedule_tpu.configs import (ModeCombinationError,
                                           validate_mode_combination)
    try:
        validate_mode_combination({
            "async": args.async_run,
            "mesh": args.mesh != "off",
            "vtrace": args.correction == "vtrace",
            "sync": not args.async_run,
        })
    except ModeCombinationError as e:
        raise SystemExit(str(e))
    if args.sweep is not None:
        if args.n_epochs != 2 or args.n_minibatches != 8 \
                or args.minibatch_size is not None:
            raise SystemExit("--sweep supplies the geometry; drop the "
                             "explicit --n-epochs/--n-minibatches/"
                             "--minibatch-size flags")
        args.n_epochs, args.n_minibatches = geometry_from_sweep(args.sweep)
    on_tpu = not args.cpu and tpu_healthy()
    if not on_tpu and os.environ.get("_BENCH_CPU") != "1":
        # re-exec without the TPU-tunnel sitecustomize so jax can init
        # CPU, forwarding the original flags
        env = cpu_env()
        env["_BENCH_CPU"] = "1"
        if args.async_run:
            # the overlap bench wants an actor/learner split even on the
            # CPU fallback: force a 2-virtual-device rig (1 actor [0],
            # 1 learner [1] — the default split)
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=2"
                                ).strip()
        fwd = [a for a in sys.argv[1:] if a != "--cpu"]
        os.execvpe(sys.executable,
                   [sys.executable, __file__, *fwd, "--cpu"], env)

    import jax
    from rlgpuschedule_tpu.algos import PPOConfig
    from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
    from rlgpuschedule_tpu.experiment import Experiment

    platform = jax.devices()[0].platform
    # scale the env batch to the platform: the TPU run is the benchmark;
    # the CPU fallback only proves liveness
    if platform == "cpu":
        n_envs, n_steps, iters = 32, 64, 3
    else:
        n_envs, n_steps, iters = 512, 128, 5
    ppo = PPOConfig(n_steps=n_steps, n_epochs=args.n_epochs,
                    n_minibatches=args.n_minibatches,
                    minibatch_size=args.minibatch_size)
    from rlgpuschedule_tpu.algos import resolve_geometry
    _, n_mb, mb_size = resolve_geometry(ppo.n_epochs, ppo.n_minibatches,
                                        ppo.minibatch_size,
                                        n_steps * n_envs)
    cfg = dataclasses.replace(PPO_MLP_SYNTH64, n_envs=n_envs, ppo=ppo)
    if args.async_run:
        bench_async(cfg, args, platform, iters)
        return
    from rlgpuschedule_tpu.parallel import rule_table_hash, rules_for
    from rlgpuschedule_tpu.train import make_run_mesh
    run_mesh = make_run_mesh(args.mesh, cfg.n_envs)
    exp = Experiment.build(cfg, mesh=run_mesh)
    # layout provenance: two bench JSONs are throughput-comparable only
    # when their layouts were (shape null = plain unsharded jit)
    mesh_record = {
        "shape": ({k: int(v) for k, v in run_mesh.shape.items()}
                  if run_mesh is not None else None),
        "rule_table_hash": rule_table_hash(rules_for(cfg))}
    n_chips = jax.device_count()

    def timed(k: int) -> float:
        # run_fused: ONE on-device lax.scan over k train steps — measures
        # the chip's sustained rate, not per-iteration tunnel-RPC dispatch
        t0 = time.perf_counter()
        jax.block_until_ready(exp.run_fused(k))
        return time.perf_counter() - t0

    timed(iters)                             # compile + warmup (fused)

    # Rounds 1-4 timed a FIXED 5 iterations per repeat — at the recorded
    # throughput that is a ~3 ms region measured through a remote TPU
    # tunnel, so the recorded 8x min-max repeat ranges (VERDICT r4 weak
    # #2) were tunnel/dispatch jitter, not chip variance. Calibrate the
    # repeat length so one repeat spans ~target_s of wall clock (chip
    # compute dominates, per-dispatch jitter amortizes), then sample
    # until the median is stable or the repeat cap is hit.
    target_s = 1.5 if platform != "cpu" else 0.4
    # min over 3 calibration timings: hiccups only ever ADD time, and a
    # single inflated calibration would shrink iters_rep back into the
    # jitter-dominated regime this exists to escape
    cal = max(min(timed(iters) for _ in range(3)), 1e-6)
    iters_rep = max(iters, min(20_000, int(iters * target_s / cal)))
    if iters_rep != iters:
        timed(iters_rep)                     # compile at the repeat size
    min_repeats, max_repeats = 7, 15

    def central_spread(s: list[float], k: int = 5) -> float:
        """Spread of the middle k sorted samples over the median — the
        stop criterion AND the reported noise figure. Min-max over ALL
        samples is monotonically non-decreasing, so one early tunnel
        hiccup would make convergence unreachable and flag a clean run
        noisy; the median-of-repeats estimator the bench reports is
        robust to exactly that hiccup, so its noise figure should be
        too (raw min/max stay in the JSON for honesty)."""
        lo = max((len(s) - k) // 2, 0)
        mid = s[lo:lo + k]
        return (mid[-1] - mid[0]) / s[len(s) // 2]

    samples: list[float] = []
    while True:
        wall = timed(iters_rep)
        samples.append(iters_rep * exp.steps_per_iteration / wall / n_chips)
        s = sorted(samples)
        value = s[len(s) // 2]
        spread = central_spread(s)
        if (len(samples) >= min_repeats and spread < 0.15) \
                or len(samples) >= max_repeats:
            break
    # comparable only when platform AND method match the baseline record;
    # otherwise null — a ratio across either boundary would read as a
    # speedup/regression that is really a measurement change
    comparable = (BENCH_BASELINE_VALUE
                  and platform == BENCH_BASELINE_PLATFORM
                  and BENCH_METHOD == BENCH_BASELINE_METHOD)
    vs = round(value / BENCH_BASELINE_VALUE, 3) if comparable else None
    print(json.dumps({
        "metric": f"ppo_env_steps_per_sec_per_chip[{platform}]",
        "method": BENCH_METHOD,
        # the update geometry is part of the benchmarked config (the
        # ISSUE-2 lever); the recorded baseline's geometry is 2x8
        "geometry": {"n_epochs": ppo.n_epochs, "n_minibatches": n_mb,
                     "minibatch_size": mb_size},
        "mesh": mesh_record,
        "value": round(value, 1),
        "unit": "env-steps/s/chip",
        "vs_baseline": vs,
        "repeats": len(samples),
        "iters_per_repeat": iters_rep,
        "min": round(s[0], 1),
        "max": round(s[-1], 1),
        "spread": round(spread, 3),
        "spread_raw": round((s[-1] - s[0]) / value, 3),
        "noisy": spread > 0.2,
    }))


if __name__ == "__main__":
    main()
