#!/usr/bin/env python
"""Headline benchmark: PPO env-steps/sec/chip (north-star metric #1,
BASELINE.json / SURVEY.md §6).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no number for this metric (BASELINE.json
``published = {}``), so ``vs_baseline`` is reported against the first
recorded value of OUR implementation (BENCH_BASELINE_VALUE below, set from
round 1); 1.0 means parity with that record.

Runs the config-1 workload (PPO-MLP, 64-GPU cluster, synthetic Poisson
trace — SURVEY.md §0) scaled to fill one chip: the fused rollout+update
train step is one jitted XLA program, so steps/sec measures the whole
RL loop, not just env stepping.

TPU expected; if the TPU tunnel is unhealthy (it hangs JAX init on this
machine) we detect that with a subprocess probe and fall back to CPU,
flagging the platform in the JSON line.
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

# First recorded value on the target chip (TPU v5 lite, round 1,
# 2026-07-29): 67.93M env-steps/s/chip for the full fused PPO loop.
BENCH_BASELINE_VALUE: float | None = 67_931_471.7
BENCH_BASELINE_PLATFORM = "tpu"


def tpu_healthy(timeout_s: float = 75.0, attempts: int = 3) -> bool:
    """The axon TPU tunnel hangs JAX init when unhealthy — probe in a
    subprocess so we can time out and fall back. One probe can also time
    out spuriously when the host is briefly loaded (measured: a parallel
    pytest run pushed JAX init past 75s on the 1-core rig and the bench
    silently recorded a CPU number), so retry before concluding the
    tunnel is down — but with an ESCALATING timeout (short first probe),
    so a genuinely dead tunnel costs ~30s + retries, not attempts × the
    full window (ADVICE r3: 3 × 75s stalled a dead-tunnel bench ~225s)."""
    timeouts = [min(30.0, timeout_s)] + [timeout_s] * max(attempts - 1, 0)
    for t in timeouts:
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d=jax.devices(); print(d[0].platform)"],
                capture_output=True, text=True, timeout=t)
            # require the probe to actually SEE the TPU: a jax that falls
            # back to CPU exits 0 too, and treating that as healthy would
            # re-import jax under the tunnel sitecustomize with no timeout
            # guard (the exact hang the probe exists to avoid)
            if r.returncode == 0 and r.stdout.strip() == "tpu":
                return True
            if r.returncode == 0:
                # fast clean exit WITHOUT the chip: jax initialized some
                # other platform — the tunnel is conclusively down, and
                # retrying cannot change that (only hangs are ambiguous)
                return False
        except subprocess.TimeoutExpired:
            pass
    return False


def cpu_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def main() -> None:
    on_tpu = "--cpu" not in sys.argv and tpu_healthy()
    if not on_tpu and os.environ.get("_BENCH_CPU") != "1":
        # re-exec without the TPU-tunnel sitecustomize so jax can init CPU
        env = cpu_env()
        env["_BENCH_CPU"] = "1"
        os.execvpe(sys.executable, [sys.executable, __file__, "--cpu"], env)

    import jax
    from rlgpuschedule_tpu.algos import PPOConfig
    from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
    from rlgpuschedule_tpu.experiment import Experiment

    platform = jax.devices()[0].platform
    # scale the env batch to the platform: the TPU run is the benchmark;
    # the CPU fallback only proves liveness
    if platform == "cpu":
        n_envs, n_steps, iters = 32, 64, 3
    else:
        n_envs, n_steps, iters = 512, 128, 5
    cfg = dataclasses.replace(
        PPO_MLP_SYNTH64, n_envs=n_envs,
        ppo=PPOConfig(n_steps=n_steps, n_epochs=2, n_minibatches=8))
    exp = Experiment.build(cfg)
    exp.run(iterations=2)                    # compile + warmup
    # One 5-iteration timing swings 2x run-to-run through the TPU tunnel
    # (VERDICT r2 weak #1: judge re-runs spanned 31.9M-67.2M steps/s on
    # identical code). Take the MEDIAN of n_repeats independent timings and
    # report the spread so a single hiccup can't halve the recorded number.
    n_repeats = 7
    n_chips = jax.device_count()
    samples = []
    for _ in range(n_repeats):
        t0 = time.perf_counter()
        exp.run(iterations=iters)
        wall = time.perf_counter() - t0
        samples.append(iters * exp.steps_per_iteration / wall / n_chips)
    samples.sort()
    value = samples[len(samples) // 2]
    spread = (samples[-1] - samples[0]) / value
    vs = (value / BENCH_BASELINE_VALUE
          if BENCH_BASELINE_VALUE and platform == BENCH_BASELINE_PLATFORM
          else 1.0)
    print(json.dumps({
        "metric": f"ppo_env_steps_per_sec_per_chip[{platform}]",
        "value": round(value, 1),
        "unit": "env-steps/s/chip",
        "vs_baseline": round(vs, 3),
        "repeats": n_repeats,
        "min": round(samples[0], 1),
        "max": round(samples[-1], 1),
        "spread": round(spread, 3),
        "noisy": spread > 0.2,
    }))


if __name__ == "__main__":
    main()
