#!/usr/bin/env python
"""Headline benchmark: PPO env-steps/sec/chip (north-star metric #1,
BASELINE.json / SURVEY.md §6).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no number for this metric (BASELINE.json
``published = {}``), so ``vs_baseline`` is reported against the first
recorded value of OUR implementation (BENCH_BASELINE_VALUE below, set from
round 1); 1.0 means parity with that record.

Runs the config-1 workload (PPO-MLP, 64-GPU cluster, synthetic Poisson
trace — SURVEY.md §0) scaled to fill one chip: the fused rollout+update
train step is one jitted XLA program, so steps/sec measures the whole
RL loop, not just env stepping.

TPU expected; if the TPU tunnel is unhealthy (it hangs JAX init on this
machine) we detect that with a subprocess probe and fall back to CPU,
flagging the platform in the JSON line.
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

# First recorded value on the target chip (TPU v5 lite, round 1,
# 2026-07-29): 67.93M env-steps/s/chip for the full fused PPO loop.
BENCH_BASELINE_VALUE: float | None = 67_931_471.7
BENCH_BASELINE_PLATFORM = "tpu"


def tpu_healthy(timeout_s: float = 75.0) -> bool:
    """The axon TPU tunnel hangs JAX init when unhealthy — probe in a
    subprocess so we can time out and fall back."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def cpu_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def main() -> None:
    on_tpu = "--cpu" not in sys.argv and tpu_healthy()
    if not on_tpu and os.environ.get("_BENCH_CPU") != "1":
        # re-exec without the TPU-tunnel sitecustomize so jax can init CPU
        env = cpu_env()
        env["_BENCH_CPU"] = "1"
        os.execvpe(sys.executable, [sys.executable, __file__, "--cpu"], env)

    import jax
    from rlgpuschedule_tpu.algos import PPOConfig
    from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
    from rlgpuschedule_tpu.experiment import Experiment

    platform = jax.devices()[0].platform
    # scale the env batch to the platform: the TPU run is the benchmark;
    # the CPU fallback only proves liveness
    if platform == "cpu":
        n_envs, n_steps, iters = 32, 64, 3
    else:
        n_envs, n_steps, iters = 512, 128, 5
    cfg = dataclasses.replace(
        PPO_MLP_SYNTH64, n_envs=n_envs,
        ppo=PPOConfig(n_steps=n_steps, n_epochs=2, n_minibatches=8))
    exp = Experiment.build(cfg)
    exp.run(iterations=1)                    # compile + warmup
    t0 = time.time()
    exp.run(iterations=iters)
    wall = time.time() - t0
    steps_per_sec = iters * exp.steps_per_iteration / wall
    n_chips = jax.device_count()
    value = steps_per_sec / n_chips
    vs = (value / BENCH_BASELINE_VALUE
          if BENCH_BASELINE_VALUE and platform == BENCH_BASELINE_PLATFORM
          else 1.0)
    print(json.dumps({
        "metric": f"ppo_env_steps_per_sec_per_chip[{platform}]",
        "value": round(value, 1),
        "unit": "env-steps/s/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
