#!/usr/bin/env bash
# CI pipeline: lint stage (PR 3), the observability smoke stage
# (ISSUE 5: a telemetry-instrumented 3-iteration run must produce a
# reportable merged timeline with zero post-warmup alarms), then the
# tier-1 pytest gate.
#
# Stage 1 — lint (fast, no JAX import for jsan's AST pass):
#   1a. jsan: the repo's JAX-pitfall + concurrency static analyzer.
#       Scope is the package + the top-level entry scripts. tests/ is
#       NOT scanned: single-shot jit(lambda) in a test body is benign
#       (each test compiles once by design) and tests/fixtures/ holds
#       jsan's own deliberately-bad corpus. Baseline:
#       jsan_baseline.json (EMPTY since PR 15), run with --fail-stale
#       so the baseline can only shrink. Both invocations share a
#       --cache dir (PR 18) keyed on (file sha1, analyzer-source sha1):
#       the SARIF pass replays the text pass's per-file results instead
#       of re-analyzing, and repeat CI runs skip unchanged files
#       entirely (cross-file rules always re-run). A second jsan
#       invocation emits SARIF and sanity-checks its shape, including
#       the PR-18 column regions — the code-scanning upload must never
#       receive a malformed document.
#   1b. ruff + mypy at the pyproject.toml config, pinned there
#       (ruff==0.6.9, mypy==1.11.2). Both gate on availability: the
#       hermetic CI image does not ship them, and the lint stage must
#       not mutate the environment by installing things — when absent
#       they are SKIPPED LOUDLY, not failed. When PRESENT, the version
#       must match the pin exactly: a drifted linter silently applies
#       different rules, which is worse than no linter.
#
# Stage 2 — the tier-1 gate (ROADMAP.md), split in two: the main pass
#   excludes the multihost_spawn subset, which then runs SERIALLY after
#   it. The spawn tests fork real jax.distributed gangs whose gloo
#   collective rendezvous (~30s window) races per-rank XLA compile —
#   on a small rig, running them next to the rest of the suite's CPU
#   load is the reproducible way to flake them. The ROADMAP one-liner
#   (everything in one pass) stays the driver's acceptance command;
#   this split is strictly more conservative.
set -euo pipefail
cd "$(dirname "$0")"

echo "=== lint 1/3: jsan (python -m rlgpuschedule_tpu.analysis) ==="
JSAN_CACHE="${JSAN_CACHE:-.jsan_cache}"
python -m rlgpuschedule_tpu.analysis \
    rlgpuschedule_tpu bench.py __graft_entry__.py \
    --baseline jsan_baseline.json --fail-stale --cache "$JSAN_CACHE"

echo "=== lint 1/3b: jsan SARIF gate (warm --cache replay) ==="
JSAN_SARIF=$(mktemp /tmp/ci_jsan.XXXXXX.sarif)
python -m rlgpuschedule_tpu.analysis \
    rlgpuschedule_tpu bench.py __graft_entry__.py \
    --baseline jsan_baseline.json --format sarif \
    --cache "$JSAN_CACHE" > "$JSAN_SARIF"
python - "$JSAN_SARIF" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["version"] == "2.1.0", doc.get("version")
assert "sarif-schema-2.1.0" in doc["$schema"]
run, = doc["runs"]
assert run["tool"]["driver"]["name"] == "jsan"
rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
for res in run["results"]:
    assert res["ruleId"] in rule_ids, res["ruleId"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"]
    region = loc["region"]
    assert region["startLine"] >= 1 and region["startColumn"] >= 1
    assert region["endLine"] >= region["startLine"]
    assert region["endColumn"] > region["startColumn"]  # exclusive end
print(f"sarif ok: {len(run['results'])} result(s), "
      f"{len(rule_ids)} rules declared, column regions present")
PY
rm -f "$JSAN_SARIF"

echo "=== lint 2/3: ruff ==="
if command -v ruff >/dev/null 2>&1; then
    want=$(sed -n 's/^#   ruff==//p' pyproject.toml)
    have=$(ruff --version | awk '{print $2}')
    if [ "$have" != "$want" ]; then
        echo "FAIL: ruff $have installed but pyproject.toml pins ruff==$want" >&2
        exit 1
    fi
    ruff check rlgpuschedule_tpu tests
else
    echo "SKIP: ruff not installed (pinned ruff==0.6.9 in pyproject.toml)"
fi

echo "=== lint 3/3: mypy ==="
if command -v mypy >/dev/null 2>&1; then
    want=$(sed -n 's/^#   mypy==//p' pyproject.toml)
    have=$(mypy --version | awk '{print $2}')
    if [ "$have" != "$want" ]; then
        echo "FAIL: mypy $have installed but pyproject.toml pins mypy==$want" >&2
        exit 1
    fi
    mypy
else
    echo "SKIP: mypy not installed (pinned mypy==1.11.2 in pyproject.toml)"
fi

echo "=== smoke: observability (3-iter CPU run + merged-timeline report) ==="
# A short geometry-stable training run with the full telemetry layer on
# must (a) produce a timeline the report CLI accepts and (b) fire ZERO
# recompile/transfer alarms after warmup — --strict-alarms asserts both
# in one exit code (ISSUE 5 acceptance).
OBS_DIR=$(mktemp -d /tmp/ci_obs.XXXXXX)
ASYNC_OBS_DIR=$(mktemp -d /tmp/ci_async_obs.XXXXXX)
VTRACE_OBS_DIR=$(mktemp -d /tmp/ci_vtrace_obs.XXXXXX)
SERVE_OBS_DIR=$(mktemp -d /tmp/ci_serve_obs.XXXXXX)
SOAK_OBS_DIR=$(mktemp -d /tmp/ci_soak_obs.XXXXXX)
CHAOS_SOAK_OBS_DIR=$(mktemp -d /tmp/ci_chaos_soak_obs.XXXXXX)
CHAOS_FLOG_DIR=$(mktemp -d /tmp/ci_chaos_flog.XXXXXX)
CHAOS_JSON=$(mktemp /tmp/ci_chaos.XXXXXX.json)
SERVE_JSON=$(mktemp /tmp/ci_serve.XXXXXX.json)
SOAK_JSON=$(mktemp /tmp/ci_soak.XXXXXX.json)
CHAOS_SOAK_JSON=$(mktemp /tmp/ci_chaos_soak.XXXXXX.json)
TRACE_JSON=$(mktemp /tmp/ci_trace.XXXXXX.json)
HOST_PATH_JSON=$(mktemp /tmp/ci_host_path.XXXXXX.json)
trap 'rm -rf "$OBS_DIR" "$ASYNC_OBS_DIR" "$VTRACE_OBS_DIR" \
    "$SERVE_OBS_DIR" "$SOAK_OBS_DIR" "$CHAOS_SOAK_OBS_DIR" \
    "$CHAOS_FLOG_DIR" \
    "$CHAOS_JSON" "$SERVE_JSON" "$SOAK_JSON" "$CHAOS_SOAK_JSON" \
    "$TRACE_JSON" "$HOST_PATH_JSON"' EXIT
# --trace-spans rides along (ISSUE 11): the flight recorder must not
# disturb the strict-alarms gate, and the exported Chrome trace must be
# Perfetto-valid (validated per layer below)
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --iterations 3 --n-envs 4 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 --log-every 1 \
    --obs-dir "$OBS_DIR" --alarms --trace-spans > /dev/null
# Perfetto-validity gate, shared by the sync/async/serve layers: the
# Chrome trace must load as JSON, every (pid,tid) track must carry
# strictly paired B/E events, at least one span must nest (depth >= 2),
# and a clean run must contain no torn spans.
validate_trace() {  # $1 = trace json path, $2 = layer label
python - "$1" "$2" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))   # valid JSON or this line throws
depth, max_depth = {}, 0
for e in doc["traceEvents"]:
    if e["ph"] not in ("B", "E"):
        continue
    key = (e["pid"], e["tid"])
    depth[key] = depth.get(key, 0) + (1 if e["ph"] == "B" else -1)
    assert depth[key] >= 0, f"unpaired E on {key}"
    max_depth = max(max_depth, depth[key])
assert not any(depth.values()), f"unpaired B: {depth}"
assert not any(e.get("args", {}).get("torn")
               for e in doc["traceEvents"]), "torn spans in a clean run"
assert max_depth >= 2, f"expected nested spans, max depth {max_depth}"
print(f"trace smoke ok ({sys.argv[2]}): "
      f"{len(doc['traceEvents'])} events, max span depth {max_depth}")
EOF
}
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$OBS_DIR" --strict-alarms \
    --trace-out "$TRACE_JSON" > /dev/null
validate_trace "$TRACE_JSON" sync

echo "=== smoke: async actor-learner (3-iter overlapped run, 2 CPU devices) ==="
# ISSUE 9 acceptance: a telemetry-instrumented train --async run on a
# 2-virtual-device CPU rig must (a) pass the same strict-alarms gate as
# the sync smoke (zero post-warmup recompile/transfer alarms — the
# engine AOT-compiles both programs up front), and (b) leave a run_end
# event carrying nonzero actor AND learner phase seconds plus the
# engine's overlap counter — proof the split actually ran both loops.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --async --staleness-bound 1 \
    --iterations 3 --n-envs 4 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 --log-every 1 \
    --obs-dir "$ASYNC_OBS_DIR" --alarms --trace-spans > /dev/null
# ISSUE 11 acceptance: the traced async run exports a Perfetto-valid
# trace AND the report upgrades the overlap headline from the phase-time
# projection to measured occupancy (async_overlap_measured)
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$ASYNC_OBS_DIR" --strict-alarms \
    --trace-out "$TRACE_JSON" | tee /tmp/_async_report.log
grep -q "async_overlap_measured" /tmp/_async_report.log
validate_trace "$TRACE_JSON" async
python - "$ASYNC_OBS_DIR" <<'EOF'
import sys
from rlgpuschedule_tpu.obs import merge_dir
from rlgpuschedule_tpu.obs.trace import SPAN_BEGIN, async_overlap_summary
events = merge_dir(sys.argv[1])
end = next(e for e in events if e["kind"] == "run_end")
ph = end["phase_seconds"]
assert ph.get("actor", 0) > 0 and ph.get("learner", 0) > 0, ph
assert "async_overlap_s" in end and "async_staleness_max" in end, end
assert not [e for e in events if e["kind"] == "recompile"], "recompiles"
# the actor thread and the learner (caller) thread must land on
# DISTINCT tracks — that is what makes the occupancy math meaningful
begins = [e for e in events if e["kind"] == SPAN_BEGIN]
tids = {e["tid"] for e in begins if e["span"] in ("actor", "learner")}
assert len(tids) == 2, f"actor/learner share a track: {tids}"
occ = async_overlap_summary(events)
assert occ is not None, "no actor/learner spans in the traced async run"
measured = occ["async_overlap_measured"]
assert 0 < measured <= 1, occ
print("async smoke ok:", {"actor_s": round(ph["actor"], 3),
                          "learner_s": round(ph["learner"], 3),
                          "overlap_s": round(end["async_overlap_s"], 3),
                          "overlap_measured": round(measured, 3),
                          "staleness_max": end["async_staleness_max"]})
EOF

echo "=== smoke: deep-staleness V-trace (bound=4 overlapped run, 2 CPU devices) ==="
# ISSUE 12 acceptance: the off-policy-corrected engine must run the
# trajectory queue DEEP (staleness bound 4) under the same strict-alarms
# gate as the bound-1 smoke, and the run_end event must carry the
# importance-ratio gauge pair with the staleness counter above 1 — proof
# the V-trace ratio recompute executed against genuinely stale batches
# (the gauges feed from logged metrics, hence --log-every 1).
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --async --staleness-bound 4 --correction vtrace \
    --iterations 6 --n-envs 4 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 --log-every 1 \
    --obs-dir "$VTRACE_OBS_DIR" --alarms > /dev/null
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$VTRACE_OBS_DIR" \
    --strict-alarms > /dev/null
python - "$VTRACE_OBS_DIR" <<'EOF'
import math, sys
from rlgpuschedule_tpu.obs import merge_dir
events = merge_dir(sys.argv[1])
end = next(e for e in events if e["kind"] == "run_end")
for k in ("async_importance_ratio_mean", "async_importance_ratio_max"):
    assert k in end and math.isfinite(end[k]) and end[k] > 0, \
        (k, end.get(k))
assert end["async_staleness_max"] >= 1, end["async_staleness_max"]
assert not [e for e in events if e["kind"] == "recompile"], "recompiles"
print("vtrace smoke ok:", {
    "rho_mean": round(end["async_importance_ratio_mean"], 4),
    "rho_max": round(end["async_importance_ratio_max"], 4),
    "staleness_max": end["async_staleness_max"]})
EOF

echo "=== smoke: chaos matrix (2 regimes x policy+SJF, CPU) ==="
# ISSUE 6 acceptance: a tiny evaluate --chaos matrix must exit 0, keep
# the no-jobs-lost conservation contract, and carry per-regime
# degradation in its JSON (the satellite's chaos smoke stage)
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.evaluate --config ppo-mlp-synth64 \
    --chaos --chaos-regimes sporadic --chaos-baselines sjf \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 --window-jobs 16 \
    --queue-len 4 --horizon 256 --max-steps 256 > "$CHAOS_JSON"
python - "$CHAOS_JSON" <<'EOF'
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["jobs_lost"] == 0, f"jobs lost under faults: {rep['jobs_lost']}"
assert set(rep["regimes"]) == {"none", "sporadic"}, rep["regimes"].keys()
for regime, rows in rep["regimes"].items():
    for sched, row in rows.items():
        assert row["degradation"] is not None, (regime, sched)
assert rep["repro"]["chaos_seed"] == 0
print("chaos smoke ok:", {r: round(rows["policy"]["degradation"], 3)
                          for r, rows in rep["regimes"].items()})
EOF

echo "=== smoke: generalization matrix (train --domains -> 2x2 cross table, CPU) ==="
# ISSUE 14 acceptance: a tiny train --domains run plus a clean twin feed
# evaluate --matrix, which must produce the train-regime x eval-regime
# cross table (mixed + clean + SJF rows, none + overload columns) with
# no jobs lost against the DRAWN capacities, degradation in every cell,
# and — under --alarms — zero post-warmup recompiles (one compiled step
# serves the whole domain distribution; strict-alarms is the gate).
MATRIX_OBS_DIR=$(mktemp -d /tmp/ci_matrix_obs.XXXXXX)
MATRIX_CKPT_DIR=$(mktemp -d /tmp/ci_matrix_ckpt.XXXXXX)
MATRIX_CLEAN_DIR=$(mktemp -d /tmp/ci_matrix_clean.XXXXXX)
MATRIX_JSON=$(mktemp /tmp/ci_matrix.XXXXXX.json)
trap 'rm -rf "$OBS_DIR" "$ASYNC_OBS_DIR" "$VTRACE_OBS_DIR" \
    "$SERVE_OBS_DIR" "$SOAK_OBS_DIR" "$CHAOS_SOAK_OBS_DIR" \
    "$CHAOS_FLOG_DIR" \
    "$CHAOS_JSON" "$SERVE_JSON" "$SOAK_JSON" "$CHAOS_SOAK_JSON" \
    "$TRACE_JSON" \
    "$MATRIX_OBS_DIR" "$MATRIX_CKPT_DIR" "$MATRIX_CLEAN_DIR" \
    "$MATRIX_JSON"' EXIT
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --domains mixed \
    --iterations 2 --n-envs 2 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 --log-every 1 \
    --ckpt-dir "$MATRIX_CKPT_DIR" --ckpt-every 1 > /dev/null
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --iterations 2 --n-envs 2 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 --log-every 1 \
    --ckpt-dir "$MATRIX_CLEAN_DIR" --ckpt-every 1 > /dev/null
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.evaluate --config ppo-mlp-synth64 \
    --domains mixed --ckpt-dir "$MATRIX_CKPT_DIR" \
    --matrix --matrix-regimes overload --matrix-baselines sjf \
    --matrix-ckpt clean="$MATRIX_CLEAN_DIR" \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 --window-jobs 16 \
    --queue-len 4 --horizon 256 --max-steps 256 \
    --obs-dir "$MATRIX_OBS_DIR" --alarms > "$MATRIX_JSON"
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$MATRIX_OBS_DIR" \
    --strict-alarms > /dev/null
python - "$MATRIX_JSON" "$MATRIX_OBS_DIR" <<'EOF'
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["jobs_lost"] == 0, f"jobs lost under domains: {rep['jobs_lost']}"
assert set(rep["cells"]) == {"none", "overload"}, rep["cells"].keys()
for regime, rows in rep["cells"].items():
    assert set(rows) == {"mixed", "clean", "sjf"}, (regime, rows.keys())
    for sched, row in rows.items():
        assert row["degradation"] is not None, (regime, sched)
assert rep["domain_stats"]["overload"]["mean_load"] > 1.5
assert rep["repro"]["matrix_seed"] == 0
assert rep["repro"]["matrix_ckpts"], rep["repro"]
from rlgpuschedule_tpu.obs import read_events
events = read_events(sys.argv[2] + "/events.matrix.jsonl")
cells = [e for e in events if e["kind"] == "domain_cell"]
assert len(cells) == 6, f"expected 2 regimes x 3 rows, got {len(cells)}"
prom = open(sys.argv[2] + "/metrics.prom").read()
assert "matrix_overload_mixed_degradation" in prom
print("matrix smoke ok:", {f"{r}/{s}": round(row["degradation"], 3)
                           for r, rows in rep["cells"].items()
                           for s, row in rows.items()})
EOF

echo "=== smoke: serving (bench + fleet replay, CPU) ==="
# ISSUE 7 acceptance: a short serve --bench must report p50/p99 decision
# latency and nonzero decisions/s with ZERO post-warmup recompiles
# across >= 3 distinct request sizes in one bucket, the fleet replay
# must complete, and the live scrape endpoint must answer with a
# well-formed Prometheus exposition (the CLI self-scrapes and records
# the verdict in its JSON).
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
    --bench --fleet 2 --bucket 8 --rounds 9 --pool-steps 2 \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 --window-jobs 16 \
    --queue-len 4 --horizon 64 --max-steps 96 \
    --obs-dir "$SERVE_OBS_DIR" --trace-spans \
    --metrics-port 0 > "$SERVE_JSON"
# the request lifecycle must land on the flight recorder too:
# serve_batch > arena_seal / (engine) pad > dispatch > scatter
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$SERVE_OBS_DIR" \
    --trace-out "$TRACE_JSON" > /dev/null
validate_trace "$TRACE_JSON" serve
python - "$SERVE_OBS_DIR" <<'EOF'
import sys
from rlgpuschedule_tpu.obs import merge_dir
from rlgpuschedule_tpu.obs.trace import SPAN_BEGIN
names = {e["span"] for e in merge_dir(sys.argv[1])
         if e["kind"] == SPAN_BEGIN}
need = {"serve_batch", "arena_seal", "pad", "dispatch", "scatter"}
assert need <= names, f"missing serve spans: {sorted(need - names)}"
EOF
python - "$SERVE_JSON" <<'EOF'
import json, sys
rep = json.load(open(sys.argv[1]))
b = rep["bench"]
assert b["post_warmup_recompiles"] == 0, b
assert b["decisions_per_s"] > 0 and b["latency_p50_ms"] > 0, b
assert len(set(b["request_sizes"])) >= 3 and b["buckets"] == [8], b
fl = rep["fleet"]
assert fl["n_clusters"] == 2 and fl["decisions"] > 0, fl
assert fl["completion"] > 0, fl
sc = rep["scrape"]
assert sc["well_formed"] and sc["status"] == 200, sc
assert sc["metric_lines"] > 0, sc
assert rep["repro"]["config"] == "ppo-mlp-synth64"
print("serve smoke ok:", {"p50_ms": round(b["latency_p50_ms"], 3),
                          "decisions_per_s": round(b["decisions_per_s"]),
                          "fleet_mean_jct": round(fl["mean_jct"], 1)})
EOF

echo "=== smoke: host-path data plane (arena vs legacy, stub engine) ==="
# ISSUE 17 acceptance: the zero-copy serving data plane. Gates are
# COUNT-BASED only (CI wall clock is noise; the recorded >= 2x
# decisions/s lives in BENCH_r09): the arena arm's steady-state window
# must make ZERO numpy batch-constructor calls and allocate ZERO new
# slabs, every arm must conserve requests exactly (submitted ==
# served + shed) and stay at ZERO post-warmup recompiles, and the
# legacy arm's nonzero allocation count proves the gauge sees through.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
    --host-path --bucket 8 --host-rounds 120 --pool-steps 2 \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 --window-jobs 16 \
    --queue-len 4 --horizon 64 > "$HOST_PATH_JSON"
python - "$HOST_PATH_JSON" <<'PYEOF'
import json, sys
rep = json.load(open(sys.argv[1]))
hp = rep["host_path"]
arms = {a["data_plane"]: a for a in hp["arms"]}
arena, legacy = arms["arena"], arms["legacy"]
assert arena["alloc_calls"] == 0, arena            # zero steady-state
assert arena["steady_state_slab_allocs"] == 0, arena
assert legacy["alloc_calls"] > 0, legacy           # the deleted churn
for arm in hp["arms"]:
    assert arm["conservation_ok"], arm
    assert arm["shed"] == 0, arm
    assert arm["post_warmup_recompiles"] == 0, arm
    assert arm["decisions_per_s"] > 0, arm
assert hp["speedup"] > 0, hp
print("host-path smoke ok:",
      {"arena_allocs": arena["alloc_calls"],
       "legacy_allocs_per_batch": round(legacy["allocs_per_batch"], 1),
       "speedup_inproc": round(hp["speedup_inproc"], 2)})
PYEOF

echo "=== smoke: soak-lite (2 routed engines, deadlines + autoscale, 2 CPU devices) ==="
# ISSUE 13 acceptance: a short multi-engine soak — 2 mesh-resolved
# engines, per-request deadlines (shedding armed), adaptive batching,
# live autoscale advisor — must hold a bounded first-half vs
# second-half p99 drift, keep ZERO post-warmup recompiles PER ENGINE,
# export the shed/autoscale/per-engine series on the scrape surface,
# produce a Perfetto-valid trace with zero torn spans (per-engine
# lanes included), and pass the same strict-alarms report gate as
# every other layer.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
    --engines 2 --soak 6 --rate 150 --deadline-ms 250 \
    --adaptive-wait --autoscale --bucket 8 --pool-steps 2 \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 --window-jobs 16 \
    --queue-len 4 --horizon 64 \
    --obs-dir "$SOAK_OBS_DIR" --trace-spans \
    --metrics-port 0 > "$SOAK_JSON"
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$SOAK_OBS_DIR" \
    --strict-alarms --trace-out "$TRACE_JSON" > /dev/null
validate_trace "$TRACE_JSON" soak-lite
python - "$SOAK_JSON" "$SOAK_OBS_DIR" <<'EOF'
import json, sys
rep = json.load(open(sys.argv[1]))
s = rep["soak"]
assert s["requests"] > 0 and s["served"] > 0, s
# the steady-state contract, per engine — the fleet aggregate can
# hide a single recompiling engine behind a quiet sibling
assert s["per_engine_recompiles"] == [0, 0], s["per_engine_recompiles"]
assert s["post_warmup_recompiles"] == 0, s
assert sum(s["per_engine_rows"]) == s["served"], s
drift = s["p99_drift"]
assert drift is not None and drift < 3.0, f"p99 drift {drift}"
assert 1 <= s["engines_active"] <= 2, s
assert s["serialized_dispatch_cpu"] is True   # honesty bit on this rig
sc = rep["scrape"]
assert sc["well_formed"] and sc["status"] == 200, sc
prom = open(sys.argv[2] + "/metrics.prom").read()
# Bare-name presence checks (serve_shed_total, serve_engines_active,
# serve_autoscale_*) moved to jsan's contract-drift rule, which keeps
# registrations and consumers in lockstep statically. Only the
# per-engine LABEL fanout stays a runtime grep — labels are runtime
# data the static rule cannot see.
for series in ('serve_engine_rows_total{engine="0"}',
               'serve_engine_rows_total{engine="1"}',
               'serve_recompile_alarms_total{engine="0"}',
               'serve_recompile_alarms_total{engine="1"}'):
    assert series in prom, f"missing scrape series: {series}"
print("soak-lite smoke ok:", {
    "requests": s["requests"], "shed": s["shed"],
    "p99_drift": round(drift, 3),
    "engines_active": s["engines_active"],
    "autoscale_resizes": s["autoscale_resizes"],
    "per_engine_rows": s["per_engine_rows"]})
EOF

echo "=== smoke: chaos-soak (engine faults mid-run, HTTP front door, 2 CPU devices) ==="
# ISSUE 16 acceptance: the same routed soak with a seeded fault
# injector killing engine 0 mid-run (two consecutive raises -> eject,
# backoff, blessed re-warm, readmit) and the HTTP front door wrapped
# around the server. The run must hold EXACT conservation
# (submitted == served + shed + failed with failed == 0 — the retry
# hedge absorbs every injected fault), count sheds exactly once
# (registry counter == shed futures observed), keep zero post-warmup
# recompiles per engine, bound the p99 drift, land the full
# eject/readmit/retry lifecycle on the event bus, and prove the drain
# contract on the wire (late submit -> typed refusal, connect refused).
# ISSUE 20 rides along: request-id conservation BY IDENTITY from the
# merged instant stream (every submitted id resolves exactly once as
# served | shed | dispatch_failed), an engine-health slo_burn_alert
# during the fault window with slo_burn_clear + budget recovery after,
# and a single-request timeline reconstruction (report --request) for
# a live sampled id joined against the flight log.
# NOTE: no --autoscale — the chaos soak does not drive the advisor
# loop, and the CLI refuses the combination outright.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
    --engines 2 --soak 6 --rate 150 --deadline-ms 250 \
    --adaptive-wait --bucket 8 --pool-steps 2 \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 --window-jobs 16 \
    --queue-len 4 --horizon 64 \
    --chaos-faults "engine-raise@40:engine=0,engine-raise@40:engine=0" \
    --frontend-port 0 \
    --flight-log "$CHAOS_FLOG_DIR" --flight-capacity 64 \
    --obs-dir "$CHAOS_SOAK_OBS_DIR" --trace-spans \
    --metrics-port 0 > "$CHAOS_SOAK_JSON"
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$CHAOS_SOAK_OBS_DIR" \
    --strict-alarms --trace-out "$TRACE_JSON" > /dev/null
validate_trace "$TRACE_JSON" chaos-soak
python - "$CHAOS_SOAK_JSON" "$CHAOS_SOAK_OBS_DIR" "$CHAOS_FLOG_DIR" <<'EOF'
import json, sys
from rlgpuschedule_tpu.obs import merge_dir
rep = json.load(open(sys.argv[1]))
s = rep["soak"]
# exact conservation: every submitted request resolved or shed, none
# failed (the retry-once hedge absorbed both injected engine faults),
# and the shed counter agrees with the futures actually observed
assert s["conservation_ok"], s
assert s["requests"] == s["served"] + s["shed"], s
assert s["failed"] == 0, s["failure_kinds"]
assert s["registry_shed_total"] == s["shed"], s
assert s["faults_fired"] == 2, s["faults_fired"]
fs = s["fault_stats"]
assert fs["failures"] >= 2, fs
assert fs["ejections"] >= 1, fs
assert fs["readmissions"] >= 1, fs         # backoff elapsed in-run
assert fs["retry_hedges"] >= 2, fs         # every fault hedged away
assert s["per_engine_recompiles"] == [0, 0], s["per_engine_recompiles"]
assert s["post_warmup_recompiles"] == 0, s
drift = s["p99_drift"]
assert drift is None or drift < 3.0, f"p99 drift {drift}"
assert s["shed_rate"] <= 0.5, s["shed_rate"]
# RSS/heap-drift gate (ISSUE 19 satellite): a faulted soak must not
# leak — eject/re-warm/readmit cycles and the retry hedge all recycle
# buffers, so resident set growth over the run stays a few percent
# (None on /proc-less hosts, where the gate degrades to a no-op)
g = s["rss_growth_frac"]
assert g is None or g < 0.15, (
    f"RSS grew {g:.1%} over the chaos soak "
    f"({s['rss_start_bytes']} -> {s['rss_end_bytes']} bytes)")
# the fault lifecycle must be a readable story on the event bus
kinds = {e["kind"] for e in merge_dir(sys.argv[2])}
for k in ("serve_fault", "engine_eject", "engine_readmit",
          "serve_retry"):
    assert k in kinds, f"missing bus event {k!r}: {sorted(kinds)}"
# wire-level drain contract, proven against the live front door
fe = rep["frontend"]
assert fe["decide_status"] == 200 and fe["decide_has_action"], fe
assert fe["drained"] and fe["late_submit"] == "server-closed", fe
assert fe["post_drain_connect"] == "refused", fe
prom = open(sys.argv[2] + "/metrics.prom").read()
# serve_retry_hedges_total / serve_frontend_requests_total presence is
# enforced statically by jsan's contract-drift rule; only the labeled
# per-engine ejection series needs a runtime grep.
assert 'serve_engine_ejections_total{engine="0"}' in prom, \
    "missing scrape series: serve_engine_ejections_total"
for name in ("serve_queue_wait_seconds_bucket", "slo_burn_rate",
             "slo_error_budget_remaining", "slo_burn_alerts_total"):
    assert name in prom, f"missing scrape series: {name}"

# ---- ISSUE 20: request-id conservation BY IDENTITY -----------------
# the count invariant above cannot see a dropped-and-double-served
# pair; ids can. submitted = enqueued + admission-shed (admission
# sheds never reach the queue, so never emit enqueue); resolved =
# served + shed (any reason) + dispatch_failed — exactly once each.
events = merge_dir(sys.argv[2])
pts = [e for e in events if e.get("kind") == "span_point"]
enq = [e["attrs"]["req_id"] for e in pts if e.get("span") == "enqueue"]
served_ids = [r for e in pts if e.get("span") == "served"
              for r in e["attrs"]["req_ids"]]
shed_pts = [(e["attrs"]["req_id"], e["attrs"]["reason"])
            for e in pts if e.get("span") == "shed"]
failed_ids = [r for e in pts if e.get("span") == "dispatch_failed"
              for r in e["attrs"]["req_ids"]]
submitted = enq + [r for r, why in shed_pts if why == "admission"]
resolved = served_ids + failed_ids + [r for r, _ in shed_pts]
assert len(submitted) == len(set(submitted)), "duplicate submit ids"
assert sorted(resolved) == sorted(submitted), (
    f"request-id conservation violated: {len(submitted)} submitted, "
    f"{len(resolved)} resolved, "
    f"symmetric diff {len(set(submitted) ^ set(resolved))}")
# the soak's own futures are a subset (the frontend selfcheck adds a
# couple of front-door requests after the pacing loop)
assert len(submitted) >= s["requests"], (len(submitted), s["requests"])
assert all(i > 0 for i in submitted), "unassigned (0) id leaked"

# ---- ISSUE 20: burn alert during the fault window, recovery after --
faults = [e for e in events if e["kind"] == "serve_fault"]
eh_alerts = [e for e in events if e["kind"] == "slo_burn_alert"
             and e["slo"] == "engine-health"]
eh_clears = [e for e in events if e["kind"] == "slo_burn_clear"
             and e["slo"] == "engine-health"]
assert eh_alerts, "no engine-health slo_burn_alert under injected faults"
assert faults and eh_alerts[0]["mono"] >= faults[0]["mono"], \
    "burn alert predates the first injected fault"
assert eh_alerts[0]["mono"] <= faults[-1]["mono"] + 2.0, \
    "burn alert fired long after the fault window (stale scrape?)"
assert eh_alerts[0]["burns"] and all(
    b >= 1.0 for b in eh_alerts[0]["burns"].values()), eh_alerts[0]
assert eh_clears and eh_clears[-1]["mono"] > eh_alerts[0]["mono"], \
    "burn alert never cleared after the bleeding stopped"
slo = s["slo"]["engine-health"]
assert not slo["alerting"] and slo["alerts_total"] >= 1, slo
assert slo["budget_remaining"] > 0.5, (
    f"engine-health budget did not recover: {slo}")

# ---- ISSUE 20: single-request timeline reconstruction --------------
# a live served id must reconstruct end to end: stages + the flight-log
# shard/row it landed in (report exits 1 if the id appears nowhere)
import subprocess
rid = served_ids[len(served_ids) // 2]
r = subprocess.run(
    [sys.executable, "-m", "rlgpuschedule_tpu.obs.report", sys.argv[2],
     "--request", f"0x{rid:x}", "--flight-log", sys.argv[3]],
    capture_output=True, text=True, timeout=60)
assert r.returncode == 0, (rid, r.stdout, r.stderr)
assert "logged:" in r.stdout, r.stdout
print("chaos-soak smoke ok:", {
    "requests": s["requests"], "shed": s["shed"],
    "faults_fired": s["faults_fired"],
    "ejections": fs["ejections"],
    "readmissions": fs["readmissions"],
    "retry_hedges": fs["retry_hedges"],
    "rss_growth": (None if g is None else round(g, 4)),
    "frontend": fe["post_drain_connect"],
    "ids_conserved": len(submitted),
    "burn_alerts": len(eh_alerts),
    "budget_recovered": round(slo["budget_remaining"], 3),
    "traced_request": f"0x{rid:x}"})
EOF

echo "=== smoke: sharding (rule-mesh train + PBT-on-mesh, 2 CPU devices) ==="
# ISSUE 10 acceptance: a rule-sharded --mesh auto run and a PBT run
# whose population rides the unified mesh's pop axis must both pass the
# strict-alarms gate (zero post-warmup recompiles — the compile-once
# contract of the rule-resolved in/out_shardings), and the train
# summary must carry the mesh shape + rule-table hash provenance.
MESH_OBS_DIR=$(mktemp -d /tmp/ci_mesh_obs.XXXXXX)
PBT_OBS_DIR=$(mktemp -d /tmp/ci_pbt_obs.XXXXXX)
MESH_JSON=$(mktemp /tmp/ci_mesh.XXXXXX.json)
PBT_JSON=$(mktemp /tmp/ci_pbt.XXXXXX.json)
trap 'rm -rf "$OBS_DIR" "$ASYNC_OBS_DIR" "$VTRACE_OBS_DIR" \
    "$SERVE_OBS_DIR" "$SOAK_OBS_DIR" "$CHAOS_SOAK_OBS_DIR" \
    "$CHAOS_FLOG_DIR" \
    "$CHAOS_JSON" "$SERVE_JSON" "$SOAK_JSON" "$CHAOS_SOAK_JSON" \
    "$TRACE_JSON" \
    "$MATRIX_OBS_DIR" "$MATRIX_CKPT_DIR" "$MATRIX_CLEAN_DIR" \
    "$MATRIX_JSON" \
    "$MESH_OBS_DIR" "$PBT_OBS_DIR" "$MESH_JSON" "$PBT_JSON"' EXIT
# JAX_ENABLE_COMPILATION_CACHE=false on BOTH mesh trains: the persistent
# compile cache flakily heap-corrupts (malloc_consolidate / segfault,
# ~25% of runs) when it round-trips a MULTI-device SPMD executable on
# the forced-multi-device CPU backend (jax 0.4.37; single-device
# programs — every other stage here — are unaffected). The mesh smokes
# recompile from scratch each run; ~2 min extra, deterministic green.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    JAX_ENABLE_COMPILATION_CACHE=false \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --mesh auto \
    --iterations 3 --n-envs 4 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 --log-every 1 \
    --obs-dir "$MESH_OBS_DIR" --alarms > "$MESH_JSON"
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$MESH_OBS_DIR" --strict-alarms
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    JAX_ENABLE_COMPILATION_CACHE=false \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --pbt --n-pop 2 --pbt-ready 1 \
    --iterations 3 --n-envs 4 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 --log-every 1 \
    --obs-dir "$PBT_OBS_DIR" --alarms > "$PBT_JSON"
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$PBT_OBS_DIR" --strict-alarms
python - "$MESH_JSON" "$PBT_JSON" <<'EOF'
import json, sys
mesh = json.load(open(sys.argv[1]))["mesh"]
assert mesh["shape"] == {"pop": 1, "data": 2, "model": 1}, mesh
assert len(mesh["rule_table_hash"]) == 12, mesh
pbt = json.load(open(sys.argv[2]))["mesh"]
assert pbt["shape"] == {"pop": 2, "data": 1, "model": 1}, pbt
assert pbt["rule_table_hash"] == mesh["rule_table_hash"], (mesh, pbt)
print("sharding smoke ok:", {"mesh": mesh["shape"], "pbt": pbt["shape"],
                             "rules": mesh["rule_table_hash"]})
EOF

echo "=== smoke: data flywheel (flight log -> continual retrain -> canary promotion, 2 CPU devices) ==="
# ISSUE 19 acceptance, the closed loop end to end: (1) a routed soak
# with the durable flight log attached seals crc-sidecar'd shards and
# holds the conservation contract (rows_logged == served, exactly);
# (2) train --continual ingests those shards through the V-trace
# trust region (zero refusals on fresh same-policy traffic) and steps
# the learner; (3) an intentionally-regressed candidate (seeded noise
# that flips decisions on the logged states) must be BLOCKED by the
# canary gate; (4) a clean candidate must promote with ZERO swap
# recompiles, then the forced post-swap SLO fault must roll back and
# restore the incumbent bit-identically — all three verdicts sealed in
# the crc'd promotion ledger, every obs dir strict-alarms green (the
# flywheel's event kinds are not alarm kinds).
FLY_DIR=$(mktemp -d /tmp/ci_flywheel.XXXXXX)
trap 'rm -rf "$OBS_DIR" "$ASYNC_OBS_DIR" "$VTRACE_OBS_DIR" \
    "$SERVE_OBS_DIR" "$SOAK_OBS_DIR" "$CHAOS_SOAK_OBS_DIR" \
    "$CHAOS_FLOG_DIR" \
    "$CHAOS_JSON" "$SERVE_JSON" "$SOAK_JSON" "$CHAOS_SOAK_JSON" \
    "$TRACE_JSON" \
    "$MATRIX_OBS_DIR" "$MATRIX_CKPT_DIR" "$MATRIX_CLEAN_DIR" \
    "$MATRIX_JSON" \
    "$MESH_OBS_DIR" "$PBT_OBS_DIR" "$MESH_JSON" "$PBT_JSON" \
    "$FLY_DIR"' EXIT
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
    --engines 2 --soak 5 --rate 120 --deadline-ms 250 \
    --adaptive-wait --bucket 8 --pool-steps 2 \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 --window-jobs 16 \
    --queue-len 4 --horizon 64 \
    --flight-log "$FLY_DIR/flog" --flight-capacity 32 --durable-log \
    --obs-dir "$FLY_DIR/obs_soak" > "$FLY_DIR/soak.json"
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$FLY_DIR/obs_soak" \
    --strict-alarms > /dev/null
python - "$FLY_DIR" <<'EOF'
import json, sys
fly = sys.argv[1]
rep = json.load(open(fly + "/soak.json"))
s, fl = rep["soak"], rep["flight_log"]
# the flywheel's conservation contract: every served row logged, shed
# rows never logged — rows_logged == served EXACTLY
assert fl["conservation_ok"], fl
assert fl["rows_logged"] == s["served"] > 0, (fl, s["served"])
assert s["post_warmup_recompiles"] == 0, s
from rlgpuschedule_tpu.obs import merge_dir
seals = [e for e in merge_dir(fly + "/obs_soak")
         if e["kind"] == "flywheel_shard_seal"]
assert seals and sum(e["rows"] for e in seals) == fl["rows_logged"], \
    (len(seals), fl)
prom = open(fly + "/obs_soak/metrics.prom").read()
for name in ("flywheel_rows_logged_total", "flywheel_shards_sealed_total"):
    assert name in prom, f"missing scrape series: {name}"
# crc-verify every sealed shard through the reader itself
from rlgpuschedule_tpu.flywheel import read_flight_log
data = read_flight_log(fly + "/flog")
assert not data.torn_tail and data.rows == fl["rows_logged"], \
    (data.torn_tail, data.rows)
print("flight-log smoke ok:", {"served": s["served"],
                               "rows_logged": fl["rows_logged"],
                               "shards": len(data.shards)})
EOF
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.train --config ppo-mlp-synth64 \
    --continual "$FLY_DIR/flog" --iterations 2 \
    --n-envs 2 --n-nodes 2 --gpus-per-node 4 \
    --window-jobs 16 --horizon 64 --queue-len 4 --n-steps 8 \
    --n-epochs 1 --n-minibatches 2 \
    --obs-dir "$FLY_DIR/obs_cont" --ckpt-dir "$FLY_DIR/ckpt" \
    > "$FLY_DIR/cont.json"
python - "$FLY_DIR" <<'EOF'
import json, sys
fly = sys.argv[1]
s = json.load(open(fly + "/cont.json"))
assert s["mode"] == "continual", s["mode"]
# fresh same-policy traffic sits at rho ~ 1: the trust region must
# admit every shard, and two V-trace iterations must step the learner
assert s["shards_seen"] > 0 and s["shards_refused"] == 0, s
assert s["shards_accepted"] == s["shards_seen"], s
assert not s["torn_tail"], s
assert s["rows_trained"] > 0 and s["final_step"] > 0, s
assert 0.5 < s["rho_mean_trained"] < 2.0, s["rho_mean_trained"]
prom = open(fly + "/obs_cont/metrics.prom").read()
for name in ("flywheel_shard_staleness", "flywheel_rho_mean",
             "flywheel_rho_max", "flywheel_shards_ingested_total",
             "flywheel_shards_refused_total"):
    assert name in prom, f"missing scrape series: {name}"
print("continual smoke ok:", {
    "shards": f"{s['shards_accepted']}/{s['shards_seen']}",
    "rows_trained": s["rows_trained"],
    "pseudo_steps": s["pseudo_steps"],
    "final_step": s["final_step"],
    "rho_mean": round(s["rho_mean_trained"], 4)})
EOF
# (3) the regressed arm: sigma 0.5 on the config seed flips the served
# decision on the logged multi-legal-action states — the canary gate
# must block it (the whole pipeline is seeded, so this is
# deterministic, not a coin flip)
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
    --bucket 8 --pool-steps 2 --n-envs 2 --n-nodes 2 \
    --gpus-per-node 4 --window-jobs 16 --queue-len 4 --horizon 64 \
    --flight-log "$FLY_DIR/flog" --durable-log --promote-noise 0.5 \
    --obs-dir "$FLY_DIR/obs_block" > "$FLY_DIR/block.json"
# (4) the clean arm: a numerically-indistinguishable candidate clears
# the gate, promotes with zero swap recompiles, then the forced SLO
# fault must roll it back and restore the incumbent bit-identically
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.serve --config ppo-mlp-synth64 \
    --bucket 8 --pool-steps 2 --n-envs 2 --n-nodes 2 \
    --gpus-per-node 4 --window-jobs 16 --queue-len 4 --horizon 64 \
    --flight-log "$FLY_DIR/flog" --durable-log --promote-noise 1e-6 \
    --promote-fault \
    --obs-dir "$FLY_DIR/obs_prom" > "$FLY_DIR/promote.json"
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$FLY_DIR/obs_block" \
    --strict-alarms > /dev/null
timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python -m rlgpuschedule_tpu.obs.report "$FLY_DIR/obs_prom" \
    --strict-alarms > /dev/null
python - "$FLY_DIR" <<'EOF'
import json, sys
fly = sys.argv[1]
blk = json.load(open(fly + "/block.json"))["promote"]
assert blk["verdict"] == "blocked" and not blk["promoted"], blk
assert blk["canary"]["max_regress_streak"] >= 2, blk["canary"]
pro = json.load(open(fly + "/promote.json"))["promote"]
assert pro["verdict"] == "promote" and pro["promoted"], pro
assert pro["swap_recompiles"] == 0, pro
assert pro["post_warmup_recompiles"] == 0, pro
assert pro["rollback"], pro
# the rollback restored the incumbent EXACTLY: the pre-promotion probe
# decisions replay bit-identically after the swap back
assert pro["probe_bit_identical"] is True, pro
# promotion lineage: blocked + promote + rollback, all crc-sealed
from rlgpuschedule_tpu.flywheel import read_ledger
sealed, tail = read_ledger(fly + "/flog")
assert [e["action"] for e in sealed] == \
    ["blocked", "promote", "rollback"], [e["action"] for e in sealed]
assert not tail, tail
from rlgpuschedule_tpu.obs import merge_dir
kinds_blk = {e["kind"] for e in merge_dir(fly + "/obs_block")}
kinds_pro = {e["kind"] for e in merge_dir(fly + "/obs_prom")}
assert "promote_blocked" in kinds_blk, sorted(kinds_blk)
for k in ("promote_apply", "promote_rollback"):
    assert k in kinds_pro, sorted(kinds_pro)
prom = open(fly + "/obs_block/metrics.prom").read()
for name in ("flywheel_canary_runs_total",
             "flywheel_promotions_blocked_total"):
    assert name in prom, f"missing scrape series: {name}"
print("promotion smoke ok:", {
    "blocked_agreement": round(blk["canary"]["candidate_agreement"], 3),
    "promoted": pro["candidate"],
    "rollback_reasons": pro["rollback_reasons"],
    "probe_bit_identical": pro["probe_bit_identical"],
    "ledger": [e["action"] for e in sealed]})
EOF

echo "=== tier-1 pytest gate 1/2: main pass (ROADMAP.md, minus spawn) ==="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow and not multihost_spawn' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
[ "$rc" -eq 0 ] || exit $rc

echo "=== tier-1 pytest gate 2/2: multihost spawn subset (serial) ==="
rm -f /tmp/_t1_spawn.log
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow and multihost_spawn' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1_spawn.log
rc=${PIPESTATUS[0]}
echo SPAWN_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_t1_spawn.log | tr -cd . | wc -c)
exit $rc
