"""L0 trace layer tests (SURVEY.md §4 "Trace-parser tests")."""
import os

import numpy as np
import pytest

from rlgpuschedule_tpu.traces import (
    ArrayTrace, JobRecord, STATUS_FAILED, STATUS_KILLED, STATUS_PASS,
    gen_poisson_jobs, gen_poisson_trace, load_pai_jobs, load_philly_jobs,
    to_array_trace, from_array_trace,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


class TestJobRecord:
    def test_validation(self):
        with pytest.raises(ValueError):
            JobRecord(0, 0.0, 0.0, 1)
        with pytest.raises(ValueError):
            JobRecord(0, 0.0, 10.0, 0)
        with pytest.raises(ValueError):
            JobRecord(0, -1.0, 10.0, 1)

    def test_array_roundtrip(self):
        jobs = [JobRecord(0, 5.0, 10.0, 2, 1), JobRecord(1, 0.0, 3.0, 1, 0)]
        tr = to_array_trace(jobs, max_jobs=4)
        assert tr.max_jobs == 4 and tr.num_jobs == 2
        # sorted by submit: job 1 first
        assert tr.submit[0] == 0.0 and tr.gpus[0] == 1
        assert np.isinf(tr.submit[2]) and not tr.valid[2]
        back = from_array_trace(tr)
        assert [(j.submit, j.gpus) for j in back] == [(0.0, 1), (5.0, 2)]

    def test_overflow_raises(self):
        with pytest.raises(ValueError):
            to_array_trace([JobRecord(i, 0.0, 1.0, 1) for i in range(3)], max_jobs=2)

    def test_slice_rebases(self):
        jobs = [JobRecord(i, 10.0 * i, 5.0, 1) for i in range(6)]
        tr = to_array_trace(jobs)
        win = tr.slice(2, 3)
        assert win.num_jobs == 3 and win.max_jobs == 3
        np.testing.assert_allclose(win.submit, [0.0, 10.0, 20.0])


class TestSynthetic:
    def test_deterministic(self):
        a = gen_poisson_jobs(rate=0.1, n_jobs=50, seed=7)
        b = gen_poisson_jobs(rate=0.1, n_jobs=50, seed=7)
        assert a == b
        c = gen_poisson_jobs(rate=0.1, n_jobs=50, seed=8)
        assert a != c

    def test_statistics(self):
        jobs = gen_poisson_jobs(rate=0.5, n_jobs=4000, seed=0, mean_duration=100.0)
        submits = np.array([j.submit for j in jobs])
        inter = np.diff(submits)
        assert abs(inter.mean() - 2.0) < 0.2          # 1/rate
        durs = np.array([j.duration for j in jobs])
        assert abs(durs.mean() - 100.0) / 100.0 < 0.15  # lognormal mean
        assert all(j.gpus in (1, 2, 4, 8) for j in jobs)
        assert submits[0] == 0.0 and np.all(np.diff(submits) >= 0)

    def test_trace_padding(self):
        tr = gen_poisson_trace(rate=1.0, n_jobs=10, seed=1, max_jobs=16)
        assert tr.max_jobs == 16 and tr.num_jobs == 10


class TestPhilly:
    def test_golden_fixture(self):
        jobs = load_philly_jobs(os.path.join(FIXTURES, "philly_small.csv"))
        # j4 is dropped (0 gpus, no times); 5 survive
        assert len(jobs) == 5
        j1, j2, j3, j5, j6 = jobs
        assert j1.submit == 0.0 and j1.duration == 600.0 and j1.gpus == 1
        assert j2.submit == 5.0 and j2.duration == 1200.0 and j2.gpus == 4
        assert j3.status == STATUS_KILLED and j5.status == STATUS_FAILED
        assert j6.status == STATUS_PASS
        # tenants dense-mapped; alice appears twice with one id
        assert j1.tenant == j3.tenant
        assert len({j.tenant for j in jobs}) == 3  # alice, bob, dave (carol dropped)

    def test_max_jobs(self):
        jobs = load_philly_jobs(os.path.join(FIXTURES, "philly_small.csv"), max_jobs=2)
        assert len(jobs) == 2


class TestPAI:
    def test_golden_fixture(self):
        jobs = load_pai_jobs(os.path.join(FIXTURES, "pai_small.csv"))
        # t5 dropped (0 gpu); plan_gpu is percent: 100->1, 50->1, 200->2, 400->4
        assert len(jobs) == 4
        assert [j.gpus for j in jobs] == [1, 1, 2, 4]
        assert jobs[0].submit == 0.0
        assert jobs[1].duration == 900.0
        # 3 distinct tenants
        assert len({j.tenant for j in jobs}) == 3
