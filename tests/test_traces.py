"""L0 trace layer tests (SURVEY.md §4 "Trace-parser tests")."""
import os

import numpy as np
import pytest

from rlgpuschedule_tpu.traces import (
    ArrayTrace, JobRecord, STATUS_FAILED, STATUS_KILLED, STATUS_PASS,
    gen_pai_proxy_jobs, gen_philly_proxy_jobs, gen_philly_proxy_trace,
    gen_poisson_jobs, gen_poisson_trace, load_pai_jobs, load_philly_jobs,
    to_array_trace, from_array_trace,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


class TestJobRecord:
    def test_validation(self):
        with pytest.raises(ValueError):
            JobRecord(0, 0.0, 0.0, 1)
        with pytest.raises(ValueError):
            JobRecord(0, 0.0, 10.0, 0)
        with pytest.raises(ValueError):
            JobRecord(0, -1.0, 10.0, 1)

    def test_array_roundtrip(self):
        jobs = [JobRecord(0, 5.0, 10.0, 2, 1), JobRecord(1, 0.0, 3.0, 1, 0)]
        tr = to_array_trace(jobs, max_jobs=4)
        assert tr.max_jobs == 4 and tr.num_jobs == 2
        # sorted by submit: job 1 first
        assert tr.submit[0] == 0.0 and tr.gpus[0] == 1
        assert np.isinf(tr.submit[2]) and not tr.valid[2]
        back = from_array_trace(tr)
        assert [(j.submit, j.gpus) for j in back] == [(0.0, 1), (5.0, 2)]

    def test_overflow_raises(self):
        with pytest.raises(ValueError):
            to_array_trace([JobRecord(i, 0.0, 1.0, 1) for i in range(3)], max_jobs=2)

    def test_slice_rebases(self):
        jobs = [JobRecord(i, 10.0 * i, 5.0, 1) for i in range(6)]
        tr = to_array_trace(jobs)
        win = tr.slice(2, 3)
        assert win.num_jobs == 3 and win.max_jobs == 3
        np.testing.assert_allclose(win.submit, [0.0, 10.0, 20.0])


class TestSynthetic:
    def test_deterministic(self):
        a = gen_poisson_jobs(rate=0.1, n_jobs=50, seed=7)
        b = gen_poisson_jobs(rate=0.1, n_jobs=50, seed=7)
        assert a == b
        c = gen_poisson_jobs(rate=0.1, n_jobs=50, seed=8)
        assert a != c

    def test_statistics(self):
        jobs = gen_poisson_jobs(rate=0.5, n_jobs=4000, seed=0, mean_duration=100.0)
        submits = np.array([j.submit for j in jobs])
        inter = np.diff(submits)
        assert abs(inter.mean() - 2.0) < 0.2          # 1/rate
        durs = np.array([j.duration for j in jobs])
        assert abs(durs.mean() - 100.0) / 100.0 < 0.15  # lognormal mean
        assert all(j.gpus in (1, 2, 4, 8) for j in jobs)
        assert submits[0] == 0.0 and np.all(np.diff(submits) >= 0)

    def test_trace_padding(self):
        tr = gen_poisson_trace(rate=1.0, n_jobs=10, seed=1, max_jobs=16)
        assert tr.max_jobs == 16 and tr.num_jobs == 10


class TestPhilly:
    def test_golden_fixture(self):
        jobs = load_philly_jobs(os.path.join(FIXTURES, "philly_small.csv"))
        # j4 is dropped (0 gpus, no times); 5 survive
        assert len(jobs) == 5
        j1, j2, j3, j5, j6 = jobs
        assert j1.submit == 0.0 and j1.duration == 600.0 and j1.gpus == 1
        assert j2.submit == 5.0 and j2.duration == 1200.0 and j2.gpus == 4
        assert j3.status == STATUS_KILLED and j5.status == STATUS_FAILED
        assert j6.status == STATUS_PASS
        # tenants dense-mapped; alice appears twice with one id
        assert j1.tenant == j3.tenant
        assert len({j.tenant for j in jobs}) == 3  # alice, bob, dave (carol dropped)

    def test_max_jobs(self):
        jobs = load_philly_jobs(os.path.join(FIXTURES, "philly_small.csv"), max_jobs=2)
        assert len(jobs) == 2


class TestPhillyProxy:
    """traces/philly_proxy.py — the published-statistics stand-in that lets
    configs 2/3 run at scale with no external CSV (VERDICT r2 missing #3)."""

    def test_deterministic(self):
        a = gen_philly_proxy_jobs(200, seed=3)
        b = gen_philly_proxy_jobs(200, seed=3)
        assert a == b
        assert a != gen_philly_proxy_jobs(200, seed=4)

    def test_philly_marginals(self):
        jobs = gen_philly_proxy_jobs(20_000, seed=0, n_gpus=512, load=1.1)
        gpus = np.array([j.gpus for j in jobs])
        durs = np.array([j.duration for j in jobs])
        status = np.array([j.status for j in jobs])
        # gang mix: 1-GPU dominates, power-of-two only, thin 128 tail
        assert set(np.unique(gpus)) <= {1, 2, 4, 8, 16, 32, 64, 128}
        frac1 = (gpus == 1).mean()
        assert 0.65 < frac1 < 0.75
        assert 0 < (gpus >= 64).mean() < 0.02
        # durations heavy-tailed: minutes median, hours mean
        assert 300 < np.median(durs) < 2000
        assert np.mean(durs) > 5 * np.median(durs)
        assert durs.min() >= 30.0 and durs.max() <= 30 * 86400.0
        # status mix ~ 2/3 passed; failed jobs die early, killed run long
        assert 0.60 < (status == STATUS_PASS).mean() < 0.72
        assert 0.16 < (status == STATUS_KILLED).mean() < 0.28
        assert 0.08 < (status == STATUS_FAILED).mean() < 0.16
        med_f = np.median(durs[status == STATUS_FAILED])
        med_k = np.median(durs[status == STATUS_KILLED])
        assert med_f < np.median(durs) < med_k

    def test_offered_load_targets_cluster(self):
        n_gpus, load = 256, 1.0
        jobs = gen_philly_proxy_jobs(30_000, seed=1, n_gpus=n_gpus, load=load)
        span = jobs[-1].submit - jobs[0].submit
        gpu_seconds = sum(j.gpus * j.duration for j in jobs)
        measured = gpu_seconds / (span * n_gpus)
        assert abs(measured - load) / load < 0.15

    def test_diurnal_cycle_present(self):
        # arrival counts binned by hour-of-day must swing with the sinusoid
        jobs = gen_philly_proxy_jobs(50_000, seed=2, n_gpus=2048)
        hours = (np.array([j.submit for j in jobs]) % 86400.0) // 3600
        counts = np.bincount(hours.astype(int), minlength=24)
        assert counts.max() > 1.5 * counts.min()

    def test_max_gang_renormalizes(self):
        jobs = gen_philly_proxy_jobs(2000, seed=5, n_gpus=64, max_gang=8)
        assert max(j.gpus for j in jobs) <= 8
        # 1-GPU share grows once the big sizes are dropped
        assert np.mean([j.gpus == 1 for j in jobs]) > 0.7

    def test_tenants_skewed(self):
        jobs = gen_philly_proxy_jobs(10_000, seed=6)
        tenants = np.array([j.tenant for j in jobs])
        assert tenants.max() < 14 and tenants.min() >= 0
        counts = np.bincount(tenants, minlength=14)
        assert counts[0] > 3 * counts[13]  # Zipf head vs tail

    def test_pai_preset_smaller_jobs(self):
        pai = gen_pai_proxy_jobs(5000, seed=0, n_gpus=128)
        assert max(j.gpus for j in pai) <= 8
        assert np.mean([j.gpus == 1 for j in pai]) > 0.75
        assert np.median([j.duration for j in pai]) < 1000
        assert max(j.tenant for j in pai) < 24

    def test_array_trace_form(self):
        tr = gen_philly_proxy_trace(100, seed=7, max_jobs=128)
        assert isinstance(tr, ArrayTrace)
        assert tr.num_jobs == 100 and tr.max_jobs == 128
        s = tr.submit[tr.valid]
        assert s[0] == 0.0 and np.all(np.diff(s) >= 0)

    def test_100k_scale_fast(self):
        import time
        t0 = time.perf_counter()
        jobs = gen_philly_proxy_jobs(100_000, seed=9)
        assert len(jobs) == 100_000
        assert time.perf_counter() - t0 < 30.0


class TestPAI:
    def test_golden_fixture(self):
        jobs = load_pai_jobs(os.path.join(FIXTURES, "pai_small.csv"))
        # t5 dropped (0 gpu); plan_gpu is percent: 100->1, 50->1, 200->2, 400->4
        assert len(jobs) == 4
        assert [j.gpus for j in jobs] == [1, 1, 2, 4]
        assert jobs[0].submit == 0.0
        assert jobs[1].duration == 900.0
        # 3 distinct tenants
        assert len({j.tenant for j in jobs}) == 3
