"""Oracle simulator unit tests: deterministic event ordering, gang
all-or-nothing, conservation, hand-computed JCTs (SURVEY.md §4)."""
import numpy as np
import pytest

from rlgpuschedule_tpu.sim.oracle import (
    OracleSim, pack_placement, spread_placement,
    NOT_ARRIVED, PENDING, RUNNING, DONE, PACK, SPREAD,
)
from rlgpuschedule_tpu.traces import JobRecord


def J(i, submit, dur, gpus, tenant=0):
    return JobRecord(i, float(submit), float(dur), gpus, tenant)


class TestPlacement:
    def test_pack_prefers_freest(self):
        free = np.array([2, 4, 1], np.int32)
        np.testing.assert_array_equal(pack_placement(free, 5), [1, 4, 0])

    def test_pack_tie_breaks_low_id(self):
        free = np.array([3, 3, 3], np.int32)
        np.testing.assert_array_equal(pack_placement(free, 4), [3, 1, 0])

    def test_pack_infeasible(self):
        assert pack_placement(np.array([1, 1], np.int32), 3) is None

    def test_spread_water_fills(self):
        free = np.array([4, 4, 4], np.int32)
        np.testing.assert_array_equal(spread_placement(free, 6), [2, 2, 2])

    def test_spread_trims_high_ids(self):
        free = np.array([4, 4, 4], np.int32)
        # t=3 gives 9 >= 7, excess 2 trimmed from nodes 2 then 1
        np.testing.assert_array_equal(spread_placement(free, 7), [3, 2, 2])

    def test_spread_respects_free(self):
        free = np.array([1, 5, 0], np.int32)
        np.testing.assert_array_equal(spread_placement(free, 4), [1, 3, 0])

    def test_exact_fit(self):
        free = np.array([2, 2], np.int32)
        assert pack_placement(free, 4).sum() == 4
        assert spread_placement(free, 4).sum() == 4


class TestOracleSemantics:
    def test_arrival_and_lifecycle(self):
        sim = OracleSim([J(0, 0, 10, 1), J(1, 5, 10, 1)], n_nodes=1, gpus_per_node=2)
        assert sim.status[0] == PENDING and sim.status[1] == NOT_ARRIVED
        assert sim.try_place(0)
        assert sim.status[0] == RUNNING and sim.start[0] == 0.0
        sim.advance_to_next_event()  # t=5 arrival
        assert sim.clock == 5.0 and sim.status[1] == PENDING
        assert sim.try_place(1)
        sim.advance_to_next_event()  # t=10: job0 completes
        assert sim.clock == 10.0 and sim.status[0] == DONE and sim.finish[0] == 10.0
        sim.advance_to_next_event()  # t=15: job1 completes
        assert sim.done()
        np.testing.assert_allclose(sim.jcts(), [10.0, 10.0])

    def test_gang_all_or_nothing(self):
        sim = OracleSim([J(0, 0, 5, 3)], n_nodes=2, gpus_per_node=2)
        assert sim.try_place(0)          # spans nodes: 2 + 1
        assert sim.alloc[0].sum() == 3

    def test_demand_over_capacity_rejected(self):
        with pytest.raises(ValueError):
            OracleSim([J(0, 0, 5, 5)], n_nodes=2, gpus_per_node=2)

    def test_infeasible_not_partially_placed(self):
        sim = OracleSim([J(0, 0, 5, 2), J(1, 0, 5, 3)], n_nodes=2, gpus_per_node=2)
        assert sim.try_place(0)
        assert not sim.try_place(1)      # only 2 free, needs 3
        assert sim.alloc[1].sum() == 0 and sim.status[1] == PENDING
        assert sim.gpus_consistent()

    def test_conservation_through_lifecycle(self):
        sim = OracleSim([J(0, 0, 4, 2), J(1, 1, 3, 4), J(2, 2, 2, 1)],
                        n_nodes=2, gpus_per_node=4)
        sim.try_place(0, PACK)
        assert sim.gpus_consistent()
        sim.advance_to_next_event()
        sim.try_place(1, SPREAD)
        assert sim.gpus_consistent()
        sim.advance_to_next_event()
        sim.try_place(2)
        assert sim.gpus_consistent()
        while not sim.done():
            sim.advance_to_next_event()
        assert sim.gpus_consistent() and sim.free.sum() == 8

    def test_preemption_preserves_attained_service(self):
        sim = OracleSim([J(0, 0, 10, 2)], n_nodes=1, gpus_per_node=2)
        sim.try_place(0)
        sim.advance_to(4.0)
        assert sim.remaining[0] == 6.0
        assert sim.preempt(0)
        assert sim.status[0] == PENDING and sim.free.sum() == 2
        assert sim.attained_service(0) == 8.0  # 4s × 2 gpus
        sim.try_place(0)
        sim.advance_to_next_event()
        assert sim.clock == 10.0 and sim.done()  # 4 run + 6 remaining

    def test_completion_before_arrival_same_instant(self):
        sim = OracleSim([J(0, 0, 5, 2), J(1, 5, 1, 2)], n_nodes=1, gpus_per_node=2)
        sim.try_place(0)
        sim.advance_to_next_event()  # t=5: completion AND arrival
        assert sim.status[0] == DONE and sim.status[1] == PENDING
        assert sim.try_place(1)      # GPUs already released

    def test_advance_cannot_skip_events(self):
        sim = OracleSim([J(0, 0, 5, 1)], n_nodes=1, gpus_per_node=1)
        sim.try_place(0)
        with pytest.raises(ValueError):
            sim.advance_to(7.0)

    def test_queue_order(self):
        # to_array_trace sorts rows by submit; queue is (submit asc, row asc)
        sim = OracleSim([J(0, 3, 1, 1), J(1, 0, 1, 1), J(2, 3, 1, 1)],
                        n_nodes=1, gpus_per_node=1)
        np.testing.assert_allclose(sim.trace.submit, [0.0, 3.0, 3.0])
        sim.advance_to(3.0)
        assert sim.pending_jobs() == [0, 1, 2]
