"""jsan static-analyzer tests (PR 3, extended by PRs 15 and 18): one
known-good + known-bad fixture pair per rule, the thread-aware
concurrency rules, the refusal-matrix drift checker, the value-lifetime
rules (view-escape / use-after-recycle / donated-alias-reuse /
torn-publish), the cross-surface contract-drift checker, the --cache
incremental mode, suppression + baseline workflows (including
--prune-baseline / --fail-stale), JSON + SARIF output (now with column
regions), --diff / --explain, the exit-code contract, and the
acceptance gates — the shipped tree is clean with an EMPTY baseline,
and seeding any known-bad snippet into a tree makes the CLI exit
nonzero.
"""
import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from rlgpuschedule_tpu.analysis import (analyze_paths, apply_baseline,
                                        make_baseline)
from rlgpuschedule_tpu.analysis.engine import (FindingCache, SKIP_DIRS,
                                               analyze_file,
                                               iter_py_files)
from rlgpuschedule_tpu.analysis.rules import rule_names

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "jsan")

# rule -> (bad fixture, expected finding count in it)
BAD = {
    "donation-discipline": ("bad_donation.py", 2),
    "host-sync": ("bad_host_sync.py", 4),
    "tracer-leak": ("bad_tracer_leak.py", 3),
    "impure-in-jit": ("bad_impure.py", 3),
    "recompile-hazard": ("bad_recompile.py", 2),
    "prng-key-reuse": ("bad_prng_reuse.py", 3),
    "sync-in-loop": ("bad_sync_in_loop.py", 3),
    "unconstrained-intermediate":
        ("bad_unconstrained_intermediate.py", 2),
    "compile-off-thread": ("bad_compile_off_thread.py", 3),
    "device-dispatch-unlocked": ("bad_device_dispatch_unlocked.py", 3),
    "donation-cross-thread": ("bad_donation_cross_thread.py", 1),
    "shared-state-unlocked": ("bad_shared_state_unlocked.py", 2),
    "blocking-under-lock": ("bad_blocking_under_lock.py", 3),
    "hung-future": ("bad_hung_future.py", 3),
    "alloc-in-hot-loop": ("bad_alloc_in_hot_loop.py", 3),
    "refusal-drift": (os.path.join("refusal_bad", "train.py"), 2),
    "view-escape": ("bad_view_escape.py", 4),
    "use-after-recycle": ("bad_use_after_recycle.py", 3),
    "donated-alias-reuse": ("bad_donated_alias_reuse.py", 2),
    "torn-publish": ("bad_torn_publish.py", 2),
    "contract-drift": ("contract_bad", 5),   # directory fixture
}
GOOD = ["good_donation.py", "good_host_sync.py", "good_tracer_leak.py",
        "good_impure.py", "good_recompile.py", "good_prng_reuse.py",
        "good_sync_in_loop.py",
        "good_unconstrained_intermediate.py",
        "good_compile_off_thread.py",
        "good_device_dispatch_unlocked.py",
        "good_donation_cross_thread.py",
        "good_shared_state_unlocked.py",
        "good_blocking_under_lock.py",
        "good_hung_future.py",
        "good_alloc_in_hot_loop.py",
        os.path.join("refusal_good", "configs.py"),
        os.path.join("refusal_good", "train.py"),
        "good_view_escape.py", "good_use_after_recycle.py",
        "good_donated_alias_reuse.py", "good_torn_publish.py",
        "contract_good"]                       # directory fixture


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "rlgpuschedule_tpu.analysis", *args],
        capture_output=True, text=True, cwd=cwd,
        env={**os.environ, "PYTHONPATH": REPO})


class TestRules:
    @pytest.mark.parametrize("rule", sorted(BAD))
    def test_bad_fixture_fires_the_rule(self, rule):
        fname, expected = BAD[rule]
        findings = analyze_paths([os.path.join(FIXTURES, fname)])
        assert len(findings) == expected, findings
        assert {f.rule for f in findings} == {rule}, findings

    @pytest.mark.parametrize("fname", GOOD)
    def test_good_fixture_is_clean(self, fname):
        assert analyze_paths([os.path.join(FIXTURES, fname)]) == []

    def test_registry_covers_every_fixture_rule(self):
        assert set(BAD) == set(rule_names())


class TestSuppressions:
    def test_inline_suppression_silences_one_rule(self, tmp_path):
        bad = open(os.path.join(FIXTURES, "bad_prng_reuse.py")).read()
        patched = bad.replace(
            "b = jax.random.uniform(key, (4,))",
            "b = jax.random.uniform(key, (4,))  "
            "# jsan: disable=prng-key-reuse -- test")
        p = tmp_path / "patched.py"
        p.write_text(patched)
        findings = analyze_paths([str(p)])
        assert len(findings) == BAD["prng-key-reuse"][1] - 1

    def test_comment_line_above_suppresses_next_line(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(
            "import jax\n\n\n"
            "def f(key):\n"
            "    a = jax.random.normal(key)\n"
            "    # jsan: disable=prng-key-reuse -- deliberate twin draw\n"
            "    b = jax.random.normal(key)\n"
            "    return a, b\n")
        assert analyze_paths([str(p)]) == []

    def test_unrelated_rule_name_does_not_suppress(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(
            "import jax\n\n\n"
            "def f(key):\n"
            "    a = jax.random.normal(key)\n"
            "    b = jax.random.normal(key)  # jsan: disable=host-sync\n"
            "    return a, b\n")
        assert [f.rule for f in analyze_paths([str(p)])] \
            == ["prng-key-reuse"]


class TestWalker:
    def test_fixture_dirs_are_skipped_in_tree_walks(self):
        assert "fixtures" in SKIP_DIRS
        walked = list(iter_py_files([os.path.join(REPO, "tests")]))
        assert not any("fixtures" in p for p in walked)
        # but explicit file arguments are always analyzed
        explicit = os.path.join(FIXTURES, "bad_impure.py")
        assert list(iter_py_files([explicit])) == [explicit]


class TestCLI:
    def test_shipped_tree_is_clean(self):
        """Acceptance gate: the analyzer exits 0 over the shipped
        package + top-level scripts (everything fixed or suppressed)."""
        r = _cli("rlgpuschedule_tpu", "bench.py", "__graft_entry__.py")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_seeded_bad_snippet_fails_the_tree(self, tmp_path):
        """Acceptance gate: seeding any one known-bad fixture into an
        otherwise-clean tree makes the CLI exit nonzero."""
        tree = tmp_path / "pkg"
        tree.mkdir()
        shutil.copy(os.path.join(FIXTURES, "good_donation.py"),
                    tree / "clean.py")
        r = _cli(str(tree), cwd=str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr
        shutil.copy(os.path.join(FIXTURES, "bad_host_sync.py"),
                    tree / "seeded.py")
        r = _cli(str(tree), cwd=str(tmp_path))
        assert r.returncode == 1
        assert "[host-sync]" in r.stdout

    def test_json_output_is_stable_and_sorted(self):
        paths = [os.path.join(FIXTURES, f) for f, _ in
                 (BAD["prng-key-reuse"], BAD["recompile-hazard"])]
        r1 = _cli(*paths, "--format", "json", "--no-baseline")
        r2 = _cli(*reversed(paths), "--format", "json", "--no-baseline")
        assert r1.returncode == r2.returncode == 1
        out1, out2 = json.loads(r1.stdout), json.loads(r2.stdout)
        assert out1 == out2            # argument order doesn't matter
        keys = [(f["path"], f["line"], f["col"], f["rule"])
                for f in out1["findings"]]
        assert keys == sorted(keys)    # sorted output
        assert out1["count"] == len(out1["findings"])

    def test_list_rules(self):
        r = _cli("--list-rules")
        assert r.returncode == 0
        for name in rule_names():
            assert name in r.stdout


class TestBaseline:
    def test_baseline_round_trips(self, tmp_path):
        """--write-baseline over a dirty tree, then a normal run with
        that baseline, exits 0; and the baseline file itself is stable
        (sorted, deterministic) across regenerations."""
        bad = os.path.join(FIXTURES, "bad_tracer_leak.py")
        base = tmp_path / "baseline.json"
        r = _cli(bad, "--write-baseline", str(base))
        assert r.returncode == 0, r.stdout + r.stderr
        first = base.read_text()
        r = _cli(bad, "--baseline", str(base))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "baselined" in r.stdout
        r = _cli(bad, "--write-baseline", str(base))
        assert base.read_text() == first           # byte-stable
        entries = json.loads(first)["entries"]
        assert entries == sorted(
            entries, key=lambda e: (e["rule"], e["path"], e["snippet"]))

    def test_baseline_survives_line_drift(self, tmp_path):
        """Identity is (rule, path, snippet): inserting lines above a
        grandfathered finding must not resurrect it."""
        src = open(os.path.join(FIXTURES, "bad_recompile.py")).read()
        p = tmp_path / "mod.py"
        p.write_text(src)
        findings = analyze_paths([str(p)])
        assert findings
        baseline = {(f.rule, f.path, f.snippet) for f in findings}
        p.write_text("# pushed\n# down\n# three lines\n" + src)
        drifted = analyze_paths([str(p)])
        assert [f.line for f in drifted] != [f.line for f in findings]
        assert apply_baseline(drifted, baseline) == []

    def test_new_findings_are_not_masked_by_baseline(self, tmp_path):
        findings = analyze_paths(
            [os.path.join(FIXTURES, "bad_impure.py")])
        baseline = {f.baseline_key for f in findings[:1]}
        kept = apply_baseline(findings, baseline)
        assert len(kept) == len(findings) - 1

    def test_make_baseline_matches_engine_format(self):
        findings = analyze_paths(
            [os.path.join(FIXTURES, "bad_donation.py")])
        data = make_baseline(findings)
        assert data["version"] == 1
        assert all(set(e) == {"rule", "path", "snippet"}
                   for e in data["entries"])


class TestRepoBaselineFile:
    def test_committed_baseline_is_valid_and_minimal(self):
        """The committed jsan_baseline.json must parse and contain only
        entries that still match a real finding — stale grandfather
        entries hide future regressions at the same line."""
        path = os.path.join(REPO, "jsan_baseline.json")
        with open(path) as f:
            data = json.load(f)
        assert data["version"] == 1
        current = {f.baseline_key for f in analyze_paths(
            [os.path.join(REPO, "rlgpuschedule_tpu"),
             os.path.join(REPO, "bench.py"),
             os.path.join(REPO, "__graft_entry__.py")])}
        stale = [e for e in data["entries"]
                 if (e["rule"], e["path"], e["snippet"]) not in current]
        assert stale == [], f"stale baseline entries: {stale}"

    def test_shipped_tree_has_zero_findings_without_baseline(self):
        """PR-15 acceptance: the full package is clean on its own —
        the committed baseline is EMPTY, nothing is grandfathered."""
        findings = analyze_paths(
            [os.path.join(REPO, "rlgpuschedule_tpu"),
             os.path.join(REPO, "bench.py"),
             os.path.join(REPO, "__graft_entry__.py")])
        assert findings == [], [f"{f.path}:{f.line} [{f.rule}]"
                                for f in findings]
        with open(os.path.join(REPO, "jsan_baseline.json")) as f:
            assert json.load(f)["entries"] == []


class TestConcurrencyRules:
    """Workflow round-trips for the thread-aware rules (the per-rule
    counts live in BAD/GOOD above)."""

    def test_inline_suppression_silences_concurrency_finding(self,
                                                             tmp_path):
        bad = open(os.path.join(
            FIXTURES, "bad_blocking_under_lock.py")).read()
        patched = bad.replace(
            "item = self._q.get()",
            "item = self._q.get()  "
            "# jsan: disable=blocking-under-lock -- test")
        p = tmp_path / "patched.py"
        p.write_text(patched)
        findings = analyze_paths([str(p)])
        assert len(findings) == BAD["blocking-under-lock"][1] - 1
        assert {f.rule for f in findings} == {"blocking-under-lock"}

    def test_baseline_survives_line_drift_for_concurrency_rule(
            self, tmp_path):
        src = open(os.path.join(
            FIXTURES, "bad_shared_state_unlocked.py")).read()
        p = tmp_path / "mod.py"
        p.write_text(src)
        findings = analyze_paths([str(p)])
        assert findings
        baseline = {f.baseline_key for f in findings}
        p.write_text("# pushed\n# down\n" + src)
        drifted = analyze_paths([str(p)])
        assert apply_baseline(drifted, baseline) == []

    def test_condition_alias_counts_as_the_wrapped_lock(self, tmp_path):
        """Dropping the Condition's wrapped-lock argument decouples the
        two regions and the good shared-state fixture goes bad — the
        alias recognition is load-bearing, not decorative."""
        src = open(os.path.join(
            FIXTURES, "good_shared_state_unlocked.py")).read()
        p = tmp_path / "mod.py"
        p.write_text(src.replace("threading.Condition(self._lock)",
                                 "threading.Condition()"))
        findings = analyze_paths([str(p)])
        assert [f.rule for f in findings] == ["shared-state-unlocked"]


class TestRefusalDrift:
    @pytest.mark.parametrize("fname,count,needle", [
        (os.path.join("refusal_bad", "configs.py"), 1,
         "no reachable guard"),
        (os.path.join("refusal_bad", "train.py"), 2, "delta"),
        (os.path.join("refusal_bad", "evaluate.py"), 1,
         "refused pair"),
    ])
    def test_bad_fixture_counts_and_messages(self, fname, count, needle):
        findings = analyze_paths([os.path.join(FIXTURES, fname)])
        assert len(findings) == count, findings
        assert {f.rule for f in findings} == {"refusal-drift"}
        assert any(needle in f.message for f in findings), findings

    def test_adhoc_raise_is_flagged(self):
        findings = analyze_paths(
            [os.path.join(FIXTURES, "refusal_bad", "train.py")])
        assert any("ad-hoc" in f.message for f in findings)

    def test_real_table_rows_are_all_guarded(self):
        """The shipped MODE_REFUSALS table has a guard for every row
        (this is what the PR-15 production fixes bought)."""
        findings = analyze_paths(
            [os.path.join(REPO, "rlgpuschedule_tpu", "configs.py")])
        assert [f for f in findings if f.rule == "refusal-drift"] == []


class TestContractDrift:
    """Cross-surface contract checker: the bad fixture tree drifts in
    all five ways (ghost metric, orphan metric, ghost kind, orphan
    kind, stale wire golden); the good twin exercises the allowlist,
    the f-string registration pattern, and the local-registration
    exemption and stays clean."""

    @pytest.mark.parametrize("needle,tail", [
        ("no code registers it", "ci.sh"),            # ghost metric
        ("'pipe_dropped_total' is registered", "pipeline.py"),  # orphan
        ("no code emits it", "test_gates.py"),        # ghost kind
        ("'debug_tick' is emitted", "pipeline.py"),   # orphan kind
        ("disagree with the frame constants", "test_gates.py"),  # wire
    ])
    def test_bad_tree_drifts_in_each_family(self, needle, tail):
        findings = analyze_paths(
            [os.path.join(FIXTURES, "contract_bad")])
        hits = [f for f in findings if needle in f.message]
        assert len(hits) == 1, findings
        assert hits[0].path.replace(os.sep, "/").endswith(tail)
        assert hits[0].rule == "contract-drift"

    def test_fixture_tree_self_roots_at_its_own_ci_sh(self):
        """The root walk stops at the fixture's own ci.sh — nothing
        from the real repo's surfaces leaks into fixture verdicts."""
        findings = analyze_paths(
            [os.path.join(FIXTURES, "contract_bad")])
        assert findings
        assert all("contract_bad" in f.path for f in findings)

    def test_real_wire_golden_matches_frame_constants(self):
        """The committed TestGoldenBytes pin in tests/test_wire.py is
        the witness the wire direction of the rule checks against."""
        findings = analyze_paths([os.path.join(
            REPO, "rlgpuschedule_tpu", "serve", "wire.py")])
        assert [f for f in findings
                if f.rule == "contract-drift"] == [], findings


class TestCache:
    """--cache DIR incremental mode: entries keyed on (file sha1,
    rule-set hash); cross-file rules are never served from cache."""

    def test_warm_hit_returns_identical_findings(self, tmp_path):
        cache = FindingCache(str(tmp_path / "c"))
        bad = os.path.join(FIXTURES, "bad_prng_reuse.py")
        cold = analyze_file(bad, cache=cache)
        assert cold and cache.misses >= 1 and cache.hits == 0
        warm = analyze_file(bad, cache=cache)
        assert warm == cold
        assert cache.hits >= 1

    def test_warm_second_run_is_faster(self, tmp_path):
        cdir = str(tmp_path / "c")
        pkg = os.path.join(REPO, "rlgpuschedule_tpu", "analysis")
        t0 = time.monotonic()
        cold = analyze_paths([pkg], cache_dir=cdir)
        t_cold = time.monotonic() - t0
        t0 = time.monotonic()
        warm = analyze_paths([pkg], cache_dir=cdir)
        t_warm = time.monotonic() - t0
        assert warm == cold
        assert t_warm < t_cold, (t_warm, t_cold)

    def test_cli_cache_flag_round_trips(self, tmp_path):
        bad = os.path.join(FIXTURES, "bad_host_sync.py")
        cdir = tmp_path / "jc"
        r1 = _cli(bad, "--no-baseline", "--cache", str(cdir))
        r2 = _cli(bad, "--no-baseline", "--cache", str(cdir))
        assert r1.returncode == r2.returncode == 1
        assert r1.stdout == r2.stdout
        assert any(cdir.iterdir())             # entries were written

    def test_corrupt_cache_entry_degrades_to_miss(self, tmp_path):
        cache = FindingCache(str(tmp_path / "c"))
        bad = os.path.join(FIXTURES, "bad_impure.py")
        cold = analyze_file(bad, cache=cache)
        for p in (tmp_path / "c").iterdir():
            p.write_text("not json")
        again = analyze_file(bad, cache=cache)
        assert again == cold

    def test_cross_file_rule_findings_survive_a_warm_run(self, tmp_path):
        """refusal-drift is cross-file: its verdict depends on other
        files, so the warm run re-derives it instead of replaying."""
        bad = os.path.join(FIXTURES, "refusal_bad", "train.py")
        cdir = str(tmp_path / "c")
        cold = analyze_paths([bad], cache_dir=cdir)
        warm = analyze_paths([bad], cache_dir=cdir)
        assert warm == cold
        assert {f.rule for f in warm} == {"refusal-drift"}


class TestSarif:
    def test_sarif_output_is_schema_shaped(self):
        fname, expected = BAD["blocking-under-lock"]
        r = _cli(os.path.join(FIXTURES, fname), "--format", "sarif",
                 "--no-baseline")
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["version"] == "2.1.0"
        assert "sarif-schema-2.1.0" in doc["$schema"]
        assert len(doc["runs"]) == 1
        driver = doc["runs"][0]["tool"]["driver"]
        assert driver["name"] == "jsan"
        assert {r_["id"] for r_ in driver["rules"]} == set(rule_names())
        assert all(r_["shortDescription"]["text"] for r_ in driver["rules"])
        results = doc["runs"][0]["results"]
        assert len(results) == expected
        for res in results:
            assert res["ruleId"] in set(rule_names())
            assert res["message"]["text"]
            assert res["partialFingerprints"]["jsanFindingId/v1"]
            loc = res["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"].endswith(".py")
            assert loc["region"]["startLine"] >= 1
            assert loc["region"]["startColumn"] >= 1
            # PR-18: column regions so editors can underline; endColumn
            # is exclusive, so it strictly exceeds startColumn
            assert loc["region"]["endLine"] >= loc["region"]["startLine"]
            assert loc["region"]["endColumn"] \
                > loc["region"]["startColumn"]

    def test_sarif_clean_tree_has_empty_results(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("X = 1\n")
        r = _cli(str(p), "--format", "sarif", cwd=str(tmp_path))
        assert r.returncode == 0
        assert json.loads(r.stdout)["runs"][0]["results"] == []


class TestDiff:
    def _git(self, cwd, *args):
        return subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=cwd, capture_output=True, text=True, check=True)

    def test_diff_restricts_to_changed_files(self, tmp_path):
        self._git(tmp_path, "init", "-q")
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        bad = open(os.path.join(FIXTURES, "bad_prng_reuse.py")).read()
        a.write_text("X = 1\n")
        b.write_text(bad)
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-qm", "seed")
        a.write_text(bad)                    # a changes, b does not
        r = _cli(".", "--diff", "HEAD", "--no-baseline",
                 cwd=str(tmp_path))
        assert r.returncode == 1, r.stdout + r.stderr
        assert "a.py" in r.stdout
        assert "b.py" not in r.stdout

    def test_diff_with_no_changes_exits_clean(self, tmp_path):
        self._git(tmp_path, "init", "-q")
        (tmp_path / "a.py").write_text("X = 1\n")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-qm", "seed")
        r = _cli(".", "--diff", "HEAD", cwd=str(tmp_path))
        assert r.returncode == 0
        assert "no analyzable files changed" in r.stdout

    def test_diff_bad_rev_is_invocation_error(self, tmp_path):
        self._git(tmp_path, "init", "-q")
        (tmp_path / "a.py").write_text("X = 1\n")
        r = _cli(".", "--diff", "no-such-rev", cwd=str(tmp_path))
        assert r.returncode == 2
        assert "git diff" in r.stderr


class TestExplain:
    def test_explain_prints_rule_rationale(self):
        r = _cli("--explain", "refusal-drift")
        assert r.returncode == 0
        assert "MODE_REFUSALS" in r.stdout
        r = _cli("--explain", "compile-off-thread")
        assert r.returncode == 0
        assert "PR-8" in r.stdout or "compile" in r.stdout

    def test_explain_unknown_rule_is_invocation_error(self):
        r = _cli("--explain", "no-such-rule")
        assert r.returncode == 2
        assert "unknown rule" in r.stderr


class TestExitCodeContract:
    def test_findings_exit_1_with_stable_ids(self):
        fname, _ = BAD["shared-state-unlocked"]
        r = _cli(os.path.join(FIXTURES, fname), "--no-baseline")
        assert r.returncode == 1
        assert "id: shared-state-unlocked@" in r.stdout
        r2 = _cli(os.path.join(FIXTURES, fname), "--no-baseline")
        assert r.stdout == r2.stdout       # IDs are deterministic

    def test_unparsable_input_exits_2(self, tmp_path):
        p = tmp_path / "nul.py"
        p.write_bytes(b"x = 1\x00\n")       # ast.parse raises ValueError
        r = _cli(str(p), cwd=str(tmp_path))
        assert r.returncode == 2
        assert "internal error" in r.stderr or "cannot parse" in r.stderr

    def test_missing_path_exits_2(self):
        r = _cli("definitely/not/a/path.py")
        assert r.returncode == 2
        assert "no such path" in r.stderr


class TestBaselineMaintenance:
    def test_fail_stale_flags_dead_entries(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(open(os.path.join(
            FIXTURES, "bad_recompile.py")).read())
        base = tmp_path / "baseline.json"
        r = _cli("bad.py", "--write-baseline", "baseline.json",
                 cwd=str(tmp_path))
        assert r.returncode == 0
        # with live entries, --fail-stale is quiet
        r = _cli("bad.py", "--baseline", "baseline.json", "--fail-stale",
                 cwd=str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr
        # fix the file: the baseline entries go stale and the gate trips
        bad.write_text("X = 1\n")
        r = _cli("bad.py", "--baseline", "baseline.json", "--fail-stale",
                 cwd=str(tmp_path))
        assert r.returncode == 1
        assert "stale baseline entry" in r.stderr
        assert base.exists()

    def test_prune_baseline_drops_only_stale_entries(self, tmp_path):
        (tmp_path / "bad.py").write_text(open(os.path.join(
            FIXTURES, "bad_recompile.py")).read())
        (tmp_path / "bad2.py").write_text(open(os.path.join(
            FIXTURES, "bad_prng_reuse.py")).read())
        r = _cli("bad.py", "bad2.py", "--write-baseline",
                 "baseline.json", cwd=str(tmp_path))
        assert r.returncode == 0
        (tmp_path / "bad2.py").write_text("X = 1\n")   # half goes stale
        r = _cli("bad.py", "bad2.py", "--baseline", "baseline.json",
                 "--prune-baseline", cwd=str(tmp_path))
        assert r.returncode == 0
        assert "pruned" in r.stdout
        entries = json.loads(
            (tmp_path / "baseline.json").read_text())["entries"]
        assert entries                         # live entries kept
        assert all(e["path"] == "bad.py" for e in entries)
        # after the prune the gate is quiet again
        r = _cli("bad.py", "bad2.py", "--baseline", "baseline.json",
                 "--fail-stale", cwd=str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr
