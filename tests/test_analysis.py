"""jsan static-analyzer tests (PR 3): one known-good + known-bad fixture
pair per rule, suppression + baseline workflows, JSON output stability,
and the two acceptance gates — the shipped tree is clean, and seeding
any known-bad snippet into a tree makes the CLI exit nonzero.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

from rlgpuschedule_tpu.analysis import (analyze_paths, apply_baseline,
                                        make_baseline)
from rlgpuschedule_tpu.analysis.engine import SKIP_DIRS, iter_py_files
from rlgpuschedule_tpu.analysis.rules import rule_names

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "jsan")

# rule -> (bad fixture, expected finding count in it)
BAD = {
    "donation-discipline": ("bad_donation.py", 2),
    "host-sync": ("bad_host_sync.py", 4),
    "tracer-leak": ("bad_tracer_leak.py", 3),
    "impure-in-jit": ("bad_impure.py", 3),
    "recompile-hazard": ("bad_recompile.py", 2),
    "prng-key-reuse": ("bad_prng_reuse.py", 3),
    "sync-in-loop": ("bad_sync_in_loop.py", 3),
    "unconstrained-intermediate":
        ("bad_unconstrained_intermediate.py", 2),
}
GOOD = ["good_donation.py", "good_host_sync.py", "good_tracer_leak.py",
        "good_impure.py", "good_recompile.py", "good_prng_reuse.py",
        "good_sync_in_loop.py",
        "good_unconstrained_intermediate.py"]


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "rlgpuschedule_tpu.analysis", *args],
        capture_output=True, text=True, cwd=cwd,
        env={**os.environ, "PYTHONPATH": REPO})


class TestRules:
    @pytest.mark.parametrize("rule", sorted(BAD))
    def test_bad_fixture_fires_the_rule(self, rule):
        fname, expected = BAD[rule]
        findings = analyze_paths([os.path.join(FIXTURES, fname)])
        assert len(findings) == expected, findings
        assert {f.rule for f in findings} == {rule}, findings

    @pytest.mark.parametrize("fname", GOOD)
    def test_good_fixture_is_clean(self, fname):
        assert analyze_paths([os.path.join(FIXTURES, fname)]) == []

    def test_registry_covers_every_fixture_rule(self):
        assert set(BAD) == set(rule_names())


class TestSuppressions:
    def test_inline_suppression_silences_one_rule(self, tmp_path):
        bad = open(os.path.join(FIXTURES, "bad_prng_reuse.py")).read()
        patched = bad.replace(
            "b = jax.random.uniform(key, (4,))",
            "b = jax.random.uniform(key, (4,))  "
            "# jsan: disable=prng-key-reuse -- test")
        p = tmp_path / "patched.py"
        p.write_text(patched)
        findings = analyze_paths([str(p)])
        assert len(findings) == BAD["prng-key-reuse"][1] - 1

    def test_comment_line_above_suppresses_next_line(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(
            "import jax\n\n\n"
            "def f(key):\n"
            "    a = jax.random.normal(key)\n"
            "    # jsan: disable=prng-key-reuse -- deliberate twin draw\n"
            "    b = jax.random.normal(key)\n"
            "    return a, b\n")
        assert analyze_paths([str(p)]) == []

    def test_unrelated_rule_name_does_not_suppress(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(
            "import jax\n\n\n"
            "def f(key):\n"
            "    a = jax.random.normal(key)\n"
            "    b = jax.random.normal(key)  # jsan: disable=host-sync\n"
            "    return a, b\n")
        assert [f.rule for f in analyze_paths([str(p)])] \
            == ["prng-key-reuse"]


class TestWalker:
    def test_fixture_dirs_are_skipped_in_tree_walks(self):
        assert "fixtures" in SKIP_DIRS
        walked = list(iter_py_files([os.path.join(REPO, "tests")]))
        assert not any("fixtures" in p for p in walked)
        # but explicit file arguments are always analyzed
        explicit = os.path.join(FIXTURES, "bad_impure.py")
        assert list(iter_py_files([explicit])) == [explicit]


class TestCLI:
    def test_shipped_tree_is_clean(self):
        """Acceptance gate: the analyzer exits 0 over the shipped
        package + top-level scripts (everything fixed or suppressed)."""
        r = _cli("rlgpuschedule_tpu", "bench.py", "__graft_entry__.py")
        assert r.returncode == 0, r.stdout + r.stderr

    def test_seeded_bad_snippet_fails_the_tree(self, tmp_path):
        """Acceptance gate: seeding any one known-bad fixture into an
        otherwise-clean tree makes the CLI exit nonzero."""
        tree = tmp_path / "pkg"
        tree.mkdir()
        shutil.copy(os.path.join(FIXTURES, "good_donation.py"),
                    tree / "clean.py")
        r = _cli(str(tree), cwd=str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr
        shutil.copy(os.path.join(FIXTURES, "bad_host_sync.py"),
                    tree / "seeded.py")
        r = _cli(str(tree), cwd=str(tmp_path))
        assert r.returncode == 1
        assert "[host-sync]" in r.stdout

    def test_json_output_is_stable_and_sorted(self):
        paths = [os.path.join(FIXTURES, f) for f, _ in
                 (BAD["prng-key-reuse"], BAD["recompile-hazard"])]
        r1 = _cli(*paths, "--format", "json", "--no-baseline")
        r2 = _cli(*reversed(paths), "--format", "json", "--no-baseline")
        assert r1.returncode == r2.returncode == 1
        out1, out2 = json.loads(r1.stdout), json.loads(r2.stdout)
        assert out1 == out2            # argument order doesn't matter
        keys = [(f["path"], f["line"], f["col"], f["rule"])
                for f in out1["findings"]]
        assert keys == sorted(keys)    # sorted output
        assert out1["count"] == len(out1["findings"])

    def test_list_rules(self):
        r = _cli("--list-rules")
        assert r.returncode == 0
        for name in rule_names():
            assert name in r.stdout


class TestBaseline:
    def test_baseline_round_trips(self, tmp_path):
        """--write-baseline over a dirty tree, then a normal run with
        that baseline, exits 0; and the baseline file itself is stable
        (sorted, deterministic) across regenerations."""
        bad = os.path.join(FIXTURES, "bad_tracer_leak.py")
        base = tmp_path / "baseline.json"
        r = _cli(bad, "--write-baseline", str(base))
        assert r.returncode == 0, r.stdout + r.stderr
        first = base.read_text()
        r = _cli(bad, "--baseline", str(base))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "baselined" in r.stdout
        r = _cli(bad, "--write-baseline", str(base))
        assert base.read_text() == first           # byte-stable
        entries = json.loads(first)["entries"]
        assert entries == sorted(
            entries, key=lambda e: (e["rule"], e["path"], e["snippet"]))

    def test_baseline_survives_line_drift(self, tmp_path):
        """Identity is (rule, path, snippet): inserting lines above a
        grandfathered finding must not resurrect it."""
        src = open(os.path.join(FIXTURES, "bad_recompile.py")).read()
        p = tmp_path / "mod.py"
        p.write_text(src)
        findings = analyze_paths([str(p)])
        assert findings
        baseline = {(f.rule, f.path, f.snippet) for f in findings}
        p.write_text("# pushed\n# down\n# three lines\n" + src)
        drifted = analyze_paths([str(p)])
        assert [f.line for f in drifted] != [f.line for f in findings]
        assert apply_baseline(drifted, baseline) == []

    def test_new_findings_are_not_masked_by_baseline(self, tmp_path):
        findings = analyze_paths(
            [os.path.join(FIXTURES, "bad_impure.py")])
        baseline = {f.baseline_key for f in findings[:1]}
        kept = apply_baseline(findings, baseline)
        assert len(kept) == len(findings) - 1

    def test_make_baseline_matches_engine_format(self):
        findings = analyze_paths(
            [os.path.join(FIXTURES, "bad_donation.py")])
        data = make_baseline(findings)
        assert data["version"] == 1
        assert all(set(e) == {"rule", "path", "snippet"}
                   for e in data["entries"])


class TestRepoBaselineFile:
    def test_committed_baseline_is_valid_and_minimal(self):
        """The committed jsan_baseline.json must parse and contain only
        entries that still match a real finding — stale grandfather
        entries hide future regressions at the same line."""
        path = os.path.join(REPO, "jsan_baseline.json")
        with open(path) as f:
            data = json.load(f)
        assert data["version"] == 1
        current = {f.baseline_key for f in analyze_paths(
            [os.path.join(REPO, "rlgpuschedule_tpu"),
             os.path.join(REPO, "bench.py"),
             os.path.join(REPO, "__graft_entry__.py")])}
        stale = [e for e in data["entries"]
                 if (e["rule"], e["path"], e["snippet"]) not in current]
        assert stale == [], f"stale baseline entries: {stale}"
