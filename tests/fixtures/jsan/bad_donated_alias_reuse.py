"""Known-bad: host reads of donated names after dispatch (2 findings).

``donate_argnums=(0,)`` lets XLA reuse ``state``'s pages for the
outputs — after the dispatch the Python name refers to a deleted
buffer, and reading it returns garbage without raising.
"""
import jax


def _decide(state, batch):
    return state + batch


class Engine:
    def __init__(self):
        self._step = jax.jit(_decide, donate_argnums=(0,))

    def run(self, state, batch):
        new = self._step(state, batch)
        stale = state.mean()           # finding: read after donation
        return new, stale

    def double_dispatch(self, state, batch):
        self._step(state, batch)
        return self._step(state, batch)   # finding: re-dispatch donated
