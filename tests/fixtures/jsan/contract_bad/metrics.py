"""Fixture metric surface: the ``class Registry`` anchor contract-drift
audits from (no allowlist here — everything must be consumed)."""


class Registry:
    def __init__(self):
        self.names = []

    def counter(self, name, help=""):
        self.names.append(name)
        return name

    def gauge(self, name, help=""):
        self.names.append(name)
        return name

    def histogram(self, name, help="", buckets=()):
        self.names.append(name)
        return name
