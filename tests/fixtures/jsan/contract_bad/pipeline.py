"""Fixture emitter: registers metrics and emits kinds the consumers
reference (or fail to)."""
from events import EventBus
from metrics import Registry


def run(n):
    reg = Registry()
    bus = EventBus()
    rows = reg.counter("pipe_rows_total", "rows processed")
    dropped = reg.counter("pipe_dropped_total",   # orphan: consumed nowhere
                          "rows dropped")
    for i in range(n):
        bus.emit("step_done", step=i)
        bus.emit("debug_tick", step=i)            # orphan: consumed nowhere
    return rows, dropped
