#!/usr/bin/env bash
set -euo pipefail

python pipeline.py --out out.prom
grep -q "pipe_rows_total" out.prom
# ghost: the metric is pipe_rows_total — this grep matches nothing
grep -q "pipe_row_total{" out.prom

python - out.jsonl <<'EOF'
import json
import sys

events = [json.loads(line) for line in open(sys.argv[1])]
kinds = {e["kind"] for e in events}
for k in ("step_done",):
    assert k in kinds
EOF
