"""Fixture consumer: one ghost kind reference and a stale golden pin."""

# wrong magic: wire.py says b"PBIN" version 2 — this pin predates both
GOLDEN_ROW_PREFIX = b"XBIN\x01\x01\x04\x00"


def test_step_events(events):
    assert any(e["kind"] == "step_done" for e in events)
    # ghost: nothing emits "step_finished" (renamed to step_done)
    assert not any(e["kind"] == "step_finished" for e in events)
