"""Fixture event surface: the ``class EventBus`` anchor."""


class EventBus:
    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        event = {"kind": kind, **fields}
        self.events.append(event)
        return event
