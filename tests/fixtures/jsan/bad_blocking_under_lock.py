"""Known-bad: unbounded blocking waits inside a held lock (3 findings)."""
import queue
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._drain_loop)

    def _drain_loop(self):
        with self._lock:
            item = self._q.get()                         # finding
            self._q.put(item)                            # finding

    def stop(self):
        with self._lock:
            self._t.join()                               # finding

    def start(self):
        self._t.start()
