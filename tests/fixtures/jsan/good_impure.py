"""Known-good: jax.random in traced code, host timing outside (0 findings)."""
import time

import jax


@jax.jit
def noisy_update(state, batch, key):
    noise = jax.random.normal(key, batch.shape)
    jax.debug.print("updating {}", noise.sum())
    return state + batch + noise


def timed_dispatch(state, batch, key):
    t0 = time.time()   # host-side timing around the dispatch is the idiom
    out = noisy_update(state, batch, key)
    jax.block_until_ready(out)
    return out, time.time() - t0
