"""Known-bad: state-threading jit without donation (2 findings)."""
import jax


def update(state, batch):
    state = state + batch.mean()
    return state, {"loss": batch.mean()}


step = jax.jit(update)              # finding: no donate_argnums


@jax.jit                             # finding: decorator form, no donate
def train_step(state, x):
    state = state * x
    return state, x
