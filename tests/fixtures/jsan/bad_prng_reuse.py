"""Known-bad: PRNG key reuse (3 findings)."""
import jax


def sample_pair(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))    # finding: key already consumed
    return a, b


def shuffle_twice(key, xs):
    perm1 = jax.random.permutation(key, xs)
    perm2 = jax.random.permutation(key, xs)   # finding: identical perms
    return perm1, perm2


def loop_draws(key, n):
    out = []
    for _ in range(n):
        out.append(jax.random.normal(key))    # finding: same draw per iter
    return out
