"""Known-good caller: one guard whose keys cover every refused pair."""
import argparse
import sys

from configs import ModeCombinationError, validate_mode_combination


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--async", dest="async_run", action="store_true")
    p.add_argument("--pbt", action="store_true")
    p.add_argument("--mesh", default="off")
    args = p.parse_args(argv)
    try:
        validate_mode_combination({"async": args.async_run,
                                   "pbt": args.pbt,
                                   "mesh": args.mesh != "off"})
    except ModeCombinationError as e:
        sys.exit(str(e))
    return args
