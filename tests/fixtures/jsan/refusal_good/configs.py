"""Known-good defining module: every refusal row has a guard in train.py."""


class ModeCombinationError(ValueError):
    pass


MODE_FLAGS = {
    "async": "--async",
    "pbt": "--pbt",
    "mesh": "--mesh",
}

MODE_REFUSALS = (
    ("async", "pbt",
     "the async engine owns the population schedule"),
    ("pbt", "mesh",
     "the PBT controller assumes the plain unsharded build"),
)


def validate_mode_combination(active):
    for key in active:
        if key not in MODE_FLAGS:
            raise KeyError(key)
    for a, b, why in MODE_REFUSALS:
        if active.get(a) and active.get(b):
            raise ModeCombinationError(why)
