"""Known-good twin of bad_torn_publish (0 findings): only copies cross
the thread boundary, so the receiver owns its memory outright."""
import queue
import threading

import numpy as np


class Fanout:
    def __init__(self, ring):
        self.ring = ring
        self.q = queue.Queue()

    def pump_loop(self):
        blk = self.ring.take_block()
        rows = blk.obs[:8]
        self.q.put(rows.copy())        # the receiver owns this copy
        self.ring.recycle(blk)

    def offload(self, pool, buf):
        view = np.frombuffer(buf, dtype=np.float32)
        pool.submit(self._consume, np.array(view))   # fresh array
        return len(buf)

    def _consume(self, arr):
        return arr.sum()

    def start(self):
        t = threading.Thread(target=self.pump_loop)
        t.start()
        return t
