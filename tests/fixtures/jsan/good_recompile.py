"""Known-good: jit at module scope or memoized (0 findings)."""
import jax
import jax.numpy as jnp

_copy_tree = jax.jit(lambda t: jax.tree.map(jnp.copy, t))

_CACHE: dict = {}


def _double(x):
    return x * 2


def cached_double(x):
    # memoization idiom: jit result stored through a subscript target
    fn = _CACHE.get("double")
    if fn is None:
        fn = _CACHE["double"] = jax.jit(_double)
    return fn(x)


class Runner:
    def __init__(self):
        self._step = None

    def run(self, state, batch):
        if self._step is None:
            self._step = jax.jit(_double)   # attribute target: memoized
        return self._step(state) + batch
