"""Known-bad: thread-side device dispatch with no lock held (3 findings)."""
import threading

import jax


def _step(x):
    return x + 1


class Engine:
    def __init__(self, x):
        self._fn = jax.jit(_step).lower(x).compile()

    def _serve_loop(self, x):
        on_device = jax.device_put(x)                    # finding
        out = self._fn(on_device)                        # finding
        return jax.device_get(out)                       # finding

    def start(self, x):
        t = threading.Thread(target=self._serve_loop, args=(x,))
        t.start()
        return t
