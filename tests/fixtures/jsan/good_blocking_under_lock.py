"""Known-good: waits bounded by a timeout or moved outside the lock."""
import queue
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._drain_loop)

    def _drain_loop(self):
        with self._lock:
            item = self._q.get(timeout=0.5)
            self._q.put(item, block=False)
        self._q.put(item)

    def summary(self, parts):
        with self._lock:
            return ",".join(parts)

    def stop(self):
        self._t.join()

    def start(self):
        self._t.start()
