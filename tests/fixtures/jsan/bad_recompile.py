"""Known-bad: jit cache defeated by construction (2 findings — the
jit-in-loop finding subsumes the fresh-lambda one on the same call)."""
import jax
import jax.numpy as jnp


def copy_tree(tree):
    # fresh lambda per call -> fresh cache entry per call
    return jax.jit(lambda t: jax.tree.map(jnp.copy, t))(tree)


def train(batches, state):
    for batch in batches:
        step = jax.jit(lambda s, b: s + b)   # findings: jit in loop body
        state = step(state, batch)           # (loop + fresh lambda)
    return state
