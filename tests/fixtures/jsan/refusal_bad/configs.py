"""Known-bad defining module: a refusal row nobody guards (1 finding)."""


class ModeCombinationError(ValueError):
    pass


MODE_FLAGS = {
    "async": "--async",
    "pbt": "--pbt",
    "mesh": "--mesh",
    "sync": "the synchronous loop (no --async)",
}

MODE_REFUSALS = (
    ("async", "pbt",
     "the async engine owns the population schedule"),
    ("pbt", "mesh",                                      # finding: unguarded row
     "no guard anywhere in this tree references the pair"),
)


def validate_mode_combination(active):
    for a, b, why in MODE_REFUSALS:
        if a not in active or b not in active:
            continue
        if active[a] and active[b]:
            raise ModeCombinationError(why)
    for key in active:
        if key not in MODE_FLAGS:
            raise KeyError(key)
