"""Known-bad caller: unknown guard key + ad-hoc refusal (2 findings)."""
import argparse

from configs import ModeCombinationError, validate_mode_combination


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--async", dest="async_run", action="store_true")
    p.add_argument("--pbt", action="store_true")
    args = p.parse_args(argv)
    validate_mode_combination({"async": args.async_run,  # finding: "delta"
                               "pbt": args.pbt,
                               "delta": False})
    if args.pbt and args.async_run:
        raise ModeCombinationError(                      # finding: ad-hoc
            "pbt is incompatible with async")
    return args
