"""Known-bad caller: exposes a refusable flag pair, no guard (1 finding)."""
import argparse


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--pbt", action="store_true")         # finding anchors here
    p.add_argument("--mesh", default="off")
    return p.parse_args(argv)
