"""Known-good: thread-side dispatch serialized behind the conditional lock."""
import contextlib
import threading

import jax

_ON_CPU = True


def _step(x):
    return x + 1


class Engine:
    def __init__(self, x):
        self._dispatch_lock = (threading.Lock() if _ON_CPU
                               else contextlib.nullcontext())
        self._fn = jax.jit(_step).lower(x).compile()

    def _serve_loop(self, x):
        with self._dispatch_lock:
            on_device = jax.device_put(x)
            out = self._fn(on_device)
            return jax.device_get(out)

    def start(self, x):
        t = threading.Thread(target=self._serve_loop, args=(x,))
        t.start()
        return t
