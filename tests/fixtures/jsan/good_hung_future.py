"""Known-good: every wait bounded, non-blocking, or off the queue path."""
import queue
import threading


class Dispatcher:
    def __init__(self):
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._pump_loop, daemon=True)

    def _pump_loop(self):
        while True:
            try:
                fut = self._q.get(timeout=0.5)           # bounded
            except queue.Empty:
                return
            fut.set_result(None)

    def wait(self, fut):
        return fut.result(timeout=30.0)                  # bounded

    def poll(self):
        return self._q.get_nowait()                      # non-blocking

    def label(self, parts):
        return ",".join(parts)                           # not a queue
