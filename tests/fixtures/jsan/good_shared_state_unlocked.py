"""Known-good: every cross-thread write shares one lock region (the
Condition wraps the same lock, so holding either holds the region)."""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._n = 0
        self._err = None

    def _count_loop(self):
        with self._lock:
            self._n = self._n + 1

    def _drain_loop(self):
        with self._wake:
            self._n = self._n + 1
            self._wake.notify_all()

    def _watch_loop(self):
        with self._lock:
            self._err = "boom"

    def reset(self):
        with self._lock:
            self._err = None

    def start(self):
        threading.Thread(target=self._count_loop).start()
        threading.Thread(target=self._drain_loop).start()
        threading.Thread(target=self._watch_loop).start()
