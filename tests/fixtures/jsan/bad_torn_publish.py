"""Known-bad: live slab/frombuffer views handed across threads
(2 findings).

The receiving thread cannot see the sender's recycle schedule — it
reads half of batch N and half of batch N+1, or a foreign batch.
"""
import queue
import threading

import numpy as np


class Fanout:
    def __init__(self, ring):
        self.ring = ring
        self.q = queue.Queue()

    def pump_loop(self):
        blk = self.ring.take_block()
        rows = blk.obs[:8]
        self.q.put(rows)               # finding: live view across threads
        self.ring.recycle(blk)

    def offload(self, pool, buf):
        view = np.frombuffer(buf, dtype=np.float32)
        pool.submit(self._consume, view)   # finding: view into executor
        return len(buf)

    def _consume(self, arr):
        return arr.sum()

    def start(self):
        t = threading.Thread(target=self.pump_loop)
        t.start()
        return t
