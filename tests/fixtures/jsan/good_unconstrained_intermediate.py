"""Known-good: every mesh-traced batch builder is pinned (or the
module has no mesh at all)."""
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

mesh = Mesh(jax.devices(), ("data",))
env_sharded = NamedSharding(mesh, PartitionSpec("data"))


@jax.jit
def fuse_batches(a, b):
    batch = jnp.concatenate([a, b])
    batch = jax.lax.with_sharding_constraint(batch, env_sharded)
    return batch * 2


def make_rollout_step(apply_fn, constrain):
    def rollout_step(params, obs_list):
        # built directly inside the constrainer call — pinned at birth
        obs = constrain(jnp.stack(obs_list), "data")
        return apply_fn(params, obs)

    return rollout_step


def host_side_prep(rows):
    # not traced: host-side batch assembly is outside the rule's scope
    return jnp.concatenate(rows)
