"""Known-good donation discipline (0 findings)."""
import jax


def update(state, batch):
    state = state + batch.mean()
    return state, {"loss": batch.mean()}


step = jax.jit(update, donate_argnums=(0,))


def project(params, x):
    # not state-threading: nothing returned leads with the first param
    return x @ params["w"]


infer = jax.jit(project)


def rollback_update(state, batch):
    state = state + batch
    return state, batch


# deliberate non-donation, documented inline
keep = jax.jit(rollback_update)  # jsan: disable=donation-discipline -- rollback keeps the old state live
