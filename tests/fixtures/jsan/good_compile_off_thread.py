"""Known-good: programs AOT-compiled at construction, on the main thread."""
import contextlib
import threading

import jax

_ON_CPU = True


def _step(x):
    return x * 2


class Engine:
    def __init__(self, x):
        self._fn = jax.jit(_step).lower(x).compile()
        self._lock = (threading.Lock() if _ON_CPU
                      else contextlib.nullcontext())

    def _actor_loop(self, x):
        with self._lock:
            return self._fn(x)

    def start(self, x):
        t = threading.Thread(target=self._actor_loop, args=(x,))
        t.start()
        return t
