"""Known-bad: unbounded waits a dead resolver turns into silent hangs (3 findings)."""
import queue
import threading


class Dispatcher:
    def __init__(self):
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._pump_loop, daemon=True)

    def _pump_loop(self):
        while True:
            fut = self._q.get()                          # finding
            fut.set_result(None)

    def wait(self, fut):
        return fut.result()                              # finding

    def first(self):
        return self._q.get()                             # finding
