"""Known-bad: per-iteration host syncs in a dispatch loop (3 findings)."""
import jax
import numpy as np


def make_train_step(apply_fn):
    def train_step(state, batch):
        return apply_fn(state, batch), {"loss": batch.sum()}

    return train_step


def drive(apply_fn, state, batches):
    train_step = make_train_step(apply_fn)
    losses = []
    for batch in batches:
        state, metrics = train_step(state, batch)
        losses.append(float(metrics["loss"]))   # finding: float per iter
        print(metrics["loss"].item())           # finding: .item per iter
        np.asarray(state)                       # finding: asarray per iter
    return state, losses
