"""Known-bad: reads reachable after the storage's kill point
(3 findings).

After ``ring.recycle(blk)`` the slab belongs to the next batch; after
``recv_into(buf)`` the old ``frombuffer`` view maps the new message.
"""
import numpy as np


class Pump:
    def __init__(self, ring):
        self.ring = ring

    def pump(self, n):
        blk = self.ring.take_block()
        rows = blk.obs[:n]
        total = rows.sum()
        self.ring.recycle(blk)
        top = float(rows[0])           # finding: strong use after recycle
        return total, top

    def weak_leak(self, summarize):
        blk = self.ring.take_block()
        info = summarize(blk)          # opaque helper: weak taint
        self.ring.recycle(blk)
        return info["rows"]            # finding: weak deref after recycle


def drain(sock, buf):
    view = np.frombuffer(buf, dtype=np.uint8)
    first = int(view[0])
    sock.recv_into(buf)                # in-place reuse kills the view
    return first, int(view[1])         # finding: deref after recv_into
