"""Known-good: each thread owns its own donated program."""
import threading

import jax


def _update(state, x):
    return state + x


class Runner:
    def __init__(self, state, x):
        self._lock = threading.Lock()
        jitted = jax.jit(_update, donate_argnums=(0,))
        self._a_step = jitted.lower(state, x).compile()
        self._b_step = jitted.lower(state, x).compile()

    def _a_loop(self, state, x):
        with self._lock:
            return self._a_step(state, x)

    def _b_loop(self, state, x):
        with self._lock:
            return self._b_step(state, x)

    def start(self, state, x):
        ta = threading.Thread(target=self._a_loop, args=(state, x))
        tb = threading.Thread(target=self._b_loop, args=(state, x))
        ta.start()
        tb.start()
        return ta, tb
