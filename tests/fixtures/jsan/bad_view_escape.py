"""Known-bad: slab/frombuffer views escaping their frame (4 findings).

Every escape hands borrowed memory to a holder that cannot see the
arena's recycle schedule: a later batch rewrites the slab under the
stored/returned view.
"""
import numpy as np

_STASH = []


class Pump:
    def __init__(self, ring):
        self.ring = ring
        self.last_rows = None

    def pump(self, n):
        blk = self.ring.take_block()
        rows = blk.obs[:n]
        self.last_rows = rows          # finding: stored on self
        _STASH.append(rows)            # finding: module-global container
        return blk                     # finding: returned, no contract


def parse(buf, shape):
    arr = np.frombuffer(buf, dtype=np.float32)
    return arr.reshape(shape)          # finding: returned, no contract
