"""Known-good twin of bad_use_after_recycle (0 findings): reads happen
before the kill, a handler-path recycle does not poison the happy path,
weak results are only returned (never dereferenced), and rebinding
``buf = sock.recv(n)`` keeps the old bytes alive under the old view."""
import numpy as np


class Pump:
    def __init__(self, ring):
        self.ring = ring

    def pump(self, n):
        blk = self.ring.take_block()
        rows = blk.obs[:n]
        top = float(rows[0])           # materialized BEFORE the recycle
        total = rows.sum()
        self.ring.recycle(blk)
        return total, top

    def pump_with_fault_path(self, n, dispatch):
        blk = self.ring.take_block()
        rows = blk.obs[:n]
        try:
            dispatch(rows)
        except RuntimeError:
            self.ring.recycle(blk)     # error path only, then re-raise
            raise
        first = float(rows[0])         # happy path: still live
        self.ring.recycle(blk)
        return first

    def weak_count(self, summarize):
        blk = self.ring.take_block()
        n_live = summarize(blk)        # weak: a count, not a view
        self.ring.recycle(blk)
        return n_live                  # no deref -> clean


def drain(sock, n):
    buf = sock.recv(n)
    view = np.frombuffer(buf, dtype=np.uint8)
    buf = sock.recv(n)                 # REBIND: old bytes stays alive
    return int(view[0]), buf
