"""Known-bad: one donated program executed from two threads (1 finding).

The dispatch lock does NOT make this safe — donation is structural, one
executing thread per donated program — so both loops lock and the rule
still fires (and device-dispatch-unlocked stays quiet).
"""
import threading

import jax


def _update(state, x):
    return state + x


class Runner:
    def __init__(self, state, x):
        self._lock = threading.Lock()
        self._step = jax.jit(                            # finding
            _update, donate_argnums=(0,)).lower(state, x).compile()

    def _a_loop(self, state, x):
        with self._lock:
            return self._step(state, x)

    def _b_loop(self, state, x):
        with self._lock:
            return self._step(state, x)

    def start(self, state, x):
        ta = threading.Thread(target=self._a_loop, args=(state, x))
        tb = threading.Thread(target=self._b_loop, args=(state, x))
        ta.start()
        tb.start()
        return ta, tb
