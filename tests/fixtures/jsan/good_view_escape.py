"""Known-good twin of bad_view_escape: every boundary is either a copy
or a documented view contract (0 findings)."""
import numpy as np

_STASH = []


class Pump:
    def __init__(self, ring):
        self.ring = ring
        self.last_rows = None

    def pump(self, n):
        blk = self.ring.take_block()
        rows = blk.obs[:n]
        self.last_rows = rows.copy()   # copy ends the taint chain
        _STASH.append(np.array(rows))  # fresh array, not a view
        total = float(rows.sum())      # scalar, not a view
        return total

    def views(self, n):
        """Rows of the current block (views, never copies): only safe
        until the caller's recycle — the documented-contract idiom."""
        blk = self.ring.take_block()
        return blk.obs[:n]


def parse(buf, shape):
    arr = np.frombuffer(buf, dtype=np.float32)
    return arr.reshape(shape).copy()   # defensive copy at the boundary
