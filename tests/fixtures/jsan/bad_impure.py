"""Known-bad: host entropy / side effects in traced code (3 findings)."""
import time

import jax
import numpy as np


@jax.jit
def noisy_update(state, batch):
    noise = np.random.normal(size=batch.shape)   # finding: baked-in sample
    t0 = time.time()                             # finding: trace-time stamp
    print("updating", t0)                        # finding: trace-time print
    return state + batch + noise
