"""Known-good: batched materialization at the log cadence (0 findings)."""
import jax


def make_train_step(apply_fn):
    def train_step(state, batch):
        return apply_fn(state, batch), {"loss": batch.sum()}

    return train_step


def drive(apply_fn, state, batches, log_every=10):
    train_step = make_train_step(apply_fn)
    window, rows = [], []
    for i, batch in enumerate(batches):
        state, metrics = train_step(state, batch)
        window.append(metrics)              # device refs: free to hold
        if (i + 1) % log_every == 0:
            host = jax.device_get(window)   # ONE batched pull per cadence
            rows.extend(float(m["loss"]) for m in host)
            window.clear()
    return state, rows
