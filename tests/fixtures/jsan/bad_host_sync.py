"""Known-bad: host syncs inside trace-reachable functions (4 findings)."""
import jax
import numpy as np


@jax.jit
def loss_scalar(params, batch):
    loss = (params * batch).sum()
    return loss.item()                  # finding: .item() in jit


def make_train_step(apply_fn):
    def train_step(state, batch):
        pred = apply_fn(state, batch)
        host = np.asarray(pred)         # finding: np.asarray in factory step
        scale = float(batch)            # finding: float() on traced arg
        return state, host * scale

    return train_step


def body(carry, x):
    return carry, np.array(x)           # finding: np.array in scanned body


def scan_it(xs):
    return jax.lax.scan(body, 0.0, xs)
