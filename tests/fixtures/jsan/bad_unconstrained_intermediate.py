"""Known-bad: unconstrained batch builders in mesh-traced code (2
findings)."""
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

mesh = Mesh(jax.devices(), ("data",))


@jax.jit
def fuse_batches(a, b, table):
    batch = jnp.concatenate([a, b])          # finding: never constrained
    tiled = jnp.tile(table, (batch.shape[0], 1))   # finding: ditto
    return batch @ tiled.T


def make_rollout_step(apply_fn):
    def rollout_step(params, obs_list):
        obs = jnp.stack(obs_list)            # pinned below — no finding
        obs = jax.lax.with_sharding_constraint(
            obs, NamedSharding(mesh, PartitionSpec("data")))
        return apply_fn(params, obs)

    return rollout_step
