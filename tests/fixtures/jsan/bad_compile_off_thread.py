"""Known-bad: jit/AOT compilation reachable from thread roots (3 findings)."""
import threading
from concurrent.futures import ThreadPoolExecutor

import jax

_CACHE = {}


def _step(x):
    return x * 2


def _warm(x):
    _CACHE["step"] = jax.jit(_step)                      # finding: thread target
    return x


def _warm_aot(x):
    _CACHE["aot"] = jax.jit(_step).lower(x).compile()    # finding: submitted
    return x


class Engine:
    def __init__(self):
        self._fn = None

    def _actor_loop(self, x):
        self._fn = jax.jit(_step)                        # finding: loop root
        return x

    def start(self, x):
        t = threading.Thread(target=self._actor_loop, args=(x,))
        t.start()
        threading.Thread(target=_warm, args=(x,)).start()
        with ThreadPoolExecutor(max_workers=1) as ex:
            ex.submit(_warm_aot, x)
        return t
