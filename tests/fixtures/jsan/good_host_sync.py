"""Known-good: host materialization stays in the host loop (0 findings)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def loss_fn(params, batch):
    return (params * batch).sum().astype(jnp.float32)


def host_loop(params, batches):
    for batch in batches:
        loss = loss_fn(params, batch)
        # host code may materialize freely — not trace-reachable
        print(float(np.asarray(loss)))
