"""Known-bad: fresh ndarray construction on dispatcher paths (3)."""
import threading

import numpy as np


class Dispatcher:
    def __init__(self):
        self._pending = []
        self._t = threading.Thread(target=self.pump_loop)

    def _stack(self, rows):
        return np.stack(rows)                            # finding

    def pump_loop(self):
        while self._pending:
            rows, self._pending = self._pending, []
            batch = self._stack(rows)
            pad = np.zeros((8 - len(rows),) + batch.shape[1:])   # finding
            self.dispatch(np.concatenate([batch, pad]))          # finding

    def dispatch(self, batch):
        pass

    def start(self):
        self._t.start()
