"""Fixture metric surface (good twin): anchor + allowlist — the
sanctioned channel for operator-only metrics no gate consumes."""

CONTRACT_ALLOWLIST = (
    "pipe_ops_seconds",        # operator dashboard only, no CI gate
)


class Registry:
    def __init__(self):
        self.names = []

    def counter(self, name, help=""):
        self.names.append(name)
        return name

    def gauge(self, name, help=""):
        self.names.append(name)
        return name

    def histogram(self, name, help="", buckets=()):
        self.names.append(name)
        return name
