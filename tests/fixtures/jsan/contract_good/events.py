"""Fixture event surface (good twin): anchor + allowlisted kind."""

CONTRACT_ALLOWLIST = (
    "debug_tick",              # developer breadcrumb, nothing gates it
)


class EventBus:
    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        event = {"kind": kind, **fields}
        self.events.append(event)
        return event
