"""Fixture wire surface (good twin): same anchor, pinned by a correct
golden in tests/."""
import struct

MAGIC = b"PBIN"
VERSION = 2
KIND_ROW = 1

PREFIX = struct.Struct("<4sBBH")     # magic, version, kind, length
PREFIX_SIZE = PREFIX.size            # 8 bytes


def pack_row(kind, payload):
    return PREFIX.pack(MAGIC, VERSION, kind, len(payload)) + payload
