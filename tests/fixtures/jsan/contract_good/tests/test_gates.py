"""Fixture consumer (good twin): every reference names a live emission;
the local registration is exempt from the ghost check."""
from metrics import Registry

# matches wire.py exactly: MAGIC b"PBIN", VERSION 2, KIND_ROW, len 4
GOLDEN_ROW_PREFIX = b"PBIN\x02\x01\x04\x00"


def test_step_events(events):
    assert any(e["kind"] == "step_done" for e in events)


def test_dropped_counter(prom_text):
    assert "pipe_dropped_total 0" in prom_text
    # per-phase counters come from the f-string registration
    assert "pipe_phase_warmup_total" in prom_text


def test_local_registry_is_not_a_reference():
    reg = Registry()
    assert reg.counter("pipe_fixture_total") == "pipe_fixture_total"
