"""Fixture emitter (good twin): every emission is either consumed or
allowlisted; the f-string registration resolves to a pattern."""
from events import EventBus
from metrics import Registry


def run(n, phase):
    reg = Registry()
    bus = EventBus()
    rows = reg.counter("pipe_rows_total", "rows processed")
    dropped = reg.counter("pipe_dropped_total", "rows dropped")
    reg.gauge("pipe_ops_seconds", "op wall time")     # allowlisted
    reg.counter(f"pipe_phase_{phase}_total", "per-phase rows")
    for i in range(n):
        bus.emit("step_done", step=i)
        bus.emit("debug_tick", step=i)                # allowlisted
    return rows, dropped
