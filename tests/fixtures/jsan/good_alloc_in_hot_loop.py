"""Known-good: slab reuse on the hot path; allocation only at setup."""
import threading

import numpy as np


class Dispatcher:
    def __init__(self, bucket=8, width=6):
        self._pending = []
        # slab construction happens once, on the main thread
        self._slab = np.zeros((bucket, width), dtype=np.float32)
        self._t = threading.Thread(target=self.pump_loop)

    def pump_loop(self):
        while self._pending:
            rows, self._pending = self._pending, []
            for i, row in enumerate(rows):
                self._slab[i] = row          # write-in-place, no alloc
            self._slab[len(rows):] = 0.0     # tail neutralized by slice
            self.dispatch(self._slab)

    def grow(self, bucket, width):
        # main-thread resize helper: not reachable from the loop
        self._slab = np.zeros((bucket, width), dtype=np.float32)

    def dispatch(self, batch):
        pass

    def start(self):
        self._t.start()
