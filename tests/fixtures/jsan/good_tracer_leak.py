"""Known-good: static Python branches + device-side selects (0 findings)."""
import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Config:
    use_bf16: bool = False
    n_layers: int = 2


@jax.jit
def select(x):
    return jnp.where(x > 1.0, jnp.clip(x, -1.0, 1.0), x)


def make_step(config: Config):
    def step(state, batch):
        # static config branch: decided at trace time, on purpose
        if config.use_bf16:
            batch = batch.astype(jnp.bfloat16)
        for _ in range(config.n_layers):
            state = state * batch
        return state, batch

    return step
