"""Known-good twin of bad_donated_alias_reuse (0 findings): the rebind
idiom threads the donated name through the dispatch, and anything that
must survive is copied out before it."""
import jax
import jax.numpy as jnp


def _decide(state, batch):
    return state + batch


class Engine:
    def __init__(self):
        self._step = jax.jit(_decide, donate_argnums=(0,))

    def run(self, state, batch):
        state = self._step(state, batch)   # rebind THROUGH the dispatch
        return state, state.mean()         # reads the new buffer

    def run_keeping_snapshot(self, state, batch):
        snapshot = jnp.array(state)        # pre-dispatch copy survives
        state = self._step(state, batch)
        return state, snapshot.mean()
