"""Known-bad: shared attributes written from racing threads (2 findings)."""
import threading


class Stats:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._n = 0
        self._err = None

    def _count_loop(self):
        with self._a_lock:
            self._n = self._n + 1                        # finding: disjoint locks

    def _drain_loop(self):
        with self._b_lock:
            self._n = self._n + 1

    def _watch_loop(self):
        self._err = "boom"                               # finding: races reset()

    def reset(self):
        self._err = None

    def start(self):
        threading.Thread(target=self._count_loop).start()
        threading.Thread(target=self._drain_loop).start()
        threading.Thread(target=self._watch_loop).start()
