"""Known-bad: Python control flow on traced values (3 findings)."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp(x):
    if jnp.any(x > 1.0):                # finding: if on traced expr
        x = jnp.clip(x, -1.0, 1.0)
    while jnp.sum(x) > 10.0:            # finding: while on traced expr
        x = x * 0.5
    return x


def make_step(apply_fn):
    def step(state, batch):
        out = apply_fn(state, batch)
        assert jnp.all(out >= 0)        # finding: assert on traced expr
        return state, out

    return step
