"""Known-good: one consumption per key (0 findings)."""
import jax


def sample_pair(key):
    ka, kb = jax.random.split(key)
    return jax.random.normal(ka, (4,)), jax.random.uniform(kb, (4,))


def resplit(key):
    key, sub = jax.random.split(key)      # key rebound by the same stmt
    x = jax.random.normal(sub)
    key, sub = jax.random.split(key)      # fine: key was rebound above
    return x + jax.random.normal(sub), key


def loop_draws(key, n):
    out = []
    for _ in range(n):
        key, sub = jax.random.split(key)  # rebound inside the loop body
        out.append(jax.random.normal(sub))
    return out


def derived(key, i):
    # fold_in derives without consuming; reuse afterwards is legal
    per_step = jax.random.fold_in(key, i)
    return jax.random.normal(per_step), jax.random.normal(key)
