"""Cluster chaos engine (ISSUE 6): the in-simulator fault process.

Three contracts pin the tentpole:

1. **Oracle parity under faults** — the jitted branch-free fault path
   (drain kills, straggler stretch, fault-transition events, masked
   placement) reproduces ``OracleSim(faults=...)`` trajectory-for-
   trajectory on integer-valued traces/schedules (f32-exact, same
   regime as tests/test_sim_core.py).
2. **Conservation invariants** — at EVERY step of random action
   sequences, with and without faults/preemption: per-node
   ``free + allocated == capacity``, RUNNING jobs hold exactly their
   gang, everything else holds nothing, and no valid job ever leaves
   the NOT_ARRIVED/PENDING/RUNNING/DONE lifecycle (a drain delays jobs,
   never loses them).
3. **Schedules are data, not code** — stepping under two different
   FaultSchedules of the same shape must not retrace the jitted step
   (CompileCounter-asserted; the zero-recompile contract the whole
   vec-env/scan stack depends on).
"""
import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu.sim import core as C
from rlgpuschedule_tpu.sim import faults as F
from rlgpuschedule_tpu.sim import oracle as O
from rlgpuschedule_tpu.sim.schedulers import run_baseline
from rlgpuschedule_tpu.traces import JobRecord, to_array_trace


def int_trace(rng, n_jobs, max_gpus, max_jobs=None):
    """Random integer-valued trace (exact in float32)."""
    jobs, t = [], 0
    for i in range(n_jobs):
        t += int(rng.integers(0, 30))
        jobs.append(JobRecord(i, float(t), float(rng.integers(1, 50)),
                              int(rng.integers(1, max_gpus + 1)),
                              int(rng.integers(0, 3))))
    return to_array_trace(jobs, max_jobs=max_jobs)


def int_faults(rng, n_nodes, n_waves=2):
    """Random integer-valued fault schedule; dyadic slowdowns keep f32
    stretched time exact (the parity-test regime)."""
    fs = F.no_faults(n_nodes, n_waves)
    for n in range(n_nodes):
        if rng.random() < 0.6:
            t = 0
            for w in range(int(rng.integers(1, n_waves + 1))):
                t += int(rng.integers(1, 120))
                d = int(rng.integers(1, 60))
                fs.down_start[n, w] = t
                fs.down_end[n, w] = t + d
                t += d
        if rng.random() < 0.5:
            fs.slowdown[n] = float(rng.choice([2.0, 4.0]))
    return F.validate_fault_schedule(n_nodes, fs)


def device_faults(fs):
    return jax.tree.map(jnp.asarray, fs)


class TestFaultScheduleBasics:
    def test_node_up_half_open_interval(self):
        fs = F.fault_schedule_from_events(2, [1], [5.0], [10.0])
        fsd = device_faults(fs)
        for t, want in [(0.0, [1, 1]), (5.0, [1, 0]), (14.9, [1, 0]),
                        (15.0, [1, 1])]:
            np.testing.assert_array_equal(
                np.asarray(F.node_up(fsd, jnp.float32(t))), want)

    def test_next_transition_strictly_after(self):
        fs = device_faults(F.fault_schedule_from_events(2, [1], [5.0],
                                                        [10.0]))
        assert float(F.next_transition(fs, jnp.float32(0.0))) == 5.0
        assert float(F.next_transition(fs, jnp.float32(5.0))) == 15.0
        assert float(F.next_transition(fs, jnp.float32(15.0))) == np.inf

    def test_job_stretch_gang_runs_at_slowest_node(self):
        fs = device_faults(F.FaultSchedule(
            *F.no_faults(3, 1)._replace(
                slowdown=np.array([1.0, 2.0, 4.0], np.float32))))
        alloc = jnp.asarray([[1, 1, 0], [0, 0, 2], [0, 0, 0]], jnp.int32)
        np.testing.assert_allclose(np.asarray(F.job_stretch(fs, alloc)),
                                   [2.0, 4.0, 1.0])

    def test_straggler_stretches_completion(self):
        trace = to_array_trace([JobRecord(0, 0.0, 10.0, 1)], max_jobs=2)
        params = C.SimParams(1, 1, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        fs = device_faults(F.FaultSchedule(
            *F.no_faults(1, 1)._replace(
                slowdown=np.array([2.0], np.float32))))
        state = C.init_state(params, tr)
        state, info = C.rl_step(params, state, tr, jnp.int32(0), fs)
        assert bool(info.placed)
        state, info = C.rl_step(params, state, tr,
                                jnp.int32(params.n_actions - 1), fs)
        # 10s of work at half speed: completes at t=20, not t=10
        assert float(state.clock) == 20.0 and bool(info.done)

    def test_drain_kills_to_pending_and_node_return_recovers(self):
        trace = to_array_trace([JobRecord(0, 0.0, 10.0, 2)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        fs = device_faults(F.fault_schedule_from_events(1, [0], [4.0],
                                                        [6.0]))
        noop = jnp.int32(params.n_actions - 1)
        state = C.init_state(params, tr)
        state, _ = C.rl_step(params, state, tr, jnp.int32(0), fs)  # place
        state, info = C.rl_step(params, state, tr, noop, fs)  # -> drain@4
        s = C.np_state(state)
        assert float(s.clock) == 4.0 and s.status[0] == O.PENDING
        # service preserved: 4 of 10 seconds done, GPUs back to free
        assert s.remaining[0] == 6.0 and s.free.sum() == 2
        # while down: placement masked AND try_place refuses
        mask = np.asarray(C.action_mask(params, state, tr, faults=fs))
        assert not mask[0] and mask[-1]
        _, ok = C.try_place(params, state, tr, jnp.int32(0), jnp.int32(0),
                            fs)
        assert not bool(ok)
        state, info = C.rl_step(params, state, tr, noop, fs)  # -> return@10
        assert float(state.clock) == 10.0
        mask = np.asarray(C.action_mask(params, state, tr, faults=fs))
        assert mask[0]
        state, info = C.rl_step(params, state, tr, jnp.int32(0), fs)
        assert bool(info.placed) and not bool(info.first_placed)
        state, info = C.rl_step(params, state, tr, noop, fs)
        assert bool(info.done) and float(state.clock) == 16.0

    def test_forced_place_fails_under_permanent_drain(self):
        # both nodes' capacity halved forever; the 2-GPU job can never
        # fit: forced-place must NOT fire (and must not lie)
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 2)], max_jobs=2)
        params = C.SimParams(2, 1, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        fs = device_faults(F.FaultSchedule(
            down_start=np.array([[0.0], [np.inf]], np.float32),
            down_end=np.array([[np.inf], [np.inf]], np.float32),
            slowdown=np.ones(2, np.float32)))
        state = C.init_state(params, tr)
        noop = jnp.int32(params.n_actions - 1)
        state, info = C.rl_step(params, state, tr, noop, fs)
        assert not bool(info.placed) and not bool(info.done)
        assert float(info.dt) == 0.0


class TestValidation:
    def test_event_list_node_id_out_of_range(self):
        with pytest.raises(ValueError, match="out of range"):
            F.fault_schedule_from_events(2, [2], [1.0], [1.0])

    def test_event_list_nonpositive_duration(self):
        with pytest.raises(ValueError, match="durations must be positive"):
            F.fault_schedule_from_events(2, [0], [1.0], [0.0])

    def test_event_list_negative_start(self):
        with pytest.raises(ValueError, match="must be >= 0"):
            F.fault_schedule_from_events(2, [0], [-1.0], [1.0])

    def test_unsorted_windows_rejected(self):
        fs = F.no_faults(1, 2)
        fs.down_start[0] = [10.0, 5.0]
        fs.down_end[0] = [12.0, 7.0]
        with pytest.raises(ValueError, match="sorted"):
            F.validate_fault_schedule(1, fs)

    def test_end_before_start_rejected(self):
        fs = F.no_faults(1, 1)
        fs.down_start[0, 0], fs.down_end[0, 0] = 5.0, 5.0
        with pytest.raises(ValueError, match="positive"):
            F.validate_fault_schedule(1, fs)

    def test_slowdown_below_one_rejected(self):
        fs = F.no_faults(1, 1)
        fs.slowdown[0] = 0.5
        with pytest.raises(ValueError, match="slowdown"):
            F.validate_fault_schedule(1, fs)

    def test_node_count_mismatch_rejected(self):
        with pytest.raises(ValueError, match="cluster has 3"):
            F.validate_fault_schedule(3, F.no_faults(2, 1))

    def test_validate_trace_delegates_fault_validation(self):
        params = C.SimParams(2, 2, max_jobs=2, queue_len=2)
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 1)], max_jobs=2)
        C.validate_trace(params, trace, faults=F.no_faults(2, 1))  # ok
        with pytest.raises(ValueError, match="cluster has 2"):
            C.validate_trace(params, trace, faults=F.no_faults(3, 1))

    def test_sampled_regimes_validate_and_seed_deterministically(self):
        for name in F.FAULT_REGIMES:
            a = F.sample_fault_schedule(4, name, (7, 0), 1000.0)
            b = F.sample_fault_schedule(4, name, (7, 0), 1000.0)
            for xa, xb in zip(a, b):
                np.testing.assert_array_equal(xa, xb)
        stats = F.schedule_stats(
            F.sample_fault_schedule(64, "storm", 0, 1000.0))
        assert stats["n_drains"] > 0 and stats["n_permanent"] == 0

    def test_unknown_regime_rejected(self):
        with pytest.raises(ValueError, match="unknown fault regime"):
            F.sample_fault_schedule(2, "meteor", 0, 100.0)


def run_pair_faulty(trace, fs, n_nodes, gpus_per_node, actions, queue_len,
                    n_placements=2, preempt_len=0):
    """Drive oracle and JAX sim with the same actions AND the same fault
    schedule; compare full trajectories after every step."""
    params = C.SimParams(n_nodes=n_nodes, gpus_per_node=gpus_per_node,
                         max_jobs=trace.max_jobs, queue_len=queue_len,
                         n_placements=n_placements, preempt_len=preempt_len)
    osim = O.OracleSim(trace, n_nodes, gpus_per_node, faults=fs)
    tr = C.Trace.from_array_trace(trace)
    fsd = device_faults(fs)
    jstate = C.init_state(params, tr)
    step = jax.jit(lambda s, f, a: C.rl_step(params, s, tr, a, f))
    for i, a in enumerate(actions):
        oinfo = osim.rl_step(int(a), queue_len, n_placements, preempt_len)
        jstate, jinfo = step(jstate, fsd, jnp.int32(a))
        s = C.np_state(jstate)
        ctx = f"step {i} action {a}"
        np.testing.assert_allclose(s.clock, osim.clock, atol=1e-3,
                                   err_msg=ctx)
        np.testing.assert_array_equal(s.status, osim.status, err_msg=ctx)
        np.testing.assert_allclose(s.remaining, osim.remaining, atol=1e-3,
                                   err_msg=ctx)
        np.testing.assert_array_equal(s.alloc, osim.alloc, err_msg=ctx)
        np.testing.assert_array_equal(s.free, osim.free, err_msg=ctx)
        assert bool(jinfo.placed) == oinfo["placed"], ctx
        assert bool(jinfo.preempted) == oinfo["preempted"], ctx
        assert bool(jinfo.first_placed) == oinfo["first_placed"], ctx
        np.testing.assert_allclose(float(jinfo.dt), oinfo["dt"], atol=1e-3,
                                   err_msg=ctx)
        assert bool(jinfo.done) == oinfo["done"], ctx
        if oinfo["done"]:
            break
    return osim, jstate, params


class TestOracleParityUnderFaults:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_actions_match_oracle(self, seed):
        rng = np.random.default_rng(200 + seed)
        trace = int_trace(rng, 20, 4, max_jobs=24)
        fs = int_faults(rng, 3)
        actions = rng.integers(0, 4 * 2 + 1, size=400)
        run_pair_faulty(trace, fs, 3, 2, actions, queue_len=4)

    @pytest.mark.parametrize("seed", range(4))
    def test_random_actions_with_preemption_match_oracle(self, seed):
        rng = np.random.default_rng(300 + seed)
        trace = int_trace(rng, 20, 4, max_jobs=24)
        fs = int_faults(rng, 3)
        n_actions = 4 * 2 + 3 + 1
        actions = rng.integers(0, n_actions, size=500)
        run_pair_faulty(trace, fs, 3, 2, actions, queue_len=4,
                        preempt_len=3)


def assert_invariants(s, trace, params, ctx):
    """The conservation contract (ISSUE 6 satellite): GPUs and jobs are
    conserved at every step, faulty or not."""
    gpus = np.asarray(trace.gpus)
    valid = np.asarray(trace.valid)
    used = s.alloc.sum(axis=0)
    np.testing.assert_array_equal(used + s.free,
                                  np.full(params.n_nodes,
                                          params.gpus_per_node), ctx)
    assert (s.free >= 0).all() and (s.alloc >= 0).all(), ctx
    running = s.status == O.RUNNING
    alloc_j = s.alloc.sum(axis=1)
    np.testing.assert_array_equal(alloc_j[running], gpus[running], ctx)
    assert (alloc_j[~running] == 0).all(), ctx
    live = np.isin(s.status, (O.NOT_ARRIVED, O.PENDING, O.RUNNING, O.DONE))
    assert live[valid].all(), ctx          # no job ever lost
    assert (s.remaining >= -1e-5).all(), ctx


class TestConservationInvariants:
    @pytest.mark.parametrize("seed,faulty,preempt_len", [
        (0, False, 0), (1, False, 2), (2, True, 0), (3, True, 2),
        (4, True, 3), (5, True, 0),
    ])
    def test_random_walk_conserves_gpus_and_jobs(self, seed, faulty,
                                                 preempt_len):
        rng = np.random.default_rng(400 + seed)
        trace = int_trace(rng, 16, 4, max_jobs=20)
        fs = int_faults(rng, 3) if faulty else None
        params = C.SimParams(3, 2, max_jobs=20, queue_len=4,
                             n_placements=2, preempt_len=preempt_len)
        tr = C.Trace.from_array_trace(trace)
        fsd = device_faults(fs) if fs is not None else None
        jstate = C.init_state(params, tr)
        step = jax.jit(lambda s, a: C.rl_step(params, s, tr, a, fsd))
        for i, a in enumerate(rng.integers(0, params.n_actions, size=300)):
            jstate, info = step(jstate, jnp.int32(a))
            assert_invariants(C.np_state(jstate), trace, params,
                              f"seed {seed} step {i}")
            if bool(info.done):
                break

    def test_drained_node_never_hosts_a_running_job(self):
        rng = np.random.default_rng(11)
        trace = int_trace(rng, 12, 4, max_jobs=16)
        fs = int_faults(rng, 3)
        params = C.SimParams(3, 2, max_jobs=16, queue_len=4,
                             n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        fsd = device_faults(fs)
        jstate = C.init_state(params, tr)
        step = jax.jit(lambda s, a: C.rl_step(params, s, tr, a, fsd))
        for a in rng.integers(0, params.n_actions, size=250):
            jstate, info = step(jstate, jnp.int32(a))
            s = C.np_state(jstate)
            up = np.asarray(F.node_up(fsd, jnp.float32(s.clock)))
            assert (s.alloc[:, ~up] == 0).all(), float(s.clock)
            if bool(info.done):
                break


class TestCompileOnceAcrossSchedules:
    def test_two_schedules_one_trace_zero_retrace(self):
        """Fault schedules are DATA: a jitted step warmed up under one
        schedule must neither trace nor compile under a different one of
        the same shape (the ISSUE 6 acceptance gate)."""
        from rlgpuschedule_tpu.analysis.sentinels import CompileCounter
        rng = np.random.default_rng(0)
        trace = int_trace(rng, 10, 4, max_jobs=12)
        params = C.SimParams(3, 2, max_jobs=12, queue_len=4,
                             n_placements=1, preempt_len=2)
        tr = C.Trace.from_array_trace(trace)
        fs_a = device_faults(int_faults(np.random.default_rng(1), 3))
        fs_b = device_faults(int_faults(np.random.default_rng(2), 3))
        step = jax.jit(lambda s, f, a: C.rl_step(params, s, tr, a, f))
        state = C.init_state(params, tr)
        state, _ = step(state, fs_a, jnp.int32(0))          # warmup
        jax.block_until_ready(state.clock)
        state2 = C.init_state(params, tr)
        actions = [jnp.int32(int(a)) for a in
                   rng.integers(0, params.n_actions, size=8)]
        with CompileCounter() as counter:
            for a in actions:
                state2, _ = step(state2, fs_b, a)
            jax.block_until_ready(state2.clock)
        assert counter.total == 0, counter.events


class TestEnvAndTrainingWiring:
    def _cfg(self, **kw):
        from rlgpuschedule_tpu.configs import CONFIGS
        base = dict(n_envs=2, n_nodes=2, gpus_per_node=4, window_jobs=16,
                    queue_len=4, horizon=64, iterations=2, faults="storm")
        return dataclasses.replace(CONFIGS["ppo-mlp-synth64"],
                                   **{**base, **kw})

    def test_fault_obs_shape_and_health_values(self):
        from rlgpuschedule_tpu.env import env as env_lib
        params = C.SimParams(2, 2, max_jobs=4, queue_len=2, n_placements=1)
        ep = env_lib.EnvParams(sim=params, fault_process=F.FAULT_REGIMES
                               ["sporadic"], fault_obs=True)
        base = env_lib.EnvParams(sim=params)
        assert ep.obs_shape()[0] == base.obs_shape()[0] + 2
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 1)], max_jobs=4)
        tr = C.Trace.from_array_trace(trace)
        fs = device_faults(F.fault_schedule_from_events(
            2, [1], [0.0], [10.0], slowdown=[2.0, 1.0]))
        state, ts = env_lib.reset(ep, tr, fs)
        # node 0: straggler at half speed; node 1: drained -> 0
        np.testing.assert_allclose(np.asarray(ts.obs[-2:]), [0.5, 0.0])
        # faults=None replay of a fault-trained policy: all-healthy
        state, ts = env_lib.reset(ep, tr)
        np.testing.assert_allclose(np.asarray(ts.obs[-2:]), [1.0, 1.0])

    def test_fault_obs_refused_for_grid(self):
        from rlgpuschedule_tpu.env import env as env_lib
        params = C.SimParams(2, 2, max_jobs=4, queue_len=2)
        with pytest.raises(ValueError, match="FLAT"):
            env_lib.EnvParams(sim=params, obs_kind="grid", fault_obs=True)

    def test_vec_env_auto_resets_under_faults(self):
        from rlgpuschedule_tpu.env import env as env_lib
        rng = np.random.default_rng(3)
        params = C.SimParams(2, 2, max_jobs=8, queue_len=4, n_placements=1)
        ep = env_lib.EnvParams(sim=params, horizon=16)
        traces = env_lib.stack_traces(
            [int_trace(np.random.default_rng(s), 6, 3, max_jobs=8)
             for s in range(2)], ep)
        faults = F.stack_fault_schedules(
            [int_faults(np.random.default_rng(10 + s), 2)
             for s in range(2)])
        state, ts = env_lib.vec_reset(ep, traces, faults)
        fresh = (state, ts)
        saw_done = False
        for i in range(40):
            acts = jnp.asarray(rng.integers(0, params.n_actions, size=2),
                               jnp.int32)
            state, ts = env_lib.vec_step(ep, state, traces, acts, fresh,
                                         faults)
            saw_done = saw_done or bool(ts.done.any())
            assert np.isfinite(np.asarray(ts.obs)).all()
        assert saw_done   # horizon 16 over 40 steps must auto-reset

    def test_experiment_trains_under_fault_regime(self):
        from rlgpuschedule_tpu.experiment import Experiment
        exp = Experiment.build(self._cfg())
        assert exp.faults is not None
        assert exp.env_params.fault_obs
        out = exp.run(log_every=1)
        assert np.isfinite(out["history"][-1]["total_loss"])

    def test_population_trains_under_faults(self):
        # ISSUE 14 satellite: PBT x faults is a supported pair now —
        # member p's env e draws its schedule from (seed, p, e), so the
        # population covers the regime P×E-wide on shared trace windows
        from rlgpuschedule_tpu.experiment import PopulationExperiment
        pop = PopulationExperiment.build(self._cfg(), n_pop=2)
        assert pop.faults is not None
        down = np.asarray(jax.tree.leaves(pop.faults)[0])
        assert down.shape[:2] == (2, 2)    # [P, E, ...] leading axes
        # independent draws per (member, env): not one broadcast schedule
        flat = down.reshape(4, -1)
        assert len({a.tobytes() for a in flat}) > 1
        out = pop.run(2)
        assert len(out["final_fitness"]) == 2
        assert all(np.isfinite(f) for f in out["final_fitness"])

    def test_hier_refuses_faults(self):
        from rlgpuschedule_tpu.experiment import Experiment
        with pytest.raises(ValueError, match="fault"):
            Experiment.build(self._cfg(n_pods=2, n_nodes=4))


class TestChaosReport:
    def test_matrix_degradation_and_conservation(self, tmp_path):
        from rlgpuschedule_tpu.eval import chaos_report
        from rlgpuschedule_tpu.experiment import Experiment
        from rlgpuschedule_tpu.configs import CONFIGS
        from rlgpuschedule_tpu.obs import EventBus, Registry, read_events
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, n_nodes=2,
            gpus_per_node=4, window_jobs=16, queue_len=4, horizon=256)
        exp = Experiment.build(cfg)
        bus = EventBus(str(tmp_path), rank=0, name="chaos")
        registry = Registry()
        report = chaos_report(exp, regimes=("sporadic",),
                              baselines=("sjf",), seed=0, bus=bus,
                              registry=registry)
        bus.close()
        # clean control always present; every cell carries the triple
        assert set(report["regimes"]) == {"none", "sporadic"}
        for rows in report["regimes"].values():
            assert set(rows) == {"policy", "sjf"}
            for row in rows.values():
                assert {"avg_jct", "completion", "degradation"} <= set(row)
        assert report["regimes"]["none"]["policy"]["degradation"] == 1.0
        assert report["jobs_lost"] == 0
        assert report["fault_stats"]["sporadic"]["n_drains"] >= 0
        events = read_events(str(tmp_path / "events.chaos.jsonl"))
        cells = [e for e in events if e["kind"] == "env_fault"]
        assert len(cells) == 4    # 2 regimes x (policy + sjf)
        assert {(e["regime"], e["scheduler"]) for e in cells} == {
            ("none", "policy"), ("none", "sjf"),
            ("sporadic", "policy"), ("sporadic", "sjf")}
        assert "chaos_none_policy_avg_jct" in registry.render()

    def test_baselines_degrade_under_pure_drains(self):
        # drains can only delay work (service is preserved, capacity
        # temporarily shrinks): oracle SJF's avg JCT under a real drain
        # schedule must be >= its clean JCT on the same trace
        rng = np.random.default_rng(9)
        trace = int_trace(rng, 15, 4, max_jobs=16)
        fs = F.fault_schedule_from_events(
            3, [0, 1], [20.0, 30.0], [200.0, 150.0])
        faulty = run_baseline(trace, 3, 2, "sjf", faults=fs)
        clean = run_baseline(trace, 3, 2, "sjf", backend="python")
        assert faulty.avg_jct() >= clean.avg_jct()
        assert faulty.done() and faulty.gpus_consistent()
