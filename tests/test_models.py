"""Model tests: encoder/head shapes, dtype, action masking (SURVEY.md §4)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu.models import (ActorCritic, CNNEncoder, GNNActorCritic,
                                      GNNEncoder, MLPEncoder, make_policy,
                                      NEG_INF)
from rlgpuschedule_tpu.env import build_adjacency


class TestActorCritic:
    def test_mlp_shapes_and_masking(self):
        net = ActorCritic(MLPEncoder(features=(32,)), n_actions=5)
        obs = jnp.ones((3, 10))
        mask = jnp.array([[1, 1, 0, 0, 1]] * 3, bool)
        params = net.init(jax.random.PRNGKey(0), obs, mask)
        logits, value = net.apply(params, obs, mask)
        assert logits.shape == (3, 5) and value.shape == (3,)
        assert logits.dtype == jnp.float32 and value.dtype == jnp.float32
        got = np.asarray(logits)
        assert (got[:, 2] <= NEG_INF).all() and (got[:, 3] <= NEG_INF).all()
        # masked actions are never sampled
        samples = jax.random.categorical(jax.random.PRNGKey(1), logits,
                                         shape=(3,))
        assert all(int(s) in (0, 1, 4) for s in samples)

    def test_cnn_shapes(self):
        net = ActorCritic(CNNEncoder(features=(8, 8), dense=32), n_actions=7)
        obs = jnp.ones((2, 12, 8, 2))
        mask = jnp.ones((2, 7), bool)
        params = net.init(jax.random.PRNGKey(0), obs, mask)
        logits, value = net.apply(params, obs, mask)
        assert logits.shape == (2, 7) and value.shape == (2,)

    def test_gnn_shapes_factored_actions(self):
        N, K, P = 4, 3, 2
        adj = jnp.asarray(build_adjacency(N, K))
        net = GNNActorCritic(GNNEncoder(features=(16, 16)), N, K, P)
        obs = jnp.ones((2, N + K, 5))
        mask = jnp.ones((2, K * P + 1), bool)
        params = net.init(jax.random.PRNGKey(0), obs, adj, mask)
        logits, value = net.apply(params, obs, adj, mask)
        assert logits.shape == (2, K * P + 1) and value.shape == (2,)

    def test_gnn_slot_logits_follow_slot_features(self):
        # per-slot head: permuting queue-slot features permutes slot logits
        N, K = 2, 3
        adj = jnp.asarray(build_adjacency(N, K))
        net = GNNActorCritic(GNNEncoder(features=(16,)), N, K, 1)
        key = jax.random.PRNGKey(0)
        obs = jax.random.normal(key, (1, N + K, 5))
        mask = jnp.ones((1, K + 1), bool)
        params = net.init(key, obs, adj, mask)
        logits, _ = net.apply(params, obs, adj, mask)
        perm = [1, 2, 0]
        obs_p = obs.at[0, N:].set(obs[0, N:][jnp.asarray(perm)])
        logits_p, _ = net.apply(params, obs_p, adj, mask)
        np.testing.assert_allclose(np.asarray(logits_p[0, :K]),
                                   np.asarray(logits[0, :K])[perm], atol=1e-5)

    def test_make_policy_factory(self):
        assert isinstance(make_policy("flat", 5), ActorCritic)
        assert isinstance(make_policy("grid", 5), ActorCritic)
        assert isinstance(make_policy("graph", 5, n_cluster_nodes=2,
                                      queue_len=2), GNNActorCritic)
        with pytest.raises(ValueError):
            make_policy("bogus", 5)
