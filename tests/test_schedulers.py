"""Baseline-scheduler oracle tests with hand-computed JCTs (SURVEY.md §4).

Every expected number below is derived by hand in the comments — these tests
validate the simulator semantics as much as the schedulers themselves.
"""
import numpy as np
import pytest

from rlgpuschedule_tpu.sim import (OracleSim, run_scheduler, fifo, sjf, srtf,
                                   tiresias, evaluate_baselines)
from rlgpuschedule_tpu.traces import JobRecord, gen_poisson_jobs


def J(i, submit, dur, gpus, tenant=0):
    return JobRecord(i, float(submit), float(dur), gpus, tenant)


# Cluster: 1 node × 2 GPUs. Jobs (all submit t=0): A needs 2 gpus 10s,
# B 1 gpu 4s, C 1 gpu 2s.
TRI = [J(0, 0, 10, 2), J(1, 0, 4, 1), J(2, 0, 2, 1)]


class TestHandComputedJCTs:
    def test_fifo(self):
        # FIFO: A first (2 gpus), B/C blocked until t=10; then B,C run
        # together: B done 14, C done 12. JCTs: 10, 14, 12 → avg 12.
        sim = run_scheduler(OracleSim(TRI, 1, 2), fifo())
        np.testing.assert_allclose(sorted(sim.jcts()), [10, 12, 14])
        assert sim.avg_jct() == pytest.approx(12.0)

    def test_sjf(self):
        # SJF: C(2) and B(4) placed at t=0; A(2 gpus) waits. C done t=2,
        # A still infeasible (1 free). B done t=4 → A runs 4..14.
        # JCTs: C=2, B=4, A=14 → avg 20/3.
        sim = run_scheduler(OracleSim(TRI, 1, 2), sjf())
        assert sim.avg_jct() == pytest.approx(20.0 / 3.0)

    def test_srtf_preempts(self):
        # Cluster 1×1. A(submit 0, dur 10), B(submit 2, dur 3).
        # SRTF: A runs 0..2 (rem 8); B arrives rem 3 < 8 → preempt A.
        # B runs 2..5; A resumes 5..13. JCT: B=3, A=13 → avg 8.
        sim = run_scheduler(OracleSim([J(0, 0, 10, 1), J(1, 2, 3, 1)], 1, 1), srtf())
        np.testing.assert_allclose(sorted(sim.jcts()), [3, 13])

    def test_fifo_does_not_preempt(self):
        sim = run_scheduler(OracleSim([J(0, 0, 10, 1), J(1, 2, 3, 1)], 1, 1), fifo())
        # A runs 0..10, B 10..13: JCTs A=10, B=11.
        np.testing.assert_allclose(sorted(sim.jcts()), [10, 11])

    def test_tiresias_demotion_wakes_mid_run(self):
        # Cluster 1×1, threshold 5 GPU-s. A(0, dur 10), B(2, dur 3).
        # t=2: B arrives; A attained 2 (queue 0) vs B (queue 0), FIFO → A
        # keeps running. t=5: A attained 5 → demoted to queue 1; B preempts.
        # B runs 5..8? NO — B was admitted at its arrival? budget=1, order
        # [A,B]: A admitted, B not. At wake t=5: order [B(q0), A(q1)] → B
        # runs 5..8 (JCT 6), A resumes 8..13 (JCT 13).
        sim = run_scheduler(OracleSim([J(0, 0, 10, 1), J(1, 2, 3, 1)], 1, 1),
                            tiresias(thresholds=(5.0,)))
        np.testing.assert_allclose(sorted(sim.jcts()), [6, 13])

    def test_tiresias_2d_wide_gang_demotes_sooner(self):
        # Cluster 1×4, threshold 8 GPU-s. A(0, dur 10, 4 gpus) attains
        # 8 GPU-s at t=2 (4 gpus × 2s) → demoted to q1; B(1, dur 4, 4 gpus)
        # still q0 → preempts A, runs from t=2. At t=4 B has itself attained
        # 8 GPU-s → demoted to q1 too; within q1 FIFO-by-submit puts A first
        # → A resumes 4..12 (JCT 12), then B finishes 12..14 (JCT 13).
        sim = run_scheduler(OracleSim([J(0, 0, 10, 4), J(1, 1, 4, 4)], 1, 4),
                            tiresias(thresholds=(8.0,)))
        np.testing.assert_allclose(sorted(sim.jcts()), [12, 13])


class TestSchedulerProperties:
    @pytest.mark.parametrize("mk", [fifo, sjf, srtf, tiresias])
    def test_all_jobs_complete_and_conserve(self, mk):
        jobs = gen_poisson_jobs(rate=0.05, n_jobs=60, seed=3, mean_duration=50.0)
        sim = run_scheduler(OracleSim(jobs, n_nodes=4, gpus_per_node=4), mk())
        assert sim.done() and sim.gpus_consistent()
        assert len(sim.jcts()) == 60
        # JCT >= duration always
        durs = sim.trace.duration[sim.trace.valid]
        assert (sim.jcts() >= durs - 1e-6).all()

    def test_srtf_beats_fifo_on_avg(self):
        from rlgpuschedule_tpu.traces import to_array_trace
        jobs = gen_poisson_jobs(rate=0.1, n_jobs=80, seed=11, mean_duration=100.0)
        table = evaluate_baselines(to_array_trace(jobs), 2, 4,
                                   names=("fifo", "srtf"))
        assert table["srtf"] <= table["fifo"] + 1e-6

    def test_evaluate_baselines_table(self):
        from rlgpuschedule_tpu.traces import to_array_trace
        tr = to_array_trace(gen_poisson_jobs(rate=0.1, n_jobs=40, seed=5,
                                             mean_duration=60.0))
        table = evaluate_baselines(tr, 2, 4)
        assert set(table) == {"fifo", "sjf", "srtf", "tiresias"}
        assert all(np.isfinite(v) and v > 0 for v in table.values())
