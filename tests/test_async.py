"""Async actor–learner engine tests (ISSUE 9): device-group carving,
the trajectory queue's backpressure/abort semantics, the OverlapMeter,
and the engine contracts — bound-0 bit-identity with the sync loop
(shared AND split device groups, across resample barriers), staleness
enforcement, crash-resume determinism of the checkpointed RNG carries,
zero post-warmup recompiles, and learning parity at a small bound.

The 8-device virtual CPU platform (conftest) makes real split groups
testable in-process; a shared single-device group exercises the same
queue/staleness/barrier code paths.
"""
import dataclasses
import threading
import time

import jax
import numpy as np
import pytest

from rlgpuschedule_tpu.async_engine import (AsyncRunner, StalenessError,
                                            TrajectoryQueue, _Aborted,
                                            _QueueItem)
from rlgpuschedule_tpu.algos import (validate_rollout_geometry,
                                     validate_update_geometry)
from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
from rlgpuschedule_tpu.experiment import Experiment
from rlgpuschedule_tpu.obs.telemetry import OverlapMeter
from rlgpuschedule_tpu.parallel.groups import (parse_group_spec,
                                               split_devices)


def small_cfg(**kw):
    ppo = dataclasses.replace(PPO_MLP_SYNTH64.ppo, n_steps=8, n_epochs=1,
                              n_minibatches=2)
    base = dict(name="async-test", n_envs=4, n_nodes=2, gpus_per_node=4,
                window_jobs=16, horizon=64, queue_len=4, resample_every=0,
                ppo=ppo)
    return dataclasses.replace(PPO_MLP_SYNTH64, **{**base, **kw})


def params_equal(a, b) -> bool:
    return bool(jax.tree.all(jax.tree.map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        jax.device_get(a), jax.device_get(b))))


class TestGroups:
    def test_parse_group_spec_forms(self):
        assert parse_group_spec(None) is None
        assert parse_group_spec(3) == 3
        assert parse_group_spec(" 2 ") == 2
        assert parse_group_spec("0,2,3") == [0, 2, 3]
        with pytest.raises(ValueError, match="spec"):
            parse_group_spec("two")
        with pytest.raises(ValueError, match="indices"):
            parse_group_spec("0,a")

    def test_default_split_halves_the_devices(self):
        g = split_devices()
        assert len(g.actor) == 4 and len(g.learner) == 4
        assert not g.shared
        assert set(g.actor).isdisjoint(g.learner)
        assert "actor" in g.describe()

    def test_single_device_defaults_to_shared(self):
        g = split_devices(devices=jax.devices()[:1])
        assert g.shared and g.actor == g.learner
        assert "shared" in g.describe()

    def test_count_specs_take_front_and_back(self):
        g = split_devices(actor=2, learner=3)
        assert [d.id for d in g.actor] == [0, 1]
        assert [d.id for d in g.learner] == [5, 6, 7]

    def test_identical_index_sets_request_shared(self):
        g = split_devices(actor="0,1", learner="1,0")
        assert g.shared

    def test_overlapping_groups_are_refused(self):
        with pytest.raises(ValueError, match="overlap"):
            split_devices(actor="0,1", learner="1,2")

    def test_unknown_device_index_is_refused(self):
        with pytest.raises(ValueError, match="not among"):
            split_devices(actor="0,99")


class TestGeometry:
    def test_rollout_geometry_checks_env_tiling(self):
        validate_rollout_geometry(8, 4, n_devices=2)
        with pytest.raises(ValueError, match="n_envs"):
            validate_rollout_geometry(8, 5, n_devices=2)
        with pytest.raises(ValueError, match="n_steps"):
            validate_rollout_geometry(0, 4)

    def test_update_geometry_checks_devices_and_batch(self):
        validate_update_geometry(1, 2, None, n_steps=8, n_envs=4,
                                 n_devices=2)
        with pytest.raises(ValueError, match="n_envs"):
            validate_update_geometry(1, 2, None, n_steps=8, n_envs=5,
                                     n_devices=2)
        with pytest.raises(ValueError):
            validate_update_geometry(1, 3, None, n_steps=8, n_envs=4)


class TestOverlapMeter:
    def test_fake_clock_credits_intersection_once(self):
        ticks = iter([0.0, 4.0, 8.0, 10.0])
        m = OverlapMeter(clock=lambda: next(ticks))
        with m.span("actor"):          # [0, 10]
            with m.span("learner"):    # [4, 8] -> overlap 4
                pass
        snap = m.snapshot()
        assert snap["overlap_s"] == pytest.approx(4.0)
        assert snap["busy_actor_s"] == pytest.approx(10.0)
        assert snap["busy_learner_s"] == pytest.approx(4.0)

    def test_disjoint_spans_credit_nothing(self):
        ticks = iter([0.0, 1.0, 2.0, 3.0])
        m = OverlapMeter(clock=lambda: next(ticks))
        with m.span("actor"):
            pass
        with m.span("learner"):
            pass
        assert m.snapshot()["overlap_s"] == 0.0


class TestTrajectoryQueue:
    def test_backpressure_blocks_put_and_drops_nothing(self):
        q = TrajectoryQueue(capacity=1, stall_timeout_s=10.0)
        q.put(_QueueItem(index=0, version=0, batch="b0"))
        done = threading.Event()

        def producer():
            q.put(_QueueItem(index=1, version=1, batch="b1"))
            done.set()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.3)
        assert not done.is_set()          # full queue blocked the put
        assert len(q) == 1                # and nothing was dropped
        item, _ = q.get()
        assert item.index == 0
        assert done.wait(timeout=10)      # pop released the producer
        item, _ = q.get()
        assert item.index == 1            # FIFO preserved, both delivered

    def test_abort_unwinds_a_blocked_get(self):
        q = TrajectoryQueue(capacity=1, stall_timeout_s=10.0)
        failed = {}

        def consumer():
            try:
                q.get()
            except _Aborted:
                failed["aborted"] = True

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        time.sleep(0.1)
        q.abort(RuntimeError("peer died"))
        t.join(timeout=10)
        assert failed.get("aborted")

    def test_capacity_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            TrajectoryQueue(capacity=0)


class TestAsyncRunner:
    def _sync_reference(self, cfg, iterations):
        exp = Experiment.build(cfg)
        exp.run(iterations=iterations)
        return exp

    def test_bound0_shared_group_is_bit_identical_to_sync(self):
        cfg = small_cfg(resample_every=3)   # cross resample barriers too
        ref = self._sync_reference(cfg, 7)
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:1]),
                        staleness_bound=0)
        out = r.run(iterations=7, log_every=3)
        assert params_equal(ref.train_state.params, exp.train_state.params)
        assert np.array_equal(jax.device_get(ref.key),
                              jax.device_get(exp.key))
        assert np.array_equal(jax.device_get(ref.carry.key),
                              jax.device_get(exp.carry.key))
        assert out["async"]["staleness_max"] == 0
        assert out["window_cursor"] == ref.window_cursor

    def test_bound0_split_groups_is_bit_identical_to_sync(self):
        """Distinct actor and learner devices (one each — the CLI rig's
        layout under --xla_force_host_platform_device_count=2): the
        queue's cross-mesh hops must not perturb a single bit."""
        cfg = small_cfg()
        ref = self._sync_reference(cfg, 5)
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=0)
        r.run(iterations=5)
        assert params_equal(ref.train_state.params, exp.train_state.params)
        assert np.array_equal(jax.device_get(ref.key),
                              jax.device_get(exp.key))

    def test_bound0_multidevice_learner_matches_sync_numerically(self):
        """A MULTI-device learner group shards the update's batch
        reductions, so float summation order differs from the
        single-placement sync run: allclose, documented as not bitwise
        (same caveat as parallel.dp data-parallel vs single-device)."""
        cfg = small_cfg()
        ref = self._sync_reference(cfg, 4)
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(actor=2, learner=2),
                        staleness_bound=0)
        r.run(iterations=4)
        ok = jax.tree.all(jax.tree.map(
            lambda a, b: bool(np.allclose(np.asarray(a), np.asarray(b),
                                          rtol=1e-2, atol=1e-3)),
            jax.device_get(ref.train_state.params),
            jax.device_get(exp.train_state.params)))
        assert ok
        assert np.array_equal(jax.device_get(ref.key),
                              jax.device_get(exp.key))

    def test_staleness_bound_is_enforced(self):
        cfg = small_cfg()
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=1, queue_capacity=2)
        out = r.run(iterations=6)
        info = out["async"]
        assert 0 <= info["staleness_max"] <= 1
        assert 0.0 <= info["staleness_mean"] <= 1.0
        # the defensive check raises on an over-stale batch
        with pytest.raises(StalenessError):
            raise StalenessError("smoke")

    def test_negative_bound_is_refused(self):
        exp = Experiment.build(small_cfg())
        with pytest.raises(ValueError, match="staleness_bound"):
            AsyncRunner(exp, staleness_bound=-1)

    def test_crash_resume_is_deterministic(self, tmp_path):
        """Restoring a barrier checkpoint into a fresh build + fresh
        runner must reproduce continuing the original runner in-process
        (same contract as the sync streaming-resume test: cadences are
        per-``run()`` call, so both sides run 3 + 3)."""
        from rlgpuschedule_tpu.checkpoint import Checkpointer
        cfg = small_cfg(resample_every=2)
        groups = lambda: split_devices(devices=jax.devices()[:1])  # noqa: E731
        # reference: one runner, 3 iterations + 3 more, uninterrupted
        ref = Experiment.build(cfg)
        ref_runner = AsyncRunner(ref, groups=groups(), staleness_bound=0)
        with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
            ref_runner.run(iterations=3, ckpt=ckpt, ckpt_every=3)
        ref_runner.run(iterations=3)
        # "crashed" process stand-in: new build + restore + new runner
        exp_b = Experiment.build(cfg)
        with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
            exp_b.restore_checkpoint(ckpt)
            AsyncRunner(exp_b, groups=groups(), staleness_bound=0).run(
                iterations=3)
        assert params_equal(ref.train_state.params,
                            exp_b.train_state.params)
        assert np.array_equal(jax.device_get(ref.key),
                              jax.device_get(exp_b.key))
        assert np.array_equal(jax.device_get(ref.carry.key),
                              jax.device_get(exp_b.carry.key))

    def test_no_post_warmup_recompiles_in_either_loop(self):
        from rlgpuschedule_tpu.analysis.sentinels import CompileCounter
        cfg = small_cfg()
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=1)
        r.run(iterations=2)               # warmup: both programs compile
        with CompileCounter() as c:
            r.run(iterations=3)           # steady state
        assert c.total == 0, c.events

    def test_learning_parity_at_small_bound(self):
        """Async with bound 1 must track the sync return on a short
        seeded workload — PPO's clipped ratio tolerates one version of
        staleness (the Sebulba premise). Loose tolerance: iteration-0
        rollouts are identical (same init params); later divergence is
        bounded, not zero."""
        cfg = small_cfg()
        sync = Experiment.build(cfg)
        s_out = sync.run(iterations=8, log_every=1)
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=1)
        a_out = r.run(iterations=8, log_every=1)
        s_r = [h["mean_reward"] for h in s_out["history"][-4:]]
        a_r = [h["mean_reward"] for h in a_out["history"][-4:]]
        assert np.isfinite(a_r).all()
        assert abs(float(np.mean(s_r)) - float(np.mean(a_r))) < 0.05

    def test_telemetry_emits_async_surface(self, tmp_path):
        from rlgpuschedule_tpu.obs import RunTelemetry
        cfg = small_cfg()
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=1)
        with RunTelemetry(str(tmp_path), alarms=True) as tel:
            r.run(iterations=3, log_every=1, telemetry=tel)
        from rlgpuschedule_tpu.obs import merge_dir
        events = merge_dir(str(tmp_path))
        kinds = [e["kind"] for e in events]
        assert "run_start" in kinds and "run_end" in kinds
        # implicit transfers RAISE under the no_implicit_transfers
        # guard — they never appear as events, only recompiles do
        assert "recompile" not in kinds
        start = next(e for e in events if e["kind"] == "run_start")
        assert start["loop"] == "async-experiment"
        assert start["staleness_bound"] == 1
        end = next(e for e in events if e["kind"] == "run_end")
        phases = end["phase_seconds"]
        assert phases.get("actor", 0) > 0 and phases.get("learner", 0) > 0
        assert "queue_wait" in phases
        assert end["async_staleness_max"] <= 1
        assert end["async_overlap_s"] >= 0.0
        prom = open(tmp_path / "metrics.prom", encoding="utf-8").read()
        assert "rlsched_async_queue_depth" in prom
        assert "rlsched_async_param_staleness" in prom
