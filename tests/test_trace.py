"""Flight recorder tests (ISSUE 11): span emission (nesting, thread
tracks, the shared no-op disabled path), the span-tree aggregation
(self/child time, torn spans), the Chrome-trace exporter (Perfetto
contract: paired B/E per track, metadata, torn-span closing), the
clock-skew handshake (two-rank correction, single-rank no-op, dedicated
stamps), the measured async actor/learner occupancy, the serve-side
latency histogram + reservoir satellites, a REAL traced async run (the
acceptance: actor/learner spans on the timeline, measured overlap in
the report, Perfetto-valid export), and the CLI refusals.
"""
import dataclasses
import json
import threading

import numpy as np
import pytest

from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.obs import (EventBus, Registry, RunTelemetry,
                                   merge_dir, read_events)
from rlgpuschedule_tpu.obs import report as report_cli
from rlgpuschedule_tpu.obs import skew
from rlgpuschedule_tpu.obs.trace import (NULL_TRACER, SPAN_BEGIN, SPAN_END,
                                         SPAN_POINT, Tracer,
                                         async_overlap_summary,
                                         build_span_tree, to_chrome_trace,
                                         tracer_of)

SMALL = dataclasses.replace(
    CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
    ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))


def span_events(*rows):
    """Hand-built span timeline: (kind, mono, span, rank, tid)."""
    return [{"kind": k, "mono": m, "span": s, "rank": r, "tid": t,
             "seq": i}
            for i, (k, m, s, r, t) in enumerate(rows)]


class TestTracer:
    def test_nested_spans_pair_with_depth(self, tmp_path):
        clock = iter([1.0, 2.0, 3.0, 4.0])
        with EventBus(str(tmp_path), rank=0,
                      clock=lambda: next(clock)) as bus:
            tracer = Tracer(bus, enabled=True)
            with tracer.span("outer", iteration=7):
                with tracer.span("inner"):
                    pass
        events = read_events(bus.path)
        assert [(e["kind"], e["span"], e["depth"]) for e in events] == [
            (SPAN_BEGIN, "outer", 0), (SPAN_BEGIN, "inner", 1),
            (SPAN_END, "inner", 1), (SPAN_END, "outer", 0)]
        assert events[0]["attrs"] == {"iteration": 7}
        assert all(e["tid"] == 0 for e in events)

    def test_disabled_tracer_is_shared_noop(self, tmp_path):
        # the hot-path contract: no allocation, no emission when off
        assert NULL_TRACER.span("x") is NULL_TRACER.span("y")
        assert not NULL_TRACER.enabled
        with EventBus(str(tmp_path), rank=0) as bus:
            t = Tracer(bus, enabled=False)
            with t.span("a"):
                t.instant("mark")
        assert read_events(bus.path) == []
        # a tracer without a bus can never be enabled
        assert not Tracer(None, enabled=True).enabled

    def test_tracer_of_falls_back_to_null(self, tmp_path):
        assert tracer_of(None) is NULL_TRACER
        assert tracer_of(object()) is NULL_TRACER
        with RunTelemetry(str(tmp_path), rank=0, trace=True) as tel:
            assert tracer_of(tel) is tel.tracer
            assert tel.tracer.enabled

    def test_threads_get_distinct_tracks(self, tmp_path):
        with EventBus(str(tmp_path), rank=0) as bus:
            tracer = Tracer(bus, enabled=True)
            with tracer.span("main_work"):
                t = threading.Thread(
                    target=lambda: tracer.span("worker_work").__enter__()
                    .__exit__(None, None, None), name="side")
                t.start()
                t.join()
        events = read_events(bus.path)
        by_span = {e["span"]: e for e in events
                   if e["kind"] == SPAN_BEGIN}
        assert by_span["main_work"]["tid"] != by_span["worker_work"]["tid"]
        # each track keeps its OWN stack: both spans are depth 0
        assert by_span["worker_work"]["depth"] == 0
        assert by_span["worker_work"]["thread"] == "side"

    def test_instant_rides_the_track(self, tmp_path):
        with EventBus(str(tmp_path), rank=0) as bus:
            Tracer(bus, enabled=True).instant("enqueue", n=3)
        (e,) = read_events(bus.path)
        assert e["kind"] == SPAN_POINT and e["span"] == "enqueue"
        assert e["attrs"] == {"n": 3}


class TestSpanTree:
    def test_self_time_excludes_children(self):
        tree = build_span_tree(span_events(
            (SPAN_BEGIN, 0.0, "outer", 0, 0),
            (SPAN_BEGIN, 2.0, "inner", 0, 0),
            (SPAN_END, 5.0, "inner", 0, 0),
            (SPAN_END, 10.0, "outer", 0, 0)))
        rows = {n["path"]: n for n in tree}
        assert rows["outer"]["total_s"] == pytest.approx(10.0)
        assert rows["outer"]["self_s"] == pytest.approx(7.0)
        assert rows["outer/inner"]["total_s"] == pytest.approx(3.0)
        assert rows["outer/inner"]["depth"] == 1
        assert all(n["open"] == 0 for n in tree)

    def test_torn_span_closed_at_track_end_and_flagged(self):
        tree = build_span_tree(span_events(
            (SPAN_BEGIN, 0.0, "outer", 0, 0),
            (SPAN_BEGIN, 1.0, "inner", 0, 0),
            (SPAN_END, 4.0, "inner", 0, 0)))   # writer died before outer end
        rows = {n["path"]: n for n in tree}
        assert rows["outer"]["open"] == 1
        assert rows["outer"]["total_s"] == pytest.approx(4.0)  # last ts
        assert rows["outer/inner"]["open"] == 0

    def test_torn_inner_closed_at_outer_end(self):
        tree = build_span_tree(span_events(
            (SPAN_BEGIN, 0.0, "outer", 0, 0),
            (SPAN_BEGIN, 1.0, "inner", 0, 0),
            (SPAN_END, 6.0, "outer", 0, 0)))   # inner's end was lost
        rows = {n["path"]: n for n in tree}
        assert rows["outer/inner"]["open"] == 1
        assert rows["outer/inner"]["total_s"] == pytest.approx(5.0)
        assert rows["outer"]["open"] == 0

    def test_concurrent_tracks_do_not_steal_ends(self):
        # same span name on two tracks, interleaved in time: pairing is
        # per (rank, tid), so each B matches ITS track's E
        tree = build_span_tree(span_events(
            (SPAN_BEGIN, 0.0, "work", 0, 0),
            (SPAN_BEGIN, 1.0, "work", 0, 1),
            (SPAN_END, 2.0, "work", 0, 0),
            (SPAN_END, 5.0, "work", 0, 1)))
        (row,) = tree
        assert row["count"] == 2
        assert row["total_s"] == pytest.approx(2.0 + 4.0)
        assert row["open"] == 0


class TestChromeTrace:
    def test_export_pairs_b_e_per_track(self, tmp_path):
        with EventBus(str(tmp_path), rank=0) as bus:
            tracer = Tracer(bus, enabled=True)
            bus.emit("run_start", config="x")
            with tracer.span("iteration", iteration=0):
                with tracer.span("step"):
                    pass
        doc = to_chrome_trace(read_events(bus.path))
        doc = json.loads(json.dumps(doc))    # must survive JSON round-trip
        evs = doc["traceEvents"]
        assert {e["ph"] for e in evs} == {"M", "B", "E", "i"}
        # B/E stack discipline per (pid, tid): never unbalanced
        depth = {}
        for e in evs:
            key = (e["pid"], e.get("tid"))
            if e["ph"] == "B":
                depth[key] = depth.get(key, 0) + 1
            elif e["ph"] == "E":
                depth[key] = depth.get(key, 0) - 1
                assert depth[key] >= 0
        assert all(v == 0 for v in depth.values())
        names = [e["name"] for e in evs if e["ph"] == "B"]
        assert names == ["iteration", "step"]   # nested order preserved
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in evs)
        b_iter = next(e for e in evs
                      if e["ph"] == "B" and e["name"] == "iteration")
        assert b_iter["args"] == {"iteration": 0}

    def test_torn_span_closed_with_flag(self):
        doc = to_chrome_trace(span_events(
            (SPAN_BEGIN, 1.0, "outer", 0, 0),
            (SPAN_BEGIN, 2.0, "inner", 0, 0),
            (SPAN_END, 3.0, "inner", 0, 0)))
        ends = [e for e in doc["traceEvents"] if e["ph"] == "E"]
        torn = [e for e in ends if e.get("args", {}).get("torn")]
        assert len(ends) == 2 and len(torn) == 1
        assert torn[0]["ts"] == pytest.approx(3.0 * 1e6)

    def test_non_span_events_become_instants(self):
        doc = to_chrome_trace([{"kind": "rollback", "mono": 2.0,
                                "rank": 1, "reason": "nan"}])
        (m, i) = doc["traceEvents"]
        assert m["ph"] == "M"
        assert i["ph"] == "i" and i["name"] == "rollback"
        assert i["pid"] == 1 and i["args"]["reason"] == "nan"


class TestSkew:
    def _two_rank_events(self):
        # rank 0's mono epoch lags wall by 100s, rank 1's by 130s: the
        # same wall instant reads mono=t on rank 0 and mono=t-30 on rank 1
        evs = []
        for rank, off in ((0, 100.0), (1, 130.0)):
            for k in range(3):
                t_wall = 1000.0 + k
                evs.append({"kind": skew.CLOCK_SKEW, "rank": rank,
                            "seq": k, "wall": t_wall,
                            "mono": t_wall - off})
        return evs

    def test_learn_offsets_median_and_residual(self):
        offs = skew.learn_offsets(self._two_rank_events())
        assert offs[0].offset_s == pytest.approx(100.0)
        assert offs[1].offset_s == pytest.approx(130.0)
        assert offs[0].residual_s == pytest.approx(0.0)
        assert offs[0].dedicated and offs[1].dedicated

    def test_correction_aligns_two_ranks(self):
        evs = self._two_rank_events()
        corrected, info = skew.correct_events(evs)
        assert info["applied"] and info["reference_rank"] == 0
        assert info["ranks"]["1"]["shift_s"] == pytest.approx(30.0)
        # after correction, simultaneous wall instants share one mono axis
        r0 = [e["mono"] for e in corrected if e["rank"] == 0]
        r1 = [e["mono"] for e in corrected if e["rank"] == 1]
        np.testing.assert_allclose(r0, r1)
        shifted = [e for e in corrected if e["rank"] == 1]
        assert all("mono_raw" in e and
                   e["skew_shift_s"] == pytest.approx(30.0)
                   for e in shifted)
        # rank 0 is the reference: untouched
        assert all("mono_raw" not in e for e in corrected
                   if e["rank"] == 0)

    def test_single_rank_is_honest_noop(self):
        evs = [{"kind": "iteration", "rank": 0, "seq": 0,
                "wall": 5.0, "mono": 1.0}]
        out, info = skew.correct_events(evs)
        assert out == evs and not info["applied"]

    def test_implicit_samples_fall_back_when_no_stamps(self):
        evs = [{"kind": "iteration", "rank": r, "seq": 0,
                "wall": 50.0, "mono": 50.0 - off}
               for r, off in ((0, 10.0), (1, 25.0))]
        offs = skew.learn_offsets(evs)
        assert not offs[0].dedicated
        assert offs[1].offset_s == pytest.approx(25.0)

    def test_stamp_rides_the_bus(self, tmp_path):
        with EventBus(str(tmp_path), rank=2) as bus:
            skew.stamp(bus, source="worker_start")
        (e,) = read_events(bus.path)
        assert e["kind"] == skew.CLOCK_SKEW
        assert e["source"] == "worker_start"
        assert "wall" in e and "mono" in e


class TestAsyncOverlapSummary:
    def test_interval_math(self):
        ov = async_overlap_summary(span_events(
            (SPAN_BEGIN, 0.0, "actor", 0, 0),
            (SPAN_END, 4.0, "actor", 0, 0),
            (SPAN_BEGIN, 3.0, "learner", 0, 1),
            (SPAN_END, 7.0, "learner", 0, 1),
            (SPAN_BEGIN, 6.0, "actor", 0, 0),
            (SPAN_END, 10.0, "actor", 0, 0)))
        assert ov["window_s"] == pytest.approx(10.0)
        assert ov["actor_busy_s"] == pytest.approx(8.0)
        assert ov["learner_busy_s"] == pytest.approx(4.0)
        assert ov["concurrent_s"] == pytest.approx(2.0)   # [3,4] + [6,7]
        assert ov["idle_s"] == pytest.approx(0.0)
        assert ov["async_overlap_measured"] == pytest.approx(1.0)

    def test_idle_gap_lowers_occupancy(self):
        ov = async_overlap_summary(span_events(
            (SPAN_BEGIN, 0.0, "actor", 0, 0),
            (SPAN_END, 2.0, "actor", 0, 0),
            (SPAN_BEGIN, 8.0, "learner", 0, 1),
            (SPAN_END, 10.0, "learner", 0, 1)))
        assert ov["idle_s"] == pytest.approx(6.0)
        assert ov["async_overlap_measured"] == pytest.approx(0.4)

    def test_none_without_both_lanes(self):
        assert async_overlap_summary(span_events(
            (SPAN_BEGIN, 0.0, "actor", 0, 0),
            (SPAN_END, 1.0, "actor", 0, 0))) is None
        assert async_overlap_summary([]) is None


class TestHistogram:
    def test_render_prometheus_cumulative_series(self):
        r = Registry()
        h = r.histogram("lat_seconds", "latency",
                        buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        text = r.render()
        assert "# TYPE lat_seconds histogram" in text
        assert 'lat_seconds_bucket{le="0.01"} 1' in text
        assert 'lat_seconds_bucket{le="0.1"} 2' in text
        assert 'lat_seconds_bucket{le="1"} 3' in text
        assert 'lat_seconds_bucket{le="+Inf"} 4' in text
        assert "lat_seconds_count 4" in text
        assert f"lat_seconds_sum {0.005 + 0.05 + 0.5 + 5.0:g}" in text

    def test_custom_buckets_honored_at_first_registration(self):
        r = Registry()
        h = r.histogram("h", buckets=(1.0, 2.0))
        assert h.buckets == (1.0, 2.0)
        assert r.histogram("h", buckets=(1.0, 2.0)) is h
        assert r.histogram("h") is h   # no buckets = accept existing
        with pytest.raises(ValueError, match="unaggregatable"):
            r.histogram("h", buckets=(3.0,))

    def test_kind_mismatch_and_bad_buckets_raise(self):
        r = Registry()
        r.counter("c")
        with pytest.raises(ValueError, match="not histogram"):
            r.histogram("c")
        with pytest.raises(ValueError, match="increasing"):
            r.histogram("bad", buckets=(2.0, 1.0))


class TestReservoir:
    def test_uniform_lifetime_sample_flat_memory(self):
        from rlgpuschedule_tpu.serve import Reservoir
        res = Reservoir(64, seed=7)
        for i in range(10_000):
            res.append(float(i))
        assert len(res) == 64 and res.count == 10_000
        # lifetime-uniform, not a trailing ring: early observations
        # survive (a deque(maxlen=64) would hold only 9936..9999)
        assert min(res) < 5000.0
        # deterministic under the seed
        res2 = Reservoir(64, seed=7)
        for i in range(10_000):
            res2.append(float(i))
        assert list(res) == list(res2)

    def test_short_stream_kept_verbatim(self):
        from rlgpuschedule_tpu.serve import Reservoir
        res = Reservoir(8, seed=0)
        for i in range(5):
            res.append(float(i))
        assert list(res) == [0.0, 1.0, 2.0, 3.0, 4.0]
        assert np.percentile(np.asarray(res), 50) == pytest.approx(2.0)

    def test_rejects_nonpositive_capacity(self):
        from rlgpuschedule_tpu.serve import Reservoir
        with pytest.raises(ValueError, match="capacity"):
            Reservoir(0)


class _FakeEngine:
    """Engine stand-in for front-end tests: no jax dispatch, fixed
    bucket math (echoes observations as actions)."""

    max_bucket = 4

    def decide(self, obs, mask, stall):
        from rlgpuschedule_tpu.serve import next_bucket
        n = obs.shape[0]
        return obs, next_bucket(n, self.max_bucket)


class TestServeObservability:
    def _server(self, tmp_path, latency_window=8):
        from rlgpuschedule_tpu.serve import PolicyServer
        bus = EventBus(str(tmp_path), rank=0, name="serve")
        reg = Registry()
        srv = PolicyServer(_FakeEngine(), registry=reg,
                           latency_window=latency_window,
                           tracer=Tracer(bus, enabled=True))
        return srv, reg, bus

    def test_latency_histogram_and_window_gauge(self, tmp_path):
        srv, reg, bus = self._server(tmp_path)
        futs = [srv.submit(np.arange(3.0) + i, np.ones(2, bool))
                for i in range(3)]
        assert srv.pump() == 3
        assert all(f.result().latency_s >= 0 for f in futs)
        text = reg.render()
        assert 'serve_decision_latency_seconds_bucket{le="+Inf"} 3' \
            in text
        assert "serve_decision_latency_seconds_count 3" in text
        assert "serve_latency_sample_window 3" in text
        bus.close()

    def test_request_lifecycle_spans_on_the_bus(self, tmp_path):
        srv, reg, bus = self._server(tmp_path)
        srv.submit(np.arange(3.0), np.ones(2, bool))
        srv.submit(np.arange(3.0), np.ones(2, bool))
        srv.pump()
        bus.close()
        events = read_events(bus.path)
        points = [e["span"] for e in events if e["kind"] == SPAN_POINT]
        assert points == ["enqueue", "enqueue", "served"]
        # every enqueue carries a minted request id, and the served
        # instant resolves exactly those ids (conservation)
        enq_ids = [e["attrs"]["req_id"] for e in events
                   if e["kind"] == SPAN_POINT and e["span"] == "enqueue"]
        served = [e for e in events
                  if e["kind"] == SPAN_POINT and e["span"] == "served"]
        assert all(i > 0 for i in enq_ids)
        assert sorted(served[0]["attrs"]["req_ids"]) == sorted(enq_ids)
        begins = [e["span"] for e in events if e["kind"] == SPAN_BEGIN]
        assert begins == ["serve_batch", "arena_seal", "scatter"]
        # arena_seal/scatter nest INSIDE serve_batch
        rows = {n["path"]: n for n in build_span_tree(events)}
        assert "serve_batch/arena_seal" in rows
        assert "serve_batch/scatter" in rows

    def test_engine_pad_dispatch_spans(self, tmp_path):
        # the real engine's decide wraps pad and dispatch in spans
        import jax

        from rlgpuschedule_tpu.serve import InferenceEngine
        bus = EventBus(str(tmp_path), rank=0, name="serve")
        eng = InferenceEngine.__new__(InferenceEngine)
        # only exercise decide()'s span structure: stub the internals
        eng.max_bucket = 4
        eng.tracer = Tracer(bus, enabled=True)
        eng._has_stall_gate = False
        eng._serve_sharding = jax.sharding.SingleDeviceSharding(
            jax.devices()[0])
        eng._dispatch = lambda o, m, s, b: o
        obs = np.ones((3, 2), np.float32)
        acts, bucket = eng.decide(obs, np.ones((3, 2), bool))
        assert bucket == 4 and acts.shape[0] == 3
        bus.close()
        begins = [e["span"] for e in read_events(bus.path)
                  if e["kind"] == SPAN_BEGIN]
        assert begins == ["pad", "dispatch"]


class TestTracedAsyncRun:
    """THE acceptance path: a traced async run yields actor/learner
    lanes on one rank's timeline, a measured occupancy in the report,
    and a Perfetto-valid Chrome trace with nesting on every layer."""

    def _run(self, tmp_path):
        import jax

        from rlgpuschedule_tpu.async_engine import AsyncRunner
        from rlgpuschedule_tpu.experiment import Experiment
        from rlgpuschedule_tpu.parallel.groups import split_devices
        cfg = dataclasses.replace(SMALL, n_envs=4, n_nodes=2,
                                  gpus_per_node=4)
        exp = Experiment.build(cfg)
        runner = AsyncRunner(exp,
                             groups=split_devices(
                                 devices=jax.devices()[:1]),
                             staleness_bound=1)
        obs = str(tmp_path / "obs")
        with RunTelemetry(obs, rank=0, alarms=False, trace=True) as tel:
            out = runner.run(iterations=3, log_every=1, telemetry=tel)
        assert out["iterations"] == 3
        return obs

    def test_async_overlap_measured_and_perfetto_valid(self, tmp_path,
                                                       capsys):
        obs = self._run(tmp_path)
        events = merge_dir(obs)
        spans = {e["span"] for e in events if e["kind"] == SPAN_BEGIN}
        # both lanes + the wait spans landed
        assert {"actor", "learner", "queue_pop_wait"} <= spans
        # actor and learner live on DIFFERENT tracks of rank 0
        tid = {e["span"]: e["tid"] for e in events
               if e["kind"] == SPAN_BEGIN}
        assert tid["actor"] != tid["learner"]
        ov = async_overlap_summary(events)
        assert ov is not None
        assert 0.0 < ov["async_overlap_measured"] <= 1.0
        assert ov["actor_busy_s"] > 0 and ov["learner_busy_s"] > 0
        # report CLI: measured occupancy printed, trace exported
        trace_path = str(tmp_path / "trace.json")
        assert report_cli.main([obs, "--trace-out", trace_path]) == 0
        text = capsys.readouterr().out
        assert "async_overlap_measured=" in text
        assert "span tree" in text
        doc = json.load(open(trace_path))
        evs = doc["traceEvents"]
        depth = {}
        max_depth = {}
        for e in evs:
            if e["ph"] not in ("B", "E"):
                continue
            key = (e["pid"], e["tid"])
            if e["ph"] == "B":
                depth[key] = depth.get(key, 0) + 1
                max_depth[key] = max(max_depth.get(key, 0), depth[key])
            else:
                depth[key] = depth[key] - 1
                assert depth[key] >= 0, "unpaired E"
        assert all(v == 0 for v in depth.values()), "unpaired B"
        # nesting exists (learner inside iteration at least)
        assert max(max_depth.values()) >= 2
        # no torn spans in a clean run
        rep = report_cli.build_report(events)
        assert rep["torn_spans"] == 0


class TestCLIRefusals:
    def test_train_trace_spans_requires_obs_dir(self):
        from rlgpuschedule_tpu import train as train_cli
        with pytest.raises(SystemExit, match="--obs-dir"):
            train_cli.main(["--config", "ppo-mlp-synth64",
                            "--trace-spans"])

    def test_evaluate_trace_spans_requires_chaos_obs_dir(self):
        from rlgpuschedule_tpu import evaluate as eval_cli
        with pytest.raises(SystemExit, match="--chaos"):
            eval_cli.main(["--config", "ppo-mlp-synth64",
                           "--trace-spans"])

    def test_serve_trace_spans_requires_obs_dir(self):
        from rlgpuschedule_tpu.serve import __main__ as serve_cli
        with pytest.raises(SystemExit, match="--obs-dir"):
            serve_cli.main(["--config", "ppo-mlp-synth64", "--bench",
                            "--trace-spans"])


class TestReportTraceOut:
    def test_trace_out_without_spans_still_valid(self, tmp_path, capsys):
        d = str(tmp_path / "obs")
        with EventBus(d, rank=0) as bus:
            bus.emit("run_start", config="x")
            bus.emit("run_end")
        path = str(tmp_path / "t.json")
        assert report_cli.main([d, "--trace-out", path]) == 0
        capsys.readouterr()
        doc = json.load(open(path))
        assert all(e["ph"] in ("M", "i") for e in doc["traceEvents"])

    def test_skew_correct_default_and_opt_out(self, tmp_path, capsys):
        d = str(tmp_path / "obs")
        clock0 = iter([10.0, 11.0, 12.0])
        clock1 = iter([40.0, 41.0, 42.0])   # same wall, shifted mono
        import time as _time
        wall = _time.time()
        with EventBus(d, rank=0, clock=lambda: next(clock0),
                      wall=lambda: wall) as b0, \
                EventBus(d, rank=1, clock=lambda: next(clock1),
                         wall=lambda: wall) as b1:
            for b in (b0, b1):
                skew.stamp(b, source="test")
                skew.stamp(b, source="test")
                skew.stamp(b, source="test")
        assert report_cli.main([d, "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["skew"]["applied"]
        assert rep["skew"]["ranks"]["1"]["shift_s"] == pytest.approx(
            -30.0)
        assert report_cli.main([d, "--json", "--no-skew-correct"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert not rep["skew"]["applied"]
