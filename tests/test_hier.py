"""Hierarchical multi-pod env + factored policy tests (SURVEY.md §2
"Hierarchical multi-agent", §3.5 — config 5)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlgpuschedule_tpu.algos import PPOConfig, action_dist
from rlgpuschedule_tpu.configs import HIER_PBT_MEMBER
from rlgpuschedule_tpu.env import hier
from rlgpuschedule_tpu.env.hier import HierParams
from rlgpuschedule_tpu.experiment import Experiment, PopulationExperiment
from rlgpuschedule_tpu.parallel import PBTConfig, make_mesh
from rlgpuschedule_tpu.sim.core import (PENDING, RUNNING, SimParams, Trace)
from rlgpuschedule_tpu.traces.records import JobRecord, to_array_trace


def make_params(n_pods=2, nodes=1, gpus=4, max_jobs=8, queue_len=4):
    return HierParams(n_pods=n_pods,
                      pod_sim=SimParams(n_nodes=nodes, gpus_per_node=gpus,
                                        max_jobs=max_jobs,
                                        queue_len=queue_len),
                      reward_scale=100.0, horizon=64)


def tiny_trace(max_jobs=8):
    """Two 2-GPU jobs at t=0 (duration 100, 50) + one at t=10."""
    return to_array_trace(
        [JobRecord(0, 0.0, 100.0, 2), JobRecord(1, 0.0, 50.0, 2),
         JobRecord(2, 10.0, 30.0, 2)], max_jobs=max_jobs)


def dev_trace(tr, params):
    return Trace.from_array_trace(tr, params.pod_sim)


NOOP_TOP = lambda p: jnp.int32(p.n_pods)


def noop_actions(p):
    return {"top": NOOP_TOP(p),
            "pods": jnp.full((p.n_pods,), p.pod_sim.n_actions - 1,
                             jnp.int32)}


class TestActionDist:
    def test_multi_head_log_prob_and_entropy(self):
        logits = {"top": jnp.zeros((5, 3)), "pods": jnp.zeros((5, 2, 4))}
        actions = {"top": jnp.zeros((5,), jnp.int32),
                   "pods": jnp.zeros((5, 2), jnp.int32)}
        lp = action_dist.log_prob(logits, actions)
        assert lp.shape == (5,)
        np.testing.assert_allclose(
            lp, np.log(1 / 3) + 2 * np.log(1 / 4), rtol=1e-6)
        ent = action_dist.entropy(logits)
        np.testing.assert_allclose(ent, np.log(3) + 2 * np.log(4),
                                   rtol=1e-6)

    def test_single_head_matches_old_semantics(self):
        logits = jnp.array([[0.0, jnp.log(3.0)]])
        a = jnp.array([1], jnp.int32)
        lp = action_dist.log_prob(logits, a)
        np.testing.assert_allclose(lp, np.log(0.75), rtol=1e-6)

    def test_sample_respects_mask(self):
        logits = {"top": jnp.array([[-1e9, 0.0, -1e9]]),
                  "pods": jnp.array([[[0.0, -1e9]]])}
        for seed in range(5):
            acts, _ = action_dist.sample(jax.random.PRNGKey(seed), logits)
            assert int(acts["top"][0]) == 1
            assert int(acts["pods"][0, 0]) == 0


class TestHierMechanics:
    def test_reset_shapes_and_masks(self):
        p = make_params()
        tr = dev_trace(tiny_trace(), p)
        state, ts = hier.reset(p, tr)
        assert ts.obs["top"].shape == p.obs_shape()["top"]
        assert ts.obs["pods"].shape == p.obs_shape()["pods"]
        assert ts.action_mask["top"].shape == (p.n_pods + 1,)
        # jobs 0,1 arrived at t=0 → routing to either pod is legal
        assert bool(ts.action_mask["top"][0]) and bool(ts.action_mask["top"][1])
        assert int(state.assignment[0]) == -1

    def test_route_assigns_head_to_pod(self):
        p = make_params()
        tr = dev_trace(tiny_trace(), p)
        state, _ = hier.reset(p, tr)
        a = noop_actions(p) | {"top": jnp.int32(1)}
        state, ts = hier.step(p, state, tr, a)
        assert int(state.assignment[0]) == 1          # head = earliest submit
        assert int(state.pods.status[1, 0]) == PENDING
        assert float(ts.info.dt) == 0.0               # routing costs no time

    def test_pod_places_routed_job(self):
        p = make_params()
        tr = dev_trace(tiny_trace(), p)
        state, _ = hier.reset(p, tr)
        state, _ = hier.step(p, state, tr,
                             noop_actions(p) | {"top": jnp.int32(0)})
        acts = noop_actions(p)
        acts["pods"] = acts["pods"].at[0].set(0)      # pod 0: place slot 0
        state, _ = hier.step(p, state, tr, acts)
        assert int(state.pods.status[0, 0]) == RUNNING
        assert int(jnp.sum(state.pods.free[0])) == p.pod_capacity - 2
        # conservation in the untouched pod
        assert int(jnp.sum(state.pods.free[1])) == p.pod_capacity

    def test_place_bonus_shapes_reward(self):
        """ADVICE r1: place_bonus must reach the hierarchical reward.
        Routing is a progress step (dt=0, placed=True), so with a bonus
        the reward is exactly +bonus; without it, 0."""
        p0 = make_params()
        pb = dataclasses.replace(p0, place_bonus=0.25)
        tr = dev_trace(tiny_trace(), p0)
        a = noop_actions(p0) | {"top": jnp.int32(1)}
        s0, _ = hier.reset(p0, tr)
        _, ts0 = hier.step(p0, s0, tr, a)
        sb, _ = hier.reset(pb, tr)
        _, tsb = hier.step(pb, sb, tr, a)
        assert float(ts0.reward) == pytest.approx(0.0)
        assert float(tsb.reward) == pytest.approx(0.25)

    def test_noop_advances_to_completion(self):
        p = make_params()
        tr = dev_trace(tiny_trace(), p)
        state, _ = hier.reset(p, tr)
        state, _ = hier.step(p, state, tr,
                             noop_actions(p) | {"top": jnp.int32(0)})
        acts = noop_actions(p)
        acts["pods"] = acts["pods"].at[0].set(0)
        state, _ = hier.step(p, state, tr, acts)
        # all no-op: next event is job 2's arrival at t=10
        state, ts = hier.step(p, state, tr, noop_actions(p))
        assert float(hier.global_clock(state)) == pytest.approx(10.0)
        assert float(ts.info.dt) == pytest.approx(10.0)
        # reward = -dt * in_system_before / scale; jobs 0,1 in system
        assert float(ts.reward) == pytest.approx(-10.0 * 2 / 100.0)

    def test_forced_progress_routes_when_idle(self):
        p = make_params()
        tr = dev_trace(tiny_trace(), p)
        state, _ = hier.reset(p, tr)
        # advance past all arrivals with nothing running: repeated no-ops
        # must eventually force-route and force-place rather than deadlock
        for _ in range(12):
            state, ts = hier.step(p, state, tr, noop_actions(p))
        assert int(jnp.sum(state.assignment >= 0)) == 3
        assert bool(ts.done) or int(jnp.sum(
            (state.pods.status == RUNNING))) > 0

    def test_episode_completes_and_jct(self):
        """Route both t=0 jobs to different pods, place immediately: both
        run in parallel; job 2 (t=10, dur 30) finishes at 40. Hand-checked
        JCTs: 100, 50, 30."""
        p = make_params()
        tr = dev_trace(tiny_trace(), p)
        state, ts = hier.reset(p, tr)
        done = False
        for i in range(40):
            mask = hier.action_mask(p, state, tr)
            # greedy: route head to pod with most free GPUs; pods place
            # their queue head whenever legal
            pod_free = jnp.sum(state.pods.free, axis=1)
            top = jnp.where(jnp.any(mask["top"][:p.n_pods]),
                            jnp.argmax(pod_free), p.n_pods)
            pods = jnp.where(mask["pods"][:, 0], 0, p.pod_sim.n_actions - 1)
            state, ts = hier.step(p, state, tr,
                                  {"top": jnp.int32(top),
                                   "pods": pods.astype(jnp.int32)})
            if bool(ts.done):
                done = True
                break
        assert done
        stats = hier.jct_stats(state, tr)
        assert int(stats["n_done"]) == 3
        np.testing.assert_allclose(float(stats["avg_jct"]),
                                   (100 + 50 + 30) / 3, rtol=1e-5)

    def test_oversized_job_rejected_at_validation(self):
        p = make_params(gpus=4)
        big = to_array_trace([JobRecord(0, 0.0, 10.0, 8)], max_jobs=4)
        with pytest.raises(ValueError):
            hier.validate_hier_trace(p, big)


TINY_HIER = dataclasses.replace(
    HIER_PBT_MEMBER, n_nodes=4, gpus_per_node=4, n_pods=2, n_envs=4,
    window_jobs=16, queue_len=4, horizon=64,
    ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))


class TestHierTraining:
    def test_experiment_end_to_end(self):
        exp = Experiment.build(TINY_HIER)
        out = exp.run(iterations=2, log_every=1)
        assert out["env_steps"] == 2 * 8 * 4
        for h in out["history"]:
            assert np.isfinite(h["total_loss"])
            assert np.isfinite(h["mean_reward"])

    def test_population_pbt_over_hier_members(self):
        """Config 5 complete: PBT population of hierarchical 2-pod agents
        on the (pop, data) mesh."""
        mesh = make_mesh(n_pop=2)
        exp = PopulationExperiment.build(
            TINY_HIER, n_pop=2, mesh=mesh,
            pbt_cfg=PBTConfig(ready_iters=2, seed=0))
        out = exp.run(iterations=4)
        assert out["pbt_events"] >= 1
        assert all(np.isfinite(out["final_fitness"]))
