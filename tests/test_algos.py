"""Algorithm tests: GAE closed form, PPO loss math, train-step smoke, and
the learning smoke test (SURVEY.md §4 "Algorithm tests": "policy beats
random on a trivial 2-GPU env within N steps")."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu.ops import compute_gae
from rlgpuschedule_tpu.algos import (PPOConfig, make_ppo_step, init_carry,
                                     rollout, masked_entropy, ppo_loss,
                                     Transition, A2CConfig, make_a2c_step)
from rlgpuschedule_tpu.algos.ppo import make_optimizer
from rlgpuschedule_tpu.env import EnvParams, stack_traces
from rlgpuschedule_tpu.sim.core import SimParams
from rlgpuschedule_tpu.models import make_policy
from rlgpuschedule_tpu.traces import JobRecord, to_array_trace
from flax.training.train_state import TrainState


class TestGAE:
    def test_closed_form(self):
        # hand-derived: gamma=0.9, lam=0.8
        r = jnp.array([[1.0], [2.0], [3.0]])
        v = jnp.array([[0.5], [1.0], [1.5]])
        d = jnp.zeros((3, 1))
        adv, ret = compute_gae(r, v, d, jnp.array([2.0]), 0.9, 0.8)
        want = [4.80272, 4.726, 3.3]
        np.testing.assert_allclose(np.asarray(adv)[:, 0], want, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(ret)[:, 0],
                                   np.asarray(v)[:, 0] + want, rtol=1e-6)

    def test_done_stops_bootstrap(self):
        r = jnp.array([[1.0], [2.0]])
        v = jnp.array([[0.5], [1.0]])
        d = jnp.array([[0.0], [1.0]])
        adv, _ = compute_gae(r, v, d, jnp.array([99.0]), 0.9, 0.8)
        # t=1 terminal: adv = 2 - 1 = 1; t=0: delta=1+0.9-0.5=1.4, +0.72*1
        np.testing.assert_allclose(np.asarray(adv)[:, 0], [2.12, 1.0],
                                   rtol=1e-6)

    def test_lambda1_is_mc_minus_v(self):
        rng = np.random.default_rng(0)
        r = jnp.asarray(rng.normal(size=(6, 2)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(6, 2)).astype(np.float32))
        d = jnp.zeros((6, 2))
        last_v = jnp.asarray(rng.normal(size=(2,)).astype(np.float32))
        adv, ret = compute_gae(r, v, d, last_v, 0.95, 1.0)
        # lambda=1: returns = discounted MC return with bootstrap
        want = np.zeros((6, 2))
        acc = np.asarray(last_v)
        for t in reversed(range(6)):
            acc = np.asarray(r)[t] + 0.95 * acc
            want[t] = acc
        np.testing.assert_allclose(np.asarray(ret), want, rtol=1e-4)


class TestPPOMath:
    def _batch(self, n=4, a=3):
        return Transition(
            obs=jnp.zeros((n, 2)), action=jnp.zeros((n,), jnp.int32),
            log_prob=jnp.full((n,), -np.log(a)), value=jnp.zeros((n,)),
            reward=jnp.zeros((n,)), done=jnp.zeros((n,), bool),
            mask=jnp.ones((n, a), bool), env_steps_dt=jnp.zeros((n,)))

    def test_ratio_one_gives_neg_mean_adv(self):
        # apply_fn returns uniform logits == behavior policy → ratio = 1
        a = 3
        apply_fn = lambda p, obs, mask: (jnp.zeros((obs.shape[0], a)),
                                         jnp.zeros((obs.shape[0],)))
        cfg = PPOConfig(ent_coef=0.0, vf_coef=0.0)
        batch = self._batch(a=a)
        adv = jnp.array([1.0, -2.0, 3.0, 0.5])
        total, (pg, vl, ent, kl, cf) = ppo_loss(apply_fn, {}, batch, adv,
                                                jnp.zeros((4,)), cfg)
        assert float(pg) == pytest.approx(-float(adv.mean()), rel=1e-5)
        assert float(kl) == pytest.approx(0.0, abs=1e-6)
        assert float(cf) == 0.0
        assert float(ent) == pytest.approx(np.log(a), rel=1e-5)

    def test_clipping_caps_ratio(self):
        # behavior logp very low → ratio huge → clipped at 1+eps for adv>0
        a = 2
        apply_fn = lambda p, obs, mask: (
            jnp.stack([jnp.full((obs.shape[0],), 5.0),
                       jnp.full((obs.shape[0],), -5.0)], axis=1),
            jnp.zeros((obs.shape[0],)))
        cfg = PPOConfig(clip_eps=0.2, ent_coef=0.0, vf_coef=0.0)
        batch = self._batch(a=a)._replace(log_prob=jnp.full((4,), -3.0))
        adv = jnp.ones((4,))
        total, (pg, *_rest) = ppo_loss(apply_fn, {}, batch, adv,
                                       jnp.zeros((4,)), cfg)
        assert float(pg) == pytest.approx(-1.2, rel=1e-3)  # -(1+eps)*adv

    def test_masked_entropy_ignores_masked(self):
        logits = jnp.array([[0.0, 0.0, -1e9, -1e9]])
        assert float(masked_entropy(logits)[0]) == pytest.approx(np.log(2),
                                                                 rel=1e-4)


def tiny_env(n_envs=4, short=10.0, long=100.0):
    """1×2-GPU cluster; batch of mixed short/long 1-GPU jobs at t≈0 —
    ordering decides avg JCT, SJF-like is optimal."""
    jobs = []
    for i in range(8):
        jobs.append(JobRecord(i, 0.01 * i, short if i % 2 else long, 1))
    window = to_array_trace(jobs, max_jobs=8)
    params = EnvParams(sim=SimParams(1, 2, max_jobs=8, queue_len=4),
                       obs_kind="flat", horizon=64, time_scale=50.0,
                       reward_scale=100.0)
    traces = stack_traces([window] * n_envs, params)
    return params, traces


class TestTrainStep:
    # the SURVEY.md §5 sanitizer subset: these two smoke tests run under
    # jax_enable_checks + jax_debug_nans (conftest's opt-in marker) so
    # every release of the suite proves one full rollout+update of each
    # algorithm is NaN-clean under the strict interpreter, not just
    # finite in its reduced metrics
    @pytest.mark.sanitize
    def test_ppo_step_runs_and_is_finite(self):
        env_params, traces = tiny_env()
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = PPOConfig(n_steps=16, n_epochs=2, n_minibatches=2)
        key = jax.random.PRNGKey(0)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=make_optimizer(cfg))
        step = jax.jit(make_ppo_step(apply_fn, env_params, cfg))
        for i in range(3):
            state, carry, metrics = step(state, carry, traces,
                                         jax.random.PRNGKey(i))
        for v in metrics:
            assert np.isfinite(float(v)), metrics

    @pytest.mark.sanitize
    def test_a2c_step_runs_and_is_finite(self):
        env_params, traces = tiny_env()
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = A2CConfig(n_steps=8)
        key = jax.random.PRNGKey(0)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        from rlgpuschedule_tpu.algos.a2c import make_optimizer as a2c_opt
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=a2c_opt(cfg))
        step = jax.jit(make_a2c_step(apply_fn, env_params, cfg))
        for i in range(3):
            state, carry, metrics = step(state, carry, traces,
                                         jax.random.PRNGKey(i))
        for v in metrics:
            assert np.isfinite(float(v)), metrics


def policy_return(apply_fn, params, env_params, traces, key, n_steps=256):
    """Mean per-step reward of a policy over a fresh rollout."""
    carry = init_carry(env_params, traces, key)
    _, tr, _ = jax.jit(
        lambda c: rollout(apply_fn, params, env_params, traces, c, n_steps)
    )(carry)
    return float(tr.reward.mean())


class TestLearning:
    def test_ppo_beats_random_on_tiny_cluster(self):
        env_params, traces = tiny_env(n_envs=8)
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = PPOConfig(n_steps=32, n_epochs=4, n_minibatches=4, lr=1e-3,
                        ent_coef=0.005)
        key = jax.random.PRNGKey(42)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=make_optimizer(cfg))
        random_score = policy_return(apply_fn, params, env_params, traces,
                                     jax.random.PRNGKey(7))
        step = jax.jit(make_ppo_step(apply_fn, env_params, cfg))
        for i in range(40):
            key, sub = jax.random.split(key)
            state, carry, metrics = step(state, carry, traces, sub)
        trained_score = policy_return(apply_fn, state.params, env_params,
                                      traces, jax.random.PRNGKey(7))
        # the trained policy must clearly beat the untrained one
        assert trained_score > random_score * 0.8  # rewards are negative
        assert trained_score > random_score + 1e-4 or trained_score > -1e-6


class TestUpdateEngine:
    """The fused minibatch-geometry engine (algos/update.py): geometry
    validation, and the bit-level equivalence contract against the legacy
    per-minibatch loop it replaced (ISSUE 2 acceptance: the engine must be
    bit-identical to the previous update path at the default geometry)."""

    def _ppo_fixture(self, cfg):
        env_params, traces = tiny_env()
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        key = jax.random.PRNGKey(0)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=make_optimizer(cfg))
        roll = jax.jit(lambda c: rollout(apply_fn, params, env_params,
                                         traces, c, cfg.n_steps))
        _, tr, last_v = roll(carry)
        adv, ret = compute_gae(tr.reward, tr.value, tr.done, last_v,
                               cfg.gamma, cfg.gae_lambda)
        return apply_fn, state, tr, adv, ret

    def test_resolve_geometry_validation(self):
        from rlgpuschedule_tpu.algos import resolve_geometry
        assert resolve_geometry(2, 8, None, 64) == (2, 8, 8)
        # minibatch_size takes precedence and derives the count
        assert resolve_geometry(2, 999, 32, 64) == (2, 2, 32)
        # fewer-larger minibatches: one number expresses full-batch
        assert resolve_geometry(2, 999, 64, 64) == (2, 1, 64)
        with pytest.raises(ValueError, match="divisible"):
            resolve_geometry(2, 3, None, 64)
        with pytest.raises(ValueError, match="divide"):
            resolve_geometry(2, 8, 24, 64)
        with pytest.raises(ValueError, match="n_epochs"):
            resolve_geometry(0, 8, None, 64)
        with pytest.raises(ValueError, match="n_minibatches"):
            resolve_geometry(1, 0, None, 64)
        with pytest.raises(ValueError, match="minibatch_size"):
            resolve_geometry(1, 1, -8, 64)

    def test_build_rejects_untileable_geometry(self):
        import dataclasses
        from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
        from rlgpuschedule_tpu.experiment import Experiment
        bad = dataclasses.replace(
            PPO_MLP_SYNTH64, n_envs=4,
            ppo=PPOConfig(n_steps=16, minibatch_size=7))
        with pytest.raises(ValueError, match="divide"):
            Experiment.build(bad)

    def test_ppo_engine_bit_identical_to_legacy_loop(self):
        """The tier-1 equivalence smoke (ISSUE 2 / conftest perf-marker
        note): the fused engine at the default shuffled-minibatch
        geometry vs the legacy per-minibatch Python loop — params AND
        optimizer state must be BIT-identical after a full update."""
        from rlgpuschedule_tpu.algos.ppo import run_ppo_epochs
        cfg = PPOConfig(n_steps=16, n_epochs=2, n_minibatches=8)
        apply_fn, state, tr, adv, ret = self._ppo_fixture(cfg)
        upd_key = jax.random.PRNGKey(7)

        engine_state, _metrics = jax.jit(
            lambda s, k: run_ppo_epochs(
                apply_fn, cfg, s, tr, adv, ret, k,
                lambda st, g: st.apply_gradients(grads=g)))(state, upd_key)

        # legacy reference: explicit Python loop, one jitted minibatch
        # step, same key/permutation derivation as the engine
        B = cfg.n_steps * tr.reward.shape[1]
        flat = jax.tree.map(lambda x: x.reshape(B, *x.shape[2:]), tr)
        mb = B // cfg.n_minibatches

        @jax.jit
        def mb_step(state, mb_data):
            m, a, r = mb_data
            (_loss, _aux), grads = jax.value_and_grad(
                ppo_loss, argnums=1, has_aux=True)(
                apply_fn, state.params, m, a, r, cfg)
            return state.apply_gradients(grads=grads)

        legacy_state, key = state, upd_key
        for _e in range(cfg.n_epochs):
            key, sub = jax.random.split(key)
            perm = jax.random.permutation(sub, B)
            shuffled = jax.tree.map(
                lambda x: x[perm].reshape(cfg.n_minibatches, mb,
                                          *x.shape[1:]),
                (flat, adv.reshape(B), ret.reshape(B)))
            for i in range(cfg.n_minibatches):
                legacy_state = mb_step(
                    legacy_state, jax.tree.map(lambda x: x[i], shuffled))

        for new, old in zip(jax.tree.leaves(engine_state.params),
                            jax.tree.leaves(legacy_state.params)):
            np.testing.assert_array_equal(np.asarray(new), np.asarray(old))
        for new, old in zip(jax.tree.leaves(engine_state.opt_state),
                            jax.tree.leaves(legacy_state.opt_state)):
            np.testing.assert_array_equal(np.asarray(new), np.asarray(old))

    def test_a2c_engine_bit_identical_to_legacy_full_batch(self):
        """A2C's default 1x1 geometry through the engine == the classic
        direct full-batch update, bit for bit."""
        from rlgpuschedule_tpu.algos.a2c import (a2c_loss, run_a2c_update,
                                                 make_optimizer as a2c_opt)
        cfg = A2CConfig(n_steps=8)
        env_params, traces = tiny_env()
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        key = jax.random.PRNGKey(0)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=a2c_opt(cfg))
        _, tr, last_v = jax.jit(
            lambda c: rollout(apply_fn, params, env_params, traces, c,
                              cfg.n_steps))(carry)
        adv, ret = compute_gae(tr.reward, tr.value, tr.done, last_v,
                               cfg.gamma, cfg.gae_lambda)
        B = cfg.n_steps * tr.reward.shape[1]

        engine_state, _m = jax.jit(
            lambda s, k: run_a2c_update(
                apply_fn, cfg, s, tr, adv, ret, k,
                lambda st, g: st.apply_gradients(grads=g)))(
            state, jax.random.PRNGKey(3))

        flat = jax.tree.map(lambda x: x.reshape(B, *x.shape[2:]), tr)

        @jax.jit
        def legacy(state):
            (_loss, _aux), grads = jax.value_and_grad(
                a2c_loss, argnums=1, has_aux=True)(
                apply_fn, state.params, flat, adv.reshape(B),
                ret.reshape(B), cfg)
            return state.apply_gradients(grads=grads)

        legacy_state = legacy(state)
        for new, old in zip(jax.tree.leaves(engine_state.params),
                            jax.tree.leaves(legacy_state.params)):
            np.testing.assert_array_equal(np.asarray(new), np.asarray(old))

    def test_fewer_larger_minibatch_geometries_run_finite(self):
        """The swept geometries (full-batch epochs, explicit
        minibatch_size) must train finitely — the lever the sweep ranks."""
        import dataclasses
        from rlgpuschedule_tpu.algos.ppo import run_ppo_epochs
        base = PPOConfig(n_steps=16, n_epochs=2, n_minibatches=8)
        apply_fn, state, tr, adv, ret = self._ppo_fixture(base)
        B = base.n_steps * tr.reward.shape[1]
        for geom in (dict(n_epochs=1, n_minibatches=1),
                     dict(n_minibatches=1),
                     dict(minibatch_size=B),
                     dict(minibatch_size=B // 2, n_minibatches=999)):
            cfg = dataclasses.replace(base, **geom)
            _s, metrics = jax.jit(
                lambda s, k, c=cfg: run_ppo_epochs(
                    apply_fn, c, s, tr, adv, ret, k,
                    lambda st, g: st.apply_gradients(grads=g)))(
                state, jax.random.PRNGKey(1))
            assert all(np.isfinite(float(v)) for v in metrics), geom

    def test_bf16_update_keeps_fp32_state(self):
        """bf16-compute path: loss/grads in bfloat16 but params and
        optimizer state (Adam moments) stay fp32, metrics finite."""
        import dataclasses
        from rlgpuschedule_tpu.algos.ppo import run_ppo_epochs
        cfg = dataclasses.replace(
            PPOConfig(n_steps=16, n_epochs=2, n_minibatches=4),
            bf16_update=True)
        apply_fn, state, tr, adv, ret = self._ppo_fixture(cfg)
        new_state, metrics = jax.jit(
            lambda s, k: run_ppo_epochs(
                apply_fn, cfg, s, tr, adv, ret, k,
                lambda st, g: st.apply_gradients(grads=g)))(
            state, jax.random.PRNGKey(2))
        for leaf in jax.tree.leaves(new_state.params):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree.leaves(new_state.opt_state):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                         jnp.floating):
                assert leaf.dtype == jnp.float32
        assert all(np.isfinite(float(v)) for v in metrics)
        # and the params actually moved (the cast path trains)
        moved = any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(new_state.params),
                            jax.tree.leaves(state.params)))
        assert moved

    @pytest.mark.perf
    def test_swept_geometry_update_is_faster(self):
        """Opt-in (-m perf) wall-clock assertion: a fewer-larger-minibatch
        geometry must beat the default 2x8 update on this backend (the
        measured CPU sweep reads ~2x; assert a conservative margin)."""
        import dataclasses
        import time
        from rlgpuschedule_tpu.algos.ppo import run_ppo_epochs
        from rlgpuschedule_tpu.algos.update import make_update_step
        base = PPOConfig(n_steps=64, n_epochs=2, n_minibatches=8)
        apply_fn, state, tr, adv, ret = self._ppo_fixture(base)

        def timed(cfg):
            upd = make_update_step(
                lambda s, t, a, r, k: run_ppo_epochs(
                    apply_fn, cfg, s, t, a, r, k,
                    lambda st, g: st.apply_gradients(grads=g)))
            cell = jax.jit(lambda t: jax.tree.map(jnp.copy, t))(state)
            cell, _ = upd(cell, tr, adv, ret, jax.random.PRNGKey(0))
            jax.block_until_ready(cell.params)
            t0 = time.perf_counter()
            for _ in range(5):
                cell, _ = upd(cell, tr, adv, ret, jax.random.PRNGKey(0))
            jax.block_until_ready(cell.params)
            return time.perf_counter() - t0

        t_default = timed(base)
        t_swept = timed(dataclasses.replace(base, n_epochs=1,
                                            n_minibatches=2))
        assert t_swept < t_default * 0.8, (t_swept, t_default)
