"""Algorithm tests: GAE closed form, PPO loss math, train-step smoke, and
the learning smoke test (SURVEY.md §4 "Algorithm tests": "policy beats
random on a trivial 2-GPU env within N steps")."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu.ops import compute_gae
from rlgpuschedule_tpu.algos import (PPOConfig, make_ppo_step, init_carry,
                                     rollout, masked_entropy, ppo_loss,
                                     Transition, A2CConfig, make_a2c_step)
from rlgpuschedule_tpu.algos.ppo import make_optimizer
from rlgpuschedule_tpu.env import EnvParams, stack_traces
from rlgpuschedule_tpu.sim.core import SimParams
from rlgpuschedule_tpu.models import make_policy
from rlgpuschedule_tpu.traces import JobRecord, to_array_trace
from flax.training.train_state import TrainState


class TestGAE:
    def test_closed_form(self):
        # hand-derived: gamma=0.9, lam=0.8
        r = jnp.array([[1.0], [2.0], [3.0]])
        v = jnp.array([[0.5], [1.0], [1.5]])
        d = jnp.zeros((3, 1))
        adv, ret = compute_gae(r, v, d, jnp.array([2.0]), 0.9, 0.8)
        want = [4.80272, 4.726, 3.3]
        np.testing.assert_allclose(np.asarray(adv)[:, 0], want, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(ret)[:, 0],
                                   np.asarray(v)[:, 0] + want, rtol=1e-6)

    def test_done_stops_bootstrap(self):
        r = jnp.array([[1.0], [2.0]])
        v = jnp.array([[0.5], [1.0]])
        d = jnp.array([[0.0], [1.0]])
        adv, _ = compute_gae(r, v, d, jnp.array([99.0]), 0.9, 0.8)
        # t=1 terminal: adv = 2 - 1 = 1; t=0: delta=1+0.9-0.5=1.4, +0.72*1
        np.testing.assert_allclose(np.asarray(adv)[:, 0], [2.12, 1.0],
                                   rtol=1e-6)

    def test_lambda1_is_mc_minus_v(self):
        rng = np.random.default_rng(0)
        r = jnp.asarray(rng.normal(size=(6, 2)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(6, 2)).astype(np.float32))
        d = jnp.zeros((6, 2))
        last_v = jnp.asarray(rng.normal(size=(2,)).astype(np.float32))
        adv, ret = compute_gae(r, v, d, last_v, 0.95, 1.0)
        # lambda=1: returns = discounted MC return with bootstrap
        want = np.zeros((6, 2))
        acc = np.asarray(last_v)
        for t in reversed(range(6)):
            acc = np.asarray(r)[t] + 0.95 * acc
            want[t] = acc
        np.testing.assert_allclose(np.asarray(ret), want, rtol=1e-4)


class TestPPOMath:
    def _batch(self, n=4, a=3):
        return Transition(
            obs=jnp.zeros((n, 2)), action=jnp.zeros((n,), jnp.int32),
            log_prob=jnp.full((n,), -np.log(a)), value=jnp.zeros((n,)),
            reward=jnp.zeros((n,)), done=jnp.zeros((n,), bool),
            mask=jnp.ones((n, a), bool), env_steps_dt=jnp.zeros((n,)))

    def test_ratio_one_gives_neg_mean_adv(self):
        # apply_fn returns uniform logits == behavior policy → ratio = 1
        a = 3
        apply_fn = lambda p, obs, mask: (jnp.zeros((obs.shape[0], a)),
                                         jnp.zeros((obs.shape[0],)))
        cfg = PPOConfig(ent_coef=0.0, vf_coef=0.0)
        batch = self._batch(a=a)
        adv = jnp.array([1.0, -2.0, 3.0, 0.5])
        total, (pg, vl, ent, kl, cf) = ppo_loss(apply_fn, {}, batch, adv,
                                                jnp.zeros((4,)), cfg)
        assert float(pg) == pytest.approx(-float(adv.mean()), rel=1e-5)
        assert float(kl) == pytest.approx(0.0, abs=1e-6)
        assert float(cf) == 0.0
        assert float(ent) == pytest.approx(np.log(a), rel=1e-5)

    def test_clipping_caps_ratio(self):
        # behavior logp very low → ratio huge → clipped at 1+eps for adv>0
        a = 2
        apply_fn = lambda p, obs, mask: (
            jnp.stack([jnp.full((obs.shape[0],), 5.0),
                       jnp.full((obs.shape[0],), -5.0)], axis=1),
            jnp.zeros((obs.shape[0],)))
        cfg = PPOConfig(clip_eps=0.2, ent_coef=0.0, vf_coef=0.0)
        batch = self._batch(a=a)._replace(log_prob=jnp.full((4,), -3.0))
        adv = jnp.ones((4,))
        total, (pg, *_rest) = ppo_loss(apply_fn, {}, batch, adv,
                                       jnp.zeros((4,)), cfg)
        assert float(pg) == pytest.approx(-1.2, rel=1e-3)  # -(1+eps)*adv

    def test_masked_entropy_ignores_masked(self):
        logits = jnp.array([[0.0, 0.0, -1e9, -1e9]])
        assert float(masked_entropy(logits)[0]) == pytest.approx(np.log(2),
                                                                 rel=1e-4)


def tiny_env(n_envs=4, short=10.0, long=100.0):
    """1×2-GPU cluster; batch of mixed short/long 1-GPU jobs at t≈0 —
    ordering decides avg JCT, SJF-like is optimal."""
    jobs = []
    for i in range(8):
        jobs.append(JobRecord(i, 0.01 * i, short if i % 2 else long, 1))
    window = to_array_trace(jobs, max_jobs=8)
    params = EnvParams(sim=SimParams(1, 2, max_jobs=8, queue_len=4),
                       obs_kind="flat", horizon=64, time_scale=50.0,
                       reward_scale=100.0)
    traces = stack_traces([window] * n_envs, params)
    return params, traces


class TestTrainStep:
    # the SURVEY.md §5 sanitizer subset: these two smoke tests run under
    # jax_enable_checks + jax_debug_nans (conftest's opt-in marker) so
    # every release of the suite proves one full rollout+update of each
    # algorithm is NaN-clean under the strict interpreter, not just
    # finite in its reduced metrics
    @pytest.mark.sanitize
    def test_ppo_step_runs_and_is_finite(self):
        env_params, traces = tiny_env()
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = PPOConfig(n_steps=16, n_epochs=2, n_minibatches=2)
        key = jax.random.PRNGKey(0)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=make_optimizer(cfg))
        step = jax.jit(make_ppo_step(apply_fn, env_params, cfg))
        for i in range(3):
            state, carry, metrics = step(state, carry, traces,
                                         jax.random.PRNGKey(i))
        for v in metrics:
            assert np.isfinite(float(v)), metrics

    @pytest.mark.sanitize
    def test_a2c_step_runs_and_is_finite(self):
        env_params, traces = tiny_env()
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = A2CConfig(n_steps=8)
        key = jax.random.PRNGKey(0)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        from rlgpuschedule_tpu.algos.a2c import make_optimizer as a2c_opt
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=a2c_opt(cfg))
        step = jax.jit(make_a2c_step(apply_fn, env_params, cfg))
        for i in range(3):
            state, carry, metrics = step(state, carry, traces,
                                         jax.random.PRNGKey(i))
        for v in metrics:
            assert np.isfinite(float(v)), metrics


def policy_return(apply_fn, params, env_params, traces, key, n_steps=256):
    """Mean per-step reward of a policy over a fresh rollout."""
    carry = init_carry(env_params, traces, key)
    _, tr, _ = jax.jit(
        lambda c: rollout(apply_fn, params, env_params, traces, c, n_steps)
    )(carry)
    return float(tr.reward.mean())


class TestLearning:
    def test_ppo_beats_random_on_tiny_cluster(self):
        env_params, traces = tiny_env(n_envs=8)
        net = make_policy("flat", env_params.n_actions)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = PPOConfig(n_steps=32, n_epochs=4, n_minibatches=4, lr=1e-3,
                        ent_coef=0.005)
        key = jax.random.PRNGKey(42)
        carry = init_carry(env_params, traces, key)
        params = net.init(key, carry.obs[:1], carry.mask[:1])
        state = TrainState.create(apply_fn=net.apply, params=params,
                                  tx=make_optimizer(cfg))
        random_score = policy_return(apply_fn, params, env_params, traces,
                                     jax.random.PRNGKey(7))
        step = jax.jit(make_ppo_step(apply_fn, env_params, cfg))
        for i in range(40):
            key, sub = jax.random.split(key)
            state, carry, metrics = step(state, carry, traces, sub)
        trained_score = policy_return(apply_fn, state.params, env_params,
                                      traces, jax.random.PRNGKey(7))
        # the trained policy must clearly beat the untrained one
        assert trained_score > random_score * 0.8  # rewards are negative
        assert trained_score > random_score + 1e-4 or trained_score > -1e-6
