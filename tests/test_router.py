"""Multi-engine serving router tests (ISSUE 13): routed-fleet-vs-single
bit-identity under the recompile sentinel, least-loaded dispatch
fairness, deadline-aware shedding (a shed request is NEVER a silent
drop — its future resolves with a typed rejection), the adaptive
batching estimators, and autoscale-advisor hysteresis (no flapping on
a steady load)."""
import numpy as np
import pytest

from rlgpuschedule_tpu.configs import (ModeCombinationError,
                                       validate_mode_combination)
from rlgpuschedule_tpu.obs import Registry
from rlgpuschedule_tpu.parallel.mesh import serve_devices
from rlgpuschedule_tpu.serve import (AutoscaleAdvisor, DeadlineSheddedError,
                                     EngineRouter, Ewma, InferenceEngine,
                                     InjectedEngineFault, PolicyServer,
                                     ServeFaultInjector, ServeFaultSpec,
                                     ServeResult, ServerClosedError,
                                     next_bucket, parse_serve_fault)

OBS_D, ACT_D = 6, 9


def linear_apply(params, obs, mask):
    """Row-wise linear policy head — batch-composition invariant by
    construction, so per-request actions are comparable no matter how
    the router coalesced them."""
    return obs @ params["w"], None


def make_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": rng.standard_normal((OBS_D, ACT_D)).astype(np.float32)}


def make_batch(rng, n):
    obs = rng.standard_normal((n, OBS_D)).astype(np.float32)
    mask = rng.integers(0, 2, (n, ACT_D)).astype(bool)
    mask[:, 0] = True           # at least one legal action per row
    return obs, mask


def make_router(n_engines=2, max_bucket=8, registry=None, **kw):
    return EngineRouter(linear_apply, make_params(), max_bucket=max_bucket,
                        registry=registry, stall_gate=False,
                        n_engines=n_engines, **kw)


class FakeEngine:
    """Host-only engine stand-in for batching-policy tests: every
    dispatch advances the shared fake clock by ``cost_s``, so the
    server's service-time estimator learns an exact, deterministic
    value (no real timing in the deadline tests)."""

    def __init__(self, clock_cell, max_bucket=8, cost_s=0.05):
        self.max_bucket = max_bucket
        self.cost_s = cost_s
        self.dispatches = 0
        self._t = clock_cell

    def bucket_for(self, n):
        return next_bucket(n, self.max_bucket)

    def decide(self, obs, mask, stall=None):
        n = int(np.asarray(obs).shape[0])
        self._t[0] += self.cost_s
        self.dispatches += 1
        return np.asarray(obs), self.bucket_for(n)


def fake_server(max_bucket=8, cost_s=0.05, **kw):
    t = [0.0]
    reg = Registry()
    server = PolicyServer(FakeEngine(t, max_bucket, cost_s), registry=reg,
                          clock=lambda: t[0], **kw)
    return server, t, reg


def row(rng):
    return (rng.standard_normal(OBS_D).astype(np.float32),
            np.ones(ACT_D, bool))


class TestRoutedBitIdentity:
    """The tentpole contract: a routed fleet of N engines is bit-identical
    to ONE engine fed the same request stream, with zero post-warmup
    recompiles PER ENGINE (CompileCounter-gated via the per-engine
    labeled sentinel counters)."""

    def test_fleet_matches_single_engine_bitwise(self):
        assert len(serve_devices()) >= 2, \
            "conftest forces 8 virtual CPU devices"
        params = make_params()
        router = EngineRouter(linear_apply, params, max_bucket=8,
                              registry=Registry(), stall_gate=False,
                              n_engines=2)
        single = InferenceEngine(linear_apply, params, max_bucket=8,
                                 registry=Registry(), stall_gate=False)
        rng = np.random.default_rng(0)
        batches = [make_batch(rng, int(rng.integers(1, 9)))
                   for _ in range(12)]
        obs0, mask0 = batches[0]
        router.warmup(obs0[0], mask0[0])
        single.warmup(obs0[0], mask0[0])
        for obs, mask in batches:
            a_r, b_r = router.decide(obs, mask)
            a_s, b_s = single.decide(obs, mask)
            assert b_r == b_s
            assert np.array_equal(np.asarray(a_r), np.asarray(a_s))
        # the zero-recompile contract is per engine, not fleet-aggregate
        assert router.per_engine_recompiles() == [0, 0]
        assert single.post_warmup_recompiles == 0
        rows = [s.rows for s in router.stats()]
        assert all(r > 0 for r in rows), \
            f"both engines must actually serve, got rows={rows}"
        assert sum(rows) == sum(o.shape[0] for o, _ in batches)

    def test_threaded_fleet_matches_rowwise_reference(self):
        """End-to-end through the PolicyServer with 2 live dispatcher
        threads: whatever batches the router coalesced, every request's
        action equals the single-engine answer for its own row."""
        params = make_params()
        reg = Registry()
        router = make_router(registry=reg)
        single = InferenceEngine(linear_apply, params, max_bucket=8,
                                 registry=Registry(), stall_gate=False)
        rng = np.random.default_rng(1)
        rows = [row(rng) for _ in range(60)]
        router.warmup(*rows[0])
        single.warmup(*rows[0])
        server = PolicyServer(router, registry=reg)
        server.start(dispatchers=2)
        try:
            futs = [server.submit(o, m) for o, m in rows]
            got = [f.result(timeout=60).action for f in futs]
        finally:
            server.stop()
        for (o, m), a in zip(rows, got):
            ref, _ = single.decide(o[None], m[None])
            assert np.array_equal(np.asarray(a), np.asarray(ref)[0])
        assert router.per_engine_recompiles() == [0, 0]

    def test_per_engine_labeled_series_in_scrape(self):
        reg = Registry()
        router = make_router(registry=reg)
        rng = np.random.default_rng(2)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0], buckets=(4,))
        router.decide(obs, mask)
        router.decide(obs, mask)
        text = reg.render()
        for i in (0, 1):
            assert f'serve_engine_rows_total{{engine="{i}"}}' in text
            assert f'serve_recompile_alarms_total{{engine="{i}"}}' in text
        assert "serve_engines_total 2" in text
        assert "serve_engines_active 2" in text


class TestLeastLoaded:
    def test_equal_batches_split_evenly(self):
        router = make_router(max_bucket=4)
        rng = np.random.default_rng(3)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0], buckets=(4,))
        for _ in range(6):
            router.decide(obs, mask)
        stats = router.stats()
        assert [s.dispatches for s in stats] == [3, 3]
        assert [s.rows for s in stats] == [12, 12]

    def test_fewest_rows_breaks_ties(self):
        """Sequential dispatches (inflight always 0 at pick time) route
        by lifetime rows: after a big batch lands on engine 0, the
        smaller ones pile onto engine 1 until it catches up."""
        router = make_router(max_bucket=8)
        rng = np.random.default_rng(4)
        o8, m8 = make_batch(rng, 8)
        o1, m1 = make_batch(rng, 1)
        router.warmup(o8[0], m8[0], buckets=(1, 8))
        router.decide(o8, m8)           # engine 0: 8 rows
        for _ in range(8):
            router.decide(o1, m1)       # all catch-up goes to engine 1
        stats = router.stats()
        assert stats[0].rows == 8
        assert stats[1].rows == 8

    def test_inflight_preferred_over_rows(self):
        router = make_router()
        assert router._acquire() == 0
        assert router._acquire() == 1   # engine 0 is busy
        router._release(0, 0, None)     # aborted dispatch: no rows booked
        assert router._acquire() == 0   # free again, beats busy engine 1
        router._release(0, 0, None)
        router._release(1, 0, None)
        assert all(s.inflight == 0 for s in router.stats())

    def test_set_active_drains_and_reactivates(self):
        router = make_router(max_bucket=4)
        rng = np.random.default_rng(5)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0], buckets=(4,))
        assert router.set_active(1) == 1
        for _ in range(4):
            router.decide(obs, mask)
        stats = router.stats()
        assert stats[0].dispatches == 4 and stats[1].dispatches == 0
        assert not stats[1].active
        assert router.set_active(2) == 2
        router.decide(obs, mask)        # least-loaded: engine 1 next
        assert router.stats()[1].dispatches == 1
        assert router.per_engine_recompiles() == [0, 0]

    def test_spinup_warms_cold_engine_before_traffic(self):
        """An engine activated AFTER warmup gets its blessed compiles
        from the stored example before it takes traffic — so its
        recompile counter stays 0 through live dispatches."""
        router = make_router(max_bucket=4)
        rng = np.random.default_rng(6)
        obs, mask = make_batch(rng, 4)
        router.set_active(1)
        router.warmup(obs[0], mask[0])          # engine 1 inactive: cold
        assert router.engines[1].warmed_buckets == ()
        router.set_active(2)
        assert router.engines[1].warmed_buckets != ()
        for _ in range(4):
            router.decide(obs, mask)
        assert router.per_engine_recompiles() == [0, 0]
        assert router.stats()[1].rows > 0

    def test_set_active_clamps(self):
        router = make_router()
        assert router.set_active(0) == 1        # never below one engine
        assert router.set_active(99) == 2       # never above the fleet

    def test_set_active_fires_rewarm_listeners_on_change_only(self):
        """ISSUE 17 satellite: a fleet change (spin-up warm or active-
        count change) notifies re-warm listeners — the PolicyServer
        resets its service-time Ewma off this hook — while a no-op
        ``set_active`` stays silent (no estimator churn on the advisor's
        steady-state ticks)."""
        router = make_router(max_bucket=4)
        fired = []
        router.add_rewarm_listener(lambda: fired.append(1))
        assert router.set_active(2) == 2        # already 2: no change
        assert fired == []
        assert router.set_active(1) == 1
        assert len(fired) == 1
        assert router.set_active(1) == 1        # steady: still silent
        assert len(fired) == 1
        rng = np.random.default_rng(7)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0])          # engine 1 inactive: cold
        router.set_active(2)                    # spin-up warm => fires
        assert len(fired) == 2

    def test_policy_server_resets_estimator_on_router_rewarm(self):
        """End-to-end wiring: PolicyServer registers on the router at
        construction; a set_active fleet change wipes the learned
        service time (back to cold-admit until relearned)."""
        router = make_router(max_bucket=4)
        rng = np.random.default_rng(8)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0])
        server = PolicyServer(router, example_obs=obs[0],
                              example_mask=mask[0])
        for i in range(4):
            server.submit(obs[i], mask[i])
        assert server.pump() == 4
        assert server.service_time_s() is not None
        router.set_active(1)                    # fleet changed
        assert server.service_time_s() is None  # estimator reset
        server.close()

    def test_n_engines_validation(self):
        with pytest.raises(ValueError, match="n_engines"):
            make_router(n_engines=0)
        with pytest.raises(ValueError, match="n_engines"):
            make_router(n_engines=len(serve_devices()) + 1)

    def test_serialized_dispatch_honesty_bit_on_cpu(self):
        assert make_router().serialized_dispatch() is True

    def test_router_hier_combination_refused(self):
        with pytest.raises(ModeCombinationError, match="router"):
            validate_mode_combination({"router": True, "hier": True})
        validate_mode_combination({"router": True, "hier": False})
        validate_mode_combination({"router": False, "hier": True})


class TestDeadlineShedding:
    def test_expired_request_resolves_with_typed_rejection(self):
        server, t, reg = fake_server()
        rng = np.random.default_rng(7)
        fut = server.submit(*row(rng), deadline_s=0.5)
        t[0] += 1.0
        assert server.pump() == 0       # nothing left to serve
        assert fut.done()
        with pytest.raises(DeadlineSheddedError) as ei:
            fut.result()
        assert ei.value.reason == "expired"
        assert ei.value.waited_s == pytest.approx(1.0)
        assert reg.counter("serve_shed_total").value == 1

    def test_admission_shed_uses_learned_service_time(self):
        server, t, reg = fake_server(cost_s=0.05)
        rng = np.random.default_rng(8)
        ok = server.submit(*row(rng))
        server.pump()                   # learns service time = 0.05
        assert isinstance(ok.result(), ServeResult)
        fut = server.submit(*row(rng), deadline_s=0.01)
        assert fut.done()               # rejected at the door, no queue
        with pytest.raises(DeadlineSheddedError) as ei:
            fut.result()
        assert ei.value.reason == "admission"
        assert ei.value.predicted_wait_s == pytest.approx(0.05)
        assert reg.counter("serve_shed_total").value == 1
        assert server.pump() == 0       # the shed request never queued

    def test_cold_server_admits_rather_than_guessing(self):
        server, t, _ = fake_server()
        rng = np.random.default_rng(9)
        fut = server.submit(*row(rng), deadline_s=1e-9)
        assert not fut.done()           # no service estimate yet: admit
        assert server.pump() == 1       # clock hasn't moved: still fresh
        assert isinstance(fut.result(), ServeResult)

    def test_mid_queue_expiry_not_masked_by_generous_head(self):
        """Deadlines are per-request: an expired TAIL request sheds even
        when the queue head has no deadline at all (full-scan, not
        head-only)."""
        server, t, reg = fake_server()
        rng = np.random.default_rng(10)
        head = server.submit(*row(rng))
        tail = server.submit(*row(rng), deadline_s=0.1)
        t[0] += 0.2
        assert server.pump() == 1
        assert isinstance(head.result(), ServeResult)
        with pytest.raises(DeadlineSheddedError):
            tail.result()
        assert reg.counter("serve_shed_total").value == 1

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_no_request_is_ever_silently_dropped(self, seed):
        """Property: for a random stream of deadlined and deadline-free
        requests under a randomly advancing clock, EVERY future
        resolves — to a ServeResult or a DeadlineSheddedError — and the
        shed counter equals exactly the number of typed rejections."""
        server, t, reg = fake_server(max_bucket=4, cost_s=0.02)
        rng = np.random.default_rng(seed)
        futs = []
        for _ in range(40):
            deadline = (None if rng.random() < 0.4
                        else float(rng.uniform(0.005, 0.2)))
            futs.append(server.submit(*row(rng), deadline_s=deadline))
            t[0] += float(rng.uniform(0.0, 0.05))
            if rng.random() < 0.3:
                server.pump()
        while server._pending:
            server.pump()
        shed = 0
        for f in futs:
            assert f.done(), "a submitted request's future never resolved"
            try:
                assert isinstance(f.result(), ServeResult)
            except DeadlineSheddedError:
                shed += 1
        assert reg.counter("serve_shed_total").value == shed
        assert shed + sum(1 for f in futs
                          if not f.exception()) == len(futs)


class TestAdaptiveWait:
    def test_static_mode_returns_the_knob(self):
        server, t, _ = fake_server(max_wait_s=0.02)
        rng = np.random.default_rng(11)
        server.submit(*row(rng))
        assert server._effective_wait() == 0.02

    def test_adaptive_holds_for_estimated_fill_time(self):
        server, t, _ = fake_server(max_bucket=8, adaptive_wait=True)
        rng = np.random.default_rng(12)
        server.submit(*row(rng))
        assert server._effective_wait() is None     # nothing learned yet
        t[0] += 0.1
        server.submit(*row(rng))
        t[0] += 0.1
        server.submit(*row(rng))                    # arrival gap -> 0.1
        # 3 pending of 8: hold ~= gap x free slots = 0.1 * 5
        assert server._effective_wait() == pytest.approx(0.5)

    def test_deadline_slack_clips_the_hold(self):
        server, t, _ = fake_server(max_bucket=8, cost_s=0.05,
                                   adaptive_wait=True)
        rng = np.random.default_rng(13)
        f = server.submit(*row(rng))
        server.pump()                               # learn service 0.05
        f.result()
        server.submit(*row(rng), deadline_s=0.08)
        # slack 0.08 minus one service time in hand = 0.03, well under
        # any fill estimate — the head sheds nothing, it dispatches early
        assert server._effective_wait() == pytest.approx(0.03)

    def test_expired_slack_floors_at_zero(self):
        server, t, _ = fake_server(max_bucket=8, cost_s=0.05,
                                   adaptive_wait=True)
        rng = np.random.default_rng(14)
        f = server.submit(*row(rng))
        server.pump()
        f.result()
        server.submit(*row(rng), deadline_s=0.06)   # admitted: 0.05 fits
        t[0] += 0.1                                 # ...then the SLO dies
        assert server._effective_wait() == 0.0


class TestEwma:
    def test_unlearned_is_none(self):
        assert Ewma().value is None

    def test_update_math(self):
        e = Ewma(alpha=0.2)
        assert e.update(1.0) == pytest.approx(1.0)
        assert e.update(2.0) == pytest.approx(0.2 * 2.0 + 0.8 * 1.0)
        assert e.count == 2

    def test_alpha_validation(self):
        for bad in (0.0, -0.1, 1.5):
            with pytest.raises(ValueError, match="alpha"):
                Ewma(alpha=bad)


def advisor_reg(p99=10.0, depth=0, occ=0.6, shed=0):
    """Registry primed with a healthy steady-state SLO surface; override
    one signal per test."""
    reg = Registry()
    reg.gauge("serve_decision_latency_p99_ms").set(p99)
    reg.gauge("serve_queue_depth").set(depth)
    reg.gauge("serve_batch_occupancy").set(occ)
    if shed:
        reg.counter("serve_shed_total").inc(shed)
    return reg


class TestAutoscaleHysteresis:
    def test_steady_load_never_flaps(self):
        """The headline property: a healthy steady load holds the fleet
        size forever — zero resizes over many ticks."""
        reg = advisor_reg()
        adv = AutoscaleAdvisor(reg, n_max=4, initial=2, hysteresis=3)
        for _ in range(20):
            assert adv.observe() == 2
        assert reg.counter("serve_autoscale_resizes_total").value == 0
        assert reg.gauge("serve_autoscale_desired_engines").value == 2

    def test_scale_up_needs_consecutive_votes(self):
        reg = advisor_reg(depth=100)
        adv = AutoscaleAdvisor(reg, n_max=4, initial=2, hysteresis=3,
                               queue_high=64)
        assert adv.observe() == 2
        assert adv.observe() == 2
        assert adv.observe() == 3       # third consecutive up vote lands
        assert reg.counter("serve_autoscale_resizes_total").value == 1

    def test_mixed_votes_reset_the_streak(self):
        reg = advisor_reg(depth=100)
        adv = AutoscaleAdvisor(reg, n_max=4, initial=2, hysteresis=3)
        adv.observe(); adv.observe()                    # two up votes
        reg.gauge("serve_queue_depth").set(0)           # healthy: hold
        assert adv.observe() == 2                       # streak reset
        reg.gauge("serve_queue_depth").set(100)
        adv.observe(); adv.observe()
        assert adv.desired == 2                         # needs a fresh 3
        assert adv.observe() == 3

    def test_scale_down_on_idle_clamps_at_n_min(self):
        reg = advisor_reg(p99=5.0, occ=0.1)
        adv = AutoscaleAdvisor(reg, n_max=4, initial=2, hysteresis=2)
        adv.observe()
        assert adv.observe() == 1
        for _ in range(6):
            assert adv.observe() == 1   # clamped, no further resizes
        assert reg.counter("serve_autoscale_resizes_total").value == 1

    def test_shedding_is_an_up_vote(self):
        reg = advisor_reg()
        adv = AutoscaleAdvisor(reg, n_max=4, initial=2, hysteresis=1)
        assert adv.observe() == 2                       # no shed delta
        reg.counter("serve_shed_total").inc(3)
        assert adv.observe() == 3                       # delta observed
        assert adv.observe() == 3                       # delta consumed

    def test_p99_over_target_is_an_up_vote(self):
        reg = advisor_reg(p99=80.0)
        adv = AutoscaleAdvisor(reg, n_max=4, initial=2, hysteresis=1,
                               p99_target_ms=50.0)
        assert adv.observe() == 3

    def test_unset_gauges_never_scale_up(self):
        """A fresh registry reads all-zero: that can only ever look like
        idleness, never pressure — the advisor must not invent load."""
        adv = AutoscaleAdvisor(Registry(), n_max=4, initial=2,
                               hysteresis=1)
        for _ in range(5):
            assert adv.observe() <= 2

    def test_router_applies_votes_live(self):
        reg = advisor_reg(p99=5.0, occ=0.1)
        router = make_router(max_bucket=4, registry=reg)
        rng = np.random.default_rng(15)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0], buckets=(4,))
        adv = AutoscaleAdvisor(reg, n_max=2, initial=2, hysteresis=1)
        assert router.apply_autoscale(adv) == 1         # idle: drain
        reg.gauge("serve_queue_depth").set(100)
        assert router.apply_autoscale(adv) == 2         # pressure: grow
        router.decide(obs, mask)
        assert router.per_engine_recompiles() == [0, 0]

    def test_validation(self):
        with pytest.raises(ValueError, match="n_min"):
            AutoscaleAdvisor(Registry(), n_max=0)
        with pytest.raises(ValueError, match="hysteresis"):
            AutoscaleAdvisor(Registry(), n_max=2, hysteresis=0)


# ---- ISSUE 16: engine fault tolerance ---------------------------------

class _Bus:
    """Event-bus stand-in recording (kind, fields) tuples."""

    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.events]


def health_router(specs, injector_kw=None, bus=None, **kw):
    """2-engine router with a fake monotonic clock (cell-advanced) and
    an armed fault injector, for deterministic ejection/backoff tests."""
    now = [100.0]
    inj = ServeFaultInjector(specs, bus=bus, **(injector_kw or {}))
    router = make_router(registry=Registry(), fault_injector=inj, bus=bus,
                         probe_backoff_s=0.5, clock=lambda: now[0], **kw)
    return router, now


class TestServeFaultSpecs:
    def test_parse_round_trip(self):
        s = parse_serve_fault("engine-hang@10:engine=1")
        assert (s.kind, s.at, s.engine, s.fired) == \
            ("engine-hang", 10, 1, False)
        assert parse_serve_fault(" engine-raise@3 ").engine == 0

    @pytest.mark.parametrize("bad", [
        "engine-raise", "nope@3", "engine-raise@x",
        "engine-raise@3:rank=1", "engine-raise@3:engine=x"])
    def test_parse_rejects_with_the_offending_spec(self, bad):
        with pytest.raises(ValueError, match="serve-fault"):
            parse_serve_fault(bad)

    def test_ge_semantics_fire_exactly_once(self):
        """A spec fires on the FIRST dispatch with seq >= at landing on
        its engine (exact-match would lose the race to the other pump
        thread forever), and never again."""
        inj = ServeFaultInjector([ServeFaultSpec("engine-raise", at=2,
                                                 engine=1)])
        inj.on_dispatch(1, 0)                   # below at: no-op
        inj.on_dispatch(0, 5)                   # wrong engine: no-op
        with pytest.raises(InjectedEngineFault):
            inj.on_dispatch(1, 5)               # >= at: fires
        inj.on_dispatch(1, 6)                   # spent: no-op
        assert inj.specs[0].fired

    def test_slow_returns_hang_raises(self):
        inj = ServeFaultInjector(
            [ServeFaultSpec("engine-slow", at=0),
             ServeFaultSpec("engine-hang", at=1)],
            slow_s=0.0, hang_s=0.0)
        inj.on_dispatch(0, 0)                   # brownout: succeeds
        with pytest.raises(InjectedEngineFault, match="hung"):
            inj.on_dispatch(0, 1)


class TestEngineHealth:
    def test_consecutive_failures_eject_then_backoff_readmits(self):
        router, now = health_router(
            [ServeFaultSpec("engine-raise", at=0),
             ServeFaultSpec("engine-raise", at=0)])
        rng = np.random.default_rng(20)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0])
        router.decide(obs, mask)        # fail 1 on engine 0 -> hedge
        router.decide(obs, mask)        # fail 2 -> EJECT -> hedge
        fs = router.fault_stats()
        assert fs == {"failures": 2, "ejections": 1, "readmissions": 0,
                      "retry_hedges": 2, "engines_ejected": 1}
        st = router.stats()
        assert st[0].ejected and not st[1].ejected
        assert st[0].consecutive_failures == 2
        router.decide(obs, mask)        # backoff not elapsed: no probe
        assert router.stats()[0].dispatches == 0
        now[0] += 1.0                   # past the 0.5s backoff
        router.decide(obs, mask)        # probe passes -> readmitted
        fs = router.fault_stats()
        assert fs["readmissions"] == 1 and fs["engines_ejected"] == 0
        st = router.stats()
        assert not st[0].ejected and st[0].consecutive_failures == 0
        assert st[0].dispatches >= 1    # taking traffic again
        assert router.per_engine_recompiles() == [0, 0]

    def test_single_transient_failure_never_ejects(self):
        router, _ = health_router([ServeFaultSpec("engine-raise", at=0)])
        rng = np.random.default_rng(21)
        obs, mask = make_batch(rng, 2)
        router.warmup(obs[0], mask[0])
        a, b = router.decide(obs, mask)         # hedged transparently
        assert np.asarray(a).shape[0] == 2 and b == 2
        router.decide(obs, mask)                # success resets streak
        fs = router.fault_stats()
        assert fs["failures"] == 1 and fs["ejections"] == 0
        assert all(s.consecutive_failures == 0 for s in router.stats())

    def test_slow_engine_is_not_ejected(self):
        """Brownout discipline: a slow dispatch SUCCEEDS — health
        tracking must not drain capacity over latency alone."""
        router, _ = health_router([ServeFaultSpec("engine-slow", at=0)],
                                  injector_kw={"slow_s": 0.0})
        rng = np.random.default_rng(22)
        obs, mask = make_batch(rng, 2)
        router.warmup(obs[0], mask[0])
        router.decide(obs, mask)
        fs = router.fault_stats()
        assert fs["failures"] == 0 and fs["retry_hedges"] == 0

    def test_failed_probe_doubles_backoff_until_fault_clears(self):
        router, now = health_router(
            [ServeFaultSpec("engine-raise", at=0),
             ServeFaultSpec("engine-raise", at=0),
             ServeFaultSpec("engine-raise", at=0)])
        rng = np.random.default_rng(23)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0])
        router.decide(obs, mask)        # fail 1
        router.decide(obs, mask)        # fail 2 -> eject, probe at +0.5
        now[0] += 0.6
        router.decide(obs, mask)        # probe fires spec 3 -> FAILS
        fs = router.fault_stats()
        assert fs["failures"] == 3 and fs["readmissions"] == 0
        assert router.stats()[0].ejected
        now[0] += 0.5                   # inside the DOUBLED (1s) backoff
        router.decide(obs, mask)
        assert router.fault_stats()["readmissions"] == 0
        now[0] += 1.0                   # past it; fault set exhausted
        router.decide(obs, mask)
        fs = router.fault_stats()
        assert fs["readmissions"] == 1 and fs["engines_ejected"] == 0

    def test_total_engine_loss_raises_then_recovers(self):
        router, now = health_router(
            [ServeFaultSpec("engine-raise", at=0, engine=0),
             ServeFaultSpec("engine-raise", at=0, engine=1)],
            eject_after=1)
        rng = np.random.default_rng(24)
        obs, mask = make_batch(rng, 2)
        router.warmup(obs[0], mask[0])
        with pytest.raises(InjectedEngineFault):
            router.decide(obs, mask)    # both engines eject, loudly
        fs = router.fault_stats()
        assert fs["engines_ejected"] == 2 and fs["retry_hedges"] == 1
        with pytest.raises(RuntimeError, match="no active healthy"):
            router.decide(obs, mask)    # nothing to serve with
        now[0] += 1.0                   # probes pass (faults spent)
        a, b = router.decide(obs, mask)
        assert b == 2
        assert router.fault_stats()["readmissions"] == 2

    def test_lifecycle_lands_on_the_event_bus(self):
        bus = _Bus()
        router, now = health_router(
            [ServeFaultSpec("engine-raise", at=0),
             ServeFaultSpec("engine-raise", at=0)], bus=bus)
        rng = np.random.default_rng(25)
        obs, mask = make_batch(rng, 2)
        router.warmup(obs[0], mask[0])
        router.decide(obs, mask)
        router.decide(obs, mask)
        now[0] += 1.0
        router.decide(obs, mask)
        kinds = bus.kinds()
        for want in ("serve_fault", "serve_retry", "engine_eject",
                     "engine_readmit"):
            assert want in kinds, kinds
        eject = dict(bus.events)["engine_eject"]
        assert eject["engine"] == 0
        assert eject["consecutive_failures"] == 2
        assert eject["error"] == "InjectedEngineFault"

    def test_hedged_batch_is_bit_identical_to_healthy_fleet(self):
        """The retry hedge must not change ANSWERS: a faulted fleet's
        output equals a healthy single engine's for the same rows."""
        router, _ = health_router([ServeFaultSpec("engine-raise", at=0)])
        single = InferenceEngine(linear_apply, make_params(),
                                 max_bucket=8, registry=Registry(),
                                 stall_gate=False)
        rng = np.random.default_rng(26)
        obs, mask = make_batch(rng, 4)
        router.warmup(obs[0], mask[0])
        single.warmup(obs[0], mask[0])
        a_r, b_r = router.decide(obs, mask)     # served via the hedge
        a_s, b_s = single.decide(obs, mask)
        assert b_r == b_s
        assert np.array_equal(np.asarray(a_r), np.asarray(a_s))


# ---- ISSUE 16: drain contract + exactly-once shed accounting ----------

class TestServerClosed:
    def test_close_refuses_submit_and_start_forever(self):
        server, t, reg = fake_server()
        rng = np.random.default_rng(30)
        fut = server.submit(*row(rng))
        server.close()
        assert isinstance(fut.result(timeout=10), ServeResult), \
            "close() must flush already-accepted work"
        assert server.closed
        with pytest.raises(ServerClosedError, match="closed"):
            server.submit(*row(rng))
        with pytest.raises(ServerClosedError):
            server.start()
        server.close()                          # idempotent

    def test_stop_is_not_terminal_close_is(self):
        server, t, reg = fake_server()
        rng = np.random.default_rng(31)
        server.start()
        server.stop()
        fut = server.submit(*row(rng))          # back in inline mode
        assert server.pump() == 1
        assert isinstance(fut.result(timeout=10), ServeResult)
        server.close()
        with pytest.raises(ServerClosedError):
            server.submit(*row(rng))

    def test_submit_refused_while_drain_in_flight(self):
        server, t, reg = fake_server()
        rng = np.random.default_rng(32)
        with server._wake:                      # freeze mid-drain state
            server._stopped = True
        with pytest.raises(ServerClosedError, match="drain in flight"):
            server.submit(*row(rng))
        with server._wake:
            server._stopped = False
        server.submit(*row(rng))
        assert server.pump() == 1

    def test_close_resolves_queued_futures_even_on_engine_failure(self):
        class DeadEngine:
            max_bucket = 8

            def bucket_for(self, n):
                return next_bucket(n, 8)

            def decide(self, obs, mask, stall=None):
                raise RuntimeError("device lost")

        reg = Registry()
        server = PolicyServer(DeadEngine(), registry=reg)
        rng = np.random.default_rng(33)
        futs = [server.submit(*row(rng)) for _ in range(3)]
        server.close()                          # must not hang or strand
        for f in futs:
            with pytest.raises(RuntimeError, match="device lost"):
                f.result(timeout=10)
        assert reg.counter("serve_dispatch_errors_total").value == 1


class TestShedAccounting:
    def test_cancelled_future_is_not_counted_as_shed(self):
        """The exactly-once invariant: a client that walked away
        (Future.cancel) is not double-counted by the expiry scan —
        ``serve_shed_total`` counts only rejections someone can SEE."""
        server, t, reg = fake_server()
        rng = np.random.default_rng(34)
        fut = server.submit(*row(rng), deadline_s=0.5)
        assert fut.cancel()
        t[0] += 1.0
        assert server.pump() == 0               # expiry scan drops it
        assert reg.counter("serve_shed_total").value == 0

    def test_multi_dispatcher_shed_counted_exactly_once(self):
        """4 dispatcher threads race the same expiry scans and admission
        path under real time; conservation must hold exactly:
        submitted == served + shed, and the counter == typed
        rejections observed (no double-count, no silent drop)."""
        import time as _time

        class SleepyEngine:
            max_bucket = 1

            def bucket_for(self, n):
                return next_bucket(n, 1)

            def decide(self, obs, mask, stall=None):
                _time.sleep(0.002)
                return np.asarray(obs), 1

        reg = Registry()
        server = PolicyServer(SleepyEngine(), registry=reg)
        rng = np.random.default_rng(35)
        o, m = row(rng)
        server.start(dispatchers=4)
        try:
            futs = [server.submit(o, m, deadline_s=0.004)
                    for _ in range(120)]
        finally:
            server.stop()                       # drains before stopping
        served = shed = 0
        for f in futs:
            try:
                assert isinstance(f.result(timeout=30), ServeResult)
                served += 1
            except DeadlineSheddedError:
                shed += 1
        assert served + shed == len(futs) == 120
        assert reg.counter("serve_shed_total").value == shed
        assert reg.counter("serve_requests_total").value == 120
        assert shed > 0, "the race was never exercised"


class TestDispatcherSurvival:
    def test_dispatcher_outlives_a_failed_dispatch(self):
        """A pump exception resolves ITS batch exceptionally and the
        dispatcher keeps serving — a dead dispatcher would strand every
        later request as a hung future."""
        class FlakyEngine:
            max_bucket = 1

            def __init__(self):
                self.fails_left = 1

            def bucket_for(self, n):
                return next_bucket(n, 1)

            def decide(self, obs, mask, stall=None):
                if self.fails_left:
                    self.fails_left -= 1
                    raise RuntimeError("transient XLA error")
                return np.asarray(obs), 1

        reg = Registry()
        server = PolicyServer(FlakyEngine(), registry=reg)
        rng = np.random.default_rng(36)
        server.start()
        try:
            f1 = server.submit(*row(rng))
            with pytest.raises(RuntimeError, match="transient"):
                f1.result(timeout=30)
            f2 = server.submit(*row(rng))       # same dispatcher thread
            assert isinstance(f2.result(timeout=30), ServeResult)
        finally:
            server.stop()
        assert reg.counter("serve_dispatch_errors_total").value == 1
