"""Property tests: the jit/vmap JAX simulator must reproduce the oracle
exactly (SURVEY.md §7 step 2 — "property-test against a slow Python oracle
sim written first as executable spec"). Integer-valued traces keep float32
virtual time exact, so comparisons are bit-meaningful."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu.sim import oracle as O
from rlgpuschedule_tpu.sim import core as C
from rlgpuschedule_tpu.traces import JobRecord, to_array_trace


def int_trace(rng, n_jobs, max_gpus, max_jobs=None):
    """Random integer-valued trace (exact in float32)."""
    jobs = []
    t = 0
    for i in range(n_jobs):
        t += int(rng.integers(0, 30))
        jobs.append(JobRecord(i, float(t), float(rng.integers(1, 50)),
                              int(rng.integers(1, max_gpus + 1)),
                              int(rng.integers(0, 3))))
    return to_array_trace(jobs, max_jobs=max_jobs)


class TestPlacementEquivalence:
    @pytest.mark.parametrize("seed", range(5))
    def test_pack_matches_oracle(self, seed):
        rng = np.random.default_rng(seed)
        for _ in range(50):
            free = rng.integers(0, 9, size=6).astype(np.int32)
            demand = int(rng.integers(1, 20))
            want = O.pack_placement(free, demand)
            got, feasible = C.pack_placement(jnp.asarray(free), jnp.asarray(demand))
            if want is None:
                assert not bool(feasible)
            else:
                assert bool(feasible)
                np.testing.assert_array_equal(np.asarray(got), want)

    @pytest.mark.parametrize("seed", range(5))
    def test_spread_matches_oracle(self, seed):
        rng = np.random.default_rng(seed)
        for _ in range(50):
            free = rng.integers(0, 9, size=6).astype(np.int32)
            demand = int(rng.integers(1, 20))
            want = O.spread_placement(free, demand)
            got, feasible = C.spread_placement(jnp.asarray(free),
                                               jnp.asarray(demand), 8)
            if want is None:
                assert not bool(feasible)
            else:
                assert bool(feasible)
                np.testing.assert_array_equal(np.asarray(got), want)


class TestQueueAndMask:
    def test_pending_queue_order_and_padding(self):
        trace = to_array_trace([JobRecord(i, float(i), 5.0, 1) for i in range(6)],
                               max_jobs=8)
        params = C.SimParams(n_nodes=1, gpus_per_node=2, max_jobs=8, queue_len=4)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        state = C.advance_to(state, tr, jnp.float32(3.0))  # jobs 0..3 pending
        q = np.asarray(C.pending_queue(params, state))
        np.testing.assert_array_equal(q, [0, 1, 2, 3])
        # place job 0 → queue shifts, tail pads with next pending
        state, ok = C.try_place(params, state, tr, jnp.int32(0), jnp.int32(0))
        assert bool(ok)
        q = np.asarray(C.pending_queue(params, state))
        np.testing.assert_array_equal(q, [1, 2, 3, -1])

    def test_action_mask(self):
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 2), JobRecord(1, 0.0, 5.0, 4)],
                               max_jobs=4)
        params = C.SimParams(n_nodes=1, gpus_per_node=4, max_jobs=4,
                             queue_len=3, n_placements=2)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        mask = np.asarray(C.action_mask(params, state, tr))
        # both jobs feasible on empty cluster; slot 2 empty; noop valid
        np.testing.assert_array_equal(mask, [1, 1, 1, 1, 0, 0, 1])
        state, ok = C.try_place(params, state, tr, jnp.int32(0), jnp.int32(0))
        mask = np.asarray(C.action_mask(params, state, tr))
        # 2 free left: job 1 (4 gpus) infeasible now
        np.testing.assert_array_equal(mask, [0, 0, 0, 0, 0, 0, 1])


def run_pair(trace, n_nodes, gpus_per_node, actions, queue_len,
             n_placements=2, preempt_len=0):
    """Drive oracle and JAX sim with the same action sequence; compare
    trajectories after every step."""
    params = C.SimParams(n_nodes=n_nodes, gpus_per_node=gpus_per_node,
                         max_jobs=trace.max_jobs, queue_len=queue_len,
                         n_placements=n_placements, preempt_len=preempt_len)
    osim = O.OracleSim(trace, n_nodes, gpus_per_node)
    tr = C.Trace.from_array_trace(trace)
    jstate = C.init_state(params, tr)
    step = jax.jit(lambda s, a: C.rl_step(params, s, tr, a))
    for i, a in enumerate(actions):
        oinfo = osim.rl_step(int(a), queue_len, n_placements, preempt_len)
        jstate, jinfo = step(jstate, jnp.int32(a))
        s = C.np_state(jstate)
        ctx = f"step {i} action {a}"
        np.testing.assert_allclose(s.clock, osim.clock, atol=1e-3, err_msg=ctx)
        np.testing.assert_array_equal(s.status, osim.status, err_msg=ctx)
        np.testing.assert_allclose(s.remaining, osim.remaining, atol=1e-3,
                                   err_msg=ctx)
        np.testing.assert_array_equal(s.alloc, osim.alloc, err_msg=ctx)
        np.testing.assert_array_equal(s.free, osim.free, err_msg=ctx)
        assert bool(jinfo.placed) == oinfo["placed"], ctx
        assert bool(jinfo.preempted) == oinfo["preempted"], ctx
        assert bool(jinfo.first_placed) == oinfo["first_placed"], ctx
        np.testing.assert_allclose(float(jinfo.dt), oinfo["dt"], atol=1e-3,
                                   err_msg=ctx)
        assert int(jinfo.in_system_before) == oinfo["in_system_before"], ctx
        assert bool(jinfo.done) == oinfo["done"], ctx
        if oinfo["done"]:
            break
    return osim, jstate, params


class TestRLStepEquivalence:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_actions_match_oracle(self, seed):
        rng = np.random.default_rng(seed)
        trace = int_trace(rng, n_jobs=20, max_gpus=4, max_jobs=24)
        queue_len, n_placements = 5, 2
        actions = rng.integers(0, queue_len * n_placements + 1, size=400)
        osim, jstate, params = run_pair(trace, n_nodes=3, gpus_per_node=2,
                                        actions=actions, queue_len=queue_len)

    def test_greedy_head_completes_trace_and_matches_jct(self):
        rng = np.random.default_rng(42)
        trace = int_trace(rng, n_jobs=15, max_gpus=4, max_jobs=16)
        # always try queue head with pack; falls through to time advance
        actions = [0] * 600
        osim, jstate, params = run_pair(trace, 2, 4, actions, queue_len=4)
        assert osim.done()
        tr = C.Trace.from_array_trace(trace)
        stats = C.jct_stats(jstate, tr)
        np.testing.assert_allclose(float(stats["avg_jct"]), osim.avg_jct(),
                                   rtol=1e-5)
        assert int(stats["n_done"]) == 15

    @pytest.mark.parametrize("seed", range(8))
    def test_random_actions_with_preemption_match_oracle(self, seed):
        """Bit-identical trajectories when the action space includes the
        preempt block (VERDICT r1 missing #5). The overloaded trace keeps
        many jobs running+pending so preempt actions actually fire."""
        rng = np.random.default_rng(100 + seed)
        trace = int_trace(rng, n_jobs=20, max_gpus=4, max_jobs=24)
        queue_len, n_placements, preempt_len = 4, 2, 3
        n_actions = queue_len * n_placements + preempt_len + 1
        actions = rng.integers(0, n_actions, size=500)
        run_pair(trace, n_nodes=3, gpus_per_node=2, actions=actions,
                 queue_len=queue_len, preempt_len=preempt_len)

    def test_running_queue_order_and_mask(self):
        """Slot 0 = most attained GPU-service; preempt mask tracks slot
        occupancy; preempting returns the job to the pending queue."""
        trace = to_array_trace(
            [JobRecord(0, 0.0, 50.0, 1), JobRecord(1, 0.0, 50.0, 2)],
            max_jobs=4)
        params = C.SimParams(1, 4, max_jobs=4, queue_len=2, n_placements=1,
                             preempt_len=2)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        state, _ = C.try_place(params, state, tr, jnp.int32(0), jnp.int32(0))
        state, _ = C.try_place(params, state, tr, jnp.int32(1), jnp.int32(0))
        state = C.advance_to(state, tr, jnp.float32(10.0))
        # attained: job0 = 10·1 = 10, job1 = 10·2 = 20 → slot 0 is job 1
        rq = np.asarray(C.running_queue(params, state, tr))
        np.testing.assert_array_equal(rq, [1, 0])
        mask = np.asarray(C.action_mask(params, state, tr))
        # layout [K=2 slots][R=2 preempt][noop]: queue empty, both running
        np.testing.assert_array_equal(mask, [0, 0, 1, 1, 1])
        # preempt slot 0 → job 1 back to PENDING with service preserved
        state, info = C.rl_step(params, state, tr,
                                jnp.int32(params.queue_len))
        assert bool(info.preempted) and not bool(info.placed)
        assert float(info.dt) == 0.0
        s = C.np_state(state)
        assert s.status[1] == O.PENDING and s.remaining[1] == 40.0
        assert s.free.sum() == 3

    def test_replace_after_preempt_is_not_first(self):
        """A preempt→re-place cycle must not farm place_bonus: the
        re-placement reports first_placed=False (shaping potential
        Φ = bonus·#{ever-started} never pays twice)."""
        trace = to_array_trace([JobRecord(0, 0.0, 50.0, 2)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2, n_placements=1,
                             preempt_len=1)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        state, info = C.rl_step(params, state, tr, jnp.int32(0))  # place
        assert bool(info.first_placed)
        state, info = C.rl_step(params, state, tr, jnp.int32(2))  # preempt
        assert bool(info.preempted)
        state, info = C.rl_step(params, state, tr, jnp.int32(0))  # re-place
        assert bool(info.placed) and not bool(info.first_placed)

    def test_preempt_len_zero_mask_unchanged(self):
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 1)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        assert params.n_actions == 3
        assert C.action_mask(params, state, tr).shape == (3,)

    def test_force_place_on_empty_event_horizon(self):
        # single job, agent always noops: the sim must force-place to
        # guarantee progress (oracle docstring semantics).
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 1)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        noop = jnp.int32(params.n_actions - 1)
        state, info = C.rl_step(params, state, tr, noop)   # force-place
        assert bool(info.placed) and float(info.dt) == 0.0
        state, info = C.rl_step(params, state, tr, noop)   # advance to done
        assert bool(info.done) and float(state.clock) == 5.0

    def test_preempt(self):
        trace = to_array_trace([JobRecord(0, 0.0, 10.0, 2)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        state, ok = C.try_place(params, state, tr, jnp.int32(0), jnp.int32(0))
        state = C.advance_to(state, tr, jnp.float32(4.0))
        state, ok = C.preempt(state, jnp.int32(0), params.max_jobs)
        assert bool(ok)
        s = C.np_state(state)
        assert s.status[0] == O.PENDING and s.free.sum() == 2
        assert s.remaining[0] == 6.0
        att = np.asarray(C.attained_service(state, tr))
        assert att[0] == 8.0  # 4s × 2 gpus, matches oracle.attained_service


class TestValidateTrace:
    def test_over_capacity_raises_on_host(self):
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 8)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2)
        with pytest.raises(ValueError, match="more than the cluster"):
            C.Trace.from_array_trace(trace, params)

    def test_clamp(self):
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 8)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2)
        clamped = C.validate_trace(params, trace, clamp=True)
        assert clamped.gpus[0] == 2

    def test_over_capacity_step_does_not_lie(self):
        # if an unvalidated over-capacity job sneaks in, rl_step must not
        # report placed=True (regression: forced-place success flag)
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 8)], max_jobs=2)
        params = C.SimParams(1, 2, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        state, info = C.rl_step(params, state, tr, jnp.int32(0))
        assert not bool(info.placed) and not bool(info.done)


class TestVmap:
    def test_vmapped_step_matches_single(self):
        rng = np.random.default_rng(0)
        traces = [int_trace(np.random.default_rng(s), 10, 2, max_jobs=12)
                  for s in range(4)]
        params = C.SimParams(2, 2, max_jobs=12, queue_len=4, n_placements=1)
        trs = [C.Trace.from_array_trace(t) for t in traces]
        batched = jax.tree.map(lambda *xs: jnp.stack(xs), *trs)
        states = jax.vmap(lambda tr: C.init_state(params, tr))(batched)
        actions = jnp.asarray(rng.integers(0, params.n_actions, size=(20, 4)),
                              jnp.int32)
        vstep = jax.jit(jax.vmap(lambda s, tr, a: C.rl_step(params, s, tr, a)))
        sstep = jax.jit(lambda s, tr, a: C.rl_step(params, s, tr, a))
        single_states = [jax.tree.map(lambda x: x[i], states) for i in range(4)]
        for t in range(20):
            states, infos = vstep(states, batched, actions[t])
            for i in range(4):
                single_states[i], _ = sstep(single_states[i], trs[i], actions[t][i])
                got = jax.tree.map(lambda x: np.asarray(x[i]), states)
                want = C.np_state(single_states[i])
                for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
                    np.testing.assert_allclose(g, w, atol=1e-4)


class TestFloat32Tolerance:
    def test_completion_fires_at_large_clock(self):
        """Regression: a job placed at a large f32 clock must still complete.

        With an absolute completion epsilon, ``remaining - dt`` can round to
        a small positive value at clocks where f32 spacing > epsilon, while
        next_event_time rounds to the current clock — advancing dt=0 forever.
        """
        # chosen so f32(1288.741577… + 1720.452392…) rounds DOWN a half-ulp:
        # the advance target then undershoots the completion time and the old
        # absolute-epsilon test left remaining ≈ 1.2e-4 > eps forever
        trace = to_array_trace([
            JobRecord(0, 0.0, 1288.7415771484375, 1),
            JobRecord(1, 0.1, 1720.4523925781250, 1),
        ])
        params = C.SimParams(1, 1, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        state = C.init_state(params, tr)
        step = jax.jit(lambda s, a: C.rl_step(params, s, tr, a))
        # run jobs back-to-back: place head, advance, place, advance
        for _ in range(8):
            state, info = step(state, jnp.int32(0))
            if bool(info.done):
                break
        assert bool(C.all_done(state, tr)), C.np_state(state)
