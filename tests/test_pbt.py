"""Population training + PBT controller tests (SURVEY.md §2 "PBT
controller", §3.5; §4 "Distributed without a real cluster" — pop-sharded
member stacks run on the 8-device virtual CPU mesh)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlgpuschedule_tpu.algos import (PPOConfig, init_carry, make_ppo_step,
                                     make_train_state)
from rlgpuschedule_tpu.algos.ppo import make_optimizer
from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
from rlgpuschedule_tpu.experiment import (PopulationExperiment,
                                          build_env_params,
                                          load_source_trace,
                                          make_env_windows)
from rlgpuschedule_tpu.env import stack_traces
from rlgpuschedule_tpu.models import make_policy
from rlgpuschedule_tpu.parallel import (HParams, PBTConfig, PBTController,
                                        exploit_explore, gather_members,
                                        init_member, make_member_step,
                                        make_mesh, sample_hparams)

TINY = dataclasses.replace(
    PPO_MLP_SYNTH64, n_nodes=2, gpus_per_node=4, n_envs=4, window_jobs=16,
    horizon=64, ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))


def _member_fixture(cfg=TINY):
    env_params = build_env_params(cfg)
    source = load_source_trace(cfg)
    windows = make_env_windows(cfg, source)
    traces = stack_traces(windows, env_params)
    net = make_policy(cfg.obs_kind, env_params.n_actions,
                      n_cluster_nodes=cfg.n_nodes, queue_len=cfg.queue_len,
                      n_placements=cfg.n_placements)
    apply_fn = lambda p, obs, mask: net.apply(p, obs, mask)
    carry = init_carry(env_params, traces, jax.random.PRNGKey(1))
    return env_params, traces, net, apply_fn, carry


class TestHParams:
    def test_sample_deterministic_and_bounded(self):
        a = sample_hparams(PPOConfig(), 8, seed=3)
        b = sample_hparams(PPOConfig(), 8, seed=3)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert a.lr.shape == (8,)
        assert (np.asarray(a.clip_eps) >= 0.05).all()
        assert (np.asarray(a.clip_eps) <= 0.5).all()
        assert (np.asarray(a.lr) > 0).all()

    def test_spread_covers_range(self):
        hp = sample_hparams(PPOConfig(lr=3e-4), 64, seed=0, spread=3.0)
        lr = np.asarray(hp.lr)
        assert lr.min() < 3e-4 < lr.max()


class TestMemberStep:
    def test_matches_single_run_ppo_at_config_hparams(self):
        """A member stepped with hp == config values must reproduce the
        plain PPO train step (optax.adam == scale_by_adam + scale(-lr))."""
        cfg = TINY
        env_params, traces, net, apply_fn, carry = _member_fixture(cfg)
        key = jax.random.PRNGKey(7)
        init_key = jax.random.PRNGKey(8)

        ts = make_train_state(net, init_key, carry.obs[:1], carry.mask[:1],
                              make_optimizer(cfg.ppo))
        ppo_step = jax.jit(make_ppo_step(apply_fn, env_params, cfg.ppo))
        ts2, _, _ = ppo_step(ts, carry, traces, key)

        member = init_member(net, init_key, carry.obs[:1], carry.mask[:1],
                             cfg.ppo)
        hp = HParams(lr=jnp.float32(cfg.ppo.lr),
                     ent_coef=jnp.float32(cfg.ppo.ent_coef),
                     clip_eps=jnp.float32(cfg.ppo.clip_eps))
        member_step = jax.jit(make_member_step(apply_fn, env_params, cfg.ppo))
        m2, _, _ = member_step(member, carry, traces, key, hp)

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5,
                                                    atol=1e-6),
            ts2.params, m2.params)

    def test_hparams_change_updates_without_recompile(self):
        cfg = TINY
        env_params, traces, net, apply_fn, carry = _member_fixture(cfg)
        member = init_member(net, jax.random.PRNGKey(0), carry.obs[:1],
                             carry.mask[:1], cfg.ppo)
        step = jax.jit(make_member_step(apply_fn, env_params, cfg.ppo))
        key = jax.random.PRNGKey(1)
        hp_small = HParams(jnp.float32(1e-5), jnp.float32(0.01),
                           jnp.float32(0.2))
        hp_big = HParams(jnp.float32(1e-2), jnp.float32(0.01),
                         jnp.float32(0.2))
        a, _, _ = step(member, carry, traces, key, hp_small)
        b, _, _ = step(member, carry, traces, key, hp_big)
        diff_small = jax.tree_util.tree_reduce(
            lambda acc, x: acc + float(jnp.abs(x).sum()),
            jax.tree.map(lambda x, y: x - y, a.params, member.params), 0.0)
        diff_big = jax.tree_util.tree_reduce(
            lambda acc, x: acc + float(jnp.abs(x).sum()),
            jax.tree.map(lambda x, y: x - y, b.params, member.params), 0.0)
        assert diff_big > diff_small * 10


class TestExploitExplore:
    def _hp(self, n):
        return HParams(lr=jnp.full((n,), 3e-4), ent_coef=jnp.full((n,), 0.01),
                       clip_eps=jnp.full((n,), 0.2))

    def test_losers_copy_winners(self):
        rng = np.random.default_rng(0)
        fitness = np.array([0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0])
        d = exploit_explore(rng, fitness, self._hp(8),
                            PBTConfig(exploit_frac=0.25))
        # bottom 2 (members 0,1) copy from top 2 (members 6,7)
        assert set(np.where(d.exploited)[0]) == {0, 1}
        assert all(s in (6, 7) for s in d.src[:2])
        np.testing.assert_array_equal(d.src[2:], np.arange(2, 8))

    def test_explore_perturbs_only_exploited_within_bounds(self):
        rng = np.random.default_rng(1)
        fitness = np.arange(8.0)
        hp = self._hp(8)
        d = exploit_explore(rng, fitness, hp, PBTConfig())
        lr = np.asarray(d.hparams.lr)
        # survivors keep their hparams (up to f32 round-trip)
        np.testing.assert_allclose(lr[~d.exploited], 3e-4, rtol=1e-6)
        # exploited get parent value × {0.8, 1.25}
        for i in np.where(d.exploited)[0]:
            assert lr[i] == pytest.approx(3e-4 * 0.8, rel=1e-5) or \
                   lr[i] == pytest.approx(3e-4 * 1.25, rel=1e-5)

    def test_gather_members_copies_weights(self):
        tree = {"w": jnp.arange(8.0), "b": jnp.arange(8.0) * 10}
        src = np.array([7, 1, 2, 3, 4, 5, 6, 7])
        out = gather_members(tree, src)
        assert float(out["w"][0]) == 7.0
        assert float(out["b"][0]) == 70.0
        assert float(out["w"][1]) == 1.0

    def test_controller_cadence(self):
        ctrl = PBTController(4, PBTConfig(ready_iters=3))
        hp = self._hp(4)
        states = {"w": jnp.arange(4.0)}
        for i in range(3):
            ctrl.record(np.arange(4.0))
            out = ctrl.maybe_update(i, states, hp)
            if i < 2:
                assert out is None
        assert out is not None
        _, _, decision = out
        assert len(ctrl.history) == 1
        # fitness window reset after the update
        assert ctrl._fitness_n == 0


class TestPopulationExperiment:
    def test_end_to_end_with_pbt_on_mesh(self):
        cfg = dataclasses.replace(
            TINY, iterations=5,
            ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))
        mesh = make_mesh(n_pop=2)          # (2 pop, 4 data) over 8 cpu devs
        exp = PopulationExperiment.build(
            cfg, n_pop=4, mesh=mesh,
            pbt_cfg=PBTConfig(ready_iters=2, seed=0))
        out = exp.run(iterations=5, log_every=1)
        assert out["pbt_events"] >= 1
        assert len(out["final_fitness"]) == 4
        assert all(np.isfinite(out["final_fitness"]))
        for h in out["history"]:
            # per-member metrics are flattened to scalar columns (CSV-safe)
            member_vals = [h[f"mean_reward_{p}"] for p in range(4)]
            assert all(np.isfinite(member_vals))
            assert all(isinstance(v, float) for v in member_vals)
            assert np.isfinite(h["mean_reward_mean"])

    def test_single_device_path(self):
        cfg = dataclasses.replace(TINY, iterations=2)
        exp = PopulationExperiment.build(cfg, n_pop=2, mesh=None)
        out = exp.run(iterations=2)
        assert out["env_steps"] == 2 * 8 * 4 * 2  # iters*T*E*P

    def test_resume_reproduces_exploit_decisions_bitforbit(self, tmp_path):
        """Interrupted+resumed PBT == uninterrupted PBT, including the
        controller's RNG draws, fitness window, and exploit decisions
        (VERDICT r2 weak #2 / next-round #5 — the flat path's exact-resume
        contract, extended to populations). ready_iters=2 with a 3-iter
        first leg leaves ONE PENDING fitness record in the window at the
        checkpoint: exactly the state round 2 dropped."""
        from rlgpuschedule_tpu.checkpoint import Checkpointer
        build = lambda: PopulationExperiment.build(
            TINY, n_pop=4, mesh=None, pbt_cfg=PBTConfig(ready_iters=2,
                                                        seed=3))
        # the TRUE uninterrupted reference: one run() call, 5 iterations
        # (not a second run() call, which would share any local-index
        # artifact with the resumed run and mask it). ready_iters=2 over
        # 5 iters = exploit rounds at i=1 and i=3; the checkpoint at i=3
        # carries 1 pending window record into the resumed leg.
        exp = build()
        exp.run(iterations=5)
        final = jax.tree.map(np.asarray, exp.states.params)

        exp1 = build()
        exp1.run(iterations=3)
        with Checkpointer(str(tmp_path / "ck")) as ck:
            exp1.save_checkpoint(ck)
            ck.wait()
            exp2 = build()
            meta = exp2.restore_checkpoint(ck)
        assert meta["pbt_events"] == len(exp2.controller.history)
        exp2.run(iterations=2)      # resumed continuation
        final2 = jax.tree.map(np.asarray, exp2.states.params)
        for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(final2)):
            np.testing.assert_array_equal(a, b)
        # exploit decisions identical, event for event
        assert len(exp.controller.history) == len(exp2.controller.history)
        for d1, d2 in zip(exp.controller.history, exp2.controller.history):
            np.testing.assert_array_equal(d1.src, d2.src)
            np.testing.assert_array_equal(d1.exploited, d2.exploited)
            for a, b in zip(jax.tree.leaves(d1.hparams._asdict()),
                            jax.tree.leaves(d2.hparams._asdict())):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and the final hparams agree
        for a, b in zip(jax.tree.leaves(exp.hparams._asdict()),
                        jax.tree.leaves(exp2.hparams._asdict())):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
