"""Partition-rule sharding engine (parallel.sharding): rule matching,
per-family coverage, and the two bit-identity acceptance gates — the
rule-sharded train step vs the hand-wired dp path, and the PBT
population as a mesh axis vs the per-member Python loop — both on
forced-CPU virtual devices with a zero-post-warmup-recompile
CompileCounter gate."""

import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from flax.training.train_state import TrainState

from rlgpuschedule_tpu.algos import PPOConfig, init_carry, make_ppo_step
from rlgpuschedule_tpu.algos.ppo import make_optimizer
from rlgpuschedule_tpu.analysis.sentinels import CompileCounter
from rlgpuschedule_tpu.env import EnvParams, stack_traces
from rlgpuschedule_tpu.models import HierActorCritic, make_policy
from rlgpuschedule_tpu.parallel import (DATA_AXIS, MODEL_AXIS, POP_AXIS,
                                        make_unified_mesh)
from rlgpuschedule_tpu.parallel import sharding as shardlib
from rlgpuschedule_tpu.parallel.dp import carry_sharding_prefix, put_carry
from rlgpuschedule_tpu.parallel.mesh import env_sharded, replicated
from rlgpuschedule_tpu.sim.core import SimParams
from rlgpuschedule_tpu.traces import gen_poisson_trace


def build(n_envs=8, dtype=jnp.float32):
    env_params = EnvParams(sim=SimParams(2, 4, max_jobs=16, queue_len=4),
                           obs_kind="flat", horizon=64, time_scale=100.0,
                           reward_scale=1000.0)
    windows = [gen_poisson_trace(0.05, 12, seed=s, max_jobs=16,
                                 mean_duration=60.0, gpu_sizes=(1, 2),
                                 gpu_probs=(0.7, 0.3))
               for s in range(n_envs)]
    traces = stack_traces(windows, env_params)
    net = make_policy("flat", env_params.n_actions, dtype=dtype)
    apply_fn = lambda p, o, m: net.apply(p, o, m)
    cfg = PPOConfig(n_steps=8, n_epochs=2, n_minibatches=2)
    key = jax.random.PRNGKey(0)
    carry = init_carry(env_params, traces, key)
    params = net.init(key, carry.obs[:1], carry.mask[:1])
    state = TrainState.create(apply_fn=net.apply, params=params,
                              tx=make_optimizer(cfg))
    step = make_ppo_step(apply_fn, env_params, cfg)
    return env_params, traces, state, carry, step


class TestRuleMatching:
    def test_scalar_and_size1_short_circuit(self):
        specs = shardlib.match_partition_rules(
            [], {"step": jnp.int32(0), "ema": jnp.ones((1,))})
        assert specs["step"] == P() and specs["ema"] == P()

    def test_first_match_wins(self):
        rules = [(r"kernel$", P(None, MODEL_AXIS)), (r".*", P())]
        got = shardlib.match_rule(rules, "params/Dense_0/kernel")
        assert got == P(None, MODEL_AXIS)
        # reversed order: the catch-all shadows the kernel rule
        got = shardlib.match_rule(list(reversed(rules)),
                                  "params/Dense_0/kernel")
        assert got == P()

    def test_unmatched_leaf_is_a_hard_error(self):
        with pytest.raises(ValueError, match="Partition rule not found"):
            shardlib.match_partition_rules(
                [(r"kernel$", P())], {"weird": jnp.ones((4, 4))})

    def test_rule_table_hash_is_stable_and_order_sensitive(self):
        h1 = shardlib.rule_table_hash(shardlib.FLAT_RULES)
        assert h1 == shardlib.rule_table_hash(list(shardlib.FLAT_RULES))
        h2 = shardlib.rule_table_hash(list(reversed(shardlib.FLAT_RULES)))
        assert h1 != h2

    def test_prune_spec_drops_axes_the_mesh_lacks(self):
        # a legacy pop x data mesh (no model axis) must not hard-error on
        # the unified tables' model-axis specs — those dims replicate
        import numpy as _np
        from jax.sharding import Mesh as JMesh
        legacy = JMesh(_np.array(jax.devices()[:1]).reshape(1, 1),
                       (POP_AXIS, DATA_AXIS))
        assert shardlib.prune_spec(
            P(POP_AXIS, None, MODEL_AXIS), legacy) == P(POP_AXIS)
        assert shardlib.prune_spec(
            P((POP_AXIS, MODEL_AXIS), DATA_AXIS), legacy) == \
            P(POP_AXIS, DATA_AXIS)
        sh = shardlib.tree_shardings(
            {"dense/kernel": jnp.ones((4, 4))},
            [(r"kernel$", P(DATA_AXIS, MODEL_AXIS))], legacy)
        assert sh["dense/kernel"].spec == P(DATA_AXIS)


class TestFamilyCoverage:
    """Every family's params are fully covered BEFORE the catch-all,
    and at least one kernel per family actually lands on ``model``."""

    def _covered(self, rules, params):
        specs = shardlib.match_partition_rules(rules[:-1], params)
        flat = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        assert any(MODEL_AXIS in (s or ()) for spec in flat
                   for s in spec), "no leaf sharded over model"

    def test_flat(self):
        net = make_policy("flat", 5, dtype=jnp.float32)
        params = net.init(jax.random.PRNGKey(0), jnp.ones((1, 24)),
                          jnp.ones((1, 5), bool))
        self._covered(shardlib.FLAT_RULES, params)

    def test_grid(self):
        net = make_policy("grid", 5, dtype=jnp.float32)
        params = net.init(jax.random.PRNGKey(0), jnp.ones((1, 8, 8, 3)),
                          jnp.ones((1, 5), bool))
        self._covered(shardlib.GRID_RULES, params)
        specs = shardlib.match_partition_rules(shardlib.GRID_RULES, params)
        conv = [s for n, s in zip(shardlib.tree_leaf_names(params),
                                  jax.tree.leaves(
                                      specs,
                                      is_leaf=lambda x: isinstance(x, P)))
                if "Conv_0/kernel" in n]
        assert conv == [P(None, None, None, MODEL_AXIS)]

    def test_graph(self):
        net = make_policy("graph", 5, n_cluster_nodes=2, queue_len=4,
                          dtype=jnp.float32)
        V = 2 + 4 + 1
        params = net.init(jax.random.PRNGKey(0), jnp.ones((1, V, 6)),
                          jnp.ones((V, V)), jnp.ones((1, 5), bool))
        self._covered(shardlib.GRAPH_RULES, params)

    def test_hier(self):
        net = HierActorCritic(n_top_actions=5, n_pod_actions=7,
                              dtype=jnp.float32)
        obs = {"top": jnp.ones((1, 16)), "pods": jnp.ones((1, 4, 16))}
        mask = {"top": jnp.ones((1, 5), bool),
                "pods": jnp.ones((1, 4, 7), bool)}
        params = net.init(jax.random.PRNGKey(0), obs, mask)
        self._covered(shardlib.HIER_RULES, params)

    def test_opt_state_shards_with_the_same_table(self):
        # Adam moments mirror param paths, so the SAME rules cover the
        # full TrainState — the zero-extra-configuration property the
        # re.search matching exists for
        _, _, state, _, _ = build(n_envs=2)
        shardlib.match_partition_rules(shardlib.FLAT_RULES, state)


class TestBitIdentityVsDP:
    """Rule-resolved in/out_shardings + bind_mesh constraints vs the
    hand-wired dp.shard_train path: same 2-device mesh, same seeds —
    params must be BITWISE identical, and the rule path must not
    recompile after warmup."""

    def _run_dp(self, iters):
        from rlgpuschedule_tpu.parallel.dp import shard_train
        _, traces, state, carry, step = build()
        mesh = make_unified_mesh(devices=jax.devices()[:2])
        jstep, state, carry, traces = shard_train(mesh, step, state,
                                                  carry, traces)
        for i in range(iters):
            state, carry, m = jstep(state, carry, traces,
                                    jax.random.PRNGKey(i))
        return state, m

    def _run_rules(self, iters):
        _, traces, state, carry, step = build()
        mesh = make_unified_mesh(devices=jax.devices()[:2])
        rules = shardlib.FLAT_RULES
        state_sh = shardlib.tree_shardings(state, rules, mesh)
        env, rep = env_sharded(mesh), replicated(mesh)
        carry_sh = carry_sharding_prefix(mesh)
        jstep = jax.jit(shardlib.bind_mesh(step, mesh),
                        in_shardings=(state_sh, carry_sh, env, rep),
                        out_shardings=(state_sh, carry_sh, rep),
                        donate_argnums=(0, 1))
        state = shardlib.put_tree(state, state_sh)
        carry = put_carry(mesh, carry)
        traces = shardlib.put_global(traces, env)
        counted = 0
        for i in range(iters):
            if i == 1:
                cc = CompileCounter()
                cc.__enter__()
                counted = 1
            state, carry, m = jstep(state, carry, traces,
                                    jax.random.PRNGKey(i))
        if counted:
            jax.block_until_ready(jax.tree.leaves(state.params))
            cc.__exit__(None, None, None)
            assert cc.total == 0, (
                f"rule-sharded step recompiled after warmup: "
                f"{cc.traces} traces, {cc.backend_compiles} compiles")
        return state, m

    def test_rule_path_matches_dp_bitwise(self):
        assert len(jax.devices()) >= 2
        dstate, _ = self._run_dp(3)
        rstate, _ = self._run_rules(3)
        for name, d, r in zip(shardlib.tree_leaf_names(dstate.params),
                              jax.tree.leaves(jax.device_get(
                                  dstate.params)),
                              jax.tree.leaves(jax.device_get(
                                  rstate.params))):
            assert np.array_equal(np.asarray(d), np.asarray(r)), (
                f"param {name} diverged between dp and rule paths")


class TestBitIdentityPBT:
    """The population as a ``pop`` mesh axis (ONE dispatch) vs a Python
    loop of per-member steps: member params identical to last-ulp
    tolerance, zero post-warmup recompiles."""

    N_POP = 2
    ITERS = 2

    def _init_population(self):
        from rlgpuschedule_tpu.parallel.population import (
            init_member, sample_hparams, stack_members)
        env_params, traces, _, _, _ = build(n_envs=4)
        net = make_policy("flat", env_params.n_actions, dtype=jnp.float32)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = PPOConfig(n_steps=8, n_epochs=2, n_minibatches=2)
        members, carries = [], []
        for i in range(self.N_POP):
            key = jax.random.PRNGKey(100 + i)
            carry = init_carry(env_params, traces, key)
            members.append(init_member(net, key, carry.obs[:1],
                                       carry.mask[:1], cfg))
            carries.append(carry)
        hp = sample_hparams(cfg, self.N_POP, seed=0)
        keys = jnp.stack([jax.random.PRNGKey(500 + i)
                          for i in range(self.ITERS)])
        return (env_params, traces, apply_fn, cfg, members, carries, hp,
                keys, stack_members)

    def test_mesh_axis_matches_python_loop_bitwise(self):
        from rlgpuschedule_tpu.parallel.population import (
            jit_population_step, make_member_step, make_population_step)
        (env_params, traces, apply_fn, cfg, members, carries, hp, keys,
         stack_members) = self._init_population()

        # --- reference: per-member jitted step in a Python loop
        member = jax.jit(make_member_step(apply_fn, env_params, cfg))
        loop_states = [m for m in members]
        loop_carries = [c for c in carries]
        for t in range(self.ITERS):
            mkeys = jax.random.split(keys[t], self.N_POP)
            for i in range(self.N_POP):
                hp_i = jax.tree.map(lambda x: x[i], hp)
                loop_states[i], loop_carries[i], _ = member(
                    loop_states[i], loop_carries[i], traces, mkeys[i],
                    hp_i)

        # --- mesh path: stacked members, pop axis, one dispatch/iter
        mesh = make_unified_mesh(n_pop=self.N_POP,
                                 devices=jax.devices()[:self.N_POP])
        states = stack_members(members)
        carry = stack_members(carries)
        pop_step = make_population_step(apply_fn, env_params, cfg)
        jstep = jit_population_step(mesh, pop_step, states=states,
                                    rules=shardlib.FLAT_RULES)
        cc = None
        for t in range(self.ITERS):
            mkeys = jax.random.split(keys[t], self.N_POP)
            if t == 1:
                cc = CompileCounter()
                cc.__enter__()
            states, carry, _ = jstep(states, carry, traces, mkeys, hp)
        jax.block_until_ready(jax.tree.leaves(states.params))
        if cc is not None:
            cc.__exit__(None, None, None)
            assert cc.total == 0, (
                f"population step recompiled after warmup: {cc.traces} "
                f"traces, {cc.backend_compiles} compiles")

        # last-ulp tolerance, not bitwise: XLA:CPU emits different dot
        # kernels for the batched (vmapped) and unbatched member shapes,
        # so loop/vmap/partitioned-vmap all differ in the final float32
        # bit after a few updates. Anything beyond ulp noise (a wrong
        # sharding, a member mixup, hp misalignment) is an O(1)
        # divergence this still catches.
        stacked = jax.device_get(states.params)
        for i in range(self.N_POP):
            got = jax.tree.map(lambda x: x[i], stacked)
            want = jax.device_get(loop_states[i].params)
            for name, g, w in zip(shardlib.tree_leaf_names(want),
                                  jax.tree.leaves(got),
                                  jax.tree.leaves(want)):
                np.testing.assert_allclose(
                    np.asarray(g), np.asarray(w), rtol=2e-5, atol=1e-7,
                    err_msg=(f"member {i} param {name} diverged between "
                             f"mesh and loop paths"))


class TestElasticByRule:
    def test_key_leaf_is_protected_by_name(self):
        # a PRNG key whose length coincides with old_n_envs: the rule
        # path keeps it whole, the deprecated dim heuristic slices it
        old_n_envs, old_world = 8, 4
        tree = {"obs": np.arange(8 * 3, dtype=np.float32).reshape(8, 3),
                "done": np.zeros(8, bool),
                "key": np.arange(8, dtype=np.uint32)}
        out = shardlib.shrink_env_rows_by_rule(
            tree, shardlib.ELASTIC_EXTRA_RULES, old_n_envs=old_n_envs,
            old_world=old_world, surviving_ranks=[0, 2])
        assert out["obs"].shape == (4, 3)
        assert out["done"].shape == (4,)
        assert out["key"].shape == (8,)          # preserved by name
        np.testing.assert_array_equal(out["key"], tree["key"])
        np.testing.assert_array_equal(out["obs"],
                                      tree["obs"][[0, 1, 4, 5]])

    def test_dp_shim_warns_and_keeps_dim_keyed_behavior(self):
        from rlgpuschedule_tpu.parallel import dp
        tree = {"key": np.arange(8, dtype=np.uint32)}
        with pytest.warns(DeprecationWarning, match="shrink_env_rows"):
            out = dp.shrink_env_rows(tree, old_n_envs=8, old_world=4,
                                     surviving_ranks=[0, 2])
        assert out["key"].shape == (4,)          # the old caveat, exactly

    def test_put_global_shim_warns_and_places(self):
        from rlgpuschedule_tpu.parallel import dp
        mesh = make_unified_mesh(devices=jax.devices()[:2])
        with pytest.warns(DeprecationWarning, match="put_global"):
            out = dp.put_global(jnp.ones((4, 2)), env_sharded(mesh))
        assert out.sharding.mesh.shape[DATA_AXIS] == 2

    def test_invalid_survivors_raise(self):
        with pytest.raises(ValueError, match="surviving_ranks"):
            shardlib.shrink_env_rows_by_rule(
                {"a": np.zeros((8,))}, shardlib.ELASTIC_EXTRA_RULES,
                old_n_envs=8, old_world=4, surviving_ranks=[0, 7])


class TestUnifiedMesh:
    def test_three_axis_shape_and_validation(self):
        m = make_unified_mesh(n_pop=2, n_model=2)
        assert (m.shape[POP_AXIS], m.shape[DATA_AXIS],
                m.shape[MODEL_AXIS]) == (2, 2, 2)
        with pytest.raises(ValueError):
            make_unified_mesh(n_pop=3)

    def test_split_mesh_partitions_devices(self):
        from rlgpuschedule_tpu.parallel import split_mesh
        groups = split_mesh(make_unified_mesh(), actor=2)
        assert len(groups.actor) == 2
        assert len(groups.learner) == len(jax.devices()) - 2


class TestModeTable:
    def test_every_refusal_names_known_modes(self):
        from rlgpuschedule_tpu.configs import MODE_FLAGS, MODE_REFUSALS
        for a, b, why in MODE_REFUSALS:
            assert a in MODE_FLAGS and b in MODE_FLAGS and why

    def test_error_format_carries_both_flag_spellings(self):
        from rlgpuschedule_tpu.configs import (MODE_FLAGS, MODE_REFUSALS,
                                               ModeCombinationError,
                                               validate_mode_combination)
        for a, b, _ in MODE_REFUSALS:
            with pytest.raises(ModeCombinationError) as ei:
                validate_mode_combination({a: True, b: True})
            assert MODE_FLAGS[a] in str(ei.value)
            assert MODE_FLAGS[b] in str(ei.value)

    def test_inactive_and_unknown_modes(self):
        from rlgpuschedule_tpu.configs import validate_mode_combination
        validate_mode_combination({"async": True, "pbt": False})
        with pytest.raises(KeyError, match="unknown mode"):
            validate_mode_combination({"warp_drive": True})


class TestFusedUnderMesh:
    """run_fused under the unified mesh (ISSUE 13 satellite): the fused
    scan's in/out_shardings come from the SAME partition-rule table as
    the per-step build — not input-inferred shardings — so the fused
    path is bit-identical to the per-step rule path given the same key
    stream, keeps the rule-table NamedSharding layout on its outputs,
    and never recompiles on a repeated fused length.

    Collected only inside the clean-interpreter subprocess spawned by
    :func:`test_fused_under_mesh_isolated` (the ``__test__`` gate below):
    compiling the fused MULTI-device SPMD program on the forced-8-device
    CPU backend after a long heap-churning session (anything after
    test_serve) SIGABRT/SIGSEGVs the whole pytest process on jax 0.4.37
    — it reproduces on a pristine checkout, with the persistent compile
    cache on OR off, and MALLOC_CHECK_ heisenbugs it away, i.e. latent
    native heap damage surfacing at the biggest multi-device compile. A
    fresh interpreter running just this class is deterministically
    green, so that is the only supported way to run it in-suite."""

    __test__ = os.environ.get("RLGS_FUSED_MESH_INPROC") == "1"

    ITERS = 3

    @pytest.fixture(autouse=True)
    def _no_persistent_cache(self):
        # independent of the in-process crash above, the persistent
        # compile cache's multi-device executable ROUND-TRIP is itself
        # flaky on this backend (the jax 0.4.37 bug ci.sh works around
        # with JAX_ENABLE_COMPILATION_CACHE=false on its mesh smokes) —
        # pay the recompile instead of betting the run on a deserialize
        import jax as _jax
        prev = _jax.config.jax_enable_compilation_cache
        _jax.config.update("jax_enable_compilation_cache", False)
        yield
        _jax.config.update("jax_enable_compilation_cache", prev)

    def _build(self):
        import dataclasses
        from rlgpuschedule_tpu.configs import CONFIGS
        from rlgpuschedule_tpu.experiment import Experiment
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16,
            horizon=64, iterations=2,
            ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))
        mesh = make_unified_mesh(devices=jax.devices()[:2])
        return Experiment.build(cfg, mesh=mesh), mesh

    def test_fused_matches_perstep_rule_path_bitwise(self):
        exp_f, mesh = self._build()
        exp_s, _ = self._build()
        # replay run_fused's exact key stream through the per-step jit
        key, sub = jax.random.split(exp_s.key)
        keys = jax.random.split(sub, self.ITERS)
        state, carry = exp_s.train_state, exp_s.carry
        for i in range(self.ITERS):
            state, carry, _ = exp_s.train_step(state, carry, exp_s.traces,
                                               keys[i], exp_s.faults)
        metrics = exp_f.run_fused(self.ITERS)
        assert all(np.isfinite(np.asarray(v)).all()
                   for v in jax.tree.leaves(jax.device_get(metrics)))
        for name, f, s in zip(
                shardlib.tree_leaf_names(exp_f.train_state.params),
                jax.tree.leaves(jax.device_get(exp_f.train_state.params)),
                jax.tree.leaves(jax.device_get(state.params))):
            assert np.array_equal(np.asarray(f), np.asarray(s)), (
                f"param {name} diverged between fused-under-mesh and "
                f"the per-step rule path")

    def test_fused_outputs_keep_rule_shardings_and_stay_warm(self):
        exp, mesh = self._build()
        exp.run_fused(self.ITERS)       # warmup: blessed compile
        for leaf in jax.tree.leaves(exp.train_state.params):
            sh = leaf.sharding
            assert isinstance(sh, jax.sharding.NamedSharding), (
                f"fused output fell back to {type(sh).__name__}: the "
                f"rule-table out_shardings were not applied")
            assert sh.mesh.shape == mesh.shape
        with CompileCounter() as cc:
            exp.run_fused(self.ITERS)   # same length: cached program
            jax.block_until_ready(jax.tree.leaves(exp.train_state.params))
        assert cc.total == 0, (
            f"fused-under-mesh recompiled on a repeated length: "
            f"{cc.traces} traces, {cc.backend_compiles} compiles")


def test_fused_under_mesh_isolated():
    """Run :class:`TestFusedUnderMesh` in a fresh interpreter (see its
    docstring for why in-process is not survivable on jax 0.4.37) and
    fail with its full output if anything inside fails. One retry, ONLY
    on a signal death (negative returncode): the fresh process dodges
    the heap-state trigger but the underlying XLA:CPU bug is still
    nondeterministic native code — a genuine test failure (rc > 0) is
    never retried."""
    env = dict(os.environ,
               RLGS_FUSED_MESH_INPROC="1",
               JAX_ENABLE_COMPILATION_CACHE="false")
    cmd = [sys.executable, "-m", "pytest",
           f"{__file__}::TestFusedUnderMesh", "-q", "-m", "not slow",
           "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"]
    for attempt in (1, 2):
        res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                             timeout=420)
        if res.returncode == 0:
            # rc 0 with nothing collected would be a silent coverage
            # hole (e.g. the __test__ gate broke); pytest exits 5 on
            # "no tests ran", but belt-and-braces the success line
            assert " passed" in res.stdout, res.stdout
            return
        if res.returncode > 0:
            break                       # real failure inside the class
    pytest.fail(
        f"isolated fused-under-mesh run failed (rc {res.returncode}, "
        f"attempt {attempt}):\n{res.stdout[-4000:]}\n{res.stderr[-4000:]}")
