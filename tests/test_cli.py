"""CLI + utils tests (SURVEY.md §2 "Config/flags" / "Metrics/logging",
§3.1 cli main, §5 tracing)."""
import csv
import json
import os

import numpy as np
import pytest

from rlgpuschedule_tpu import evaluate as evaluate_cli
from rlgpuschedule_tpu import train as train_cli
from rlgpuschedule_tpu.utils import (MetricsLogger, SectionTimer,
                                     ThroughputMeter)

FAST = ["--iterations", "2", "--n-envs", "4", "--n-nodes", "2",
        "--gpus-per-node", "4", "--window-jobs", "16", "--log-every", "1",
        # suite-speed: the CLI tests exercise mechanics (flags, logging,
        # checkpoint/resume), not learning — shrink the compiled programs
        # (preset n_steps=128/epochs=4 cost multi-second XLA compiles per
        # distinct shape on the 1-core CI host)
        "--horizon", "64", "--queue-len", "4", "--n-steps", "8",
        "--n-epochs", "1", "--n-minibatches", "2"]


class TestMetricsLogger:
    def test_csv_rows_and_echo(self, tmp_path, capsys):
        path = str(tmp_path / "m.csv")
        with MetricsLogger(path, echo=False) as log:
            log(0, {"loss": 1.5, "reward": -2.0})
            log(10, {"loss": 1.0, "reward": -1.0})
        rows = list(csv.DictReader(open(path)))
        assert len(rows) == 2
        assert float(rows[1]["loss"]) == 1.0
        assert rows[1]["iteration"] == "10"

    def test_throughput_meter(self):
        m = ThroughputMeter()
        m.tick(100)
        m.tick(100)
        assert m.steps_per_sec > 0

    def test_tensorboard_writer_roundtrip(self, tmp_path):
        # the hand-encoded Event/TFRecord bytes must read back through
        # stock TensorBoard's own loader (crc framing + proto layout)
        tb_mod = pytest.importorskip(
            "tensorboard.backend.event_processing.event_file_loader")
        from rlgpuschedule_tpu.utils import TensorBoardWriter
        with TensorBoardWriter(str(tmp_path)) as tb:
            tb(3, {"mean_reward": -0.5, "note": "skipped-non-float"})
            tb(7, {"mean_reward": 1.25})
            path = tb.path
        from tensorboard.compat.proto import event_pb2
        events = [event_pb2.Event.FromString(raw) for raw in
                  tb_mod.RawEventFileLoader(path).Load()]
        assert events[0].file_version == "brain.Event:2"
        scalars = {(e.step, v.tag): v.simple_value
                   for e in events[1:] for v in e.summary.value}
        assert scalars[(3, "mean_reward")] == -0.5
        assert scalars[(7, "mean_reward")] == 1.25

    def test_section_timer(self):
        t = SectionTimer()
        with t("a"):
            pass
        with t("a"):
            pass
        assert "a" in t.report() and t.report()["a"] >= 0


class TestCompileCache:
    def test_enable_compile_cache_points_jax_at_the_dir(self, tmp_path,
                                                        monkeypatch):
        # CLI processes must reuse one persistent XLA cache (measured:
        # the grid-CNN program build is ~10 min on this host, re-paid
        # per process without it). Explicit env var wins; jax config and
        # the subprocess-facing env var both end up set.
        import jax
        from rlgpuschedule_tpu.utils.platform import enable_compile_cache
        prev = jax.config.jax_compilation_cache_dir
        target = str(tmp_path / "cache")
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", target)
        try:
            assert enable_compile_cache() == target
            assert jax.config.jax_compilation_cache_dir == target
            assert os.environ["JAX_COMPILATION_CACHE_DIR"] == target
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)


class TestTrainCLI:
    def test_list_configs(self, capsys):
        train_cli.main(["--list-configs"])
        out = capsys.readouterr().out
        for name in ("ppo-mlp-synth64", "ppo-cnn-philly512", "a2c-pai-fair",
                     "gnn-gang-place", "hier-pbt-member"):
            assert name in out

    def test_unknown_config_exits(self):
        with pytest.raises(SystemExit):
            train_cli.main(["--config", "nope"])

    def test_train_logs_and_checkpoints(self, tmp_path, capsys):
        csv_path = str(tmp_path / "metrics.csv")
        ckpt_dir = str(tmp_path / "ckpt")
        summary = train_cli.main(
            ["--config", "ppo-mlp-synth64", *FAST,
             "--log-csv", csv_path, "--ckpt-dir", ckpt_dir,
             "--ckpt-every", "1"])
        assert summary["iterations"] == 2
        assert np.isfinite(summary["env_steps_per_sec"])
        rows = list(csv.DictReader(open(csv_path)))
        assert len(rows) == 2
        assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)
        # stdout's last line is the summary JSON
        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["iterations"] == 2

    def test_resume_roundtrip(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        args = ["--config", "ppo-mlp-synth64", *FAST,
                "--ckpt-dir", ckpt_dir, "--ckpt-every", "2"]
        train_cli.main(args)
        out = train_cli.main(args + ["--resume"])
        assert out["iterations"] == 2

    def test_pbt_training(self, tmp_path):
        summary = train_cli.main(
            ["--config", "hier-pbt-member", "--pbt", "--n-pop", "2",
             "--pbt-ready", "1", "--iterations", "2", "--n-envs", "4",
             "--n-nodes", "4", "--gpus-per-node", "4",
             "--window-jobs", "16", "--log-every", "1",
             "--horizon", "48", "--queue-len", "4", "--n-steps", "8",
             "--n-epochs", "1", "--n-minibatches", "2"])
        assert summary["pbt_events"] >= 1
        assert all(np.isfinite(summary["final_fitness"]))

    def test_select_checkpoint_ranks_retained_series(self, tmp_path):
        # --ckpt-keep retains a checkpoint SERIES; select_checkpoint ranks
        # it by full-trace JCT on a held-out validation stream and emits
        # the argmin step (round-5 finding: per-window probes do not rank
        # full-trace quality, so selection must use the deliverable's own
        # metric on a third stream)
        from rlgpuschedule_tpu import select_checkpoint
        ckpt_dir = str(tmp_path / "ckpt")
        train_cli.main(["--config", "ppo-mlp-synth64", *FAST,
                        "--ckpt-dir", ckpt_dir, "--ckpt-every", "1",
                        "--ckpt-keep", "2"])
        out = select_checkpoint.main(
            ["--config", "ppo-mlp-synth64", "--ckpt-dir", ckpt_dir,
             "--n-envs", "4", "--n-nodes", "2", "--gpus-per-node", "4",
             "--window-jobs", "16", "--queue-len", "4", "--horizon", "64",
             "--val-jobs", "48", "--val-seed", "77"])
        assert len(out["ranking"]) == 2
        assert out["step"] in [s for _, s in out["ranking"]]
        assert out["val_ratio"] == out["ranking"][0][0]
        with pytest.raises(SystemExit, match="training seed"):
            select_checkpoint.main(
                ["--config", "ppo-mlp-synth64", "--ckpt-dir", ckpt_dir,
                 "--val-seed", "0"])

    def test_source_jobs_override(self):
        # --source-jobs pins the generated source trace size explicitly
        # (the north-star run trains on a 100k+-job trace by contract,
        # not as a side effect of n_envs * window_jobs)
        from rlgpuschedule_tpu.configs import CONFIGS
        from rlgpuschedule_tpu.experiment import load_source_trace
        args = train_cli.build_parser().parse_args(
            ["--config", "ppo-mlp-synth64", "--source-jobs", "2048"])
        cfg = train_cli.apply_overrides(CONFIGS["ppo-mlp-synth64"], args)
        assert cfg.source_jobs == 2048
        assert load_source_trace(cfg).num_jobs == 2048

    def test_algo_hparam_overrides(self):
        # --lr/--ent-coef/--n-steps/--n-epochs/--n-minibatches land in the
        # active algo's config; PPO-only knobs are rejected for A2C
        args = train_cli.build_parser().parse_args(
            ["--config", "ppo-mlp-synth64", "--lr", "1e-3",
             "--n-steps", "32", "--n-epochs", "2", "--n-minibatches", "2",
             "--ent-coef", "0.02"])
        from rlgpuschedule_tpu.configs import CONFIGS
        cfg = train_cli.apply_overrides(CONFIGS["ppo-mlp-synth64"], args)
        assert (cfg.ppo.lr, cfg.ppo.n_steps, cfg.ppo.n_epochs,
                cfg.ppo.n_minibatches, cfg.ppo.ent_coef) == \
            (1e-3, 32, 2, 2, 0.02)
        a2c_args = train_cli.build_parser().parse_args(
            ["--config", "a2c-pai-fair", "--lr", "1e-3", "--n-steps", "8"])
        cfg = train_cli.apply_overrides(CONFIGS["a2c-pai-fair"], a2c_args)
        assert (cfg.a2c.lr, cfg.a2c.n_steps) == (1e-3, 8)
        # A2C runs the shared minibatch-geometry engine too (its preset
        # 1x1 geometry is the classic full-batch update), so geometry
        # overrides now land in cfg.a2c instead of being refused
        a2c_geom = train_cli.build_parser().parse_args(
            ["--config", "a2c-pai-fair", "--n-epochs", "2",
             "--n-minibatches", "4"])
        cfg = train_cli.apply_overrides(CONFIGS["a2c-pai-fair"], a2c_geom)
        assert (cfg.a2c.n_epochs, cfg.a2c.n_minibatches) == (2, 4)

    def test_minibatch_geometry_and_bf16_overrides(self):
        # the ISSUE-2 lever flags: --minibatch-size (overrides
        # --n-minibatches, algos.update contract) and --bf16-update
        from rlgpuschedule_tpu.configs import CONFIGS
        args = train_cli.build_parser().parse_args(
            ["--config", "ppo-mlp-synth64", "--minibatch-size", "64",
             "--bf16-update"])
        cfg = train_cli.apply_overrides(CONFIGS["ppo-mlp-synth64"], args)
        assert cfg.ppo.minibatch_size == 64
        assert cfg.ppo.bf16_update is True
        # untouched flags keep preset values
        assert cfg.ppo.n_epochs == 4 and cfg.ppo.bf16_update is True
        base = train_cli.build_parser().parse_args(
            ["--config", "ppo-mlp-synth64"])
        cfg = train_cli.apply_overrides(CONFIGS["ppo-mlp-synth64"], base)
        assert cfg.ppo.minibatch_size is None
        assert cfg.ppo.bf16_update is False

    def test_obs_kind_override(self):
        # --obs-kind swaps the preset's encoder family (e.g. config 2's
        # grid CNN down to the flat MLP for a CPU-host training run)
        args = train_cli.build_parser().parse_args(
            ["--config", "ppo-cnn-philly512", "--obs-kind", "flat"])
        from rlgpuschedule_tpu.configs import CONFIGS
        cfg = train_cli.apply_overrides(CONFIGS["ppo-cnn-philly512"], args)
        assert cfg.obs_kind == "flat"
        # no override keeps the preset encoder
        args = train_cli.build_parser().parse_args(
            ["--config", "ppo-cnn-philly512"])
        cfg = train_cli.apply_overrides(CONFIGS["ppo-cnn-philly512"], args)
        assert cfg.obs_kind == "grid"

    def test_eval_every_probe(self, tmp_path):
        # --eval-every: held-out greedy replay scored vs cached baselines,
        # logged to a separate .eval.csv stream (schemas differ from the
        # train rows) and returned as eval_history
        csv_path = str(tmp_path / "m.csv")
        summary = train_cli.main(
            ["--config", "ppo-mlp-synth64", *FAST, "--eval-every", "1",
             "--eval-windows", "2", "--log-csv", csv_path])
        hist = summary["eval_history"]
        assert len(hist) == 2        # iterations=2, probe each iteration
        for row in hist:
            assert np.isfinite(row["eval_avg_jct"])
            assert np.isfinite(row["eval_vs_tiresias"])
            assert 0 < row["eval_completion"] <= 1.0
        rows = list(csv.DictReader(open(csv_path + ".eval.csv")))
        assert len(rows) == 2 and "eval_vs_tiresias" in rows[0]

    @pytest.mark.timing_flake(retries=2)
    def test_keep_best_checkpoint(self, tmp_path):
        # --keep-best: the best-by-held-out-probe params survive under
        # <ckpt-dir>/best even if later iterations regress (the GNN
        # late-collapse lesson); the eval rows carry an eval_is_best flag
        #
        # timing_flake TRACKING NOTE (carried 1F since the seed, ~1 in
        # N full-suite runs; always passes standalone): the --resume
        # half fails with FileNotFoundError("no checkpoint found under
        # .../ckpt") — the FIRST run's final periodic save (experiment
        # .run's b == iterations-1 save into <ckpt-dir>) is missing
        # from disk when the second run restores, while the best/
        # sidecar store written moments earlier IS present (its
        # assertions above pass in the failing runs). Orbax
        # CheckpointManager is synchronous on CPU here, so the step
        # was handed to orbax but its directory did not survive to
        # the re-open — pointing at tmp/step-dir lifecycle, not our
        # save logic. Until the orbax-side race is pinned, the retry
        # marker reruns with a FRESH tmp_path so tier-1 stays clean
        # and the flake stays visible as a PytestWarning.
        ckpt_dir = str(tmp_path / "ckpt")
        summary = train_cli.main(
            ["--config", "ppo-mlp-synth64", *FAST, "--eval-every", "1",
             "--eval-windows", "2", "--ckpt-dir", ckpt_dir,
             "--keep-best"])
        hist = summary["eval_history"]
        assert hist[0]["eval_is_best"] == 1.0   # first probe always best
        from rlgpuschedule_tpu.checkpoint import Checkpointer
        with Checkpointer(os.path.join(ckpt_dir, "best")) as best:
            assert len(best.all_steps()) == 1
        best_jcts = [r["eval_avg_jct"] for r in hist
                     if r["eval_is_best"] == 1.0]
        # keep-best only tracks full-completion probes (its contract)
        assert min(r["eval_avg_jct"] for r in hist
                   if r["eval_completion"] >= 1.0) == best_jcts[-1]
        # a resumed run recovers the bar from the best meta instead of
        # resetting to +inf (which would rotate out the prior best)
        with Checkpointer(os.path.join(ckpt_dir, "best")) as best:
            prior = best.read_meta()["eval_avg_jct"]
        summary2 = train_cli.main(
            ["--config", "ppo-mlp-synth64", *FAST, "--eval-every", "1",
             "--eval-windows", "2", "--ckpt-dir", ckpt_dir,
             "--keep-best", "--resume"])
        for row in summary2["eval_history"]:
            if row["eval_is_best"] == 1.0:
                assert row["eval_avg_jct"] < prior

    def test_report_flag(self, capsys):
        summary = train_cli.main(
            ["--config", "ppo-mlp-synth64", *FAST, "--report"])
        assert "tiresias" in summary["jct_report"]


class TestEvaluateCLI:
    def test_baselines_only(self, capsys):
        report = evaluate_cli.main(
            ["--config", "ppo-mlp-synth64", "--baselines-only"])
        assert set(report) >= {"fifo", "sjf", "srtf", "tiresias"}

    def test_policy_eval_untrained(self):
        report = evaluate_cli.main(
            ["--config", "ppo-mlp-synth64", "--n-envs", "4", "--no-random",
             "--n-nodes", "2", "--gpus-per-node", "4", "--window-jobs", "16",
             "--horizon", "64", "--max-steps", "64"])
        assert "policy" in report and "vs_tiresias" in report

    def test_drain_frac_eval(self):
        # --drain-frac 1.0 evaluates on backlog-drain copies of the
        # windows: every valid job submits at t=0, so the baseline FIFO
        # JCT must differ from the streaming-windows evaluation of the
        # same config (reproduces the BASELINE.md drain tables)
        common = ["--config", "ppo-mlp-synth64", "--n-envs", "4",
                  "--no-random", "--n-nodes", "2", "--gpus-per-node", "4",
                  "--window-jobs", "16", "--horizon", "64",
                  "--max-steps", "64"]
        stream = evaluate_cli.main(common)
        drain = evaluate_cli.main(common + ["--drain-frac", "1.0"])
        assert np.isfinite(drain["policy"])
        assert drain["fifo"] != stream["fifo"]

    def test_eval_windows_decoupled_from_training_batch(self, tmp_path):
        # a checkpoint trained at n_envs=4 must evaluate on a 2-window
        # batch: --n-envs stays 4 (the carry restore template), while
        # --eval-windows re-cuts the replay batch (the big-batch-TPU-
        # checkpoint-on-CPU-host case)
        ckpt_dir = str(tmp_path / "ckpt")
        train_cli.main(["--config", "ppo-mlp-synth64", *FAST,
                        "--ckpt-dir", ckpt_dir, "--ckpt-every", "2"])
        report = evaluate_cli.main(
            ["--config", "ppo-mlp-synth64", "--n-envs", "4",
             "--n-nodes", "2", "--gpus-per-node", "4", "--queue-len", "4",
             "--window-jobs", "16", "--horizon", "64", "--max-steps", "64",
             "--no-random", "--ckpt-dir", ckpt_dir, "--eval-windows", "2"])
        assert np.isfinite(report["policy"])
        with pytest.raises(SystemExit):
            evaluate_cli.main(["--config", "hier-pbt-member", "--pbt",
                               "--eval-windows", "2"])

    def test_pbt_population_eval(self, tmp_path):
        # config-5 eval path: train a tiny PBT population, checkpoint it,
        # then restore + replay the fittest member against the baselines
        ckpt_dir = str(tmp_path / "pop")
        small = ["--n-envs", "4", "--n-nodes", "4", "--gpus-per-node", "4",
                 "--window-jobs", "16", "--horizon", "48",
                 "--queue-len", "4"]
        train_small = [*small, "--n-steps", "8", "--n-epochs", "1",
                       "--n-minibatches", "2"]   # train-CLI-only knobs
        train_cli.main(
            ["--config", "hier-pbt-member", "--pbt", "--n-pop", "2",
             "--pbt-ready", "1", "--iterations", "2", *train_small,
             "--log-every", "0", "--ckpt-dir", ckpt_dir,
             "--ckpt-every", "2"])
        report = evaluate_cli.main(
            ["--config", "hier-pbt-member", "--pbt", "--n-pop", "2",
             *small, "--max-steps", "48", "--no-random",
             "--ckpt-dir", ckpt_dir])
        assert "policy" in report and "tiresias" in report
        assert np.isfinite(report["policy"])

    def test_stall_guard_flag_and_report_marker(self):
        # VERDICT r4 weak #6: guarded and unguarded preemptive runs must
        # be distinguishable from the emitted report, and the guard must
        # be A/B-able from the CLI
        common = ["--config", "ppo-mlp-preempt", "--n-envs", "4",
                  "--no-random", "--n-nodes", "2", "--gpus-per-node", "4",
                  "--window-jobs", "16", "--horizon", "64",
                  "--queue-len", "4", "--max-steps", "64"]
        guarded = evaluate_cli.main(common)
        assert guarded["stall_guard"] is True
        raw = evaluate_cli.main(common + ["--no-stall-guard"])
        assert raw["stall_guard"] is False
        # non-preemptive configs: the guard is structurally a no-op, so
        # disabling it is refused rather than silently ignored
        with pytest.raises(SystemExit):
            evaluate_cli.main(["--config", "ppo-mlp-synth64",
                               "--no-stall-guard"])

    def test_hier_policy_eval(self):
        report = evaluate_cli.main(
            ["--config", "hier-pbt-member", "--n-envs", "2", "--no-random",
             "--n-nodes", "4", "--gpus-per-node", "4", "--window-jobs", "16",
             "--horizon", "48", "--max-steps", "48"])
        assert "policy" in report and "tiresias" in report
        assert np.isfinite(report["policy"])

    def test_repro_tuple_in_json_output(self, capsys):
        # ISSUE 6 satellite: every evaluate JSON carries the full
        # reproducibility tuple (seed, scenario params, checkpoint step)
        evaluate_cli.main(
            ["--config", "ppo-mlp-synth64", "--n-envs", "2", "--no-random",
             "--n-nodes", "2", "--gpus-per-node", "4", "--window-jobs",
             "16", "--horizon", "64", "--max-steps", "64", "--seed", "5"])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        repro = out["repro"]
        assert repro["seed"] == 5 and repro["config"] == "ppo-mlp-synth64"
        assert {"trace", "n_nodes", "gpus_per_node", "window_jobs",
                "faults", "ckpt_dir", "ckpt_step"} <= set(repro)
        assert repro["ckpt_step"] is None   # untrained init weights

    def test_chaos_matrix_cli(self, capsys):
        # the ISSUE 6 acceptance shape: regime x scheduler degradation
        # matrix on CPU, conservation holding, repro tuple attached
        report = evaluate_cli.main(
            ["--config", "ppo-mlp-synth64", "--chaos",
             "--chaos-regimes", "sporadic", "--chaos-baselines", "sjf",
             "--n-envs", "2", "--n-nodes", "2", "--gpus-per-node", "4",
             "--window-jobs", "16", "--queue-len", "4",
             "--horizon", "256", "--max-steps", "256"])
        assert set(report["regimes"]) == {"none", "sporadic"}
        assert report["jobs_lost"] == 0
        row = report["regimes"]["sporadic"]["policy"]
        assert np.isfinite(row["avg_jct"]) and row["degradation"] >= 0
        assert report["repro"]["chaos_seed"] == 0
        err = capsys.readouterr().err
        assert "chaos matrix" in err and "degradation" in err

    def test_chaos_flag_refusals(self):
        with pytest.raises(SystemExit):   # chaos sub-flag without --chaos
            evaluate_cli.main(["--config", "ppo-mlp-synth64",
                               "--chaos-regimes", "storm"])
        with pytest.raises(SystemExit):   # incompatible mode
            evaluate_cli.main(["--config", "ppo-mlp-synth64", "--chaos",
                               "--baselines-only"])
        with pytest.raises(SystemExit):   # unknown regime, named early
            evaluate_cli.main(["--config", "ppo-mlp-synth64", "--chaos",
                               "--chaos-regimes", "meteor"])

    def test_train_faults_refusals(self):
        with pytest.raises(SystemExit):   # unknown fault regime
            train_cli.main(["--config", "ppo-mlp-synth64", *FAST,
                            "--faults", "meteor"])
        with pytest.raises(SystemExit):   # unknown domain regime
            train_cli.main(["--config", "ppo-mlp-synth64", *FAST,
                            "--domains", "meteor"])
        # --faults x --pbt is SUPPORTED since the domain PR (per-member
        # (seed, member, env) schedules); --domains x --pbt is not
        with pytest.raises(SystemExit):
            train_cli.main(["--config", "ppo-mlp-synth64", *FAST,
                            "--domains", "mixed", "--pbt"])


class TestMinibatchSweep:
    """profile_breakdown --sweep-minibatch: the automated geometry lever
    sweep must emit a ranked artifact that bench.py can consume."""

    def test_sweep_artifact_ranked_and_written(self, tmp_path, capsys):
        from rlgpuschedule_tpu import profile_breakdown
        out_path = str(tmp_path / "sweep.json")
        art = profile_breakdown.main(
            ["--n-envs", "2", "--n-steps", "8", "--repeats", "1",
             "--iters-per-repeat", "1", "--sweep-minibatch",
             "--sweep-out", out_path])
        capsys.readouterr()
        assert art["sweep"] == "minibatch-geometry"
        assert art["batch_per_iteration"] == 16
        times = [r["update_s_per_iteration"] for r in art["results"]]
        assert times == sorted(times), "results must rank fastest-first"
        assert art["best"] == art["results"][0]
        # grid covers the epochs axis and every tiling minibatch count
        geoms = {(r["n_epochs"], r["n_minibatches"])
                 for r in art["results"]}
        assert {(1, 1), (1, 16), (2, 8)} <= geoms
        for r in art["results"]:
            assert r["minibatch_size"] * r["n_minibatches"] == 16
            assert r["update_env_steps_per_sec"] > 0
            assert "mfu_update" in r          # null off-TPU, present always
            assert r["speedup_vs_default"] > 0
        default = next(r for r in art["results"]
                       if (r["n_epochs"], r["n_minibatches"]) == (2, 8))
        assert default["speedup_vs_default"] == pytest.approx(1.0)
        # the artifact on disk is the same object bench.py --sweep reads
        with open(out_path) as f:
            on_disk = json.load(f)
        assert on_disk["best"] == art["best"]
        import bench
        e, m = bench.geometry_from_sweep(out_path)
        assert (e, m) == (art["best"]["n_epochs"],
                          art["best"]["n_minibatches"])

    def test_bench_refuses_non_sweep_artifact(self, tmp_path):
        import bench
        bad = tmp_path / "not_a_sweep.json"
        bad.write_text(json.dumps({"metric": "x"}))
        with pytest.raises(SystemExit):
            bench.geometry_from_sweep(str(bad))


class TestStallGuardEngage:
    def test_guard_engage_path_decides_completion_from_cli(self, tmp_path):
        """ISSUE-2 satellite (VERDICT r5 weak #4): a REAL place<->preempt
        deadlock driven from the evaluate CLI — guard-off must read <100%
        completion (the completion guard flags it), guard-on must
        complete. The cycler is the synthetic form of the measured
        config-1p staller (BASELINE.md 'Learned preemption'): a constant-
        logit policy that prefers preempting the most-attained running
        job over placing, so greedy replay ping-pongs place<->preempt at
        clock 0.0 forever."""
        import dataclasses
        import flax
        import jax.numpy as jnp
        from rlgpuschedule_tpu.checkpoint import Checkpointer
        from rlgpuschedule_tpu.configs import CONFIGS
        from rlgpuschedule_tpu.experiment import Experiment

        over = dict(n_nodes=2, gpus_per_node=4, n_envs=2, window_jobs=16,
                    queue_len=4, horizon=1024, drain_frac=1.0)
        cfg = dataclasses.replace(CONFIGS["ppo-mlp-preempt"], **over)
        exp = Experiment.build(cfg)
        sim = exp.env_params.sim
        K, P = sim.queue_len, sim.n_placements
        flat = flax.traverse_util.flatten_dict(exp.train_state.params)
        bias = np.zeros(sim.n_actions, np.float32)
        bias[:K * P] = 1.0       # placements: preferred over no-op
        bias[K * P] = 2.0        # preempt slot 0: preferred over all
        bias[-1] = -1.0          # no-op: last resort (advances time)
        flat[("params", "policy", "kernel")] = jnp.zeros_like(
            flat[("params", "policy", "kernel")])
        flat[("params", "policy", "bias")] = jnp.asarray(bias)
        exp.train_state = exp.train_state.replace(
            params=flax.traverse_util.unflatten_dict(flat))
        with Checkpointer(str(tmp_path / "ck")) as ck:
            exp.save_checkpoint(ck)

        common = ["--config", "ppo-mlp-preempt", "--n-nodes", "2",
                  "--gpus-per-node", "4", "--n-envs", "2",
                  "--window-jobs", "16", "--queue-len", "4",
                  "--horizon", "1024", "--drain-frac", "1.0",
                  "--ckpt-dir", str(tmp_path / "ck"), "--no-random"]
        raw = evaluate_cli.main(common + ["--no-stall-guard"])
        assert raw["stall_guard"] is False
        assert raw["policy_completion"] < 1.0   # deadlocked, flagged
        guarded = evaluate_cli.main(common)
        assert guarded["stall_guard"] is True
        assert guarded["policy_completion"] == 1.0
        assert np.isfinite(guarded["policy"])


class TestPBTKeepBest:
    def test_pbt_eval_probe_and_best_population_retention(self, tmp_path):
        """ISSUE-2 satellite (VERDICT r5 weak #2): the PBT path honors
        --ckpt-keep (series rotation) and retains a probe-selected best/
        population on the eval cadence."""
        ck = str(tmp_path / "ck")
        summary = train_cli.main(
            ["--config", "ppo-mlp-synth64", "--pbt", "--n-pop", "2",
             "--pbt-ready", "1", "--iterations", "2", "--n-envs", "4",
             "--n-nodes", "2", "--gpus-per-node", "4",
             "--window-jobs", "16", "--horizon", "64", "--queue-len", "4",
             "--n-steps", "8", "--n-epochs", "1", "--n-minibatches", "2",
             "--log-every", "1", "--eval-every", "1", "--eval-windows",
             "2", "--keep-best", "--ckpt-dir", ck, "--ckpt-every", "1",
             "--ckpt-keep", "1"])
        assert summary["pbt_events"] >= 1
        # the probe ran on the eval cadence and its rows are in the summary
        assert [row["iteration"] for row in summary["eval_history"]] \
            == [0, 1]
        assert all("eval_avg_jct" in row for row in summary["eval_history"])
        from rlgpuschedule_tpu.checkpoint import Checkpointer
        # --ckpt-keep 1 honored in the PBT path: one retained series step
        with Checkpointer(ck) as series:
            assert len(series.all_steps()) == 1
        # best/ holds a full population checkpoint + the probe bar in meta
        with Checkpointer(os.path.join(ck, "best")) as best:
            steps = best.all_steps()
            assert len(steps) == 1
            meta = best.read_meta()
            assert "eval_avg_jct" in meta
        # and it restores as a population (evaluate --pbt's path)
        report = evaluate_cli.main(
            ["--config", "ppo-mlp-synth64", "--pbt", "--n-pop", "2",
             "--n-envs", "4", "--n-nodes", "2", "--gpus-per-node", "4",
             "--window-jobs", "16", "--horizon", "64", "--queue-len", "4",
             "--ckpt-dir", os.path.join(ck, "best"), "--no-random",
             "--max-steps", "32"])
        assert np.isfinite(report["policy"])
