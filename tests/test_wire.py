"""Wire-framing unit tests (ISSUE 17, v2 in ISSUE 20): the
length-prefixed binary protocol is small enough to pin completely —
prefix round-trip (32-byte v2 with the ``req_id`` causality field, and
backward decode of legacy 24-byte v1 frames), the descriptor grammar,
every rejection path of :func:`unpack_prefix`, the
request/response/error pack helpers, and the blocking client reader's
EOF semantics (clean boundary EOF vs mid-frame truncation)."""
import socket
import threading

import numpy as np
import pytest

from rlgpuschedule_tpu.serve import wire


def example():
    rng = np.random.default_rng(0)
    return (rng.standard_normal(6).astype(np.float32),
            np.ones(9, bool))


class TestPrefix:
    def test_prefix_sizes(self):
        assert wire.PREFIX_SIZE == 32
        assert wire.PREFIX_V1_SIZE == 24
        assert wire.VERSION == 2

    def test_pack_unpack_round_trip(self):
        frame = wire.pack_frame(wire.KIND_REQ, b"hdr", b"body",
                                meta64=123456, meta32=7,
                                req_id=0xDEADBEEF)
        kind, hlen, blen, meta64, meta32, req_id = wire.unpack_prefix(
            frame[:wire.PREFIX_SIZE])
        assert (kind, hlen, blen, meta64, meta32, req_id) == \
            (wire.KIND_REQ, 3, 4, 123456, 7, 0xDEADBEEF)
        assert frame[wire.PREFIX_SIZE:wire.PREFIX_SIZE + hlen] == b"hdr"
        assert frame[wire.PREFIX_SIZE + hlen:] == b"body"

    def test_req_id_defaults_to_zero(self):
        frame = wire.pack_frame(wire.KIND_REQ, b"", b"")
        assert wire.unpack_prefix(frame[:wire.PREFIX_SIZE])[5] == 0

    @pytest.mark.parametrize("mutate,msg", [
        (lambda b: b"XXXX" + b[4:], "bad magic"),
        (lambda b: b[:4] + bytes([99]) + b[5:], "wire version"),
        (lambda b: b[:5] + bytes([0]) + b[6:], "frame kind"),
        (lambda b: b[:-1], r"must be 24 \(v1\) or 32 \(v2\) bytes"),
        # a 24-byte prefix claiming v2 is a torn v2 prefix, not a v1 one
        (lambda b: b[:24], "wire version"),
    ])
    def test_unpack_prefix_rejects_malformed(self, mutate, msg):
        good = wire.pack_frame(wire.KIND_REQ, b"", b"")
        with pytest.raises(wire.WireError, match=msg):
            wire.unpack_prefix(mutate(good[:wire.PREFIX_SIZE]))

    def test_unpack_prefix_rejects_oversized_body(self):
        raw = wire.PREFIX.pack(wire.MAGIC, wire.VERSION, wire.KIND_REQ,
                               0, wire.MAX_BODY_BYTES + 1, 0, 0, 0)
        with pytest.raises(wire.WireError, match="exceeds"):
            wire.unpack_prefix(raw)

    def test_pack_frame_rejects_bad_kind_and_oversize(self):
        with pytest.raises(wire.WireError, match="kind"):
            wire.pack_frame(0, b"")
        with pytest.raises(wire.WireError, match="header too large"):
            wire.pack_frame(wire.KIND_REQ, b"x" * 0x10000)


class TestV1Backward:
    """A v2 server must keep decoding the 24-byte v1 frames every
    pre-ISSUE-20 client still sends — ``req_id`` reads as 0 (the
    "unassigned" sentinel the server mints over)."""

    @staticmethod
    def v1_frame(kind, header=b"", body=b"", meta64=0, meta32=0):
        return wire.PREFIX_V1.pack(wire.MAGIC, 1, kind, len(header),
                                   len(body), meta64, meta32) \
            + header + body

    def test_v1_prefix_decodes_with_zero_req_id(self):
        raw = self.v1_frame(wire.KIND_REQ, b"hdr", b"body!",
                            meta64=250_000, meta32=3)
        out = wire.unpack_prefix(raw[:wire.PREFIX_V1_SIZE])
        assert out == (wire.KIND_REQ, 3, 5, 250_000, 3, 0)

    def test_v1_prefix_rejections_still_fire(self):
        raw = self.v1_frame(wire.KIND_REQ)[:wire.PREFIX_V1_SIZE]
        with pytest.raises(wire.WireError, match="bad magic"):
            wire.unpack_prefix(b"XXXX" + raw[4:])
        with pytest.raises(wire.WireError, match="frame kind"):
            wire.unpack_prefix(raw[:5] + bytes([0]) + raw[6:])

    def test_recv_frame_reads_v1_stream(self):
        a, b = socket.socketpair()
        try:
            a.sendall(self.v1_frame(wire.KIND_REQ, b"h", b"xyz",
                                    meta32=7))
            kind, header, body, meta64, meta32, req_id = \
                wire.recv_frame(b)
            assert (kind, header, body) == (wire.KIND_REQ, b"h", b"xyz")
            assert (meta64, meta32, req_id) == (0, 7, 0)
        finally:
            a.close()
            b.close()


class TestDescriptor:
    def test_descriptor_is_exact_ascii_schema(self):
        obs, mask = example()
        assert wire.descriptor(obs) == b"float32:(6,)"
        assert wire.descriptor(mask) == b"bool:(9,)"
        # pytrees flatten in leaf order
        assert wire.descriptor({"a": obs, "b": mask}) == \
            b"float32:(6,)|bool:(9,)"

    def test_descriptor_distinguishes_dtype_and_shape(self):
        a = np.zeros(4, np.float32)
        assert wire.descriptor(a) != wire.descriptor(a.astype(np.float64))
        assert wire.descriptor(a) != wire.descriptor(np.zeros(5, np.float32))


class TestPackHelpers:
    def test_pack_request_carries_deadline_stall_and_req_id(self):
        obs, mask = example()
        frame = wire.pack_request(obs, mask, deadline_s=0.25, stall=3,
                                  req_id=0x68C90000000001)
        kind, hlen, blen, meta64, meta32, req_id = wire.unpack_prefix(
            frame[:wire.PREFIX_SIZE])
        assert kind == wire.KIND_REQ
        assert meta64 == 250_000 and meta32 == 3
        assert req_id == 0x68C90000000001
        assert blen == obs.nbytes + mask.nbytes
        header = frame[wire.PREFIX_SIZE:wire.PREFIX_SIZE + hlen]
        assert header == wire.descriptor(obs) + b"|" + wire.descriptor(mask)
        # no deadline -> meta64 == 0 (the "no SLO" sentinel); no id ->
        # req_id == 0 (the server mints one)
        frame = wire.pack_request(obs, mask)
        out = wire.unpack_prefix(frame[:wire.PREFIX_SIZE])
        assert out[3] == 0 and out[5] == 0

    def test_pack_response_action_round_trip(self):
        action = np.arange(5, dtype=np.int32)
        frame = wire.pack_response(action, latency_s=0.002,
                                   req_id=0xBEEF)
        kind, hlen, blen, meta64, _, req_id = wire.unpack_prefix(
            frame[:wire.PREFIX_SIZE])
        assert kind == wire.KIND_RESP and meta64 == 2000
        assert req_id == 0xBEEF
        header = frame[wire.PREFIX_SIZE:wire.PREFIX_SIZE + hlen]
        body = frame[wire.PREFIX_SIZE + hlen:]
        out = wire.unpack_action(header, body)
        np.testing.assert_array_equal(out, action)
        assert out.dtype == np.int32

    def test_unpack_action_rejects_garbage_descriptor(self):
        with pytest.raises(wire.WireError, match="bad action descriptor"):
            wire.unpack_action(b"nonsense", b"")

    def test_pack_error_retry_after_microseconds(self):
        frame = wire.pack_error("shed:admission", {"x": 1},
                                retry_after_s=0.05, req_id=42)
        kind, hlen, _, meta64, _, req_id = wire.unpack_prefix(
            frame[:wire.PREFIX_SIZE])
        assert kind == wire.KIND_ERR and meta64 == 50_000
        assert req_id == 42
        assert frame[wire.PREFIX_SIZE:wire.PREFIX_SIZE + hlen] == \
            b"shed:admission"
        # retry omitted -> 0 = "do not retry here"
        frame = wire.pack_error("closed", {})
        assert wire.unpack_prefix(frame[:wire.PREFIX_SIZE])[3] == 0


class TestRecvFrame:
    def _pipe(self):
        a, b = socket.socketpair()
        return a, b

    def test_recv_frame_reassembles_split_writes(self):
        a, b = self._pipe()
        try:
            obs, mask = example()
            frame = wire.pack_request(obs, mask, req_id=0x123456789AB)

            def dribble():
                for i in range(0, len(frame), 7):
                    a.sendall(frame[i:i + 7])

            t = threading.Thread(target=dribble)
            t.start()
            kind, header, body, _, _, req_id = wire.recv_frame(b)
            t.join()
            assert kind == wire.KIND_REQ
            assert req_id == 0x123456789AB
            assert body == obs.tobytes() + mask.tobytes()
            assert header == (wire.descriptor(obs) + b"|"
                              + wire.descriptor(mask))
        finally:
            a.close()
            b.close()

    def test_clean_eof_at_boundary_vs_truncation_mid_frame(self):
        obs, mask = example()
        frame = wire.pack_request(obs, mask)
        # clean close at a frame boundary -> EOFError (normal shutdown)
        a, b = self._pipe()
        a.close()
        with pytest.raises(EOFError):
            wire.recv_frame(b)
        b.close()
        # close mid-frame -> ConnectionError (the peer died on us)
        a, b = self._pipe()
        a.sendall(frame[:10])
        a.close()
        with pytest.raises(ConnectionError):
            wire.recv_frame(b)
        b.close()

    def test_truncated_v2_tail_is_connection_error(self):
        # the 24-byte head of a v2 frame arrives, the 8-byte req_id
        # tail never does: mid-frame death, not a clean boundary
        obs, mask = example()
        frame = wire.pack_request(obs, mask)
        a, b = self._pipe()
        a.sendall(frame[:wire.PREFIX_V1_SIZE])
        a.close()
        with pytest.raises(ConnectionError):
            wire.recv_frame(b)
        b.close()


class TestGoldenBytes:
    """The exact 32-byte v2 frame prefix, pinned as a literal.

    This is the protocol's change detector: if an edit to
    ``serve/wire.py`` flips any of these bytes, old clients and new
    servers are speaking different protocols — bump ``VERSION`` and
    regenerate the pin deliberately. jsan's ``contract-drift`` rule
    cross-validates this literal against the wire module's ``MAGIC``/
    ``VERSION``/``struct`` constants (and fires on the wire module if
    the pin is ever deleted), so the two can only change together.
    ``V1_PREFIX_PIN`` keeps the RETIRED 24-byte v1 layout decodable
    forever (backward-compat contract, not the live protocol pin).
    """

    # PREFIX.pack(MAGIC, VERSION, KIND_REQ, hlen=4, blen=10,
    #             meta64=0x1122334455667788, meta32=0x99AABBCC,
    #             req_id=0x0F1E2D3C4B5A6978)
    GOLDEN_PREFIX = (b"RLSF"                              # magic
                     b"\x02"                              # version
                     b"\x01"                              # kind=REQ
                     b"\x04\x00"                          # hlen=4 LE
                     b"\x0a\x00\x00\x00"                  # blen=10 LE
                     b"\x88\x77\x66\x55\x44\x33\x22\x11"  # meta64 LE
                     b"\xcc\xbb\xaa\x99"                  # meta32 LE
                     b"\x78\x69\x5a\x4b\x3c\x2d\x1e\x0f") # req_id LE

    # the frozen v1 layout (no req_id field): decode-only since v2
    V1_PREFIX_PIN = (b"RLSF"                              # magic
                     b"\x01"                              # version
                     b"\x01"                              # kind=REQ
                     b"\x04\x00"                          # hlen=4 LE
                     b"\x0a\x00\x00\x00"                  # blen=10 LE
                     b"\x88\x77\x66\x55\x44\x33\x22\x11"  # meta64 LE
                     b"\xcc\xbb\xaa\x99")                 # meta32 LE

    def test_packed_prefix_matches_golden_bytes(self):
        frame = wire.pack_frame(wire.KIND_REQ, b"hdr!", b"body-bytes",
                                meta64=0x1122334455667788,
                                meta32=0x99AABBCC,
                                req_id=0x0F1E2D3C4B5A6978)
        assert len(self.GOLDEN_PREFIX) == wire.PREFIX_SIZE == 32
        assert frame[:wire.PREFIX_SIZE] == self.GOLDEN_PREFIX
        assert frame[wire.PREFIX_SIZE:] == b"hdr!" + b"body-bytes"

    def test_golden_bytes_parse_back_exactly(self):
        kind, hlen, blen, meta64, meta32, req_id = wire.unpack_prefix(
            self.GOLDEN_PREFIX)
        assert kind == wire.KIND_REQ
        assert (hlen, blen) == (4, 10)
        assert meta64 == 0x1122334455667788
        assert meta32 == 0x99AABBCC
        assert req_id == 0x0F1E2D3C4B5A6978

    def test_v1_pin_parses_back_exactly(self):
        assert len(self.V1_PREFIX_PIN) == wire.PREFIX_V1_SIZE == 24
        kind, hlen, blen, meta64, meta32, req_id = wire.unpack_prefix(
            self.V1_PREFIX_PIN)
        assert kind == wire.KIND_REQ
        assert (hlen, blen) == (4, 10)
        assert meta64 == 0x1122334455667788
        assert meta32 == 0x99AABBCC
        assert req_id == 0
