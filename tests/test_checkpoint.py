"""Checkpoint/resume tests (SURVEY.md §5 "Checkpoint / resume"): Orbax
roundtrip of TrainState, rotation, meta payloads, sharded restore,
experiment-level resume determinism, crc32 integrity sidecars, and
shrink-to-fit elastic restore (ISSUE 4)."""
import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training.train_state import TrainState

from rlgpuschedule_tpu.checkpoint import (Checkpointer, ElasticRestoreError,
                                          validate_shrunk_geometry)
from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.experiment import Experiment


def _mk_state(value: float, step: int = 0) -> TrainState:
    params = {"w": jnp.full((4, 3), value), "b": jnp.zeros((3,))}
    state = TrainState.create(apply_fn=lambda p, x: x, params=params,
                              tx=optax.adam(1e-3))
    return state.replace(step=step)


class TestCheckpointer:
    def test_roundtrip_params_opt_state_step_key_meta(self, tmp_path):
        key = jax.random.PRNGKey(7)
        with Checkpointer(str(tmp_path / "ck")) as ck:
            state = _mk_state(2.5, step=11)
            # advance optimizer state so opt_state restore is non-trivial
            grads = jax.tree.map(jnp.ones_like, state.params)
            state = state.apply_gradients(grads=grads)
            ck.save(12, state, key=key, meta={"lr": 1e-3, "gen": 3})
            ck.wait()

            restored, rkey, extra, meta = ck.restore(_mk_state(0.0), key * 0)
        assert int(restored.step) == int(state.step)
        assert np.allclose(restored.params["w"], state.params["w"])
        chex_leaves = jax.tree.leaves(restored.opt_state)
        orig_leaves = jax.tree.leaves(state.opt_state)
        for a, b in zip(chex_leaves, orig_leaves):
            assert np.allclose(a, b)
        assert np.array_equal(rkey, key)
        assert meta == {"lr": 1e-3, "gen": 3}

    def test_restore_without_key(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")) as ck:
            ck.save(0, _mk_state(1.0))
            ck.wait()
            restored, rkey, extra, meta = ck.restore(_mk_state(0.0))
        assert rkey is None and extra is None and meta == {}
        assert np.allclose(restored.params["w"], 1.0)

    def test_rotation_keeps_max_to_keep(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck"), max_to_keep=2) as ck:
            for s in range(4):
                ck.save(s, _mk_state(float(s), step=s))
            ck.wait()
            assert ck.all_steps() == [2, 3]
            assert ck.latest_step() == 3
            # restore a specific retained step
            restored, _, _, _ = ck.restore(_mk_state(0.0), step=2)
        assert np.allclose(restored.params["w"], 2.0)

    def test_restore_empty_raises(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")) as ck:
            with pytest.raises(FileNotFoundError):
                ck.restore(_mk_state(0.0))

    def test_sharded_state_roundtrips_onto_mesh(self, tmp_path):
        """Replicated-on-mesh params save and restore with shardings intact
        (SURVEY.md §5: 'sharded-aware')."""
        from rlgpuschedule_tpu.parallel import make_mesh
        from rlgpuschedule_tpu.parallel.mesh import replicated

        mesh = make_mesh(4)
        state = jax.device_put(_mk_state(3.0, step=5), replicated(mesh))
        with Checkpointer(str(tmp_path / "ck")) as ck:
            ck.save(5, state)
            ck.wait()
            template = jax.device_put(_mk_state(0.0), replicated(mesh))
            restored, _, _, _ = ck.restore(template)
        assert restored.params["w"].sharding == state.params["w"].sharding
        assert np.allclose(restored.params["w"], 3.0)


class TestChecksumSidecars:
    def test_wait_writes_sidecar_per_retained_step(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")) as ck:
            ck.save(1, _mk_state(1.0, 1))
            ck.save(2, _mk_state(2.0, 2))
            ck.wait()
            d = ck.directory
            for s in (1, 2):
                path = os.path.join(d, ".crc", f"{s}.json")
                assert os.path.exists(path)
                sums = json.load(open(path))
                # every payload file is covered, with plausible crc32s
                assert sums and all(isinstance(v, int) for v in
                                    sums.values())
                assert all(os.path.exists(os.path.join(d, str(s), rel))
                           for rel in sums)

    def test_rotation_prunes_stale_sidecars(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck"), max_to_keep=2) as ck:
            for s in range(4):
                ck.save(s, _mk_state(float(s), step=s))
                ck.wait()
            assert ck.all_steps() == [2, 3]
            crc_dir = os.path.join(ck.directory, ".crc")
            assert sorted(os.listdir(crc_dir)) == ["2.json", "3.json"]

    def test_force_overwrite_refreshes_sidecar(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")) as ck:
            ck.save(3, _mk_state(1.0, 3))
            ck.wait()
            before = json.load(open(
                os.path.join(ck.directory, ".crc", "3.json")))
            ck.save(3, _mk_state(9.0, 3), force=True)
            after = json.load(open(
                os.path.join(ck.directory, ".crc", "3.json")))
            # different params => different payload bytes => new crcs
            assert before != after
            restored, _, _, _ = ck.restore(_mk_state(0.0))
        assert np.allclose(restored.params["w"], 9.0)


class TestElasticRestore:
    """Shrink-to-fit restore (ISSUE 4 satellite): a checkpoint written at
    world size N restores onto N-k surviving shards — replicated state
    bit-exact, env-batched extras reduced to the surviving ranks' row
    blocks, untileable geometry refused up front."""

    def _save_world8(self, tmp_path, n_envs=8):
        from rlgpuschedule_tpu.parallel import make_mesh
        from rlgpuschedule_tpu.parallel.mesh import replicated

        mesh8 = make_mesh(8)
        state = jax.device_put(_mk_state(3.5, step=5), replicated(mesh8))
        extra = {"obs": np.arange(n_envs * 3, dtype=np.float32)
                 .reshape(n_envs, 3),
                 "done": np.arange(n_envs) % 2 == 0}
        key = jax.random.PRNGKey(11)
        ck = Checkpointer(str(tmp_path / "ck"))
        ck.save(5, state, key=key, extra=extra, meta={"it": 5})
        ck.wait()
        return ck, state, extra, key

    def test_shrink_is_bit_exact_on_surviving_shards(self, tmp_path):
        """8 shards -> 4 survivors: params/opt_state restore bit-exact
        (replicated state is world-size independent) and each surviving
        shard's env rows come back exactly as saved."""
        from rlgpuschedule_tpu.parallel import make_mesh
        from rlgpuschedule_tpu.parallel.mesh import replicated

        ck, state, extra, key = self._save_world8(tmp_path)
        surviving = [0, 2, 3, 5]
        mesh4 = make_mesh(4)
        restored, rkey, rextra, meta = ck.elastic_restore(
            _mk_state(0.0), old_world=8, surviving_ranks=surviving,
            mesh=mesh4, geometry=(1, 2, None, 8))
        assert meta == {"it": 5} and int(restored.step) == 5
        for a, b in zip(jax.tree.leaves(restored.params),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(restored.opt_state),
                        jax.tree.leaves(state.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(rkey), np.asarray(key))
        # env-batched extras: exactly the surviving shards' rows (1 row
        # per shard at 8 envs / 8 shards), order-preserving
        np.testing.assert_array_equal(rextra["obs"],
                                      extra["obs"][surviving])
        np.testing.assert_array_equal(rextra["done"],
                                      extra["done"][surviving])
        # state landed replicated on the SURVIVING mesh
        assert restored.params["w"].sharding.is_equivalent_to(
            replicated(mesh4), ndim=2)
        ck.close()

    def test_multi_row_shards_keep_contiguous_blocks(self, tmp_path):
        ck, _state, extra, _key = self._save_world8(tmp_path)
        # 8 envs over 4 saved shards = 2 rows per shard; survivors {0, 3}
        restored, _, rextra, _ = ck.elastic_restore(
            _mk_state(0.0), old_world=4, surviving_ranks=[0, 3])
        np.testing.assert_array_equal(rextra["obs"],
                                      extra["obs"][[0, 1, 6, 7]])
        ck.close()

    def test_untileable_shrink_fails_fast(self, tmp_path):
        """The fail-fast gate: a surviving batch the update geometry
        cannot tile raises ElasticRestoreError naming the shrink — not a
        shape error mid-step."""
        ck, *_ = self._save_world8(tmp_path)
        with pytest.raises(ElasticRestoreError,
                           match="shrink-to-fit.*does not tile"):
            ck.elastic_restore(_mk_state(0.0), old_world=8,
                               surviving_ranks=[0, 1, 2],
                               geometry=(1, 7, None, 8))
        ck.close()

    def test_shrunk_batch_must_divide_surviving_mesh(self, tmp_path):
        from rlgpuschedule_tpu.parallel import make_mesh

        ck, *_ = self._save_world8(tmp_path)
        with pytest.raises(ElasticRestoreError, match="data axis"):
            ck.elastic_restore(_mk_state(0.0), old_world=8,
                               surviving_ranks=[0, 1, 2],
                               mesh=make_mesh(2))
        ck.close()

    def test_validate_shrunk_geometry_passthrough_and_error(self):
        assert validate_shrunk_geometry(1, 2, None, 8, 6) == (1, 2, 24)
        with pytest.raises(ElasticRestoreError, match="was 64"):
            validate_shrunk_geometry(1, 7, None, 8, 3, old_n_envs=8)


class TestExperimentResume:
    def test_resume_continues_identically(self, tmp_path):
        """Train 2 iters, checkpoint, train 2 more; a fresh build restored
        from the checkpoint reproduces the same final params (fixed-seed
        determinism, SURVEY.md §4 'Determinism/regression')."""
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
            ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))
        exp = Experiment.build(cfg)
        exp.run(iterations=2)
        with Checkpointer(str(tmp_path / "ck")) as ck:
            exp.save_checkpoint(ck, meta={"iters": 2})
            ck.wait()
            exp.run(iterations=2)
            final = jax.tree.map(np.asarray, exp.train_state.params)

            exp2 = Experiment.build(cfg)
            meta = exp2.restore_checkpoint(ck)
        assert meta == {"iters": 2, "window_cursor": 0}
        exp2.run(iterations=2)
        final2 = jax.tree.map(np.asarray, exp2.train_state.params)
        for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(final2)):
            assert np.allclose(a, b, atol=1e-6)

    def test_streaming_resume_continues_identically(self, tmp_path):
        """Same determinism contract with window streaming on: the restore
        re-cuts the windows at the checkpointed cursor, so the resumed run
        trains on the same rotating windows as the uninterrupted one."""
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
            resample_every=1,
            ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))
        exp = Experiment.build(cfg)
        exp.run(iterations=3)
        assert exp.window_cursor > 0
        with Checkpointer(str(tmp_path / "ck")) as ck:
            exp.save_checkpoint(ck)
            ck.wait()
            exp.run(iterations=2)
            final = jax.tree.map(np.asarray, exp.train_state.params)

            exp2 = Experiment.build(cfg)
            meta = exp2.restore_checkpoint(ck)
        assert meta["window_cursor"] == exp2.window_cursor > 0
        exp2.run(iterations=2)
        final2 = jax.tree.map(np.asarray, exp2.train_state.params)
        for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(final2)):
            assert np.allclose(a, b, atol=1e-6)

    def test_force_overwrites_same_step(self, tmp_path):
        """Weight copies without an optimizer update (PBT exploit) land at
        the same step; force=True must overwrite, plain save must report the
        silent skip."""
        state = _mk_state(1.0, step=3)
        with Checkpointer(str(tmp_path / "ck")) as ck:
            assert ck.save(3, state, meta={"v": 1})
            ck.wait()
            assert not ck.save(3, _mk_state(9.0, step=3), meta={"v": 2})
            assert ck.save(3, _mk_state(9.0, step=3), meta={"v": 2},
                           force=True)
            ck.wait()
            restored, _, _, meta = ck.restore(_mk_state(0.0))
        assert np.allclose(restored.params["w"], 9.0)
        assert meta == {"v": 2}
