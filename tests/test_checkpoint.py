"""Checkpoint/resume tests (SURVEY.md §5 "Checkpoint / resume"): Orbax
roundtrip of TrainState, rotation, meta payloads, sharded restore, and
experiment-level resume determinism."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training.train_state import TrainState

from rlgpuschedule_tpu.checkpoint import Checkpointer
from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.experiment import Experiment


def _mk_state(value: float, step: int = 0) -> TrainState:
    params = {"w": jnp.full((4, 3), value), "b": jnp.zeros((3,))}
    state = TrainState.create(apply_fn=lambda p, x: x, params=params,
                              tx=optax.adam(1e-3))
    return state.replace(step=step)


class TestCheckpointer:
    def test_roundtrip_params_opt_state_step_key_meta(self, tmp_path):
        key = jax.random.PRNGKey(7)
        with Checkpointer(str(tmp_path / "ck")) as ck:
            state = _mk_state(2.5, step=11)
            # advance optimizer state so opt_state restore is non-trivial
            grads = jax.tree.map(jnp.ones_like, state.params)
            state = state.apply_gradients(grads=grads)
            ck.save(12, state, key=key, meta={"lr": 1e-3, "gen": 3})
            ck.wait()

            restored, rkey, extra, meta = ck.restore(_mk_state(0.0), key * 0)
        assert int(restored.step) == int(state.step)
        assert np.allclose(restored.params["w"], state.params["w"])
        chex_leaves = jax.tree.leaves(restored.opt_state)
        orig_leaves = jax.tree.leaves(state.opt_state)
        for a, b in zip(chex_leaves, orig_leaves):
            assert np.allclose(a, b)
        assert np.array_equal(rkey, key)
        assert meta == {"lr": 1e-3, "gen": 3}

    def test_restore_without_key(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")) as ck:
            ck.save(0, _mk_state(1.0))
            ck.wait()
            restored, rkey, extra, meta = ck.restore(_mk_state(0.0))
        assert rkey is None and extra is None and meta == {}
        assert np.allclose(restored.params["w"], 1.0)

    def test_rotation_keeps_max_to_keep(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck"), max_to_keep=2) as ck:
            for s in range(4):
                ck.save(s, _mk_state(float(s), step=s))
            ck.wait()
            assert ck.all_steps() == [2, 3]
            assert ck.latest_step() == 3
            # restore a specific retained step
            restored, _, _, _ = ck.restore(_mk_state(0.0), step=2)
        assert np.allclose(restored.params["w"], 2.0)

    def test_restore_empty_raises(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")) as ck:
            with pytest.raises(FileNotFoundError):
                ck.restore(_mk_state(0.0))

    def test_sharded_state_roundtrips_onto_mesh(self, tmp_path):
        """Replicated-on-mesh params save and restore with shardings intact
        (SURVEY.md §5: 'sharded-aware')."""
        from rlgpuschedule_tpu.parallel import make_mesh
        from rlgpuschedule_tpu.parallel.mesh import replicated

        mesh = make_mesh(4)
        state = jax.device_put(_mk_state(3.0, step=5), replicated(mesh))
        with Checkpointer(str(tmp_path / "ck")) as ck:
            ck.save(5, state)
            ck.wait()
            template = jax.device_put(_mk_state(0.0), replicated(mesh))
            restored, _, _, _ = ck.restore(template)
        assert restored.params["w"].sharding == state.params["w"].sharding
        assert np.allclose(restored.params["w"], 3.0)


class TestExperimentResume:
    def test_resume_continues_identically(self, tmp_path):
        """Train 2 iters, checkpoint, train 2 more; a fresh build restored
        from the checkpoint reproduces the same final params (fixed-seed
        determinism, SURVEY.md §4 'Determinism/regression')."""
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
            ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))
        exp = Experiment.build(cfg)
        exp.run(iterations=2)
        with Checkpointer(str(tmp_path / "ck")) as ck:
            exp.save_checkpoint(ck, meta={"iters": 2})
            ck.wait()
            exp.run(iterations=2)
            final = jax.tree.map(np.asarray, exp.train_state.params)

            exp2 = Experiment.build(cfg)
            meta = exp2.restore_checkpoint(ck)
        assert meta == {"iters": 2, "window_cursor": 0}
        exp2.run(iterations=2)
        final2 = jax.tree.map(np.asarray, exp2.train_state.params)
        for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(final2)):
            assert np.allclose(a, b, atol=1e-6)

    def test_streaming_resume_continues_identically(self, tmp_path):
        """Same determinism contract with window streaming on: the restore
        re-cuts the windows at the checkpointed cursor, so the resumed run
        trains on the same rotating windows as the uninterrupted one."""
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
            resample_every=1,
            ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))
        exp = Experiment.build(cfg)
        exp.run(iterations=3)
        assert exp.window_cursor > 0
        with Checkpointer(str(tmp_path / "ck")) as ck:
            exp.save_checkpoint(ck)
            ck.wait()
            exp.run(iterations=2)
            final = jax.tree.map(np.asarray, exp.train_state.params)

            exp2 = Experiment.build(cfg)
            meta = exp2.restore_checkpoint(ck)
        assert meta["window_cursor"] == exp2.window_cursor > 0
        exp2.run(iterations=2)
        final2 = jax.tree.map(np.asarray, exp2.train_state.params)
        for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(final2)):
            assert np.allclose(a, b, atol=1e-6)

    def test_force_overwrites_same_step(self, tmp_path):
        """Weight copies without an optimizer update (PBT exploit) land at
        the same step; force=True must overwrite, plain save must report the
        silent skip."""
        state = _mk_state(1.0, step=3)
        with Checkpointer(str(tmp_path / "ck")) as ck:
            assert ck.save(3, state, meta={"v": 1})
            ck.wait()
            assert not ck.save(3, _mk_state(9.0, step=3), meta={"v": 2})
            assert ck.save(3, _mk_state(9.0, step=3), meta={"v": 2},
                           force=True)
            ck.wait()
            restored, _, _, meta = ck.restore(_mk_state(0.0))
        assert np.allclose(restored.params["w"], 9.0)
        assert meta == {"v": 2}
