"""SLO burn-rate engine unit tests (ISSUE 20): spec validation, the
multi-window alert condition (fast-fire AND fast-clear), the rolling
error-budget gauge's recovery, the histogram-tail SLI, and the registry
pre-scrape collector hook that keeps every scrape fresh (including the
broken-collector containment contract)."""
import numpy as np
import pytest

from rlgpuschedule_tpu.obs import Registry
from rlgpuschedule_tpu.obs.slo import (DEFAULT_WINDOWS, SLOEngine,
                                       SLOSpec, histogram_sli)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class FakeBus:
    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append(dict(fields, kind=kind))


def make_engine(windows=((1.0, 1.0), (3.0, 1.0)), objective=0.9,
                budget_window_s=None):
    reg = Registry()
    clock = FakeClock()
    bus = FakeBus()
    eng = SLOEngine(reg, bus=bus, clock=clock)
    spec = SLOSpec("health", objective=objective, windows=windows,
                   budget_window_s=budget_window_s)
    state = {"bad": 0.0, "total": 0.0}
    eng.watch(spec, lambda: (state["bad"], state["total"]))
    return reg, clock, bus, eng, state


class TestSpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="objective"):
            SLOSpec("x", objective=1.0)
        with pytest.raises(ValueError, match="objective"):
            SLOSpec("x", objective=0.0)
        with pytest.raises(ValueError, match="window"):
            SLOSpec("x", objective=0.9, windows=())
        with pytest.raises(ValueError, match="bad window"):
            SLOSpec("x", objective=0.9, windows=((0.0, 1.0),))
        with pytest.raises(ValueError, match="budget_window_s"):
            SLOSpec("x", objective=0.9, budget_window_s=-1.0)

    def test_budget_window_defaults_to_longest(self):
        spec = SLOSpec("x", objective=0.99)
        assert spec.windows == DEFAULT_WINDOWS
        assert spec.budget_window == max(w for w, _ in DEFAULT_WINDOWS)
        assert SLOSpec("y", objective=0.99,
                       budget_window_s=7.0).budget_window == 7.0

    def test_duplicate_watch_rejected(self):
        _, _, _, eng, _ = make_engine()
        with pytest.raises(ValueError, match="already watched"):
            eng.watch(SLOSpec("health", objective=0.5), lambda: (0, 0))


class TestBurnAndBudget:
    def test_healthy_traffic_never_alerts(self):
        reg, clock, bus, eng, state = make_engine()
        for _ in range(10):
            clock.tick(0.5)
            state["total"] += 50
            eng.collect()
        st = eng.status()["health"]
        assert not st["alerting"] and st["alerts_total"] == 0
        assert st["budget_remaining"] == 1.0
        assert bus.events == []

    def test_alert_fires_clears_and_budget_recovers(self):
        reg, clock, bus, eng, state = make_engine()
        clock.tick(0.5)
        state["total"] += 50
        eng.collect()
        # incident: 40% bad over a 10% budget -> burn 4x on all windows
        for _ in range(3):
            clock.tick(0.5)
            state["total"] += 50
            state["bad"] += 20
            eng.collect()
        st = eng.status()["health"]
        assert st["alerting"] and st["alerts_total"] == 1
        assert all(b >= 1.0 for b in st["burn"].values())
        assert st["budget_remaining"] < 1.0
        alerts = [e for e in bus.events if e["kind"] == "slo_burn_alert"]
        assert len(alerts) == 1
        assert alerts[0]["slo"] == "health"
        assert set(alerts[0]["burns"]) == {"1s", "3s"}
        # bleeding stops: the 1s window un-trips within a second...
        clock.tick(1.0)
        state["total"] += 100
        eng.collect()
        st = eng.status()["health"]
        assert not st["alerting"]
        clears = [e for e in bus.events if e["kind"] == "slo_burn_clear"]
        assert len(clears) == 1
        # ...and the 3s budget window slides past the incident entirely
        for _ in range(4):
            clock.tick(1.0)
            state["total"] += 100
            eng.collect()
        st = eng.status()["health"]
        assert st["budget_remaining"] == 1.0
        # edges, not levels: still exactly one alert and one clear
        assert st["alerts_total"] == 1
        assert len([e for e in bus.events
                    if e["kind"] == "slo_burn_alert"]) == 1

    def test_all_windows_must_exceed_threshold(self):
        # long window poisoned by an old incident, short window clean:
        # the AND condition holds the alert back (fast-clear property)
        reg, clock, bus, eng, state = make_engine(
            windows=((1.0, 1.0), (10.0, 1.0)))
        clock.tick(0.5)
        state["total"] += 50
        eng.collect()                  # pre-incident baseline
        clock.tick(0.5)
        state["total"] += 50
        state["bad"] += 25
        eng.collect()                  # the incident
        for _ in range(4):
            clock.tick(1.0)
            state["total"] += 10       # light clean traffic
            eng.collect()
        st = eng.status()["health"]
        assert st["burn"]["10s"] >= 1.0     # long window still burning
        assert st["burn"]["1s"] < 1.0       # short window recovered
        assert not st["alerting"]

    def test_zero_traffic_window_suppresses_alert(self):
        _, clock, bus, eng, state = make_engine()
        clock.tick(0.5)
        eng.collect()                       # no traffic at all
        assert not eng.status()["health"]["alerting"]
        assert bus.events == []

    def test_gauges_render_through_collector_hook(self):
        reg, clock, bus, eng, state = make_engine()
        clock.tick(1.0)
        state["total"] += 10
        state["bad"] += 5
        # render() runs the collector -- no manual collect() call here
        text = reg.render()
        assert 'slo_burn_rate{slo="health",window="1s"}' in text
        assert 'slo_error_budget_remaining{slo="health"}' in text
        assert 'slo_burn_alerts_total{slo="health"}' in text

    def test_close_detaches_collector(self):
        reg, clock, bus, eng, state = make_engine()
        eng.close()
        clock.tick(1.0)
        state["total"] += 10
        state["bad"] += 10
        reg.render()
        assert not eng.status()["health"]["alerting"]
        assert eng.status()["health"]["burn"]["1s"] == 0.0


class TestHistogramSLI:
    def test_tail_fraction(self):
        reg = Registry()
        hist = reg.histogram("t_seconds", "x",
                             buckets=(0.1, 0.25, 1.0))
        sample = histogram_sli(hist, 0.25)
        for v in (0.05, 0.2, 0.2, 0.5, 2.0):
            hist.observe(v)
        bad, total = sample()
        assert (bad, total) == (2.0, 5.0)

    def test_target_between_bounds_is_conservative(self):
        reg = Registry()
        hist = reg.histogram("u_seconds", "x", buckets=(0.1, 1.0))
        sample = histogram_sli(hist, 0.5)    # snaps down to le=0.1
        hist.observe(0.3)                    # under target, over 0.1
        bad, total = sample()
        assert (bad, total) == (1.0, 1.0)

    def test_target_below_all_buckets_rejected(self):
        reg = Registry()
        hist = reg.histogram("v_seconds", "x", buckets=(0.1, 1.0))
        with pytest.raises(ValueError, match="below the"):
            histogram_sli(hist, 0.01)


class TestCollectorContainment:
    def test_broken_collector_is_counted_not_fatal(self):
        reg = Registry()

        def broken():
            raise RuntimeError("boom")

        reg.add_collector(broken)
        g = reg.gauge("ok_gauge", "x")
        g.set(3.0)
        text = reg.render()                  # must not raise
        assert "ok_gauge 3" in text
        assert reg.collector_errors >= 1

    def test_collect_is_reentrancy_guarded(self):
        reg = Registry()
        calls = []

        def nested():
            calls.append(1)
            reg.collect()                    # must not recurse

        reg.add_collector(nested)
        reg.collect()
        assert len(calls) == 1

    def test_add_remove_idempotent(self):
        reg = Registry()
        calls = []
        fn = lambda: calls.append(1)
        reg.add_collector(fn)
        reg.add_collector(fn)                # dedup
        reg.collect()
        assert len(calls) == 1
        reg.remove_collector(fn)
        reg.remove_collector(fn)             # no-op
        reg.collect()
        assert len(calls) == 1
