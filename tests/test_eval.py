"""Evaluation-harness tests (SURVEY.md §3.4): deterministic policy replay,
JCT table vs oracle baselines on identical windows."""
import dataclasses

import jax
import numpy as np
import pytest

from rlgpuschedule_tpu import eval as eval_lib
from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.env import stack_traces
from rlgpuschedule_tpu.env.env import EnvParams
from rlgpuschedule_tpu.traces import gen_poisson_trace
from rlgpuschedule_tpu.traces.records import ArrayTrace
from rlgpuschedule_tpu.experiment import (Experiment, load_source_trace,
                                          make_env_windows)
from rlgpuschedule_tpu.sim.core import SimParams, validate_trace
from rlgpuschedule_tpu.sim.schedulers import evaluate_baselines


def small_cfg(**kw):
    return dataclasses.replace(
        CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=12, horizon=96,
        n_nodes=4, gpus_per_node=4, queue_len=4,
        ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2), **kw)


@pytest.fixture(scope="module")
def exp():
    return Experiment.build(small_cfg())


@pytest.fixture(scope="module")
def windows(exp):
    src = validate_trace(exp.env_params.sim, load_source_trace(exp.cfg),
                         clamp=True)
    return make_env_windows(exp.cfg, src)


class TestReplay:
    def test_greedy_replay_completes_and_is_deterministic(self, exp, windows):
        traces = stack_traces(windows, exp.env_params)
        r1 = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, traces)
        r2 = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, traces)
        np.testing.assert_array_equal(np.asarray(r1.avg_jct),
                                      np.asarray(r2.avg_jct))
        # horizon is generous for 12 jobs: every window must complete
        assert (np.asarray(r1.n_done) == np.asarray(r1.n_valid)).all()
        assert np.isfinite(np.asarray(r1.avg_jct)).all()
        assert (np.asarray(r1.avg_jct) > 0).all()
        assert (np.asarray(r1.utilization) > 0).all()
        assert (np.asarray(r1.utilization) <= 1.0 + 1e-6).all()

    def test_random_replay_runs(self, exp, windows):
        traces = stack_traces(windows, exp.env_params)
        r = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                            exp.env_params, traces, policy="random",
                            key=jax.random.PRNGKey(7))
        assert (np.asarray(r.n_done) == np.asarray(r.n_valid)).all()

    def test_frozen_envs_stop_counting_steps(self, exp, windows):
        traces = stack_traces(windows, exp.env_params)
        r = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                            exp.env_params, traces, max_steps=400)
        # steps freeze at episode end, far below max_steps
        assert (np.asarray(r.steps) < 400).all()


class TestJctTable:
    def test_baseline_table_matches_single_window_oracle(self, exp, windows):
        table = eval_lib.baseline_jct_table(
            windows[:1], exp.cfg.n_nodes, exp.cfg.gpus_per_node,
            names=("fifo", "sjf"))
        direct = evaluate_baselines(windows[0], exp.cfg.n_nodes,
                                    exp.cfg.gpus_per_node,
                                    names=("fifo", "sjf"))
        for k in table:
            assert table[k] == pytest.approx(direct[k], rel=1e-6)

    def test_report_has_all_schedulers_and_ratio(self, exp, windows):
        report = eval_lib.jct_report(exp, windows=windows)
        for k in ("policy", "random", "fifo", "sjf", "srtf", "tiresias",
                  "vs_tiresias", "policy_completion"):
            assert k in report, k
        assert report["policy"] > 0
        assert report["policy_completion"] == pytest.approx(1.0)
        text = eval_lib.format_report(report)
        assert "tiresias" in text and "policy" in text

    def test_report_builds_own_windows_when_omitted(self, exp):
        report = eval_lib.jct_report(exp, include_random=False,
                                     baselines=("fifo",))
        assert "fifo" in report and "random" not in report

    def test_percentile_columns(self, exp, windows):
        """p50/p90/p99 tail columns (SURVEY.md §2 "avg/percentile JCT"):
        baseline percentiles must equal np.percentile over the oracle's
        own pooled per-job JCTs, and every completed row's p50 <= p99."""
        report = eval_lib.jct_report(exp, windows=windows,
                                     include_random=False,
                                     baselines=("fifo",),
                                     percentiles=(50, 99))
        pct = report["percentiles"]
        assert set(pct) == {"policy", "fifo"}
        jcts = eval_lib.baseline_jcts(windows, exp.cfg.n_nodes,
                                      exp.cfg.gpus_per_node, "fifo")
        assert pct["fifo"]["p50"] == pytest.approx(
            np.percentile(jcts, 50), rel=1e-9)
        assert pct["fifo"]["p99"] == pytest.approx(
            np.percentile(jcts, 99), rel=1e-9)
        for row in pct.values():
            assert row["p50"] <= row["p99"]
        # policy pooled mean must equal the report's avg (same jobs)
        text = eval_lib.format_report(report)
        assert "p99" in text

    def test_percentiles_guard_truncated_replay(self, exp, windows):
        """A max_steps-truncated replay drops the longest jobs, which
        would flatter the policy's tail columns — the row must be empty,
        not silently survivor-biased (baselines always complete)."""
        report = eval_lib.jct_report(exp, windows=windows,
                                     include_random=False,
                                     baselines=("fifo",),
                                     percentiles=(50, 99), max_steps=4)
        assert report["policy_completion"] < 1.0
        assert report["percentiles"]["policy"] == {}
        assert report["percentiles"]["fifo"]  # baselines still reported
        assert "—" in eval_lib.format_report(report)


class TestBacklogGate:
    @staticmethod
    def _fifo_backfill_apply_for(env_params):
        """The gate's fall-through as a hand policy: oldest FITTING queue
        slot (FIFO-with-backfill, the oracle baselines' admit rule),
        no-op only when nothing fits, preempt slots below the no-op so
        the layout mirrors _gate_to_fifo even on preemptive configs."""
        import jax.numpy as jnp
        sim = env_params.sim
        K, P, R = sim.queue_len, sim.n_placements, sim.preempt_len
        prefs = jnp.concatenate([
            jnp.arange(K * P, 0, -1, dtype=jnp.float32),
            jnp.full((R,), -1.0),
            jnp.array([0.5], jnp.float32),
        ])

        def apply(_params, obs, mask):
            return jnp.where(mask, prefs, -1e9), jnp.zeros(obs.shape[:-1])

        return apply

    def test_gate_always_on_equals_fifo_policy(self, exp, windows):
        # a gate deeper than the job table is always engaged, so gated
        # replay of ANY policy must equal the FIFO-backfill hand policy
        traces = stack_traces(windows, exp.env_params)
        gated = eval_lib.replay(
            exp.apply_fn, exp.train_state.params, exp.env_params, traces,
            backlog_gate=exp.env_params.sim.max_jobs + 1)
        head = eval_lib.replay(self._fifo_backfill_apply_for(exp.env_params),
                               {}, exp.env_params, traces)
        np.testing.assert_array_equal(np.asarray(gated.avg_jct),
                                      np.asarray(head.avg_jct))
        np.testing.assert_array_equal(np.asarray(gated.steps),
                                      np.asarray(head.steps))

    def test_gate_zero_matches_plain_greedy(self, exp, windows):
        traces = stack_traces(windows, exp.env_params)
        plain = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                                exp.env_params, traces)
        gated = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                                exp.env_params, traces, backlog_gate=0)
        np.testing.assert_array_equal(np.asarray(plain.avg_jct),
                                      np.asarray(gated.avg_jct))

    def test_gate_in_full_trace_stitch(self):
        # an always-on gate through the stitcher must track oracle FIFO
        # on an underloaded trace (the fall-through is the same
        # FIFO-with-backfill admit rule the oracle uses)
        from rlgpuschedule_tpu.sim.schedulers import run_baseline
        sim = SimParams(n_nodes=2, gpus_per_node=4, max_jobs=8, queue_len=4)
        params = EnvParams(sim=sim, obs_kind="flat", horizon=512)
        tr = validate_trace(sim, gen_poisson_trace(
            0.02, 16, seed=3, mean_duration=150.0, gpu_sizes=(1, 2),
            gpu_probs=(0.7, 0.3)), clamp=True)

        def junk_apply(_params, obs, mask):
            # adversarial policy: prefers the no-op; the gate must
            # override it everywhere
            import jax.numpy as jnp
            n = mask.shape[-1]
            prefs = jnp.arange(n, dtype=jnp.float32)
            return jnp.where(mask, prefs, -1e9), jnp.zeros(obs.shape[:-1])

        out = eval_lib.full_trace_replay(junk_apply, {}, params, tr,
                                         backlog_gate=sim.max_jobs + 1)
        bl = run_baseline(tr, 2, 4, "fifo")
        np.testing.assert_allclose(out["finish"][:16], bl.finish[:16],
                                   rtol=1e-4)

    def test_gate_rejected_for_hier(self):
        from rlgpuschedule_tpu.env.hier import HierParams
        hp = HierParams(n_pods=2, pod_sim=SimParams(
            n_nodes=2, gpus_per_node=4, max_jobs=8, queue_len=4))
        with pytest.raises(ValueError, match="backlog_gate"):
            eval_lib.replay(None, {}, hp, None, backlog_gate=4)

    def test_gate_rejected_for_random_policy(self, exp, windows):
        # ADVICE r3: gating the random control would silently turn it
        # into a FIFO hybrid, inflating the baseline — must refuse
        traces = stack_traces(windows, exp.env_params)
        with pytest.raises(ValueError, match="random"):
            eval_lib.replay(exp.apply_fn, exp.train_state.params,
                            exp.env_params, traces, policy="random",
                            backlog_gate=2)
        with pytest.raises(ValueError, match="random"):
            eval_lib.full_trace_replay(exp.apply_fn,
                                       exp.train_state.params,
                                       exp.env_params, windows[0],
                                       policy="random", backlog_gate=2)

    def test_gate_mid_threshold_switches_within_episode(self):
        """ADVICE r3: the CLI ships MID-range gates, but only the two
        extremes were pinned. A stranding premise cannot distinguish them
        (forced-progress liveness, ``sim/core.py`` ``rl_step``, places the
        queue head whenever the event horizon empties — every policy
        completes every feasible job). Instead drive the switch with a
        policy whose ORDERING differs from FIFO: newest-first (LIFO).
        Four full-cluster jobs run strictly serially, so per-job finish
        times are a pure function of who controls each placement:

        - pure LIFO places 0 (alone), then 3, 2, 1  → finish 50/200/150/100
        - always-FIFO places 0, 1, 2, 3             → finish 50/100/150/200
        - gate=3 (FIFO while backlog < 3): FIFO takes job 0 solo, LIFO
          owns the 3-deep backlog at t=50 (places 3), FIFO resumes on the
          2-deep remainder (1 then 2)               → finish 50/150/200/100

        Three distinct vectors ⇒ the gate demonstrably switched control
        mid-episode, both directions."""
        sim = SimParams(n_nodes=2, gpus_per_node=4, max_jobs=8,
                        queue_len=4)
        params = EnvParams(sim=sim, obs_kind="flat", horizon=256)
        J = sim.max_jobs
        submit = np.full(J, np.inf, np.float32)
        submit[:4] = [0.0, 10.0, 20.0, 30.0]
        duration = np.full(J, 1.0, np.float32)
        duration[:4] = 50.0
        gpus = np.zeros(J, np.int32)
        gpus[:4] = sim.capacity  # whole cluster: strictly serial
        tr = ArrayTrace(submit, duration, gpus, np.zeros(J, np.int32),
                        (np.arange(J) < 4))
        traces = stack_traces([tr], params)

        def newest_first(_params, obs, mask):
            import jax.numpy as jnp
            # highest feasible queue slot (queue is submit-sorted, so
            # highest = newest); no-op only when nothing fits
            prefs = jnp.arange(mask.shape[-1], dtype=jnp.float32) + 2.0
            prefs = prefs.at[-1].set(0.5)
            return jnp.where(mask, prefs, -1e9), jnp.zeros(obs.shape[:-1])

        def finishes(**kw):
            res, state = eval_lib.replay(newest_first, {}, params, traces,
                                         return_states=True, **kw)
            assert int(np.asarray(res.n_done)[0]) == 4  # liveness holds
            return np.asarray(state.sim.finish)[0, :4]

        np.testing.assert_allclose(finishes(), [50, 200, 150, 100],
                                   rtol=1e-5)
        np.testing.assert_allclose(
            finishes(backlog_gate=sim.max_jobs + 1), [50, 100, 150, 200],
            rtol=1e-5)
        np.testing.assert_allclose(finishes(backlog_gate=3),
                                   [50, 150, 200, 100], rtol=1e-5)


class TestStallGuard:
    """Eval-time breaker for the measured place↔preempt argmax deadlock
    (BASELINE.md config-1p: 1 of 8 drain windows froze at 87.7%
    completion). The guard masks preempt actions after the legitimate
    zero-dt activity bound; sub-threshold replay is untouched."""

    @staticmethod
    def _params():
        sim = SimParams(n_nodes=2, gpus_per_node=4, max_jobs=8,
                        queue_len=4, preempt_len=2)
        return EnvParams(sim=sim, obs_kind="flat", horizon=512)

    @staticmethod
    def _cycle_apply_for(env_params):
        """Adversarial policy that realizes the deadlock exactly as the
        trained policy did (BASELINE.md: `preempt3 → place126 → …` at
        clock 0.0): prefer any preempt, else any placement, no-op last —
        place→preempt→place forever at zero sim time."""
        import jax.numpy as jnp
        sim = env_params.sim
        K, P, R = sim.queue_len, sim.n_placements, sim.preempt_len
        prefs = jnp.concatenate([
            jnp.ones(K * P), jnp.full((R,), 2.0),
            jnp.zeros(1)]).astype(jnp.float32)

        def apply(_params, obs, mask):
            return jnp.where(mask, prefs, -1e9), jnp.zeros(obs.shape[:-1])

        return apply

    @staticmethod
    def _drain_traces(params):
        J = params.sim.max_jobs
        submit = np.full(J, np.inf, np.float32)
        submit[:6] = 0.0
        duration = np.full(J, 1.0, np.float32)
        duration[:6] = [60.0, 120.0, 90.0, 30.0, 45.0, 75.0]
        gpus = np.zeros(J, np.int32)
        gpus[:6] = [1, 2, 1, 1, 2, 1]
        tr = ArrayTrace(submit, duration, gpus, np.zeros(J, np.int32),
                        (np.arange(J) < 6))
        return stack_traces([tr], params)

    def test_guard_breaks_cycle_unguarded_deadlocks(self):
        params = self._params()
        apply = self._cycle_apply_for(params)
        traces = self._drain_traces(params)
        raw = eval_lib.replay(apply, {}, params, traces,
                              stall_guard=False)
        # the deadlock is real: zero completions across a 512-step replay
        assert int(np.asarray(raw.n_done)[0]) == 0
        guarded = eval_lib.replay(apply, {}, params, traces)
        assert int(np.asarray(guarded.n_done)[0]) == 6
        assert float(np.asarray(guarded.makespan)[0]) > 0.0

    def test_guard_breaks_cycle_in_full_trace_stitch(self):
        params = self._params()
        apply = self._cycle_apply_for(params)
        J = params.sim.max_jobs
        submit = np.full(J, np.inf, np.float32)
        submit[:6] = 0.0
        duration = np.full(J, 1.0, np.float32)
        duration[:6] = [60.0, 120.0, 90.0, 30.0, 45.0, 75.0]
        gpus = np.zeros(J, np.int32)
        gpus[:6] = [1, 2, 1, 1, 2, 1]
        tr = ArrayTrace(submit, duration, gpus, np.zeros(J, np.int32),
                        (np.arange(J) < 6))
        # unguarded would trip the stitcher's no-progress RuntimeError;
        # guarded completes every job (the function asserts finiteness)
        out = eval_lib.full_trace_replay(apply, {}, params, tr)
        assert out["n_jobs"] == 6
        assert np.isfinite(out["jct"]).all()

    def test_guard_leaves_subthreshold_replay_bit_identical(self):
        """A legitimate preemptive policy below the zero-dt bound must
        replay EXACTLY as without the guard (the guard only ever engages
        past _stall_threshold consecutive zero-dt steps)."""
        params = self._params()
        import jax.numpy as jnp
        sim = params.sim
        K, P, R = sim.queue_len, sim.n_placements, sim.preempt_len
        # place-everything policy: no preempt preference, no cycles
        prefs = jnp.concatenate([
            jnp.full((K * P,), 2.0), jnp.zeros(R),
            jnp.ones(1)]).astype(jnp.float32)

        def apply(_params, obs, mask):
            return jnp.where(mask, prefs, -1e9), jnp.zeros(obs.shape[:-1])

        traces = self._drain_traces(params)
        a = eval_lib.replay(apply, {}, params, traces, stall_guard=False)
        b = eval_lib.replay(apply, {}, params, traces, stall_guard=True)
        np.testing.assert_array_equal(np.asarray(a.avg_jct),
                                      np.asarray(b.avg_jct))
        np.testing.assert_array_equal(np.asarray(a.steps),
                                      np.asarray(b.steps))
        assert int(np.asarray(a.n_done)[0]) == 6


class TestFairnessReport:
    def test_tenant_table_and_jain(self):
        """fairness_report (config 3's quality metric): per-tenant avg JCT
        pooled over windows for policy + baselines, Jain index in (0, 1]."""
        cfg = dataclasses.replace(
            small_cfg(), reward_kind="fair", n_tenants=3)
        exp = Experiment.build(cfg)
        rep = eval_lib.fairness_report(exp, max_steps=64,
                                       baselines=("fifo", "sjf"))
        assert set(rep) == {"policy", "fifo", "sjf"}
        for row in rep.values():
            assert np.isfinite(row["avg_jct"]) and row["avg_jct"] > 0
            assert 0 < row["jain"] <= 1.0
            assert 0 < row["completion"] <= 1.0
            assert len(row["tenant_avg_jct"]) == 3
        # baselines' per-tenant means must average (job-weighted) to the
        # plain table's numbers on the same windows
        plain = eval_lib.baseline_jct_table(exp.windows, cfg.n_nodes,
                                            cfg.gpus_per_node,
                                            names=("fifo",))
        assert rep["fifo"]["avg_jct"] == pytest.approx(plain["fifo"],
                                                       rel=1e-6)
        out = eval_lib.format_fairness(rep)
        assert "Jain" in out and "policy" in out

    def test_tenant_ids_beyond_config_bins_still_pooled(self):
        """A real CSV maps each distinct user to a dense id unbounded by
        cfg.n_tenants; jobs of tenants >= n_tenants must still count
        (the pre-fix code silently dropped them from every row)."""
        cfg = dataclasses.replace(
            small_cfg(), reward_kind="fair", n_tenants=2)
        exp = Experiment.build(cfg)
        windows = []
        for w in exp.windows:
            t = np.asarray(w.tenant).copy()
            t[w.valid] = 2 + (np.flatnonzero(w.valid) % 3)   # ids 2..4
            windows.append(dataclasses.replace(w, tenant=t))
        rep = eval_lib.fairness_report(exp, windows=windows, max_steps=64,
                                       baselines=("fifo",))
        assert rep["fifo"]["completion"] == pytest.approx(1.0)
        assert len(rep["fifo"]["tenant_avg_jct"]) == 5
        plain = eval_lib.baseline_jct_table(windows, cfg.n_nodes,
                                            cfg.gpus_per_node,
                                            names=("fifo",))
        assert rep["fifo"]["avg_jct"] == pytest.approx(plain["fifo"],
                                                       rel=1e-6)


class TestFullTraceReplay:
    def test_single_window_matches_plain_replay(self):
        """With max_jobs >= the whole trace, the stitched replay is one
        window run to completion — its avg JCT must equal the plain frozen
        replay of the same trace."""
        cfg = dataclasses.replace(small_cfg(), window_jobs=40,
                                  horizon=400)
        exp = Experiment.build(cfg)
        src = exp.source.slice(0, 40)
        out = eval_lib.full_trace_replay(
            exp.apply_fn, exp.train_state.params, exp.env_params, src)
        assert out["windows"] == 1 and out["n_jobs"] == 40
        traces = stack_traces([src], exp.env_params)
        res = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                              exp.env_params, traces, max_steps=400)
        assert int(res.n_done[0]) == 40
        assert out["avg_jct"] == pytest.approx(float(res.avg_jct[0]),
                                               rel=1e-5)

    def test_residual_carry_covers_whole_trace(self):
        """A window table much smaller than the trace forces residual
        carry; every job must still finish, with sane JCT accounting."""
        cfg = small_cfg()
        exp = Experiment.build(cfg)
        src = load_source_trace(cfg, n_jobs=150, seed=7)
        src = validate_trace(exp.env_params.sim, src, clamp=True)
        out = eval_lib.full_trace_replay(
            exp.apply_fn, exp.train_state.params, exp.env_params, src)
        assert out["n_jobs"] == 150
        assert out["windows"] >= 150 // 12
        assert np.isfinite(out["jct"]).all() and (out["jct"] >= 0).all()
        # same trace through the native/oracle baselines: same order of
        # magnitude (the untrained policy is bad, not absurd — forced
        # placement keeps it live)
        table = evaluate_baselines(src, cfg.n_nodes, cfg.gpus_per_node,
                                   names=("fifo",))
        assert out["avg_jct"] < 50 * table["fifo"]

    def test_full_trace_report_table(self):
        cfg = dataclasses.replace(small_cfg(), window_jobs=16)
        exp = Experiment.build(cfg)
        report = eval_lib.full_trace_report(exp, max_jobs=60,
                                            percentiles=(50, 99))
        for k in ("policy", "random", "fifo", "sjf", "srtf", "tiresias",
                  "vs_tiresias"):
            assert k in report and np.isfinite(report[k])
        assert report["n_jobs"] == 60
        pct = report["percentiles"]
        assert set(pct) == {"policy", "random", "fifo", "sjf", "srtf",
                            "tiresias"}
        for row in pct.values():
            assert 0 < row["p50"] <= row["p99"]
        # baseline percentile must equal np.percentile over the oracle's
        # own per-job JCTs on the same sliced trace
        sliced = exp.source.slice(0, 60)
        sim = eval_lib.run_baseline(sliced, cfg.n_nodes, cfg.gpus_per_node,
                                    "fifo")
        finish = np.asarray(sim.finish, np.float64)
        done = np.asarray(sliced.valid) & np.isfinite(finish)
        ref = finish[done] - np.asarray(sliced.submit, np.float64)[done]
        assert pct["fifo"]["p50"] == pytest.approx(
            np.percentile(ref, 50), rel=1e-6)

    def test_full_trace_stitch_window_override(self):
        """A checkpoint can stitch-replay through a DEEPER window than it
        trained with (policy nets are max_jobs-independent); the deeper
        window must need fewer stitched windows, complete every job, and
        reject cluster-shape changes."""
        cfg = dataclasses.replace(small_cfg(), window_jobs=16)
        exp = Experiment.build(cfg)
        base = eval_lib.full_trace_report(exp, max_jobs=60,
                                          include_random=False,
                                          baselines=("fifo",))
        deep_params = dataclasses.replace(
            exp.env_params, sim=dataclasses.replace(exp.env_params.sim,
                                                    max_jobs=48))
        deep = eval_lib.full_trace_report(exp, max_jobs=60,
                                          include_random=False,
                                          baselines=("fifo",),
                                          env_params=deep_params)
        assert deep["n_jobs"] == base["n_jobs"] == 60
        assert deep["policy_windows"] < base["policy_windows"]
        assert np.isfinite(deep["policy"]) and deep["policy"] > 0
        bad = dataclasses.replace(
            exp.env_params, sim=dataclasses.replace(exp.env_params.sim,
                                                    queue_len=8))
        with pytest.raises(ValueError, match="stitch window"):
            eval_lib.full_trace_report(exp, env_params=bad)

    @staticmethod
    def _fifo_apply(_params, obs, mask):
        """Hand policy: lowest valid queue slot (FIFO-with-backfill),
        no-op only when nothing fits."""
        import jax.numpy as jnp
        n = mask.shape[-1]
        prefs = jnp.arange(n, 0, -1, dtype=jnp.float32).at[-1].set(0.5)
        return jnp.where(mask, prefs, -1e9), jnp.zeros(obs.shape[:-1])

    def test_stitched_fifo_tracks_oracle_fifo_underload(self):
        """On a trace with no sustained backlog the stitched replay of a
        hand-built FIFO policy must match the oracle FIFO sim per-job
        (regression for the round-3 stitching fix: the pre-fix code let
        an already-arrived cutoff go negative, moving global time
        BACKWARD and deleting queueing delay)."""
        from rlgpuschedule_tpu.sim.schedulers import run_baseline
        sim = SimParams(n_nodes=2, gpus_per_node=4, max_jobs=8, queue_len=4)
        params = EnvParams(sim=sim, obs_kind="flat", horizon=512)
        tr = validate_trace(sim, gen_poisson_trace(
            0.05, 24, seed=0, mean_duration=200.0, gpu_sizes=(1, 2),
            gpu_probs=(0.7, 0.3)), clamp=True)
        out = eval_lib.full_trace_replay(self._fifo_apply, {}, params, tr)
        bl = run_baseline(tr, 2, 4, "fifo")
        np.testing.assert_allclose(out["finish"][:24], bl.finish[:24],
                                   rtol=1e-4)

    def test_stitched_fifo_sane_under_overload(self):
        """Deep backlog (table ≪ outstanding jobs): the stitched number
        may only be PESSIMISTIC vs the full-visibility oracle FIFO (the
        window sees just the oldest table-full of jobs, so it cannot
        backfill like the oracle — a conservative, documented handicap),
        and must stay within ~1.5× of it. The pre-fix accounting instead
        went wildly OPTIMISTIC at scale (flat avg JCT while every true
        baseline grew linearly with the backlog)."""
        from rlgpuschedule_tpu.sim.schedulers import run_baseline
        sim = SimParams(n_nodes=2, gpus_per_node=4, max_jobs=8, queue_len=4)
        params = EnvParams(sim=sim, obs_kind="flat", horizon=512)
        tr = validate_trace(sim, gen_poisson_trace(
            0.3, 30, seed=0, mean_duration=200.0, gpu_sizes=(1, 2),
            gpu_probs=(0.7, 0.3)), clamp=True)
        out = eval_lib.full_trace_replay(self._fifo_apply, {}, params, tr)
        true_jct = run_baseline(tr, 2, 4, "fifo").avg_jct()
        assert out["avg_jct"] >= true_jct * 0.999
        assert out["avg_jct"] <= true_jct * 1.5

    def test_drain_completions_batches_deep_backlog_windows(self):
        """drain_completions=k must cut the deep-backlog window count
        roughly k× on an overloaded trace while landing in the SAME
        pessimistic band vs oracle FIFO (the batching changes seam
        granularity, not the carry approximation), completing every job.
        The default (1) is pinned bit-compatible with the recorded tables
        by the test above."""
        from rlgpuschedule_tpu.sim.schedulers import run_baseline
        sim = SimParams(n_nodes=2, gpus_per_node=4, max_jobs=8, queue_len=4)
        params = EnvParams(sim=sim, obs_kind="flat", horizon=512)
        tr = validate_trace(sim, gen_poisson_trace(
            0.3, 30, seed=0, mean_duration=200.0, gpu_sizes=(1, 2),
            gpu_probs=(0.7, 0.3)), clamp=True)
        one = eval_lib.full_trace_replay(self._fifo_apply, {}, params, tr)
        batched = eval_lib.full_trace_replay(self._fifo_apply, {}, params,
                                             tr, drain_completions=4)
        assert batched["n_jobs"] == 30
        assert np.isfinite(batched["jct"]).all()
        assert batched["windows"] < one["windows"] / 2
        true_jct = run_baseline(tr, 2, 4, "fifo").avg_jct()
        assert true_jct * 0.999 <= batched["avg_jct"] <= true_jct * 1.5
        # the result reports the EFFECTIVE batching: an over-ask is
        # clamped to max_jobs//2 (here 4), so both calls replay the same
        assert batched["drain_completions"] == 4
        over = eval_lib.full_trace_replay(self._fifo_apply, {}, params,
                                          tr, drain_completions=100)
        assert over["drain_completions"] == 4
        assert over["avg_jct"] == batched["avg_jct"]
        with pytest.raises(ValueError, match="drain_completions"):
            eval_lib.full_trace_replay(self._fifo_apply, {}, params, tr,
                                       drain_completions=0)
