"""Evaluation-harness tests (SURVEY.md §3.4): deterministic policy replay,
JCT table vs oracle baselines on identical windows."""
import dataclasses

import jax
import numpy as np
import pytest

from rlgpuschedule_tpu import eval as eval_lib
from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.env import stack_traces
from rlgpuschedule_tpu.experiment import (Experiment, load_source_trace,
                                          make_env_windows)
from rlgpuschedule_tpu.sim.core import validate_trace
from rlgpuschedule_tpu.sim.schedulers import evaluate_baselines


def small_cfg(**kw):
    return dataclasses.replace(
        CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=12, horizon=96,
        n_nodes=4, gpus_per_node=4, queue_len=4,
        ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2), **kw)


@pytest.fixture(scope="module")
def exp():
    return Experiment.build(small_cfg())


@pytest.fixture(scope="module")
def windows(exp):
    src = validate_trace(exp.env_params.sim, load_source_trace(exp.cfg),
                         clamp=True)
    return make_env_windows(exp.cfg, src)


class TestReplay:
    def test_greedy_replay_completes_and_is_deterministic(self, exp, windows):
        traces = stack_traces(windows, exp.env_params)
        r1 = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, traces)
        r2 = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, traces)
        np.testing.assert_array_equal(np.asarray(r1.avg_jct),
                                      np.asarray(r2.avg_jct))
        # horizon is generous for 12 jobs: every window must complete
        assert (np.asarray(r1.n_done) == np.asarray(r1.n_valid)).all()
        assert np.isfinite(np.asarray(r1.avg_jct)).all()
        assert (np.asarray(r1.avg_jct) > 0).all()
        assert (np.asarray(r1.utilization) > 0).all()
        assert (np.asarray(r1.utilization) <= 1.0 + 1e-6).all()

    def test_random_replay_runs(self, exp, windows):
        traces = stack_traces(windows, exp.env_params)
        r = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                            exp.env_params, traces, policy="random",
                            key=jax.random.PRNGKey(7))
        assert (np.asarray(r.n_done) == np.asarray(r.n_valid)).all()

    def test_frozen_envs_stop_counting_steps(self, exp, windows):
        traces = stack_traces(windows, exp.env_params)
        r = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                            exp.env_params, traces, max_steps=400)
        # steps freeze at episode end, far below max_steps
        assert (np.asarray(r.steps) < 400).all()


class TestJctTable:
    def test_baseline_table_matches_single_window_oracle(self, exp, windows):
        table = eval_lib.baseline_jct_table(
            windows[:1], exp.cfg.n_nodes, exp.cfg.gpus_per_node,
            names=("fifo", "sjf"))
        direct = evaluate_baselines(windows[0], exp.cfg.n_nodes,
                                    exp.cfg.gpus_per_node,
                                    names=("fifo", "sjf"))
        for k in table:
            assert table[k] == pytest.approx(direct[k], rel=1e-6)

    def test_report_has_all_schedulers_and_ratio(self, exp, windows):
        report = eval_lib.jct_report(exp, windows=windows)
        for k in ("policy", "random", "fifo", "sjf", "srtf", "tiresias",
                  "vs_tiresias", "policy_completion"):
            assert k in report, k
        assert report["policy"] > 0
        assert report["policy_completion"] == pytest.approx(1.0)
        text = eval_lib.format_report(report)
        assert "tiresias" in text and "policy" in text

    def test_report_builds_own_windows_when_omitted(self, exp):
        report = eval_lib.jct_report(exp, include_random=False,
                                     baselines=("fifo",))
        assert "fifo" in report and "random" not in report


class TestFullTraceReplay:
    def test_single_window_matches_plain_replay(self):
        """With max_jobs >= the whole trace, the stitched replay is one
        window run to completion — its avg JCT must equal the plain frozen
        replay of the same trace."""
        cfg = dataclasses.replace(small_cfg(), window_jobs=40,
                                  horizon=400)
        exp = Experiment.build(cfg)
        src = exp.source.slice(0, 40)
        out = eval_lib.full_trace_replay(
            exp.apply_fn, exp.train_state.params, exp.env_params, src)
        assert out["windows"] == 1 and out["n_jobs"] == 40
        traces = stack_traces([src], exp.env_params)
        res = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                              exp.env_params, traces, max_steps=400)
        assert int(res.n_done[0]) == 40
        assert out["avg_jct"] == pytest.approx(float(res.avg_jct[0]),
                                               rel=1e-5)

    def test_residual_carry_covers_whole_trace(self):
        """A window table much smaller than the trace forces residual
        carry; every job must still finish, with sane JCT accounting."""
        cfg = small_cfg()
        exp = Experiment.build(cfg)
        src = load_source_trace(cfg, n_jobs=150, seed=7)
        src = validate_trace(exp.env_params.sim, src, clamp=True)
        out = eval_lib.full_trace_replay(
            exp.apply_fn, exp.train_state.params, exp.env_params, src)
        assert out["n_jobs"] == 150
        assert out["windows"] >= 150 // 12
        assert np.isfinite(out["jct"]).all() and (out["jct"] >= 0).all()
        # same trace through the native/oracle baselines: same order of
        # magnitude (the untrained policy is bad, not absurd — forced
        # placement keeps it live)
        table = evaluate_baselines(src, cfg.n_nodes, cfg.gpus_per_node,
                                   names=("fifo",))
        assert out["avg_jct"] < 50 * table["fifo"]

    def test_full_trace_report_table(self):
        cfg = dataclasses.replace(small_cfg(), window_jobs=16)
        exp = Experiment.build(cfg)
        report = eval_lib.full_trace_report(exp, max_jobs=60)
        for k in ("policy", "fifo", "sjf", "srtf", "tiresias",
                  "vs_tiresias"):
            assert k in report and np.isfinite(report[k])
        assert report["n_jobs"] == 60
