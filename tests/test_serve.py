"""Serving subsystem tests (ISSUE 7): bucket coalescing properties,
compile-once-per-bucket sentinel gates, fleet-vs-sequential bit parity,
the eval↔serve shared-decision refactor guard, the scrape endpoint, and
the serve CLI."""
import dataclasses
import json
import urllib.request

import jax
import numpy as np
import pytest

from rlgpuschedule_tpu import decision
from rlgpuschedule_tpu import eval as eval_lib
from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.analysis.sentinels import (CompileCounter,
                                                  RecompileSentinelError,
                                                  assert_no_recompiles)
from rlgpuschedule_tpu.configs import CONFIGS, repro_tuple
from rlgpuschedule_tpu.env import env as env_lib
from rlgpuschedule_tpu.eval import EvalResult, pooled_avg_jct
from rlgpuschedule_tpu.experiment import Experiment, make_env_windows
from rlgpuschedule_tpu.obs import Registry, serve_http
from rlgpuschedule_tpu.serve import (InferenceEngine, PolicyServer,
                                     fleet_replay, fleet_windows,
                                     next_bucket, pad_batch,
                                     sample_fleet_faults, scatter_results,
                                     stack_requests)
from rlgpuschedule_tpu.serve import __main__ as serve_cli
from rlgpuschedule_tpu.serve.bench import (build_request_pool,
                                           default_request_sizes,
                                           run_bench)


def small_cfg(**kw):
    return dataclasses.replace(
        CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=12, horizon=96,
        n_nodes=4, gpus_per_node=4, queue_len=4,
        ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2), **kw)


@pytest.fixture(scope="module")
def exp():
    return Experiment.build(small_cfg())


@pytest.fixture(scope="module")
def exp_pre():
    """Preemptive action space — exercises the served stall gate."""
    return Experiment.build(
        dataclasses.replace(small_cfg(), name="pre", preempt_len=2))


def host_requests(exp, n=None):
    """First reset's per-env (obs, mask) request rows as host arrays."""
    _state, ts = env_lib.vec_reset(exp.env_params, exp.traces)
    obs = np.asarray(jax.device_get(ts.obs))
    mask = np.asarray(jax.device_get(ts.action_mask))
    n = obs.shape[0] if n is None else n
    return obs[:n], mask[:n]


class TestBucketing:
    def test_next_bucket_rounds_to_power_of_two(self):
        assert [next_bucket(n, 16) for n in (1, 2, 3, 5, 8, 9, 16)] == \
            [1, 2, 4, 8, 8, 16, 16]

    def test_next_bucket_refuses_bad_inputs(self):
        with pytest.raises(ValueError):
            next_bucket(0, 16)
        with pytest.raises(ValueError):
            next_bucket(17, 16)
        with pytest.raises(ValueError):
            next_bucket(3, 12)      # max_bucket not a power of two

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_pad_scatter_roundtrips_request_order(self, seed):
        """Property (satellite): for random request batches, stacking +
        padding + scattering returns every request's own row, in FIFO
        order, regardless of bucket slack."""
        rng = np.random.default_rng(seed)
        n = int(rng.integers(1, 16))
        bucket = next_bucket(n, 16)
        rows = [(rng.standard_normal(7).astype(np.float32),
                 rng.integers(0, 2, 9).astype(bool))
                for _ in range(n)]
        obs = stack_requests([r[0] for r in rows])
        mask = stack_requests([r[1] for r in rows])
        obs_p = pad_batch(obs, bucket)
        mask_p = pad_batch(mask, bucket, fill_mask_true=True)
        assert obs_p.shape[0] == mask_p.shape[0] == bucket
        # padded mask rows are all-legal (finite-logits contract)
        assert mask_p[n:].all()
        assert (obs_p[n:] == 0).all()
        # identity "dispatch": scatter returns each request's own row
        back = scatter_results(obs_p, n)
        for i in range(n):
            np.testing.assert_array_equal(back[i], rows[i][0])

    def test_pad_batch_refuses_overfull(self):
        with pytest.raises(ValueError):
            pad_batch(np.zeros((5, 2)), 4)

    def test_pad_fill_constants_hoisted_and_dtype_stable(self):
        """ISSUE 17 satellite: the pad-fill constant is built once per
        (rows, tail, dtype, mask) key, shared immutably across batches,
        and padding can never promote a leaf's dtype."""
        from rlgpuschedule_tpu.serve.batching import _pad_fill
        for dtype in (np.float32, np.float64, np.int32, np.bool_):
            x = np.ones((3, 2), dtype)
            out = pad_batch(x, 8)
            assert out.dtype == x.dtype, dtype       # never promotes
            assert out.shape == (8, 2)
        f1 = _pad_fill(5, (2,), np.dtype(np.float32), False)
        f2 = _pad_fill(5, (2,), np.dtype(np.float32), False)
        assert f1 is f2                              # hoisted, not rebuilt
        with pytest.raises((ValueError, RuntimeError)):
            f1[0] = 1.0                              # shared => immutable
        # bool + fill_mask_true pads all-legal; bool otherwise pads False
        m = pad_batch(np.zeros((2, 3), bool), 4, fill_mask_true=True)
        assert m[2:].all() and m.dtype == np.bool_
        z = pad_batch(np.ones((2, 3), bool), 4)
        assert not z[2:].any()
        # fill_mask_true on a float leaf still pads ZEROS (the flag only
        # flips boolean mask leaves)
        f = pad_batch(np.ones((2, 3), np.float32), 4, fill_mask_true=True)
        assert (f[2:] == 0).all() and f.dtype == np.float32

    def test_default_request_sizes_share_one_bucket(self):
        for bucket in (8, 16, 64):
            sizes = default_request_sizes(bucket)
            assert len(set(sizes)) == 3
            assert {next_bucket(s, bucket) for s in sizes} == {bucket}
        with pytest.raises(ValueError):
            default_request_sizes(4)


class TestSharedDecision:
    """Satellite 1 guard: the extracted decision helpers are bit-identical
    to the pre-refactor inline logic of eval.replay."""

    def test_policy_decision_is_inline_masked_argmax(self, exp):
        obs, mask = host_requests(exp)
        got = decision.policy_decision(
            exp.apply_fn, exp.train_state.params, obs, mask)
        logits, _ = exp.apply_fn(exp.train_state.params, obs, mask)
        want = jax.tree.map(lambda lg: np.argmax(np.asarray(lg), -1),
                            logits)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_gate_stalled_matches_pre_refactor_formulas(self, exp_pre):
        pre = decision.preempt_slice(exp_pre.env_params)
        thresh = decision.stall_threshold(exp_pre.env_params)
        assert pre is not None and int(np.asarray(pre).sum()) == 2
        rng = np.random.default_rng(0)
        A = exp_pre.env_params.n_actions
        mask_b = rng.integers(0, 2, (3, A)).astype(bool)
        stall_b = np.asarray([0, thresh, thresh + 3], np.int32)
        # the exact expressions replay()/full_trace_replay() inlined
        want_b = mask_b & ~((stall_b >= thresh)[:, None]
                            & np.asarray(pre)[None, :])
        got_b = decision.gate_stalled(mask_b, stall_b, thresh, pre)
        np.testing.assert_array_equal(np.asarray(got_b), want_b)
        mask_1 = mask_b[0]
        for s in (0, thresh):
            want_1 = mask_1 & ~((np.int32(s) >= thresh) & np.asarray(pre))
            got_1 = decision.gate_stalled(mask_1, np.int32(s), thresh, pre)
            np.testing.assert_array_equal(np.asarray(got_1), want_1)

    def test_eval_replay_still_deterministic_after_refactor(self, exp):
        r1 = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, exp.traces)
        r2 = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, exp.traces)
        np.testing.assert_array_equal(np.asarray(r1.avg_jct),
                                      np.asarray(r2.avg_jct))
        assert (np.asarray(r1.n_done) == np.asarray(r1.n_valid)).all()


class TestInferenceEngine:
    def test_served_actions_match_eval_decision(self, exp):
        """serve↔eval no-drift: the engine's dispatched action for an
        observation is bit-identical to what eval's decision rule
        produces for the same observation."""
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        actions, bucket = engine.decide(obs, mask)
        assert bucket == 2
        want = decision.policy_decision(
            exp.apply_fn, exp.train_state.params, obs, mask)
        np.testing.assert_array_equal(np.asarray(actions),
                                      np.asarray(want))

    def test_batch_composition_invariance(self, exp):
        """A request's action does not depend on who it was batched
        with (padding rows included)."""
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        together, _ = engine.decide(obs, mask)
        for i in range(obs.shape[0]):
            alone, _ = engine.decide(obs[i:i + 1], mask[i:i + 1])
            np.testing.assert_array_equal(np.asarray(alone)[0],
                                          np.asarray(together)[i])

    def test_stall_gate_masks_preempts_when_served(self, exp_pre):
        obs, mask = host_requests(exp_pre)
        mask = np.ones_like(mask)       # every action legal
        engine = InferenceEngine(exp_pre.apply_fn,
                                 exp_pre.train_state.params,
                                 exp_pre.env_params, max_bucket=8)
        thresh = decision.stall_threshold(exp_pre.env_params)
        pre = np.asarray(decision.preempt_slice(exp_pre.env_params))
        stalled = np.full(obs.shape[0], thresh, np.int32)
        actions, _ = engine.decide(obs, mask, stalled)
        assert not pre[np.asarray(actions)].any(), \
            "stalled requests must never be served a preempt action"
        # control: the same requests un-stalled see the ungated mask
        calm, _ = engine.decide(obs, mask, np.zeros_like(stalled))
        want = decision.policy_decision(
            exp_pre.apply_fn, exp_pre.train_state.params, obs, mask)
        np.testing.assert_array_equal(np.asarray(calm), np.asarray(want))

    def test_compile_once_per_bucket(self, exp):
        """The sentinel gate (satellite): two+ loads of the same bucket
        size must not retrace — across DIFFERENT request counts."""
        obs, mask = host_requests(exp)
        pool_obs = np.concatenate([obs] * 4)     # 8 rows to draw from
        pool_mask = np.concatenate([mask] * 4)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        engine.warmup(obs[0], mask[0], buckets=(8,))
        with assert_no_recompiles("warmed serve bucket"):
            for n in (5, 7, 8, 6, 5):
                engine.decide(pool_obs[:n], pool_mask[:n])
        assert engine.post_warmup_recompiles == 0

    def test_new_bucket_compiles_and_is_blessed(self, exp):
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        engine.warmup(obs[0], mask[0], buckets=(2,))
        with CompileCounter() as c:
            engine.decide(np.concatenate([obs] * 2),
                          np.concatenate([mask] * 2))  # bucket 4: first use
        assert c.total > 0
        assert engine.post_warmup_recompiles == 0      # blessed warmup
        assert set(engine.warmed_buckets) == {2, 4}

    def test_recompile_on_warmed_bucket_raises_when_strict(self, exp):
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8,
                                 strict=True)
        # claim bucket 4 is warm without ever compiling it: the next
        # dispatch at 4 MUST trace -> the alarm path fires
        engine._warmed.add(4)
        with pytest.raises(RecompileSentinelError):
            engine.decide(np.concatenate([obs] * 2),
                          np.concatenate([mask] * 2))
        assert engine.post_warmup_recompiles == 1

    def test_warmup_all_buckets_covers_every_size(self, exp):
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=4)
        done = engine.warmup(obs[0], mask[0])
        assert done == (1, 2, 4)
        with assert_no_recompiles("fully warmed engine"):
            for n in (1, 2):
                engine.decide(obs[:n], mask[:n])


class TestPolicyServer:
    def test_submit_pump_scatters_in_fifo_order(self, exp):
        obs, mask = host_requests(exp)
        registry = Registry()
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8,
                                 registry=registry)
        server = PolicyServer(engine, registry=registry)
        futs = [server.submit(obs[i % obs.shape[0]],
                              mask[i % mask.shape[0]]) for i in range(5)]
        assert server.pump() == 5
        want, _ = engine.decide(
            np.stack([obs[i % obs.shape[0]] for i in range(5)]),
            np.stack([mask[i % mask.shape[0]] for i in range(5)]))
        for i, f in enumerate(futs):
            res = f.result(timeout=10)
            np.testing.assert_array_equal(np.asarray(res.action),
                                          np.asarray(want)[i])
            assert res.latency_s > 0
        assert server.pump() == 0           # queue drained
        snap = server.slo_snapshot()
        assert snap["requests"] == 5 and snap["dispatches"] == 1
        assert snap["latency_p50_ms"] > 0
        assert snap["batch_occupancy_mean"] == pytest.approx(5 / 8)
        rendered = registry.render()
        assert "serve_requests_total 5" in rendered
        assert "serve_decision_latency_p99_ms" in rendered

    def test_pump_max_wait_dispatches_partial_after_deadline(self, exp):
        import time
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        engine.warmup(obs[0], mask[0], buckets=(2, 8))
        server = PolicyServer(engine)
        futs = [server.submit(obs[i], mask[i]) for i in range(2)]
        t0 = time.perf_counter()
        assert server.pump(max_wait_s=0.2) == 2   # partial bucket, held
        waited = time.perf_counter() - t0
        assert waited >= 0.15                      # sat out the deadline
        assert all(f.result(timeout=10) for f in futs)
        # a FULL bucket never waits on the deadline
        futs = [server.submit(obs[i % obs.shape[0]],
                              mask[i % mask.shape[0]]) for i in range(8)]
        t0 = time.perf_counter()
        assert server.pump(max_wait_s=30.0) == 8
        assert time.perf_counter() - t0 < 5.0
        assert all(f.result(timeout=10) for f in futs)

    def test_pump_max_wait_cut_short_when_bucket_fills(self, exp):
        import threading
        import time
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=2)
        engine.warmup(obs[0], mask[0], buckets=(1, 2))
        server = PolicyServer(engine)
        server.submit(obs[0], mask[0])
        late = threading.Timer(0.1, server.submit, (obs[1], mask[1]))
        late.start()
        try:
            t0 = time.perf_counter()
            assert server.pump(max_wait_s=60.0) == 2   # filled mid-wait
            assert time.perf_counter() - t0 < 30.0
        finally:
            late.cancel()
        assert server.pump() == 0

    def test_max_wait_ctor_knob_validates_and_reaches_pump(self, exp):
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        with pytest.raises(ValueError, match="max_wait_s"):
            PolicyServer(engine, max_wait_s=-1.0)
        server = PolicyServer(engine, max_wait_s=0.0)   # explicit no-wait
        server.submit(obs[0], mask[0])
        assert server.pump() == 1                       # ctor default used

    def test_background_dispatcher_serves_and_stops(self, exp):
        obs, mask = host_requests(exp)
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        engine.warmup(obs[0], mask[0], buckets=(1, 2, 4, 8))
        server = PolicyServer(engine)
        server.start()
        try:
            futs = [server.submit(obs[i % 2], mask[i % 2])
                    for i in range(12)]
            results = [f.result(timeout=30) for f in futs]
            assert len(results) == 12
        finally:
            server.stop()
        # a stopped server is back in inline mode — submit+pump works
        fut = server.submit(obs[0], mask[0])
        assert server.pump() == 1
        assert fut.result(timeout=10) is not None


class ArgmaxEngine:
    """Deterministic host-only engine: per-row argmax over obs. Returns
    a FRESH array per dispatch (so plane-parity is a real comparison,
    not view aliasing)."""

    def __init__(self, max_bucket=8):
        self.max_bucket = max_bucket
        self.post_warmup_recompiles = 0

    def bucket_for(self, n):
        return next_bucket(n, self.max_bucket)

    def decide(self, obs, mask, stall=None):
        a = np.argmax(np.asarray(obs), axis=-1).astype(np.int32)
        return a, self.bucket_for(a.shape[0])


class RewarmEngine(ArgmaxEngine):
    """ArgmaxEngine exposing the router's re-warm listener hook."""

    def __init__(self, max_bucket=8):
        super().__init__(max_bucket)
        self.listeners = []

    def add_rewarm_listener(self, cb):
        self.listeners.append(cb)


def request_rows(n, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal(6).astype(np.float32),
             rng.integers(0, 2, 9).astype(bool) | True)
            for _ in range(n)]


class TestArenaDataPlane:
    """ISSUE 17 tentpole: the preallocated batch arena — zero
    steady-state ndarray construction, plane parity, zero-copy scatter
    views, shape policing at the door, and the estimator re-warm
    reset."""

    def test_plane_parity_bit_identical(self):
        rows = request_rows(40)
        actions = {}
        for plane in ("legacy", "arena"):
            server = PolicyServer(ArgmaxEngine(8), data_plane=plane,
                                  example_obs=rows[0][0],
                                  example_mask=rows[0][1])
            futs = [server.submit(o, m) for o, m in rows]
            while server.pump():
                pass
            actions[plane] = np.stack(
                [np.asarray(f.result(timeout=10).action) for f in futs])
            server.close()
        np.testing.assert_array_equal(actions["legacy"], actions["arena"])

    def test_zero_steady_state_allocations(self):
        """THE perf contract: after warmup, a full-bucket round on the
        arena plane calls none of the numpy batch constructors and
        allocates no new slabs; the legacy plane's nonzero count is the
        churn being deleted (and proves the counter sees through)."""
        from rlgpuschedule_tpu.serve.bench import StubEngine, _AllocCounter
        rows = request_rows(16)
        counts = {}
        for plane in ("legacy", "arena"):
            reg = Registry()
            server = PolicyServer(StubEngine(8), registry=reg,
                                  data_plane=plane,
                                  example_obs=rows[0][0],
                                  example_mask=rows[0][1])

            def one_round():
                for i in range(8):
                    server.submit(*rows[i % len(rows)])
                return server.pump()

            for _ in range(4):                      # warmup: ring growth
                one_round()
            slabs_before = server.arena_stats()["slab_allocs"]
            served = 0
            with _AllocCounter() as counter:
                for _ in range(32):
                    served += one_round()
            counts[plane] = counter.calls
            assert served == 32 * 8                  # conservation
            assert (server.arena_stats()["slab_allocs"]
                    == slabs_before)                 # no slab growth
            server.close()
        assert counts["arena"] == 0
        assert counts["legacy"] > 0

    def test_scatter_returns_views_into_actions_buffer(self):
        """Zero-copy tail: when the engine's actions don't alias the
        request slabs (the device-fetch shape) and rows are non-scalar,
        scatter hands back VIEWS of the actions buffer, not per-row
        copies. (Scalar-per-request actions degenerate to numpy scalars
        — there is no 0-d view to take.)"""
        class VecActionEngine(ArgmaxEngine):
            def __init__(self, max_bucket=8):
                super().__init__(max_bucket)
                self.buf = np.zeros((max_bucket, 2), np.int32)

            def decide(self, obs, mask, stall=None):
                n = np.asarray(obs).shape[0]
                return self.buf[:n], self.bucket_for(n)

        rows = request_rows(8)
        engine = VecActionEngine(8)
        server = PolicyServer(engine, data_plane="arena",
                              example_obs=rows[0][0],
                              example_mask=rows[0][1])
        futs = [server.submit(o, m) for o, m in rows]
        assert server.pump() == 8
        for f in futs:
            action = np.asarray(f.result(timeout=10).action)
            assert action.shape == (2,)
            assert np.may_share_memory(action, engine.buf)
        server.close()

    def test_submit_rejects_wrong_row_shape_at_the_door(self):
        rows = request_rows(2)
        server = PolicyServer(ArgmaxEngine(8), data_plane="arena",
                              example_obs=rows[0][0],
                              example_mask=rows[0][1])
        with pytest.raises(ValueError):
            server.submit(np.zeros(7, np.float32), rows[0][1])
        with pytest.raises(ValueError):
            server.submit(rows[0][0], np.ones(4, bool))
        # the arena survives the rejections: a good row still serves
        fut = server.submit(*rows[1])
        assert server.pump() == 1
        assert fut.result(timeout=10) is not None
        server.close()

    def test_arena_stats_surface(self):
        rows = request_rows(1)
        server = PolicyServer(ArgmaxEngine(8), data_plane="arena",
                              example_obs=rows[0][0],
                              example_mask=rows[0][1])
        stats = server.arena_stats()
        assert stats["data_plane"] == "arena"
        assert stats["blocks"] >= 1
        assert stats["rows"] == stats["blocks"] * 8
        # one counted allocation per slab array: obs leaves + mask
        # leaves + the stall vector + the req-id lane, per block
        assert stats["slab_allocs"] == stats["blocks"] * 4
        legacy = PolicyServer(ArgmaxEngine(8), data_plane="legacy")
        assert legacy.arena_stats()["blocks"] == 0
        legacy.close()
        server.close()

    def test_rewarm_listener_resets_service_time_estimator(self):
        """ISSUE 17 satellite: a fleet re-warm (weight swap /
        set_active) resets the learned service time — admission returns
        to cold-admit instead of shedding on the stale estimate."""
        rows = request_rows(8)
        engine = RewarmEngine(8)
        server = PolicyServer(engine, data_plane="arena",
                              example_obs=rows[0][0],
                              example_mask=rows[0][1])
        assert len(engine.listeners) == 1            # hook registered
        for o, m in rows:
            server.submit(o, m)
        assert server.pump() == 8
        assert server.service_time_s() is not None   # learned
        engine.listeners[0]()                        # fleet re-warmed
        assert server.service_time_s() is None       # forgotten
        server.close()


class TestRequestCausality:
    """ISSUE 20 tentpole: the 64-bit request id threads submit ->
    arena slot -> dispatch -> scatter -> result, and every submitted id
    resolves exactly once as served, shed, or failed."""

    def test_minted_ids_unique_salted_and_on_results(self):
        rows = request_rows(8)
        server = PolicyServer(ArgmaxEngine(8), data_plane="arena",
                              example_obs=rows[0][0],
                              example_mask=rows[0][1])
        futs = [server.submit(o, m) for o, m in rows]
        assert server.pump() == 8
        ids = [f.result(timeout=10).req_id for f in futs]
        assert len(set(ids)) == 8
        salts = {i >> 40 for i in ids}
        assert len(salts) == 1                   # same rank+pid salt
        assert all(0 < i < (1 << 63) for i in ids)   # int64-safe
        server.close()

    @pytest.mark.parametrize("plane", ["legacy", "arena"])
    def test_explicit_id_round_trips(self, plane):
        rows = request_rows(1)
        server = PolicyServer(ArgmaxEngine(8), data_plane=plane,
                              example_obs=rows[0][0],
                              example_mask=rows[0][1])
        fut = server.submit(*rows[0], req_id=0x123456789ABCDEF)
        server.pump()
        assert fut.result(timeout=10).req_id == 0x123456789ABCDEF
        server.close()

    def test_conservation_every_id_resolves_exactly_once(self, tmp_path):
        """The property the ci.sh chaos gate asserts at scale: over a
        run with served, failed, and in-queue-expired requests, the
        merged instant stream resolves every enqueued id exactly once
        as served | shed | dispatch_failed."""
        from rlgpuschedule_tpu.obs import EventBus, Tracer
        from rlgpuschedule_tpu.obs.events import merge_dir

        class FlakyEngine(ArgmaxEngine):
            def __init__(self, max_bucket=8):
                super().__init__(max_bucket)
                self.dispatches = 0

            def decide(self, obs, mask, stall=None):
                self.dispatches += 1
                if self.dispatches == 2:
                    raise RuntimeError("injected fault")
                return super().decide(obs, mask, stall)

        bus = EventBus(str(tmp_path), rank=0, name="serve")
        server = PolicyServer(FlakyEngine(8), data_plane="arena",
                              example_obs=request_rows(1)[0][0],
                              example_mask=request_rows(1)[0][1],
                              tracer=Tracer(bus, enabled=True))
        rows = request_rows(24)
        futs = [server.submit(o, m) for o, m in rows[:8]]
        assert server.pump() == 8                    # dispatch 1: served
        futs += [server.submit(o, m) for o, m in rows[8:16]]
        with pytest.raises(RuntimeError):
            server.pump()                            # dispatch 2: fails
        for f in futs[8:16]:
            with pytest.raises(RuntimeError):
                f.result(timeout=10)
        # round 3: half shed at admission (deadline below any predicted
        # wait), half admitted but left to expire in the queue
        futs += [server.submit(o, m, deadline_s=1e-9)
                 for o, m in rows[16:20]]
        futs += [server.submit(o, m, deadline_s=0.01)
                 for o, m in rows[20:24]]
        import time as _time
        _time.sleep(0.05)
        server.pump()                                # expire the admitted ones
        from rlgpuschedule_tpu.serve.batching import DeadlineSheddedError
        for f in futs[16:]:
            with pytest.raises(DeadlineSheddedError):
                f.result(timeout=10)
        server.close()
        bus.close()

        pts = [e for e in merge_dir(str(tmp_path))
               if e.get("kind") == "span_point"]
        enq = [e["attrs"]["req_id"] for e in pts
               if e.get("span") == "enqueue"]
        served = [r for e in pts if e.get("span") == "served"
                  for r in e["attrs"]["req_ids"]]
        shed = [(e["attrs"]["req_id"], e["attrs"]["reason"])
                for e in pts if e.get("span") == "shed"]
        failed = [r for e in pts if e.get("span") == "dispatch_failed"
                  for r in e["attrs"]["req_ids"]]
        # the ci.sh gate's ledger: submitted = enqueued + admission-shed
        # (admission sheds never reach the queue so never emit enqueue);
        # resolved = served + shed (any reason) + dispatch_failed
        submitted = enq + [r for r, why in shed if why == "admission"]
        resolved = served + failed + [r for r, _ in shed]
        assert len(submitted) == len(set(submitted)) == 24
        assert sorted(resolved) == sorted(submitted)  # exactly once each
        assert (len(served), len(failed), len(shed)) == (8, 8, 8)
        reasons = {why for _, why in shed}
        assert reasons == {"admission", "expired"}    # both shed paths hit

    def test_shed_exception_and_instant_carry_req_id(self, tmp_path):
        from rlgpuschedule_tpu.obs import EventBus, Tracer
        from rlgpuschedule_tpu.obs.events import merge_dir
        from rlgpuschedule_tpu.serve.batching import DeadlineSheddedError
        bus = EventBus(str(tmp_path), rank=0, name="serve")
        rows = request_rows(2)
        server = PolicyServer(ArgmaxEngine(8), data_plane="arena",
                              example_obs=rows[0][0],
                              example_mask=rows[0][1],
                              tracer=Tracer(bus, enabled=True))
        fut = server.submit(*rows[0], deadline_s=1e-6, req_id=777)
        import time as _time
        _time.sleep(0.005)
        server.pump()
        with pytest.raises(DeadlineSheddedError) as ei:
            fut.result(timeout=10)
        assert ei.value.req_id == 777
        server.close()
        bus.close()
        sheds = [e for e in merge_dir(str(tmp_path))
                 if e.get("kind") == "span_point"
                 and e.get("span") == "shed"]
        assert [e["attrs"]["req_id"] for e in sheds] == [777]

    def test_p99_exemplar_rides_snapshot(self):
        rows = request_rows(16)
        server = PolicyServer(ArgmaxEngine(8), data_plane="arena",
                              example_obs=rows[0][0],
                              example_mask=rows[0][1])
        futs = [server.submit(o, m) for o, m in rows]
        while server.pump():
            pass
        ids = {f.result(timeout=10).req_id for f in futs}
        snap = server.slo_snapshot()
        assert snap["latency_p99_exemplar_req_id"] in ids
        assert "slo" in snap                     # engine status attached
        server.close()


class TestBench:
    def test_run_bench_zero_recompiles_across_sizes(self, exp):
        registry = Registry()
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8,
                                 registry=registry)
        server = PolicyServer(engine, registry=registry)
        pool = build_request_pool(exp.apply_fn, exp.train_state.params,
                                  exp.env_params, exp.traces, steps=2)
        assert len(pool) == 3 * exp.cfg.n_envs
        report = run_bench(engine, server, pool, rounds=6,
                           request_sizes=(5, 7, 8))
        assert report["post_warmup_recompiles"] == 0
        assert report["buckets"] == [8]
        assert report["requests"] == 2 * (5 + 7 + 8)
        assert report["decisions_per_s"] > 0
        assert report["latency_p50_ms"] > 0
        assert report["latency_p99_ms"] >= report["latency_p50_ms"]

    def test_run_host_path_gates_and_report_shape(self):
        """BENCH_r09's driver: both in-process arms present, the arena
        arm allocation-free and slab-flat, conservation structural, the
        stub engine recompile-free. (The >= 2x speedup itself is gated
        on the recorded BENCH run, not a CI-noise-sensitive assert.)"""
        from rlgpuschedule_tpu.serve.bench import run_host_path
        pool = request_rows(16)
        report = run_host_path(pool, max_bucket=8, rounds=40,
                               warmup_rounds=4)
        assert [a["data_plane"] for a in report["arms"]] == \
            ["legacy", "arena"]
        arena, legacy = report["arms"][1], report["arms"][0]
        assert arena["alloc_calls"] == 0
        assert arena["allocs_per_batch"] == 0
        assert arena["steady_state_slab_allocs"] == 0
        assert legacy["alloc_calls"] > 0
        for arm in report["arms"]:
            assert arm["conservation_ok"]
            assert arm["requests"] == 40 * 8
            assert arm["served"] == 40 * 8 and arm["shed"] == 0
            assert arm["post_warmup_recompiles"] == 0
            assert arm["decisions_per_s"] > 0
        assert arena["arena"]["slab_allocs"] >= 1
        assert report["speedup"] == report["speedup_inproc"]
        assert not report["paced"]

    def test_run_host_path_wire_arms_over_live_sockets(self):
        """The transport half of BENCH_r09: HTTP connection-per-request
        (pre-PR) vs one framed keep-alive connection per client
        (post-PR), both conserving every request, with the headline
        speedup switched to the wire ratio."""
        from rlgpuschedule_tpu.serve.bench import run_host_path
        pool = request_rows(16)
        report = run_host_path(pool, max_bucket=8, rounds=10,
                               warmup_rounds=2, wire_requests=64,
                               clients=4)
        before, after = report["wire_arms"]
        assert before["transport"] == "http connection-per-request"
        assert before["data_plane"] == "legacy"
        assert after["transport"] == "framed keep-alive"
        assert after["data_plane"] == "arena"
        for arm in report["wire_arms"]:
            assert arm["conservation_ok"]
            assert arm["served"] == arm["requests"]
            assert arm["decisions_per_s"] > 0
            assert arm["post_warmup_recompiles"] == 0
        assert report["speedup"] == pytest.approx(
            after["decisions_per_s"] / before["decisions_per_s"])
        assert "speedup_inproc" in report

    def test_run_host_path_refusals(self):
        from rlgpuschedule_tpu.serve.bench import run_host_path
        pool = request_rows(4)
        with pytest.raises(ValueError, match="rounds"):
            run_host_path(pool, rounds=0)
        with pytest.raises(ValueError, match="empty request pool"):
            run_host_path([])
        with pytest.raises(ValueError, match="rate_hz"):
            run_host_path(pool, fit=object())


class TestFleetReplay:
    def test_fleet_matches_sequential_replay_bit_for_bit(self, exp):
        """ISSUE 7 acceptance: fleet replay of N seeded clusters ==
        N sequential eval.replay runs, mean JCT/completion bit-for-bit
        on CPU."""
        fleet = fleet_replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, exp.traces)
        n = fleet["n_clusters"]
        assert n == exp.cfg.n_envs
        seq = []
        for i in range(n):
            ti = jax.tree.map(lambda x: x[i:i + 1], exp.traces)
            seq.append(eval_lib.replay(exp.apply_fn,
                                       exp.train_state.params,
                                       exp.env_params, ti))
        pooled = EvalResult(*[np.concatenate([np.asarray(getattr(r, f))
                                              for r in seq])
                              for f in EvalResult._fields])
        want_jct, want_completion = pooled_avg_jct(pooled)
        assert fleet["mean_jct"] == want_jct
        assert fleet["completion"] == want_completion
        np.testing.assert_array_equal(
            np.asarray(fleet["per_cluster"]["avg_jct"], np.float32),
            np.asarray(pooled.avg_jct, np.float32))

    def test_fleet_under_faults_matches_sequential(self, exp):
        windows, traces = fleet_windows(exp.cfg, 2, source=exp.source)
        faults = sample_fleet_faults(exp.cfg.n_nodes, "sporadic", 0, 2,
                                     windows)
        fleet = fleet_replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, traces, faults=faults,
                             max_steps=96)
        seq_jct = []
        for i in range(2):
            ti = jax.tree.map(lambda x: x[i:i + 1], traces)
            fi = jax.tree.map(lambda x: x[i:i + 1], faults)
            r = eval_lib.replay(exp.apply_fn, exp.train_state.params,
                                exp.env_params, ti, max_steps=96,
                                faults=fi)
            seq_jct.append(float(np.asarray(r.avg_jct)[0]))
        np.testing.assert_array_equal(
            np.asarray(fleet["per_cluster"]["avg_jct"], np.float32),
            np.asarray(seq_jct, np.float32))

    def test_fleet_windows_are_the_eval_tiling(self, exp):
        windows, traces = fleet_windows(exp.cfg, 3, source=exp.source)
        want = make_env_windows(dataclasses.replace(exp.cfg, n_envs=3),
                                exp.source)
        assert len(windows) == 3
        for w, v in zip(windows, want):
            np.testing.assert_array_equal(w.submit, v.submit)
            np.testing.assert_array_equal(w.gpus, v.gpus)

    def test_fleet_reports_throughput(self, exp):
        fleet = fleet_replay(exp.apply_fn, exp.train_state.params,
                             exp.env_params, exp.traces)
        assert fleet["decisions"] > 0
        assert fleet["decisions_per_s"] > 0
        assert fleet["wall_s"] > 0


class TestScrapeEndpoint:
    def test_scrape_serves_live_exposition(self):
        registry = Registry()
        registry.counter("serve_requests_total", "n").inc(3)
        with serve_http(registry, port=0) as srv:
            with urllib.request.urlopen(srv.url, timeout=10) as resp:
                body = resp.read().decode()
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
            assert body == registry.render()
            assert "serve_requests_total 3" in body
            # live: a scrape observes updates without restart
            registry.gauge("serve_queue_depth", "d").set(7)
            with urllib.request.urlopen(srv.url, timeout=10) as resp:
                assert "serve_queue_depth 7" in resp.read().decode()
            # root alias works, anything else 404s
            root = srv.url.rsplit("/", 1)[0] + "/"
            with urllib.request.urlopen(root, timeout=10) as resp:
                assert resp.status == 200
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(srv.url + "/nope", timeout=10)

    def test_close_releases_the_port(self):
        registry = Registry()
        srv = serve_http(registry, port=0)
        port = srv.port
        srv.close()
        srv2 = serve_http(registry, port=port)   # re-bindable after close
        assert srv2.port == port
        srv2.close()


SERVE_FAST = ["--config", "ppo-mlp-synth64", "--n-envs", "2",
              "--n-nodes", "2", "--gpus-per-node", "4",
              "--window-jobs", "12", "--queue-len", "4",
              "--horizon", "64"]


class TestServeCLI:
    def test_bench_reports_slo_and_repro(self, capsys):
        report = serve_cli.main(
            SERVE_FAST + ["--bench", "--bucket", "8", "--rounds", "6",
                          "--max-steps", "64", "--pool-steps", "2"])
        b = report["bench"]
        assert b["post_warmup_recompiles"] == 0
        assert len(set(b["request_sizes"])) >= 3
        assert b["buckets"] == [8]
        assert b["decisions_per_s"] > 0
        assert b["latency_p50_ms"] > 0 and b["latency_p99_ms"] > 0
        # the same repro tuple evaluate emits (shared constructor)
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, n_nodes=2,
            gpus_per_node=4, window_jobs=12, queue_len=4, horizon=64)
        assert report["repro"] == repro_tuple(cfg)
        out = capsys.readouterr().out
        assert json.loads(out.strip().splitlines()[-1])["bench"][
            "post_warmup_recompiles"] == 0

    def test_fleet_mode_and_metrics_port(self):
        report = serve_cli.main(
            SERVE_FAST + ["--fleet", "2", "--max-steps", "96",
                          "--metrics-port", "0"])
        fl = report["fleet"]
        assert fl["n_clusters"] == 2
        assert fl["completion"] > 0
        assert np.isfinite(fl["mean_jct"])
        scrape = report["scrape"]
        assert scrape["well_formed"] and scrape["status"] == 200
        assert scrape["metric_lines"] > 0

    def test_bench_resolved_ckpt_step_in_repro(self, tmp_path):
        from rlgpuschedule_tpu.checkpoint import Checkpointer
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, n_nodes=2,
            gpus_per_node=4, window_jobs=12, queue_len=4, horizon=64,
            ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))
        exp = Experiment.build(cfg)
        with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
            exp.save_checkpoint(ckpt, step=3)
        report = serve_cli.main(
            SERVE_FAST + ["--bench", "--bucket", "8", "--rounds", "3",
                          "--pool-steps", "1",
                          "--ckpt-dir", str(tmp_path / "ckpt")])
        assert report["repro"]["ckpt_step"] == 3
        assert report["repro"]["ckpt_dir"] == str(tmp_path / "ckpt")

    def test_host_path_mode(self):
        report = serve_cli.main(
            SERVE_FAST + ["--host-path", "--bucket", "8",
                          "--host-rounds", "20", "--pool-steps", "1"])
        hp = report["host_path"]
        arena = [a for a in hp["arms"] if a["data_plane"] == "arena"][0]
        assert arena["alloc_calls"] == 0
        assert arena["steady_state_slab_allocs"] == 0
        assert all(a["conservation_ok"] for a in hp["arms"])
        assert hp["speedup"] > 0
        assert "wire_arms" not in hp                   # not requested

    def test_refusals(self):
        with pytest.raises(SystemExit):
            serve_cli.main(SERVE_FAST)                     # no mode
        with pytest.raises(SystemExit):
            serve_cli.main(SERVE_FAST + ["--bench", "--bucket", "6"])
        with pytest.raises(SystemExit):
            serve_cli.main(SERVE_FAST + ["--fleet", "0"])
        with pytest.raises(SystemExit):                    # silent no-op
            serve_cli.main(SERVE_FAST + ["--fleet-regime", "storm",
                                         "--bench"])
        with pytest.raises(SystemExit):
            serve_cli.main(SERVE_FAST + ["--request-sizes", "2,4",
                                         "--fleet", "1"])
        with pytest.raises(SystemExit):                    # > bucket
            serve_cli.main(SERVE_FAST + ["--bench", "--bucket", "8",
                                         "--request-sizes", "9"])
        with pytest.raises(SystemExit):
            serve_cli.main(SERVE_FAST + ["--fleet", "1",
                                         "--fleet-regime", "nope"])
        with pytest.raises(SystemExit):                    # silent no-op
            serve_cli.main(SERVE_FAST + ["--wire-requests", "64",
                                         "--bench"])
        with pytest.raises(SystemExit):
            serve_cli.main(SERVE_FAST + ["--host-path",
                                         "--host-rounds", "0"])
