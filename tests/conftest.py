"""Test config: force an 8-device virtual CPU platform before jax backends
initialize.

This is the standard JAX substitute for a multi-chip test rig (SURVEY.md §4
"Distributed without a real cluster"): all pjit/shard_map/psum code paths run
against 8 virtual CPU devices, so the data-parallel and PBT sync logic is
exercised in CI with no TPU attached.

The pinning itself (including the machine's axon-sitecustomize quirk it
defends against) lives in ``rlgpuschedule_tpu.utils.platform.force_cpu``,
shared with ``__graft_entry__.dryrun_multichip``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rlgpuschedule_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(8)  # raises (with the cause named) if 8 CPU devices can't be had
