"""Test config: force an 8-device virtual CPU platform before jax backends
initialize.

This is the standard JAX substitute for a multi-chip test rig (SURVEY.md §4
"Distributed without a real cluster"): all pjit/shard_map/psum code paths run
against 8 virtual CPU devices, so the data-parallel and PBT sync logic is
exercised in CI with no TPU attached.

The pinning itself (including the machine's axon-sitecustomize quirk it
defends against) lives in ``rlgpuschedule_tpu.utils.platform.force_cpu``,
shared with ``__graft_entry__.dryrun_multichip``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rlgpuschedule_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(8)  # raises (with the cause named) if 8 CPU devices can't be had

# Persistent XLA compilation cache (VERDICT r2 next-round #7: the suite is
# compile-bound; every compile cached including sub-second ones — measured
# round 5, warm suite 444s -> 288s). One source of truth with the CLIs:
# the helper sets the env var too, so the CLI tests' subprocesses inherit
# the same cache and even a cold suite run gets hits on programs the
# in-process tests already compiled.
from rlgpuschedule_tpu.utils.platform import enable_compile_cache  # noqa: E402

enable_compile_cache()

# jsan's fixture corpus is deliberately-broken code, and the contract-drift
# directory fixtures carry their own tests/test_*.py as analysis INPUT —
# never collect any of it as real tests.
collect_ignore = ["fixtures"]

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (ROADMAP.md runs -m 'not "
        "slow')")
    config.addinivalue_line(
        "markers",
        "sanitize: run under jax_enable_checks + jax_debug_nans (SURVEY.md "
        "§5 sanitizer note). Opt-in: debug_nans re-executes every jitted "
        "program eagerly on a hit and disables some fusions, so only a "
        "fast smoke subset carries it — and never a test that produces "
        "NaN on purpose (the resilience fault-injection tests)")
    config.addinivalue_line(
        "markers",
        "multihost_spawn: spawns a real multi-process jax.distributed "
        "gang (tests/test_multihost.py). CPU-contention-sensitive on "
        "small rigs — gloo's collective rendezvous races per-rank XLA "
        "compile — so ci.sh runs this subset serially AFTER the main "
        "tier-1 pass; the tests still run (not skipped) under a plain "
        "-m 'not slow' invocation")
    config.addinivalue_line(
        "markers",
        "perf: wall-clock performance measurements (update-geometry "
        "timing assertions). Opt-in via `-m perf`: timing asserts are "
        "load-sensitive on the shared 1-core CI host, so tier-1 skips "
        "them; the bit-level EQUIVALENCE contract of the fused update "
        "engine runs unmarked on every tier-1 pass "
        "(tests/test_algos.py::TestUpdateEngine)")
    config.addinivalue_line(
        "markers",
        "timing_flake(retries=N): rerun the test up to N extra times "
        "(fresh tmp_path each try) before reporting failure. Isolation "
        "for KNOWN order/timing-dependent flakes only — each use must "
        "carry a tracking note naming the observed failure signature; "
        "a test that fails deterministically still fails after the "
        "retries, so real regressions cannot hide behind the marker")


def pytest_runtest_protocol(item, nextitem):
    """Retry protocol for ``timing_flake``-marked tests (no
    pytest-rerunfailures in the image — this is the dependency-free
    subset we need). A failed try is re-run up to ``retries`` more
    times; only the LAST try's reports are posted, plus a visible
    warning that a retry happened so the flake stays observable in
    ``-W error``-less runs rather than silently absorbed."""
    marker = item.get_closest_marker("timing_flake")
    if marker is None:
        return None
    retries = int(marker.kwargs.get("retries", 2))
    from _pytest.runner import runtestprotocol
    for attempt in range(retries + 1):
        item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                           location=item.location)
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
        failed = [r for r in reports if r.failed]
        if not failed or attempt == retries:
            if failed and attempt:
                pass        # exhausted: last try's failure is reported
            elif attempt:
                item.warn(pytest.PytestWarning(
                    f"timing_flake: {item.nodeid} passed on retry "
                    f"{attempt}/{retries} (tracking note on the test "
                    f"names the signature)"))
            for r in reports:
                item.ihook.pytest_runtest_logreport(report=r)
            item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                                location=item.location)
            return True
        item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                            location=item.location)
        # a retry must not reuse the failed try's tmp_path/fixtures:
        # teardown ran inside runtestprotocol, setup reruns next loop
    return True


def pytest_collection_modifyitems(config, items):
    """Skip ``perf``-marked tests unless explicitly selected with
    ``-m perf`` (mirrors the sanitize marker's opt-in philosophy, but by
    skipping: a timing assert that flakes under CI load would poison
    tier-1, while silently running it un-asserted would be a no-op)."""
    if "perf" in (config.option.markexpr or ""):
        return
    skip = pytest.mark.skip(reason="perf measurement: opt-in with -m perf")
    for item in items:
        if "perf" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _sanitize(request):
    """Enable the JAX sanitizers for tests marked ``sanitize``:
    jax_enable_checks + jax_debug_nans (the original pair), plus
    jax_numpy_rank_promotion="raise" (PR 3): an implicit [E] vs [T, E]
    broadcast in an obs builder or loss silently trains on wrong data —
    raising turns the silent wrong-math class into a test failure."""
    if request.node.get_closest_marker("sanitize") is None:
        yield
        return
    import jax
    prev_checks = jax.config.jax_enable_checks
    prev_nans = jax.config.jax_debug_nans
    prev_rank = jax.config.jax_numpy_rank_promotion
    jax.config.update("jax_enable_checks", True)
    jax.config.update("jax_debug_nans", True)
    jax.config.update("jax_numpy_rank_promotion", "raise")
    try:
        yield
    finally:
        jax.config.update("jax_enable_checks", prev_checks)
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_numpy_rank_promotion", prev_rank)
