"""Test config: force an 8-device virtual CPU platform before jax backends
initialize.

This is the standard JAX substitute for a multi-chip test rig (SURVEY.md §4
"Distributed without a real cluster"): all pjit/shard_map/psum code paths run
against 8 virtual CPU devices, so the data-parallel and PBT sync logic is
exercised in CI with no TPU attached.

Machine quirk: a sitecustomize on PYTHONPATH registers a real-TPU tunnel
backend ("axon") in every Python process and pins ``jax_platforms`` to it;
when the tunnel is unhealthy, initializing it hangs forever. jax is therefore
already imported by the time this conftest runs, but its backends are still
lazy — so we flip ``jax_platforms`` to cpu and set the virtual device count
before any backend initializes.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (sitecustomize imported it already; this is a no-op)

jax.config.update("jax_platforms", "cpu")
assert not jax._src.xla_bridge.backends_are_initialized(), (
    "a plugin initialized JAX backends before conftest; CPU forcing failed")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
