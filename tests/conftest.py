"""Test config: force an 8-device virtual CPU platform before jax backends
initialize.

This is the standard JAX substitute for a multi-chip test rig (SURVEY.md §4
"Distributed without a real cluster"): all pjit/shard_map/psum code paths run
against 8 virtual CPU devices, so the data-parallel and PBT sync logic is
exercised in CI with no TPU attached.

The pinning itself (including the machine's axon-sitecustomize quirk it
defends against) lives in ``rlgpuschedule_tpu.utils.platform.force_cpu``,
shared with ``__graft_entry__.dryrun_multichip``.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent XLA compilation cache (VERDICT r2 next-round #7: the suite is
# compile-bound). Set via the env var BEFORE jax initializes so the CLI
# tests' subprocesses inherit it too — they re-jit the same programs the
# in-process tests already compiled, so even a cold suite run gets hits;
# warm re-runs skip nearly all compilation.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "rlgpuschedule_jax_cache"))

from rlgpuschedule_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(8)  # raises (with the cause named) if 8 CPU devices can't be had

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
# cache EVERY compile (default floor 1s, previously 0.5): the suite is
# hundreds of small programs on a 1-core host — sub-second compiles in
# aggregate are a large share of warm-run wall clock
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
