"""Fault-tolerance tests (ISSUE 1 / SURVEY.md §5 "Failure detection /
elastic recovery / fault injection"): every recovery path is driven by the
deterministic fault-injection harness on CPU —

- injected NaN gradient -> divergence watchdog rolls back to the last
  good checkpoint exactly once and the run completes finite;
- corrupted latest checkpoint -> ``Checkpointer.restore`` falls back to
  the previous retained step (and raises ``CheckpointRestoreError`` only
  when EVERY retained step is corrupt);
- a dead PBT member (non-finite fitness) -> exploit re-seeds it from the
  best finite member instead of letting NaN win the tournament.

The killed-multihost-rank path lives in ``test_multihost.py`` (it spawns
real processes); this file covers everything in-process.
"""
import dataclasses
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlgpuschedule_tpu import train as train_cli
from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.checkpoint import Checkpointer, CheckpointRestoreError
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.experiment import Experiment, PopulationExperiment
from rlgpuschedule_tpu.parallel import (HParams, PBTConfig, exploit_explore)
from rlgpuschedule_tpu.resilience import (DivergenceError,
                                          DivergenceWatchdog, FaultInjector,
                                          HeartbeatMonitor, HeartbeatWriter,
                                          corrupt_checkpoint, parse_fault)

# same shapes as test_checkpoint's resume tests so the persistent XLA
# cache already holds every program this file compiles
SMALL = dataclasses.replace(
    CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
    ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))

# matches tests/test_cli.py FAST (again: compile-cache reuse)
CLI_FAST = ["--iterations", "4", "--n-envs", "4", "--n-nodes", "2",
            "--gpus-per-node", "4", "--window-jobs", "16",
            "--log-every", "1", "--horizon", "64", "--queue-len", "4",
            "--n-steps", "8", "--n-epochs", "1", "--n-minibatches", "2"]


class TestParseFault:
    def test_parses_kind_at_rank(self):
        s = parse_fault("nan-grad@3")
        assert (s.kind, s.at, s.rank, s.fired) == ("nan-grad", 3, 0, False)
        s = parse_fault("kill-rank@2:rank=1")
        assert (s.kind, s.at, s.rank) == ("kill-rank", 2, 1)
        s = parse_fault("corrupt-ckpt@7")
        assert (s.kind, s.at) == ("corrupt-ckpt", 7)

    @pytest.mark.parametrize("bad", ["nan@3", "nan-grad", "nan-grad@x",
                                     "nan-grad@3:bogus=2", "@2", ""])
    def test_bad_specs_raise_with_the_spec_named(self, bad):
        with pytest.raises(ValueError, match="fault"):
            parse_fault(bad)


class TestWatchdogChecks:
    def test_finite_metrics_pass(self):
        wd = DivergenceWatchdog()
        assert wd.check({"total_loss": 0.5, "mean_reward": -1.0}) is None

    def test_non_finite_metric_flagged(self):
        wd = DivergenceWatchdog()
        assert "nan" in wd.check({"total_loss": float("nan")}).lower()
        assert wd.check({"mean_reward": float("inf")}) is not None

    def test_loss_blowup_flagged_against_ema(self):
        wd = DivergenceWatchdog(blowup_factor=100.0)
        for _ in range(5):
            assert wd.check({"total_loss": 1.0}) is None
        reason = wd.check({"total_loss": 1e6})
        assert reason is not None and "blow-up" in reason

    def test_first_iteration_large_loss_is_not_a_blowup(self):
        # no EMA yet -> nothing to blow up against
        wd = DivergenceWatchdog(blowup_factor=100.0)
        assert wd.check({"total_loss": 1e9}) is None

    def test_population_single_dead_member_is_pbts_job(self):
        wd = DivergenceWatchdog()
        assert wd.check_population([float("nan"), 1.0]) is None
        reason = wd.check_population([float("nan"), float("inf")])
        assert reason is not None and "non-finite" in reason

    def test_zero_budget_raises_cleanly(self):
        wd = DivergenceWatchdog(max_rollbacks=0)
        with pytest.raises(DivergenceError, match="max_rollbacks"):
            wd.rollback(None, None, 3, "non-finite total_loss")


class TestHeartbeat:
    def test_beat_read_roundtrip(self, tmp_path):
        hb = HeartbeatWriter(str(tmp_path), rank=1)
        hb.beat(4)
        mon = HeartbeatMonitor(str(tmp_path), n_ranks=2, timeout_s=60.0)
        beats = mon.read()
        assert beats[1][0] == 4
        # rank 0 never wrote but is inside the startup grace window
        assert mon.stale_ranks() == []

    def test_stale_rank_detected_and_restart_rearms(self, tmp_path):
        hb = HeartbeatWriter(str(tmp_path), rank=0)
        hb.beat(0)
        mon = HeartbeatMonitor(str(tmp_path), n_ranks=2, timeout_s=0.05)
        time.sleep(0.1)
        # rank 0's file is stale; rank 1 never appeared past its grace
        assert mon.stale_ranks() == [0, 1]
        mon.restart()
        assert 0 in mon.stale_ranks() and 1 not in mon.stale_ranks()
        hb.beat(1)
        assert 0 not in mon.stale_ranks()


class TestNaNGradRollback:
    def test_injected_nan_triggers_one_rollback_and_run_completes(
            self, tmp_path, capsys):
        """Acceptance path 1: nan-grad@2 poisons params+metrics; the
        watchdog rolls back to the iteration-1 checkpoint, the retry (LR
        halved, RNG folded) converges on, and the summary records exactly
        one rollback with the recovery visible in the run log."""
        exp = Experiment.build(SMALL)
        wd = DivergenceWatchdog(max_rollbacks=3)
        inj = FaultInjector([parse_fault("nan-grad@2")])
        with Checkpointer(str(tmp_path / "ck")) as ck:
            out = exp.run(iterations=4, log_every=1, ckpt=ck,
                          ckpt_every=1, watchdog=wd, injector=inj)
        assert out["rollbacks"] == 1
        ev = out["rollback_events"][0]
        assert ev["iteration"] == 2
        assert ev["resume_iteration"] == 2
        assert ev["lr_scale"] == 0.5
        assert ev["restored_step"] is not None
        assert "non-finite" in ev["reason"]
        # the run converged on: final params and every logged row finite
        assert all(np.isfinite(v)
                   for v in jax.tree.leaves(
                       jax.tree.map(lambda x: float(jnp.sum(x)),
                                    exp.train_state.params)))
        final_rows = [h for h in out["history"] if h["iteration"] == 3]
        assert final_rows and all(
            math.isfinite(v) for h in final_rows for v in h.values())
        err = capsys.readouterr().err
        assert "fault-injection: nan-grad at iteration 2" in err
        assert "watchdog:" in err and "rolled back" in err

    def test_without_watchdog_the_fault_really_poisons(self):
        # the control: same fault, no watchdog -> params end non-finite
        # (proves the recovery test above is recovering from a real fault)
        exp = Experiment.build(SMALL)
        inj = FaultInjector([parse_fault("nan-grad@1")])
        exp.run(iterations=2, injector=inj)
        total = sum(float(jnp.sum(x))
                    for x in jax.tree.leaves(exp.train_state.params))
        assert not math.isfinite(total)

    def test_exhausted_budget_raises_divergence_error(self, tmp_path):
        # two distinct faults, budget of one: the second rollback attempt
        # must give up cleanly
        exp = Experiment.build(SMALL)
        wd = DivergenceWatchdog(max_rollbacks=1)
        inj = FaultInjector([parse_fault("nan-grad@1"),
                             parse_fault("nan-grad@2")])
        with Checkpointer(str(tmp_path / "ck")) as ck:
            with pytest.raises(DivergenceError, match="giving up"):
                exp.run(iterations=4, ckpt=ck, ckpt_every=1,
                        watchdog=wd, injector=inj)

    def test_watchdog_requires_checkpointer(self):
        exp = Experiment.build(SMALL)
        with pytest.raises(ValueError, match="ckpt"):
            exp.run(iterations=1, watchdog=DivergenceWatchdog())


class TestCorruptCheckpointFallback:
    def _two_step_store(self, tmp_path):
        exp = Experiment.build(SMALL)
        ck = Checkpointer(str(tmp_path / "ck"), max_to_keep=3)
        exp.run(iterations=2, ckpt=ck, ckpt_every=1)
        ck.wait()
        assert len(ck.all_steps()) >= 2
        return exp, ck

    def test_corrupt_latest_falls_back_to_previous(self, tmp_path, capsys):
        """Acceptance path 2: the latest step's data files are truncated;
        restore lands on the previous retained step instead of raising,
        and says so in the log."""
        exp, ck = self._two_step_store(tmp_path)
        steps = ck.all_steps()
        n = corrupt_checkpoint(ck.directory, steps[-1])
        assert n > 0
        exp2 = Experiment.build(SMALL)
        exp2.restore_checkpoint(ck)
        assert ck.last_restored_step == steps[-2]
        total = sum(float(jnp.sum(x))
                    for x in jax.tree.leaves(exp2.train_state.params))
        assert math.isfinite(total)
        err = capsys.readouterr().err
        assert "falling back to step" in err
        ck.close()

    def test_all_steps_corrupt_raises_restore_error(self, tmp_path):
        exp, ck = self._two_step_store(tmp_path)
        for s in ck.all_steps():
            corrupt_checkpoint(ck.directory, s)
        with pytest.raises(CheckpointRestoreError, match="failed to"):
            Experiment.build(SMALL).restore_checkpoint(ck)
        ck.close()

    def test_explicit_step_does_not_fall_back(self, tmp_path):
        exp, ck = self._two_step_store(tmp_path)
        bad = ck.all_steps()[-1]
        corrupt_checkpoint(ck.directory, bad)
        with pytest.raises(Exception) as ei:
            Experiment.build(SMALL).restore_checkpoint(ck, step=bad)
        assert not isinstance(ei.value, CheckpointRestoreError)
        ck.close()

    def test_corrupt_missing_step_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            corrupt_checkpoint(str(tmp_path), 123)


class TestPBTDeadMembers:
    def _hp(self, n):
        return HParams(lr=jnp.full((n,), 3e-4),
                       ent_coef=jnp.full((n,), 0.01),
                       clip_eps=jnp.full((n,), 0.2))

    def test_dead_members_reseed_from_best_even_past_quota(self):
        # 2 of 4 dead with exploit_frac=0.25 (quota 1): the old NaN-ranks-
        # worst rule would leave one dead member alive; now both re-seed
        # from the best finite member
        rng = np.random.default_rng(0)
        fitness = np.array([np.nan, 1.0, 2.0, np.inf])
        d = exploit_explore(rng, fitness, self._hp(4),
                            PBTConfig(exploit_frac=0.25))
        assert d.src[0] == 2 and d.src[3] == 2
        assert d.exploited[0] and d.exploited[3]

    def test_winners_never_drawn_from_dead_members(self):
        # divergence reaching the top quantile: member 3 (NaN) sits where
        # argsort-with-NaN-last used to place a winner
        rng = np.random.default_rng(1)
        fitness = np.array([0.0, 1.0, 2.0, np.nan])
        for _ in range(10):
            d = exploit_explore(rng, fitness, self._hp(4),
                                PBTConfig(exploit_frac=0.25))
            assert d.src[0] != 3 and d.src[3] == 2

    def test_no_finite_member_means_nobody_copies(self):
        # nobody to re-seed from: keep states (whole-run rollback is the
        # population watchdog's job, not exploit's)
        rng = np.random.default_rng(2)
        fitness = np.full((4,), np.nan)
        d = exploit_explore(rng, fitness, self._hp(4), PBTConfig())
        assert not d.exploited.any()

    def test_population_run_recovers_injected_member_nan(self, tmp_path):
        """Acceptance path 1 (population flavor): member 1 is poisoned at
        iteration 1; the next exploit round re-seeds it from the best
        member and the run ends with every member finite."""
        cfg = dataclasses.replace(SMALL, n_envs=4)
        exp = PopulationExperiment.build(
            cfg, n_pop=2, mesh=None,
            pbt_cfg=PBTConfig(ready_iters=1, seed=0))
        inj = FaultInjector([parse_fault("nan-grad@1:rank=1")])
        with Checkpointer(str(tmp_path / "ck")) as ck:
            out = exp.run(iterations=4, log_every=1, ckpt=ck,
                          ckpt_every=2, injector=inj,
                          watchdog=DivergenceWatchdog(max_rollbacks=1))
        assert all(np.isfinite(out["final_fitness"])), out["final_fitness"]
        # the catastrophic-case watchdog never had to fire: one dead
        # member is exploit's job
        assert out["rollbacks"] == 0
        total = sum(float(jnp.sum(x))
                    for x in jax.tree.leaves(exp.states.params))
        assert math.isfinite(total)


class TestResilienceCLI:
    def test_nan_grad_rollback_end_to_end(self, tmp_path, capsys):
        summary = train_cli.main(
            ["--config", "ppo-mlp-synth64", *CLI_FAST,
             "--ckpt-dir", str(tmp_path / "ck"), "--ckpt-every", "1",
             "--fault", "nan-grad@2", "--max-rollbacks", "2"])
        assert summary["rollbacks"] == 1
        assert summary["rollback_events"][0]["iteration"] == 2
        assert np.isfinite(summary["env_steps_per_sec"])
        err = capsys.readouterr().err
        assert "fault-injection" in err and "watchdog" in err

    def test_corrupt_ckpt_fault_then_resume_falls_back(self, tmp_path,
                                                       capsys):
        """Acceptance path 2, end to end: the checkpoint saved at
        iteration 3 (the latest) is truncated by the injected fault; the
        resumed run restores the iteration-2 step instead and completes."""
        args = ["--config", "ppo-mlp-synth64", *CLI_FAST,
                "--ckpt-dir", str(tmp_path / "ck"), "--ckpt-every", "1"]
        train_cli.main(args + ["--fault", "corrupt-ckpt@3"])
        assert "corrupted checkpoint" in capsys.readouterr().err
        out = train_cli.main(args + ["--resume"])
        assert out["iterations"] == 4
        assert np.isfinite(out["env_steps_per_sec"])
        assert "falling back to step" in capsys.readouterr().err

    def test_kill_rank_refused_by_single_process_cli(self):
        with pytest.raises(SystemExit, match="multihost"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--fault", "kill-rank@1:rank=0"])

    def test_bad_fault_spec_exits_with_message(self):
        with pytest.raises(SystemExit, match="fault"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--fault", "nonsense"])

    def test_max_rollbacks_requires_ckpt_dir(self):
        with pytest.raises(SystemExit, match="ckpt-dir"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--max-rollbacks", "2"])

    def test_corrupt_ckpt_fault_requires_ckpt_dir(self):
        with pytest.raises(SystemExit, match="ckpt-dir"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--fault", "corrupt-ckpt@1"])


class TestSelectCheckpointSeedGuards:
    def test_val_seed_matching_eval_probe_default_refused(self):
        from rlgpuschedule_tpu import select_checkpoint
        # config seed 0 -> the in-training probe's default held-out
        # stream is seed 1000; selecting on it is not validation
        with pytest.raises(SystemExit, match="eval-every"):
            select_checkpoint.main(["--ckpt-dir", "/nonexistent",
                                    "--val-seed", "1000"])

    def test_test_seed_must_differ_from_val_seed(self):
        from rlgpuschedule_tpu import select_checkpoint
        with pytest.raises(SystemExit, match="disjoint"):
            select_checkpoint.main(["--ckpt-dir", "/nonexistent",
                                    "--val-seed", "77",
                                    "--test-seed", "77"])

    def test_test_seed_must_differ_from_training_seed(self):
        from rlgpuschedule_tpu import select_checkpoint
        with pytest.raises(SystemExit, match="training seed"):
            select_checkpoint.main(["--ckpt-dir", "/nonexistent",
                                    "--val-seed", "77",
                                    "--test-seed", "0"])
