"""Fault-tolerance tests (ISSUE 1 / SURVEY.md §5 "Failure detection /
elastic recovery / fault injection"): every recovery path is driven by the
deterministic fault-injection harness on CPU —

- injected NaN gradient -> divergence watchdog rolls back to the last
  good checkpoint exactly once and the run completes finite;
- corrupted latest checkpoint -> ``Checkpointer.restore`` falls back to
  the previous retained step (and raises ``CheckpointRestoreError`` only
  when EVERY retained step is corrupt);
- a dead PBT member (non-finite fitness) -> exploit re-seeds it from the
  best finite member instead of letting NaN win the tournament;
- the elastic gang supervisor (ISSUE 4): restart/shrink decisions,
  restart-storm double-charging, budget/floor give-up reasons — unit
  tested against scripted fake launchers (no processes spawned).

The killed/lost-multihost-rank paths live in ``test_multihost.py`` (they
spawn real gangs); this file covers everything in-process.
"""
import dataclasses
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlgpuschedule_tpu import train as train_cli
from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.checkpoint import Checkpointer, CheckpointRestoreError
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.experiment import Experiment, PopulationExperiment
from rlgpuschedule_tpu.parallel import (HParams, PBTConfig, exploit_explore)
from rlgpuschedule_tpu.resilience import (KILL_RANK_EXIT, LOSE_RANK_EXIT,
                                          DivergenceError,
                                          DivergenceWatchdog, FaultInjector,
                                          Gang, HeartbeatMonitor,
                                          HeartbeatWriter, Launcher,
                                          RestartPolicy, Supervisor,
                                          SupervisorTimeout,
                                          corrupt_checkpoint, parse_fault)

# same shapes as test_checkpoint's resume tests so the persistent XLA
# cache already holds every program this file compiles
SMALL = dataclasses.replace(
    CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
    ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))

# matches tests/test_cli.py FAST (again: compile-cache reuse)
CLI_FAST = ["--iterations", "4", "--n-envs", "4", "--n-nodes", "2",
            "--gpus-per-node", "4", "--window-jobs", "16",
            "--log-every", "1", "--horizon", "64", "--queue-len", "4",
            "--n-steps", "8", "--n-epochs", "1", "--n-minibatches", "2"]


class TestParseFault:
    def test_parses_kind_at_rank(self):
        s = parse_fault("nan-grad@3")
        assert (s.kind, s.at, s.rank, s.fired) == ("nan-grad", 3, 0, False)
        s = parse_fault("kill-rank@2:rank=1")
        assert (s.kind, s.at, s.rank) == ("kill-rank", 2, 1)
        s = parse_fault("corrupt-ckpt@7")
        assert (s.kind, s.at) == ("corrupt-ckpt", 7)
        s = parse_fault("lose-rank@2:rank=1")
        assert (s.kind, s.at, s.rank) == ("lose-rank", 2, 1)

    @pytest.mark.parametrize("bad", ["nan@3", "nan-grad", "nan-grad@x",
                                     "nan-grad@3:bogus=2", "@2", ""])
    def test_bad_specs_raise_with_the_spec_named(self, bad):
        with pytest.raises(ValueError, match="fault"):
            parse_fault(bad)


class TestWatchdogChecks:
    def test_finite_metrics_pass(self):
        wd = DivergenceWatchdog()
        assert wd.check({"total_loss": 0.5, "mean_reward": -1.0}) is None

    def test_non_finite_metric_flagged(self):
        wd = DivergenceWatchdog()
        assert "nan" in wd.check({"total_loss": float("nan")}).lower()
        assert wd.check({"mean_reward": float("inf")}) is not None

    def test_loss_blowup_flagged_against_ema(self):
        wd = DivergenceWatchdog(blowup_factor=100.0)
        for _ in range(5):
            assert wd.check({"total_loss": 1.0}) is None
        reason = wd.check({"total_loss": 1e6})
        assert reason is not None and "blow-up" in reason

    def test_first_iteration_large_loss_is_not_a_blowup(self):
        # no EMA yet -> nothing to blow up against
        wd = DivergenceWatchdog(blowup_factor=100.0)
        assert wd.check({"total_loss": 1e9}) is None

    def test_population_single_dead_member_is_pbts_job(self):
        wd = DivergenceWatchdog()
        assert wd.check_population([float("nan"), 1.0]) is None
        reason = wd.check_population([float("nan"), float("inf")])
        assert reason is not None and "non-finite" in reason

    def test_zero_budget_raises_cleanly(self):
        wd = DivergenceWatchdog(max_rollbacks=0)
        with pytest.raises(DivergenceError, match="max_rollbacks"):
            wd.rollback(None, None, 3, "non-finite total_loss")


class TestHeartbeat:
    def test_beat_read_roundtrip(self, tmp_path):
        hb = HeartbeatWriter(str(tmp_path), rank=1)
        hb.beat(4)
        mon = HeartbeatMonitor(str(tmp_path), n_ranks=2, timeout_s=60.0)
        beats = mon.read()
        assert beats[1][0] == 4
        # rank 0 never wrote but is inside the startup grace window
        assert mon.stale_ranks() == []

    def test_stale_rank_detected_and_restart_rearms(self, tmp_path):
        hb = HeartbeatWriter(str(tmp_path), rank=0)
        hb.beat(0)
        mon = HeartbeatMonitor(str(tmp_path), n_ranks=2, timeout_s=0.05)
        time.sleep(0.1)
        # rank 0's file is stale; rank 1 never appeared past its grace
        assert mon.stale_ranks() == [0, 1]
        mon.restart()
        assert 0 in mon.stale_ranks() and 1 not in mon.stale_ranks()
        hb.beat(1)
        assert 0 not in mon.stale_ranks()

    def test_monotonic_clock_immune_to_wall_jump(self, tmp_path):
        """Beats carry monotonic stamps: a wall-clock step (NTP) between
        beat and check can neither fake staleness nor fake liveness.
        Simulated with injected clocks — the writer and monitor share one
        monotonic source while the 'wall clock' jumps an hour."""
        mono = [100.0]
        hb = HeartbeatWriter(str(tmp_path), rank=0, clock=lambda: mono[0])
        mon = HeartbeatMonitor(str(tmp_path), n_ranks=1, timeout_s=5.0,
                               clock=lambda: mono[0])
        hb.beat(0)
        # a wall-clock jump has no representation at all: only the shared
        # monotonic clock advances staleness
        mono[0] += 4.9
        assert mon.stale_ranks() == []      # would be false-stale under a
        mono[0] += 0.2                      # +1h wall jump with time.time
        assert mon.stale_ranks() == [0]
        hb.beat(1)
        assert mon.stale_ranks() == []

    def test_threshold_is_per_monitor_not_a_constant(self, tmp_path):
        hb = HeartbeatWriter(str(tmp_path), rank=0)
        hb.beat(0)
        time.sleep(0.06)
        strict = HeartbeatMonitor(str(tmp_path), n_ranks=1, timeout_s=0.05)
        lax = HeartbeatMonitor(str(tmp_path), n_ranks=1, timeout_s=60.0)
        assert strict.stale_ranks() == [0]
        assert lax.stale_ranks() == []

    def test_torn_tmp_file_never_surfaces(self, tmp_path):
        """A crashed writer's leftover tmp must not shadow the rank file,
        and a garbage rank file reads as 'no beat yet' (grace), not a
        crash."""
        hb = HeartbeatWriter(str(tmp_path), rank=0)
        hb.beat(3)
        # a dying predecessor's half-written tmp (pid-unique name)
        (tmp_path / "rank0.hb.tmp.99999").write_text("2 12")
        (tmp_path / "rank1.hb").write_text("garbage")
        mon = HeartbeatMonitor(str(tmp_path), n_ranks=2, timeout_s=60.0)
        assert mon.read() == {0: (3, mon.read()[0][1])}
        assert mon.stale_ranks() == []


class _FakeGang(Gang):
    def __init__(self, codes, outs=None):
        self._codes = codes
        self._outs = outs
        self.killed = False

    def poll(self):
        return list(self._codes)

    def kill(self):
        self.killed = True

    def outputs(self):
        return self._outs or [""] * len(self._codes)


class _FakeLauncher(Launcher):
    """Scripted launcher: each launch() pops the next exit-code vector;
    completed-step sidecars are a plain dict."""

    def __init__(self, world, script, steps=None):
        self.world_size = world
        self._script = list(script)
        self._steps = {} if steps is None else dict(steps)
        self.plans = []
        self.gangs = []

    def launch(self, plan):
        self.plans.append(plan)
        gang = _FakeGang(self._script.pop(0))
        self.gangs.append(gang)
        return gang

    def completed_steps(self, ranks):
        return {r: self._steps[r] for r in ranks if r in self._steps}


def _supervise(launcher, policy, **kw):
    kw.setdefault("sleep", lambda s: None)
    return Supervisor(launcher, policy, **kw).run()


class TestRestartPolicy:
    def test_backoff_grows_exponentially_and_caps(self):
        pol = RestartPolicy(10, backoff_s=1.0, backoff_max_s=4.0,
                            jitter_frac=0.0)
        delays = []
        for _ in range(4):
            pol.record_failure()
            delays.append(pol.next_delay())
        assert delays == [1.0, 2.0, 4.0, 4.0]

    def test_jitter_is_seeded_and_bounded(self):
        a = RestartPolicy(10, backoff_s=1.0, jitter_frac=0.5,
                          jitter_seed=7)
        b = RestartPolicy(10, backoff_s=1.0, jitter_frac=0.5,
                          jitter_seed=7)
        a.record_failure(), b.record_failure()
        da, db = a.next_delay(), b.next_delay()
        assert da == db                       # reproducible
        assert 1.0 <= da <= 1.5               # jitter only stretches

    def test_storm_failure_charges_double(self):
        t = [0.0]
        pol = RestartPolicy(10, backoff_s=1.0, clock=lambda: t[0])
        assert pol.record_failure() == 1      # first failure: no storm
        pol.next_delay()
        t[0] += 0.5                           # died within the window
        assert pol.record_failure() == 2
        t[0] += 1000.0                        # a long healthy run resets
        assert pol.record_failure() == 1
        assert (pol.failures, pol.spent, pol.storm_charges) == (3, 4, 1)

    def test_budget_semantics_allow_exactly_max_restarts(self):
        t = [0.0]
        pol = RestartPolicy(2, backoff_s=1.0, clock=lambda: t[0])
        for _ in range(2):
            t[0] += 1000.0
            pol.record_failure()
        assert not pol.exhausted()            # 2 healthy restarts allowed
        t[0] += 1000.0
        pol.record_failure()
        assert pol.exhausted()

    def test_negative_budget_rejected(self):
        with pytest.raises(ValueError, match="max_restarts"):
            RestartPolicy(-1)


class TestSupervisor:
    def test_same_size_restart_resumes_from_min_step(self):
        fl = _FakeLauncher(2, [[0, 9], [0, 0]], steps={0: 3, 1: 2})
        res = _supervise(fl, RestartPolicy(2, backoff_s=0.001))
        assert res.outcome == "completed" and res.reason is None
        assert res.restarts == 1 and res.detected_by == "exit=9"
        assert fl.plans[1].world_size == 2
        assert fl.plans[1].resume_step == 2       # gang-wide minimum
        assert fl.plans[1].restore_ranks is None  # identity at same size
        assert fl.gangs[0].killed

    def test_restart_is_fresh_when_a_rank_never_checkpointed(self):
        fl = _FakeLauncher(2, [[17, None], [0, 0]], steps={0: 1})
        res = _supervise(fl, RestartPolicy(2, backoff_s=0.001))
        assert res.outcome == "completed"
        assert fl.plans[1].resume_step is None

    def test_permanent_loss_shrinks_to_surviving_ranks(self):
        fl = _FakeLauncher(3, [[None, LOSE_RANK_EXIT, None], [0, 0]],
                           steps={0: 3, 1: 3, 2: 2})
        res = _supervise(fl, RestartPolicy(2, backoff_s=0.001))
        assert res.outcome == "completed" and res.shrunk
        assert res.world_size == 2
        plan = fl.plans[1]
        # new rank i restores surviving old rank (0, 2)[i]'s checkpoint,
        # from the SURVIVORS' minimum (dead rank 1's step 3 is ignored)
        assert plan.world_size == 2
        assert plan.restore_ranks == (0, 2)
        assert plan.resume_step == 2

    def test_permanent_loss_wins_attribution_over_peer_exits(self):
        # the dying rank's peers often exit non-zero too (torn from the
        # collective); restarting same-size on a peer's code would miss
        # the shrink
        fl = _FakeLauncher(3, [[1, LOSE_RANK_EXIT, 1], [0, 0]],
                           steps={0: 2, 1: 2, 2: 2})
        res = _supervise(fl, RestartPolicy(2, backoff_s=0.001))
        assert res.outcome == "completed"
        assert res.world_size == 2 and res.shrunk
        assert res.events[0].rank == 1
        assert res.events[0].detected_by == f"exit={LOSE_RANK_EXIT}"

    def test_crash_loop_storm_terminates_early(self):
        """Satellite: a gang whose rank 0 dies at every step (kill-rank@
        every-step — each relaunch dies ~immediately) burns the budget at
        DOUBLE rate: max_restarts=4 would allow 4 healthy relaunches, but
        the storm guard gives up after 3 failures (1+2+2 = 5 > 4)."""
        fl = _FakeLauncher(2, [[KILL_RANK_EXIT, None]] * 10,
                           steps={0: 0, 1: 0})
        res = _supervise(fl, RestartPolicy(4, backoff_s=0.001))
        assert res.outcome == "gave_up"
        assert len(fl.plans) == 3            # not 5
        assert res.budget_spent == 5 and res.storm_charges == 2
        assert "storm" in res.reason and "budget exhausted" in res.reason

    def test_shrink_below_min_world_gives_up_with_reason(self):
        fl = _FakeLauncher(2, [[None, LOSE_RANK_EXIT]], steps={0: 2, 1: 2})
        res = _supervise(fl, RestartPolicy(5, backoff_s=0.001),
                         min_world=2)
        assert res.outcome == "gave_up"
        assert "min_world=2" in res.reason and "permanently lost" \
            in res.reason

    def test_zero_budget_reports_first_failure(self):
        fl = _FakeLauncher(2, [[17, None]], steps={})
        res = _supervise(fl, RestartPolicy(0, backoff_s=0.001))
        assert res.outcome == "gave_up" and "max_restarts=0" in res.reason

    def test_deadline_raises_supervisor_timeout(self):
        fl = _FakeLauncher(2, [[None, None]] * 10)
        with pytest.raises(SupervisorTimeout, match="deadline"):
            _supervise(fl, RestartPolicy(2, backoff_s=0.001),
                       deadline_s=0.05, poll_interval_s=0.01)
        assert fl.gangs[0].killed

    def test_heartbeat_detection_via_monitor_factory(self, tmp_path):
        class Mon:
            timeout_s = 1.0

            def __init__(self):
                self.calls = 0

            def stale_ranks(self):
                self.calls += 1
                return [1] if self.calls > 1 else []

        mons = []

        def factory(world):
            mons.append(Mon())
            return mons[-1]

        fl = _FakeLauncher(2, [[None, None], [0, 0]], steps={0: 2, 1: 2})
        res = _supervise(fl, RestartPolicy(2, backoff_s=0.001),
                         monitor_factory=factory, poll_interval_s=0.0)
        assert res.outcome == "completed"
        assert res.events[0].detected_by == "heartbeat>1.0s"
        assert len(mons) == 2                # fresh monitor per launch


class TestNaNGradRollback:
    def test_injected_nan_triggers_one_rollback_and_run_completes(
            self, tmp_path, capsys):
        """Acceptance path 1: nan-grad@2 poisons params+metrics; the
        watchdog rolls back to the iteration-1 checkpoint, the retry (LR
        halved, RNG folded) converges on, and the summary records exactly
        one rollback with the recovery visible in the run log."""
        exp = Experiment.build(SMALL)
        wd = DivergenceWatchdog(max_rollbacks=3)
        inj = FaultInjector([parse_fault("nan-grad@2")])
        with Checkpointer(str(tmp_path / "ck")) as ck:
            out = exp.run(iterations=4, log_every=1, ckpt=ck,
                          ckpt_every=1, watchdog=wd, injector=inj)
        assert out["rollbacks"] == 1
        ev = out["rollback_events"][0]
        assert ev["iteration"] == 2
        assert ev["resume_iteration"] == 2
        assert ev["lr_scale"] == 0.5
        assert ev["restored_step"] is not None
        assert "non-finite" in ev["reason"]
        # the run converged on: final params and every logged row finite
        assert all(np.isfinite(v)
                   for v in jax.tree.leaves(
                       jax.tree.map(lambda x: float(jnp.sum(x)),
                                    exp.train_state.params)))
        final_rows = [h for h in out["history"] if h["iteration"] == 3]
        assert final_rows and all(
            math.isfinite(v) for h in final_rows for v in h.values())
        err = capsys.readouterr().err
        assert "fault-injection: nan-grad at iteration 2" in err
        assert "watchdog:" in err and "rolled back" in err

    def test_without_watchdog_the_fault_really_poisons(self):
        # the control: same fault, no watchdog -> params end non-finite
        # (proves the recovery test above is recovering from a real fault)
        exp = Experiment.build(SMALL)
        inj = FaultInjector([parse_fault("nan-grad@1")])
        exp.run(iterations=2, injector=inj)
        total = sum(float(jnp.sum(x))
                    for x in jax.tree.leaves(exp.train_state.params))
        assert not math.isfinite(total)

    def test_exhausted_budget_raises_divergence_error(self, tmp_path):
        # two distinct faults, budget of one: the second rollback attempt
        # must give up cleanly
        exp = Experiment.build(SMALL)
        wd = DivergenceWatchdog(max_rollbacks=1)
        inj = FaultInjector([parse_fault("nan-grad@1"),
                             parse_fault("nan-grad@2")])
        with Checkpointer(str(tmp_path / "ck")) as ck:
            with pytest.raises(DivergenceError, match="giving up"):
                exp.run(iterations=4, ckpt=ck, ckpt_every=1,
                        watchdog=wd, injector=inj)

    def test_watchdog_requires_checkpointer(self):
        exp = Experiment.build(SMALL)
        with pytest.raises(ValueError, match="ckpt"):
            exp.run(iterations=1, watchdog=DivergenceWatchdog())


class TestCorruptCheckpointFallback:
    def _two_step_store(self, tmp_path):
        exp = Experiment.build(SMALL)
        ck = Checkpointer(str(tmp_path / "ck"), max_to_keep=3)
        exp.run(iterations=2, ckpt=ck, ckpt_every=1)
        ck.wait()
        assert len(ck.all_steps()) >= 2
        return exp, ck

    def test_corrupt_latest_falls_back_to_previous(self, tmp_path, capsys):
        """Acceptance path 2: the latest step's data files are truncated;
        restore lands on the previous retained step instead of raising,
        and says so in the log."""
        exp, ck = self._two_step_store(tmp_path)
        steps = ck.all_steps()
        n = corrupt_checkpoint(ck.directory, steps[-1])
        assert n > 0
        exp2 = Experiment.build(SMALL)
        exp2.restore_checkpoint(ck)
        assert ck.last_restored_step == steps[-2]
        total = sum(float(jnp.sum(x))
                    for x in jax.tree.leaves(exp2.train_state.params))
        assert math.isfinite(total)
        err = capsys.readouterr().err
        assert "falling back to step" in err
        ck.close()

    def test_all_steps_corrupt_raises_restore_error(self, tmp_path):
        exp, ck = self._two_step_store(tmp_path)
        for s in ck.all_steps():
            corrupt_checkpoint(ck.directory, s)
        with pytest.raises(CheckpointRestoreError, match="failed to"):
            Experiment.build(SMALL).restore_checkpoint(ck)
        ck.close()

    def test_explicit_step_does_not_fall_back(self, tmp_path):
        exp, ck = self._two_step_store(tmp_path)
        bad = ck.all_steps()[-1]
        corrupt_checkpoint(ck.directory, bad)
        with pytest.raises(Exception) as ei:
            Experiment.build(SMALL).restore_checkpoint(ck, step=bad)
        assert not isinstance(ei.value, CheckpointRestoreError)
        ck.close()

    def test_corrupt_missing_step_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            corrupt_checkpoint(str(tmp_path), 123)

    def test_checksum_precheck_catches_corruption_cheaply(self, tmp_path,
                                                          capsys):
        """Satellite: the crc32 sidecar rejects the truncated step BEFORE
        orbax ever deserializes it — the fallback log names the checksum
        error, not a deep deserialization failure."""
        exp, ck = self._two_step_store(tmp_path)
        steps = ck.all_steps()
        corrupt_checkpoint(ck.directory, steps[-1])
        Experiment.build(SMALL).restore_checkpoint(ck)
        assert ck.last_restored_step == steps[-2]
        err = capsys.readouterr().err
        assert "CheckpointChecksumError" in err
        assert "crc32 mismatch" in err
        ck.close()

    def test_corruption_past_the_checksum_still_falls_back(self, tmp_path,
                                                           capsys):
        """Satellite: corruption that keeps the sidecar consistent
        (``fix_checksums=True`` re-checksums the truncated files) slips
        past the cheap pre-check — the deep failed-load fallback must
        still land on the previous step."""
        exp, ck = self._two_step_store(tmp_path)
        steps = ck.all_steps()
        corrupt_checkpoint(ck.directory, steps[-1], fix_checksums=True)
        Experiment.build(SMALL).restore_checkpoint(ck)
        assert ck.last_restored_step == steps[-2]
        err = capsys.readouterr().err
        assert "falling back to step" in err
        assert "CheckpointChecksumError" not in err
        ck.close()


class TestPBTDeadMembers:
    def _hp(self, n):
        return HParams(lr=jnp.full((n,), 3e-4),
                       ent_coef=jnp.full((n,), 0.01),
                       clip_eps=jnp.full((n,), 0.2))

    def test_dead_members_reseed_from_best_even_past_quota(self):
        # 2 of 4 dead with exploit_frac=0.25 (quota 1): the old NaN-ranks-
        # worst rule would leave one dead member alive; now both re-seed
        # from the best finite member
        rng = np.random.default_rng(0)
        fitness = np.array([np.nan, 1.0, 2.0, np.inf])
        d = exploit_explore(rng, fitness, self._hp(4),
                            PBTConfig(exploit_frac=0.25))
        assert d.src[0] == 2 and d.src[3] == 2
        assert d.exploited[0] and d.exploited[3]

    def test_winners_never_drawn_from_dead_members(self):
        # divergence reaching the top quantile: member 3 (NaN) sits where
        # argsort-with-NaN-last used to place a winner
        rng = np.random.default_rng(1)
        fitness = np.array([0.0, 1.0, 2.0, np.nan])
        for _ in range(10):
            d = exploit_explore(rng, fitness, self._hp(4),
                                PBTConfig(exploit_frac=0.25))
            assert d.src[0] != 3 and d.src[3] == 2

    def test_no_finite_member_means_nobody_copies(self):
        # nobody to re-seed from: keep states (whole-run rollback is the
        # population watchdog's job, not exploit's)
        rng = np.random.default_rng(2)
        fitness = np.full((4,), np.nan)
        d = exploit_explore(rng, fitness, self._hp(4), PBTConfig())
        assert not d.exploited.any()

    def test_population_run_recovers_injected_member_nan(self, tmp_path):
        """Acceptance path 1 (population flavor): member 1 is poisoned at
        iteration 1; the next exploit round re-seeds it from the best
        member and the run ends with every member finite."""
        cfg = dataclasses.replace(SMALL, n_envs=4)
        exp = PopulationExperiment.build(
            cfg, n_pop=2, mesh=None,
            pbt_cfg=PBTConfig(ready_iters=1, seed=0))
        inj = FaultInjector([parse_fault("nan-grad@1:rank=1")])
        with Checkpointer(str(tmp_path / "ck")) as ck:
            out = exp.run(iterations=4, log_every=1, ckpt=ck,
                          ckpt_every=2, injector=inj,
                          watchdog=DivergenceWatchdog(max_rollbacks=1))
        assert all(np.isfinite(out["final_fitness"])), out["final_fitness"]
        # the catastrophic-case watchdog never had to fire: one dead
        # member is exploit's job
        assert out["rollbacks"] == 0
        total = sum(float(jnp.sum(x))
                    for x in jax.tree.leaves(exp.states.params))
        assert math.isfinite(total)


class TestResilienceCLI:
    def test_nan_grad_rollback_end_to_end(self, tmp_path, capsys):
        summary = train_cli.main(
            ["--config", "ppo-mlp-synth64", *CLI_FAST,
             "--ckpt-dir", str(tmp_path / "ck"), "--ckpt-every", "1",
             "--fault", "nan-grad@2", "--max-rollbacks", "2"])
        assert summary["rollbacks"] == 1
        assert summary["rollback_events"][0]["iteration"] == 2
        assert np.isfinite(summary["env_steps_per_sec"])
        err = capsys.readouterr().err
        assert "fault-injection" in err and "watchdog" in err

    def test_corrupt_ckpt_fault_then_resume_falls_back(self, tmp_path,
                                                       capsys):
        """Acceptance path 2, end to end: the checkpoint saved at
        iteration 3 (the latest) is truncated by the injected fault; the
        resumed run restores the iteration-2 step instead and completes."""
        args = ["--config", "ppo-mlp-synth64", *CLI_FAST,
                "--ckpt-dir", str(tmp_path / "ck"), "--ckpt-every", "1"]
        train_cli.main(args + ["--fault", "corrupt-ckpt@3"])
        assert "corrupted checkpoint" in capsys.readouterr().err
        out = train_cli.main(args + ["--resume"])
        assert out["iterations"] == 4
        assert np.isfinite(out["env_steps_per_sec"])
        assert "falling back to step" in capsys.readouterr().err

    def test_kill_rank_refused_by_single_process_cli(self):
        with pytest.raises(SystemExit, match="multihost"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--fault", "kill-rank@1:rank=0"])

    def test_lose_rank_refused_by_single_process_cli(self):
        with pytest.raises(SystemExit, match="multihost"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--fault", "lose-rank@1:rank=0"])

    def test_bad_fault_spec_exits_with_message(self):
        with pytest.raises(SystemExit, match="fault"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--fault", "nonsense"])

    def test_max_rollbacks_requires_ckpt_dir(self):
        with pytest.raises(SystemExit, match="ckpt-dir"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--max-rollbacks", "2"])

    def test_corrupt_ckpt_fault_requires_ckpt_dir(self):
        with pytest.raises(SystemExit, match="ckpt-dir"):
            train_cli.main(["--config", "ppo-mlp-synth64", *CLI_FAST,
                            "--fault", "corrupt-ckpt@1"])


class TestSelectCheckpointSeedGuards:
    def test_val_seed_matching_eval_probe_default_refused(self):
        from rlgpuschedule_tpu import select_checkpoint
        # config seed 0 -> the in-training probe's default held-out
        # stream is seed 1000; selecting on it is not validation
        with pytest.raises(SystemExit, match="eval-every"):
            select_checkpoint.main(["--ckpt-dir", "/nonexistent",
                                    "--val-seed", "1000"])

    def test_test_seed_must_differ_from_val_seed(self):
        from rlgpuschedule_tpu import select_checkpoint
        with pytest.raises(SystemExit, match="disjoint"):
            select_checkpoint.main(["--ckpt-dir", "/nonexistent",
                                    "--val-seed", "77",
                                    "--test-seed", "77"])

    def test_test_seed_must_differ_from_training_seed(self):
        from rlgpuschedule_tpu import select_checkpoint
        with pytest.raises(SystemExit, match="training seed"):
            select_checkpoint.main(["--ckpt-dir", "/nonexistent",
                                    "--val-seed", "77",
                                    "--test-seed", "0"])
