"""Env API tests: reset/step contract, observation shapes/dtypes, action
masking, reward sign, auto-reset, vectorization (SURVEY.md §4 "Env API
tests")."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu.env import (EnvParams, reset, step, auto_reset_step,
                                   stack_traces, vec_reset, vec_step,
                                   build_adjacency)
from rlgpuschedule_tpu.sim.core import SimParams, Trace
from rlgpuschedule_tpu.traces import gen_poisson_trace, to_array_trace, JobRecord


def make_params(obs_kind="flat", reward_kind="jct", **kw):
    sim = SimParams(n_nodes=4, gpus_per_node=4, max_jobs=16, queue_len=4,
                    n_placements=kw.pop("n_placements", 1))
    return EnvParams(sim=sim, obs_kind=obs_kind, reward_kind=reward_kind,
                     time_scale=100.0, reward_scale=100.0, horizon=64, **kw)


def make_trace(seed=0, n_jobs=12, max_jobs=16):
    tr = gen_poisson_trace(rate=0.05, n_jobs=n_jobs, seed=seed,
                           max_jobs=max_jobs, mean_duration=50.0,
                           gpu_sizes=(1, 2, 4), gpu_probs=(0.6, 0.3, 0.1))
    return Trace.from_array_trace(tr)


class TestResetStep:
    # sanitize: all three obs builders under jax_enable_checks +
    # debug_nans + rank_promotion="raise" (PR 3) — an implicit [K] vs
    # [K, 1] broadcast in queue/run features would silently mis-shape
    # the training signal; raising makes it a failure here
    @pytest.mark.sanitize
    @pytest.mark.parametrize("obs_kind", ["flat", "grid", "graph"])
    def test_obs_shapes_and_dtypes(self, obs_kind):
        params = make_params(obs_kind)
        state, ts = reset(params, make_trace())
        assert ts.obs.shape == params.obs_shape()
        assert ts.obs.dtype == jnp.float32
        assert np.isfinite(np.asarray(ts.obs)).all()
        state, ts = step(params, state, make_trace(), jnp.int32(0))
        assert ts.obs.shape == params.obs_shape()
        assert np.isfinite(np.asarray(ts.obs)).all()

    def test_grid_obs_per_slot_remaining_waterfall(self):
        """VERDICT r4 weak #5: cluster ch1 must expose per-JOB remaining
        within a node, not a node average. Two running jobs sharing node 0
        (2 GPUs at remaining 80, 1 GPU at remaining 20) must paint three
        distinct-valued slots sorted longest-first; the old average would
        paint one uniform value on all three."""
        from rlgpuschedule_tpu.env.obs import grid_obs
        from rlgpuschedule_tpu.sim.core import SimState, RUNNING, DONE, INF

        params = make_params("grid")
        sim = params.sim
        J, N, G = sim.max_jobs, sim.n_nodes, sim.gpus_per_node
        status = np.full(J, DONE, np.int32)
        status[:2] = RUNNING
        remaining = np.zeros(J, np.float32)
        remaining[:2] = [80.0, 20.0]
        alloc = np.zeros((J, N), np.int32)
        alloc[0, 0] = 2
        alloc[1, 0] = 1
        free = np.full(N, G, np.int32)
        free[0] = G - 3
        state = SimState(
            clock=jnp.float32(100.0), status=jnp.asarray(status),
            remaining=jnp.asarray(remaining),
            start=jnp.zeros(J, jnp.float32),
            finish=jnp.full(J, INF, jnp.float32),
            alloc=jnp.asarray(alloc), free=jnp.asarray(free))
        tr = make_trace()
        img = np.asarray(grid_obs(sim, state, tr, params.time_scale))
        node0 = img[0]                       # [G, 2]
        t = params.time_scale
        expect = [np.tanh(80.0 / t), np.tanh(80.0 / t), np.tanh(20.0 / t),
                  0.0]
        np.testing.assert_allclose(node0[:4, 1], expect, rtol=1e-6)
        np.testing.assert_allclose(node0[:, 0],
                                   [1, 1, 1] + [0] * (G - 3))
        # every other node is idle
        assert np.all(img[1:params.sim.n_nodes, :, 1] == 0.0)

    def test_mask_shape_and_noop_always_valid(self):
        params = make_params()
        state, ts = reset(params, make_trace())
        assert ts.action_mask.shape == (params.n_actions,)
        assert bool(ts.action_mask[-1])

    def test_reward_nonpositive_jct(self):
        params = make_params()
        trace = make_trace()
        state, ts = reset(params, trace)
        total = 0.0
        for _ in range(50):
            state, ts = step(params, state, trace, jnp.int32(params.n_actions - 1))
            total += float(ts.reward)
            assert float(ts.reward) <= 0.0
            if bool(ts.done):
                break
        assert total < 0.0  # idling must be penalized

    def test_episode_return_equals_neg_sum_jct(self):
        # greedy head-scheduling to completion: undiscounted return must be
        # exactly -sum(JCT)/scale (reward_jct docstring property)
        params = make_params()
        trace = make_trace()
        state, ts = reset(params, trace)
        total = 0.0
        for _ in range(params.horizon):
            state, ts = step(params, state, trace, jnp.int32(0))
            total += float(ts.reward)
            if bool(ts.done):
                break
        assert bool(ts.info.done)
        from rlgpuschedule_tpu.sim.core import jct_stats
        stats = jct_stats(state.sim, trace)
        want = -float(stats["avg_jct"]) * float(stats["n_done"]) / params.reward_scale
        assert total == pytest.approx(want, rel=1e-4)

    def test_horizon_termination(self):
        params = make_params()
        trace = make_trace()
        state, ts = reset(params, trace)
        noop = jnp.int32(params.n_actions - 1)
        # A pure-noop policy still advances sim time (or force-places), so it
        # terminates via sim completion or horizon — never loops forever.
        for i in range(params.horizon + 1):
            state, ts = step(params, state, trace, noop)
            if bool(ts.done):
                break
        assert bool(ts.done)

    @pytest.mark.parametrize("reward_kind", ["jct", "fair"])
    def test_preempt_cost_charges_the_stall_cycle(self, reward_kind):
        # the pause-the-game exploit: place<->preempt advances no sim
        # time; with preempt_cost each round trip must read strictly
        # negative reward (and the placement leg pays no place_bonus —
        # only FIRST placements do). Parametrized over BOTH reward
        # branches: the charge lives at env.step level because the
        # exploit is an action-space property, not a reward-function one
        import dataclasses as dc
        params = make_params(reward_kind=reward_kind)
        place_bonus = 0.05 if reward_kind == "jct" else 0.0
        params = dc.replace(
            params, preempt_cost=0.05, place_bonus=place_bonus,
            sim=dc.replace(params.sim, preempt_len=2))
        trace = make_trace()
        state, ts = reset(params, trace)
        K, P = params.sim.queue_len, params.sim.n_placements
        place_head = jnp.int32(0)
        preempt_0 = jnp.int32(K * P)       # first preempt slot
        state, ts = step(params, state, trace, place_head)
        first = float(ts.reward)           # first placement: bonus, dt=0
        assert first == pytest.approx(place_bonus)
        total = 0.0
        for _ in range(3):                 # preempt -> re-place cycles
            state, ts = step(params, state, trace, preempt_0)
            assert bool(ts.info.preempted)
            assert float(ts.reward) == pytest.approx(-0.05)
            total += float(ts.reward)
            state, ts = step(params, state, trace, place_head)
            # the re-place leg is charged too (both legs of the stall
            # cycle must bleed) and earns no place_bonus
            assert float(ts.reward) == pytest.approx(-0.05)
            total += float(ts.reward)
        assert total == pytest.approx(6 * -0.05)

    def test_fair_reward_penalizes_concentration(self):
        jobs_conc = [JobRecord(i, 0.0, 100.0, 1, tenant=0) for i in range(4)]
        jobs_even = [JobRecord(i, 0.0, 100.0, 1, tenant=i % 4) for i in range(4)]
        params = make_params(reward_kind="fair", n_tenants=4)
        noop = jnp.int32(params.n_actions - 1)
        rewards = []
        for jobs in (jobs_conc, jobs_even):
            trace = Trace.from_array_trace(to_array_trace(jobs, max_jobs=16))
            state, _ = reset(params, trace)
            # schedule nothing; first noop force-places head, second advances
            state, ts = step(params, state, trace, noop)
            state, ts = step(params, state, trace, noop)
            rewards.append(float(ts.reward))
        # same backlog, but concentrated on one tenant must cost more
        assert rewards[0] < rewards[1] < 0.0


class TestEmptyWindow:
    @pytest.mark.parametrize("obs_kind", ["flat", "grid", "graph"])
    def test_all_padding_trace_obs_finite(self, obs_kind):
        # regression: padding rows have submit=+inf; (clock - inf) * 0 used
        # to produce NaN observations on empty trace windows
        params = make_params(obs_kind)
        empty = Trace.from_array_trace(to_array_trace([], max_jobs=16))
        state, ts = reset(params, empty)
        assert np.isfinite(np.asarray(ts.obs)).all()


class TestAutoReset:
    def test_auto_reset_restarts_episode(self):
        params = make_params()
        trace = make_trace(n_jobs=3)
        state, ts = reset(params, trace)
        jit_step = jax.jit(lambda s, a: auto_reset_step(params, s, trace, a))
        saw_done = False
        for _ in range(200):
            state, ts = jit_step(state, jnp.int32(0))
            if bool(ts.done):
                saw_done = True
                # state must be freshly reset: t == 0, clock == 0
                assert int(state.t) == 0
                assert float(state.sim.clock) == 0.0
                break
        assert saw_done


class TestVectorized:
    @pytest.mark.sanitize   # vmapped reset/step under the strict config
    def test_vec_env_batch(self):
        params = make_params()
        traces = stack_traces([gen_poisson_trace(0.05, 10, seed=s, max_jobs=16,
                                                 mean_duration=50.0,
                                                 gpu_sizes=(1, 2), gpu_probs=(0.7, 0.3))
                               for s in range(3)])
        state, ts = vec_reset(params, traces)
        assert ts.obs.shape == (3,) + params.obs_shape()
        actions = jnp.zeros((3,), jnp.int32)
        state, ts = vec_step(params, state, traces, actions)
        assert ts.reward.shape == (3,)
        assert ts.done.shape == (3,)
        assert ts.action_mask.shape == (3, params.n_actions)


class TestAdjacency:
    def test_build_adjacency(self):
        a = build_adjacency(4, 2, nodes_per_rack=2)
        assert a.shape == (6, 6)
        assert a[0, 1] == 1 and a[0, 2] == 0    # rack-local only
        assert a[0, 4] == 1 and a[4, 0] == 1    # queue bipartite
        assert np.all(np.diag(a) == 1)
