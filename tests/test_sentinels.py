"""Runtime sentinel tests (PR 3): the compile-count monitor and the
transfer guard, plus the recompile-regression gate that protects PR 2's
fused update engine from silent cache-miss regressions.

The regression this gate exists for: a change that makes the jitted
update step re-trace per call (shape-unstable argument, rebuilt function
object, unhashable static capture) slows training by the full compile
time per iteration while every numeric test still passes. The bench
would eventually notice; this makes it a test failure instead.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from rlgpuschedule_tpu.algos.update import (make_update_step,
                                            run_minibatch_epochs)
from rlgpuschedule_tpu.analysis.sentinels import (CompileCounter,
                                                  RecompileSentinelError,
                                                  assert_no_recompiles,
                                                  no_implicit_transfers)


def _make_problem(batch=32, dim=8, seed=0):
    """Tiny linear-regression state + batch for the fused engine."""
    rng = np.random.default_rng(seed)
    params = {"w": jnp.asarray(rng.normal(size=(dim,)).astype(np.float32)),
              "b": jnp.float32(0.0)}
    tx = optax.sgd(1e-2)
    state = (params, tx.init(params))
    x = jnp.asarray(rng.normal(size=(batch, dim)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(batch,)).astype(np.float32))

    def grad_step(state, mb):
        params, opt_state = state
        xb, yb = mb

        def loss_fn(p):
            pred = xb @ p["w"] + p["b"]
            return jnp.mean((pred - yb) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), loss

    return grad_step, state, (x, y)


class TestCompileCounter:
    def test_counts_a_fresh_compile(self):
        with CompileCounter() as c:
            # a never-before-seen shape forces trace + compile
            jax.jit(lambda v: v * 3 + 1)(jnp.ones((7, 13, 3))) \
                .block_until_ready()
        assert c.traces >= 1
        assert c.backend_compiles + c.traces == c.total
        assert c.total >= 1

    def test_listener_detaches_on_exit(self):
        with CompileCounter() as c:
            pass
        before = c.total
        jax.jit(lambda v: v - 2)(jnp.ones((5, 11))).block_until_ready()
        assert c.total == before   # no counting outside the context

    def test_assert_no_recompiles_raises_and_names_the_cause(self):
        with pytest.raises(RecompileSentinelError, match="recompiling"):
            with assert_no_recompiles("fresh-shape region"):
                jax.jit(lambda v: v + 5)(jnp.ones((3, 17, 9)))


class TestTransferGuard:
    def test_implicit_transfer_raises_inside_guard(self):
        # mixing a host numpy array into device math is an implicit
        # host->device transfer — the hidden-upload class the guard
        # exists for. (On the CPU backend device->host reads are
        # zero-copy and unguarded, so host->device is the observable
        # direction in CI; on a TPU both directions trip it.)
        dev = jnp.arange(8.0)
        host = np.ones(8, np.float32)
        with pytest.raises(Exception, match="[Dd]isallow"):
            with no_implicit_transfers():
                _ = (dev + host).block_until_ready()

    def test_explicit_transfers_stay_legal(self):
        dev = jnp.arange(8.0)
        with no_implicit_transfers():
            host = jax.device_get(dev)          # explicit: allowed
            dev2 = jax.device_put(host)         # explicit: allowed
        assert float(np.asarray(dev2)[3]) == 3.0


class TestUpdateStepCompilesOnce:
    """The acceptance gate: N train iterations through make_update_step
    at fixed geometry trigger exactly one compilation — iterations 2..N
    reuse the cached executable, device-resident end to end.

    sanitize-marked (NOT perf): no timing asserts, so CI load can't
    flake it, and running under jax_enable_checks + debug_nans +
    rank_promotion="raise" proves the sentinel composes with the strict
    interpreter the sanitize tier runs."""

    @pytest.mark.sanitize
    def test_geometry_stable_iterations_compile_once(self):
        grad_step, state, data = _make_problem()

        def run_update(state, data, key):
            return run_minibatch_epochs(grad_step, state, data, key,
                                        n_epochs=2, n_minibatches=4)

        step = make_update_step(run_update)   # donates the state
        # precompute per-iteration keys OUTSIDE the counted region —
        # jax.random.split dispatches its own tiny programs
        keys = list(jax.random.split(jax.random.PRNGKey(0), 6))

        with CompileCounter() as warm:
            state, _ = step(state, data, keys[0])
            jax.block_until_ready(state)
        assert warm.traces >= 1   # the one allowed compilation

        # steady state: same geometry, fresh keys, donated state threads
        # through; zero traces, zero backend compiles, zero implicit
        # transfers
        with assert_no_recompiles("geometry-stable update step"):
            with no_implicit_transfers():
                for k in keys[1:]:
                    state, _ = step(state, data, k)
        jax.block_until_ready(state)

    @pytest.mark.sanitize
    def test_geometry_change_recompiles_once_then_caches(self):
        """Control for the gate above: a DIFFERENT geometry must compile
        (proves the counter actually sees this program class), and
        returning to it again must not."""
        grad_step, state, data = _make_problem(batch=48)

        def run_update(state, data, key):
            return run_minibatch_epochs(grad_step, state, data, key,
                                        n_epochs=1, n_minibatches=3)

        step = make_update_step(run_update)
        keys = list(jax.random.split(jax.random.PRNGKey(1), 3))
        with CompileCounter() as first:
            state, _ = step(state, data, keys[0])
            jax.block_until_ready(state)
        assert first.traces >= 1
        with assert_no_recompiles("repeat of a cached geometry"):
            for k in keys[1:]:
                state, _ = step(state, data, k)
        jax.block_until_ready(state)
