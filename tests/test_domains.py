"""Domain-randomization engine + generalization matrix (ISSUE 14).

Five contracts pin the tentpole:

1. **Samplers are seeded data with fail-fast validation** — draws are
   bit-deterministic in (seed, regime), capacities respect the
   [0, gpus_per_node] bound, and malformed specs/schedules are refused
   loudly (never a silently-wrong cluster).
2. **Oracle parity under heterogeneous speeds + drawn geometry** — the
   jitted sim under a :class:`DomainSchedule` (per-node capacity AND
   dyadic speed factors) reproduces ``OracleSim`` trajectory-for-
   trajectory, f32-exact — same regime as tests/test_sim_faults.py.
3. **Conservation under geometry randomization** — at every step of
   random action sequences, each node's ``free + allocated`` equals its
   DRAWN capacity and no valid job leaves the lifecycle.
4. **Domains are data, not code** — stepping under draws from different
   regimes must not retrace (CompileCounter), and a whole second
   ``matrix_report`` over fresh draws compiles NOTHING.
5. **The matrix** — shape, degradation-vs-none, conservation, obs bus
   events/gauges, and CLI refusals for the mode combinations that have
   no domain threading.
"""
import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu import domains as D
from rlgpuschedule_tpu.sim import core as C
from rlgpuschedule_tpu.sim import faults as F
from rlgpuschedule_tpu.sim import oracle as O
from rlgpuschedule_tpu.traces import JobRecord, to_array_trace
from rlgpuschedule_tpu.traces.fit import TraceFit, fit_jobs, gen_domain_window

from tests.test_sim_faults import int_faults, int_trace


def device_schedule(ds):
    return jax.tree.map(jnp.asarray, ds)


def dyadic_draw(rng, n_nodes, gpus_per_node):
    """Hand-built draw with dyadic slowdowns (f32-exact stretch — the
    oracle-parity regime) and random but non-empty geometry."""
    cap = rng.integers(0, gpus_per_node + 1, size=n_nodes).astype(np.int32)
    if cap.sum() == 0:
        cap[0] = gpus_per_node
    slow = rng.choice([1.0, 2.0, 4.0], size=n_nodes).astype(np.float32)
    return D.DomainDraw(spec_name="test", capacity=cap, slowdown=slow,
                        load=1.0, duration_scale=1.0, burst_frac=0.0,
                        diurnal=False)


class TestSamplers:
    def test_spec_range_fail_fasts(self):
        with pytest.raises(ValueError, match="capacity_min_frac"):
            D.DomainSpec("x", capacity_min_frac=0.0)
        with pytest.raises(ValueError, match="p_node_off"):
            D.DomainSpec("x", p_node_off=1.5)
        with pytest.raises(ValueError, match="slowdown_min"):
            D.DomainSpec("x", slowdown_min=0.5)
        with pytest.raises(ValueError, match="load_min"):
            D.DomainSpec("x", load_min=1.2, load_max=0.8)
        with pytest.raises(ValueError, match="duration_scale"):
            D.DomainSpec("x", duration_scale_min=0.0)

    def test_unknown_regime_rejected(self):
        with pytest.raises(ValueError, match="unknown domain regime"):
            D.resolve_domain("meteor")

    def test_draws_seed_deterministically_per_regime(self):
        for name in D.DOMAIN_REGIMES:
            a = D.sample_domain(name, 4, 8, (7, 0))
            b = D.sample_domain(name, 4, 8, (7, 0))
            np.testing.assert_array_equal(a.capacity, b.capacity)
            np.testing.assert_array_equal(a.slowdown, b.slowdown)
            assert (a.load, a.duration_scale) == (b.load, b.duration_scale)
        # the regime name is folded into the entropy: same seed, distinct
        # regimes must not alias onto one cluster
        caps = {tuple(D.sample_domain(n, 16, 8, 0).capacity)
                for n in ("geom", "mixed")}
        loads = {D.sample_domain(n, 16, 8, 0).load
                 for n in ("baseline", "mixed")}
        assert len(caps) == 2 or len(loads) == 2

    def test_draw_capacity_bounds_and_nonempty(self):
        for e in range(50):
            d = D.sample_domain("mixed", 6, 4, (3, e))
            assert d.capacity.dtype == np.int32
            assert (d.capacity >= 0).all() and (d.capacity <= 4).all()
            assert d.total_gpus >= 1
            assert (d.slowdown >= 1.0).all()
            assert d.load > 0 and d.duration_scale > 0

    def test_overload_regime_pins_the_weakness_load(self):
        d = D.sample_domain("overload", 4, 8, (0, 0))
        assert d.load == pytest.approx(1.6)
        assert d.total_gpus == 32    # overload is a LOAD shift only

    def test_validate_schedule_fail_fasts(self):
        good = D.domain_schedule(dyadic_draw(np.random.default_rng(0),
                                             3, 4))
        D.validate_domain_schedule(3, 4, good)   # ok
        bad = good._replace(capacity=good.capacity[:2])
        with pytest.raises(ValueError, match="shape"):
            D.validate_domain_schedule(3, 4, bad)
        bad = good._replace(capacity=good.capacity.astype(np.float32))
        with pytest.raises(ValueError, match="integral"):
            D.validate_domain_schedule(3, 4, bad)
        bad = good._replace(capacity=np.array([9, 1, 1], np.int32))
        with pytest.raises(ValueError, match=r"\[0, 4\]"):
            D.validate_domain_schedule(3, 4, bad)
        bad = good._replace(capacity=np.zeros(3, np.int32))
        with pytest.raises(ValueError, match="zero GPUs"):
            D.validate_domain_schedule(3, 4, bad)

    def test_schedule_composes_worst_slowdown_with_faults(self):
        draw = D.DomainDraw("test", np.array([4, 4], np.int32),
                            np.array([1.0, 4.0], np.float32),
                            1.0, 1.0, 0.0, False)
        fs = F.no_faults(2, 1)
        fs.slowdown[:] = [2.0, 2.0]
        ds = D.domain_schedule(draw, F.validate_fault_schedule(2, fs))
        # elementwise max: the worst factor wins, never the product
        np.testing.assert_array_equal(ds.slowdown, [2.0, 4.0])
        with pytest.raises(ValueError, match="node"):
            D.domain_schedule(draw, F.no_faults(3, 1))


class TestFitAndWindows:
    def _jobs(self, rng, n=200):
        return [JobRecord(i, float(rng.uniform(0, 1000)),
                          float(rng.lognormal(5.0, 1.0)),
                          int(rng.choice([1, 2, 4, 8])),
                          int(rng.integers(0, 3)))
                for i in range(n)]

    def test_fit_jobs_recovers_the_mix(self):
        rng = np.random.default_rng(0)
        fit = fit_jobs(self._jobs(rng), name="t")
        assert fit.median_duration_s > 0 and 0.5 < fit.sigma < 2.0
        assert set(fit.gpu_sizes) == {1, 2, 4, 8}
        assert abs(sum(fit.gpu_probs) - 1.0) < 1e-6
        assert fit.n_tenants == 3

    def test_gen_window_fail_fasts(self):
        fit = TraceFit("t", 100.0, 1.0, (1, 2), (0.5, 0.5))
        with pytest.raises(ValueError, match="n_jobs"):
            gen_domain_window(fit, 0, 0, n_gpus=8, load=1.0)
        with pytest.raises(ValueError, match="load"):
            gen_domain_window(fit, 8, 0, n_gpus=8, load=0.0)
        with pytest.raises(ValueError, match="n_gpus"):
            gen_domain_window(fit, 8, 0, n_gpus=0, load=1.0)

    def test_gen_window_deterministic_and_gang_renormalized(self):
        fit = TraceFit("t", 100.0, 1.0, (1, 2, 4, 8),
                       (0.4, 0.3, 0.2, 0.1))
        a = gen_domain_window(fit, 32, (5, 0), n_gpus=4, load=1.0,
                              max_gang=2)
        b = gen_domain_window(fit, 32, (5, 0), n_gpus=4, load=1.0,
                              max_gang=2)
        np.testing.assert_array_equal(a.submit, b.submit)
        np.testing.assert_array_equal(a.gpus, b.gpus)
        # a shrunken cluster never receives a gang it cannot place
        assert np.asarray(a.gpus)[np.asarray(a.valid)].max() <= 2
        assert (np.asarray(a.duration)[np.asarray(a.valid)] >= 1.0).all()

    def test_offered_load_scales_arrivals(self):
        fit = TraceFit("t", 100.0, 1.0, (1,), (1.0,))
        lo = gen_domain_window(fit, 64, 1, n_gpus=8, load=0.5)
        hi = gen_domain_window(fit, 64, 1, n_gpus=8, load=2.0)
        span = lambda w: float(np.asarray(w.submit)[np.asarray(w.valid)]
                               .max())
        # 4x the offered load packs the same jobs into ~1/4 the span
        assert span(hi) < span(lo) / 2

    def test_flash_crowd_concentrates_arrivals(self):
        fit = TraceFit("t", 100.0, 1.0, (1,), (1.0,))
        flash = gen_domain_window(fit, 64, 2, n_gpus=8, load=1.0,
                                  burst_frac=0.5)
        sub = np.sort(np.asarray(flash.submit)[np.asarray(flash.valid)])
        gaps = np.diff(sub)
        # half the window lands on one instant: many near-zero gaps
        assert (gaps < 1e-3).sum() >= 16


def run_pair_domain(trace, ds, n_nodes, gpus_per_node, actions, queue_len,
                    n_placements=2, preempt_len=0):
    """Oracle and JAX sim under the same DomainSchedule (drawn capacity +
    hetero speed + drains); full-trajectory comparison after every step.
    The twin of test_sim_faults.run_pair_faulty with geometry as data:
    init_state seeds the free vector from the schedule."""
    params = C.SimParams(n_nodes=n_nodes, gpus_per_node=gpus_per_node,
                         max_jobs=trace.max_jobs, queue_len=queue_len,
                         n_placements=n_placements, preempt_len=preempt_len)
    osim = O.OracleSim(trace, n_nodes, gpus_per_node, faults=ds)
    np.testing.assert_array_equal(osim.node_capacity, ds.capacity)
    tr = C.Trace.from_array_trace(trace)
    dsd = device_schedule(ds)
    jstate = C.init_state(params, tr, dsd)
    step = jax.jit(lambda s, f, a: C.rl_step(params, s, tr, a, f))
    for i, a in enumerate(actions):
        oinfo = osim.rl_step(int(a), queue_len, n_placements, preempt_len)
        jstate, jinfo = step(jstate, dsd, jnp.int32(a))
        s = C.np_state(jstate)
        ctx = f"step {i} action {a}"
        np.testing.assert_allclose(s.clock, osim.clock, atol=1e-3,
                                   err_msg=ctx)
        np.testing.assert_array_equal(s.status, osim.status, err_msg=ctx)
        np.testing.assert_allclose(s.remaining, osim.remaining, atol=1e-3,
                                   err_msg=ctx)
        np.testing.assert_array_equal(s.alloc, osim.alloc, err_msg=ctx)
        np.testing.assert_array_equal(s.free, osim.free, err_msg=ctx)
        assert bool(jinfo.placed) == oinfo["placed"], ctx
        assert bool(jinfo.done) == oinfo["done"], ctx
        # conservation against the DRAWN capacity at every step
        np.testing.assert_array_equal(s.alloc.sum(axis=0) + s.free,
                                      ds.capacity, err_msg=ctx)
    assert osim.gpus_consistent()


class TestOracleParityHeteroGeometry:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_actions_random_domains(self, seed):
        rng = np.random.default_rng(seed)
        n_nodes, g = int(rng.integers(2, 5)), int(rng.integers(2, 5))
        draw = dyadic_draw(rng, n_nodes, g)
        # widest valid gang = the drawn total, not the static one
        trace = int_trace(rng, 12, max(draw.total_gpus // 2, 1),
                          max_jobs=16)
        ds = D.validate_domain_schedule(
            n_nodes, g, D.domain_schedule(draw, int_faults(rng, n_nodes)))
        actions = rng.integers(
            0, C.SimParams(n_nodes, g, 16, 4, 2, 2).n_actions, size=60)
        run_pair_domain(trace, ds, n_nodes, g, actions, queue_len=4,
                        preempt_len=2)

    def test_half_speed_node_doubles_service(self):
        trace = to_array_trace([JobRecord(0, 0.0, 10.0, 2)], max_jobs=2)
        params = C.SimParams(2, 2, max_jobs=2, queue_len=2, n_placements=1)
        tr = C.Trace.from_array_trace(trace)
        draw = D.DomainDraw("test", np.array([2, 2], np.int32),
                            np.array([2.0, 1.0], np.float32),
                            1.0, 1.0, 0.0, False)
        ds = device_schedule(D.domain_schedule(draw))
        state = C.init_state(params, tr, ds)
        state, info = C.rl_step(params, state, tr, jnp.int32(0), ds)
        assert bool(info.placed)
        state, info = C.rl_step(params, state, tr,
                                jnp.int32(params.n_actions - 1), ds)
        # placed on the x2 node: 10s of work completes at t=20
        assert float(state.clock) == 20.0 and bool(info.done)

    def test_absent_node_is_never_allocated(self):
        rng = np.random.default_rng(4)
        draw = D.DomainDraw("test", np.array([0, 4], np.int32),
                            np.ones(2, np.float32), 1.0, 1.0, 0.0, False)
        trace = int_trace(rng, 8, 3, max_jobs=8)
        params = C.SimParams(2, 4, max_jobs=8, queue_len=4, n_placements=2)
        tr = C.Trace.from_array_trace(trace)
        ds = device_schedule(D.domain_schedule(draw))
        state = C.init_state(params, tr, ds)
        step = jax.jit(lambda s, a: C.rl_step(params, s, tr, a, ds))
        for a in rng.integers(0, params.n_actions, size=40):
            state, _ = step(state, jnp.int32(a))
            s = C.np_state(state)
            assert s.alloc[:, 0].sum() == 0 and s.free[0] == 0


class TestCompileOnceAcrossDomains:
    def test_step_zero_retrace_across_regime_draws(self):
        from rlgpuschedule_tpu.analysis.sentinels import CompileCounter
        rng = np.random.default_rng(0)
        trace = int_trace(rng, 10, 2, max_jobs=12)
        params = C.SimParams(3, 4, max_jobs=12, queue_len=4,
                             n_placements=1, preempt_len=2)
        tr = C.Trace.from_array_trace(trace)
        schedules = [device_schedule(D.validate_domain_schedule(
            3, 4, D.domain_schedule(D.sample_domain(name, 3, 4, (s, 0)))))
            for s, name in enumerate(D.DOMAIN_REGIMES)]
        step = jax.jit(lambda s, f, a: C.rl_step(params, s, tr, a, f))
        state = C.init_state(params, tr, schedules[0])
        state, _ = step(state, schedules[0], jnp.int32(0))     # warmup
        jax.block_until_ready(state.clock)
        with CompileCounter() as counter:
            for ds in schedules[1:]:
                st = C.init_state(params, tr, ds)
                for a in rng.integers(0, params.n_actions, size=4):
                    st, _ = step(st, ds, jnp.int32(a))
            jax.block_until_ready(st.clock)
        assert counter.total == 0, counter.events

    def test_matrix_report_second_sweep_compiles_nothing(self):
        """A whole second matrix (fresh seed -> fresh draws, fresh
        generated windows, every regime) must reuse the first sweep's
        compiled cell — the ISSUE 14 acceptance gate: one compiled step
        serves the entire domain distribution."""
        from rlgpuschedule_tpu.analysis.sentinels import CompileCounter
        from rlgpuschedule_tpu.eval import matrix_report
        from rlgpuschedule_tpu.experiment import Experiment
        from rlgpuschedule_tpu.configs import CONFIGS
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, n_nodes=2,
            gpus_per_node=4, window_jobs=16, queue_len=4, horizon=256)
        exp = Experiment.build(cfg)
        kw = dict(regimes=("geom", "overload"), baselines=("sjf",),
                  max_steps=192)
        matrix_report(exp, seed=0, **kw)                       # warmup
        with CompileCounter() as counter:
            report = matrix_report(exp, seed=1, **kw)
        assert counter.total == 0, counter.events
        assert report["jobs_lost"] == 0


class TestEnvAndTrainingWiring:
    def _cfg(self, **kw):
        from rlgpuschedule_tpu.configs import CONFIGS
        base = dict(n_envs=2, n_nodes=2, gpus_per_node=4, window_jobs=16,
                    queue_len=4, horizon=64, iterations=2,
                    domains="mixed")
        return dataclasses.replace(CONFIGS["ppo-mlp-synth64"],
                                   **{**base, **kw})

    def test_domain_obs_shape_and_geometry_values(self):
        from rlgpuschedule_tpu.env import env as env_lib
        params = C.SimParams(2, 4, max_jobs=4, queue_len=2, n_placements=1)
        ep = env_lib.EnvParams(sim=params,
                               domain_process=D.resolve_domain("mixed"),
                               domain_obs=True)
        base = env_lib.EnvParams(sim=params)
        assert ep.obs_shape()[0] == base.obs_shape()[0] + 2
        trace = to_array_trace([JobRecord(0, 0.0, 5.0, 1)], max_jobs=4)
        tr = C.Trace.from_array_trace(trace)
        draw = D.DomainDraw("test", np.array([2, 4], np.int32),
                            np.ones(2, np.float32), 1.0, 1.0, 0.0, False)
        ds = device_schedule(D.domain_schedule(draw))
        _, ts = env_lib.reset(ep, tr, ds)
        # geometry channel: capacity / gpus_per_node, appended LAST
        np.testing.assert_allclose(np.asarray(ts.obs[-2:]), [0.5, 1.0])
        # schedule=None replay reads as the full fixed cluster
        _, ts = env_lib.reset(ep, tr)
        np.testing.assert_allclose(np.asarray(ts.obs[-2:]), [1.0, 1.0])

    def test_domain_obs_refused_for_grid(self):
        from rlgpuschedule_tpu.env import env as env_lib
        params = C.SimParams(2, 2, max_jobs=4, queue_len=2)
        with pytest.raises(ValueError, match="FLAT"):
            env_lib.EnvParams(sim=params, obs_kind="grid",
                              domain_obs=True)

    def test_domains_none_is_bit_identical(self):
        # the pre-domains program: no schedule -> static full cluster,
        # and a full-capacity no-fault DomainSchedule is the SAME state
        rng = np.random.default_rng(0)
        trace = int_trace(rng, 6, 4, max_jobs=8)
        params = C.SimParams(2, 4, max_jobs=8, queue_len=4)
        tr = C.Trace.from_array_trace(trace)
        clean = C.init_state(params, tr)
        np.testing.assert_array_equal(np.asarray(clean.free), [4, 4])
        draw = D.DomainDraw("test", np.array([4, 4], np.int32),
                            np.ones(2, np.float32), 1.0, 1.0, 0.0, False)
        ds = device_schedule(D.domain_schedule(draw))
        seeded = C.init_state(params, tr, ds)
        for a, b in zip(jax.tree.leaves(clean), jax.tree.leaves(seeded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_experiment_trains_under_domains(self):
        from rlgpuschedule_tpu.experiment import Experiment
        exp = Experiment.build(self._cfg())
        assert exp.domains is not None and len(exp.domains) == 2
        assert exp.env_params.domain_obs and exp.env_params.fault_obs
        assert isinstance(exp.faults, D.DomainSchedule)
        # windows were generated against each draw's ACTUAL capacity
        for w, d in zip(exp.windows, exp.domains):
            gpus = np.asarray(w.gpus)[np.asarray(w.valid)]
            assert gpus.max() <= d.total_gpus
        out = exp.run(log_every=1)
        assert np.isfinite(out["history"][-1]["total_loss"])

    def test_window_streaming_regenerates_domain_windows(self):
        from rlgpuschedule_tpu.experiment import Experiment
        exp = Experiment.build(self._cfg(resample_every=1))
        first = [np.asarray(w.submit).copy() for w in exp.windows]
        exp.run(log_every=1)
        assert exp.window_cursor > 0
        changed = any(not np.array_equal(a, np.asarray(w.submit))
                      for a, w in zip(first, exp.windows))
        assert changed    # fresh draws of the arrival process, same shape

    def test_mode_table_rows(self):
        from rlgpuschedule_tpu.configs import MODE_REFUSALS
        pairs = {frozenset((a, b)) for a, b, _ in MODE_REFUSALS}
        assert frozenset(("pbt", "faults")) not in pairs   # ISSUE 14 sat 1
        assert frozenset(("pbt", "domains")) in pairs
        assert frozenset(("hier", "domains")) in pairs

    def test_hier_and_pbt_refuse_domains(self):
        from rlgpuschedule_tpu.experiment import (Experiment,
                                                  PopulationExperiment)
        with pytest.raises(ValueError, match="domain"):
            Experiment.build(self._cfg(n_pods=2, n_nodes=4))
        with pytest.raises(ValueError, match="domain"):
            PopulationExperiment.build(self._cfg(), n_pop=2)


class TestMatrixReport:
    def _exp(self):
        from rlgpuschedule_tpu.experiment import Experiment
        from rlgpuschedule_tpu.configs import CONFIGS
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, n_nodes=2,
            gpus_per_node=4, window_jobs=16, queue_len=4, horizon=256)
        return Experiment.build(cfg)

    def test_matrix_shape_degradation_conservation_and_bus(self, tmp_path):
        from rlgpuschedule_tpu.eval import matrix_report
        from rlgpuschedule_tpu.obs import EventBus, Registry, read_events
        exp = self._exp()
        bus = EventBus(str(tmp_path), rank=0, name="matrix")
        registry = Registry()
        report = matrix_report(exp, regimes=("geom",), baselines=("sjf",),
                               seed=0, max_steps=192, bus=bus,
                               registry=registry)
        bus.close()
        assert set(report["cells"]) == {"none", "geom"}
        for cols in report["cells"].values():
            assert set(cols) == {"policy", "sjf"}
            for row in cols.values():
                assert {"avg_jct", "completion", "degradation"} <= set(row)
        assert report["cells"]["none"]["policy"]["degradation"] == 1.0
        assert report["jobs_lost"] == 0
        assert report["domain_stats"]["geom"]["mean_total_gpus"] <= 8.0
        events = read_events(str(tmp_path / "events.matrix.jsonl"))
        cells = [e for e in events if e["kind"] == "domain_cell"]
        assert {(e["regime"], e["scheduler"]) for e in cells} == {
            ("none", "policy"), ("none", "sjf"),
            ("geom", "policy"), ("geom", "sjf")}
        assert "matrix_none_policy_avg_jct" in registry.render()

    def test_matrix_refuses_mismatched_row_geometry(self):
        from rlgpuschedule_tpu.eval import matrix_report
        exp = self._exp()
        other = dataclasses.replace(
            exp.env_params, sim=dataclasses.replace(exp.env_params.sim,
                                                    gpus_per_node=8))
        with pytest.raises(ValueError, match="sim geometry"):
            matrix_report(exp, regimes=("geom",), policies={
                "a": (exp.apply_fn, exp.train_state.params,
                      exp.env_params),
                "b": (exp.apply_fn, exp.train_state.params, other)})


class TestFullTraceSchedules:
    def test_shift_schedule_rebase(self):
        from rlgpuschedule_tpu.eval import _shift_schedule
        fs = F.no_faults(1, 3)
        fs.down_start[0] = [10.0, 50.0, 90.0]
        fs.down_end[0] = [20.0, 60.0, 100.0]
        out = _shift_schedule(F.validate_fault_schedule(1, fs), 55.0)
        # past window -> never-active; straddling -> active from local 0;
        # future -> shifted left
        np.testing.assert_allclose(out.down_start[0], [np.inf, 0.0, 35.0])
        np.testing.assert_allclose(out.down_end[0], [np.inf, 5.0, 45.0])
        draw = D.DomainDraw("test", np.array([3], np.int32),
                            np.array([2.0], np.float32),
                            1.0, 1.0, 0.0, False)
        ds = D.domain_schedule(draw, F.validate_fault_schedule(1, fs))
        out = _shift_schedule(ds, 55.0)
        assert isinstance(out, D.DomainSchedule)   # type survives rebase
        np.testing.assert_array_equal(out.capacity, [3])
        np.testing.assert_array_equal(out.slowdown, [2.0])

    def test_stitched_replay_feels_hetero_slowdown(self):
        from rlgpuschedule_tpu.eval import full_trace_report
        from rlgpuschedule_tpu.experiment import Experiment
        from rlgpuschedule_tpu.configs import CONFIGS
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, n_nodes=2,
            gpus_per_node=4, window_jobs=16, queue_len=4, horizon=64,
            source_jobs=24)
        exp = Experiment.build(cfg)
        draw = D.DomainDraw("test", np.array([4, 4], np.int32),
                            np.array([2.0, 2.0], np.float32),
                            1.0, 1.0, 0.0, False)
        ds = D.domain_schedule(draw)
        slow = full_trace_report(exp, include_random=False,
                                 baselines=("sjf",), faults=ds)
        clean = full_trace_report(exp, include_random=False,
                                  baselines=("sjf",))
        assert slow["faulty_cluster"] is True
        # every node at half speed: strictly worse JCT for everyone
        assert slow["policy"] > clean["policy"]
        assert slow["sjf"] > clean["sjf"]

    def test_demand_check_uses_drawn_capacity(self):
        from rlgpuschedule_tpu.eval import full_trace_replay
        from rlgpuschedule_tpu.experiment import Experiment
        from rlgpuschedule_tpu.configs import CONFIGS
        cfg = dataclasses.replace(
            CONFIGS["ppo-mlp-synth64"], n_envs=2, n_nodes=2,
            gpus_per_node=4, window_jobs=16, queue_len=4, horizon=64,
            source_jobs=24)
        exp = Experiment.build(cfg)
        draw = D.DomainDraw("test", np.array([1, 0], np.int32),
                            np.ones(2, np.float32), 1.0, 1.0, 0.0, False)
        with pytest.raises(ValueError, match="drawn cluster has 1"):
            full_trace_replay(exp.apply_fn, exp.train_state.params,
                              exp.env_params, exp.source,
                              faults=D.domain_schedule(draw))


class TestCLIRefusals:
    def test_matrix_flag_refusals(self):
        from rlgpuschedule_tpu import evaluate
        for argv in (["--matrix", "--chaos"],
                     ["--matrix-regimes", "geom"],
                     ["--matrix", "--matrix-regimes", "meteor"],
                     ["--matrix", "--eval-windows", "4"],
                     ["--matrix", "--matrix-ckpt", "nodir"],
                     ["--stitch-domain", "hetero"],
                     ["--obs-dir", "/tmp/x"]):
            with pytest.raises(SystemExit):
                evaluate.main(argv)
