"""Native C++ baseline engine: cross-validation against the Python oracle
(SURVEY.md §2 "Native components", §4 "Baseline-scheduler oracle tests" —
the two backends must produce identical schedules)."""
import time

import numpy as np
import pytest

from rlgpuschedule_tpu import native
from rlgpuschedule_tpu.sim.schedulers import run_baseline
from rlgpuschedule_tpu.traces import gen_poisson_trace
from rlgpuschedule_tpu.traces.records import JobRecord, to_array_trace

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native engine unavailable: {native.build_error()}")

POLICIES = ("fifo", "sjf", "srtf", "tiresias")


class TestCrossValidation:
    @pytest.mark.parametrize("name", POLICIES)
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_python_oracle(self, name, seed):
        """Bit-identical finish times vs the oracle on overloaded random
        traces (rate·E[dur]·E[gpus] >> capacity forces deep queues,
        preemption, and Tiresias demotions)."""
        tr = gen_poisson_trace(0.05, 80, seed=seed, mean_duration=2000.0)
        py = run_baseline(tr, 2, 8, name, backend="python")
        nat = run_baseline(tr, 2, 8, name, backend="native")
        np.testing.assert_allclose(
            np.where(np.isnan(nat.finish), np.inf, nat.finish)[tr.valid],
            np.where(np.isnan(py.finish), np.inf, py.finish)[tr.valid],
            rtol=0, atol=1e-6)
        np.testing.assert_allclose(
            np.where(np.isnan(nat.start), np.inf, nat.start)[tr.valid],
            np.where(np.isnan(py.start), np.inf, py.start)[tr.valid],
            rtol=0, atol=1e-6)
        assert nat.avg_jct() == pytest.approx(py.avg_jct(), rel=1e-9)
        np.testing.assert_array_equal(nat.status, py.status)

    @pytest.mark.parametrize("name", POLICIES)
    def test_underloaded_trace(self, name):
        tr = gen_poisson_trace(0.001, 30, seed=3, mean_duration=100.0)
        py = run_baseline(tr, 4, 8, name, backend="python")
        nat = run_baseline(tr, 4, 8, name, backend="native")
        assert nat.avg_jct() == pytest.approx(py.avg_jct(), rel=1e-9)

    def test_hand_checked_fifo(self):
        """2-GPU cluster, three 2-GPU jobs of 10s at t=0: FIFO serializes
        them → finishes 10/20/30, JCTs 10/20/30."""
        tr = to_array_trace([JobRecord(0, 0.0, 10.0, 2),
                             JobRecord(1, 0.0, 10.0, 2),
                             JobRecord(2, 0.0, 10.0, 2)])
        nat = run_baseline(tr, 1, 2, "fifo", backend="native")
        np.testing.assert_allclose(sorted(nat.jcts()), [10.0, 20.0, 30.0])

    def test_srtf_preempts(self):
        """Long job starts, short job arrives: SRTF preempts the long one;
        short JCT = its duration."""
        tr = to_array_trace([JobRecord(0, 0.0, 100.0, 2),
                             JobRecord(1, 5.0, 10.0, 2)])
        nat = run_baseline(tr, 1, 2, "srtf", backend="native")
        py = run_baseline(tr, 1, 2, "srtf", backend="python")
        np.testing.assert_allclose(sorted(nat.jcts()), sorted(py.jcts()))
        assert min(nat.jcts()) == pytest.approx(10.0)


class TestErrorsAndSpeed:
    def test_oversized_gang_raises(self):
        tr = to_array_trace([JobRecord(0, 0.0, 10.0, 64)])
        with pytest.raises(RuntimeError):
            native.run_baseline_native(tr, 1, 8, "fifo")

    def test_unknown_policy(self):
        tr = to_array_trace([JobRecord(0, 0.0, 10.0, 1)])
        with pytest.raises(ValueError):
            native.run_baseline_native(tr, 1, 8, "nope")

    def test_large_trace_fast(self):
        """Production-scale sanity: 20k jobs through a preemptive policy in
        seconds, not minutes (the point of the native engine)."""
        tr = gen_poisson_trace(0.5, 20_000, seed=7, mean_duration=1800.0)
        t0 = time.time()
        nat = run_baseline(tr, 64, 8, "tiresias", backend="native")
        wall = time.time() - t0
        assert np.isfinite(nat.avg_jct())
        assert len(nat.jcts()) == tr.num_jobs
        assert wall < 30.0, f"native tiresias took {wall:.1f}s on 20k jobs"
