"""In-process coverage of the driver entry points (__graft_entry__.py).

The driver compile-checks ``entry()`` single-chip and runs
``dryrun_multichip(8)`` on a virtual 8-CPU mesh (SURVEY.md §4
"Distributed without a real cluster"). These tests call the exact same
functions under the conftest-pinned 8-device CPU platform, so a breakage
in either gate is caught in CI rather than at judge time.
"""
import jax


def test_entry_compiles_and_runs():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    for leaf in jax.tree.leaves(out):
        assert bool(jax.numpy.all(jax.numpy.isfinite(leaf)))


def test_dryrun_multichip_8_devices():
    import __graft_entry__ as ge

    assert jax.device_count() >= 8
    ge.dryrun_multichip(8)


def test_force_cpu_idempotent_when_initialized():
    # backends are already initialized as CPU by conftest; the pin must be
    # a no-op that still returns the CPU devices
    from rlgpuschedule_tpu.utils.platform import force_cpu

    devices = force_cpu(8)
    assert len(devices) == 8
    assert all(d.platform == "cpu" for d in devices)
