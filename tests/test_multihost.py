"""Multi-host execution tests (SURVEY.md §5 "Distributed communication
backend" DCN row; VERDICT r2 missing #2 / next-round #4).

``dryrun_multihost`` spawns 2 fresh jax.distributed processes × 4 CPU
devices each and proves the DP gradient psum and the PBT exploit gather
cross the process boundary — the same program shape a 2-host v5e-16
deployment runs, with gloo standing in for DCN. The in-process helpers
(``process_env_slice``, ``global_traces``) are additionally unit-tested on
the conftest's single-process 8-device mesh, where global == local.

Every test that spawns a real gang carries the ``multihost_spawn``
marker: they are CPU-contention-sensitive (gloo's ~30s collective
rendezvous races per-rank XLA compile on a loaded small rig), so
``ci.sh`` runs this subset serially AFTER the main tier-1 pass.
"""
import numpy as np
import jax
import pytest

from rlgpuschedule_tpu.parallel import make_mesh
from rlgpuschedule_tpu.parallel import multihost


class TestHelpersSingleProcess:
    def test_process_env_slice_covers_all_rows(self):
        mesh = make_mesh()
        assert multihost.process_env_slice(mesh, 16) == slice(0, 16)

    def test_global_traces_roundtrip(self):
        from rlgpuschedule_tpu.parallel import env_sharded
        mesh = make_mesh()
        local = {"a": np.arange(32, dtype=np.float32).reshape(16, 2),
                 "b": np.ones((16,), np.int32)}
        glob = multihost.global_traces(mesh, local, 16)
        np.testing.assert_array_equal(np.asarray(glob["a"]), local["a"])
        # rows must land under the SAME sharding dp.shard_train uses, so
        # no cross-process reshard ever happens
        assert glob["a"].sharding.is_equivalent_to(env_sharded(mesh),
                                                   ndim=2)
        assert glob["b"].sharding.is_equivalent_to(env_sharded(mesh),
                                                   ndim=1)

    def test_global_mesh_shape(self):
        m = multihost.global_mesh()
        assert m.devices.size == len(jax.devices())


@pytest.mark.multihost_spawn
def test_dryrun_multihost_2proc():
    """The real gate: 2 fresh processes, cross-process psum + PBT gather.
    Raises on rank failure, fingerprint disagreement, or timeout.
    2 devices per rank (not 4): the boundary being tested is the PROCESS
    boundary — the collective crosses it identically at any per-rank
    device count, and the smaller per-rank mesh halves the worker's XLA
    compile on the 1-core CI host."""
    import __graft_entry__ as ge

    ge.dryrun_multihost(n_processes=2, devices_per_process=2)


@pytest.mark.multihost_spawn
def test_dryrun_multihost_supervised_recovers_killed_rank(tmp_path):
    """Acceptance (a), ISSUE 4: rank 1 is fault-injected to die right
    before step 2 (kill-rank — a RESTARTABLE death); the supervisor
    detects it (fast path: non-zero exit; general path: stale heartbeat),
    restarts the gang AT THE SAME world size from the per-rank step-2
    checkpoints, and the restarted ranks finish with IDENTICAL
    replicated-params fingerprints — i.e. restart-from-checkpoint
    preserved the collective's state, losing at most one step of work.

    ISSUE 5 acceptance rides along: with ``obs_dir`` wired through, the
    merged per-rank event timeline must tell the SAME restart story as
    the returned SupervisorResult — supervisor-side detect/decide events
    agreeing with the worker-side fault/resume events, in causal order
    under the shared monotonic clock."""
    import __graft_entry__ as ge

    from rlgpuschedule_tpu.obs import merge_dir

    obs = str(tmp_path / "obs")
    out = ge.dryrun_multihost_supervised(
        n_processes=2, devices_per_process=2, steps=4, kill_step=2,
        kill_rank=1, obs_dir=obs)
    assert out["restarts"] == 1
    # kill-before-the-collective: the dying rank checkpointed >= step 2,
    # a peer torn down mid-step may be one behind — at most one step lost
    assert out["resume_step"] >= 1
    assert out["detected_by"].startswith(("exit=", "heartbeat"))
    assert out["world_size"] == 2 and not out["shrunk"]

    events = merge_dir(obs)
    kinds = [e["kind"] for e in events]
    # one launch per attempt: initial + out["restarts"]
    assert kinds.count("gang_launch") == 1 + out["restarts"]
    fails = [e for e in events if e["kind"] == "rank_failure"]
    assert [(e["failed_rank"], e["permanent"]) for e in fails] == \
        [(1, False)]
    assert fails[0]["detected_by"] == out["detected_by"]
    restart = next(e for e in events if e["kind"] == "gang_restart")
    assert restart["world_size"] == 2
    assert restart["resume_step"] == out["resume_step"]
    assert "gang_shrink" not in kinds
    done = next(e for e in events if e["kind"] == "supervisor_done")
    assert done["outcome"] == "completed"
    assert done["budget_spent"] == out["budget_spent"]
    # worker-side story agrees: rank 1's fault fired, and after the
    # relaunch both ranks resumed from the supervisor's chosen step
    fault = next(e for e in events if e["kind"] == "fault")
    assert (fault["rank"], fault["fault"]) == (1, "kill-rank")
    resumed = [e for e in events if e["kind"] == "worker_resumed"]
    assert sorted(e["rank"] for e in resumed) == [0, 1]
    assert all(e["step"] == out["resume_step"] for e in resumed)
    # causal order on the merged timeline: fault -> detection ->
    # relaunch decision -> workers resume
    assert kinds.index("fault") < kinds.index("rank_failure") \
        < kinds.index("gang_restart") < kinds.index("worker_resumed")


@pytest.mark.multihost_spawn
def test_dryrun_multihost_elastic_shrinks_to_surviving_world(tmp_path):
    """Acceptance (b), ISSUE 4 — shrink-to-fit: rank 1 of 3 is
    PERMANENTLY lost (lose-rank -> exit 23) before step 2. The
    supervisor must relaunch at world size 2, mapping the new ranks onto
    the SURVIVING old ranks' checkpoints (replicated state re-seeds the
    shrunk gang from the survivors' minimum completed step), and the
    2-rank gang must finish with MATCHING cross-rank fingerprints at the
    new size — the fingerprint contract holds at any world size.
    1 device per rank: the surface under test is the world-size change,
    and the smaller per-rank mesh keeps 3+2 spawned compiles cheap.

    ISSUE 5: the merged timeline's ``gang_shrink`` event must match the
    SupervisorResult's shrink (3 -> 2, the lost rank named, the restore
    rank map pointing every new rank at a surviving old rank)."""
    import __graft_entry__ as ge

    from rlgpuschedule_tpu.obs import merge_dir
    from rlgpuschedule_tpu.resilience import LOSE_RANK_EXIT

    obs = str(tmp_path / "obs")
    out = ge.dryrun_multihost_elastic(
        n_processes=3, devices_per_process=1, steps=4, lose_step=2,
        lose_rank=1, obs_dir=obs)
    assert out["shrunk"] and out["world_size"] == 2
    assert out["restarts"] == 1
    assert out["resume_step"] >= 1
    assert out["detected_by"] == f"exit={LOSE_RANK_EXIT}"

    events = merge_dir(obs)
    kinds = [e["kind"] for e in events]
    shrink = next(e for e in events if e["kind"] == "gang_shrink")
    assert (shrink["from_world"], shrink["to_world"]) == (3, 2)
    assert shrink["lost_rank"] == 1
    assert shrink["resume_step"] == out["resume_step"]
    assert shrink["restore_ranks"] == [0, 2]   # survivors of losing 1
    fails = [e for e in events if e["kind"] == "rank_failure"]
    assert [(e["failed_rank"], e["permanent"]) for e in fails] == \
        [(1, True)]
    assert "gang_restart" not in kinds   # this drill shrinks, not respawns
    done = next(e for e in events if e["kind"] == "supervisor_done")
    assert (done["outcome"], done["world_size"]) == ("completed", 2)
    # the shrunk gang's two ranks each restored a SURVIVING old rank's
    # checkpoint at the supervisor's resume step
    resumed = [e for e in events if e["kind"] == "worker_resumed"]
    assert sorted((e["rank"], e["from_rank"]) for e in resumed) == \
        [(0, 0), (1, 2)]
    assert all(e["step"] == out["resume_step"] for e in resumed)
