"""Multi-host execution tests (SURVEY.md §5 "Distributed communication
backend" DCN row; VERDICT r2 missing #2 / next-round #4).

``dryrun_multihost`` spawns 2 fresh jax.distributed processes × 4 CPU
devices each and proves the DP gradient psum and the PBT exploit gather
cross the process boundary — the same program shape a 2-host v5e-16
deployment runs, with gloo standing in for DCN. The in-process helpers
(``process_env_slice``, ``global_traces``) are additionally unit-tested on
the conftest's single-process 8-device mesh, where global == local.
"""
import numpy as np
import jax

from rlgpuschedule_tpu.parallel import make_mesh
from rlgpuschedule_tpu.parallel import multihost


class TestHelpersSingleProcess:
    def test_process_env_slice_covers_all_rows(self):
        mesh = make_mesh()
        assert multihost.process_env_slice(mesh, 16) == slice(0, 16)

    def test_global_traces_roundtrip(self):
        from rlgpuschedule_tpu.parallel import env_sharded
        mesh = make_mesh()
        local = {"a": np.arange(32, dtype=np.float32).reshape(16, 2),
                 "b": np.ones((16,), np.int32)}
        glob = multihost.global_traces(mesh, local, 16)
        np.testing.assert_array_equal(np.asarray(glob["a"]), local["a"])
        # rows must land under the SAME sharding dp.shard_train uses, so
        # no cross-process reshard ever happens
        assert glob["a"].sharding.is_equivalent_to(env_sharded(mesh),
                                                   ndim=2)
        assert glob["b"].sharding.is_equivalent_to(env_sharded(mesh),
                                                   ndim=1)

    def test_global_mesh_shape(self):
        m = multihost.global_mesh()
        assert m.devices.size == len(jax.devices())


def test_dryrun_multihost_2proc():
    """The real gate: 2 fresh processes, cross-process psum + PBT gather.
    Raises on rank failure, fingerprint disagreement, or timeout.
    2 devices per rank (not 4): the boundary being tested is the PROCESS
    boundary — the collective crosses it identically at any per-rank
    device count, and the smaller per-rank mesh halves the worker's XLA
    compile on the 1-core CI host."""
    import __graft_entry__ as ge

    ge.dryrun_multihost(n_processes=2, devices_per_process=2)


def test_dryrun_multihost_supervised_recovers_killed_rank():
    """Acceptance path 3 (ISSUE 1): rank 1 is fault-injected to die right
    before step 2; the supervisor detects the death (fast path: non-zero
    exit; general path: stale heartbeat), restarts the gang from the
    per-rank step-2 checkpoints, and the restarted ranks finish with
    IDENTICAL replicated-params fingerprints — i.e. restart-from-checkpoint
    preserved the collective's state, losing at most one step of work."""
    import __graft_entry__ as ge

    out = ge.dryrun_multihost_supervised(
        n_processes=2, devices_per_process=2, steps=4, kill_step=2,
        kill_rank=1)
    assert out["restarts"] == 1
    # kill-before-the-collective: the dying rank checkpointed >= step 2,
    # a peer torn down mid-step may be one behind — at most one step lost
    assert out["resume_step"] >= 1
    assert out["detected_by"].startswith(("exit=", "heartbeat"))
