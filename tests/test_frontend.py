"""HTTP frontend tests (ISSUE 16, rebuilt ISSUE 17): the wire contract
of the serving front door — zero-copy decide path, deadline propagation
to a 503 + ``Retry-After`` derived from the LEARNED service-time Ewma
(a cold server admits instead of guessing; the hint is clamped to a
sanity band), malformed-input 400s, queue-depth connection
backpressure, the graceful-drain contract (late submits get a typed
:class:`ServerClosedError`, never a hung future), the keep-alive
HTTP/1.1 loop (one connection, many requests, pipelining, mid-stream
SIGTERM drain -> 503 + ``Connection: close``), and the framed binary
dialect sniffed off the same port."""
import contextlib
import json
import signal
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from rlgpuschedule_tpu.obs import Registry
from rlgpuschedule_tpu.serve import (PolicyServer, ServerClosedError,
                                     next_bucket, start_frontend, wire)
from rlgpuschedule_tpu.serve.batching import DeadlineSheddedError
from rlgpuschedule_tpu.serve.frontend import (DECIDE_PATH, HEALTH_PATH,
                                              RETRY_AFTER_MAX_S,
                                              RETRY_AFTER_MIN_S)

OBS_D, ACT_D = 6, 9


class HostEngine:
    """Host-only engine stand-in: argmax over the observation row, an
    optional real sleep per dispatch so the service-time Ewma learns a
    controllable value."""

    def __init__(self, max_bucket=8, cost_s=0.0):
        self.max_bucket = max_bucket
        self.cost_s = cost_s

    def bucket_for(self, n):
        return next_bucket(n, self.max_bucket)

    def decide(self, obs, mask, stall=None):
        if self.cost_s:
            time.sleep(self.cost_s)
        n = int(np.asarray(obs).shape[0])
        return (np.argmax(np.asarray(obs), axis=-1).astype(np.int32),
                self.bucket_for(n))


def example(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(OBS_D).astype(np.float32),
            np.ones(ACT_D, bool))


@contextlib.contextmanager
def serving_stack(cost_s=0.0, max_bucket=8, **fe_kw):
    reg = Registry()
    server = PolicyServer(HostEngine(max_bucket, cost_s), registry=reg)
    server.start()
    obs, mask = example()
    handle = start_frontend(server, obs, mask, port=0, **fe_kw)
    try:
        yield handle, server, reg, obs, mask
    finally:
        handle.close()


def post(url, body, headers=None, timeout=30):
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestDecidePath:
    def test_decide_200_round_trip(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            status, headers, payload = post(
                handle.url + DECIDE_PATH, obs.tobytes() + mask.tobytes())
            assert status == 200
            assert payload["action"] == int(np.argmax(obs))
            assert payload["latency_ms"] >= 0
            assert reg.counter("serve_frontend_requests_total").value == 1

    def test_healthz_and_unknown_route(self):
        with serving_stack() as (handle, *_):
            status, payload = get(handle.url + HEALTH_PATH)
            assert status == 200
            assert payload["status"] == "ok"
            assert payload["queue_depth"] == 0
            status, _, payload = post(handle.url + "/nope", b"")
            assert status == 404 and payload["error"] == "unknown route"

    def test_wrong_length_body_is_400_with_expected_bytes(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            want = obs.nbytes + mask.nbytes
            status, _, payload = post(handle.url + DECIDE_PATH, b"x" * 3)
            assert status == 400
            assert f"{want} bytes" in payload["detail"]
            assert reg.counter(
                "serve_frontend_bad_requests_total").value == 1

    def test_bad_deadline_header_is_400(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            for bad in ("junk", "nan", "inf", "-5", "0"):
                status, _, payload = post(
                    handle.url + DECIDE_PATH, body,
                    headers={"X-Deadline-Ms": bad})
                assert status == 400, bad
                assert "X-Deadline-Ms" in payload["detail"]


class TestShedMapping:
    """The satellite contract: wire deadline -> 503 with a finite,
    positive ``Retry-After`` derived from the learned Ewma; a COLD
    server (no service-time observation yet) admits instead."""

    def test_cold_server_admits_deadlined_request(self):
        with serving_stack(cost_s=0.0) as (handle, server, reg, obs, mask):
            assert server.service_time_s() is None      # nothing learned
            status, _, payload = post(
                handle.url + DECIDE_PATH, obs.tobytes() + mask.tobytes(),
                headers={"X-Deadline-Ms": "1"})
            assert status == 200
            assert payload["action"] == int(np.argmax(obs))
            assert reg.counter("serve_frontend_shed_total").value == 0

    def test_shed_503_retry_after_from_learned_ewma(self):
        with serving_stack(cost_s=0.05, max_bucket=1) as (
                handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            status, _, _ = post(handle.url + DECIDE_PATH, body)
            assert status == 200                        # learns svc
            svc = server.service_time_s()
            assert svc is not None and svc > 0
            status, headers, payload = post(
                handle.url + DECIDE_PATH, body,
                headers={"X-Deadline-Ms": "1"})
            assert status == 503
            assert payload["error"] == "shed"
            assert payload["reason"] == "admission"
            assert payload["deadline_ms"] == pytest.approx(1.0)
            retry = float(headers["Retry-After"])
            assert np.isfinite(retry) and retry > 0
            assert retry == pytest.approx(payload["retry_after_s"],
                                          abs=1e-3)
            # one learned service time + the predicted excess wait
            # (queue empty at admission: predicted == one svc)
            assert payload["retry_after_s"] == pytest.approx(
                svc + max(svc - 1e-3, 0.0), rel=1e-6)
            assert reg.counter("serve_frontend_shed_total").value == 1
            assert reg.counter("serve_shed_total").value == 1


class TestBackpressure:
    def test_high_water_pauses_reads_and_all_requests_resolve(self):
        with serving_stack(cost_s=0.02, max_bucket=1, high_water=2,
                           low_water=1) as (handle, server, reg, obs,
                                            mask):
            body = obs.tobytes() + mask.tobytes()
            results = []

            def one():
                results.append(post(handle.url + DECIDE_PATH, body)[0])

            threads = [threading.Thread(target=one) for _ in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert results == [200] * 12
            assert reg.counter(
                "serve_frontend_backpressure_pauses_total").value >= 1


def raw_request(obs, mask, headers=()):
    """One HTTP/1.1 decide request as raw bytes (keep-alive by default
    — urllib always sends ``Connection: close``, so the keep-alive
    tests speak the protocol themselves)."""
    body = obs.tobytes() + mask.tobytes()
    head = [f"POST {DECIDE_PATH} HTTP/1.1", "Host: test",
            f"Content-Length: {len(body)}", *headers]
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


def read_response(f):
    """Read exactly one framed-by-Content-Length HTTP response off a
    socket file; returns (status, headers, payload)."""
    status_line = f.readline()
    if not status_line:
        raise EOFError("connection closed before a status line")
    status = int(status_line.split()[1])
    headers = {}
    while True:
        line = f.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode().partition(":")
        headers[k.strip().lower()] = v.strip()
    body = f.read(int(headers.get("content-length", "0")))
    return status, headers, (json.loads(body) if body else None)


def onehot(i, d=OBS_D):
    x = np.zeros(d, np.float32)
    x[i] = 1.0
    return x


class TestKeepAlive:
    """ISSUE 17 satellite: the persistent-connection HTTP loop."""

    def test_one_connection_serves_many_requests(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s, \
                    s.makefile("rb") as f:
                for i in range(10):
                    s.sendall(raw_request(onehot(i % OBS_D), mask))
                    status, headers, payload = read_response(f)
                    assert status == 200
                    assert headers["connection"] == "keep-alive"
                    assert payload["action"] == i % OBS_D  # FIFO, mine
            assert reg.counter(
                "serve_frontend_requests_total").value == 10

    def test_pipelined_requests_answered_in_order(self):
        """N requests written back-to-back before any read: responses
        come back 1:1, in order — the loop never interleaves or drops."""
        n = 6
        with serving_stack() as (handle, server, reg, obs, mask):
            burst = b"".join(raw_request(onehot(i % OBS_D), mask)
                             for i in range(n))
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s, \
                    s.makefile("rb") as f:
                s.sendall(burst)
                for i in range(n):
                    status, _, payload = read_response(f)
                    assert status == 200
                    assert payload["action"] == i % OBS_D

    def test_client_connection_close_is_honored(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s, \
                    s.makefile("rb") as f:
                s.sendall(raw_request(obs, mask,
                                      ("Connection: close",)))
                status, headers, _ = read_response(f)
                assert status == 200
                assert headers["connection"] == "close"
                assert f.readline() == b""          # server closed it

    def test_bad_request_line_closes_after_400(self):
        """HTTP framing cannot resync after a malformed request line:
        400, ``Connection: close``, EOF — never a hang."""
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s, \
                    s.makefile("rb") as f:
                s.sendall(b"NONSENSE\r\n\r\n")
                status, headers, _ = read_response(f)
                assert status == 400
                assert headers["connection"] == "close"
                assert f.readline() == b""

    def test_mid_stream_sigterm_drains_typed_never_hangs(self):
        """S3 core: a keep-alive client mid-stream when SIGTERM lands
        gets the typed 503 + ``Connection: close`` on its next request
        — a signal to re-resolve, never a hung read."""
        prev = signal.getsignal(signal.SIGTERM)
        try:
            with serving_stack() as (handle, server, reg, obs, mask):
                handle.install_sigterm()
                with socket.create_connection(
                        ("127.0.0.1", handle.port), timeout=30) as s, \
                        s.makefile("rb") as f:
                    s.sendall(raw_request(obs, mask))
                    assert read_response(f)[0] == 200   # mid-stream now
                    signal.raise_signal(signal.SIGTERM)
                    deadline = time.monotonic() + 30
                    while not server.closed:
                        assert time.monotonic() < deadline, \
                            "drain never completed"
                        time.sleep(0.01)
                    s.sendall(raw_request(obs, mask))
                    status, headers, payload = read_response(f)
                    assert status == 503
                    assert payload["error"] == "closed"
                    assert headers["connection"] == "close"
                    assert f.readline() == b""          # then EOF
                assert reg.counter(
                    "serve_frontend_closed_total").value == 1
        finally:
            signal.signal(signal.SIGTERM, prev)


class TestFramedDialect:
    """ISSUE 17 tentpole: the binary frame mode, sniffed off the magic
    on the shared port."""

    def test_framed_round_trip_many_on_one_connection(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                for i in range(8):
                    o = onehot(i % OBS_D)
                    s.sendall(wire.pack_request(o, mask))
                    kind, header, body, meta64, _, _ = wire.recv_frame(s)
                    assert kind == wire.KIND_RESP
                    action = wire.unpack_action(header, body)
                    assert int(np.ravel(action)[0]) == i % OBS_D
                    assert meta64 > 0                   # latency in us
            assert reg.counter(
                "serve_frontend_requests_total").value == 8

    def test_http_and_framed_coexist_on_one_port(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            status, _, payload = post(
                handle.url + DECIDE_PATH, obs.tobytes() + mask.tobytes())
            assert status == 200
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                s.sendall(wire.pack_request(obs, mask))
                kind, header, body, _, _, _ = wire.recv_frame(s)
                assert kind == wire.KIND_RESP
                assert int(np.ravel(
                    wire.unpack_action(header, body))[0]) == \
                    payload["action"]

    def test_descriptor_mismatch_errs_but_keeps_connection(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                # wrong dtype in the descriptor, right body length
                bad = wire.pack_frame(
                    wire.KIND_REQ, b"float64:(6,)|bool:(9,)",
                    obs.tobytes() + mask.tobytes())
                s.sendall(bad)
                kind, header, body, _, _, _ = wire.recv_frame(s)
                assert kind == wire.KIND_ERR
                assert header == b"bad-request"
                assert "descriptor" in json.loads(body)["detail"]
                # the stream is still framed: a good request serves
                s.sendall(wire.pack_request(obs, mask))
                assert wire.recv_frame(s)[0] == wire.KIND_RESP
            assert reg.counter(
                "serve_frontend_bad_requests_total").value == 1

    def test_wrong_kind_errs_and_closes(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                s.sendall(wire.pack_response(np.int32(0), 0.0))
                kind, header, _, _, _, _ = wire.recv_frame(s)
                assert kind == wire.KIND_ERR
                assert header == b"bad-request"
                with pytest.raises(EOFError):
                    wire.recv_frame(s)                  # server hung up

    def test_framed_shed_carries_retry_after_micros(self):
        with serving_stack(cost_s=0.05, max_bucket=1) as (
                handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                s.sendall(wire.pack_request(obs, mask))
                assert wire.recv_frame(s)[0] == wire.KIND_RESP  # learns
                s.sendall(wire.pack_request(obs, mask,
                                            deadline_s=0.001))
                kind, header, body, meta64, _, _ = wire.recv_frame(s)
                assert kind == wire.KIND_ERR
                assert header == b"shed:admission"
                detail = json.loads(body)
                assert detail["retry_after_s"] > 0
                assert meta64 == pytest.approx(
                    detail["retry_after_s"] * 1e6, rel=1e-3)
                assert meta64 >= RETRY_AFTER_MIN_S * 1e6
                # a shed is not terminal: the connection still serves
                s.sendall(wire.pack_request(obs, mask))
                assert wire.recv_frame(s)[0] == wire.KIND_RESP

    def test_framed_drain_is_typed_and_terminal(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                s.sendall(wire.pack_request(obs, mask))
                assert wire.recv_frame(s)[0] == wire.KIND_RESP
                handle.drain()
                s.sendall(wire.pack_request(obs, mask))
                kind, header, _, _, _, _ = wire.recv_frame(s)
                assert kind == wire.KIND_ERR
                assert header == b"closed"
                with pytest.raises(EOFError):
                    wire.recv_frame(s)


class TestRequestCausality:
    """ISSUE 20: the 64-bit request id rides every reply shape on both
    dialects — inbound via ``X-Request-Id`` / the v2 frame field,
    server-minted when absent, echoed even on sheds."""

    def test_http_keepalive_echoes_inbound_id(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s, \
                    s.makefile("rb") as f:
                for rid in (1, 0xABC123, (1 << 62) + 5):
                    s.sendall(raw_request(obs, mask,
                                          (f"X-Request-Id: {rid}",)))
                    status, _, payload = read_response(f)
                    assert status == 200
                    assert payload["request_id"] == rid

    def test_http_mints_distinct_ids_when_absent(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            ids = set()
            for _ in range(4):
                status, _, payload = post(handle.url + DECIDE_PATH, body)
                assert status == 200
                ids.add(payload["request_id"])
            assert len(ids) == 4 and 0 not in ids

    def test_http_bad_request_id_is_400(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            for bad in ("junk", "-3", str(1 << 63)):
                status, _, payload = post(
                    handle.url + DECIDE_PATH, body,
                    headers={"X-Request-Id": bad})
                assert status == 400, bad
                assert "X-Request-Id" in payload["detail"]

    def test_http_shed_echoes_id(self):
        with serving_stack(cost_s=0.05, max_bucket=1) as (
                handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            assert post(handle.url + DECIDE_PATH, body)[0] == 200
            status, _, payload = post(
                handle.url + DECIDE_PATH, body,
                headers={"X-Deadline-Ms": "1",
                         "X-Request-Id": "314159"})
            assert status == 503 and payload["error"] == "shed"
            assert payload["request_id"] == 314159

    def test_framed_echoes_and_mints(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                s.sendall(wire.pack_request(obs, mask, req_id=0x5150))
                kind, _, _, _, _, rid = wire.recv_frame(s)
                assert kind == wire.KIND_RESP and rid == 0x5150
                # id 0 = unassigned: the server mints one and echoes it
                s.sendall(wire.pack_request(obs, mask))
                kind, _, _, _, _, rid = wire.recv_frame(s)
                assert kind == wire.KIND_RESP and rid > 0

    def test_framed_error_frames_echo_id(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                bad = wire.pack_frame(
                    wire.KIND_REQ, b"float64:(6,)|bool:(9,)",
                    obs.tobytes() + mask.tobytes(), req_id=0x77)
                s.sendall(bad)
                kind, header, _, _, _, rid = wire.recv_frame(s)
                assert kind == wire.KIND_ERR
                assert header == b"bad-request" and rid == 0x77

    def test_framed_v1_frame_still_served(self):
        """A legacy client's 24-byte v1 frame decodes on the live port:
        the server mints an id and answers with a v2 response frame."""
        with serving_stack() as (handle, server, reg, obs, mask):
            desc = wire.descriptor(obs) + b"|" + wire.descriptor(mask)
            body = obs.tobytes() + mask.tobytes()
            v1 = wire.PREFIX_V1.pack(wire.MAGIC, 1, wire.KIND_REQ,
                                     len(desc), len(body), 0, 0) \
                + desc + body
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                s.sendall(v1)
                kind, header, rbody, _, _, rid = wire.recv_frame(s)
                assert kind == wire.KIND_RESP and rid > 0
                assert int(np.ravel(
                    wire.unpack_action(header, rbody))[0]) == \
                    int(np.argmax(obs))

    def test_framed_int64_overflow_id_rejected(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            with socket.create_connection(("127.0.0.1", handle.port),
                                          timeout=30) as s:
                s.sendall(wire.pack_request(obs, mask,
                                            req_id=(1 << 63) + 1))
                kind, header, body, _, _, _ = wire.recv_frame(s)
                assert kind == wire.KIND_ERR
                assert header == b"bad-request"
                assert "2**63" in json.loads(body)["detail"]
                # not terminal: the stream stays framed
                s.sendall(wire.pack_request(obs, mask))
                assert wire.recv_frame(s)[0] == wire.KIND_RESP


class TestRetryAfterClamp:
    """ISSUE 17 satellite: the Retry-After hint is clamped to
    [RETRY_AFTER_MIN_S, RETRY_AFTER_MAX_S] — a poisoned or degenerate
    estimator can advertise neither a microsecond retry storm nor an
    hour-long outage."""

    def _exc(self, predicted=None):
        return DeadlineSheddedError("admission", deadline_s=0.001,
                                    waited_s=0.0,
                                    predicted_wait_s=predicted)

    def test_clamp_band_on_degenerate_estimates(self, monkeypatch):
        with serving_stack() as (handle, server, reg, obs, mask):
            fe = handle.frontend
            monkeypatch.setattr(server, "service_time_s", lambda: 1e9)
            assert fe._retry_after_s(self._exc()) == RETRY_AFTER_MAX_S
            monkeypatch.setattr(server, "service_time_s", lambda: 1e-9)
            assert fe._retry_after_s(self._exc()) == RETRY_AFTER_MIN_S
            # cold estimator: 1s fallback, inside the band untouched
            monkeypatch.setattr(server, "service_time_s", lambda: None)
            assert fe._retry_after_s(self._exc()) == 1.0
            # a sane learned value passes through unclamped, plus the
            # predicted excess wait on admission sheds
            monkeypatch.setattr(server, "service_time_s", lambda: 0.25)
            assert fe._retry_after_s(self._exc()) == 0.25
            assert fe._retry_after_s(self._exc(predicted=0.101)) == \
                pytest.approx(0.25 + 0.1)

    def test_wire_shed_retry_after_is_clamped(self, monkeypatch):
        """End-to-end: with a poisoned (huge) estimator the shed 503's
        Retry-After header is the ceiling, not the raw estimate."""
        with serving_stack(cost_s=0.02, max_bucket=1) as (
                handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            assert post(handle.url + DECIDE_PATH, body)[0] == 200
            monkeypatch.setattr(server, "service_time_s", lambda: 1e9)
            status, headers, payload = post(
                handle.url + DECIDE_PATH, body,
                headers={"X-Deadline-Ms": "1"})
            assert status == 503
            assert float(headers["Retry-After"]) == RETRY_AFTER_MAX_S
            assert payload["retry_after_s"] == RETRY_AFTER_MAX_S


class TestDrain:
    def test_drain_refuses_late_work_typed(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            assert post(handle.url + DECIDE_PATH, body)[0] == 200
            handle.drain()
            assert server.closed
            # a straggler submit gets the typed refusal, never a future
            # no dispatcher will resolve
            with pytest.raises(ServerClosedError):
                server.submit(obs, mask)
            # and the listener is gone: connect refused, not a hang
            with pytest.raises((urllib.error.URLError, ConnectionError,
                                OSError)):
                post(handle.url + DECIDE_PATH, body, timeout=5)
            handle.drain()                              # idempotent

    def test_frontend_counts_draining_rejections(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            handle.drain()
            assert reg.counter("serve_frontend_closed_total").value == 0
            assert handle.frontend.draining

    def test_drain_refuses_backlog_connection_never_hangs(self):
        # A client whose TCP handshake completed but which the loop has
        # not yet turned into a transport when drain() runs (kernel
        # accept backlog, or a still-queued asyncio accept task) must
        # STILL get the typed draining refusal — not an orphaned socket
        # that hangs forever. Park the event loop so the connection is
        # guaranteed un-accepted at drain time.
        with serving_stack() as (handle, server, reg, obs, mask):
            handle._loop.call_soon_threadsafe(time.sleep, 0.3)
            time.sleep(0.05)          # the park is now running
            with socket.create_connection(
                    ("127.0.0.1", handle.port), timeout=30) as c:
                handle.drain()
                c.sendall(raw_request(obs, mask))
                c.settimeout(30)
                f = c.makefile("rb")
                status, headers, payload = read_response(f)
                assert status == 503
                assert payload["error"] == "closed"
                assert headers["connection"] == "close"
                assert f.readline() == b""      # server hung up after
