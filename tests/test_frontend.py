"""HTTP frontend tests (ISSUE 16): the wire contract of the serving
front door — zero-copy decide path, deadline propagation to a 503 +
``Retry-After`` derived from the LEARNED service-time Ewma (a cold
server admits instead of guessing), malformed-input 400s, queue-depth
connection backpressure, and the graceful-drain contract (late submits
get a typed :class:`ServerClosedError`, never a hung future)."""
import contextlib
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from rlgpuschedule_tpu.obs import Registry
from rlgpuschedule_tpu.serve import (PolicyServer, ServerClosedError,
                                     next_bucket, start_frontend)
from rlgpuschedule_tpu.serve.frontend import DECIDE_PATH, HEALTH_PATH

OBS_D, ACT_D = 6, 9


class HostEngine:
    """Host-only engine stand-in: argmax over the observation row, an
    optional real sleep per dispatch so the service-time Ewma learns a
    controllable value."""

    def __init__(self, max_bucket=8, cost_s=0.0):
        self.max_bucket = max_bucket
        self.cost_s = cost_s

    def bucket_for(self, n):
        return next_bucket(n, self.max_bucket)

    def decide(self, obs, mask, stall=None):
        if self.cost_s:
            time.sleep(self.cost_s)
        n = int(np.asarray(obs).shape[0])
        return (np.argmax(np.asarray(obs), axis=-1).astype(np.int32),
                self.bucket_for(n))


def example(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(OBS_D).astype(np.float32),
            np.ones(ACT_D, bool))


@contextlib.contextmanager
def serving_stack(cost_s=0.0, max_bucket=8, **fe_kw):
    reg = Registry()
    server = PolicyServer(HostEngine(max_bucket, cost_s), registry=reg)
    server.start()
    obs, mask = example()
    handle = start_frontend(server, obs, mask, port=0, **fe_kw)
    try:
        yield handle, server, reg, obs, mask
    finally:
        handle.close()


def post(url, body, headers=None, timeout=30):
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestDecidePath:
    def test_decide_200_round_trip(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            status, headers, payload = post(
                handle.url + DECIDE_PATH, obs.tobytes() + mask.tobytes())
            assert status == 200
            assert payload["action"] == int(np.argmax(obs))
            assert payload["latency_ms"] >= 0
            assert reg.counter("serve_frontend_requests_total").value == 1

    def test_healthz_and_unknown_route(self):
        with serving_stack() as (handle, *_):
            status, payload = get(handle.url + HEALTH_PATH)
            assert status == 200
            assert payload["status"] == "ok"
            assert payload["queue_depth"] == 0
            status, _, payload = post(handle.url + "/nope", b"")
            assert status == 404 and payload["error"] == "unknown route"

    def test_wrong_length_body_is_400_with_expected_bytes(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            want = obs.nbytes + mask.nbytes
            status, _, payload = post(handle.url + DECIDE_PATH, b"x" * 3)
            assert status == 400
            assert f"{want} bytes" in payload["detail"]
            assert reg.counter(
                "serve_frontend_bad_requests_total").value == 1

    def test_bad_deadline_header_is_400(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            for bad in ("junk", "nan", "inf", "-5", "0"):
                status, _, payload = post(
                    handle.url + DECIDE_PATH, body,
                    headers={"X-Deadline-Ms": bad})
                assert status == 400, bad
                assert "X-Deadline-Ms" in payload["detail"]


class TestShedMapping:
    """The satellite contract: wire deadline -> 503 with a finite,
    positive ``Retry-After`` derived from the learned Ewma; a COLD
    server (no service-time observation yet) admits instead."""

    def test_cold_server_admits_deadlined_request(self):
        with serving_stack(cost_s=0.0) as (handle, server, reg, obs, mask):
            assert server.service_time_s() is None      # nothing learned
            status, _, payload = post(
                handle.url + DECIDE_PATH, obs.tobytes() + mask.tobytes(),
                headers={"X-Deadline-Ms": "1"})
            assert status == 200
            assert payload["action"] == int(np.argmax(obs))
            assert reg.counter("serve_frontend_shed_total").value == 0

    def test_shed_503_retry_after_from_learned_ewma(self):
        with serving_stack(cost_s=0.05, max_bucket=1) as (
                handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            status, _, _ = post(handle.url + DECIDE_PATH, body)
            assert status == 200                        # learns svc
            svc = server.service_time_s()
            assert svc is not None and svc > 0
            status, headers, payload = post(
                handle.url + DECIDE_PATH, body,
                headers={"X-Deadline-Ms": "1"})
            assert status == 503
            assert payload["error"] == "shed"
            assert payload["reason"] == "admission"
            assert payload["deadline_ms"] == pytest.approx(1.0)
            retry = float(headers["Retry-After"])
            assert np.isfinite(retry) and retry > 0
            assert retry == pytest.approx(payload["retry_after_s"],
                                          abs=1e-3)
            # one learned service time + the predicted excess wait
            # (queue empty at admission: predicted == one svc)
            assert payload["retry_after_s"] == pytest.approx(
                svc + max(svc - 1e-3, 0.0), rel=1e-6)
            assert reg.counter("serve_frontend_shed_total").value == 1
            assert reg.counter("serve_shed_total").value == 1


class TestBackpressure:
    def test_high_water_pauses_reads_and_all_requests_resolve(self):
        with serving_stack(cost_s=0.02, max_bucket=1, high_water=2,
                           low_water=1) as (handle, server, reg, obs,
                                            mask):
            body = obs.tobytes() + mask.tobytes()
            results = []

            def one():
                results.append(post(handle.url + DECIDE_PATH, body)[0])

            threads = [threading.Thread(target=one) for _ in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert results == [200] * 12
            assert reg.counter(
                "serve_frontend_backpressure_pauses_total").value >= 1


class TestDrain:
    def test_drain_refuses_late_work_typed(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            body = obs.tobytes() + mask.tobytes()
            assert post(handle.url + DECIDE_PATH, body)[0] == 200
            handle.drain()
            assert server.closed
            # a straggler submit gets the typed refusal, never a future
            # no dispatcher will resolve
            with pytest.raises(ServerClosedError):
                server.submit(obs, mask)
            # and the listener is gone: connect refused, not a hang
            with pytest.raises((urllib.error.URLError, ConnectionError,
                                OSError)):
                post(handle.url + DECIDE_PATH, body, timeout=5)
            handle.drain()                              # idempotent

    def test_frontend_counts_draining_rejections(self):
        with serving_stack() as (handle, server, reg, obs, mask):
            handle.drain()
            assert reg.counter("serve_frontend_closed_total").value == 0
            assert handle.frontend.draining
