"""V-trace off-policy correction + fused advantage pipeline (ISSUE 12):
the scan-level op contracts (on-policy bit-identity with GAE, ρ̄/c̄
ratio clipping against hand-computed trajectories), the
compute_advantages pipeline (reward-norm Welford stats, bf16 storage
tolerances), and the engine contracts — bound-0 async vtrace runs
bit-identical to the sync GAE loop with zero post-warmup recompiles,
deep bounds (≥4) train finite with measured staleness above 1, and the
PBT population runner reproduces the sync PBT loop bit for bit at
bound 0 across exploit rounds.

The 8-device virtual CPU platform (conftest) backs the async tests.
"""
import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlgpuschedule_tpu.algos.ppo import (NormTrainState, compute_advantages,
                                         init_reward_stats,
                                         normalize_advantages, reward_scale,
                                         update_reward_stats)
from rlgpuschedule_tpu.algos.rollout import rollout
from rlgpuschedule_tpu.algos.vtrace import compute_vtrace, importance_ratios
from rlgpuschedule_tpu.async_engine import AsyncRunner
from rlgpuschedule_tpu.configs import PPO_MLP_SYNTH64
from rlgpuschedule_tpu.experiment import Experiment, PopulationExperiment
from rlgpuschedule_tpu.ops.gae import compute_gae
from rlgpuschedule_tpu.parallel.groups import split_devices
from rlgpuschedule_tpu.parallel.pbt import PBTConfig


def small_cfg(**kw):
    ppo = dataclasses.replace(PPO_MLP_SYNTH64.ppo, n_steps=8, n_epochs=1,
                              n_minibatches=2, **kw.pop("ppo_kw", {}))
    base = dict(name="vtrace-test", n_envs=4, n_nodes=2, gpus_per_node=4,
                window_jobs=16, horizon=64, queue_len=4, resample_every=0,
                ppo=ppo)
    return dataclasses.replace(PPO_MLP_SYNTH64, **{**base, **kw})


def params_equal(a, b) -> bool:
    return bool(jax.tree.all(jax.tree.map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        jax.device_get(a), jax.device_get(b))))


def ref_vtrace(r, v, d, last_v, rho, gamma, lam, rho_bar, c_bar):
    """Plain-Python reverse recurrence — the spec the scan must match."""
    T = len(r)
    acc, next_v, adv = 0.0, last_v, [0.0] * T
    for t in reversed(range(T)):
        nonterm = 1.0 - d[t]
        rh = min(rho[t], rho_bar)
        c = min(rho[t], c_bar)
        delta = rh * (r[t] + gamma * next_v * nonterm - v[t])
        acc = delta + gamma * lam * nonterm * c * acc
        adv[t] = acc
        next_v = v[t]
    return np.asarray(adv, np.float32)


class TestVtraceOp:
    def test_on_policy_reduces_bitwise_to_gae(self):
        """rho ≡ 1.0 exactly → every correction multiply is by the IEEE
        identity and the scan collapses to the GAE body, bit for bit —
        the contract the bound-0 async bit-identity rests on. Checked
        THROUGH jit (what production runs), not just eager."""
        rng = np.random.default_rng(0)
        T, E = 16, 5
        r = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
        d = jnp.asarray(rng.random((T, E)) < 0.15, jnp.float32)
        lv = jnp.asarray(rng.normal(size=(E,)), jnp.float32)

        @jax.jit
        def both(r, v, d, lv):
            a_g, ret_g = compute_gae(r, v, d, lv, 0.995, 0.95)
            a_v, ret_v = compute_vtrace(r, v, d, lv, jnp.ones_like(r),
                                        0.995, 0.95)
            return a_g, ret_g, a_v, ret_v

        a_g, ret_g, a_v, ret_v = jax.device_get(both(r, v, d, lv))
        assert np.array_equal(a_g, a_v)
        assert np.array_equal(ret_g, ret_v)

    def test_hand_computed_three_step_trajectory(self):
        """Literal hand-worked numbers: ρ=2.0 clips to ρ̄=1 at t=0, the
        under-1 ratio 0.5 passes through un-clipped at t=1 (clips are
        one-sided minima), and the mid-trajectory done cuts both the
        bootstrap and the trace at t=1."""
        r = jnp.asarray([1.0, -0.5, 2.0])
        v = jnp.asarray([0.3, 0.1, -0.2])
        d = jnp.asarray([0.0, 1.0, 0.0])
        rho = jnp.asarray([2.0, 0.5, 1.3])
        adv, ret = compute_vtrace(r, v, d, jnp.asarray(0.7), rho,
                                  gamma=0.9, lam=0.8)
        # t=2: delta = 1.0*(2 + 0.9*0.7 + 0.2)           = 2.83
        # t=1: done → delta = 0.5*(-0.5 - 0.1) = -0.3, no trace
        # t=0: delta = 1.0*(1 + 0.9*0.1 - 0.3) = 0.79;
        #      acc   = 0.79 + 0.9*0.8*1.0*(-0.3)         = 0.574
        np.testing.assert_allclose(np.asarray(adv), [0.574, -0.3, 2.83],
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(ret), [0.874, -0.2, 2.63],
                                   atol=1e-6)

    @pytest.mark.parametrize("rho_bar,c_bar", [(1.0, 1.0), (2.0, 1.0),
                                               (1.0, 0.5), (3.0, 3.0)])
    def test_matches_reference_recurrence(self, rho_bar, c_bar):
        rng = np.random.default_rng(7)
        T = 12
        r = rng.normal(size=T).astype(np.float32)
        v = rng.normal(size=T).astype(np.float32)
        d = (rng.random(T) < 0.2).astype(np.float32)
        rho = np.exp(rng.normal(size=T)).astype(np.float32)
        lv = np.float32(0.4)
        adv, ret = compute_vtrace(
            jnp.asarray(r), jnp.asarray(v), jnp.asarray(d),
            jnp.asarray(lv), jnp.asarray(rho), 0.99, 0.9, rho_bar, c_bar)
        want = ref_vtrace(r, v, d, lv, rho, 0.99, 0.9, rho_bar, c_bar)
        np.testing.assert_allclose(np.asarray(adv), want, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(ret), want + v, rtol=1e-5,
                                   atol=1e-6)

    def test_importance_ratios_exact_identity_on_policy(self):
        lp = jnp.asarray([-1.3, -0.2, -4.0])
        assert np.all(np.asarray(importance_ratios(lp, lp)) == 1.0)
        off = importance_ratios(jnp.asarray([0.0]), jnp.asarray([-1.0]))
        np.testing.assert_allclose(np.asarray(off), np.exp(-1.0),
                                   rtol=1e-6)


class TestAdvantagePipeline:
    @pytest.fixture(scope="class")
    def rolled(self):
        """One real rollout batch (+ the builder's state/apply_fn) shared
        by the pipeline tests."""
        exp = Experiment.build(small_cfg())
        _, tr, last_value = jax.jit(
            lambda p, c: rollout(exp.apply_fn, p, exp.env_params,
                                 exp.traces, c, 8))(
            exp.train_state.params, exp.carry)
        return exp, tr, last_value

    def _run(self, exp, ppo, tr, last_value, state=None):
        f = jax.jit(partial(compute_advantages, exp.apply_fn, ppo))
        return f(state if state is not None else exp.train_state,
                 tr, last_value)

    def test_default_config_is_the_historical_gae_path(self, rolled):
        exp, tr, lv = rolled
        _, adv, ret, rho = self._run(exp, small_cfg().ppo, tr, lv)
        want_adv, want_ret = compute_gae(tr.reward, tr.value, tr.done, lv,
                                         exp.cfg.ppo.gamma,
                                         exp.cfg.ppo.gae_lambda)
        assert rho is None
        assert np.array_equal(np.asarray(adv),
                              np.asarray(normalize_advantages(want_adv)))
        assert np.array_equal(np.asarray(ret), np.asarray(want_ret))

    def test_vtrace_on_policy_is_bitwise_gae_with_unit_ratios(self, rolled):
        """Same params produced the batch → the batched log-prob
        recompute is bitwise equal to the rollout's, ratios are exactly
        1.0, and the whole pipeline output matches the GAE path bit for
        bit."""
        exp, tr, lv = rolled
        ppo_v = dataclasses.replace(small_cfg().ppo, correction="vtrace")
        _, adv_g, ret_g, _ = self._run(exp, small_cfg().ppo, tr, lv)
        _, adv_v, ret_v, rho = self._run(exp, ppo_v, tr, lv)
        assert float(rho[0]) == 1.0 and float(rho[1]) == 1.0
        assert np.array_equal(np.asarray(adv_g), np.asarray(adv_v))
        assert np.array_equal(np.asarray(ret_g), np.asarray(ret_v))

    def test_bf16_advantages_dtype_and_tolerance(self, rolled):
        """bf16 storage halves the tensors; the values must stay within
        bf16 resolution of the fp32 pipeline (advantages are normalized
        to unit scale, so an absolute pin is meaningful)."""
        exp, tr, lv = rolled
        ppo16 = dataclasses.replace(small_cfg().ppo, bf16_advantages=True)
        _, adv32, ret32, _ = self._run(exp, small_cfg().ppo, tr, lv)
        _, adv16, ret16, _ = self._run(exp, ppo16, tr, lv)
        assert adv16.dtype == jnp.bfloat16 and ret16.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(adv16, np.float32), np.asarray(adv32),
            atol=0.05, rtol=0.02)
        np.testing.assert_allclose(
            np.asarray(ret16, np.float32), np.asarray(ret32),
            atol=0.05, rtol=0.02)

    def test_welford_stats_match_numpy_across_batches(self):
        rng = np.random.default_rng(3)
        b1 = rng.normal(loc=2.0, scale=3.0, size=(8, 4)).astype(np.float32)
        b2 = rng.normal(loc=-1.0, scale=0.5, size=(8, 4)).astype(np.float32)
        stats = update_reward_stats(init_reward_stats(), jnp.asarray(b1))
        var1 = float(stats.m2 / stats.count)
        assert var1 == pytest.approx(float(np.var(b1)), rel=1e-4)
        stats = update_reward_stats(stats, jnp.asarray(b2))
        both = np.concatenate([b1.ravel(), b2.ravel()])
        assert float(stats.count) == both.size
        assert float(stats.mean) == pytest.approx(float(np.mean(both)),
                                                  rel=1e-4)
        assert float(stats.m2 / stats.count) == pytest.approx(
            float(np.var(both)), rel=1e-4)
        assert float(reward_scale(stats)) == pytest.approx(
            1.0 / np.sqrt(np.var(both) + 1e-8), rel=1e-4)

    def test_reward_norm_threads_stats_through_the_state(self, rolled):
        exp, tr, lv = rolled
        cfg = small_cfg(ppo_kw={"reward_norm": True})
        nexp = Experiment.build(cfg)
        assert isinstance(nexp.train_state, NormTrainState)
        state, _, _, _ = self._run(nexp, cfg.ppo, tr, lv,
                                   state=nexp.train_state)
        assert float(state.reward_stats.count) == tr.reward.size
        assert np.isfinite(float(reward_scale(state.reward_stats)))


class TestVtraceAsync:
    def test_bound0_vtrace_is_bit_identical_to_sync_gae(self):
        """The acceptance contract: --correction vtrace at bound 0 must
        not move a single bit vs the uncorrected sync loop. The fetched
        ratio stats sit within an ulp of 1.0 — the batched recompute can
        differ from the rollout's per-step log-probs in the last bit, and
        the one-sided min-clips at ρ̄ = c̄ = 1 are what absorb that drift
        before it can touch the advantage scan."""
        ref = Experiment.build(small_cfg())
        ref.run(iterations=5)
        cfg = small_cfg(ppo_kw={"correction": "vtrace"})
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=0)
        out = r.run(iterations=5, log_every=1)
        assert params_equal(ref.train_state.params, exp.train_state.params)
        assert np.array_equal(jax.device_get(ref.key),
                              jax.device_get(exp.key))
        assert out["async"]["importance_ratio_mean"] == pytest.approx(
            1.0, abs=1e-5)
        assert out["async"]["importance_ratio_max"] == pytest.approx(
            1.0, abs=1e-5)

    def test_no_post_warmup_recompiles_with_vtrace(self):
        from rlgpuschedule_tpu.analysis.sentinels import CompileCounter
        cfg = small_cfg(ppo_kw={"correction": "vtrace"})
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=1)
        r.run(iterations=2)               # warmup: both programs compile
        with CompileCounter() as c:
            r.run(iterations=3)           # steady state
        assert c.total == 0, c.events

    def test_deep_bound_trains_finite_with_measured_staleness(self,
                                                              tmp_path):
        """Bound 4 — the queue actually runs deep (staleness_max > 1),
        losses stay finite, the ratio gauges move off the on-policy
        identity, and the telemetry layer sees zero recompile/transfer
        alarms (the no-extra-host-sync discipline)."""
        from rlgpuschedule_tpu.obs import RunTelemetry, merge_dir
        cfg = small_cfg(ppo_kw={"correction": "vtrace"})
        exp = Experiment.build(cfg)
        r = AsyncRunner(exp, groups=split_devices(devices=jax.devices()[:2]),
                        staleness_bound=4, queue_capacity=4)
        with RunTelemetry(str(tmp_path), alarms=True) as tel:
            out = r.run(iterations=10, log_every=1, telemetry=tel)
        info = out["async"]
        assert info["staleness_max"] > 1
        assert info["importance_ratio_max"] >= 1.0
        rewards = [h["mean_reward"] for h in out["history"]]
        losses = [h["total_loss"] for h in out["history"]]
        assert np.isfinite(rewards).all() and np.isfinite(losses).all()
        events = merge_dir(str(tmp_path))
        # implicit transfers RAISE under the no_implicit_transfers
        # guard — they never appear as events, only recompiles do
        assert not any(e["kind"] == "recompile" for e in events)
        end = next(e for e in events if e["kind"] == "run_end")
        assert end["async_staleness_max"] > 1
        assert end["async_importance_ratio_mean"] > 0


class TestAsyncPopulation:
    @pytest.mark.parametrize("corr", ["none", "vtrace"])
    def test_bound0_reproduces_sync_pbt_bitwise(self, corr):
        """The new population engine at bound 0 must reproduce the sync
        PBT loop bit for bit — params, hparams AND rng keys — across
        exploit rounds (ready_iters=2 fires twice in 5 iterations), for
        both advantage pipelines. Single-device actor/learner groups:
        the sync reference is a single-device program, and a 4-device
        REPLICATED executable is numerically (not bitwise) equal to it —
        XLA fuses multi-partition programs differently."""
        cfg = small_cfg(ppo_kw={"correction": corr})
        pbt = lambda: PBTConfig(seed=cfg.seed, ready_iters=2)  # noqa: E731
        groups = split_devices(devices=jax.devices()[:2])
        sync = PopulationExperiment.build(cfg, n_pop=2, mesh=None,
                                          pbt_cfg=pbt())
        sync.run(5, log_every=1)
        apop = PopulationExperiment.build(cfg, n_pop=2, mesh=None,
                                          pbt_cfg=pbt())
        out = apop.run_async(5, groups=groups, staleness_bound=0,
                             log_every=1)
        assert params_equal(sync.states.params, apop.states.params)
        assert params_equal(sync.hparams, apop.hparams)
        assert np.array_equal(jax.device_get(sync.keys),
                              jax.device_get(apop.keys))
        assert out["pbt_events"] == 2

    def test_deep_bound_population_tracks_staleness_per_member(self):
        cfg = small_cfg(ppo_kw={"correction": "vtrace"})
        apop = PopulationExperiment.build(
            cfg, n_pop=2, mesh=None,
            pbt_cfg=PBTConfig(seed=cfg.seed, ready_iters=3))
        out = apop.run_async(6, groups=split_devices(
            devices=jax.devices()[:2]), staleness_bound=2,
            queue_capacity=2, log_every=1)
        info = out["async"]
        assert info["staleness_max"] >= 1
        assert len(info["staleness_max_per_member"]) == 2
        assert len(info["staleness_last_per_member"]) == 2
        assert np.isfinite(out["final_fitness"]).all()
        assert out["pbt_events"] >= 1
