"""Distributed tests on the 8-device virtual CPU mesh (SURVEY.md §4
"Distributed without a real cluster"): sharded train step runs, params stay
replicated-identical, and DP matches single-device training bit-for-bit
given the same global batch."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rlgpuschedule_tpu.algos import PPOConfig, init_carry, make_ppo_step
from rlgpuschedule_tpu.algos.ppo import make_optimizer
from rlgpuschedule_tpu.env import EnvParams, stack_traces
from rlgpuschedule_tpu.models import make_policy
from rlgpuschedule_tpu.parallel import (DATA_AXIS, POP_AXIS, make_mesh,
                                        shard_map_train, shard_train)
from rlgpuschedule_tpu.sim.core import SimParams
from rlgpuschedule_tpu.traces import gen_poisson_trace
from flax.training.train_state import TrainState


def build(n_envs=8, dtype=jnp.bfloat16):
    env_params = EnvParams(sim=SimParams(2, 4, max_jobs=16, queue_len=4),
                           obs_kind="flat", horizon=64, time_scale=100.0,
                           reward_scale=1000.0)
    windows = [gen_poisson_trace(0.05, 12, seed=s, max_jobs=16,
                                 mean_duration=60.0, gpu_sizes=(1, 2),
                                 gpu_probs=(0.7, 0.3))
               for s in range(n_envs)]
    traces = stack_traces(windows, env_params)
    net = make_policy("flat", env_params.n_actions, dtype=dtype)
    apply_fn = lambda p, o, m: net.apply(p, o, m)
    cfg = PPOConfig(n_steps=8, n_epochs=2, n_minibatches=2)
    key = jax.random.PRNGKey(0)
    carry = init_carry(env_params, traces, key)
    params = net.init(key, carry.obs[:1], carry.mask[:1])
    state = TrainState.create(apply_fn=net.apply, params=params,
                              tx=make_optimizer(cfg))
    step = make_ppo_step(apply_fn, env_params, cfg)
    return env_params, traces, state, carry, step


class TestMesh:
    def test_make_mesh_shapes(self):
        assert len(jax.devices()) == 8, "conftest must provide 8 cpu devices"
        m = make_mesh()
        assert m.shape[POP_AXIS] == 1 and m.shape[DATA_AXIS] == 8
        m2 = make_mesh(n_pop=4)
        assert m2.shape[POP_AXIS] == 4 and m2.shape[DATA_AXIS] == 2
        with pytest.raises(ValueError):
            make_mesh(n_pop=3)


class TestDPTraining:
    def test_sharded_step_runs_and_params_replicated(self):
        env_params, traces, state, carry, step = build(n_envs=8)
        mesh = make_mesh()
        jstep, state, carry, traces = shard_train(mesh, step, state, carry,
                                                  traces)
        for i in range(2):
            state, carry, metrics = jstep(state, carry, traces,
                                          jax.random.PRNGKey(i))
        assert all(np.isfinite(float(v)) for v in metrics)
        # params must be fully replicated across all 8 devices
        leaf = jax.tree.leaves(state.params)[0]
        assert leaf.sharding.is_fully_replicated

    def test_dp_matches_single_device(self):
        # same global batch, same key: DP-sharded training must track
        # single-device training. f32 policies so the only differences are
        # collective reduction order (~1e-6); a missing/incorrect sharding
        # shows up as a crash or O(1) divergence.
        env_params, traces, state, carry, step = build(n_envs=8,
                                                       dtype=jnp.float32)
        sstate, scarry = state, carry
        jstep = jax.jit(step)
        for i in range(2):
            sstate, scarry, _ = jstep(sstate, scarry, traces,
                                      jax.random.PRNGKey(i))
        env_params2, traces2, state2, carry2, step2 = build(n_envs=8,
                                                            dtype=jnp.float32)
        mesh = make_mesh()
        dstep, dstate, dcarry, dtraces = shard_train(mesh, step2, state2,
                                                     carry2, traces2)
        for i in range(2):
            dstate, dcarry, _ = dstep(dstate, dcarry, dtraces,
                                      jax.random.PRNGKey(i))
        single = jax.tree.leaves(jax.device_get(sstate.params))
        distributed = jax.tree.leaves(jax.device_get(dstate.params))
        for s, d in zip(single, distributed):
            np.testing.assert_allclose(s, d, atol=1e-3)

    def test_dp_gradient_equals_single_gradient(self):
        # exact check at one-update granularity: gradients of the same
        # fixed minibatch under sharded vs single execution
        from rlgpuschedule_tpu.algos import ppo_loss, Transition
        from rlgpuschedule_tpu.parallel import env_sharded, replicated
        env_params, traces, state, carry, _ = build(n_envs=8,
                                                    dtype=jnp.float32)
        net = make_policy("flat", env_params.n_actions, dtype=jnp.float32)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = PPOConfig()
        B = 8
        batch = Transition(
            obs=jnp.tile(carry.obs[:1], (B, 1)) + jnp.arange(B)[:, None] * 0.01,
            action=jnp.zeros((B,), jnp.int32),
            log_prob=jnp.full((B,), -1.0), value=jnp.zeros((B,)),
            reward=jnp.zeros((B,)), done=jnp.zeros((B,), bool),
            mask=jnp.ones((B, env_params.n_actions), bool),
            env_steps_dt=jnp.zeros((B,)))
        adv = jnp.linspace(-1, 1, B)
        ret = jnp.linspace(0, 1, B)
        grad_fn = jax.grad(lambda p, b, a, r: ppo_loss(
            apply_fn, p, b, a, r, cfg)[0])
        g_single = jax.jit(grad_fn)(state.params, batch, adv, ret)
        mesh = make_mesh()
        g_dp = jax.jit(grad_fn,
                       in_shardings=(replicated(mesh), env_sharded(mesh),
                                     env_sharded(mesh), env_sharded(mesh)),
                       out_shardings=replicated(mesh))(
            state.params, batch, adv, ret)
        for s, d in zip(jax.tree.leaves(g_single), jax.tree.leaves(g_dp)):
            np.testing.assert_allclose(np.asarray(s), np.asarray(d),
                                       atol=1e-5)

    def test_advantage_normalization_uses_global_moments(self):
        # regression: pmean of per-shard variances is NOT the global
        # variance; the E[x²]−mean² form is. With per-shard-constant values
        # the old form divided by ~0 and exploded.
        from jax.sharding import PartitionSpec as P
        from rlgpuschedule_tpu.parallel.dp import shard_map_compat
        mesh = make_mesh()
        x = jnp.repeat(jnp.arange(8.0), 2)  # 16 vals, constant per shard

        def normalize(xs):
            m = jax.lax.pmean(jnp.mean(xs), DATA_AXIS)
            sq = jax.lax.pmean(jnp.mean(xs ** 2), DATA_AXIS)
            return (xs - m) / jnp.sqrt(sq - m ** 2 + 1e-8)

        y = shard_map_compat(normalize, mesh=mesh, in_specs=P(DATA_AXIS),
                             out_specs=P(DATA_AXIS))(x)
        np.testing.assert_allclose(float(jnp.std(y)), 1.0, rtol=1e-4)

    def test_indivisible_envs_rejected(self):
        env_params, traces, state, carry, step = build(n_envs=6)
        with pytest.raises(ValueError, match="divisible"):
            shard_train(make_mesh(), step, state, carry, traces)


class TestShardMapDP:
    """parallel.dp.shard_map_train — the explicit-collective
    (axis_name=DATA_AXIS) DP assembly (VERDICT r2 weak #4: the pmean branch
    was previously reachable only from a micro-test)."""

    def test_shard_map_step_runs_and_params_replicated(self):
        env_params, traces, state, carry, _ = build(n_envs=8)
        step = make_ppo_step(
            lambda p, o, m: make_policy("flat", env_params.n_actions
                                        ).apply(p, o, m),
            env_params, PPOConfig(n_steps=8, n_epochs=2, n_minibatches=2),
            DATA_AXIS)
        mesh = make_mesh()
        jstep, state, carry, traces = shard_map_train(mesh, step, state,
                                                      carry, traces)
        assert carry.key.shape == (8, 2)  # per-shard key stack
        for i in range(2):
            state, carry, metrics = jstep(state, carry, traces,
                                          jax.random.PRNGKey(i))
        assert all(np.isfinite(float(v)) for v in metrics)
        # pmean'd grads keep params bitwise identical on every device
        leaf = jax.tree.leaves(state.params)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)

    def test_matches_gspmd_updates_on_identical_rollouts(self):
        # Freeze the rollout noise out of the comparison: run ONE update
        # on the same fixed transitions through both assemblies via their
        # gradient paths — the pmean'd mean-of-shard-grads must equal the
        # global-batch gradient GSPMD computes (linearity of the mean; the
        # per-shard advantage moments are globally pmean'd).
        from jax.sharding import PartitionSpec as P
        from rlgpuschedule_tpu.algos import ppo_loss, Transition
        from rlgpuschedule_tpu.parallel.dp import shard_map_compat
        from rlgpuschedule_tpu.algos.ppo import normalize_advantages
        env_params, traces, state, carry, _ = build(n_envs=8,
                                                    dtype=jnp.float32)
        net = make_policy("flat", env_params.n_actions, dtype=jnp.float32)
        apply_fn = lambda p, o, m: net.apply(p, o, m)
        cfg = PPOConfig()
        B = 16
        batch = Transition(
            obs=jnp.tile(carry.obs[:1], (B, 1))
            + jnp.arange(B)[:, None] * 0.01,
            action=jnp.zeros((B,), jnp.int32),
            log_prob=jnp.full((B,), -1.0), value=jnp.zeros((B,)),
            reward=jnp.zeros((B,)), done=jnp.zeros((B,), bool),
            mask=jnp.ones((B, env_params.n_actions), bool),
            env_steps_dt=jnp.zeros((B,)))
        adv = jnp.linspace(-1.0, 1.0, B)
        ret = jnp.linspace(0.0, 1.0, B)
        mesh = make_mesh()

        def global_grad(p):
            a = normalize_advantages(adv)
            return jax.grad(lambda q: ppo_loss(
                apply_fn, q, batch, a, ret, cfg)[0])(p)

        def shard_grad(p, b, a_raw, r):
            a = normalize_advantages(a_raw, DATA_AXIS)
            g = jax.grad(lambda q: ppo_loss(apply_fn, q, b, a, r,
                                            cfg)[0])(p)
            return jax.lax.pmean(g, DATA_AXIS)

        g_ref = jax.jit(global_grad)(state.params)
        g_map = jax.jit(shard_map_compat(
            shard_grad, mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(), check=False))(state.params, batch, adv, ret)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_map)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)
