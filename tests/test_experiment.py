"""Experiment assembly tests: the five named configs build; config 1 trains
(SURVEY.md §7 step 5 milestone)."""
import numpy as np
import pytest

import dataclasses

from rlgpuschedule_tpu.configs import CONFIGS, ExperimentConfig
from rlgpuschedule_tpu.experiment import (Experiment, build_env_params,
                                          load_source_trace,
                                          make_env_windows,
                                          windows_per_pass)
from rlgpuschedule_tpu.algos import PPOConfig, A2CConfig


def test_run_fused_advances_like_run():
    """run_fused(k) is one on-device scan over the train step (the bench's
    sustained-throughput mode): it must advance training (params change,
    finite metrics) and leave the experiment reusable by the host loop."""
    import numpy as np
    import jax

    cfg = small(CONFIGS["ppo-mlp-synth64"])
    exp = Experiment.build(cfg)
    before = jax.tree.leaves(exp.train_state.params)[0].copy()
    metrics = exp.run_fused(3)
    assert all(np.isfinite(float(v)) for v in metrics)
    after = jax.tree.leaves(exp.train_state.params)[0]
    assert not np.allclose(np.asarray(before), np.asarray(after))
    out = exp.run(iterations=1)       # host loop still works afterwards
    assert out["iterations"] == 1


def test_run_fused_chunked_hooks_fire_on_grid():
    """run(fused_chunk=N): hooks fire on the same cadence grid as the
    per-step loop (boundary-aligned phase), indivisible cadences are
    refused, and training advances."""
    import numpy as np
    import pytest

    cfg = small(CONFIGS["ppo-mlp-synth64"])
    exp = Experiment.build(cfg)
    rows = []
    out = exp.run(iterations=8, log_every=4,
                  logger=lambda i, m: rows.append(i), fused_chunk=4)
    assert rows == [3, 7]                  # boundaries of the 4-cadence
    assert out["iterations"] == 8
    assert np.isfinite(out["env_steps_per_sec"])
    with pytest.raises(ValueError, match="fused_chunk"):
        exp.run(iterations=8, log_every=3, fused_chunk=4)
    with pytest.raises(ValueError, match="fused_chunk"):
        exp.run(iterations=6, fused_chunk=4)


def small(cfg: ExperimentConfig, **kw) -> ExperimentConfig:
    """Shrink a preset for CPU testing."""
    return dataclasses.replace(
        cfg, n_envs=2, window_jobs=16, horizon=64, iterations=2,
        ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2),
        a2c=A2CConfig(n_steps=8), **kw)


class TestConfigs:
    def test_presets_registered(self):
        assert {"ppo-mlp-synth64", "ppo-cnn-philly512", "a2c-pai-fair",
                "gnn-gang-place", "hier-pbt-member",
                "ppo-mlp-preempt"} <= set(CONFIGS)
        assert CONFIGS["ppo-mlp-synth64"].total_gpus == 64
        assert CONFIGS["ppo-cnn-philly512"].total_gpus == 512

    def test_real_trace_configs_require_path(self):
        csv_cfg = dataclasses.replace(CONFIGS["ppo-cnn-philly512"],
                                      trace="philly")
        with pytest.raises(ValueError, match="trace_path"):
            load_source_trace(csv_cfg)

    def test_proxy_presets_load_without_csv(self):
        """Configs 2/3 ship on the published-statistics proxies so they run
        with no external file (VERDICT r2 missing #3 / weak #5)."""
        for name in ("ppo-cnn-philly512", "a2c-pai-fair"):
            cfg = CONFIGS[name]
            tr = load_source_trace(cfg, n_jobs=512)
            assert tr.num_jobs == 512
            assert tr.gpus[tr.valid].max() <= cfg.total_gpus
        pai = load_source_trace(CONFIGS["a2c-pai-fair"], n_jobs=512)
        assert pai.tenant[pai.valid].max() < CONFIGS["a2c-pai-fair"].n_tenants

    def test_drain_frac_zeroes_submits_for_last_envs(self):
        """drain_frac: the last round(n_envs*frac) envs get backlog-drain
        windows (all valid submits 0), and streaming resamples keep the
        same envs drained."""
        cfg = dataclasses.replace(small(CONFIGS["ppo-mlp-synth64"]),
                                  n_envs=4, drain_frac=0.5)
        src = load_source_trace(cfg)
        for start in (0, 4):
            wins = make_env_windows(cfg, src, start)
            for e, w in enumerate(wins):
                drained = (w.submit[w.valid] == 0.0).all()
                assert drained == (e >= 2), (start, e)
        # drained window still trains end-to-end
        exp = Experiment.build(cfg)
        out = exp.run(iterations=2)
        assert out["env_steps"] == 2 * exp.steps_per_iteration

    def test_windows_cut_and_rebase(self):
        cfg = small(CONFIGS["ppo-mlp-synth64"])
        src = load_source_trace(cfg)
        wins = make_env_windows(cfg, src)
        assert len(wins) == cfg.n_envs
        for w in wins:
            assert w.num_jobs == cfg.window_jobs
            assert w.submit[0] == 0.0

    def test_window_tiling_covers_every_source_job(self):
        """Advancing the cursor by n_envs per resample must sweep the
        whole trace (VERDICT r1 missing #3)."""
        cfg = small(CONFIGS["ppo-mlp-synth64"])
        src = load_source_trace(cfg, n_jobs=100)  # not a multiple of 16
        per_pass = windows_per_pass(100, cfg.window_jobs)
        seen = set()
        for start in range(0, per_pass, cfg.n_envs):
            for w in make_env_windows(cfg, src, start):
                # recover source rows by (duration, gpus) fingerprint
                for j in range(w.max_jobs):
                    if w.valid[j]:
                        hits = np.flatnonzero(
                            (src.duration == w.duration[j])
                            & (src.gpus == w.gpus[j]))
                        seen.update(hits.tolist())
        assert len(seen) == 100


class TestWindowStreaming:
    def test_resample_rotates_windows_without_recompile(self):
        cfg = small(CONFIGS["ppo-mlp-synth64"], resample_every=1)
        exp = Experiment.build(cfg)
        first = np.asarray(exp.traces.duration).copy()
        out = exp.run(iterations=3, log_every=1)
        assert out["window_cursor"] == 2 * cfg.n_envs  # 2 resamples fired
        assert not np.array_equal(first, np.asarray(exp.traces.duration))
        assert all(np.isfinite(list(h.values())).all()
                   for h in out["history"])


class TestExperimentRuns:
    @pytest.mark.parametrize("name", ["ppo-mlp-synth64", "gnn-gang-place",
                                      "a2c-pai-fair", "hier-pbt-member"])
    def test_build_and_train_two_iterations(self, name):
        cfg = small(CONFIGS[name])
        if cfg.trace != "synthetic":  # pai config: use synthetic source in CI
            cfg = dataclasses.replace(cfg, trace="synthetic")
        exp = Experiment.build(cfg)
        out = exp.run(iterations=2, log_every=1)
        assert out["env_steps"] == 2 * exp.steps_per_iteration
        assert all(np.isfinite(list(h.values())).all() for h in out["history"])

    @pytest.mark.parametrize("obs_kind", ["flat", "grid", "graph"])
    def test_preemptive_action_space_trains(self, obs_kind):
        """VERDICT r1 missing #5: a preset variant trains with preemption
        enabled, for every encoder family."""
        cfg = small(CONFIGS["ppo-mlp-preempt"], obs_kind=obs_kind,
                    n_placements=2 if obs_kind == "graph" else 1)
        exp = Experiment.build(cfg)
        assert exp.env_params.n_actions == \
            cfg.queue_len * cfg.n_placements + cfg.preempt_len + 1
        assert exp.carry.mask.shape[-1] == exp.env_params.n_actions
        out = exp.run(iterations=2, log_every=1)
        assert all(np.isfinite(list(h.values())).all()
                   for h in out["history"])

    def test_grid_config_small(self):
        cfg = small(CONFIGS["ppo-cnn-philly512"], trace="synthetic",
                    n_nodes=8, queue_len=4)
        exp = Experiment.build(cfg)
        out = exp.run(iterations=2)
        assert out["env_steps_per_sec"] > 0

    def test_train_step_clean_under_debug_nans(self):
        """The sanitizer hook (utils.profiling.debug_checks, SURVEY.md §5
        'Race detection / sanitizers') actually wired into CI: two full
        train iterations execute NaN-free under jax_debug_nans, and the
        hook demonstrably trips on a real NaN (VERDICT r2 missing #5)."""
        import jax
        import jax.numpy as jnp
        from rlgpuschedule_tpu.utils import profiling
        cfg = small(CONFIGS["ppo-mlp-synth64"])
        exp = Experiment.build(cfg)
        with profiling.debug_checks():
            out = exp.run(iterations=2, log_every=1)
        assert all(np.isfinite(list(h.values())).all()
                   for h in out["history"])
        # and the flag is not a no-op: a NaN-producing program raises
        with profiling.debug_checks():
            with pytest.raises(FloatingPointError):
                jax.jit(lambda x: x / x)(jnp.float32(0.0)).block_until_ready()
        # flag restored after the context
        assert not jax.config.jax_debug_nans
