"""Unified telemetry layer tests (ISSUE 5): event-bus schema round-trip,
rank-merge ordering under interleaved monotonic clocks, torn-last-line
tolerance, the counters/gauges registry's Prometheus snapshot, the
production alarms (recompile / transfer — the alarm-fires-on-forced-
recompile gate mirrors tests/test_sentinels.py's geometry-change
control), the span-traced run loop (including the zero-added-host-syncs
contract: ONE device_get per logged iteration, telemetry attached or
not), the report CLI, and the MetricsLogger append/resume satellite.
"""
import csv
import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.obs import (AlarmError, Alarms, EventBus, Registry,
                                   RunTelemetry, SCHEMA_VERSION, merge_dir,
                                   merge_events, read_events)
from rlgpuschedule_tpu.obs import report as report_cli
from rlgpuschedule_tpu.utils import MetricsLogger, ThroughputMeter

# same shapes as test_resilience/test_checkpoint so the persistent XLA
# cache already holds every program this file compiles
SMALL = dataclasses.replace(
    CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=16, horizon=64,
    ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2))


class TestEventBus:
    def test_schema_roundtrip(self, tmp_path):
        with EventBus(str(tmp_path), rank=3) as bus:
            bus.emit("run_start", config="x", iterations=7)
            bus.emit("iteration", iteration=0, phases={"step": 0.5})
        events = read_events(bus.path)
        assert [e["kind"] for e in events] == ["run_start", "iteration"]
        first = events[0]
        assert first["v"] == SCHEMA_VERSION
        assert first["rank"] == 3 and first["pid"] == os.getpid()
        assert first["seq"] == 0 and events[1]["seq"] == 1
        assert isinstance(first["mono"], float)
        assert isinstance(first["wall"], float)
        assert first["config"] == "x" and first["iterations"] == 7
        assert events[1]["phases"] == {"step": 0.5}

    def test_reserved_field_collision_raises(self, tmp_path):
        with EventBus(str(tmp_path)) as bus:
            with pytest.raises(ValueError, match="stamp"):
                bus.emit("x", rank=9)

    def test_closed_bus_refuses_emit(self, tmp_path):
        bus = EventBus(str(tmp_path))
        bus.close()
        with pytest.raises(ValueError, match="closed"):
            bus.emit("x")

    def test_torn_last_line_tolerated(self, tmp_path):
        with EventBus(str(tmp_path), rank=0) as bus:
            bus.emit("a")
            bus.emit("b")
        # a writer killed mid-write leaves a truncated last line — the
        # one torn state append+flush-per-event can produce
        with open(bus.path, "a") as f:
            f.write('{"v": 1, "kind": "tor')
        events = read_events(bus.path)
        assert [e["kind"] for e in events] == ["a", "b"]

    def test_merge_orders_interleaved_monotonic_clocks(self, tmp_path):
        # two ranks whose emissions interleave in time but are written
        # to separate streams; the merge must re-interleave them by the
        # shared monotonic clock, not file order
        clock_a = iter([1.0, 4.0, 5.0])
        clock_b = iter([2.0, 3.0, 6.0])
        with EventBus(str(tmp_path), rank=0,
                      clock=lambda: next(clock_a)) as a, \
                EventBus(str(tmp_path), rank=1,
                         clock=lambda: next(clock_b)) as b:
            a.emit("a0")
            b.emit("b0")
            b.emit("b1")
            a.emit("a1")
            a.emit("a2")
            b.emit("b2")
        merged = merge_dir(str(tmp_path))
        assert [e["kind"] for e in merged] == \
            ["a0", "b0", "b1", "a1", "a2", "b2"]

    def test_merge_tie_breaks_deterministically(self):
        tie = [{"mono": 1.0, "rank": 1, "seq": 0, "kind": "r1"},
               {"mono": 1.0, "rank": 0, "seq": 1, "kind": "r0b"},
               {"mono": 1.0, "rank": 0, "seq": 0, "kind": "r0a"}]
        assert [e["kind"] for e in merge_events(tie)] == \
            ["r0a", "r0b", "r1"]

    def test_merge_dir_without_streams_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="no event streams"):
            merge_dir(str(tmp_path))

    def test_relaunched_rank_appends_to_its_stream(self, tmp_path):
        # a supervisor relaunch reopens the same rank id: one stream
        # tells the rank's whole story across attempts
        with EventBus(str(tmp_path), rank=0) as bus:
            bus.emit("worker_start")
        with EventBus(str(tmp_path), rank=0) as bus:
            bus.emit("worker_start")
        events = read_events(bus.path)
        assert [e["kind"] for e in events] == ["worker_start"] * 2


class TestRegistry:
    def test_counter_and_gauge_render_prometheus_text(self):
        r = Registry()
        c = r.counter("rlsched_iterations_total", "iterations run")
        c.inc()
        c.inc(2)
        r.gauge("rlsched_env_steps_per_sec", "throughput").set(12.5)
        text = r.render()
        assert "# HELP rlsched_iterations_total iterations run" in text
        assert "# TYPE rlsched_iterations_total counter" in text
        assert "rlsched_iterations_total 3" in text
        assert "# TYPE rlsched_env_steps_per_sec gauge" in text
        assert "rlsched_env_steps_per_sec 12.5" in text

    def test_counter_refuses_negative_increment(self):
        with pytest.raises(ValueError, match="negative"):
            Registry().counter("c").inc(-1)

    def test_reregistration_returns_same_object_kind_mismatch_raises(self):
        r = Registry()
        assert r.counter("c") is r.counter("c")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("c")

    def test_bad_metric_name_raises(self):
        with pytest.raises(ValueError, match="bad metric name"):
            Registry().counter("steps/s")

    def test_write_snapshot_atomic(self, tmp_path):
        r = Registry()
        r.counter("c").inc(5)
        path = str(tmp_path / "metrics.prom")
        r.write(path)
        assert open(path).read() == r.render()
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


class TestMetricsLoggerAppend:
    """Satellite: a supervisor relaunch / --resume must APPEND to the
    metrics CSV instead of truncating the history (mode "w" wiped it)."""

    def test_append_resumes_without_truncation(self, tmp_path):
        path = str(tmp_path / "m.csv")
        with MetricsLogger(path) as log:
            log(0, {"loss": 1.5})
            log(1, {"loss": 1.0})
        with MetricsLogger(path, append=True) as log:
            log(2, {"loss": 0.5})
        rows = list(csv.DictReader(open(path)))
        assert [r["iteration"] for r in rows] == ["0", "1", "2"]
        assert float(rows[2]["loss"]) == 0.5
        # exactly one header line in the file
        with open(path) as f:
            assert sum(1 for line in f if line.startswith("iteration")) == 1

    def test_append_validates_schema_against_existing_header(self, tmp_path):
        path = str(tmp_path / "m.csv")
        with MetricsLogger(path) as log:
            log(0, {"loss": 1.5})
        with MetricsLogger(path, append=True) as log:
            with pytest.raises(ValueError, match="schema drift"):
                log(1, {"reward": -1.0})

    def test_append_on_fresh_file_degrades_to_write(self, tmp_path):
        path = str(tmp_path / "m.csv")
        with MetricsLogger(path, append=True) as log:
            log(0, {"loss": 1.5})
        rows = list(csv.DictReader(open(path)))
        assert len(rows) == 1

    def test_throughput_meter_uses_injected_monotonic_clock(self):
        ticks = iter([0.0, 10.0])
        m = ThroughputMeter(clock=lambda: next(ticks))
        m.tick(50)
        assert m.steps_per_sec == pytest.approx(5.0)


class TestAlarms:
    """The production-alarm gate, mirroring test_sentinels' geometry-
    change control: a forced recompile in a post-warmup dispatch MUST
    emit a ``recompile`` event; geometry-stable dispatches must not."""

    @pytest.mark.sanitize
    def test_recompile_alarm_fires_on_forced_recompile(self, tmp_path):
        bus = EventBus(str(tmp_path), rank=0)
        f = jax.jit(lambda x: x * 3 + 1)
        x_warm = jnp.ones((4, 5))
        x_fresh = jnp.ones((6, 7))   # built OUTSIDE the guarded dispatch
        with Alarms(bus, warmup_iters=1) as al:
            with al.dispatch(0):     # warmup: the one allowed compile
                f(x_warm).block_until_ready()
            with al.dispatch(1):     # steady state: cached, clean
                f(x_warm).block_until_ready()
            with al.dispatch(2):     # forced recompile: shape change
                f(x_fresh).block_until_ready()
            with al.dispatch(3):     # control: BACK to a cached shape
                f(x_warm).block_until_ready()
        bus.close()
        events = read_events(bus.path)
        kinds = [(e["kind"], e["iteration"]) for e in events]
        assert ("compile", 0) in kinds       # warmup recorded, not alarmed
        assert ("recompile", 2) in kinds     # the alarm
        alarmed = [i for k, i in kinds if k == "recompile"]
        assert alarmed == [2]                # 1 and 3 stayed clean
        assert al.registry.counter(
            "rlsched_recompile_alarms_total").value == 1

    @pytest.mark.sanitize
    def test_transfer_alarm_emits_and_fails_fast(self, tmp_path):
        bus = EventBus(str(tmp_path), rank=0)
        dev = jnp.arange(8.0)
        host = np.ones(8, np.float32)   # implicit host->device operand
        with Alarms(bus, warmup_iters=0) as al:
            with pytest.raises(AlarmError, match="transfer alarm"):
                with al.dispatch(0):
                    (dev + host).block_until_ready()
        bus.close()
        events = read_events(bus.path)
        assert [e["kind"] for e in events] == ["transfer"]
        assert al.registry.counter(
            "rlsched_transfer_alarms_total").value == 1

    def test_expected_recompile_amnesty(self, tmp_path):
        bus = EventBus(str(tmp_path), rank=0)
        f = jax.jit(lambda x: x - 2)
        a, b = jnp.ones((3, 11)), jnp.ones((5, 13))
        with Alarms(bus, warmup_iters=1) as al:
            with al.dispatch(0):
                f(a).block_until_ready()
            al.expect_recompile("rollback lr rescale")
            with al.dispatch(1):            # re-trace, but blessed
                f(b).block_until_ready()
        bus.close()
        events = read_events(bus.path)
        assert [e["kind"] for e in events] == ["compile", "compile"]
        assert events[1]["expected"] == "rollback lr rescale"

    def test_slow_iteration_alarm(self, tmp_path):
        bus = EventBus(str(tmp_path), rank=0)
        with Alarms(bus, warmup_iters=0, slow_iter_s=0.5,
                    profile_dir=None) as al:
            al.observe_wall(4, 0.1)     # fast: no alarm
            al.observe_wall(5, 2.0)     # slow: alarm
        bus.close()
        events = read_events(bus.path)
        assert [(e["kind"], e["iteration"]) for e in events] == \
            [("slow_iteration", 5)]
        assert events[0]["threshold_s"] == 0.5


class TestRunTelemetry:
    def test_experiment_run_emits_spans_and_stays_alarm_clean(
            self, tmp_path):
        """3 geometry-stable iterations under full telemetry + alarms:
        run_start / per-iteration spans with the phase breakdown /
        run_end on the stream, the Prometheus snapshot on disk, and ZERO
        recompile/transfer alarm events after the warmup iteration (the
        acceptance criterion)."""
        from rlgpuschedule_tpu.experiment import Experiment
        exp = Experiment.build(SMALL)
        with RunTelemetry(str(tmp_path), rank=0, alarms=True) as tel:
            out = exp.run(iterations=3, log_every=1, telemetry=tel)
        assert out["iterations"] == 3
        events = read_events(tel.bus.path)
        kinds = [e["kind"] for e in events]
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"
        iters = [e for e in events if e["kind"] == "iteration"]
        assert [e["iteration"] for e in iters] == [0, 1, 2]
        assert all("step" in e["phases"] for e in iters)
        assert all("sync" in e["phases"] for e in iters)
        assert all(np.isfinite(e["metrics"]["total_loss"])
                   for e in iters)
        assert "recompile" not in kinds and "transfer" not in kinds
        prom = open(os.path.join(str(tmp_path), "metrics.prom")).read()
        assert "rlsched_iterations_total 3" in prom
        assert "rlsched_env_steps_total 48" in prom   # 3 * 8 * 2

    def test_host_sync_count_unchanged_by_telemetry(self, tmp_path,
                                                    monkeypatch):
        """The zero-added-host-syncs contract: an instrumented run calls
        jax.device_get exactly once per logged iteration — the same
        single batched sync the bare loop pays (jsan host-sync review,
        PR 3). Runs with the flight recorder ON (trace=True): span
        emission must touch host clocks and the JSONL file only, never
        a device value — the --trace-spans acceptance gate."""
        from rlgpuschedule_tpu.experiment import Experiment
        exp = Experiment.build(SMALL)
        calls = {"n": 0}
        real = jax.device_get

        def counting(x):
            calls["n"] += 1
            return real(x)

        with RunTelemetry(str(tmp_path), rank=0, alarms=True,
                          trace=True) as tel:
            monkeypatch.setattr(jax, "device_get", counting)
            exp.run(iterations=3, log_every=1, telemetry=tel)
            monkeypatch.setattr(jax, "device_get", real)
        assert calls["n"] == 3   # one per logged iteration, none extra
        # and the spans actually landed (tracing was really on)
        from rlgpuschedule_tpu.obs.trace import SPAN_BEGIN
        events = read_events(tel.bus.path)
        assert any(e["kind"] == SPAN_BEGIN for e in events)

    def test_rollback_story_lands_on_one_timeline(self, tmp_path):
        """fault -> ckpt_restore -> rollback -> amnestied compile on the
        merged stream, and the retry's legitimate re-trace does NOT fire
        the recompile alarm."""
        from rlgpuschedule_tpu.checkpoint import Checkpointer
        from rlgpuschedule_tpu.experiment import Experiment
        from rlgpuschedule_tpu.resilience import (DivergenceWatchdog,
                                                  FaultInjector,
                                                  parse_fault)
        obs = str(tmp_path / "obs")
        exp = Experiment.build(SMALL)
        with RunTelemetry(obs, rank=0, alarms=True) as tel:
            with Checkpointer(str(tmp_path / "ck"), bus=tel.bus) as ckpt:
                out = exp.run(
                    iterations=3, log_every=1, ckpt=ckpt, ckpt_every=1,
                    watchdog=DivergenceWatchdog(max_rollbacks=1,
                                                bus=tel.bus),
                    injector=FaultInjector([parse_fault("nan-grad@1")],
                                           bus=tel.bus),
                    telemetry=tel)
        assert out["rollbacks"] == 1
        events = merge_dir(obs)
        kinds = [e["kind"] for e in events]
        assert "fault" in kinds and "rollback" in kinds
        assert "ckpt_save" in kinds and "ckpt_restore" in kinds
        assert kinds.index("fault") < kinds.index("rollback")
        assert "recompile" not in kinds   # retry re-trace was amnestied
        rb = next(e for e in events if e["kind"] == "rollback")
        assert rb["reason"].startswith("non-finite")
        assert rb["iteration"] == 1


class TestPopulationTelemetry:
    def test_pbt_run_emits_spans_and_exploit_events(self, tmp_path,
                                                    capsys):
        """The population loop speaks the same span protocol, plus
        ``pbt_exploit`` rounds (who copied whom) on the timeline.
        Shapes match test_cli's PBT test for compile-cache reuse."""
        from rlgpuschedule_tpu import train as train_cli
        obs = str(tmp_path / "obs")
        train_cli.main(
            ["--config", "hier-pbt-member", "--pbt", "--n-pop", "2",
             "--pbt-ready", "1", "--iterations", "2", "--n-envs", "4",
             "--n-nodes", "4", "--gpus-per-node", "4",
             "--window-jobs", "16", "--log-every", "1",
             "--horizon", "48", "--queue-len", "4", "--n-steps", "8",
             "--n-epochs", "1", "--n-minibatches", "2",
             "--obs-dir", obs])
        capsys.readouterr()
        events = merge_dir(obs)
        kinds = [e["kind"] for e in events]
        start = next(e for e in events if e["kind"] == "run_start")
        assert start["loop"] == "population" and start["n_pop"] == 2
        assert kinds.count("iteration") == 2
        exploits = [e for e in events if e["kind"] == "pbt_exploit"]
        assert len(exploits) >= 1
        assert all(len(e["src"]) == 2 for e in exploits)
        iters = [e for e in events if e["kind"] == "iteration"]
        # flattened per-member metric columns ride the iteration event
        assert all("mean_reward_mean" in e["metrics"] for e in iters)


class TestReportCLI:
    def _seed_dir(self, tmp_path) -> str:
        d = str(tmp_path / "obs")
        with EventBus(d, rank=0) as bus:
            bus.emit("run_start", config="x")
            bus.emit("iteration", iteration=0, wall_s=0.5,
                     steps_per_sec=100.0, phases={"step": 0.4,
                                                  "sync": 0.1},
                     metrics={"total_loss": 0.1})
            bus.emit("run_end")
        return d

    def test_report_exits_zero_and_prints_sections(self, tmp_path,
                                                   capsys):
        d = self._seed_dir(tmp_path)
        assert report_cli.main([d]) == 0
        out = capsys.readouterr().out
        assert "phase-time table" in out
        assert "steps/s curve" in out
        assert "alarms:" in out and "(clean)" in out

    def test_report_json_and_merged_out(self, tmp_path, capsys):
        d = self._seed_dir(tmp_path)
        merged = str(tmp_path / "merged.jsonl")
        assert report_cli.main([d, "--json", "--out", merged]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["n_events"] == 3
        assert rep["phase_seconds"]["step"] == pytest.approx(0.4)
        lines = [json.loads(line) for line in open(merged)]
        assert [e["kind"] for e in lines] == \
            ["run_start", "iteration", "run_end"]

    def test_strict_alarms_fails_on_recompile_event(self, tmp_path):
        d = self._seed_dir(tmp_path)
        assert report_cli.main([d, "--strict-alarms"]) == 0
        with EventBus(d, rank=0) as bus:
            bus.emit("recompile", iteration=7, events=2)
        assert report_cli.main([d, "--strict-alarms"]) == 1

    def test_missing_dir_exits_one(self, tmp_path):
        assert report_cli.main([str(tmp_path / "nope")]) == 1


class TestTrainCLIObs:
    def test_alarms_require_obs_dir(self):
        from rlgpuschedule_tpu import train as train_cli
        with pytest.raises(SystemExit, match="--obs-dir"):
            train_cli.main(["--config", "ppo-mlp-synth64", "--alarms"])

    def test_train_obs_dir_produces_reportable_clean_timeline(
            self, tmp_path, capsys):
        """The CI smoke contract from the CLI surface: a short run with
        --obs-dir + --alarms produces a merged timeline the report CLI
        accepts with --strict-alarms (zero post-warmup recompiles)."""
        from rlgpuschedule_tpu import train as train_cli
        obs = str(tmp_path / "obs")
        # same shapes as test_cli.FAST (compile-cache reuse)
        train_cli.main(
            ["--config", "ppo-mlp-synth64", "--iterations", "2",
             "--n-envs", "4", "--n-nodes", "2", "--gpus-per-node", "4",
             "--window-jobs", "16", "--log-every", "1", "--horizon",
             "64", "--queue-len", "4", "--n-steps", "8", "--n-epochs",
             "1", "--n-minibatches", "2", "--obs-dir", obs, "--alarms"])
        capsys.readouterr()
        assert report_cli.main([obs, "--strict-alarms"]) == 0
        out = capsys.readouterr().out
        assert "alarms:" in out
        events = merge_dir(obs)
        kinds = [e["kind"] for e in events]
        assert "run_start" in kinds and "run_end" in kinds
        assert kinds.count("iteration") == 2
        assert os.path.exists(os.path.join(obs, "metrics.prom"))
