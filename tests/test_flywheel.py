"""Data-flywheel tests (ISSUE 19): crash-safe flight log (crc
sidecars, torn-tail vs interior-corruption semantics, served == logged
conservation through the live server), continual V-trace ingest with
the measured-staleness trust region, canary-gated promotion with
hysteresis, live swap bit-identity + SLO watchdog rollback, the
crc-sidecar'd promotion ledger, the durable event-bus mode, and the
piecewise hour-of-day diurnal fit."""
import dataclasses
import json
import os
import types

import jax
import numpy as np
import pytest

from rlgpuschedule_tpu.algos import PPOConfig
from rlgpuschedule_tpu.configs import CONFIGS
from rlgpuschedule_tpu.env import env as env_lib
from rlgpuschedule_tpu.experiment import Experiment
from rlgpuschedule_tpu.flywheel.canary import (CanaryReport, LedgerCorruptError,
                                               PromotionLedger, SLOWatchdog,
                                               action_agreement, read_ledger,
                                               replay_decisions, run_canary)
from rlgpuschedule_tpu.flywheel.continual import (admit_shards,
                                                  gate_logged_mask,
                                                  run_continual,
                                                  shard_rho_stats)
from rlgpuschedule_tpu.flywheel.flightlog import (FlightLogCorruptError,
                                                  FlightLogError,
                                                  FlightLogWriter, FlightShard,
                                                  read_flight_log, shard_name)
from rlgpuschedule_tpu.obs import EventBus, Registry, read_events
from rlgpuschedule_tpu.serve import InferenceEngine, PolicyServer
from rlgpuschedule_tpu.traces.fit import TraceFit, fit_hourly_curve, fit_jobs
from rlgpuschedule_tpu.traces.philly_proxy import (PHILLY_HOURLY,
                                                   _diurnal_arrivals,
                                                   gen_philly_proxy_jobs)


def small_cfg(**kw):
    return dataclasses.replace(
        CONFIGS["ppo-mlp-synth64"], n_envs=2, window_jobs=12, horizon=96,
        n_nodes=4, gpus_per_node=4, queue_len=4,
        ppo=PPOConfig(n_steps=8, n_epochs=1, n_minibatches=2), **kw)


@pytest.fixture(scope="module")
def exp():
    """Read-only experiment: params are never mutated by these tests."""
    return Experiment.build(small_cfg())


@pytest.fixture(scope="module")
def exp_cont():
    """Continual-training experiment: run_continual advances its
    train_state in place, so it gets its own instance."""
    return Experiment.build(small_cfg(name="fly-cont"))


def host_requests(exp, n=None):
    _state, ts = env_lib.vec_reset(exp.env_params, exp.traces)
    obs = np.asarray(jax.device_get(ts.obs))
    mask = np.asarray(jax.device_get(ts.action_mask))
    n = obs.shape[0] if n is None else n
    return obs[:n], mask[:n]


def synth_rows(n, seed=0, n_feat=5, n_act=7):
    """Synthetic single-leaf flight-log columns (no env needed for the
    pure write/read crash-safety tests)."""
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, n_feat)).astype(np.float32),
            rng.integers(0, 2, (n, n_act)).astype(bool),
            rng.integers(0, n_act, n).astype(np.int32),
            rng.standard_normal(n).astype(np.float32),
            rng.standard_normal(n).astype(np.float32),
            np.zeros(n, np.int32),
            rng.integers(0, 3, n).astype(np.int8))


def write_synth_log(directory, n=20, capacity=8, seed=0, **kw):
    obs, mask, act, lp, val, stall, oc = synth_rows(n, seed)
    with FlightLogWriter(directory, capacity=capacity, **kw) as w:
        # uneven batches so seals straddle append boundaries
        for lo, hi in ((0, 7), (7, 14), (14, n)):
            w.append_batch(obs[lo:hi], mask[lo:hi], act[lo:hi], lp[lo:hi],
                           val[lo:hi], stall[lo:hi], oc[lo:hi])
    return obs, mask, act, lp, val, stall, oc


class TestFlightLog:
    def test_roundtrip_bit_identical(self, tmp_path):
        d = str(tmp_path / "flog")
        reg = Registry()
        obs, mask, act, lp, val, stall, oc = write_synth_log(
            d, n=20, capacity=8, policy_step=17, registry=reg)
        data = read_flight_log(d)
        assert not data.torn_tail
        assert [s.rows for s in data.shards] == [8, 8, 4]
        assert all(s.policy_step == 17 for s in data.shards)
        assert data.rows == 20
        cat = data.concat()
        np.testing.assert_array_equal(cat.obs_leaves[0], obs)
        np.testing.assert_array_equal(cat.mask_leaves[0], mask)
        np.testing.assert_array_equal(cat.act_leaves[0], act)
        np.testing.assert_array_equal(cat.log_prob, lp)
        np.testing.assert_array_equal(cat.value, val)
        np.testing.assert_array_equal(cat.outcome, oc)
        assert cat.policy_step == 17
        rendered = reg.render()
        assert "flywheel_rows_logged_total 20" in rendered
        assert "flywheel_shards_sealed_total 3" in rendered

    def test_req_id_column_round_trips(self, tmp_path):
        d = str(tmp_path)
        obs, mask, act, lp, val, stall, oc = synth_rows(20)
        rids = np.arange(1, 21, dtype=np.int64) << 40   # salted-looking
        with FlightLogWriter(d, capacity=8) as w:
            for lo, hi in ((0, 7), (7, 14), (14, 20)):
                w.append_batch(obs[lo:hi], mask[lo:hi], act[lo:hi],
                               lp[lo:hi], val[lo:hi], stall[lo:hi],
                               oc[lo:hi], req_id=rids[lo:hi])
        cat = read_flight_log(d).concat()
        assert cat.req_id.dtype == np.int64
        np.testing.assert_array_equal(cat.req_id, rids)

    def test_req_id_defaults_to_unassigned_zero(self, tmp_path):
        d = str(tmp_path)
        write_synth_log(d, n=8, capacity=8)    # no req_id passed
        cat = read_flight_log(d).concat()
        np.testing.assert_array_equal(cat.req_id, np.zeros(8, np.int64))

    def test_pre_issue20_shard_loads_with_zero_req_ids(self, tmp_path):
        """A shard written before the req_id column existed must still
        load (ids read as 0 = unassigned), and concat must not trip on
        the mixed old/new shard case."""
        from rlgpuschedule_tpu.flywheel.flightlog import _crc32_file
        d = str(tmp_path)
        obs, mask, act, lp, val, stall, oc = synth_rows(16)
        rids = np.arange(100, 116, dtype=np.int64)
        with FlightLogWriter(d, capacity=8) as w:
            w.append_batch(obs, mask, act, lp, val, stall, oc,
                           req_id=rids)
        # strip req_id out of shard 0 as if written by the old code,
        # then re-bless its crc sidecar
        path = os.path.join(d, shard_name(0))
        with np.load(path) as z:
            cols = {k: z[k] for k in z.files if k != "req_id"}
        with open(path, "wb") as f:
            np.savez(f, **cols)
        side = os.path.join(d, ".crc", "shard-000000.json")
        meta = json.load(open(side))
        meta["crc32"] = _crc32_file(path)
        json.dump(meta, open(side, "w"))
        cat = read_flight_log(d).concat()
        np.testing.assert_array_equal(
            cat.req_id, np.concatenate([np.zeros(8, np.int64), rids[8:]]))

    def test_req_id_length_mismatch_rejected(self, tmp_path):
        obs, mask, act, lp, val, stall, oc = synth_rows(4)
        with FlightLogWriter(str(tmp_path), capacity=8) as w:
            with pytest.raises(ValueError, match="req_id"):
                w.append_batch(obs, mask, act, lp, val, stall, oc,
                               req_id=np.arange(3, dtype=np.int64))

    def test_rows_logged_counts_sealed_plus_buffered(self, tmp_path):
        obs, mask, act, lp, val, stall, oc = synth_rows(5)
        w = FlightLogWriter(str(tmp_path), capacity=4)
        w.append_batch(obs, mask, act, lp, val, stall, oc)
        assert w.rows_logged == 5 and w.shards_sealed == 1
        w.close()
        assert w.shards_sealed == 2       # tail sealed on close
        with pytest.raises(FlightLogError, match="closed"):
            w.append_batch(obs, mask, act, lp, val, stall, oc)
        w.close()                         # idempotent

    def test_seal_event_uses_shard_not_seq(self, tmp_path):
        """Regression: the seal event's payload key must not shadow the
        bus's reserved `seq` stamp field — emit() raises on shadowing,
        and a raise inside a dispatch pump once stranded futures."""
        bus = EventBus(str(tmp_path / "obs"))
        try:
            write_synth_log(str(tmp_path / "flog"), n=8, capacity=8,
                            policy_step=3, bus=bus)
        finally:
            bus.close()
        seals = [e for e in read_events(bus.path)
                 if e["kind"] == "flywheel_shard_seal"]
        assert [e["shard"] for e in seals] == [0]
        assert seals[0]["rows"] == 8 and seals[0]["policy_step"] == 3

    def test_torn_tail_dropped_and_flagged(self, tmp_path):
        d = str(tmp_path)
        write_synth_log(d, n=20, capacity=8)
        os.remove(os.path.join(d, ".crc", "shard-000002.json"))
        data = read_flight_log(d)
        assert data.torn_tail and "shard-000002" in data.torn_reason
        assert [s.seq for s in data.shards] == [0, 1]
        assert data.rows == 16

    def test_truncated_tail_payload_is_torn(self, tmp_path):
        d = str(tmp_path)
        write_synth_log(d, n=20, capacity=8)
        path = os.path.join(d, shard_name(2))
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:len(blob) // 2])   # kill mid-write
        data = read_flight_log(d)
        assert data.torn_tail and len(data.shards) == 2

    def test_interior_corruption_raises(self, tmp_path):
        d = str(tmp_path)
        write_synth_log(d, n=20, capacity=8)
        path = os.path.join(d, shard_name(0))
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(path, "wb").write(bytes(blob))
        with pytest.raises(FlightLogCorruptError, match="crc32 mismatch"):
            read_flight_log(d)

    def test_interior_missing_sidecar_raises(self, tmp_path):
        d = str(tmp_path)
        write_synth_log(d, n=20, capacity=8)
        os.remove(os.path.join(d, ".crc", "shard-000001.json"))
        with pytest.raises(FlightLogCorruptError, match="non-tail"):
            read_flight_log(d)

    def test_interior_missing_shard_raises(self, tmp_path):
        """A seq gap (interior shard file lost WITH its sidecar) is
        data loss, not a torn tail — per-file crc checks cannot see it,
        the contiguity check must."""
        d = str(tmp_path)
        write_synth_log(d, n=20, capacity=8)
        os.remove(os.path.join(d, shard_name(1)))
        os.remove(os.path.join(d, ".crc", "shard-000001.json"))
        with pytest.raises(FlightLogCorruptError, match="missing"):
            read_flight_log(d)

    def test_lost_sealed_tail_raises(self, tmp_path):
        """A tail shard whose payload vanished AFTER publication leaves
        its sidecar behind (payload-then-sidecar ordering) — that is
        loss of sealed data, not the at-most-one torn tail."""
        d = str(tmp_path)
        write_synth_log(d, n=20, capacity=8)
        os.remove(os.path.join(d, shard_name(2)))
        with pytest.raises(FlightLogCorruptError, match="lost"):
            read_flight_log(d)

    def test_tmp_leftovers_ignored(self, tmp_path):
        d = str(tmp_path)
        write_synth_log(d, n=8, capacity=8)
        open(os.path.join(d, "shard-000001.npz.tmp.999"), "wb").write(b"x")
        data = read_flight_log(d)
        assert not data.torn_tail and data.rows == 8

    def test_capacity_validates(self, tmp_path):
        with pytest.raises(ValueError, match="capacity"):
            FlightLogWriter(str(tmp_path), capacity=0)

    def test_empty_log_refuses_concat(self, tmp_path):
        data = read_flight_log(str(tmp_path))
        assert data.shards == [] and not data.torn_tail
        with pytest.raises(FlightLogError, match="empty"):
            data.concat()


class TestServedConservation:
    def make_server(self, exp, tmp_path, registry, **log_kw):
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8,
                                 registry=registry, capture=True)
        obs, mask = host_requests(exp)
        engine.warmup(obs[0], mask[0])
        writer = FlightLogWriter(str(tmp_path / "flog"), registry=registry,
                                 **log_kw)
        return PolicyServer(engine, registry=registry,
                            flight_log=writer), writer, engine

    def test_served_rows_equal_logged_rows_bit_identically(self, exp,
                                                           tmp_path):
        reg = Registry()
        server, writer, engine = self.make_server(
            exp, tmp_path, reg, capacity=6,
            policy_step=int(exp.train_state.step))
        obs, mask = host_requests(exp)
        futs = [server.submit(obs[i % 2], mask[i % 2]) for i in range(10)]
        while server.pump():
            pass
        served = [f.result(timeout=30) for f in futs]
        server.close()
        writer.close()
        # conservation: every served row is logged, nothing else is
        assert writer.rows_logged == len(served) == 10
        data = read_flight_log(str(tmp_path / "flog"))
        assert not data.torn_tail and data.rows == 10
        cat = data.concat()
        np.testing.assert_array_equal(
            cat.act_leaves[0],
            np.stack([np.asarray(r.action) for r in served]))
        np.testing.assert_array_equal(cat.obs_leaves[0],
                                      np.stack([obs[i % 2]
                                                for i in range(10)]))
        assert cat.policy_step == int(exp.train_state.step)
        # the logged behavior columns replay bit-identically under the
        # incumbent: the canary's reference leg is exact by construction
        rep = run_canary(exp.apply_fn, exp.train_state.params,
                         exp.train_state.params, cat, obs[0], mask[0],
                         env_params=exp.env_params)
        assert rep.verdict == "promote"
        assert rep.incumbent_agreement == 1.0
        assert rep.candidate_agreement == 1.0

    def test_flight_log_requires_capture_engine(self, exp, tmp_path):
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        with pytest.raises(ValueError, match="capture"):
            PolicyServer(engine,
                         flight_log=FlightLogWriter(str(tmp_path)))

    def test_failing_append_fails_futures_loudly(self, exp, tmp_path):
        """Regression: a raising flight-log append must resolve the
        batch's futures with the exception — the background dispatcher
        swallows pump errors on the assumption the pump already did, so
        anything else strands clients in result() forever."""
        reg = Registry()
        server, writer, _ = self.make_server(exp, tmp_path, reg)

        def boom(*a, **kw):
            raise RuntimeError("disk gone")

        writer.append_batch = boom
        obs, mask = host_requests(exp)
        server.start()
        try:
            fut = server.submit(obs[0], mask[0])
            with pytest.raises(RuntimeError, match="disk gone"):
                fut.result(timeout=30)
        finally:
            server.stop()
            server.close()


class TestCanaryGate:
    @pytest.fixture(scope="class")
    def flip_obs(self, exp):
        """An observation where negated params flip the full-mask greedy
        action — the deterministic 'regressed candidate' probe."""
        obs, mask = host_requests(exp)
        full = np.ones_like(mask[0])
        neg = jax.tree.map(lambda x: -x, exp.train_state.params)
        for row in obs:
            a0, _, _ = replay_decisions(exp.apply_fn, exp.train_state.params,
                                        row[None], full[None], None)
            a1, _, _ = replay_decisions(exp.apply_fn, neg, row[None],
                                        full[None], None)
            if not action_agreement(a0, a1)[0]:
                return row, full, neg
        pytest.fail("no probe observation flips under negated params")

    def make_window(self, exp, flip_obs, flip_slices, n=80, slices=8):
        """A window whose rows force agreement except inside
        ``flip_slices``: forced rows carry a one-hot mask (any policy
        must pick the single legal action), flip rows carry a full mask
        at an observation where the negated candidate provably departs
        from the incumbent. Logged actions = the incumbent's replay, so
        the incumbent leg is exact."""
        from rlgpuschedule_tpu.flywheel.flightlog import FlightShard
        row, full, _ = flip_obs
        per = n // slices
        obs = np.repeat(row[None], n, axis=0)
        mask = np.zeros((n,) + full.shape, full.dtype)
        mask[:, 0] = True                       # forced: only action 0
        for s in flip_slices:
            mask[s * per:(s + 1) * per] = True  # free: candidate departs
        act, lp, val = replay_decisions(exp.apply_fn, exp.train_state.params,
                                        obs, mask, None)
        return FlightShard(
            seq=0, path="<synth>", rows=n,
            policy_step=int(exp.train_state.step),
            obs_leaves=[obs], mask_leaves=[mask],
            act_leaves=[np.asarray(a) for a in jax.tree.leaves(act)],
            log_prob=np.asarray(lp), value=np.asarray(val),
            stall=np.zeros(n, np.int32), outcome=np.zeros(n, np.int8))

    def test_regressed_candidate_blocked_with_evidence(self, exp, flip_obs,
                                                       tmp_path):
        reg = Registry()
        bus = EventBus(str(tmp_path))
        window = self.make_window(exp, flip_obs, flip_slices=range(8))
        try:
            rep = run_canary(exp.apply_fn, exp.train_state.params,
                             flip_obs[2], window, flip_obs[0][None][0],
                             flip_obs[1], registry=reg, bus=bus)
        finally:
            bus.close()
        assert rep.verdict == "blocked"
        assert rep.incumbent_agreement == 1.0
        assert rep.candidate_agreement < 1.0
        assert rep.max_regress_streak >= 2
        rendered = reg.render()
        assert "flywheel_canary_runs_total 1" in rendered
        assert "flywheel_promotions_blocked_total 1" in rendered
        kinds = [e["kind"] for e in read_events(bus.path)]
        assert "promote_blocked" in kinds

    def test_single_regressing_slice_promotes(self, exp, flip_obs):
        """Hysteresis: one noisy slice cannot veto a candidate."""
        window = self.make_window(exp, flip_obs, flip_slices=[3])
        rep = run_canary(exp.apply_fn, exp.train_state.params, flip_obs[2],
                         window, flip_obs[0], flip_obs[1])
        assert rep.verdict == "promote"
        assert rep.regress_slices == 1 and rep.max_regress_streak == 1

    def test_consecutive_regressing_slices_block(self, exp, flip_obs):
        reg = Registry()
        window = self.make_window(exp, flip_obs, flip_slices=[3, 4])
        rep = run_canary(exp.apply_fn, exp.train_state.params, flip_obs[2],
                         window, flip_obs[0], flip_obs[1], registry=reg)
        assert rep.verdict == "blocked" and rep.max_regress_streak == 2
        assert rep.regress_slices == 2

    def test_incumbent_is_the_reference_not_absolute_agreement(self, exp,
                                                               flip_obs):
        """A slice where the LOG disagrees with everyone (behavior
        snapshot older than the incumbent) penalizes both legs equally
        — the candidate is judged relative to the incumbent."""
        window = self.make_window(exp, flip_obs, flip_slices=[])
        # corrupt the logged actions on slice 0: nobody can agree there
        window.act_leaves = [np.array(l) for l in window.act_leaves]
        for leaf in window.act_leaves:
            leaf[:10] = (leaf[:10] + 1) % 2
        rep = run_canary(exp.apply_fn, exp.train_state.params,
                         exp.train_state.params, window, flip_obs[0],
                         flip_obs[1])
        assert rep.verdict == "promote"
        assert rep.incumbent_agreement < 1.0
        assert rep.candidate_agreement == rep.incumbent_agreement

    def test_validates_knobs(self, exp, flip_obs):
        window = self.make_window(exp, flip_obs, flip_slices=[])
        with pytest.raises(ValueError, match="slices"):
            run_canary(exp.apply_fn, exp.train_state.params,
                       exp.train_state.params, window, flip_obs[0],
                       flip_obs[1], slices=0)
        with pytest.raises(ValueError, match="hysteresis"):
            run_canary(exp.apply_fn, exp.train_state.params,
                       exp.train_state.params, window, flip_obs[0],
                       flip_obs[1], hysteresis=0)


class TestSwapAndWatchdog:
    def test_swap_rewarm_zero_recompiles_and_rollback_bit_identity(
            self, exp):
        reg = Registry()
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8,
                                 registry=reg, strict=True)
        obs, mask = host_requests(exp)
        warmed = engine.warmup(obs[0], mask[0])
        incumbent = exp.train_state.params
        before, _ = engine.decide(obs, mask)
        candidate = jax.tree.map(lambda x: x + 0.125, incumbent)
        engine.set_params(candidate)
        assert engine.rewarm() == warmed      # blessed pass, every bucket
        assert engine.post_warmup_recompiles == 0
        # rollback restores the incumbent program bit-identically
        engine.set_params(incumbent)
        assert engine.rewarm() == warmed
        after, _ = engine.decide(obs, mask)
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert engine.post_warmup_recompiles == 0

    def test_shape_changing_swap_refused(self, exp):
        engine = InferenceEngine(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, max_bucket=8)
        with pytest.raises(ValueError):
            engine.set_params({"not": np.zeros(3, np.float32)})

    def make_watchdog(self, tmp_path=None, **kw):
        reg = Registry()
        eng = types.SimpleNamespace(post_warmup_recompiles=0)
        bus = EventBus(str(tmp_path)) if tmp_path is not None else None
        wd = SLOWatchdog(reg, engine=eng, p99_factor=1.5, breach_after=2,
                         bus=bus, **kw)
        return wd, reg, eng, bus

    def test_p99_breach_streak_requests_rollback(self, tmp_path):
        wd, reg, _, bus = self.make_watchdog(tmp_path)
        g = reg.gauge("serve_decision_latency_p99_ms")
        try:
            g.set(10.0)
            for _ in range(3):
                wd.sample_baseline()
            wd.arm()
            g.set(11.0)
            assert not wd.observe()["rollback"]     # within 1.5x
            g.set(100.0)
            tick = wd.observe()
            assert not tick["rollback"] and tick["streak"] == 1
            tick = wd.observe()
            assert tick["rollback"] and tick["streak"] == 2
            assert any("p99" in r for r in tick["reasons"])
        finally:
            bus.close()
        kinds = [e["kind"] for e in read_events(bus.path)]
        assert "promote_rollback" in kinds

    def test_breach_streak_resets_on_a_clean_tick(self):
        wd, reg, _, _ = self.make_watchdog()
        g = reg.gauge("serve_decision_latency_p99_ms")
        g.set(10.0)
        wd.sample_baseline()
        wd.arm()
        g.set(100.0)
        assert wd.observe()["streak"] == 1
        g.set(10.0)
        assert wd.observe()["streak"] == 0      # hysteresis reset
        g.set(100.0)
        assert not wd.observe()["rollback"]     # streak restarts at 1

    def test_post_swap_recompile_is_immediate_rollback(self):
        wd, reg, eng, _ = self.make_watchdog()
        reg.gauge("serve_decision_latency_p99_ms").set(10.0)
        wd.sample_baseline()
        wd.arm()
        eng.post_warmup_recompiles = 1
        tick = wd.observe()
        assert tick["rollback"] and any("recompile" in r
                                        for r in tick["reasons"])

    def test_new_shedding_votes_breach(self):
        wd, reg, _, _ = self.make_watchdog()
        reg.gauge("serve_decision_latency_p99_ms").set(10.0)
        shed = reg.counter("serve_shed_total")
        shed.inc(5)                       # pre-swap shed is not counted
        wd.sample_baseline()
        wd.arm()
        assert wd.observe()["streak"] == 0
        shed.inc()
        assert wd.observe()["streak"] == 1
        shed.inc()
        assert wd.observe()["rollback"]

    def test_validates_and_orders(self):
        reg = Registry()
        with pytest.raises(ValueError, match="p99_factor"):
            SLOWatchdog(reg, p99_factor=1.0)
        with pytest.raises(ValueError, match="breach_after"):
            SLOWatchdog(reg, breach_after=0)
        wd = SLOWatchdog(reg)
        with pytest.raises(RuntimeError, match="arm"):
            wd.observe()


class TestPromotionLedger:
    def test_roundtrip_and_tail_semantics(self, tmp_path):
        d = str(tmp_path)
        led = PromotionLedger(d, durable=False)
        for i, ev in enumerate(("canary", "promote", "rollback")):
            led.append({"event": ev, "step": i})
        sealed, tail = read_ledger(d)
        assert [e["event"] for e in sealed] == ["canary", "promote",
                                                "rollback"]
        assert tail == []
        # an append that died before the sidecar rewrite: parseable but
        # outside the integrity contract -> surfaced as the tail
        with open(led.path, "a") as f:
            f.write(json.dumps({"event": "late"}) + "\n")
        sealed, tail = read_ledger(d)
        assert len(sealed) == 3 and [e["event"] for e in tail] == ["late"]
        # a TORN final line parses to nothing but is not fatal
        with open(led.path, "a") as f:
            f.write('{"event": "to')
        sealed, tail = read_ledger(d)
        assert len(sealed) == 3 and len(tail) == 1

    def test_corrupt_sealed_prefix_raises(self, tmp_path):
        d = str(tmp_path)
        led = PromotionLedger(d)
        led.append({"event": "promote"})
        blob = bytearray(open(led.path, "rb").read())
        blob[2] ^= 0xFF
        open(led.path, "wb").write(bytes(blob))
        with pytest.raises(LedgerCorruptError):
            read_ledger(d)

    def test_missing_ledger_is_empty(self, tmp_path):
        assert read_ledger(str(tmp_path / "nope")) == ([], [])


class TestContinualIngest:
    def write_served_log(self, exp, directory, n=64, capacity=16,
                         lp_shift=0.0, policy_step=None):
        """A flight log of real served-style rows whose behavior columns
        come from the experiment's own params (rho == 1 exactly unless
        ``lp_shift`` poisons the stored behavior log-probs)."""
        obs1, mask1 = host_requests(exp)
        reps = n // obs1.shape[0]
        obs = np.tile(obs1, (reps, 1))
        mask = np.tile(mask1, (reps, 1))
        act, lp, val = replay_decisions(exp.apply_fn, exp.train_state.params,
                                        obs, mask, None, exp.env_params)
        step = (int(exp.train_state.step) if policy_step is None
                else policy_step)
        with FlightLogWriter(directory, capacity=capacity,
                             policy_step=step) as w:
            w.append_batch(obs, mask, act, np.asarray(lp) + lp_shift,
                           val, np.zeros(n, np.int32),
                           np.ones(n, np.int8))
        return n

    def test_on_policy_log_ingests_and_trains(self, exp_cont, tmp_path):
        d = str(tmp_path / "flog")
        self.write_served_log(exp_cont, d, n=64, capacity=16)
        reg = Registry()
        step0 = int(exp_cont.train_state.step)
        summary = run_continual(exp_cont, d, iterations=2, registry=reg)
        assert summary["mode"] == "continual"
        assert summary["shards_seen"] == summary["shards_accepted"] == 4
        assert summary["shards_refused"] == 0
        assert not summary["torn_tail"]
        assert summary["rows_logged"] == summary["rows_accepted"] == 64
        # folded [T, E] geometry: 64 rows over 2 lanes, tiling the
        # minibatch count
        assert summary["pseudo_steps"] == 32
        assert summary["rows_trained"] == 64
        # behavior == target params at ingest time -> rho is exactly 1
        for shard in summary["per_shard"]:
            assert shard["accepted"] and shard["staleness"] == 0
            assert shard["rho_mean"] == pytest.approx(1.0, abs=1e-4)
        # two optimizer updates per iteration (1 epoch x 2 minibatches)
        assert summary["final_step"] == step0 + 4
        assert 0.5 < summary["rho_mean_trained"] < 2.0
        rendered = reg.render()
        assert "flywheel_shards_ingested_total 4" in rendered
        assert "flywheel_shards_refused_total 0" in rendered
        assert "flywheel_shard_staleness 0" in rendered

    def test_off_policy_shards_refused_by_trust_region(self, exp_cont,
                                                       tmp_path):
        """Stored behavior log-probs 4 nats above the target's put rho
        ~ e^-4, far outside [1/trust, trust]: every shard is refused
        and the run fails loudly instead of training on noise."""
        d = str(tmp_path / "poisoned")
        self.write_served_log(exp_cont, d, n=32, capacity=16, lp_shift=4.0)
        reg = Registry()
        with pytest.raises(FlightLogError, match="trust region"):
            run_continual(exp_cont, d, registry=reg)
        assert "flywheel_shards_refused_total 2" in reg.render()

    def test_mixed_log_trains_on_admitted_shards_only(self, exp_cont,
                                                      tmp_path):
        d = str(tmp_path / "mixed")
        obs1, mask1 = host_requests(exp_cont)
        obs = np.tile(obs1, (16, 1))
        mask = np.tile(mask1, (16, 1))
        act, lp, val = replay_decisions(
            exp_cont.apply_fn, exp_cont.train_state.params, obs, mask,
            None, exp_cont.env_params)
        with FlightLogWriter(d, capacity=32,
                             policy_step=int(exp_cont.train_state.step)) as w:
            w.append_batch(obs, mask, act, lp, val,
                           np.zeros(32, np.int32), np.ones(32, np.int8))
            w.append_batch(obs, mask, act, np.asarray(lp) + 4.0, val,
                           np.zeros(32, np.int32), np.ones(32, np.int8))
        summary = run_continual(exp_cont, d, iterations=1)
        assert summary["shards_seen"] == 2
        assert summary["shards_accepted"] == 1
        assert summary["shards_refused"] == 1
        assert summary["rows_accepted"] == summary["rows_trained"] == 32
        accepted = [s for s in summary["per_shard"] if s["accepted"]]
        assert [s["seq"] for s in accepted] == [0]

    def test_empty_log_refuses(self, exp_cont, tmp_path):
        with pytest.raises(FlightLogError, match="no verified shards"):
            run_continual(exp_cont, str(tmp_path))

    def test_trust_knob_validates(self, exp_cont, tmp_path):
        self.write_served_log(exp_cont, str(tmp_path / "f"), n=8,
                              capacity=8)
        with pytest.raises(ValueError, match="trust"):
            run_continual(exp_cont, str(tmp_path / "f"), trust=0.5)


class TestContinualGateParity:
    """The stored behavior log-prob comes out of the engine's GATED
    decision program; the continual path must measure ρ against the
    same gated distribution (the canary's replay already does)."""

    @pytest.fixture(scope="class")
    def exp_pre(self):
        return Experiment.build(small_cfg(name="fly-gate", preempt_len=2))

    def test_rho_is_one_only_under_the_replayed_stall_gate(self, exp_pre):
        from rlgpuschedule_tpu.decision import (preempt_slice,
                                                stall_threshold)
        exp = exp_pre
        pre = preempt_slice(exp.env_params)
        assert pre is not None
        thresh = stall_threshold(exp.env_params)
        obs, mask = host_requests(exp)
        mask = np.ones_like(mask)              # preempt actions live
        stall = np.full(mask.shape[0], thresh, np.int32)  # gate fires
        act, blp, val = replay_decisions(
            exp.apply_fn, exp.train_state.params, obs, mask, stall,
            exp.env_params)
        shard = FlightShard(
            seq=0, path="<mem>", rows=int(obs.shape[0]),
            policy_step=int(exp.train_state.step),
            obs_leaves=[obs], mask_leaves=[mask],
            act_leaves=[np.asarray(l) for l in jax.tree.leaves(act)],
            log_prob=np.asarray(blp), value=np.asarray(val),
            stall=stall, outcome=np.zeros(obs.shape[0], np.int8))
        ex_act = jax.tree.map(lambda l: np.asarray(l)[:1], act)
        gated_mean, gated_max = shard_rho_stats(
            exp.apply_fn, exp.train_state.params, shard, obs[:1],
            mask[:1], ex_act, env_params=exp.env_params)
        # zero staleness + the replayed gate => exactly on-policy
        np.testing.assert_allclose([gated_mean, gated_max], 1.0,
                                   rtol=1e-4)
        raw_mean, _ = shard_rho_stats(
            exp.apply_fn, exp.train_state.params, shard, obs[:1],
            mask[:1], ex_act)
        # the PRE-gate mask renormalizes over actions the engine never
        # had => ratios are wrong even at zero staleness
        assert abs(raw_mean - 1.0) > 1e-3

    def test_gate_logged_mask_matches_engine_gate(self, exp_pre):
        from rlgpuschedule_tpu.decision import (gate_stalled,
                                                preempt_slice,
                                                stall_threshold)
        exp = exp_pre
        pre = preempt_slice(exp.env_params)
        thresh = stall_threshold(exp.env_params)
        _, mask = host_requests(exp)
        mask = np.ones_like(mask)
        stall = np.asarray([thresh, 0], np.int32)[:mask.shape[0]]
        got = gate_logged_mask(mask, stall, exp.env_params)
        want = np.asarray(jax.device_get(
            gate_stalled(mask, stall, thresh, pre)))
        np.testing.assert_array_equal(got, want)
        assert not got[0].all()                # stalled row was gated
        # no env_params / no preempt actions: explicit no-op
        np.testing.assert_array_equal(
            gate_logged_mask(mask, stall, None), mask)


class TestReplayProgramCache:
    def test_weakly_keyed_no_pin_after_apply_fn_dies(self):
        """Regression: the jitted-replay cache must not pin apply_fn
        (and its executable) forever — one entry per Experiment build
        in a long-lived process was unbounded growth."""
        import gc
        import weakref
        from rlgpuschedule_tpu.flywheel.canary import (_REPLAY_PROGRAMS,
                                                       _replay_program)

        def apply_fn(p, o, m):
            return o, o
        prog = _replay_program(apply_fn, 3, True)
        assert _replay_program(apply_fn, 3, True) is prog   # cache hit
        assert _replay_program(apply_fn, 3, False) is not prog
        assert apply_fn in _REPLAY_PROGRAMS
        ref = weakref.ref(apply_fn)
        del apply_fn, prog
        gc.collect()
        assert ref() is None                    # entry did not pin it


class TestDurableEventBus:
    def test_durable_mode_survives_torn_final_write(self, tmp_path):
        bus = EventBus(str(tmp_path), durable=True)
        bus.emit("promote_apply", step=1)
        bus.emit("promote_rollback", step=2)
        bus.close()
        # a killed writer's one reachable bad state: a torn last line
        with open(bus.path, "a") as f:
            f.write('{"kind": "promote_app')
        events = read_events(bus.path)
        assert [e["kind"] for e in events] == ["promote_apply",
                                               "promote_rollback"]
        assert [e["seq"] for e in events] == [0, 1]

    def test_emit_refuses_reserved_stamp_fields(self, tmp_path):
        """The contract the flight log's seal event once tripped over:
        payload keys must not shadow the bus's own stamp fields."""
        bus = EventBus(str(tmp_path))
        try:
            with pytest.raises(ValueError, match="seq"):
                bus.emit("flywheel_shard_seal", seq=0)
            with pytest.raises(ValueError, match="wall"):
                bus.emit("x", wall=1.0)
        finally:
            bus.close()


class TestDiurnalFit:
    def test_philly_hourly_curve_shape(self):
        assert len(PHILLY_HOURLY) == 24
        assert sum(PHILLY_HOURLY) == pytest.approx(24.0, abs=1e-9)
        # working-hours peak, small-hours trough — piecewise, not a
        # symmetric sinusoid
        assert max(PHILLY_HOURLY) == max(PHILLY_HOURLY[9:18])
        assert min(PHILLY_HOURLY) == min(PHILLY_HOURLY[0:7])

    def test_fit_round_trips_the_generating_curve(self):
        rng = np.random.default_rng(0)
        submit = _diurnal_arrivals(0.02, 5000, rng, hourly=PHILLY_HOURLY)
        curve = fit_hourly_curve(submit)
        assert len(curve) == 24
        assert sum(curve) == pytest.approx(24.0, abs=1e-6)
        err = np.abs(np.asarray(curve) - np.asarray(PHILLY_HOURLY))
        assert err.max() < 0.2

    def test_fit_is_deterministic(self):
        a = _diurnal_arrivals(0.02, 2000, np.random.default_rng(7),
                              hourly=PHILLY_HOURLY)
        b = _diurnal_arrivals(0.02, 2000, np.random.default_rng(7),
                              hourly=PHILLY_HOURLY)
        np.testing.assert_array_equal(a, b)
        assert fit_hourly_curve(a) == fit_hourly_curve(b)

    def test_fit_jobs_carries_the_hourly_curve(self):
        jobs = gen_philly_proxy_jobs(3000, seed=3, n_gpus=256)
        fit = fit_jobs(jobs, "roundtrip")
        assert len(fit.hourly) == 24
        assert sum(fit.hourly) == pytest.approx(24.0, abs=1e-6)
        assert max(fit.hourly) > 1.2 * min(fit.hourly)

    def test_fit_hourly_validates(self):
        with pytest.raises(ValueError, match="zero arrivals"):
            fit_hourly_curve([])
        with pytest.raises(ValueError, match="finite"):
            fit_hourly_curve([0.0, np.inf])

    def test_tracefit_rejects_bad_hourly(self):
        with pytest.raises(ValueError, match="24 bins"):
            TraceFit("x", 100.0, 1.0, (1,), (1.0,), hourly=(1.0, 2.0))
