"""A2C trainer (L4): synchronous advantage actor-critic.

Capability parity: SURVEY.md §2 "A2C trainer" / config 3 — the same fused
rollout and GAE machinery as PPO, but a single full-batch policy-gradient
update per iteration (no ratio clipping, no minibatch epochs). Multi-actor
parallelism is an env-batch/mesh axis, not processes: more vmapped envs per
chip × data-parallel chips with pmean gradient sync (SURVEY.md §2
"Multi-actor runner" rebuild form).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from flax.training.train_state import TrainState

from ..env.env import EnvParams
from ..ops.gae import compute_gae
from . import action_dist
from .rollout import PolicyApply, RolloutCarry, Transition, rollout


@dataclasses.dataclass(frozen=True)
class A2CConfig:
    n_steps: int = 16           # shorter rollouts, more frequent updates
    gamma: float = 0.995
    gae_lambda: float = 1.0     # plain n-step advantage by default
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    lr: float = 7e-4
    max_grad_norm: float = 0.5


class A2CMetrics(NamedTuple):
    total_loss: jax.Array
    pg_loss: jax.Array
    v_loss: jax.Array
    entropy: jax.Array
    mean_reward: jax.Array
    mean_value: jax.Array


def make_optimizer(config: A2CConfig) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(config.max_grad_norm),
                       optax.rmsprop(config.lr, decay=0.99, eps=1e-5))


def a2c_loss(apply_fn: PolicyApply, net_params, batch: Transition,
             advantages: jax.Array, returns: jax.Array, config: A2CConfig):
    logits, value = apply_fn(net_params, batch.obs, batch.mask)
    log_prob = action_dist.log_prob(logits, batch.action)
    pg_loss = -jnp.mean(log_prob * advantages)
    v_loss = 0.5 * jnp.mean((value - returns) ** 2)
    entropy = jnp.mean(action_dist.entropy(logits))
    total = pg_loss + config.vf_coef * v_loss - config.ent_coef * entropy
    return total, (pg_loss, v_loss, entropy)


def make_train_step(apply_fn: PolicyApply, env_params: EnvParams,
                    config: A2CConfig, axis_name: str | None = None):
    """(train_state, carry, traces, key) -> (train_state', carry', metrics).
    Action sampling draws from carry.key (advanced inside the rollout);
    ``key`` is accepted for signature uniformity with PPO's train_step."""

    def train_step(train_state: TrainState, carry: RolloutCarry, traces,
                   key: jax.Array):
        del key
        carry, tr, last_value = rollout(apply_fn, train_state.params,
                                        env_params, traces, carry,
                                        config.n_steps)
        advantages, returns = compute_gae(tr.reward, tr.value, tr.done,
                                          last_value, config.gamma,
                                          config.gae_lambda)
        B = config.n_steps * tr.reward.shape[1]
        flat = jax.tree.map(lambda x: x.reshape(B, *x.shape[2:]), tr)
        (loss, (pg, vl, ent)), grads = jax.value_and_grad(
            a2c_loss, argnums=1, has_aux=True)(
            apply_fn, train_state.params, flat, advantages.reshape(B),
            returns.reshape(B), config)
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
        train_state = train_state.apply_gradients(grads=grads)
        metrics = A2CMetrics(total_loss=loss, pg_loss=pg, v_loss=vl,
                             entropy=ent, mean_reward=jnp.mean(tr.reward),
                             mean_value=jnp.mean(tr.value))
        return train_state, carry, metrics

    return train_step
