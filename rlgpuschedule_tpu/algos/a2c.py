"""A2C trainer (L4): synchronous advantage actor-critic.

Capability parity: SURVEY.md §2 "A2C trainer" / config 3 — the same fused
rollout and GAE machinery as PPO, and now the same fused minibatch-update
engine (:mod:`algos.update`): the classic single full-batch
policy-gradient update is the engine's degenerate ``1 × 1`` geometry (the
default, bit-identical to the hand-written full-batch update it
replaces), and minibatched/multi-epoch A2C variants are a config change
rather than a different code path. Multi-actor parallelism is an
env-batch/mesh axis, not processes: more vmapped envs per chip ×
data-parallel chips with pmean gradient sync (SURVEY.md §2 "Multi-actor
runner" rebuild form).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from flax.training.train_state import TrainState

from ..env.env import EnvParams
from ..ops.gae import compute_gae
from . import action_dist
from . import ppo as ppo_norm  # shared RewardNormState/Welford helpers
from . import update as update_engine
from .rollout import PolicyApply, RolloutCarry, Transition, rollout


@dataclasses.dataclass(frozen=True)
class A2CConfig:
    n_steps: int = 16           # shorter rollouts, more frequent updates
    # update geometry (same contract as PPOConfig; validated by
    # algos.update.resolve_geometry). The 1 × 1 default IS classic A2C —
    # one full-batch update per iteration, bit-identical to the legacy
    # hand-written path; other geometries run the shared fused engine.
    n_epochs: int = 1
    n_minibatches: int = 1
    minibatch_size: int | None = None
    bf16_update: bool = False   # same contract as PPOConfig.bf16_update
    # fused advantage-pipeline passthrough (same contracts as PPOConfig;
    # A2C has NO correction field — V-trace's clipped-ratio targets are
    # a surrogate-objective correction, and the async engine refuses
    # a2c×vtrace loudly):
    reward_norm: bool = False
    bf16_advantages: bool = False
    gamma: float = 0.995
    gae_lambda: float = 1.0     # plain n-step advantage by default
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    lr: float = 7e-4
    max_grad_norm: float = 0.5


class A2CMetrics(NamedTuple):
    total_loss: jax.Array
    pg_loss: jax.Array
    v_loss: jax.Array
    entropy: jax.Array
    mean_reward: jax.Array
    mean_value: jax.Array


def make_optimizer(config: A2CConfig) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(config.max_grad_norm),
                       optax.rmsprop(config.lr, decay=0.99, eps=1e-5))


def a2c_loss(apply_fn: PolicyApply, net_params, batch: Transition,
             advantages: jax.Array, returns: jax.Array, config: A2CConfig):
    logits, value = apply_fn(net_params, batch.obs, batch.mask)
    log_prob = action_dist.log_prob(logits, batch.action)
    pg_loss = -jnp.mean(log_prob * advantages)
    v_loss = 0.5 * jnp.mean((value - returns) ** 2)
    entropy = jnp.mean(action_dist.entropy(logits))
    total = pg_loss + config.vf_coef * v_loss - config.ent_coef * entropy
    return total, (pg_loss, v_loss, entropy)


def make_a2c_grad_step(apply_fn: PolicyApply, config: A2CConfig,
                       apply_grads):
    """One policy-gradient minibatch update for the fused engine:
    ``(state, (mb, adv, ret)) -> (state, (loss, pg, vl, ent))``. Same
    bf16-compute contract as :func:`ppo.make_ppo_grad_step`."""

    def grad_step(state: TrainState, mb_data):
        mb, adv, ret = mb_data
        if config.bf16_update:
            c = lambda t: update_engine.cast_floating(t, jnp.bfloat16)
            (loss, aux), grads = jax.value_and_grad(
                a2c_loss, argnums=1, has_aux=True)(
                apply_fn, c(state.params), c(mb), c(adv), c(ret), config)
            grads = jax.tree.map(lambda g, p: g.astype(p.dtype),
                                 grads, state.params)
            loss, aux = jax.tree.map(
                lambda x: x.astype(jnp.float32), (loss, aux))
        else:
            (loss, aux), grads = jax.value_and_grad(
                a2c_loss, argnums=1, has_aux=True)(
                apply_fn, state.params, mb, adv, ret, config)
        state = apply_grads(state, grads)
        return state, (loss, *aux)

    return grad_step


def run_a2c_update(apply_fn: PolicyApply, config: A2CConfig,
                   state: TrainState, tr: Transition,
                   advantages: jax.Array, returns: jax.Array,
                   key: jax.Array, apply_grads):
    """A2C's update through the fused minibatch-geometry engine: flatten
    [T, E] → [B] and run the config geometry (default 1 × 1 = classic
    full-batch A2C, bit-identical to the legacy direct update). Returns
    (state, metrics)."""
    B = config.n_steps * tr.reward.shape[1]
    flat = jax.tree.map(lambda x: x.reshape(B, *x.shape[2:]), tr)
    grad_step = make_a2c_grad_step(apply_fn, config, apply_grads)
    state, stats = update_engine.run_minibatch_epochs(
        grad_step, state, (flat, advantages.reshape(B), returns.reshape(B)),
        key, n_epochs=config.n_epochs, n_minibatches=config.n_minibatches,
        minibatch_size=config.minibatch_size)
    metrics = A2CMetrics(
        total_loss=jnp.mean(stats[0]), pg_loss=jnp.mean(stats[1]),
        v_loss=jnp.mean(stats[2]), entropy=jnp.mean(stats[3]),
        mean_reward=jnp.mean(tr.reward), mean_value=jnp.mean(tr.value))
    return state, metrics


def make_learn_step(apply_fn: PolicyApply, config: A2CConfig,
                    axis_name: str | None = None):
    """Build the learn half of the A2C iteration:
    (train_state, tr, last_value, key) -> (train_state', metrics).
    Same factoring contract as :func:`ppo.make_learn_step` — the fused
    train step and the async learner loop compose/jit this identical
    code (no advantage normalization in A2C, matching the legacy path)."""

    def apply_grads(state: TrainState, grads):
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
        return state.apply_gradients(grads=grads)

    def learn_step(train_state: TrainState, tr: Transition,
                   last_value: jax.Array, key: jax.Array):
        rewards = tr.reward
        if config.reward_norm:
            stats = ppo_norm.update_reward_stats(
                train_state.reward_stats, rewards, axis_name)
            rewards = rewards * ppo_norm.reward_scale(stats)
            train_state = train_state.replace(reward_stats=stats)
        advantages, returns = compute_gae(rewards, tr.value, tr.done,
                                          last_value, config.gamma,
                                          config.gae_lambda)
        if config.bf16_advantages:
            advantages = advantages.astype(jnp.bfloat16)
            returns = returns.astype(jnp.bfloat16)
        return run_a2c_update(apply_fn, config, train_state, tr,
                              advantages, returns, key, apply_grads)

    return learn_step


def make_train_step(apply_fn: PolicyApply, env_params: EnvParams,
                    config: A2CConfig, axis_name: str | None = None):
    """(train_state, carry, traces, key) -> (train_state', carry', metrics).
    Action sampling draws from carry.key (advanced inside the rollout);
    ``key`` feeds the update engine's per-epoch minibatch shuffles and is
    untouched at the default 1 × 1 geometry (which consumes no
    randomness), preserving the legacy signature contract."""
    learn_step = make_learn_step(apply_fn, config, axis_name)

    def train_step(train_state: TrainState, carry: RolloutCarry, traces,
                   key: jax.Array, faults=None):
        carry, tr, last_value = rollout(apply_fn, train_state.params,
                                        env_params, traces, carry,
                                        config.n_steps, faults)
        train_state, metrics = learn_step(train_state, tr, last_value, key)
        return train_state, carry, metrics

    return train_step
