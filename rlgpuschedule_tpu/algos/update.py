"""Fused minibatch-update engine (L4): ONE geometry-configurable
epoch × minibatch ``lax.scan`` shared by PPO, A2C, and the PBT member step.

Motivation (BASELINE.md "Where the time goes"): the minibatch update is
76.7% of the fused train step and its small matmuls underfill the MXU, so
minibatch geometry — ``n_epochs × n_minibatches × minibatch_size`` — is
the first throughput lever. This module makes that geometry an explicit,
validated, sweepable property instead of a hard-coded split:

- :func:`resolve_geometry` validates the triple against the rollout batch
  (``minibatch_size``, when set, *determines* the minibatch count —
  "fewer, larger minibatches" is one number away).
- :func:`run_minibatch_epochs` is the engine: an epoch scan carrying
  ``(state, key)`` whose body gathers ONE whole-batch permutation and
  scans a ``grad_step`` over contiguous minibatch blocks. At the trivial
  ``1 × 1`` geometry it calls ``grad_step`` on the whole batch directly
  (no permutation, no scan machinery) so A2C's classic full-batch update
  is the same engine at the degenerate geometry, bit-identically. At
  ``n_minibatches == 1`` the permutation gather is skipped entirely (a
  full-batch epoch sees every sample regardless of order), which is
  exactly the swept fewer-larger-minibatch fast path.
- :func:`cast_floating` backs the optional bf16-compute path: loss +
  grads evaluated in bfloat16, gradients cast back to the parameter
  dtype so the optimizer state (Adam moments) stays fp32. Behind a flag
  (``bf16_update``) because it is NOT bit-identical to fp32 compute.

Buffer discipline: inside the fused train step the engine is one jitted
region — XLA's scan carries the optimizer state in place and the rollout
batch is consumed without copies. For a *standalone* update dispatch
(stage profiling, the minibatch sweep), :func:`make_update_step` jits the
engine with the state donated, so repeated calls reuse the
parameter/optimizer buffers instead of allocating fresh ones per call.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

# grad_step(state, minibatch_data) -> (state, stats): one optimizer
# update on one minibatch. ``stats`` is any pytree of scalars; the engine
# stacks it to [n_epochs, n_minibatches, ...].
GradStep = Callable[[Any, Any], tuple[Any, Any]]


def resolve_geometry(n_epochs: int, n_minibatches: int,
                     minibatch_size: int | None,
                     batch_size: int) -> tuple[int, int, int]:
    """Validate the update geometry against the flattened rollout batch.

    Returns the resolved ``(n_epochs, n_minibatches, minibatch_size)``
    triple. ``minibatch_size``, when set, takes precedence: it determines
    the minibatch count (``batch_size // minibatch_size``) and the
    configured ``n_minibatches`` is required to either agree or be left
    at any value (it is ignored) — so "fewer, larger minibatches" needs
    only one number. Everything must tile the batch exactly: a silently
    dropped remainder would train on less data than configured."""
    if n_epochs < 1:
        raise ValueError(f"n_epochs must be >= 1, got {n_epochs}")
    if minibatch_size is not None:
        if minibatch_size < 1:
            raise ValueError(
                f"minibatch_size must be >= 1, got {minibatch_size}")
        if batch_size % minibatch_size:
            raise ValueError(
                f"minibatch_size={minibatch_size} must divide the rollout "
                f"batch (n_steps * n_envs = {batch_size}); a remainder "
                f"minibatch would change shapes mid-scan")
        n_minibatches = batch_size // minibatch_size
    else:
        if n_minibatches < 1:
            raise ValueError(
                f"n_minibatches must be >= 1, got {n_minibatches}")
        if batch_size % n_minibatches:
            raise ValueError(
                f"n_steps * n_envs = {batch_size} must be divisible by "
                f"n_minibatches={n_minibatches}")
        minibatch_size = batch_size // n_minibatches
    return n_epochs, n_minibatches, minibatch_size


def validate_update_geometry(n_epochs: int, n_minibatches: int,
                             minibatch_size: int | None, *, n_steps: int,
                             n_envs: int, n_devices: int = 1
                             ) -> tuple[int, int, int]:
    """Validate the update phase's geometry on its own terms — the
    counterpart of ``algos.rollout.validate_rollout_geometry`` for the
    async split, where the update runs on a learner device group that
    need not match the actor group. Checks that the trajectory batch
    tiles the learner group (the [T, E] env axis is what's sharded) and
    resolves the minibatch triple against the flattened T·E batch.
    Returns the resolved ``(n_epochs, n_minibatches, minibatch_size)``."""
    if n_devices > 1 and n_envs % n_devices:
        raise ValueError(
            f"n_envs={n_envs} must be divisible by the update device "
            f"group size ({n_devices}) to shard the trajectory batch")
    return resolve_geometry(n_epochs, n_minibatches, minibatch_size,
                            n_steps * n_envs)


def cast_floating(tree: Any, dtype) -> Any:
    """Cast every floating leaf of ``tree`` to ``dtype`` (bool/int leaves
    — action ids, masks, done flags — pass through untouched)."""
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x, tree)


def _batch_size(data: Any) -> int:
    leaves = jax.tree.leaves(data)
    if not leaves:
        raise ValueError("update engine got an empty data pytree")
    return leaves[0].shape[0]


def run_minibatch_epochs(grad_step: GradStep, state: Any, data: Any,
                         key: jax.Array, *, n_epochs: int = 1,
                         n_minibatches: int = 1,
                         minibatch_size: int | None = None
                         ) -> tuple[Any, Any]:
    """The fused update engine: run ``grad_step`` over ``n_epochs``
    shuffled passes of ``data`` split into ``n_minibatches`` contiguous
    blocks. ``data`` is any pytree of ``[B, ...]`` arrays (B = flattened
    rollout batch). Returns ``(state, stats)`` with stats stacked
    ``[n_epochs, n_minibatches, ...]``.

    Numerics contract (pinned by tests/test_algos.py): at any geometry
    this is bit-identical to the legacy per-minibatch Python loop with
    the same key — one ``jax.random.split`` per epoch, one whole-batch
    ``jax.random.permutation`` gather per epoch, minibatches read as
    contiguous blocks of the shuffled batch. At the degenerate ``1 × 1``
    geometry the batch is passed to ``grad_step`` whole, unpermuted —
    bit-identical to a classic single full-batch update (A2C's default).
    """
    B = _batch_size(data)
    n_epochs, n_mb, _mb = resolve_geometry(n_epochs, n_minibatches,
                                           minibatch_size, B)
    if n_epochs == 1 and n_mb == 1:
        # degenerate geometry: one full-batch update, no permutation, no
        # scan machinery, no key consumed (A2C's classic update)
        state, stats = grad_step(state, data)
        return state, jax.tree.map(lambda s: jnp.asarray(s)[None, None],
                                   stats)

    def epoch(state_and_key, _):
        state, key = state_and_key
        key, sub = jax.random.split(key)
        if n_mb > 1:
            perm = jax.random.permutation(sub, B)
            # ONE whole-batch gather per epoch, then scan over contiguous
            # [n_mb, mb, ...] blocks — identical minibatch contents to
            # gathering x[perm[i]] inside the scan body (same perm, same
            # row order), but the inner loop reads each minibatch as a
            # contiguous dynamic-slice instead of issuing a fresh
            # row-gather per minibatch (the update scan is the measured
            # hot stage — BASELINE.md "where the time goes").
            blocks = jax.tree.map(
                lambda x: x[perm].reshape(n_mb, _mb, *x.shape[1:]), data)
        else:
            # full-batch epochs: a permutation would only reorder a mean —
            # skip the gather (the swept fewer-larger-minibatch fast path)
            blocks = jax.tree.map(lambda x: x[None], data)
        state, stats = jax.lax.scan(grad_step, state, blocks)
        return (state, key), stats

    (state, _), stats = jax.lax.scan(epoch, (state, key), None,
                                     length=n_epochs)
    return state, stats


def make_update_step(run_update: Callable, donate: bool = True) -> Callable:
    """Jit a standalone update dispatch ``run_update(state, *batch_args)
    -> (state, metrics)`` with the state donated (parameter + optimizer
    buffers reused across calls instead of re-allocated — the
    "allocation-free across epochs" contract at the dispatch boundary;
    inside the fused train step the same engine is one scan and needs no
    donation). Callers must thread the returned state back in and treat
    the donated input as dead."""
    return jax.jit(run_update, donate_argnums=(0,) if donate else ())
