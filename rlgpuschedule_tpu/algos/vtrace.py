"""V-trace off-policy correction as a reverse lax.scan (L4 op).

IMPALA-style importance-weighted value targets (Espeholt et al., the
Sebulba/Podracer lineage — PAPERS.md) in the λ-generalized form, so the
async trajectory queue (:mod:`~rlgpuschedule_tpu.async_engine`) can run
deep staleness bounds without the bias PPO's clip alone cannot remove.

Shape contract mirrors :func:`ops.gae.compute_gae` exactly — [T, ...]
time-major inputs, one reverse scan, returns ``(advantages, returns)``.
The advantage handed to the surrogate loss is ``vs_t − V_t`` (the
λ-discounted importance-weighted TD accumulation), NOT the canonical
IMPALA policy-gradient advantage ``ρ_t (r_t + γ vs_{t+1} − V_t)`` —
the accumulated form is what reduces to GAE when the data is on-policy.

**On-policy bit-identity contract:** with ``rho ≡ 1`` (behavior params
== target params, so the recomputed log-probs are bitwise equal and
``exp(0) == 1.0`` exactly), every extra multiply below is by the IEEE
identity 1.0 and the scan body collapses bitwise to the GAE body:
``delta = 1.0 * (r + γ·v̂·nt − v)`` and the left-to-right product
``((γλ)·nt)·1.0·acc ≡ ((γλ)·nt)·acc``. ``staleness_bound=0`` async runs
with ``correction="vtrace"`` therefore reproduce the sync GAE path bit
for bit (tests/test_vtrace.py pins this end to end).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def importance_ratios(behavior_log_prob: jax.Array,
                      target_log_prob: jax.Array) -> jax.Array:
    """π_target(a|s) / π_behavior(a|s) from joint action log-probs.

    On-policy (bitwise-equal log-probs) the difference is exactly 0.0
    and the ratio exactly 1.0 — the premise of the bit-identity
    contract above."""
    return jnp.exp(target_log_prob - behavior_log_prob)


def compute_vtrace(rewards: jax.Array, values: jax.Array,
                   dones: jax.Array, last_value: jax.Array,
                   rho: jax.Array, gamma: float, lam: float,
                   rho_bar: float = 1.0, c_bar: float = 1.0,
                   ) -> tuple[jax.Array, jax.Array]:
    """Returns (advantages, returns), each [T, ...].

    Args:
      rewards: [T, ...] reward at each step.
      values:  [T, ...] value estimate of the state the action was taken in.
      dones:   [T, ...] episode ended AT this step (auto-reset envs: the
               next state belongs to a fresh episode — no bootstrap across).
      last_value: [...] value of the state after the final step.
      rho: [T, ...] unclipped importance ratios π_target/π_behavior for
           the taken actions (:func:`importance_ratios`).
      rho_bar: clip on the TD-error weight ρ_t = min(ρ̄, ratio) — bounds
               the fixed point the targets converge to.
      c_bar:   clip on the trace coefficient c_t = λ·min(c̄, ratio) —
               bounds how far corrections propagate backwards (variance).
    """
    rho_clipped = jnp.minimum(rho, rho_bar)
    c_clipped = jnp.minimum(rho, c_bar)

    def step(next_acc_and_v, x):
        next_acc, next_v = next_acc_and_v
        r, v, d, rh, c = x
        nonterm = 1.0 - d
        delta = rh * (r + gamma * next_v * nonterm - v)
        acc = delta + gamma * lam * nonterm * c * next_acc
        return (acc, v), acc

    (_, _), advantages = jax.lax.scan(
        step, (jnp.zeros_like(last_value), last_value),
        (rewards, values, dones.astype(rewards.dtype),
         rho_clipped, c_clipped), reverse=True)
    return advantages, advantages + values
