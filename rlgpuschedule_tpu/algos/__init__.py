"""L4 RL algorithms: fused rollouts, GAE, the shared minibatch-geometry
update engine, PPO, A2C."""
from .rollout import (Transition, RolloutCarry, PolicyApply, rollout,
                      init_carry, make_rollout_step,
                      validate_rollout_geometry)
from .update import (resolve_geometry, validate_update_geometry,
                     run_minibatch_epochs, make_update_step, cast_floating)
from .ppo import (PPOConfig, PPOMetrics, make_train_step as make_ppo_step,
                  make_learn_step as make_ppo_learn_step,
                  make_train_state, ppo_loss, masked_entropy,
                  compute_advantages, NormTrainState, RewardNormState)
from .a2c import (A2CConfig, A2CMetrics, make_train_step as make_a2c_step,
                  make_learn_step as make_a2c_learn_step)
from .vtrace import compute_vtrace, importance_ratios
from . import action_dist

__all__ = [
    "Transition", "RolloutCarry", "PolicyApply", "rollout", "init_carry",
    "make_rollout_step", "validate_rollout_geometry",
    "resolve_geometry", "validate_update_geometry", "run_minibatch_epochs",
    "make_update_step", "cast_floating",
    "PPOConfig", "PPOMetrics", "make_ppo_step", "make_ppo_learn_step",
    "make_train_state", "ppo_loss", "masked_entropy",
    "compute_advantages", "NormTrainState", "RewardNormState",
    "A2CConfig", "A2CMetrics", "make_a2c_step", "make_a2c_learn_step",
    "compute_vtrace", "importance_ratios",
    "action_dist",
]
