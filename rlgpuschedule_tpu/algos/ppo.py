"""PPO trainer (L4): clipped surrogate, minibatch epochs, entropy bonus.

Capability parity: SURVEY.md §2 "PPO trainer" and §3.1 — the reference's
rollout→GAE→minibatch-update iteration, lowered end-to-end to XLA: the
whole train step (fused rollout scan + GAE reverse scan + epoch×minibatch
update scans) is ONE jitted function. Data-parallel gradient sync — the
TPU-native replacement for the reference's NCCL allreduce (SURVEY.md §2
"Distributed comm backend") — has two assemblies in ``parallel.dp``:
``shard_train`` jits the ``axis_name=None`` step with GSPMD shardings
(XLA inserts the psum), and ``shard_map_train`` wraps an
``axis_name=DATA_AXIS`` step in ``shard_map`` so the ``lax.pmean`` calls
below bind to the mesh axis explicitly.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from flax.training.train_state import TrainState

from ..env.env import EnvParams
from ..ops.gae import compute_gae
from . import action_dist
from . import update as update_engine
from . import vtrace as vtrace_ops
from .rollout import PolicyApply, RolloutCarry, Transition, rollout


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    n_steps: int = 128          # rollout length T per iteration
    # update geometry (algos.update.resolve_geometry validates the triple
    # against n_steps * n_envs at build time): minibatch_size, when set,
    # DETERMINES the minibatch count and n_minibatches is ignored — so
    # "fewer, larger minibatches" (the measured MXU-fill lever,
    # BASELINE.md "Where the time goes") is one number away.
    n_epochs: int = 4
    n_minibatches: int = 4
    minibatch_size: int | None = None
    # bf16-compute / fp32-optimizer-state update path (NOT bit-identical
    # to fp32 compute — opt-in): loss + grads evaluated in bfloat16,
    # grads cast back to the param dtype before Adam, so moments stay
    # fp32. The encoders already run bf16 activations; this extends the
    # low precision to the update-path params/grads.
    bf16_update: bool = False
    # off-policy correction for the advantage targets: "none" = GAE on
    # the behavior values (the on-policy path), "vtrace" = IMPALA-style
    # importance-weighted targets (algos.vtrace) against the learner's
    # CURRENT value function — required for deep async staleness bounds,
    # pure overhead when the data is on-policy (ratios ≡ 1 reduces it
    # bit-identically to GAE, so bound-0 async runs stay bitwise equal
    # to sync).
    correction: str = "none"
    rho_bar: float = 1.0       # V-trace TD-error weight clip ρ̄
    c_bar: float = 1.0         # V-trace trace-coefficient clip c̄
    # streaming reward standardization (HEPPO-style): scale rewards by
    # 1/√(running variance) with Welford stats carried in the train
    # state (NormTrainState). Scale-only — no centering, which would
    # change the optimal policy under episodic returns.
    reward_norm: bool = False
    # store normalized advantages/returns in bf16 through the
    # epoch×minibatch engine (HEPPO's compressed-advantage pipeline).
    # NOT bit-identical — opt-in, rides the bf16_update seam.
    bf16_advantages: bool = False
    gamma: float = 0.995
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    lr: float = 3e-4
    max_grad_norm: float = 0.5

    def __post_init__(self):
        if self.correction not in ("none", "vtrace"):
            raise ValueError(
                f"PPOConfig.correction must be 'none' or 'vtrace', "
                f"got {self.correction!r}")


def make_optimizer(config: PPOConfig) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(config.max_grad_norm),
                       optax.adam(config.lr, eps=1e-5))


def masked_entropy(logits: jax.Array) -> jax.Array:
    """Entropy of the masked categorical (−1e9 logits contribute ~0).
    Alias of :func:`action_dist.entropy` kept for the public API."""
    return action_dist.entropy(logits)


class PPOMetrics(NamedTuple):
    total_loss: jax.Array
    pg_loss: jax.Array
    v_loss: jax.Array
    entropy: jax.Array
    approx_kl: jax.Array
    clip_frac: jax.Array
    mean_reward: jax.Array
    mean_value: jax.Array
    # unclipped importance-ratio stats from the advantage pipeline —
    # constant 1.0 on the GAE path, the off-policyness monitor under
    # correction="vtrace" (surfaced as async gauges / run_end fields).
    rho_mean: jax.Array
    rho_max: jax.Array


class RewardNormState(NamedTuple):
    """Welford running moments of the raw reward stream (fp32 scalars),
    carried in :class:`NormTrainState` when ``reward_norm`` is on."""
    count: jax.Array
    mean: jax.Array
    m2: jax.Array


def init_reward_stats() -> RewardNormState:
    # three DISTINCT buffers: aliasing one zeros array across the fields
    # trips XLA's double-donation check once the state is donated
    return RewardNormState(count=jnp.zeros((), jnp.float32),
                           mean=jnp.zeros((), jnp.float32),
                           m2=jnp.zeros((), jnp.float32))


def update_reward_stats(stats: RewardNormState, rewards: jax.Array,
                        axis_name: str | None = None) -> RewardNormState:
    """Streaming (Chan/Welford parallel-combine) update from one rollout
    batch. Batch moments are globally reduced across the mesh axis so DP
    replicas carry identical statistics."""
    r = rewards.astype(jnp.float32)
    batch_count = jnp.asarray(r.size, jnp.float32)
    batch_mean = jnp.mean(r)
    batch_sq = jnp.mean(r * r)
    if axis_name is not None:
        batch_count = jax.lax.psum(batch_count, axis_name)
        batch_mean = jax.lax.pmean(batch_mean, axis_name)
        batch_sq = jax.lax.pmean(batch_sq, axis_name)
    batch_m2 = (batch_sq - batch_mean ** 2) * batch_count
    total = stats.count + batch_count
    delta = batch_mean - stats.mean
    new_mean = stats.mean + delta * batch_count / total
    new_m2 = (stats.m2 + batch_m2
              + delta ** 2 * stats.count * batch_count / total)
    return RewardNormState(count=total, mean=new_mean, m2=new_m2)


def reward_scale(stats: RewardNormState) -> jax.Array:
    """1/√(running variance + ε). Scale-only normalization — rewards are
    NOT centered (subtracting a baseline from per-step rewards changes
    the optimal policy; rescaling does not)."""
    var = stats.m2 / jnp.maximum(stats.count, 1.0)
    return jax.lax.rsqrt(var + 1e-8)


class NormTrainState(TrainState):
    """TrainState + streaming reward moments. Only built when
    ``reward_norm`` is on, so default checkpoints/pytrees are
    unchanged."""
    reward_stats: RewardNormState = None


def ppo_loss(apply_fn: PolicyApply, net_params, batch: Transition,
             advantages: jax.Array, returns: jax.Array, config: PPOConfig,
             clip_eps: jax.Array | float | None = None,
             ent_coef: jax.Array | float | None = None):
    """``clip_eps`` / ``ent_coef`` default to the (static) config values;
    pass traced scalars to make them per-member PBT-explorable
    (``parallel.population``) without recompilation."""
    clip_eps = config.clip_eps if clip_eps is None else clip_eps
    ent_coef = config.ent_coef if ent_coef is None else ent_coef
    logits, value = apply_fn(net_params, batch.obs, batch.mask)
    log_prob = action_dist.log_prob(logits, batch.action)
    ratio = jnp.exp(log_prob - batch.log_prob)
    pg1 = ratio * advantages
    pg2 = jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * advantages
    pg_loss = -jnp.mean(jnp.minimum(pg1, pg2))
    # clipped value loss (PPO2-style trust region on the critic)
    v_clipped = batch.value + jnp.clip(value - batch.value,
                                       -clip_eps, clip_eps)
    v_loss = 0.5 * jnp.mean(jnp.maximum((value - returns) ** 2,
                                        (v_clipped - returns) ** 2))
    entropy = jnp.mean(action_dist.entropy(logits))
    total = pg_loss + config.vf_coef * v_loss - ent_coef * entropy
    approx_kl = jnp.mean(batch.log_prob - log_prob)
    clip_frac = jnp.mean((jnp.abs(ratio - 1.0) > clip_eps)
                         .astype(jnp.float32))
    return total, (pg_loss, v_loss, entropy, approx_kl, clip_frac)


def normalize_advantages(advantages: jax.Array,
                         axis_name: str | None = None) -> jax.Array:
    """Normalize over the full batch (global across the mesh axis so DP
    replicas agree on the statistics). Global variance must be
    E[x²] − (E[x])² over globally-reduced moments — a pmean of per-shard
    variances would drop the between-shard term."""
    adv_mean = jnp.mean(advantages)
    adv_sq = jnp.mean(advantages ** 2)
    if axis_name is not None:
        adv_mean = jax.lax.pmean(adv_mean, axis_name)
        adv_sq = jax.lax.pmean(adv_sq, axis_name)
    adv_var = adv_sq - adv_mean ** 2
    return (advantages - adv_mean) / jnp.sqrt(adv_var + 1e-8)


def compute_advantages(apply_fn: PolicyApply, config: PPOConfig, state,
                       tr: Transition, last_value: jax.Array,
                       axis_name: str | None = None):
    """The fused advantage pipeline (HEPPO-style): streaming reward
    standardization → GAE or V-trace → global normalization → optional
    bf16 storage, all inside the caller's jitted/donated update dispatch
    so none of it runs as a separate fp32 pass.

    Returns ``(state, advantages, returns, rho_stats)`` where
    ``rho_stats`` is ``(mean, max)`` of the *unclipped* importance
    ratios under ``correction="vtrace"`` and ``None`` on the GAE path.
    With the default config this emits exactly the historical
    ``compute_gae`` + ``normalize_advantages`` ops — bit-identical to
    the pre-fusion path. ``state`` is any struct with ``.params``
    (TrainState or the population's MemberState); it is only replaced
    when ``reward_norm`` updates the Welford stats."""
    rewards = tr.reward
    if config.reward_norm:
        stats = update_reward_stats(state.reward_stats, rewards, axis_name)
        rewards = rewards * reward_scale(stats)
        state = state.replace(reward_stats=stats)
    rho_stats = None
    if config.correction == "vtrace":
        T, E = tr.reward.shape[:2]
        B = T * E
        flat = lambda x: x.reshape(B, *x.shape[2:])
        # One batched apply under the learner's current params. The
        # [T·E] logits (and the log-softmax behind log_prob) are bitwise
        # row-equal to the rollout's per-step [E] applies on the tested
        # backends, so on-policy data yields target_lp == tr.log_prob
        # exactly and ratios ≡ 1.0 exactly. The value HEAD does not share
        # that property (its [B,1] gemm reassociates with batch size), so
        # V-trace bootstraps the stored behavior values like GAE does —
        # the sample-factory/APPO convention, and the choice that keeps
        # the bound-0 path bit-identical.
        logits, _ = apply_fn(_params_of(state), flat(tr.obs),
                             flat(tr.mask))
        target_lp = action_dist.log_prob(
            logits, flat(tr.action)).reshape(T, E)
        rho = vtrace_ops.importance_ratios(tr.log_prob, target_lp)
        advantages, returns = vtrace_ops.compute_vtrace(
            rewards, tr.value, tr.done, last_value, rho,
            config.gamma, config.gae_lambda, config.rho_bar, config.c_bar)
        rho_stats = (jnp.mean(rho), jnp.max(rho))
    else:
        advantages, returns = compute_gae(rewards, tr.value, tr.done,
                                          last_value, config.gamma,
                                          config.gae_lambda)
    advantages = normalize_advantages(advantages, axis_name)
    if config.bf16_advantages:
        advantages = advantages.astype(jnp.bfloat16)
        returns = returns.astype(jnp.bfloat16)
    return state, advantages, returns, rho_stats


def make_ppo_grad_step(apply_fn: PolicyApply, config: PPOConfig,
                       apply_grads, clip_eps=None, ent_coef=None):
    """One clipped-surrogate minibatch update for the fused engine:
    ``(state, (mb, adv, ret)) -> (state, (loss, *aux))``. With
    ``config.bf16_update`` the loss/grad evaluation runs on bf16 casts of
    the params and batch; grads are cast back to each param's dtype so
    the optimizer (and its Adam moments) stays fp32."""

    def grad_step(state, mb_data):
        mb, adv, ret = mb_data
        params = _params_of(state)
        if config.bf16_update:
            c = lambda t: update_engine.cast_floating(t, jnp.bfloat16)
            (loss, aux), grads = jax.value_and_grad(
                ppo_loss, argnums=1, has_aux=True)(
                apply_fn, c(params), c(mb), c(adv), c(ret),
                config, clip_eps=clip_eps, ent_coef=ent_coef)
            grads = jax.tree.map(lambda g, p: g.astype(p.dtype),
                                 grads, params)
            loss, aux = jax.tree.map(
                lambda x: x.astype(jnp.float32), (loss, aux))
        else:
            (loss, aux), grads = jax.value_and_grad(
                ppo_loss, argnums=1, has_aux=True)(
                apply_fn, params, mb, adv, ret,
                config, clip_eps=clip_eps, ent_coef=ent_coef)
        state = apply_grads(state, grads)
        return state, (loss, *aux)

    return grad_step


def run_ppo_epochs(apply_fn: PolicyApply, config: PPOConfig, state,
                   tr: Transition, advantages: jax.Array,
                   returns: jax.Array, key: jax.Array, apply_grads,
                   clip_eps=None, ent_coef=None, rho_stats=None):
    """The PPO update core shared by the single-run trainer and the PBT
    member step: flatten [T, E] → [B], then hand the batch to the fused
    minibatch-geometry engine (:mod:`algos.update`) at the config's
    ``n_epochs × n_minibatches × minibatch_size`` geometry.
    ``apply_grads(state, grads) -> state`` injects the optimizer strategy
    (TrainState vs the population's manual traced-lr update);
    ``clip_eps``/``ent_coef`` optionally override the config with traced
    values. Returns (state, metrics)."""
    B = config.n_steps * tr.reward.shape[1]
    flat = jax.tree.map(lambda x: x.reshape(B, *x.shape[2:]), tr)
    grad_step = make_ppo_grad_step(apply_fn, config, apply_grads,
                                   clip_eps=clip_eps, ent_coef=ent_coef)
    state, stats = update_engine.run_minibatch_epochs(
        grad_step, state, (flat, advantages.reshape(B), returns.reshape(B)),
        key, n_epochs=config.n_epochs, n_minibatches=config.n_minibatches,
        minibatch_size=config.minibatch_size)
    rho_mean, rho_max = (rho_stats if rho_stats is not None
                         else (jnp.asarray(1.0, jnp.float32),
                               jnp.asarray(1.0, jnp.float32)))
    metrics = PPOMetrics(
        total_loss=jnp.mean(stats[0]), pg_loss=jnp.mean(stats[1]),
        v_loss=jnp.mean(stats[2]), entropy=jnp.mean(stats[3]),
        approx_kl=jnp.mean(stats[4]), clip_frac=jnp.mean(stats[5]),
        mean_reward=jnp.mean(tr.reward), mean_value=jnp.mean(tr.value),
        rho_mean=rho_mean, rho_max=rho_max)
    return state, metrics


def _params_of(state):
    return state.params  # TrainState and population.MemberState both


def make_learn_step(apply_fn: PolicyApply, config: PPOConfig,
                    axis_name: str | None = None):
    """Build the learn half of the PPO iteration:
    (train_state, tr, last_value, key) -> (train_state', metrics).

    GAE + advantage normalization + the fused minibatch-epoch engine —
    everything downstream of the rollout. The fused
    :func:`make_train_step` composes this with :func:`rollout`, and the
    async engine (:mod:`~rlgpuschedule_tpu.async_engine`) jits it alone
    on the learner device group, so both paths run literally the same
    update code (the staleness-bound-0 bit-identity contract)."""

    def apply_grads(state: TrainState, grads):
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
        return state.apply_gradients(grads=grads)

    def learn_step(train_state: TrainState, tr: Transition,
                   last_value: jax.Array, key: jax.Array):
        train_state, advantages, returns, rho_stats = compute_advantages(
            apply_fn, config, train_state, tr, last_value, axis_name)
        return run_ppo_epochs(apply_fn, config, train_state, tr,
                              advantages, returns, key, apply_grads,
                              rho_stats=rho_stats)

    return learn_step


def make_train_step(apply_fn: PolicyApply, env_params: EnvParams,
                    config: PPOConfig, axis_name: str | None = None):
    """Build the jittable PPO iteration:
    (train_state, carry, traces, key) -> (train_state', carry', metrics).

    ``axis_name``: mesh axis for data-parallel gradient pmean (None =
    single-device)."""
    learn_step = make_learn_step(apply_fn, config, axis_name)

    def train_step(train_state: TrainState, carry: RolloutCarry, traces,
                   key: jax.Array, faults=None):
        carry, tr, last_value = rollout(apply_fn, train_state.params,
                                        env_params, traces, carry,
                                        config.n_steps, faults)
        train_state, metrics = learn_step(train_state, tr, last_value, key)
        return train_state, carry, metrics

    return train_step


def make_train_state(net, key: jax.Array, example_obs: jax.Array,
                     example_mask: jax.Array,
                     tx: optax.GradientTransformation,
                     extra_apply_args: tuple = (),
                     reward_norm: bool = False) -> TrainState:
    """Initialize params + optimizer into a flax TrainState.
    ``extra_apply_args`` go between obs and mask (the GNN's adjacency).
    ``reward_norm`` swaps in :class:`NormTrainState` carrying the
    streaming reward moments (different pytree — checkpoints are not
    interchangeable with the default state, by design)."""
    params = net.init(key, example_obs, *extra_apply_args, example_mask)
    if reward_norm:
        return NormTrainState.create(apply_fn=net.apply, params=params,
                                     tx=tx,
                                     reward_stats=init_reward_stats())
    return TrainState.create(apply_fn=net.apply, params=params, tx=tx)
