"""Fused policy+env rollout collection (L4).

Capability parity: SURVEY.md §2 "Rollout buffer" / "Multi-actor runner" and
§3.1 HOT LOOP #1. The reference alternates host-side env stepping with
device policy inference per step; here the policy forward, action sampling,
and the vmapped env step fuse into ONE ``lax.scan`` that never leaves the
device — the Podracer/Anakin pattern (SURVEY.md §7 step 5 `[P: Podracer]`),
which removes the per-step host↔device sync that bottlenecks the reference
(SURVEY.md §7 hard part (d)).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax

from ..env import env as env_lib
from ..env.env import EnvParams, EnvState
from . import action_dist

# (net_params, obs, mask) -> (masked_logits, value[E]). obs/mask/logits may
# each be a single array or a pytree (multi-head policies — see
# algos.action_dist); the rollout is agnostic.
PolicyApply = Callable[[Any, Any, Any], tuple[Any, jax.Array]]


class Transition(NamedTuple):
    """One scan slice of the rollout buffer; stacked to [T, E, ...].
    ``obs``/``action``/``mask`` are arrays for single-head policies and
    pytrees for multi-head (hierarchical) ones; ``log_prob`` is always the
    joint [E] log-prob under the BEHAVIOR params the rollout ran with —
    PPO's surrogate ratio and V-trace's importance ratios
    (``algos.vtrace``) both divide the target policy by exactly this
    stored quantity, so it must never be recomputed post-hoc."""
    obs: Any
    action: Any
    log_prob: jax.Array
    value: jax.Array
    reward: jax.Array
    done: jax.Array
    mask: Any
    env_steps_dt: jax.Array  # simulated seconds advanced (metrics)


class RolloutCarry(NamedTuple):
    env_state: EnvState
    obs: jax.Array
    mask: jax.Array
    key: jax.Array


def init_carry(params: EnvParams, traces, key: jax.Array,
               faults=None) -> RolloutCarry:
    env_state, ts = env_lib.vec_reset(params, traces, faults)
    return RolloutCarry(env_state, ts.obs, ts.action_mask, key)


def validate_rollout_geometry(n_steps: int, n_envs: int,
                              n_devices: int = 1) -> None:
    """Validate the rollout phase's batch geometry on its own terms —
    decoupled from the update phase's minibatch constraints
    (:func:`..algos.update.validate_update_geometry`), because the async
    actor–learner engine runs the two phases on *different* device
    groups: the env batch must tile the actor group; whether the
    flattened [T·E] batch tiles the update's minibatch geometry is the
    learner group's problem."""
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    if n_envs < 1:
        raise ValueError(f"n_envs must be >= 1, got {n_envs}")
    if n_devices > 1 and n_envs % n_devices:
        raise ValueError(
            f"n_envs={n_envs} must be divisible by the rollout device "
            f"group size ({n_devices}) to shard the env batch evenly")


def make_rollout_step(apply_fn: PolicyApply, env_params: EnvParams,
                      n_steps: int):
    """Build the jittable rollout half of an iteration:
    (net_params, carry, traces, faults) -> (carry', tr, last_value).

    The fused ``make_train_step`` inlines :func:`rollout` directly; the
    async engine jits this factory's product alone on the actor device
    group, so the collection program is byte-for-byte the same scan in
    both paths."""

    def rollout_step(net_params, carry: RolloutCarry, traces, faults=None):
        return rollout(apply_fn, net_params, env_params, traces, carry,
                       n_steps, faults)

    return rollout_step


def rollout(apply_fn: PolicyApply, net_params, env_params: EnvParams,
            traces, carry: RolloutCarry, n_steps: int, faults=None,
            ) -> tuple[RolloutCarry, Transition, jax.Array]:
    """Collect ``n_steps`` transitions from the vectorized envs in one scan.
    Returns (carry', transitions [T,E,...], last_value [E]).

    ``faults``: batched per-env FaultSchedule threaded next to the traces
    (auto-reset restarts an episode under the SAME schedule); None =
    healthy cluster, the bit-identical pre-chaos program."""
    # the auto-reset bundle depends only on the traces (and the fault
    # schedules): build it once here (a scan constant) instead of
    # re-running a full reset every step
    fresh = env_lib.vec_reset(env_params, traces, faults)

    def step(c: RolloutCarry, _):
        logits, value = apply_fn(net_params, c.obs, c.mask)
        key, sub = jax.random.split(c.key)
        action, log_prob = action_dist.sample(sub, logits)
        env_state, ts = env_lib.vec_step(env_params, c.env_state, traces,
                                         action, fresh, faults)
        t = Transition(obs=c.obs, action=action, log_prob=log_prob,
                       value=value, reward=ts.reward, done=ts.done,
                       mask=c.mask, env_steps_dt=ts.info.dt)
        return RolloutCarry(env_state, ts.obs, ts.action_mask, key), t

    carry, transitions = jax.lax.scan(step, carry, None, length=n_steps)
    # Pin the trajectory stack's env axis to the mesh's data axis before it
    # feeds GAE + the minibatch update: without the constraint GSPMD is free
    # to replicate the [T, E, ...] buffer on every device, which is exactly
    # the memory ceiling the partition-rule mesh exists to lift. Identity
    # when no mesh is bound (single-device / legacy dp paths).
    from ..parallel.sharding import DATA_AXIS, constrain_tree
    transitions = constrain_tree(transitions, None, DATA_AXIS)
    _, last_value = apply_fn(net_params, carry.obs, carry.mask)
    return carry, transitions, last_value
