"""Fused policy+env rollout collection (L4).

Capability parity: SURVEY.md §2 "Rollout buffer" / "Multi-actor runner" and
§3.1 HOT LOOP #1. The reference alternates host-side env stepping with
device policy inference per step; here the policy forward, action sampling,
and the vmapped env step fuse into ONE ``lax.scan`` that never leaves the
device — the Podracer/Anakin pattern (SURVEY.md §7 step 5 `[P: Podracer]`),
which removes the per-step host↔device sync that bottlenecks the reference
(SURVEY.md §7 hard part (d)).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from ..env import env as env_lib
from ..env.env import EnvParams, EnvState, TimeStep

# (net_params, obs[E,...], mask[E,A]) -> (masked_logits[E,A], value[E])
PolicyApply = Callable[[Any, jax.Array, jax.Array],
                       tuple[jax.Array, jax.Array]]


class Transition(NamedTuple):
    """One scan slice of the rollout buffer; stacked to [T, E, ...]."""
    obs: jax.Array
    action: jax.Array
    log_prob: jax.Array
    value: jax.Array
    reward: jax.Array
    done: jax.Array
    mask: jax.Array
    env_steps_dt: jax.Array  # simulated seconds advanced (metrics)


class RolloutCarry(NamedTuple):
    env_state: EnvState
    obs: jax.Array
    mask: jax.Array
    key: jax.Array


def init_carry(params: EnvParams, traces, key: jax.Array) -> RolloutCarry:
    env_state, ts = env_lib.vec_reset(params, traces)
    return RolloutCarry(env_state, ts.obs, ts.action_mask, key)


def rollout(apply_fn: PolicyApply, net_params, env_params: EnvParams,
            traces, carry: RolloutCarry, n_steps: int,
            ) -> tuple[RolloutCarry, Transition, jax.Array]:
    """Collect ``n_steps`` transitions from the vectorized envs in one scan.
    Returns (carry', transitions [T,E,...], last_value [E])."""

    def step(c: RolloutCarry, _):
        logits, value = apply_fn(net_params, c.obs, c.mask)
        key, sub = jax.random.split(c.key)
        action = jax.random.categorical(sub, logits)
        log_prob = jnp.take_along_axis(
            jax.nn.log_softmax(logits), action[:, None], axis=1).squeeze(1)
        env_state, ts = env_lib.vec_step(env_params, c.env_state, traces, action)
        t = Transition(obs=c.obs, action=action, log_prob=log_prob,
                       value=value, reward=ts.reward, done=ts.done,
                       mask=c.mask, env_steps_dt=ts.info.dt)
        return RolloutCarry(env_state, ts.obs, ts.action_mask, key), t

    carry, transitions = jax.lax.scan(step, carry, None, length=n_steps)
    _, last_value = apply_fn(net_params, carry.obs, carry.mask)
    return carry, transitions, last_value
