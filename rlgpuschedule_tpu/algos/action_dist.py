"""Multi-head masked-categorical action distribution over logit pytrees.

Capability parity: SURVEY.md §2 "Actor/critic heads" (job-select ×
placement logits) and "Hierarchical multi-agent" — the hierarchical agent's
joint action factorizes into independent categorical heads (top-level
router + per-pod placers, §3.5), so one distribution abstraction serves
both the flat single-head policies (configs 1–4) and the factored
hierarchical policy (config 5).

Shape convention: a policy's ``logits`` may be a single ``[*B, A]`` array
(one head) or any pytree of such arrays. All leaves share the leading
batch axes ``*B``; a leaf may carry extra axes between batch and ``A``
(e.g. the hierarchical policy's per-pod heads ``[*B, P, A]``) — each slice
along those axes is an independent head, and joint log-probs/entropies sum
them away. The batch rank is inferred as the minimum per-head rank across
leaves (the single-head leaves anchor it; a policy with ONLY stacked-head
leaves should add a size-1 head leaf or reshape). PPO/A2C and the rollout
are written against these helpers and are head-structure-agnostic.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def split_like(key: jax.Array, tree: Any) -> Any:
    """One PRNG key per tree leaf, packaged in the same structure."""
    leaves, treedef = jax.tree.flatten(tree)
    keys = jax.random.split(key, len(leaves))
    return jax.tree.unflatten(treedef, list(keys))


def _sum_heads(per_head: Any) -> jax.Array:
    """Reduce per-head values [*B, *heads] to joint [*B]: batch rank =
    minimum leaf rank; extra trailing axes are stacked heads, summed."""
    leaves = jax.tree.leaves(per_head)
    batch_ndim = min(l.ndim for l in leaves)
    total = 0
    for l in leaves:
        if l.ndim > batch_ndim:
            l = l.sum(axis=tuple(range(batch_ndim, l.ndim)))
        total = total + l
    return total


def sample(key: jax.Array, logits: Any) -> tuple[Any, jax.Array]:
    """Draw one action per head; returns (actions pytree of i32 arrays,
    joint log-prob [*B]). Masked (−1e9) logits sample a masked action with
    probability ~0."""
    keys = split_like(key, logits)
    actions = jax.tree.map(
        lambda lg, k: jax.random.categorical(k, lg), logits, keys)
    return actions, log_prob(logits, actions)


def log_prob(logits: Any, actions: Any) -> jax.Array:
    """Joint log-probability [*B] of an action pytree under a logits
    pytree: selected-action log-softmax summed over all heads."""

    def head_logp(lg: jax.Array, a: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(lg)
        return jnp.take_along_axis(logp, a[..., None], axis=-1).squeeze(-1)

    return _sum_heads(jax.tree.map(head_logp, logits, actions))


def entropy(logits: Any) -> jax.Array:
    """Joint entropy [*B] = sum of per-head masked-categorical entropies
    (heads are independent). Masked entries (p≈0) contribute 0."""

    def head_entropy(lg: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(lg)
        p = jnp.exp(logp)
        return -jnp.sum(p * jnp.where(p > 0, logp, 0.0), axis=-1)

    return _sum_heads(jax.tree.map(head_entropy, logits))
