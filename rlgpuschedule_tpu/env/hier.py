"""Hierarchical multi-pod environment (L2/L5) — config 5's workload.

Capability parity: SURVEY.md §2 "Hierarchical multi-agent" / §3.5 — a
scheduler-of-schedulers over ``n_pods`` simulated pods: a **top-level
router** assigns each arriving job to one pod; **per-pod placement agents**
(shared weights, one action per pod per step) schedule their own pod's
queue. The reference runs these as communicating agents across processes;
here the whole hierarchy is one pure-functional step over a pytree —
per-pod simulators are ONE stacked :class:`~..sim.core.SimState` with a
leading pod axis driven by ``vmap``, clocks held in lockstep by advancing
every pod to the same global next-event time.

Joint-action semantics per decision step (mirrors ``sim.core.rl_step``'s
branchless pattern):

1. the router action (``action["top"]``: pod index or no-op) routes the
   HEAD arrived-but-unassigned job into that pod's queue;
2. every pod's action (``action["pods"][p]``: queue-slot×placement or
   no-op) gang-places within its pod, all at the same virtual time;
3. iff nothing was routed or placed, time advances to the next global
   event (earliest trace arrival or pod completion); with no event left,
   forced progress (route head to freest pod, else pack pod queue heads)
   guarantees liveness, as in the flat env.

Jobs live in exactly one pod: pods are initialized with every job inert
(status DONE, the sim's "not mine" sentinel — completions/queues/events
all ignore it) and routing flips the job to PENDING in the chosen pod
only. Global metrics (JCT, done) therefore reduce over the pod axis:
``finish[j] = min_p pods.finish[p, j]``.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..sim import core
from ..sim.core import (DONE, INF, PACK, PENDING, RUNNING, SimParams,
                        SimState, Trace)
from ..traces.records import ArrayTrace
from . import env as env_lib
from . import obs as obs_lib
from . import rewards as reward_lib
from .env import TimeStep
from ..sim.core import StepInfo


@dataclasses.dataclass(frozen=True)
class HierParams:
    """Static hierarchical-env configuration. ``pod_sim`` describes ONE
    pod's geometry (nodes per pod × GPUs); the cluster is
    ``n_pods × pod_sim.n_nodes`` nodes."""
    n_pods: int
    pod_sim: SimParams
    time_scale: float = 600.0
    reward_scale: float = 10_000.0
    place_bonus: float = 0.0    # shaping per progress step (rewards.py)
    horizon: int = 512

    @property
    def n_top_actions(self) -> int:
        return self.n_pods + 1          # route-to-pod p | no-op

    @property
    def pod_capacity(self) -> int:
        return self.pod_sim.capacity

    # top-level observation: per-pod summaries + head-job features + globals
    POD_SUMMARY_FEATURES = 3
    HEAD_FEATURES = 4

    def top_obs_dim(self) -> int:
        return (self.n_pods * self.POD_SUMMARY_FEATURES
                + self.HEAD_FEATURES + 2)

    def obs_shape(self) -> dict:
        pod = self.pod_sim
        return {"top": (self.top_obs_dim(),),
                "pods": (self.n_pods, pod.n_nodes + 4 * pod.queue_len + 2)}


class HierState(NamedTuple):
    pods: SimState        # stacked [P, ...]
    assignment: jax.Array  # i32[J]; -1 = not yet routed
    t: jax.Array           # i32 decision-step counter


def validate_hier_trace(params: HierParams, tr: ArrayTrace,
                        clamp: bool = False) -> ArrayTrace:
    """A job demanding more GPUs than ONE POD holds can never be placed
    (gangs do not span pods); mirror sim.core.validate_trace at pod
    granularity."""
    return core.validate_trace(params.pod_sim, tr, clamp=clamp)


def pod_init(params: HierParams, trace: Trace) -> SimState:
    """One pod's initial state: every job inert (DONE) until routed in."""
    J, N = params.pod_sim.max_jobs, params.pod_sim.n_nodes
    return SimState(
        clock=jnp.float32(0.0),
        status=jnp.full((J,), DONE, jnp.int32),
        remaining=jnp.array(trace.duration, jnp.float32, copy=True),
        start=jnp.full((J,), INF, jnp.float32),
        finish=jnp.full((J,), INF, jnp.float32),
        alloc=jnp.zeros((J, N), jnp.int32),
        free=jnp.full((N,), params.pod_sim.gpus_per_node, jnp.int32),
    )


# ---- global queries ---------------------------------------------------------

def global_clock(state: HierState) -> jax.Array:
    return state.pods.clock[0]          # pods advance in lockstep


def finished_mask(state: HierState, trace: Trace) -> jax.Array:
    """bool[J]: job completed in whichever pod ran it."""
    return trace.valid & (jnp.min(state.pods.finish, axis=0) < INF)


def arrived_mask(state: HierState, trace: Trace,
                 clock: jax.Array | None = None) -> jax.Array:
    clock = global_clock(state) if clock is None else clock
    return trace.valid & (trace.submit <= clock)


def unassigned_mask(state: HierState, trace: Trace) -> jax.Array:
    return arrived_mask(state, trace) & (state.assignment < 0)


def head_unassigned(state: HierState, trace: Trace,
                    ) -> tuple[jax.Array, jax.Array]:
    """(row index of the earliest-submitted arrived-unassigned job, exists).
    Trace rows are submit-sorted, so argmax of the mask is the head."""
    mask = unassigned_mask(state, trace)
    return jnp.argmax(mask).astype(jnp.int32), jnp.any(mask)


def in_system(state: HierState, trace: Trace) -> jax.Array:
    """Arrived and not finished — counts jobs still waiting in the router,
    so leaving work unrouted is penalized exactly like leaving it queued."""
    return jnp.sum(arrived_mask(state, trace)
                   & ~finished_mask(state, trace))


def all_done(state: HierState, trace: Trace) -> jax.Array:
    return jnp.all(jnp.where(trace.valid, finished_mask(state, trace), True))


def jct_stats(state: HierState, trace: Trace) -> dict[str, jax.Array]:
    finish = jnp.min(state.pods.finish, axis=0)
    done = finished_mask(state, trace)
    jct = jnp.where(done, finish - trace.submit, 0.0)
    n = jnp.maximum(jnp.sum(done), 1)
    return {"avg_jct": jnp.sum(jct) / n,
            "max_jct": jnp.max(jnp.where(done, jct, -INF)),
            "n_done": jnp.sum(done)}


# ---- state transforms -------------------------------------------------------

def apply_route(params: HierParams, state: HierState, trace: Trace,
                pod: jax.Array, j: jax.Array, ok: jax.Array) -> HierState:
    """Route job row ``j`` into ``pod``'s queue (PENDING there); masked
    no-op unless ``ok``."""
    row = (jax.nn.one_hot(j, params.pod_sim.max_jobs, dtype=jnp.int32)
           * ok.astype(jnp.int32)).astype(bool)          # [J]
    pod_row = (jax.nn.one_hot(pod, params.n_pods, dtype=jnp.int32)
               * ok.astype(jnp.int32)).astype(bool)      # [P]
    hit = pod_row[:, None] & row[None, :]                # [P, J]
    return HierState(
        pods=state.pods._replace(
            status=jnp.where(hit, PENDING, state.pods.status)),
        assignment=jnp.where(row, pod.astype(jnp.int32), state.assignment),
        t=state.t)


def pod_place(params: HierParams, pod_state: SimState, trace: Trace,
              action: jax.Array) -> tuple[SimState, jax.Array]:
    """One pod's placement action (queue-slot × placement | no-op), the
    action-decode + try_place half of ``core.rl_step`` (no time advance —
    the hierarchy advances time globally)."""
    sp = params.pod_sim
    K, Pl = sp.queue_len, sp.n_placements
    queue = core.pending_queue(sp, pod_state)
    is_noop = action >= K * Pl
    k = jnp.clip(action // Pl, 0, K - 1)
    mode = action % Pl
    j = jnp.where(is_noop, -1, queue[k])
    return core.try_place(sp, pod_state, trace, j, mode)


def _vmap_pods(fn, pods: SimState, *args):
    return jax.vmap(lambda ps, *a: fn(ps, *a))(pods, *args)


def _pod_queues(params: HierParams, pods: SimState) -> jax.Array:
    """Every pod's pending queue, [P, K]."""
    return _vmap_pods(lambda ps: core.pending_queue(params.pod_sim, ps),
                      pods)


def next_event_time(state: HierState, trace: Trace) -> jax.Array:
    """Earliest future trace arrival or any-pod completion (+inf if none)."""
    clock = global_clock(state)
    t_arr = jnp.min(jnp.where(trace.valid & (trace.submit > clock),
                              trace.submit, INF))
    pod_next = _vmap_pods(lambda ps: core.next_event_time(ps, trace),
                          state.pods)
    return jnp.minimum(t_arr, jnp.min(pod_next))


def advance_all(state: HierState, trace: Trace, t: jax.Array) -> HierState:
    pods = _vmap_pods(lambda ps: core.advance_to(ps, trace, t), state.pods)
    return state._replace(pods=pods)


def forced_progress(params: HierParams, state: HierState, trace: Trace,
                    ) -> tuple[HierState, jax.Array]:
    """Liveness fallback when agents no-op with no event left: route the
    head unassigned job to the pod with the most free GPUs; with nothing to
    route, pack-place every pod's queue head (mirrors ``core.rl_step``'s
    forced placement; validate_hier_trace guarantees head demands fit an
    empty pod)."""
    j, exists = head_unassigned(state, trace)
    pod_free = jnp.sum(state.pods.free, axis=1)              # [P]
    best = jnp.argmax(pod_free).astype(jnp.int32)
    routed = apply_route(params, state, trace, best, j, exists)

    def head_place(ps: SimState) -> tuple[SimState, jax.Array]:
        queue = core.pending_queue(params.pod_sim, ps)
        return core.try_place(params.pod_sim, ps, trace, queue[0],
                              jnp.int32(PACK))

    placed_pods, placed_ok = _vmap_pods(head_place, state.pods)
    placed = state._replace(pods=placed_pods)
    pick = lambda a, b: jax.tree.map(
        lambda x, y: jnp.where(exists, x, y), a, b)
    return pick(routed, placed), exists | jnp.any(placed_ok)


# ---- observations / masks ---------------------------------------------------

def build_obs(params: HierParams, state: HierState, trace: Trace,
              queues: jax.Array | None = None) -> dict:
    sp = params.pod_sim
    clock = global_clock(state)
    # per-pod flat observations (shared-weight pod agents), [P, D_pod]
    if queues is None:
        queues = _pod_queues(params, state.pods)
    pod_obs = _vmap_pods(
        lambda ps, q: obs_lib.flat_obs(sp, ps, trace, params.time_scale, q),
        state.pods, queues)
    # router observation: per-pod summaries + head job + global load
    free_frac = jnp.sum(state.pods.free, axis=1) / sp.capacity       # [P]
    pending = jnp.sum(state.pods.status == PENDING, axis=1)          # [P]
    running = jnp.sum(state.pods.status == RUNNING, axis=1)          # [P]
    summary = jnp.stack([free_frac,
                         pending / sp.queue_len,
                         running / sp.capacity], axis=1)             # [P, 3]
    j, exists = head_unassigned(state, trace)
    e = exists.astype(jnp.float32)
    head = jnp.stack([
        e,
        trace.gpus[j].astype(jnp.float32) / sp.capacity * e,
        jnp.tanh(jnp.where(exists, clock - trace.submit[j], 0.0)
                 / params.time_scale),
        jnp.tanh(jnp.where(exists, trace.duration[j], 0.0)
                 / params.time_scale)])
    n_unassigned = jnp.sum(unassigned_mask(state, trace))
    globals_ = jnp.stack([n_unassigned / sp.max_jobs,
                          in_system(state, trace) / sp.max_jobs])
    top = jnp.concatenate([summary.reshape(-1), head, globals_]
                          ).astype(jnp.float32)
    return {"top": top, "pods": pod_obs}


def action_mask(params: HierParams, state: HierState, trace: Trace,
                queues: jax.Array | None = None) -> dict:
    j, exists = head_unassigned(state, trace)
    fits = trace.gpus[j] <= params.pod_capacity
    route_ok = jnp.broadcast_to(exists & fits, (params.n_pods,))
    top = jnp.concatenate([route_ok, jnp.ones((1,), bool)])
    if queues is None:
        queues = _pod_queues(params, state.pods)
    pod_masks = _vmap_pods(
        lambda ps, q: core.action_mask(params.pod_sim, ps, trace, q),
        state.pods, queues)
    return {"top": top, "pods": pod_masks}


def _observe(params: HierParams, state: HierState, trace: Trace,
             ) -> tuple[dict, dict]:
    """(obs, mask), computing each pod's pending queue once and sharing it
    between the observation builder and the action mask."""
    queues = _pod_queues(params, state.pods)
    return (build_obs(params, state, trace, queues),
            action_mask(params, state, trace, queues))


# ---- reset / step -----------------------------------------------------------

def reset(params: HierParams, trace: Trace) -> tuple[HierState, TimeStep]:
    pods = jax.vmap(lambda _: pod_init(params, trace)
                    )(jnp.arange(params.n_pods))
    state = HierState(pods=pods,
                      assignment=jnp.full((params.pod_sim.max_jobs,), -1,
                                          jnp.int32),
                      t=jnp.int32(0))
    info = StepInfo(placed=jnp.bool_(False), dt=jnp.float32(0.0),
                    in_system_before=in_system(state, trace),
                    done=jnp.bool_(False), preempted=jnp.bool_(False),
                    first_placed=jnp.bool_(False))
    obs, mask = _observe(params, state, trace)
    ts = TimeStep(obs=obs, reward=jnp.float32(0.0), done=jnp.bool_(False),
                  action_mask=mask, info=info)
    return state, ts


def step(params: HierParams, state: HierState, trace: Trace,
         action: dict) -> tuple[HierState, TimeStep]:
    """One joint decision step; see module docstring for semantics.
    ``action = {"top": i32, "pods": i32[P]}``."""
    clock = global_clock(state)
    n_before = in_system(state, trace)

    # 1. route (top head)
    top = action["top"]
    j, exists = head_unassigned(state, trace)
    is_route = top < params.n_pods
    pod_choice = jnp.clip(top, 0, params.n_pods - 1).astype(jnp.int32)
    fits = trace.gpus[j] <= params.pod_capacity
    route_ok = is_route & exists & fits
    routed = apply_route(params, state, trace, pod_choice, j, route_ok)

    # 2. pod placements (on the post-routing pods, same virtual time)
    pods2, placed = _vmap_pods(
        lambda ps, a: pod_place(params, ps, trace, a),
        routed.pods, action["pods"])
    acted = routed._replace(pods=pods2)
    progress = route_ok | jnp.any(placed)
    # a failed route / failed placements leave the state bit-identical, so
    # the advance/forced candidates below start from `acted` in every case

    # 3. advance time — or forced progress when the event horizon is empty
    t_next = next_event_time(acted, trace)
    has_event = jnp.isfinite(t_next)
    advanced = advance_all(acted, trace, t_next)
    forced, forced_ok = forced_progress(params, acted, trace)

    def pick(a, b, c):  # progress ? a : (has_event ? b : c)
        return jnp.where(progress, a, jnp.where(has_event, b, c))

    new_state = jax.tree.map(pick, acted, advanced, forced)
    new_state = new_state._replace(t=state.t + 1)
    dt = jnp.where(progress | ~has_event, 0.0, t_next - clock)
    acted_ok = progress | (~progress & ~has_event & forced_ok)
    # no preemption in the hierarchy, so every progress step is "first"
    # (a job routes once and places once — the bonus stays bounded)
    info = StepInfo(placed=acted_ok, dt=dt, in_system_before=n_before,
                    done=all_done(new_state, trace),
                    preempted=jnp.bool_(False), first_placed=acted_ok)
    # same JCT integrand + placement shaping as the flat env (ADVICE r1:
    # place_bonus was silently dropped for hierarchical configs)
    reward = reward_lib.reward_jct(info, params.reward_scale,
                                   params.place_bonus)
    done = info.done | (new_state.t >= params.horizon)
    obs, mask = _observe(params, new_state, trace)
    ts = TimeStep(obs=obs, reward=reward, done=done, action_mask=mask,
                  info=info)
    return new_state, ts


def auto_reset_step(params: HierParams, state: HierState, trace: Trace,
                    action: dict, fresh=None) -> tuple[HierState, TimeStep]:
    """Step + fused auto-reset; pass a precomputed ``fresh = reset(params,
    trace)`` when stepping in a loop (see env.auto_reset_step)."""
    stepped, ts = step(params, state, trace, action)
    fresh_state, fresh_ts = (reset(params, trace) if fresh is None
                             else fresh)
    return env_lib.auto_reset(stepped, ts, fresh_state, fresh_ts)


# ---- vectorization (rollout integration via singledispatch) -----------------

@env_lib.vec_reset.register
def _(params: HierParams, traces: Trace,
      faults=None) -> tuple[HierState, TimeStep]:
    if faults is not None:
        raise ValueError("the hierarchical env has no fault-process "
                         "support; cluster chaos (sim.faults) is a flat-"
                         "config feature for now")
    return jax.vmap(lambda tr: reset(params, tr))(traces)


@env_lib.vec_step.register
def _(params: HierParams, state: HierState, traces: Trace,
      actions: dict, fresh=None, faults=None) -> tuple[HierState, TimeStep]:
    if faults is not None:
        raise ValueError("the hierarchical env has no fault-process "
                         "support; cluster chaos (sim.faults) is a flat-"
                         "config feature for now")
    if fresh is None:
        return jax.vmap(lambda s, tr, a: auto_reset_step(params, s, tr, a)
                        )(state, traces, actions)
    return jax.vmap(lambda s, tr, a, f: auto_reset_step(params, s, tr, a, f)
                    )(state, traces, actions, fresh)
