"""L2 environment layer: pure-functional gym-style env over the JAX sim."""
from .env import (EnvParams, EnvState, TimeStep, reset, step, auto_reset_step,
                  stack_traces, vec_reset, vec_step, build_obs)
from .obs import flat_obs, grid_obs, graph_obs, build_adjacency, GRAPH_FEATURES
from .rewards import reward_jct, reward_fair, tenant_counts
from .hier import HierParams, HierState

__all__ = [
    "EnvParams", "EnvState", "TimeStep", "reset", "step", "auto_reset_step",
    "stack_traces", "vec_reset", "vec_step", "build_obs",
    "flat_obs", "grid_obs", "graph_obs", "build_adjacency", "GRAPH_FEATURES",
    "reward_jct", "reward_fair", "tenant_counts",
    "HierParams", "HierState",
]
