"""Gym-style pure-functional cluster environment (L2).

Capability parity: SURVEY.md §2 "Gym-style env wrapper" / "Vectorized env":
``reset/step`` over the jitted simulator, an episode = one trace-window
replay, action masking for infeasible placements, and vectorization via
``jax.vmap`` over a batched Trace pytree (the reference's subprocess/serial
VecEnv becomes a vmap axis — SURVEY.md §2 "rebuild: vmap").

Everything is pure: ``step`` is (params, state, action) → (state', timestep),
so the whole interaction loop fuses into one ``lax.scan`` with the policy
(Anakin pattern, SURVEY.md §7 step 5).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Literal, NamedTuple

import jax
import jax.numpy as jnp

from ..sim import core
from ..sim.core import SimParams, SimState, Trace, StepInfo
from ..sim.faults import FaultRegime, FaultSchedule
from ..traces.records import ArrayTrace
from . import obs as obs_lib
from . import rewards as reward_lib


@dataclasses.dataclass(frozen=True)
class EnvParams:
    """Static env configuration (hashable; closed over by jit)."""
    sim: SimParams
    obs_kind: Literal["flat", "grid", "graph"] = "flat"
    reward_kind: Literal["jct", "fair"] = "jct"
    n_tenants: int = 1
    time_scale: float = 600.0     # normalizes times in observations
    reward_scale: float = 1000.0  # divides reward magnitudes
    place_bonus: float = 0.0      # potential-based shaping (rewards.py)
    preempt_cost: float = 0.0     # anti-stall preemption charge (rewards.py)
    horizon: int = 512            # max decision steps per episode
    # cluster fault process (sim.faults): the static DISTRIBUTION the
    # env's fault schedules are drawn from (the sampled FaultSchedule is
    # per-env data threaded next to the trace). None = permanently
    # healthy — the pre-chaos program, bit-identical.
    fault_process: FaultRegime | None = None
    # append a per-node health channel (1/slowdown while up, 0 while
    # drained) to the observation so the policy can LEARN to route
    # around drains. Flat observations only (the grid/graph encoders pin
    # their channel/feature counts); checked in __post_init__.
    fault_obs: bool = False
    # domain randomization (domains.schedule): the static DISTRIBUTION
    # cluster geometry / hardware speed / arrival knobs are drawn from
    # (the sampled DomainSchedule is per-env data riding the faults
    # slot). None = the fixed-cluster program, bit-identical.
    domain_process: Any = None
    # append a per-node geometry channel (capacity / gpus_per_node) so
    # the policy can tell a shrunken node from a busy one. Flat only,
    # like fault_obs; checked in __post_init__.
    domain_obs: bool = False

    def __post_init__(self):
        if self.fault_obs and self.obs_kind != "flat":
            raise ValueError(
                f"fault_obs appends per-node health to the FLAT "
                f"observation; obs_kind={self.obs_kind!r} pins its "
                f"feature layout (train grid/graph fault policies "
                f"without health visibility, or use flat)")
        if self.domain_obs and self.obs_kind != "flat":
            raise ValueError(
                f"domain_obs appends per-node geometry to the FLAT "
                f"observation; obs_kind={self.obs_kind!r} pins its "
                f"feature layout")

    @property
    def n_actions(self) -> int:
        return self.sim.n_actions

    def obs_shape(self) -> tuple[int, ...]:
        s, k, r = self.sim, self.sim.queue_len, self.sim.preempt_len
        if self.obs_kind == "flat":
            n_health = s.n_nodes if self.fault_obs else 0
            n_geom = s.n_nodes if self.domain_obs else 0
            return (s.n_nodes + 4 * k + 4 * r + 2 + n_health + n_geom,)
        if self.obs_kind == "grid":
            return (s.n_nodes + k + r, s.gpus_per_node, 2)
        return (s.n_nodes + k + r, obs_lib.GRAPH_FEATURES)


class EnvState(NamedTuple):
    sim: SimState
    t: jax.Array  # i32 decision-step counter within the episode


class TimeStep(NamedTuple):
    obs: jax.Array
    reward: jax.Array
    done: jax.Array
    action_mask: jax.Array
    info: StepInfo


def build_obs(params: EnvParams, sim: SimState, trace: Trace,
              queue: jax.Array | None = None,
              run_queue: jax.Array | None = None,
              faults: FaultSchedule | None = None) -> jax.Array:
    fn = {"flat": obs_lib.flat_obs, "grid": obs_lib.grid_obs,
          "graph": obs_lib.graph_obs}[params.obs_kind]
    obs = fn(params.sim, sim, trace, params.time_scale, queue, run_queue)
    if params.fault_obs:
        # health appended LAST so the fault-free feature prefix is laid
        # out identically to the pre-chaos observation; faults=None (a
        # fault-trained policy replayed on a clean cluster) reads as
        # every node healthy at full speed
        obs = jnp.concatenate(
            [obs, obs_lib.node_health(params.sim, sim, faults)])
    if params.domain_obs:
        # geometry after health, same append-only contract: the prefix
        # stays laid out identically to the fixed-cluster observation
        obs = jnp.concatenate(
            [obs, obs_lib.node_geometry(params.sim, faults)])
    return obs


def _observe(params: EnvParams, sim: SimState, trace: Trace,
             faults: FaultSchedule | None = None,
             ) -> tuple[jax.Array, jax.Array]:
    """(obs, action_mask) for ``sim``, computing the pending (and, for
    preemptive configs, running) queue once and sharing them between the
    two (VERDICT r1 weak #2)."""
    queue = core.pending_queue(params.sim, sim)
    run_queue = (core.running_queue(params.sim, sim, trace)
                 if params.sim.preempt_len else None)
    return (build_obs(params, sim, trace, queue, run_queue, faults),
            core.action_mask(params.sim, sim, trace, queue, run_queue,
                             faults))


def reset(params: EnvParams, trace: Trace,
          faults: FaultSchedule | None = None) -> tuple[EnvState, TimeStep]:
    # the schedule seeds init_state too: a DomainSchedule's per-node
    # capacity IS the initial free vector (plain FaultSchedule/None keep
    # the static full cluster, bit-identical)
    sim = core.init_state(params.sim, trace, faults)
    state = EnvState(sim=sim, t=jnp.int32(0))
    obs, mask = _observe(params, sim, trace, faults)
    ts = TimeStep(
        obs=obs,
        reward=jnp.float32(0.0),
        done=jnp.bool_(False),
        action_mask=mask,
        info=StepInfo(placed=jnp.bool_(False), dt=jnp.float32(0.0),
                      in_system_before=core.in_system(sim),
                      done=jnp.bool_(False), preempted=jnp.bool_(False),
                      first_placed=jnp.bool_(False)),
    )
    return state, ts


def step(params: EnvParams, state: EnvState, trace: Trace,
         action: jax.Array,
         faults: FaultSchedule | None = None) -> tuple[EnvState, TimeStep]:
    sim_before = state.sim
    sim, info = core.rl_step(params.sim, sim_before, trace, action, faults)
    if params.reward_kind == "fair":
        reward = reward_lib.reward_fair(sim_before, trace, info,
                                        params.n_tenants, params.reward_scale)
    else:
        reward = reward_lib.reward_jct(info, params.reward_scale,
                                       params.place_bonus)
    # the anti-stall preemption charge is a property of the ACTION SPACE
    # (any preemptive config can generate zero-dt actions forever — the
    # pause-the-game exploit, rewards.preempt_charge), not of one reward
    # function, so it applies after whichever reward branch ran
    if params.preempt_cost:
        reward = reward + reward_lib.preempt_charge(info,
                                                    params.preempt_cost)
    t = state.t + 1
    done = info.done | (t >= params.horizon)
    new_state = EnvState(sim=sim, t=t)
    obs, mask = _observe(params, sim, trace, faults)
    ts = TimeStep(obs=obs, reward=reward, done=done, action_mask=mask,
                  info=info)
    return new_state, ts


def auto_reset(stepped_state, ts: TimeStep, fresh_state, fresh_ts: TimeStep,
               ) -> tuple[Any, TimeStep]:
    """Blend a stepped (state, timestep) with a fresh reset on episode end
    (obs/mask from the fresh episode, reward/done from the finished one) —
    the standard fused auto-reset so rollouts never leave the device.
    obs/mask may be pytrees (hierarchical env)."""
    pick = lambda a, b: jax.tree.map(
        lambda x, y: jnp.where(ts.done, x, y), a, b)
    new_state = pick(fresh_state, stepped_state)
    obs = pick(fresh_ts.obs, ts.obs)
    mask = pick(fresh_ts.action_mask, ts.action_mask)
    return new_state, ts._replace(obs=obs, action_mask=mask)


def auto_reset_step(params: EnvParams, state: EnvState, trace: Trace,
                    action: jax.Array, fresh=None,
                    faults: FaultSchedule | None = None,
                    ) -> tuple[EnvState, TimeStep]:
    """Step + fused auto-reset. The reset bundle depends only on the trace
    (and fault schedule), so callers stepping in a loop should compute
    ``fresh = reset(params, trace, faults)`` ONCE outside it and pass it
    here — recomputing a full reset (init + obs + mask) every step was
    round 1's single largest hot-loop redundancy (VERDICT r1 weak #2).
    A mid-episode fault episode auto-resets the same way: the fresh
    episode restarts at clock 0 under the SAME schedule (fault times are
    episode-relative, like submits)."""
    stepped, ts = step(params, state, trace, action, faults)
    fresh_state, fresh_ts = (reset(params, trace, faults)
                             if fresh is None else fresh)
    return auto_reset(stepped, ts, fresh_state, fresh_ts)


# ---- vectorization ----------------------------------------------------------

def stack_traces(traces: list[ArrayTrace],
                 params: EnvParams | SimParams | None = None) -> Trace:
    """Stack per-env trace windows into a batched Trace (leading axis E).
    All windows must share max_jobs (pad at construction). Pass ``params``
    to validate gang sizes against cluster capacity (see
    ``sim.core.validate_trace``)."""
    sim_params = params.sim if isinstance(params, EnvParams) else params
    devs = [Trace.from_array_trace(t, sim_params) for t in traces]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *devs)


@functools.singledispatch
def vec_reset(params, traces: Trace, faults=None) -> tuple[Any, TimeStep]:
    """Vectorized reset, dispatched on the params type (EnvParams here;
    env.hier registers HierParams) so the rollout/algorithms layer is
    env-agnostic. ``faults``: batched per-env FaultSchedule (leading axis
    E, ``sim.faults.stack_fault_schedules``), or None = healthy."""
    raise TypeError(f"no env registered for params type {type(params)}")


@functools.singledispatch
def vec_step(params, state, traces: Trace, actions,
             fresh=None, faults=None) -> tuple[Any, TimeStep]:
    """Vectorized auto-reset step, dispatched on the params type. Pass
    ``fresh = vec_reset(params, traces, faults)`` when stepping in a loop
    so the trace-constant reset bundle is built once, not per step."""
    raise TypeError(f"no env registered for params type {type(params)}")


@vec_reset.register
def _(params: EnvParams, traces: Trace,
      faults=None) -> tuple[EnvState, TimeStep]:
    if faults is None:
        return jax.vmap(lambda tr: reset(params, tr))(traces)
    return jax.vmap(lambda tr, f: reset(params, tr, f))(traces, faults)


@vec_step.register
def _(params: EnvParams, state: EnvState, traces: Trace,
      actions: jax.Array, fresh=None,
      faults=None) -> tuple[EnvState, TimeStep]:
    if faults is None:
        if fresh is None:
            return jax.vmap(lambda s, tr, a: auto_reset_step(params, s, tr, a)
                            )(state, traces, actions)
        return jax.vmap(lambda s, tr, a, f: auto_reset_step(params, s, tr, a, f)
                        )(state, traces, actions, fresh)
    if fresh is None:
        return jax.vmap(
            lambda s, tr, a, fl: auto_reset_step(params, s, tr, a,
                                                 faults=fl)
        )(state, traces, actions, faults)
    return jax.vmap(
        lambda s, tr, a, f, fl: auto_reset_step(params, s, tr, a, f, fl)
    )(state, traces, actions, fresh, faults)
