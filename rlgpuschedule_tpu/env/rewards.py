"""Reward functions (L2).

Capability parity: SURVEY.md §2 "Reward functions" — a JCT-minimizing reward
and a multi-tenant fairness variant (config 3).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..sim.core import SimState, Trace, StepInfo, PENDING, RUNNING


def preempt_charge(info: StepInfo, preempt_cost: float) -> jax.Array:
    """Anti-stall charge for preemptive action spaces: −``preempt_cost``
    per PREEMPTION and per RE-placement (a placement of a job that
    already ran — only possible after a preemption).

    Why it exists (measured): the JCT/fairness rewards only charge
    −f(n)·dt, placements and preemptions cost no simulated time, and
    with preemption available the agent can ALWAYS generate a zero-dt
    action — so an infinite place↔preempt cycle never pays the backlog
    penalty and stalling the clock forever is return-optimal inside the
    horizon (a 3000-iteration ppo-mlp-preempt run completed ZERO of 192
    jobs at replay, greedy AND sampled — the pause-the-game exploit).
    Both legs of the cycle are charged; first placements are never
    charged (see ``reward_jct``'s place_bonus, which still REWARDS
    them).

    Tuning: a genuinely useful demotion pays the charge TWICE over its
    lifetime — once at the preemption and once at the unavoidable later
    re-placement — so the break-even JCT gain per demotion is
    ≈ 2·preempt_cost (in reward units). The magnitude must also
    dominate the discounted per-step cost of real scheduling: with
    γ=0.995 a stalling policy's γ-sum over a 1024-step horizon is
    ≈200·cost, while the discounted JCT penalty of actually draining a
    deep backlog is of order −20 at the default scales — a 0.05 cost
    measurably left stalling OPTIMAL (the cycle survived retraining),
    which is why the preset charges 0.25. This charge is applied by
    ``env.step`` AFTER whichever reward branch ran: the exploit is a
    property of the action space, not of one reward function."""
    replaced = info.placed & ~info.first_placed
    return -preempt_cost * (info.preempted | replaced).astype(jnp.float32)


def reward_jct(info: StepInfo, reward_scale: float,
               place_bonus: float = 0.0) -> jax.Array:
    """Exact JCT objective: Σ JCT = ∫ n_in_system(t) dt, so accumulating
    ``-dt · n_in_system`` over decision intervals makes the (undiscounted)
    episode return equal −Σ JCT / scale.

    ``place_bonus`` adds a small reward per FIRST placement of a job
    (``info.first_placed``): the shaping potential is φ = bonus ·
    #{jobs ever started}, which only ever increments and is bounded by the
    job count, so the bonus telescopes to a per-episode constant for every
    policy that schedules all jobs — including under the preemptive action
    space, where paying on every placement would let a zero-time
    preempt→re-place cycle farm unbounded reward. It gives the actor
    immediate credit for admitting work instead of waiting for that credit
    to propagate through the critic; empirically this breaks the
    idle-until-drained local optimum (policy no-ops ~50% of feasible steps
    without it). NOTE: with episodes cut at the env horizon the telescoping
    argument is approximate at the boundary — eval replay (eval.py) scores
    policies with the unshaped JCT objective, so reported JCT numbers are
    unaffected.

    Preemptive action spaces additionally need the anti-stall
    :func:`preempt_charge`, applied by ``env.step`` after this (or the
    fairness) reward — see its docstring for the exploit and tuning."""
    base = -(info.dt * info.in_system_before.astype(jnp.float32)) / reward_scale
    if place_bonus:
        return base + place_bonus * info.first_placed.astype(jnp.float32)
    return base


def tenant_counts(state: SimState, trace: Trace, n_tenants: int) -> jax.Array:
    """In-system job count per tenant, [n_tenants]."""
    insys = (state.status == PENDING) | (state.status == RUNNING)
    onehot = jax.nn.one_hot(trace.tenant, n_tenants, dtype=jnp.float32)
    return jnp.sum(onehot * insys[:, None].astype(jnp.float32), axis=0)


def reward_fair(state_before: SimState, trace: Trace, info: StepInfo,
                n_tenants: int, reward_scale: float) -> jax.Array:
    """Multi-tenant fairness: accumulate −dt · Σ_t n_t² (n_t = tenant t's
    in-system count over the interval). The quadratic makes backlog
    concentrated on one tenant cost more than the same backlog spread evenly
    (Σ n_t² is minimized at equal shares for fixed Σ n_t), so the policy is
    pushed toward finishing jobs AND serving tenants evenly — the fairness
    pressure of config 3's multi-tenant reward."""
    n_t = tenant_counts(state_before, trace, n_tenants)
    return -(info.dt * jnp.sum(n_t * n_t)) / reward_scale
