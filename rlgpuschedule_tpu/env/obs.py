"""Observation builders (L2): flat / occupancy-grid / topology-graph.

Capability parity: SURVEY.md §2 "Observation builders" — node×GPU occupancy
grid (image-like, CNN config 2), flat features (MLP config 1), topology graph
+ node features (GNN config 4). All are fixed-shape pure functions of
(SimState, Trace) so they live inside the jitted rollout.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..sim.core import (SimParams, SimState, Trace, pending_queue,
                        running_queue, RUNNING, in_system, utilization)
from ..sim.faults import FaultSchedule, node_up


def node_health(params: SimParams, state: SimState,
                faults: FaultSchedule | None = None) -> jax.Array:
    """Per-node effective-speed feature [N]: 1.0 = healthy full speed,
    ``1/slowdown`` = straggling, 0.0 = drained at the current clock — the
    single channel a policy needs to route around sick nodes. With
    ``faults=None`` (clean replay of a fault-trained policy) every node
    reads healthy."""
    if faults is None:
        return jnp.ones((params.n_nodes,), jnp.float32)
    up = node_up(faults, state.clock)
    return jnp.where(up, 1.0 / faults.slowdown, 0.0).astype(jnp.float32)


def node_geometry(params: SimParams, faults=None) -> jax.Array:
    """Per-node capacity feature [N]: usable GPUs / gpus_per_node — the
    geometry channel a domain-randomized policy needs to tell a shrunken
    (or absent) node from a merely busy one. Reads the ``capacity``
    carried by a ``domains.DomainSchedule`` in the faults slot; a plain
    FaultSchedule or ``faults=None`` (clean replay) reads as a full
    homogeneous cluster."""
    cap = getattr(faults, "capacity", None)
    if cap is None:
        return jnp.ones((params.n_nodes,), jnp.float32)
    return jnp.asarray(cap, jnp.float32) / params.gpus_per_node


def queue_features(params: SimParams, state: SimState, trace: Trace,
                   queue: jax.Array | None = None) -> jax.Array:
    """Per-queue-slot features [K, 4]: demand/capacity, waiting time,
    service demand (both in units of ``time_scale`` via the caller), valid.
    Pass a precomputed ``pending_queue`` to share it with the action mask
    (the env step computes it once — VERDICT r1 weak #2)."""
    if queue is None:
        queue = pending_queue(params, state)               # [K]
    jc = jnp.clip(queue, 0, params.max_jobs - 1)
    occupied = queue >= 0
    valid = occupied.astype(jnp.float32)
    demand = trace.gpus[jc].astype(jnp.float32) / params.capacity * valid
    # where (not *valid): padding rows have submit=+inf, and (clock-inf)*0
    # would be NaN and poison the whole vmapped obs batch
    wait = jnp.where(occupied, state.clock - trace.submit[jc], 0.0)
    service = jnp.where(occupied, trace.duration[jc], 0.0)
    return jnp.stack([demand, wait, service, valid], axis=1)


def run_features(params: SimParams, state: SimState, trace: Trace,
                 time_scale: float, run_queue: jax.Array | None = None,
                 ) -> jax.Array:
    """Per-preempt-slot features [R, 4] over :func:`running_queue` (most
    attained GPU-service first): demand/capacity, executed seconds,
    remaining seconds (both tanh-squashed by ``time_scale``), valid — what
    the agent needs to judge a demotion."""
    if run_queue is None:
        run_queue = running_queue(params, state, trace)     # [R]
    jc = jnp.clip(run_queue, 0, params.max_jobs - 1)
    occupied = run_queue >= 0
    valid = occupied.astype(jnp.float32)
    demand = trace.gpus[jc].astype(jnp.float32) / params.capacity * valid
    executed = jnp.where(occupied,
                         trace.duration[jc] - state.remaining[jc], 0.0)
    remaining = jnp.where(occupied, state.remaining[jc], 0.0)
    return jnp.stack([demand, jnp.tanh(executed / time_scale),
                      jnp.tanh(remaining / time_scale), valid], axis=1)


def flat_obs(params: SimParams, state: SimState, trace: Trace,
             time_scale: float, queue: jax.Array | None = None,
             run_queue: jax.Array | None = None) -> jax.Array:
    """[N + 4K + 4R + 2] vector: per-node free fraction, queue features,
    running-job features (preemptive configs, R = preempt_len),
    utilization, normalized in-system count."""
    free_frac = state.free.astype(jnp.float32) / params.gpus_per_node
    qf = queue_features(params, state, trace, queue)
    qf = qf.at[:, 1].set(jnp.tanh(qf[:, 1] / time_scale))
    qf = qf.at[:, 2].set(jnp.tanh(qf[:, 2] / time_scale))
    util = utilization(params, state)
    n_insys = in_system(state) / params.max_jobs
    parts = [free_frac, qf.reshape(-1)]
    if params.preempt_len:
        parts.append(run_features(params, state, trace, time_scale,
                                  run_queue).reshape(-1))
    parts.append(jnp.stack([util, n_insys]))
    return jnp.concatenate(parts).astype(jnp.float32)


def grid_obs(params: SimParams, state: SimState, trace: Trace,
             time_scale: float, queue: jax.Array | None = None,
             run_queue: jax.Array | None = None) -> jax.Array:
    """Occupancy image [N + K (+ R), G, 2] (the reference's CNN input shape
    class — cluster occupancy stacked over queue-demand rows, SURVEY.md §2):

    cluster rows n<N:  ch0 = GPU slot occupied; ch1 = PER-SLOT normalized
                       remaining service: each job's remaining painted on
                       the slots it holds, slots sorted longest-remaining
                       first within a node (a canonical waterfall — GPU
                       slots are fungible, so sorting removes a spurious
                       permutation symmetry). VERDICT r4 weak #5: the
                       earlier node-AVERAGE hid per-job boundaries within
                       a node; the waterfall strictly generalizes it (mean-
                       pooling ch1 recovers the average) while exposing
                       how many distinct jobs a node hosts and how skewed
                       their remaining work is — what drain-regime packing
                       decisions actually need.
    queue rows:        ch0 = demand bar (capped at G); ch1 = normalized
                       service demand painted on the bar.
    preempt rows (preemptive configs): ch0 = demand bar of running-queue
                       slots; ch1 = normalized remaining service on the bar.
    """
    N, G, K = params.n_nodes, params.gpus_per_node, params.queue_len
    used = (params.gpus_per_node - state.free).astype(jnp.float32)    # [N]
    slots = jnp.arange(G, dtype=jnp.float32)                          # [G]
    occ = (slots[None, :] < used[:, None]).astype(jnp.float32)        # [N,G]
    running = (state.status == RUNNING).astype(jnp.float32)
    val = running * jnp.tanh(state.remaining / time_scale)            # [J]
    order = jnp.argsort(-val)                                         # [J]
    # slot s of node n belongs to the first job (longest-remaining-first)
    # whose cumulative GPU count on n exceeds s
    cum = jnp.cumsum(state.alloc.astype(jnp.int32)[order, :], axis=0)  # [J,N]
    sidx = jnp.arange(G, dtype=cum.dtype)
    idx = jax.vmap(lambda c: jnp.searchsorted(c, sidx, side="right"))(
        cum.T)                                                        # [N,G]
    J = params.max_jobs
    rem_img = val[order][jnp.clip(idx, 0, J - 1)] * (idx < J)         # [N,G]
    cluster = jnp.stack([occ, occ * rem_img], axis=-1)                # [N,G,2]

    if queue is None:
        queue = pending_queue(params, state)
    jc = jnp.clip(queue, 0, params.max_jobs - 1)
    valid = (queue >= 0).astype(jnp.float32)
    demand = jnp.minimum(trace.gpus[jc], G).astype(jnp.float32) * valid
    bar = (slots[None, :] < demand[:, None]).astype(jnp.float32)      # [K,G]
    service = jnp.tanh(trace.duration[jc] / time_scale) * valid
    qimg = jnp.stack([bar, bar * service[:, None]], axis=-1)          # [K,G,2]
    parts = [cluster, qimg]
    if params.preempt_len:
        if run_queue is None:
            run_queue = running_queue(params, state, trace)
        rc = jnp.clip(run_queue, 0, params.max_jobs - 1)
        rvalid = (run_queue >= 0).astype(jnp.float32)
        rdemand = jnp.minimum(trace.gpus[rc], G).astype(jnp.float32) * rvalid
        rbar = (slots[None, :] < rdemand[:, None]).astype(jnp.float32)
        rrem = jnp.tanh(state.remaining[rc] / time_scale) * rvalid
        parts.append(jnp.stack([rbar, rbar * rrem[:, None]], axis=-1))
    return jnp.concatenate(parts, axis=0)                     # [N+K+R,G,2]


def build_adjacency(n_nodes: int, queue_len: int,
                    nodes_per_rack: int | None = None,
                    preempt_len: int = 0) -> np.ndarray:
    """Static topology adjacency [V, V], V = N + K + R: cluster nodes
    connected within a rack (all-to-all if ``nodes_per_rack`` is None),
    every queue slot and every running (preempt) slot connected to every
    cluster node (placement / eviction candidates), self-loops. Static
    because cluster topology never changes — only features do."""
    V = n_nodes + queue_len + preempt_len
    a = np.zeros((V, V), np.float32)
    if nodes_per_rack is None:
        a[:n_nodes, :n_nodes] = 1.0
    else:
        for r0 in range(0, n_nodes, nodes_per_rack):
            r1 = min(r0 + nodes_per_rack, n_nodes)
            a[r0:r1, r0:r1] = 1.0
    a[:n_nodes, n_nodes:] = 1.0   # node ↔ {queue, running} bipartite
    a[n_nodes:, :n_nodes] = 1.0
    np.fill_diagonal(a, 1.0)
    return a


GRAPH_FEATURES = 5


def graph_obs(params: SimParams, state: SimState, trace: Trace,
              time_scale: float, queue: jax.Array | None = None,
              run_queue: jax.Array | None = None) -> jax.Array:
    """Node-feature matrix [N + K (+ R), 5] over the static topology graph:
    cluster rows: [free_frac, used_frac, avg_remaining, 1, 0];
    queue rows:   [demand/capacity, wait, service, 0, 1] (times tanh-squashed);
    preempt rows: [demand/capacity, executed, remaining, 0, 0] (type flags
    both 0 distinguish running slots from cluster/queue rows).
    The adjacency comes from :func:`build_adjacency` (static)."""
    N, G = params.n_nodes, params.gpus_per_node
    free_frac = state.free.astype(jnp.float32) / G
    used = (G - state.free).astype(jnp.float32)
    running = (state.status == RUNNING).astype(jnp.float32)
    rem_n = jnp.einsum("jn,j->n", state.alloc.astype(jnp.float32),
                       running * jnp.tanh(state.remaining / time_scale))
    rem_avg = rem_n / jnp.maximum(used, 1.0)
    ones = jnp.ones((N,), jnp.float32)
    cluster = jnp.stack([free_frac, 1.0 - free_frac, rem_avg,
                         ones, 0.0 * ones], axis=1)            # [N,5]
    qf = queue_features(params, state, trace, queue)           # [K,4]
    wait = jnp.tanh(qf[:, 1] / time_scale)
    service = jnp.tanh(qf[:, 2] / time_scale)
    zeros = jnp.zeros((params.queue_len,), jnp.float32)
    queue = jnp.stack([qf[:, 0], wait, service, zeros, qf[:, 3]], axis=1)
    parts = [cluster, queue]
    if params.preempt_len:
        rf = run_features(params, state, trace, time_scale, run_queue)
        rzeros = jnp.zeros((params.preempt_len,), jnp.float32)
        parts.append(jnp.stack([rf[:, 0], rf[:, 1], rf[:, 2],
                                rzeros, rzeros], axis=1))
    return jnp.concatenate(parts, axis=0)                      # [N+K+R,5]
