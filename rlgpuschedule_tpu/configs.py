"""Named experiment configs (L6).

Capability parity: SURVEY.md §2 "Config/flags" and §0 — dataclass configs
with named presets matching the five driver-specified capability configs
exactly (SURVEY.md §5 "Config / flag system").
"""
from __future__ import annotations

import dataclasses
from typing import Literal

from .algos.a2c import A2CConfig
from .algos.ppo import PPOConfig


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    name: str
    algo: Literal["ppo", "a2c"] = "ppo"
    # cluster
    n_nodes: int = 8
    gpus_per_node: int = 8
    # trace source. "philly"/"pai" parse a real CSV at trace_path;
    # "philly-proxy"/"pai-proxy" generate a seeded trace with the published
    # Philly/PAI workload statistics (traces/philly_proxy.py) so the
    # large-cluster configs run end-to-end with no external file
    # (VERDICT r2 missing #3).
    trace: Literal["synthetic", "philly", "pai",
                   "philly-proxy", "pai-proxy"] = "synthetic"
    trace_path: str | None = None
    trace_load: float = 1.1             # proxy traces: offered load target
    # generated traces (synthetic / *-proxy): pin the SOURCE trace size in
    # jobs. None = sized to one window-streaming pass over the env batch
    # (window_jobs * max(n_envs, 8), floored at 1024/4096). The north-star
    # full-Philly run pins this at 100k+ so "the whole trace" is explicit
    # rather than implied by the batch geometry.
    source_jobs: int | None = None
    arrival_rate: float = 0.08          # synthetic: jobs/sec
    mean_duration: float = 600.0        # synthetic: log-normal mean
    window_jobs: int = 64               # jobs per episode window (max_jobs)
    # env
    n_envs: int = 4
    queue_len: int = 8
    n_placements: int = 1
    preempt_len: int = 0                # >0 = preemptive RL action space
    n_pods: int = 1                     # >1 = hierarchical env (config 5)
    obs_kind: Literal["flat", "grid", "graph"] = "flat"
    reward_kind: Literal["jct", "fair"] = "jct"
    n_tenants: int = 1
    nodes_per_rack: int | None = None   # graph topology granularity
    horizon: int = 512
    time_scale: float = 600.0
    reward_scale: float = 10_000.0
    place_bonus: float = 0.05   # shaping vs the idle local optimum (rewards.py)
    # preemptive configs: reward charge per preemption AND per
    # re-placement. Without it the agent can stall the clock forever in
    # a zero-dt place<->preempt cycle (the pause-the-game exploit,
    # measured: a 3000-iteration preempt run completed ZERO jobs at
    # replay); an under-priced charge (0.05) measurably left stalling
    # return-optimal under discounting — see rewards.preempt_charge for
    # the magnitude analysis behind 0.25.
    preempt_cost: float = 0.25
    # training
    ppo: PPOConfig = PPOConfig()
    a2c: A2CConfig = A2CConfig()
    iterations: int = 100
    seed: int = 0
    # window streaming: every N iterations rotate every env onto the next
    # windows of the source-trace tiling (and reset episodes), so a long
    # run trains on the WHOLE trace instead of replaying the first
    # n_envs windows forever. 0 = static windows (round-1 behavior).
    resample_every: int = 0
    # backlog-drain curriculum: this fraction of the env batch trains on
    # DRAINED copies of its windows (every submit zeroed, so the episode
    # is "drain a full backlog"). Ordering/packing decisions carry the
    # whole JCT signal there — measured in round 3, a drain-trained
    # config-1 policy beats oracle SJF on drain episodes and transfers to
    # streaming windows (vs_tiresias 0.81), while pure streaming training
    # plateaus at random-order quality (credit assignment: a placement's
    # JCT consequence lands hundreds of steps later).
    drain_frac: float = 0.0
    # cluster chaos (sim.faults): train on a seeded in-simulator fault
    # distribution — per-env FaultSchedules (node drains, drain storms,
    # stragglers) sampled from this named regime (FAULT_REGIMES) and
    # threaded through the rollout next to the traces. Flat configs also
    # expose per-node health in the observation so the policy can LEARN
    # to route around drains. None = permanently healthy cluster.
    faults: str | None = None
    # domain randomization (domains.schedule): train across a named
    # scenario DISTRIBUTION (DOMAIN_REGIMES) — per-env cluster geometry,
    # hardware speed, and arrival-process draws threaded through the
    # rollout as data next to the traces, composing with cfg.faults.
    # None = the single fixed cluster, bit-identical.
    domains: str | None = None

    @property
    def total_gpus(self) -> int:
        return self.n_nodes * self.gpus_per_node


# The five driver-specified capability configs (SURVEY.md §0, `[B]`).
CONFIGS: dict[str, ExperimentConfig] = {}


def _register(cfg: ExperimentConfig) -> ExperimentConfig:
    CONFIGS[cfg.name] = cfg
    return cfg


# 1. PPO-MLP scheduler, 64-GPU synthetic Poisson trace, 4 vectorized envs.
PPO_MLP_SYNTH64 = _register(ExperimentConfig(
    name="ppo-mlp-synth64", algo="ppo", n_nodes=8, gpus_per_node=8,
    trace="synthetic", n_envs=4, obs_kind="flat"))

# 2. PPO-CNN on Microsoft Philly trace, 512-GPU simulated cluster.
# Ships on the Philly-statistics proxy so it runs with no external CSV
# (none can exist on this machine); pass --trace philly --trace-path x.csv
# to train on the real trace instead.
PPO_CNN_PHILLY512 = _register(ExperimentConfig(
    name="ppo-cnn-philly512", algo="ppo", n_nodes=64, gpus_per_node=8,
    trace="philly-proxy", n_envs=8, obs_kind="grid", window_jobs=128,
    queue_len=16, horizon=1024))

# 3. A2C multi-actor on Alibaba PAI trace, multi-tenant fairness reward.
# Same proxy arrangement as config 2 (PAI-statistics preset).
A2C_PAI_FAIR = _register(ExperimentConfig(
    name="a2c-pai-fair", algo="a2c", n_nodes=16, gpus_per_node=8,
    trace="pai-proxy", n_envs=16, obs_kind="flat", reward_kind="fair",
    n_tenants=8, window_jobs=96))

# 4. GNN policy over cluster topology, gang-scheduling + placement actions.
GNN_GANG_PLACE = _register(ExperimentConfig(
    name="gnn-gang-place", algo="ppo", n_nodes=16, gpus_per_node=8,
    trace="synthetic", n_envs=4, obs_kind="graph", n_placements=2,
    nodes_per_rack=4, window_jobs=64))

# Preemptive variant of config 1: the agent can also evict the R most-
# attained running jobs (sim.core.running_queue), like Tiresias' demotions
# but learned (VERDICT r1 missing #5 — Tiresias preempts, so a policy that
# cannot is handicapped on overloaded traces).
PPO_MLP_PREEMPT = _register(ExperimentConfig(
    name="ppo-mlp-preempt", algo="ppo", n_nodes=8, gpus_per_node=8,
    trace="synthetic", n_envs=4, obs_kind="flat", preempt_len=4))

# 5. Hierarchical multi-agent across 4 pods + PBT: each population member
# IS a hierarchical agent (top-level router + shared per-pod placers) over
# a 4-pod cluster; PopulationExperiment runs a PBT population of these
# (parallel.population / parallel.pbt).
HIER_PBT_MEMBER = _register(ExperimentConfig(
    name="hier-pbt-member", algo="ppo", n_nodes=16, gpus_per_node=8,
    n_pods=4, trace="synthetic", n_envs=4, obs_kind="flat",
    window_jobs=64))


class ModeCombinationError(ValueError):
    """Two requested run modes are mutually unsupported (the single
    refusal format `train` reports — see :data:`MODE_REFUSALS`)."""


# How each mode name is spelled to the user in refusal messages.
MODE_FLAGS: dict[str, str] = {
    "async": "--async",
    "pbt": "--pbt",
    "faults": "--faults",
    "domains": "--domains",
    "fault_injection": "--fault",
    "fused_chunk": "--fused-chunk",
    "rollbacks": "--max-rollbacks",
    "hier": "hierarchical config (n_pods > 1)",
    "shard_map": "shard_map/axis_name build",
    "mesh": "--mesh",
    "vtrace": "--correction vtrace",
    "sync": "the synchronous loop (no --async)",
    "router": "--engines > 1 (multi-engine serving router)",
    "continual": "--continual LOGDIR (flight-log retraining)",
}

# THE mode-combination refusal matrix — every pairwise refusal `train`
# (or a programmatic caller) enforces, in one place with one error
# format, instead of the per-flag sys.exit checks that used to be
# scattered through train.main. Order within a pair is cosmetic; the
# check is symmetric. Each entry: (mode_a, mode_b, why-it-refuses).
MODE_REFUSALS: tuple[tuple[str, str, str], ...] = (
    # async x pbt was refused here until ISSUE 12: AsyncPopulationRunner
    # now runs PBT exploit/explore at drained-queue barriers, with
    # V-trace keeping stale batches from skewing the fitness ranking
    ("vtrace", "sync",
     "importance correction divides the target policy by the behavior "
     "policy; the sync loop collects every batch on-policy (ratios are "
     "identically 1), so --correction vtrace without --async would only "
     "buy the extra forward pass — the bit-identity contract makes this "
     "a no-op, refuse it loudly instead"),
    ("vtrace", "hier",
     "the hierarchical joint log-prob sums router+placer heads; the "
     "V-trace ratio recompute has not been validated against the "
     "multi-head action distribution yet"),
    ("async", "fused_chunk",
     "the async engine already overlaps phases — pick one"),
    ("async", "rollbacks",
     "the divergence watchdog is sync-path-only for now"),
    ("async", "fault_injection",
     "fault injection hooks the sync loop's iteration boundary"),
    ("async", "mesh",
     "the async engine resolves its own actor/learner submeshes from "
     "the unified mesh"),
    # pbt x faults was refused here until ISSUE 14: the population step
    # now threads per-member [P, E] fault schedules (seeded (seed,
    # member, env)) through the vmapped member rollout
    ("pbt", "domains",
     "per-member domain draws would need member-indexed trace windows "
     "through the population stack; sample domain diversity across "
     "single-run seeds instead"),
    ("hier", "domains",
     "domain schedules carry per-node capacity through the flat sim "
     "path only; the pod-sharded hierarchical env has no geometry "
     "threading yet"),
    ("pbt", "fused_chunk",
     "the PBT loop interleaves host-side exploit/explore between steps"),
    ("pbt", "mesh",
     "--pbt builds the population mesh from the unified mesh "
     "automatically"),
    ("hier", "faults",
     "sim faults thread per-node health through flat observations only"),
    ("shard_map", "pbt",
     "the population step is a GSPMD vmap, not an axis-name program"),
    ("shard_map", "async",
     "the async engine jits per-group GSPMD programs, not shard_map"),
    ("shard_map", "fused_chunk",
     "run_fused jits the raw step; an axis-name step needs "
     "dp.shard_map_train"),
    ("shard_map", "mesh",
     "rule-table shardings are GSPMD in/out_shardings; the axis-name "
     "path wires its own specs in dp.shard_map_train"),
    ("router", "hier",
     "the engine router resolves one single-device engine per data-axis "
     "device; a hierarchical (n_pods > 1) policy's router+placer heads "
     "have not been validated under per-engine replicated serving — "
     "serve hierarchical configs single-engine until they are"),
    # continual mode (ISSUE 19 flywheel) replaces simulator rollouts
    # with logged served traffic: the data source IS the mode, so every
    # combination that reshapes the rollout/update loop is refused
    ("continual", "pbt",
     "continual ingest folds ONE flight log into one learner's "
     "pseudo-trajectories; a population would train every member on "
     "the same behavior stream (no per-member exploration signal)"),
    ("continual", "async",
     "the async engine overlaps simulator rollout collection with the "
     "update; continual mode has no rollout to overlap — the flight "
     "log is read once up front"),
    ("continual", "hier",
     "logged rows carry the flat policy's action heads; the "
     "hierarchical joint log-prob has not been validated against "
     "flight-log replay (same gap as vtrace x hier)"),
    ("continual", "fused_chunk",
     "run_fused scans the simulator train step; continual updates run "
     "their own jitted learn step over a fixed ingested batch"),
)


def _validate_refusal_table() -> None:
    """The table is validated at import: a typo'd mode name would
    otherwise silently never refuse anything."""
    for a, b, why in MODE_REFUSALS:
        for m in (a, b):
            if m not in MODE_FLAGS:
                raise AssertionError(
                    f"MODE_REFUSALS names unknown mode {m!r} (known: "
                    f"{sorted(MODE_FLAGS)})")
        if a == b or not why:
            raise AssertionError(f"malformed refusal entry {(a, b, why)!r}")


_validate_refusal_table()


def validate_mode_combination(active: dict[str, bool]) -> None:
    """Raise :class:`ModeCombinationError` if any two ACTIVE modes are a
    refused pair. ``active`` maps mode names (:data:`MODE_FLAGS` keys) to
    whether the run requests them; unknown names raise (fail-loud — a
    misspelled key would otherwise never be checked)."""
    unknown = set(active) - set(MODE_FLAGS)
    if unknown:
        raise KeyError(f"unknown mode name(s) {sorted(unknown)}; known: "
                       f"{sorted(MODE_FLAGS)}")
    for a, b, why in MODE_REFUSALS:
        if active.get(a) and active.get(b):
            raise ModeCombinationError(
                f"unsupported mode combination: {MODE_FLAGS[a]} × "
                f"{MODE_FLAGS[b]} — {why}")


def repro_tuple(cfg: ExperimentConfig, ckpt_dir: str | None = None,
                ckpt_step: int | None = None) -> dict:
    """The reproducibility tuple every evaluate/serve JSON carries: the
    resolved config fields that determine a replay plus the checkpoint
    provenance — enough to regenerate any reported row exactly. ONE
    definition shared by ``evaluate`` and ``serve`` so serving numbers
    are reproducible the same way evaluation numbers are (PR 7).

    ``ckpt_step`` must be the RESOLVED restored step
    (``Checkpointer.last_restored_step``), not the requested one: the
    integrity fallback may restore an older retained step than asked
    for, and the tuple exists to name what actually ran."""
    return {"config": cfg.name, "seed": cfg.seed, "trace": cfg.trace,
            "trace_path": cfg.trace_path, "trace_load": cfg.trace_load,
            "source_jobs": cfg.source_jobs, "n_envs": cfg.n_envs,
            "n_nodes": cfg.n_nodes, "gpus_per_node": cfg.gpus_per_node,
            "window_jobs": cfg.window_jobs, "queue_len": cfg.queue_len,
            "horizon": cfg.horizon, "obs_kind": cfg.obs_kind,
            "drain_frac": cfg.drain_frac, "faults": cfg.faults,
            "domains": cfg.domains,
            "ckpt_dir": ckpt_dir, "ckpt_step": ckpt_step}
