"""Crash-safe served-traffic flight log: the flywheel's write path.

The serving data plane (PR 17's arena) already holds every decision's
inputs and outputs in preallocated host slabs for the lifetime of one
dispatch; this module gives those rows somewhere durable to go. The
:class:`FlightLogWriter` owns ONE recycled shard buffer (arena-style:
allocated once from the first batch's shapes, reused for every shard —
the hot path is memcpy into a slab, never an allocation); when the
buffer fills it is **sealed**: written to a temp file, atomically
renamed to ``shard-NNNNNN.npz``, and only then described by a crc32
sidecar under ``.crc/`` (the Checkpointer's sidecar pattern,
:mod:`..checkpoint`). The payload-then-sidecar ordering is the torn-tail
contract: a crash can leave at most a trailing shard without a valid
sidecar, and :func:`read_flight_log` drops exactly that tail (flagged,
counted) while a bad crc ANYWHERE EARLIER is corruption and raises.

Row schema (fixed per log; enumerated pytree leaves):

==============  =======================================================
column          meaning
==============  =======================================================
``obs<i>``      observation leaves, one row per served request
``mask<i>``     action-mask leaves
``act<i>``      the served greedy action leaves (what the client got)
``log_prob``    joint behavior log-prob of the served action (f32) —
                straight out of the engine's compiled decision program
                (:func:`..decision.policy_decision_full`), never
                recomputed post-hoc
``value``       the behavior critic's estimate (f32) — continual
                training bootstraps its V-trace scan with it
``stall``       the client's consecutive-zero-dt count (i32)
``outcome``     deadline outcome (i8): 0 = no deadline, 1 = met,
                2 = served late (resolved past its SLO but not shed)
``req_id``      request-causality id (i64, ISSUE 20): the 64-bit key
                minted at the front door — joins a logged row back to
                its serve-side span/instant events, the request's
                wire frames, and any canary verdict that replayed it
                (0 = pre-v2 row / id-less submit)
``policy_step`` scalar i64: the behavior policy's train step (staleness
                numerator for the ingest trust region)
==============  =======================================================

Conservation: shed requests never reach a dispatch, so the writer's
``rows_logged`` equals the server's ``served`` count EXACTLY — the same
structural submitted == served + shed contract the serving tier pins
(tests assert ``rows_logged == served``, crc-verified on reload).
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
from typing import Any

import numpy as np

from ..checkpoint import _crc32_file

_SHARD_RE = re.compile(r"^shard-(\d{6})\.npz$")


def shard_name(seq: int) -> str:
    return f"shard-{seq:06d}.npz"


def _sidecar_path(directory: str, seq: int) -> str:
    return os.path.join(directory, ".crc", f"shard-{seq:06d}.json")


class FlightLogError(RuntimeError):
    """Base: the flight log on disk cannot be used as asked."""


class FlightLogCorruptError(FlightLogError):
    """A NON-tail shard failed its crc/sidecar check: interior
    corruption, not a torn tail — refusing to silently drop data."""


def _leaves(tree: Any) -> "list[np.ndarray]":
    import jax
    return [np.asarray(l) for l in jax.tree.leaves(tree)]


class FlightLogWriter:
    """Appends served rows into one recycled buffer; seals full (or
    final partial) buffers to crc-sidecar'd shards.

    Thread-safe: dispatcher pumps append concurrently under one lock
    (the copy is slab-to-slab memcpy, same cost class as the arena's own
    row writes). ``durable=True`` fsyncs each sealed payload and sidecar
    before the atomic rename publishes it, so a sealed shard survives
    process kill AND power loss; the default rides the page cache (a
    process crash still loses nothing — the rename is the publish)."""

    def __init__(self, directory: str, capacity: int = 4096,
                 policy_step: int = 0, registry=None, bus=None,
                 durable: bool = False):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        os.makedirs(os.path.join(self.directory, ".crc"), exist_ok=True)
        self.capacity = int(capacity)
        self.policy_step = int(policy_step)
        self.durable = bool(durable)
        self._bus = bus
        self._lock = threading.Lock()
        self._obs: "list[np.ndarray] | None" = None
        self._mask: "list[np.ndarray] | None" = None
        self._act: "list[np.ndarray] | None" = None
        self._lp = np.zeros(capacity, np.float32)
        self._value = np.zeros(capacity, np.float32)
        self._stall = np.zeros(capacity, np.int32)
        self._outcome = np.zeros(capacity, np.int8)
        self._req = np.zeros(capacity, np.int64)
        self._n = 0
        self._seq = 0
        self._seq_rows = 0       # rows already sealed to disk
        self._closed = False
        if registry is not None:
            self._c_rows = registry.counter(
                "flywheel_rows_logged_total",
                "served decision rows appended to the flight log "
                "(conservation: must equal the server's served count)")
            self._c_shards = registry.counter(
                "flywheel_shards_sealed_total",
                "flight-log shards sealed to disk with crc sidecars")
        else:
            self._c_rows = self._c_shards = None

    # ---- introspection ----------------------------------------------

    @property
    def rows_logged(self) -> int:
        """Total rows accepted (sealed + still buffered)."""
        with self._lock:
            return self._seq_rows + self._n

    @property
    def shards_sealed(self) -> int:
        with self._lock:
            return self._seq

    # ---- append ------------------------------------------------------

    def _alloc(self, obs_l, mask_l, act_l) -> None:
        cap = self.capacity
        mk = lambda ls: [np.zeros((cap,) + l.shape[1:], l.dtype)
                         for l in ls]
        self._obs, self._mask, self._act = mk(obs_l), mk(mask_l), mk(act_l)

    def append_batch(self, obs: Any, mask: Any, actions: Any,
                     log_prob, value, stall, outcome,
                     req_id=None) -> None:
        """Append one dispatch's rows (leading axis = rows; pytrees for
        ``obs``/``mask``/``actions``). Copies into the recycled buffer;
        seals as many full shards as the batch fills. ``req_id`` is the
        per-row causality-id column (``None`` — id-less callers —
        writes zeros, the "unassigned" sentinel)."""
        obs_l, mask_l, act_l = _leaves(obs), _leaves(mask), _leaves(actions)
        lp = np.asarray(log_prob, np.float32)
        val = np.asarray(value, np.float32)
        st = np.asarray(stall, np.int32)
        oc = np.asarray(outcome, np.int8)
        n = int(lp.shape[0])
        rid = (np.zeros(n, np.int64) if req_id is None
               else np.asarray(req_id, np.int64))
        if rid.shape != (n,):
            raise ValueError(
                f"req_id must be one id per row: got shape {rid.shape} "
                f"for {n} rows")
        with self._lock:
            if self._closed:
                raise FlightLogError("FlightLogWriter is closed")
            if self._obs is None:
                self._alloc(obs_l, mask_l, act_l)
            off = 0
            while off < n:
                m = min(self.capacity - self._n, n - off)
                s, e = self._n, self._n + m
                for dst, src in zip(self._obs, obs_l):
                    dst[s:e] = src[off:off + m]
                for dst, src in zip(self._mask, mask_l):
                    dst[s:e] = src[off:off + m]
                for dst, src in zip(self._act, act_l):
                    dst[s:e] = src[off:off + m]
                self._lp[s:e] = lp[off:off + m]
                self._value[s:e] = val[off:off + m]
                self._stall[s:e] = st[off:off + m]
                self._outcome[s:e] = oc[off:off + m]
                self._req[s:e] = rid[off:off + m]
                self._n += m
                off += m
                if self._n == self.capacity:
                    self._seal_locked()
            if self._c_rows is not None:
                self._c_rows.inc(n)

    # ---- seal --------------------------------------------------------

    def _seal_locked(self) -> None:
        n, seq = self._n, self._seq
        if n == 0:
            return
        cols: "dict[str, np.ndarray]" = {}
        for i, l in enumerate(self._obs):
            cols[f"obs{i}"] = l[:n]
        for i, l in enumerate(self._mask):
            cols[f"mask{i}"] = l[:n]
        for i, l in enumerate(self._act):
            cols[f"act{i}"] = l[:n]
        cols["log_prob"] = self._lp[:n]
        cols["value"] = self._value[:n]
        cols["stall"] = self._stall[:n]
        cols["outcome"] = self._outcome[:n]
        cols["req_id"] = self._req[:n]
        cols["policy_step"] = np.int64(self.policy_step)
        path = os.path.join(self.directory, shard_name(seq))
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **cols)
            f.flush()
            if self.durable:
                os.fsync(f.fileno())
        crc = _crc32_file(tmp)
        # publish payload FIRST, sidecar second: a crash between the two
        # leaves a sidecar-less tail shard, which the reader treats as
        # torn (dropped + flagged) — never a sidecar naming a missing or
        # half-written payload
        os.replace(tmp, path)
        side = _sidecar_path(self.directory, seq)
        stmp = f"{side}.tmp.{os.getpid()}"
        with open(stmp, "w") as f:
            json.dump({"file": shard_name(seq), "crc32": crc, "rows": n,
                       "policy_step": self.policy_step}, f)
            f.flush()
            if self.durable:
                os.fsync(f.fileno())
        os.replace(stmp, side)
        self._seq = seq + 1
        self._seq_rows += n
        self._n = 0
        if self._c_shards is not None:
            self._c_shards.inc()
        if self._bus is not None:
            # "shard", not "seq": seq is one of the bus's own reserved
            # stamp fields and emit() refuses payload keys that shadow it
            self._bus.emit("flywheel_shard_seal", shard=seq, rows=n,
                           policy_step=self.policy_step)

    def seal(self) -> None:
        """Seal the buffered partial shard now (no-op when empty)."""
        with self._lock:
            self._seal_locked()

    def close(self) -> None:
        """Seal the tail and refuse further appends (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._seal_locked()
            self._closed = True

    def __enter__(self) -> "FlightLogWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---- read path -------------------------------------------------------


@dataclasses.dataclass
class FlightShard:
    """One verified shard, columns as host arrays (leaves enumerated in
    the writer's order — :func:`unflatten_like` rebuilds pytrees)."""
    seq: int
    path: str
    rows: int
    policy_step: int
    obs_leaves: "list[np.ndarray]"
    mask_leaves: "list[np.ndarray]"
    act_leaves: "list[np.ndarray]"
    log_prob: np.ndarray
    value: np.ndarray
    stall: np.ndarray
    outcome: np.ndarray
    # LAST + defaulted: pre-ISSUE-20 call sites construct positionally
    req_id: "np.ndarray | None" = None


@dataclasses.dataclass
class FlightLogData:
    """A verified flight log: every shard crc-checked, torn tail (at
    most one trailing shard without a valid sidecar) dropped + flagged."""
    shards: "list[FlightShard]"
    torn_tail: bool = False
    torn_reason: str = ""

    @property
    def rows(self) -> int:
        return sum(s.rows for s in self.shards)

    def concat(self) -> "FlightShard":
        """All shards as one pseudo-shard (columns concatenated in seq
        order; ``policy_step`` of the OLDEST shard — the conservative
        staleness bound)."""
        if not self.shards:
            raise FlightLogError("empty flight log (no verified shards)")
        cat = lambda ls: [np.concatenate(x) for x in zip(*ls)]
        return FlightShard(
            seq=-1, path="<concat>", rows=self.rows,
            policy_step=min(s.policy_step for s in self.shards),
            obs_leaves=cat([s.obs_leaves for s in self.shards]),
            mask_leaves=cat([s.mask_leaves for s in self.shards]),
            act_leaves=cat([s.act_leaves for s in self.shards]),
            log_prob=np.concatenate([s.log_prob for s in self.shards]),
            value=np.concatenate([s.value for s in self.shards]),
            stall=np.concatenate([s.stall for s in self.shards]),
            outcome=np.concatenate([s.outcome for s in self.shards]),
            req_id=np.concatenate(
                [s.req_id if s.req_id is not None
                 else np.zeros(s.rows, np.int64) for s in self.shards]))


def unflatten_like(example: Any, leaves: "list[np.ndarray]") -> Any:
    """Rebuild a logged pytree column from an example with the same
    structure (the env/net the caller already holds — the log stores
    leaves, not treedefs)."""
    import jax
    return jax.tree.unflatten(jax.tree.structure(example), leaves)


def _load_shard(directory: str, seq: int, path: str) -> FlightShard:
    side = _sidecar_path(directory, seq)
    with open(side) as f:
        meta = json.load(f)
    actual = _crc32_file(path)
    if actual != int(meta["crc32"]):
        raise FlightLogCorruptError(
            f"{os.path.basename(path)}: crc32 mismatch (sidecar "
            f"{int(meta['crc32']):#010x}, on disk {actual:#010x})")
    with np.load(path) as z:
        grab = lambda pre: [z[k] for k in sorted(
            (k for k in z.files if re.fullmatch(pre + r"\d+", k)),
            key=lambda k: int(k[len(pre):]))]
        shard = FlightShard(
            seq=seq, path=path, rows=int(meta["rows"]),
            policy_step=int(meta["policy_step"]),
            obs_leaves=grab("obs"), mask_leaves=grab("mask"),
            act_leaves=grab("act"), log_prob=z["log_prob"],
            value=z["value"], stall=z["stall"], outcome=z["outcome"],
            # pre-ISSUE-20 shards have no req_id column: read as
            # all-zeros ("unassigned") instead of failing the load
            req_id=(z["req_id"] if "req_id" in z.files
                    else np.zeros(int(meta["rows"]), np.int64)))
    if shard.rows != int(shard.log_prob.shape[0]):
        raise FlightLogCorruptError(
            f"{os.path.basename(path)}: sidecar says {shard.rows} rows, "
            f"payload has {int(shard.log_prob.shape[0])}")
    return shard


def read_flight_log(directory: str) -> FlightLogData:
    """Load and verify every shard under ``directory`` in sequence
    order. Sidecar-less/corrupt LAST shard = torn tail (dropped,
    flagged); any earlier failure raises
    :class:`FlightLogCorruptError`. ``.tmp.`` leftovers are ignored
    (they are, by construction, unpublished torn writes)."""
    directory = os.path.abspath(directory)
    found = []
    for name in os.listdir(directory):
        m = _SHARD_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(directory, name)))
    found.sort()
    # the writer numbers shards 0..N-1 with no holes, so a seq gap in
    # what survived on disk means an INTERIOR shard file vanished (with
    # its sidecar) — data loss the per-file crc checks cannot see. A
    # lost TAIL shard is detectable too: its sidecar (written after the
    # payload) outlives the payload
    for i, (seq, _) in enumerate(found):
        if seq != i:
            raise FlightLogCorruptError(
                f"{directory}: shard seq {i} is missing (found "
                f"{shard_name(seq)} after {i} earlier shard(s)) — "
                f"interior data loss, not a torn tail")
    crc_dir = os.path.join(directory, ".crc")
    if os.path.isdir(crc_dir):
        side_seqs = sorted(
            int(m.group(1)) for m in
            (re.fullmatch(r"shard-(\d{6})\.json", n)
             for n in os.listdir(crc_dir)) if m)
        if side_seqs and side_seqs[-1] >= len(found):
            raise FlightLogCorruptError(
                f"{directory}: sidecar for seq {side_seqs[-1]} exists "
                f"but only {len(found)} shard payload(s) remain — a "
                f"sealed shard was lost after publication")
    shards: "list[FlightShard]" = []
    torn, reason = False, ""
    for i, (seq, path) in enumerate(found):
        try:
            shards.append(_load_shard(directory, seq, path))
        except Exception as e:
            # missing sidecar / truncated zip / crc mismatch: on the
            # LAST shard any of these is the at-most-one torn tail the
            # payload-then-sidecar ordering guarantees; anywhere earlier
            # it is interior corruption and must not be papered over
            if i == len(found) - 1:
                torn = True
                reason = f"{os.path.basename(path)}: {type(e).__name__}"
                break
            if isinstance(e, FlightLogCorruptError):
                raise
            raise FlightLogCorruptError(
                f"non-tail shard {os.path.basename(path)} is unreadable "
                f"({type(e).__name__}: {e}); interior corruption, not a "
                f"torn tail") from e
    return FlightLogData(shards=shards, torn_tail=torn, torn_reason=reason)
