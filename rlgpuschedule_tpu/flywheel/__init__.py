"""The data flywheel: serve → log → continually retrain → promote.

The repo's first end-to-end self-improving path, built as three
robustness problems (ISSUE 19):

- :mod:`.flightlog` — crash-safe served-traffic trajectory log
  (recycled shard buffers, crc32 sidecars, torn-tail-tolerant reads,
  rows_logged == served conservation);
- :mod:`.continual` — ``train --continual LOGDIR``: V-trace-corrected
  off-policy retraining from logged shards, with measured staleness and
  an importance-ratio trust region that refuses shards too off-policy
  to learn from;
- :mod:`.canary` — canary-gated promotion: shared-rule replay of a
  held-out logged window, hysteresis regression gate, live
  ``swap_params`` with blessed re-warm, post-swap SLO watchdog with
  automatic rollback, and a crc-sidecar'd promotion ledger.

Event kinds by emitter: ``flywheel_shard_seal`` (FlightLogWriter),
``promote_blocked`` (canary gate), ``promote_apply`` (the serve CLI's
promotion driver), ``promote_rollback`` (SLOWatchdog). None are alarm
kinds — ``obs report --strict-alarms`` stays green across a healthy
promotion.
"""
from .canary import (CanaryReport, LedgerCorruptError, PromotionLedger,
                     SLOWatchdog, action_agreement, read_ledger,
                     replay_decisions, run_canary)
from .continual import (IngestReport, admit_shards, gate_logged_mask,
                        run_continual, shard_rho_stats,
                        shards_to_transition)
from .flightlog import (FlightLogCorruptError, FlightLogData,
                        FlightLogError, FlightLogWriter, FlightShard,
                        read_flight_log, unflatten_like)

__all__ = [
    "CanaryReport", "FlightLogCorruptError", "FlightLogData",
    "FlightLogError", "FlightLogWriter", "FlightShard", "IngestReport",
    "LedgerCorruptError", "PromotionLedger", "SLOWatchdog",
    "action_agreement", "admit_shards", "gate_logged_mask",
    "read_flight_log", "read_ledger", "replay_decisions", "run_canary",
    "run_continual", "shard_rho_stats", "shards_to_transition",
    "unflatten_like",
]
