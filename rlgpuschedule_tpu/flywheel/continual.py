"""Continual training from served traffic: the flywheel's learn path.

``train --continual LOGDIR`` lands here: verified flight-log shards
(:mod:`.flightlog`) become off-policy pseudo-trajectories and feed the
SAME fused PPO learn step the simulator path uses
(:func:`..algos.ppo.make_learn_step`) with ``correction="vtrace"``
forced — logged traffic is *measurably* behavior-lagged (the learner
has stepped since the serving snapshot), which is precisely the
actor-learner staleness V-trace (PR 12) exists to correct. The lag is
measured, not assumed (the Podracer contract):

- **staleness** — ``learner_step - shard.policy_step`` per shard, on
  the ``flywheel_shard_staleness`` gauge;
- **importance ratios** — one batched apply under the learner's current
  params gives target log-probs against the shard's STORED behavior
  log-probs (never recomputed post-hoc — the Transition contract);
  ``flywheel_rho_mean``/``flywheel_rho_max`` gauges publish the stats;
- **trust region** — a shard whose mean ratio leaves
  ``[1/trust, trust]`` or whose max ratio exceeds ``rho_max_cap`` is
  REFUSED (``flywheel_shards_refused_total``): off-policy enough that
  V-trace's clipped correction would be all clip and no signal, so the
  honest move is to drop it loudly rather than train on noise.

Ingest shape: served rows arrive in dispatch order and carry no
successor observation, so rows fold into ``[T, E]`` pseudo-trajectories
(row ``t*E + e`` → step ``t``, lane ``e``), ``done`` stays False, the
reward is the row's SLO outcome (+1 served within deadline or
deadline-free, −1 served late — the serving tier's own objective), and
the V-trace scan bootstraps from the stored behavior values with the
final row batch's value as the tail — documented approximations, pinned
by tests, not silent ones.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np

from ..algos import action_dist
from ..algos.ppo import make_learn_step
from ..algos.rollout import Transition
from ..decision import (gate_stalled, greedy_actions, preempt_slice,
                        stall_threshold)
from .flightlog import (FlightLogData, FlightLogError, FlightShard,
                        read_flight_log, unflatten_like)


@dataclasses.dataclass
class IngestReport:
    """What one ingest pass accepted/refused (per-shard admission)."""
    shards_seen: int
    shards_accepted: int
    shards_refused: int
    rows_accepted: int
    torn_tail: bool
    per_shard: "list[dict]"


def gate_logged_mask(mask: Any, stall, env_params):
    """Re-apply the serving engines' stall gate to a logged PRE-gate
    mask column. The stored behavior log-prob/value came out of the
    engine's compiled program AFTER :func:`..decision.gate_stalled`, so
    any target distribution compared against it (ρ-stats, the learn
    step's log-probs) must see the SAME gated mask — exactly what the
    canary's ``replay_decisions`` already does. No-op when the env has
    no preempt actions (hier env / preempt_len == 0): the engine gate
    is a no-op there too."""
    pre = (preempt_slice(env_params) if env_params is not None else None)
    if pre is None:
        return mask
    thresh = stall_threshold(env_params)
    return np.asarray(jax.device_get(gate_stalled(
        mask, np.asarray(stall, np.int32), thresh, pre)))


def shard_rho_stats(apply_fn, params, shard: FlightShard,
                    example_obs: Any, example_mask: Any,
                    example_act: Any, env_params=None,
                    ) -> "tuple[float, float]":
    """(mean, max) unclipped importance ratios of ``shard`` under the
    learner's current ``params`` — one batched apply, target log-prob
    against the shard's stored behavior log-prob. ``env_params`` (when
    given) re-applies the serving stall gate to the logged pre-gate
    mask so the target distribution matches the one the behavior
    log-prob was drawn from."""
    obs = unflatten_like(example_obs, shard.obs_leaves)
    mask = gate_logged_mask(
        unflatten_like(example_mask, shard.mask_leaves), shard.stall,
        env_params)
    act = unflatten_like(example_act, shard.act_leaves)
    logits, _ = apply_fn(params, obs, mask)
    target_lp = action_dist.log_prob(logits, act)
    rho = np.exp(np.asarray(target_lp, np.float64)
                 - np.asarray(shard.log_prob, np.float64))
    return float(rho.mean()), float(rho.max())


def admit_shards(data: FlightLogData, apply_fn, params, learner_step: int,
                 example_obs: Any, example_mask: Any, example_act: Any,
                 trust: float = 2.0, rho_max_cap: float = 8.0,
                 registry=None, env_params=None,
                 ) -> "tuple[list[FlightShard], IngestReport]":
    """Trust-region admission over every verified shard. Returns the
    accepted shards (seq order) and the per-shard report; publishes the
    staleness/ρ gauges and the refusal counter when a registry rides
    along."""
    if trust < 1.0:
        raise ValueError(f"trust must be >= 1.0, got {trust}")
    g_stale = g_mean = g_max = c_refused = c_ingested = None
    if registry is not None:
        g_stale = registry.gauge(
            "flywheel_shard_staleness",
            "learner_step - policy_step of the last shard considered "
            "for ingest (behavior lag, in train steps)")
        g_mean = registry.gauge(
            "flywheel_rho_mean",
            "mean unclipped V-trace importance ratio of the last shard "
            "considered for ingest")
        g_max = registry.gauge(
            "flywheel_rho_max",
            "max unclipped V-trace importance ratio of the last shard "
            "considered for ingest")
        c_refused = registry.counter(
            "flywheel_shards_refused_total",
            "shards refused by the ingest trust region (ρ-stats outside "
            "[1/trust, trust] / rho_max_cap)")
        c_ingested = registry.counter(
            "flywheel_shards_ingested_total",
            "shards accepted by the ingest trust region")
    accepted: "list[FlightShard]" = []
    per_shard: "list[dict]" = []
    for s in data.shards:
        stale = int(learner_step) - s.policy_step
        rho_mean, rho_max = shard_rho_stats(
            apply_fn, params, s, example_obs, example_mask, example_act,
            env_params=env_params)
        ok = (1.0 / trust <= rho_mean <= trust
              and rho_max <= rho_max_cap)
        if g_stale is not None:
            g_stale.set(stale)
            g_mean.set(rho_mean)
            g_max.set(rho_max)
            (c_ingested if ok else c_refused).inc()
        per_shard.append({"seq": s.seq, "rows": s.rows,
                          "staleness": stale, "rho_mean": rho_mean,
                          "rho_max": rho_max, "accepted": ok})
        if ok:
            accepted.append(s)
    report = IngestReport(
        shards_seen=len(data.shards), shards_accepted=len(accepted),
        shards_refused=len(data.shards) - len(accepted),
        rows_accepted=sum(s.rows for s in accepted),
        torn_tail=data.torn_tail, per_shard=per_shard)
    return accepted, report


def _fold_rows(leaves: "list[np.ndarray]", T: int, E: int):
    return [l[:T * E].reshape(T, E, *l.shape[1:]) for l in leaves]


def shards_to_transition(shards: "list[FlightShard]", n_envs: int,
                         tile: int, example_obs: Any,
                         example_mask: Any, example_act: Any,
                         env_params=None,
                         ) -> "tuple[Transition, jax.Array, int]":
    """Fold accepted shards' rows into one ``[T, E]`` Transition (row
    ``t*E + e`` → step t, lane e; the tail remainder that cannot fill a
    step — and any steps past the largest ``T`` whose flattened batch
    tiles ``tile`` (the update geometry's minibatch size or count) — is
    dropped, counted by the caller via ``rows - T*E``). The Transition
    mask is the logged mask with the serving stall gate re-applied
    (``env_params`` given): the stored behavior log-prob is defined
    over the GATED action set, and the learn step's ratio needs the
    same support. Returns ``(transition, last_value[E], T)``."""
    if not shards:
        raise FlightLogError("no shards survived the ingest trust region")
    E = int(n_envs)
    cat = lambda ls: [np.concatenate(x) for x in zip(*ls)]
    obs_l = cat([s.obs_leaves for s in shards])
    stall_cat = np.concatenate([s.stall for s in shards])
    mask_rows = gate_logged_mask(
        unflatten_like(example_mask,
                       cat([s.mask_leaves for s in shards])),
        stall_cat, env_params)
    mask_l = [np.asarray(l) for l in jax.tree.leaves(mask_rows)]
    act_l = cat([s.act_leaves for s in shards])
    lp = np.concatenate([s.log_prob for s in shards])
    value = np.concatenate([s.value for s in shards])
    outcome = np.concatenate([s.outcome for s in shards])
    rows = int(lp.shape[0])
    T = rows // E
    while T >= 2 and (T * E) % tile:
        T -= 1
    if T < 2:
        raise FlightLogError(
            f"{rows} ingested rows cannot form >= 2 pseudo-steps of "
            f"{E} lanes with a flattened batch tiling {tile}; log more "
            f"traffic or shrink n_envs / the minibatch geometry")
    tr = Transition(
        obs=unflatten_like(example_obs, _fold_rows(obs_l, T, E)),
        action=unflatten_like(example_act, _fold_rows(act_l, T, E)),
        log_prob=lp[:T * E].reshape(T, E),
        value=value[:T * E].reshape(T, E),
        reward=np.where(outcome[:T * E] == 2, -1.0, 1.0
                        ).astype(np.float32).reshape(T, E),
        done=np.zeros((T, E), bool),
        mask=unflatten_like(example_mask, _fold_rows(mask_l, T, E)),
        env_steps_dt=np.zeros((T, E), np.float32))
    # no successor observation exists for the final served rows, so the
    # scan bootstraps from the last row batch's stored behavior value
    last_value = value[(T - 1) * E:T * E].astype(np.float32)
    return tr, last_value, T


def run_continual(exp, logdir: str, iterations: int = 1, *,
                  trust: float = 2.0, rho_max_cap: float = 8.0,
                  registry=None, ckpt=None) -> dict:
    """The continual-training loop: verify + admit the flight log once,
    then run ``iterations`` V-trace-corrected learn steps over the
    folded pseudo-trajectories. ``exp`` is a built
    :class:`..experiment.Experiment` (params possibly checkpoint-
    restored); its train_state advances in place and is saved through
    ``ckpt`` (a :class:`..checkpoint.Checkpointer`) when given. Returns
    the summary the CLI prints."""
    data = read_flight_log(logdir)
    if not data.shards:
        raise FlightLogError(
            f"no verified shards under {logdir}"
            + (f" (torn tail: {data.torn_reason})" if data.torn_tail
               else ""))
    ex_obs = jax.tree.map(lambda x: np.asarray(x[:1]), exp.carry.obs)
    ex_mask = jax.tree.map(lambda x: np.asarray(x[:1]), exp.carry.mask)
    logits, _ = exp.apply_fn(exp.train_state.params, ex_obs, ex_mask)
    ex_act = jax.tree.map(np.asarray, greedy_actions(logits))
    learner_step = int(exp.train_state.step)
    accepted, report = admit_shards(
        data, exp.apply_fn, exp.train_state.params, learner_step,
        ex_obs, ex_mask, ex_act, trust=trust, rho_max_cap=rho_max_cap,
        registry=registry, env_params=exp.env_params)
    algo = dataclasses.replace(exp.cfg.ppo, correction="vtrace")
    tile = (algo.minibatch_size if algo.minibatch_size is not None
            else algo.n_minibatches)
    tr, last_value, T = shards_to_transition(
        accepted, exp.cfg.n_envs, tile, ex_obs, ex_mask, ex_act,
        env_params=exp.env_params)
    # the learn step's flatten reads n_steps from the config — bind it
    # to the folded T (data decides the geometry here, not the config)
    algo = dataclasses.replace(algo, n_steps=T)
    learn = jax.jit(make_learn_step(exp.apply_fn, algo))
    metrics = None
    for _ in range(int(iterations)):
        exp.key, key = jax.random.split(exp.key)
        exp.train_state, metrics = learn(exp.train_state, tr,
                                         last_value, key)
        if ckpt is not None:
            ckpt.save(int(exp.train_state.step), exp.train_state)
    rows_trained = T * exp.cfg.n_envs
    summary = {
        "mode": "continual",
        "logdir": logdir,
        "iterations": int(iterations),
        "rows_logged": data.rows,
        "rows_accepted": report.rows_accepted,
        "rows_trained": rows_trained,
        "rows_dropped_fold": report.rows_accepted - rows_trained,
        "shards_seen": report.shards_seen,
        "shards_accepted": report.shards_accepted,
        "shards_refused": report.shards_refused,
        "torn_tail": report.torn_tail,
        "per_shard": report.per_shard,
        "pseudo_steps": T,
        "final_step": int(exp.train_state.step),
    }
    if metrics is not None:
        m = jax.device_get(metrics)
        summary["rho_mean_trained"] = float(np.asarray(m.rho_mean))
        summary["rho_max_trained"] = float(np.asarray(m.rho_max))
        summary["total_loss"] = float(np.asarray(m.total_loss))
    return summary
