"""Canary-gated promotion with automatic rollback: the flywheel's
apply path.

A candidate checkpoint never takes traffic on faith. First it replays a
held-out logged window (:mod:`.flightlog`) next to the incumbent —
both through :func:`..decision.policy_decision_full`, the ONE decision
rule serving and evaluation already share, so the canary cannot drift
from what the engines actually execute. The replay is compared row-wise
against the **logged behavior actions** (ground truth of what was
served): the incumbent's agreement is the reference (bit-identical
when the incumbent IS the behavior snapshot), and a candidate whose
per-slice agreement falls more than ``tol`` below the incumbent's votes
"regress". Votes feed a signed-streak hysteresis gate (the
AutoscaleAdvisor pattern): only ``hysteresis`` CONSECUTIVE regressing
slices block, so one noisy slice cannot veto and one good slice cannot
launder a trend. This is a behavior-drift gate — it bounds how far the
candidate's served decisions move from measured traffic; outcome-based
(reward-carrying) canarying is the documented open end.

Promotion itself is :meth:`..serve.router.EngineRouter.swap_params`:
shape-checked in-place weight swap + blessed re-warm through every
warmed bucket (zero compiles expected — a compile would be a recompile
alarm, which is the proof, not an accident). Afterward the
:class:`SLOWatchdog` compares live p99/shed/recompile against EWMAs it
learned from PRE-swap traffic; a breach streak (or a single post-swap
recompile) triggers automatic rollback to the retained incumbent
params. Every verdict — blocked, promoted, rolled back — lands in the
:class:`PromotionLedger`, a crc-sidecar'd JSONL lineage that survives
the same crash model as the flight log.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import weakref
from typing import Any

import jax
import numpy as np

from ..checkpoint import _crc32_file
from ..decision import (gate_stalled, policy_decision_full, preempt_slice,
                        stall_threshold)

LEDGER_NAME = "promotions.jsonl"


class LedgerCorruptError(RuntimeError):
    """The promotion ledger's sealed prefix fails its crc sidecar."""


# ---- shared-rule replay ----------------------------------------------


# one jitted replay program per (policy, stall-gate) pair — the
# _GATHER_CACHE idiom, so repeated canary runs against the same
# apply_fn reuse the compiled executable instead of re-tracing per
# call. WEAK-keyed on apply_fn: each entry pins a jitted executable,
# so a strong key would leak one per Experiment build in a long-lived
# process — the cache must die with the policy it serves. The cached
# closure holds apply_fn through a weakref too: a strong ref in the
# VALUE would keep the weak KEY alive and defeat the eviction
_REPLAY_PROGRAMS: "weakref.WeakKeyDictionary[Any, dict]" = (
    weakref.WeakKeyDictionary())


def _replay_program(apply_fn, thresh: int, gated: bool):
    try:
        per_fn = _REPLAY_PROGRAMS.get(apply_fn)
        if per_fn is None:
            per_fn = _REPLAY_PROGRAMS[apply_fn] = {}
        fn_ref = weakref.ref(apply_fn)
    except TypeError:        # un-weakref-able callable: trace per call
        per_fn, fn_ref = {}, (lambda af=apply_fn: af)
    key = (thresh, gated)
    fn = per_fn.get(key)
    if fn is None:
        def _replay(p, o, m, s, pre):
            af = fn_ref()    # live: the caller holds apply_fn
            if gated:
                m = gate_stalled(m, s, thresh, pre)
            return policy_decision_full(af, p, o, m)
        fn = per_fn[key] = jax.jit(_replay)
    return fn


def replay_decisions(apply_fn, params, obs: Any, mask: Any, stall,
                     env_params=None):
    """Replay a logged window (host pytrees, leading row axis) through
    the SAME gated decision rule the serving engine compiles
    (stall gate included) — ``(actions, log_prob, value)`` on host.

    One full-window batch: the policy is batch-composition invariant
    (pinned in tests/test_serve.py), so replaying [N] rows at once is
    decision-equivalent to the engines' bucketed dispatches."""
    pre = (preempt_slice(env_params) if env_params is not None else None)
    thresh = stall_threshold(env_params) if pre is not None else 0

    stall = np.zeros(int(np.asarray(jax.tree.leaves(mask)[0]).shape[0]),
                     np.int32) if stall is None else np.asarray(stall,
                                                                np.int32)
    fn = _replay_program(apply_fn, int(thresh), pre is not None)
    out = fn(params, obs, mask, stall, pre)
    return jax.device_get(out)


def action_agreement(a: Any, b: Any) -> np.ndarray:
    """Row-wise agreement of two action pytrees: True where EVERY head
    matches (bool[N])."""
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    agree = None
    for x, y in zip(la, lb):
        eq = np.asarray(x) == np.asarray(y)
        eq = eq.reshape(eq.shape[0], -1).all(axis=1)
        agree = eq if agree is None else (agree & eq)
    return agree


# ---- canary gate -----------------------------------------------------


@dataclasses.dataclass
class CanaryReport:
    """One canary run's verdict and evidence."""
    verdict: str                     # "promote" | "blocked"
    rows: int
    slices: int
    incumbent_agreement: float       # vs logged behavior actions, overall
    candidate_agreement: float
    regress_slices: int
    max_regress_streak: int
    per_slice: "list[dict]"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def run_canary(apply_fn, incumbent_params, candidate_params, window,
               example_obs: Any, example_mask: Any, env_params=None,
               slices: int = 8, tol: float = 0.02, hysteresis: int = 2,
               registry=None, bus=None) -> CanaryReport:
    """Gate a candidate against the incumbent over a held-out logged
    ``window`` (a :class:`.flightlog.FlightShard`, e.g. ``concat()``).
    Blocks when ``hysteresis`` consecutive slices regress (candidate
    agreement with the logged behavior actions more than ``tol`` below
    the incumbent's on the same slice)."""
    from .flightlog import unflatten_like
    if slices < 1:
        raise ValueError(f"slices must be >= 1, got {slices}")
    if hysteresis < 1:
        raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
    obs = unflatten_like(example_obs, window.obs_leaves)
    mask = unflatten_like(example_mask, window.mask_leaves)
    logged = window.act_leaves
    inc_act, _, _ = replay_decisions(apply_fn, incumbent_params, obs,
                                     mask, window.stall, env_params)
    cand_act, _, _ = replay_decisions(apply_fn, candidate_params, obs,
                                      mask, window.stall, env_params)
    inc_rows = action_agreement(inc_act, logged)
    cand_rows = action_agreement(cand_act, logged)
    n = int(inc_rows.shape[0])
    bounds = np.linspace(0, n, min(slices, n) + 1, dtype=int)
    per_slice: "list[dict]" = []
    streak = best_streak = regress = 0
    for k in range(len(bounds) - 1):
        lo, hi = int(bounds[k]), int(bounds[k + 1])
        if hi <= lo:
            continue
        ia = float(inc_rows[lo:hi].mean())
        ca = float(cand_rows[lo:hi].mean())
        bad = ca < ia - tol
        streak = streak + 1 if bad else 0
        best_streak = max(best_streak, streak)
        regress += int(bad)
        per_slice.append({"slice": k, "rows": hi - lo,
                          "incumbent_agreement": ia,
                          "candidate_agreement": ca, "regress": bad})
    verdict = "blocked" if best_streak >= hysteresis else "promote"
    report = CanaryReport(
        verdict=verdict, rows=n, slices=len(per_slice),
        incumbent_agreement=float(inc_rows.mean()),
        candidate_agreement=float(cand_rows.mean()),
        regress_slices=regress, max_regress_streak=best_streak,
        per_slice=per_slice)
    if registry is not None:
        registry.counter(
            "flywheel_canary_runs_total",
            "canary replays executed against a candidate").inc()
        if verdict == "blocked":
            registry.counter(
                "flywheel_promotions_blocked_total",
                "candidate promotions blocked by the canary gate").inc()
    if bus is not None and verdict == "blocked":
        bus.emit("promote_blocked", rows=n,
                 incumbent_agreement=report.incumbent_agreement,
                 candidate_agreement=report.candidate_agreement,
                 max_regress_streak=best_streak)
    return report


# ---- post-swap SLO watchdog ------------------------------------------


class SLOWatchdog:
    """Live-regression tripwire for a just-promoted candidate.

    Pre-swap, :meth:`sample_baseline` folds the serving tier's own SLO
    surface (the ``serve_decision_latency_p99_ms`` gauge the server's
    ``slo_snapshot`` publishes) into an EWMA — the LEARNED baseline, so
    the breach test compares the candidate to this deployment's actual
    behavior, not a config constant. :meth:`arm` snapshots the shed and
    recompile counters at swap time; each post-swap :meth:`observe`
    tick then votes *breach* when p99 exceeds ``p99_factor ×`` the
    learned baseline or NEW shedding appears, and ``breach_after``
    consecutive breach votes request rollback. A post-swap recompile is
    an immediate rollback — the swap contract says there must be none,
    so one recompile means the fleet is not running the program that
    was blessed."""

    def __init__(self, registry, engine=None, p99_factor: float = 1.5,
                 breach_after: int = 3, alpha: float = 0.2, bus=None):
        from ..serve.batching import Ewma
        if p99_factor <= 1.0:
            raise ValueError(f"p99_factor must be > 1, got {p99_factor}")
        if breach_after < 1:
            raise ValueError(
                f"breach_after must be >= 1, got {breach_after}")
        self.registry = registry
        self.engine = engine          # router/engine: recompile surface
        self.p99_factor = float(p99_factor)
        self.breach_after = int(breach_after)
        self._bus = bus
        self._g_p99 = registry.gauge("serve_decision_latency_p99_ms")
        self._c_shed = registry.counter("serve_shed_total")
        self._ewma = Ewma(alpha=alpha)
        self._streak = 0
        self._armed = False
        self._shed0 = 0.0
        self._shed_prev = 0.0
        self._rec0 = 0

    def _recompiles(self) -> int:
        if self.engine is None:
            return 0
        return int(self.engine.post_warmup_recompiles)

    @property
    def baseline_p99_ms(self) -> "float | None":
        return self._ewma.value

    def sample_baseline(self) -> None:
        """One pre-swap tick: learn the incumbent's p99 EWMA."""
        p99 = float(self._g_p99.value)
        if p99 > 0:
            self._ewma.update(p99)

    def arm(self) -> None:
        """Snapshot shed/recompile counters at swap time; breach votes
        only count deltas accrued AFTER this."""
        self._shed0 = self._shed_prev = float(self._c_shed.value)
        self._rec0 = self._recompiles()
        self._streak = 0
        self._armed = True

    def observe(self) -> dict:
        """One post-swap tick. Returns ``{rollback, reasons, streak,
        p99_ms, baseline_p99_ms}`` — ``rollback=True`` means the caller
        must swap the incumbent back NOW."""
        if not self._armed:
            raise RuntimeError("SLOWatchdog.observe() before arm()")
        reasons = []
        rec_delta = self._recompiles() - self._rec0
        if rec_delta > 0:
            reasons.append(f"recompile(+{rec_delta})")
        p99 = float(self._g_p99.value)
        base = self._ewma.value
        p99_breach = (base is not None and p99 > 0
                      and p99 > base * self.p99_factor)
        if p99_breach:
            reasons.append(f"p99({p99:.1f}ms > {self.p99_factor:g}x"
                           f"{base:.1f}ms)")
        shed = float(self._c_shed.value)
        if shed > self._shed_prev:
            reasons.append(f"shed(+{shed - self._shed_prev:g})")
        self._shed_prev = shed
        vote = bool(reasons)
        self._streak = self._streak + 1 if vote else 0
        rollback = rec_delta > 0 or self._streak >= self.breach_after
        out = {"rollback": rollback, "reasons": reasons,
               "streak": self._streak, "p99_ms": p99,
               "baseline_p99_ms": base,
               "shed_delta": shed - self._shed0,
               "recompile_delta": rec_delta}
        if rollback and self._bus is not None:
            self._bus.emit("promote_rollback", reasons=reasons,
                           streak=self._streak, p99_ms=p99,
                           baseline_p99_ms=base)
        return out


# ---- promotion ledger ------------------------------------------------


class PromotionLedger:
    """Crash-safe JSONL lineage of every promotion decision.

    Appends are flush (+fsync when ``durable``) then the crc sidecar
    ``.crc/promotions.json`` — ``{"bytes": N, "crc32": C}`` over the
    sealed prefix — is rewritten atomically. A crash between the two
    leaves entries PAST the sealed prefix: :func:`read_ledger` returns
    them separately as the unsealed tail (parseable lines are not data
    loss, they are just not yet covered by the integrity contract), and
    a prefix that fails its crc raises :class:`LedgerCorruptError`."""

    def __init__(self, directory: str, durable: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        os.makedirs(os.path.join(self.directory, ".crc"), exist_ok=True)
        self.path = os.path.join(self.directory, LEDGER_NAME)
        self.durable = bool(durable)
        self._lock = threading.Lock()

    @property
    def _sidecar(self) -> str:
        return os.path.join(self.directory, ".crc", "promotions.json")

    def append(self, record: dict) -> None:
        """Append one decision record (a json-able dict; an ``event``
        key naming the decision — canary/promote/rollback/blocked — is
        the convention the CLI and tests read back)."""
        line = json.dumps(record, sort_keys=True) + "\n"
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line)
                f.flush()
                if self.durable:
                    os.fsync(f.fileno())
            crc = _crc32_file(self.path)
            size = os.path.getsize(self.path)
            tmp = f"{self._sidecar}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"bytes": size, "crc32": crc}, f)
                f.flush()
                if self.durable:
                    os.fsync(f.fileno())
            os.replace(tmp, self._sidecar)


def read_ledger(directory: str) -> "tuple[list[dict], list[dict]]":
    """Load a promotion ledger: ``(sealed, tail)`` — sealed entries are
    crc-verified against the sidecar; tail entries (appended after the
    last sidecar update, e.g. a crash mid-append) parse but are flagged
    by position. Missing ledger = ``([], [])``."""
    directory = os.path.abspath(directory)
    path = os.path.join(directory, LEDGER_NAME)
    side = os.path.join(directory, ".crc", "promotions.json")
    if not os.path.exists(path):
        return [], []
    with open(path, "rb") as f:
        blob = f.read()
    sealed_bytes = 0
    if os.path.exists(side):
        with open(side) as f:
            meta = json.load(f)
        sealed_bytes = int(meta["bytes"])
        import zlib
        if zlib.crc32(blob[:sealed_bytes]) != int(meta["crc32"]):
            raise LedgerCorruptError(
                f"{path}: sealed prefix ({sealed_bytes} bytes) fails its "
                f"crc sidecar — the lineage cannot be trusted")
    parse = lambda chunk: [json.loads(l) for l in
                           chunk.decode().splitlines() if l.strip()]
    sealed = parse(blob[:sealed_bytes])
    tail = []
    for l in blob[sealed_bytes:].decode(errors="replace").splitlines():
        try:
            tail.append(json.loads(l))
        except json.JSONDecodeError:
            pass                     # torn final line: flagged by count
    return sealed, tail
