"""Experiment assembly (L6): config -> traces + env + policy + train loop.

Capability parity: SURVEY.md §3.1 — the `train()` call stack: build trace,
make vectorized envs, build policy, run the trainer loop, log metrics.
"""
from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from .algos import (init_carry, make_a2c_step, make_ppo_step,
                    make_train_state, resolve_geometry)
from .algos.rollout import RolloutCarry
from .algos.ppo import make_optimizer
from .configs import ExperimentConfig
from .env import EnvParams, build_adjacency, stack_traces
from .models import make_policy
from .domains import (domain_schedule, resolve_domain, sample_env_domains,
                      stack_domain_schedules, validate_domain_schedule)
from .sim.core import SimParams, validate_trace
from .sim.faults import (fault_horizon, resolve_regime,
                         sample_env_fault_schedules, sample_fault_schedule)
from .traces import (ArrayTrace, gen_domain_window, gen_poisson_trace,
                     load_pai, load_philly)
from .traces.fit import domain_fit
from flax.training.train_state import TrainState


def build_env_params(cfg: ExperimentConfig) -> EnvParams:
    sim = SimParams(n_nodes=cfg.n_nodes, gpus_per_node=cfg.gpus_per_node,
                    max_jobs=cfg.window_jobs, queue_len=cfg.queue_len,
                    n_placements=cfg.n_placements,
                    preempt_len=cfg.preempt_len)
    fault_process = resolve_regime(cfg.faults) if cfg.faults else None
    domain_process = resolve_domain(cfg.domains) if cfg.domains else None
    return EnvParams(sim=sim, obs_kind=cfg.obs_kind,
                     reward_kind=cfg.reward_kind, n_tenants=cfg.n_tenants,
                     time_scale=cfg.time_scale, reward_scale=cfg.reward_scale,
                     place_bonus=cfg.place_bonus,
                     preempt_cost=cfg.preempt_cost, horizon=cfg.horizon,
                     fault_process=fault_process,
                     # per-node health rides the FLAT observation only
                     # (grid/graph pin their feature layouts); those
                     # encoders still train on fault dynamics, blind to
                     # which node is sick. Domain runs always carry a
                     # DomainSchedule (with a possibly-heterogeneous
                     # slowdown) so they get the health channel too
                     fault_obs=((fault_process is not None
                                 or domain_process is not None)
                                and cfg.obs_kind == "flat"),
                     domain_process=domain_process,
                     domain_obs=(domain_process is not None
                                 and cfg.obs_kind == "flat"))


def load_source_trace(cfg: ExperimentConfig, n_jobs: int | None = None,
                      seed: int | None = None) -> ArrayTrace:
    """The full source trace this experiment schedules."""
    seed = cfg.seed if seed is None else seed
    if cfg.trace in ("synthetic", "philly-proxy", "pai-proxy"):
        # cfg.source_jobs pins GENERATED traces only (its documented
        # scope); a CSV load is the file's own size (n_jobs caps it)
        n_jobs = n_jobs or cfg.source_jobs
    if cfg.trace == "synthetic":
        n = n_jobs or max(cfg.window_jobs * max(cfg.n_envs, 8), 1024)
        return gen_poisson_trace(cfg.arrival_rate, n, seed,
                                 mean_duration=cfg.mean_duration,
                                 n_tenants=max(cfg.n_tenants, 1))
    if cfg.trace in ("philly-proxy", "pai-proxy"):
        from .traces import gen_pai_proxy_trace, gen_philly_proxy_trace
        n = n_jobs or max(cfg.window_jobs * max(cfg.n_envs, 8), 4096)
        gen = (gen_philly_proxy_trace if cfg.trace == "philly-proxy"
               else gen_pai_proxy_trace)
        kw = {}
        if cfg.n_tenants:       # keep tenant ids inside the env's bins
            kw["n_tenants"] = cfg.n_tenants
        return gen(n, seed, n_gpus=cfg.total_gpus, load=cfg.trace_load,
                   max_gang=cfg.total_gpus, **kw)
    if cfg.trace_path is None:
        raise ValueError(
            f"config {cfg.name!r} uses trace={cfg.trace!r} but has no "
            f"trace_path; pass one (CSV) or use trace='synthetic'")
    loader = load_philly if cfg.trace == "philly" else load_pai
    return loader(cfg.trace_path, max_jobs=n_jobs)


def build_stack(cfg: ExperimentConfig):
    """Shared assembly for single-run and population experiments: trace
    load/validate/window/stack + policy net + (obs, mask) apply closure.
    Returns (env_params, windows, traces [E, ...], net, apply_fn, extra,
    source) where ``extra`` are the apply args between obs and mask (the
    GNN's adjacency) and ``source`` is the full validated source trace
    (window streaming re-cuts windows from it). ``cfg.n_pods > 1`` selects
    the hierarchical env + policy (config 5) — env_params is then a
    ``env.hier.HierParams``."""
    if cfg.n_pods > 1:
        from .env import hier as hier_lib   # registers the vec dispatch
        from .models.hier import HierActorCritic
        if cfg.faults:
            raise ValueError(
                "hierarchical configs have no fault-process support yet "
                "(sim.faults is a flat-config feature); unset faults")
        if cfg.domains:
            raise ValueError(
                "hierarchical configs have no domain-randomization "
                "support yet (domain schedules carry per-node capacity "
                "through the flat sim path only); unset domains")
        if cfg.n_nodes % cfg.n_pods != 0:
            raise ValueError(f"n_nodes={cfg.n_nodes} not divisible by "
                             f"n_pods={cfg.n_pods}")
        if cfg.obs_kind != "flat" or cfg.reward_kind != "jct":
            raise ValueError(
                f"hierarchical configs use flat pod observations and the "
                f"JCT reward; got obs_kind={cfg.obs_kind!r}, "
                f"reward_kind={cfg.reward_kind!r}")
        if cfg.preempt_len:
            raise ValueError(
                "hierarchical configs do not support the preemptive action "
                "space (pod actions are queue-slot×placement + no-op); set "
                "preempt_len=0")
        pod_sim = SimParams(n_nodes=cfg.n_nodes // cfg.n_pods,
                            gpus_per_node=cfg.gpus_per_node,
                            max_jobs=cfg.window_jobs,
                            queue_len=cfg.queue_len,
                            n_placements=cfg.n_placements)
        env_params = hier_lib.HierParams(
            n_pods=cfg.n_pods, pod_sim=pod_sim, time_scale=cfg.time_scale,
            reward_scale=cfg.reward_scale, place_bonus=cfg.place_bonus,
            horizon=cfg.horizon)
        source = validate_trace(pod_sim, load_source_trace(cfg), clamp=True)
        windows = make_env_windows(cfg, source)
        traces = stack_traces(windows, pod_sim)
        net = HierActorCritic(n_top_actions=env_params.n_top_actions,
                              n_pod_actions=pod_sim.n_actions)
        apply_fn = lambda p, obs, mask: net.apply(p, obs, mask)
        return env_params, windows, traces, net, apply_fn, (), source

    env_params = build_env_params(cfg)
    source = validate_trace(env_params.sim, load_source_trace(cfg),
                            clamp=True)
    if env_params.domain_process is not None:
        # domain windows are GENERATED per env from the config's fitted
        # job mix under each env's seeded domain draw (arrival knobs +
        # that draw's actual capacity), not cut from the source — the
        # source stays loaded so --full-trace/window accounting on the
        # same config keep working
        draws = sample_env_domains(env_params.domain_process, cfg.n_nodes,
                                   cfg.gpus_per_node, cfg.seed, cfg.n_envs)
        windows = make_domain_windows(cfg, draws)
    else:
        windows = make_env_windows(cfg, source)
    traces = stack_traces(windows, env_params)
    net = make_policy(cfg.obs_kind, env_params.n_actions,
                      n_cluster_nodes=cfg.n_nodes, queue_len=cfg.queue_len,
                      n_placements=cfg.n_placements,
                      preempt_len=cfg.preempt_len)
    if cfg.obs_kind == "graph":
        adj = jnp.asarray(build_adjacency(cfg.n_nodes, cfg.queue_len,
                                          cfg.nodes_per_rack,
                                          cfg.preempt_len))
        apply_fn = lambda p, obs, mask: net.apply(p, obs, adj, mask)
        extra = (adj,)
    else:
        apply_fn = lambda p, obs, mask: net.apply(p, obs, mask)
        extra = ()
    return env_params, windows, traces, net, apply_fn, extra, source


def windows_per_pass(total_jobs: int, window_jobs: int) -> int:
    """Windows in one full tiling pass over the trace (the last window is
    the final ``window_jobs`` jobs, so every job appears in some window)."""
    return max(-(-total_jobs // window_jobs), 1)


def drain_window(w: ArrayTrace) -> ArrayTrace:
    """A backlog-drain copy of a window: every job submitted at t=0, so
    the episode is purely "drain this backlog" — the regime where the
    ordering/packing decision carries the whole JCT signal (see
    ``ExperimentConfig.drain_frac``)."""
    import numpy as np
    return dataclasses.replace(
        w, submit=np.where(w.valid, 0.0, np.inf).astype(np.float32))


def make_env_windows(cfg: ExperimentConfig, source: ArrayTrace,
                     start: int = 0) -> list[ArrayTrace]:
    """Cut n_envs episode windows out of the source trace: windows
    ``start+e`` (e < n_envs) of a tiling of the trace by ``window_jobs``,
    wrapping around at the end of the trace. Advancing ``start`` by
    ``n_envs`` per resample therefore sweeps the ENTIRE trace every
    ``windows_per_pass / n_envs`` resamples — round 1 trained forever on
    the first n_envs windows (VERDICT r1 missing #3). Windows are
    demand-clamped by stack_traces at upload.

    With ``cfg.drain_frac > 0`` the LAST ``round(n_envs * drain_frac)``
    envs train on drained copies of their windows (the backlog-drain
    curriculum); streaming resamples keep the same envs drained."""
    total = source.num_jobs
    if total < cfg.window_jobs:
        raise ValueError(f"source trace has {total} jobs < window "
                         f"{cfg.window_jobs}")
    per_pass = windows_per_pass(total, cfg.window_jobs)
    windows = []
    for e in range(cfg.n_envs):
        k = (start + e) % per_pass
        off = min(k * cfg.window_jobs, total - cfg.window_jobs)
        windows.append(source.slice(off, cfg.window_jobs))
    n_drain = int(round(cfg.n_envs * cfg.drain_frac))
    for e in range(cfg.n_envs - n_drain, cfg.n_envs):
        windows[e] = drain_window(windows[e])
    return windows


def make_domain_windows(cfg: ExperimentConfig, draws, start: int = 0,
                        ) -> list[ArrayTrace]:
    """The domain-randomized twin of :func:`make_env_windows`: one
    GENERATED window per env from the config's fitted job mix
    (``traces.fit.domain_fit``) under that env's :class:`DomainDraw` —
    offered load against the draw's ACTUAL capacity, duration scaling,
    diurnal/burst arrivals, gang mix renormalized to what the shrunken
    cluster can place. ``start`` is the window-streaming cursor: window
    seeds are ``(cfg.seed, env, start)``, so advancing the cursor draws
    fresh windows of identical shape (no recompilation), and a
    checkpoint restore at a cursor regenerates bit-identical windows.
    The drain-curriculum tail works exactly like the env-window path."""
    fit = domain_fit(cfg)
    windows = []
    for e, d in enumerate(draws):
        total = d.total_gpus
        windows.append(gen_domain_window(
            fit, cfg.window_jobs, (cfg.seed, e, start), n_gpus=total,
            load=d.load, duration_scale=d.duration_scale,
            burst_frac=d.burst_frac, diurnal=d.diurnal, max_gang=total,
            n_tenants=max(cfg.n_tenants, 1)))
    n = len(windows)     # the matrix evaluates draw batches != n_envs
    n_drain = int(round(n * cfg.drain_frac))
    for e in range(n - n_drain, n):
        windows[e] = drain_window(windows[e])
    return windows


@dataclasses.dataclass
class Experiment:
    """Assembled experiment: jitted train step + host loop."""
    cfg: ExperimentConfig
    env_params: EnvParams
    windows: list            # host ArrayTrace windows (reused by eval)
    traces: Any              # batched device Trace [E, ...]
    net: Any
    apply_fn: Callable
    train_state: TrainState
    train_step: Callable     # jitted
    carry: Any
    key: jax.Array
    source: Any = None       # full source ArrayTrace (window streaming)
    window_cursor: int = 0   # first window index of the current env batch
    train_step_raw: Callable | None = None   # unjitted (for run_fused)
    _fused_jit: Callable | None = None       # lazy; jit caches per length
    # batched per-env sim.faults.FaultSchedule [E, ...] (cfg.faults), or
    # None = healthy cluster. DATA like the traces: threaded through the
    # jitted step as an argument, never closed over, so schedules can
    # change without recompiling. Under cfg.domains this slot holds the
    # batched domains.DomainSchedule instead (a strict superset the
    # fault consumers read field-by-field), composing any cfg.faults
    # draw into its windows/slowdown
    faults: Any = None
    # host list[domains.DomainDraw] (cfg.domains), or None: the per-env
    # draws behind self.faults, kept so window streaming can regenerate
    # windows under the SAME cluster draws at a new cursor
    domains: Any = None
    # unified Mesh(pop × data × model) the step was rule-sharded against
    # (parallel.sharding), or None = plain single-program jit
    mesh: Any = None

    @staticmethod
    def build(cfg: ExperimentConfig, axis_name: str | None = None,
              jit: bool = True, mesh=None) -> "Experiment":
        env_params, windows, traces, net, apply_fn, extra, source = \
            build_stack(cfg)
        faults = None
        domains = None
        fp = getattr(env_params, "fault_process", None)
        if getattr(env_params, "domain_process", None) is not None:
            # the SAME seeded draws build_stack generated windows from
            # (host sampling is deterministic in (seed, env)); the
            # device data is one batched DomainSchedule riding the
            # faults slot, composing any cfg.faults draw per env
            domains = sample_env_domains(
                env_params.domain_process, cfg.n_nodes, cfg.gpus_per_node,
                cfg.seed, cfg.n_envs)
            horizon_s = fault_horizon(windows)
            schedules = []
            for e, d in enumerate(domains):
                f = (sample_fault_schedule(cfg.n_nodes, fp, (cfg.seed, e),
                                           horizon_s)
                     if fp is not None else None)
                schedules.append(validate_domain_schedule(
                    cfg.n_nodes, cfg.gpus_per_node, domain_schedule(d, f)))
            faults = stack_domain_schedules(schedules)
        elif fp is not None:
            # seeded per-env draws over the window batch's time span, so
            # drain windows intersect live episodes at every trace scale
            faults = sample_env_fault_schedules(
                cfg.n_nodes, fp, cfg.seed, cfg.n_envs,
                fault_horizon(windows))
        key = jax.random.PRNGKey(cfg.seed)
        key, init_key, carry_key = jax.random.split(key, 3)
        algo_cfg = cfg.ppo if cfg.algo == "ppo" else cfg.a2c
        # fail fast on a geometry that cannot tile the rollout batch —
        # inside the jitted step the same check would surface as an
        # opaque reshape trace error
        resolve_geometry(algo_cfg.n_epochs, algo_cfg.n_minibatches,
                         algo_cfg.minibatch_size,
                         algo_cfg.n_steps * cfg.n_envs)
        if cfg.algo == "ppo":
            tx = make_optimizer(algo_cfg)
            step_fn = make_ppo_step(apply_fn, env_params, algo_cfg, axis_name)
        else:
            from .algos.a2c import make_optimizer as a2c_opt
            tx = a2c_opt(algo_cfg)
            step_fn = make_a2c_step(apply_fn, env_params, algo_cfg, axis_name)
        carry = init_carry(env_params, traces, carry_key, faults)
        ex_obs, ex_mask = jax.tree.map(lambda x: x[:1],
                                       (carry.obs, carry.mask))
        train_state = make_train_state(net, init_key, ex_obs, ex_mask, tx,
                                       extra,
                                       reward_norm=algo_cfg.reward_norm)
        if jit:
            if axis_name is not None:
                # pmean(axis_name) is unbound under plain jit — the
                # explicit-collective assembly lives in
                # parallel.dp.shard_map_train: build with jit=False and
                # hand the returned step to it (module docstring there)
                raise ValueError(
                    "axis_name requires jit=False: hand the returned "
                    "train_step to parallel.dp.shard_map_train, which "
                    "wraps it in shard_map over the mesh axis")
            if mesh is not None:
                # rule-sharded single program: params/opt-state laid out
                # by the model family's partition-rule table, env batch
                # over data, and the step traced with the mesh bound so
                # rollout's with_sharding_constraint pins the trajectory
                from .parallel import sharding as shardlib
                from .parallel.dp import carry_sharding_prefix
                from .parallel.mesh import (DATA_AXIS, env_sharded,
                                            replicated)
                if cfg.n_envs % mesh.shape[DATA_AXIS]:
                    raise ValueError(
                        f"n_envs={cfg.n_envs} not divisible by the mesh's "
                        f"data axis size {mesh.shape[DATA_AXIS]}")
                rules = shardlib.rules_for(cfg)
                state_sh = shardlib.tree_shardings(train_state, rules, mesh)
                env = env_sharded(mesh)
                rep = replicated(mesh)
                carry_sh = carry_sharding_prefix(mesh)
                jit_step = jax.jit(
                    shardlib.bind_mesh(step_fn, mesh),
                    in_shardings=(state_sh, carry_sh, env, rep, env),
                    out_shardings=(state_sh, carry_sh, rep),
                    donate_argnums=(0, 1))
                train_state = shardlib.put_tree(train_state, state_sh)
                carry = RolloutCarry(
                    env_state=shardlib.put_global(carry.env_state, env),
                    obs=shardlib.put_global(carry.obs, env),
                    mask=shardlib.put_global(carry.mask, env),
                    key=shardlib.put_global(carry.key, rep))
                traces = shardlib.put_global(traces, env)
                if faults is not None:
                    faults = shardlib.put_global(faults, env)
            else:
                # state and carry are replaced every iteration in run(),
                # so donating them halves live copies in the benchmarked
                # hot loop
                jit_step = jax.jit(step_fn, donate_argnums=(0, 1))
        else:
            if mesh is not None:
                raise ValueError("mesh requires jit=True (the rule-table "
                                 "shardings are jit in/out_shardings)")
            jit_step = step_fn
        return Experiment(cfg=cfg, env_params=env_params, windows=windows,
                          traces=traces, net=net, apply_fn=apply_fn,
                          train_state=train_state, train_step=jit_step,
                          carry=carry, key=key, source=source,
                          train_step_raw=step_fn, faults=faults,
                          domains=domains, mesh=mesh)

    @property
    def steps_per_iteration(self) -> int:
        algo_cfg = self.cfg.ppo if self.cfg.algo == "ppo" else self.cfg.a2c
        return algo_cfg.n_steps * self.cfg.n_envs

    def run_fused(self, iterations: int):
        """Run ``iterations`` train steps as ONE on-device program — a
        ``lax.scan`` over the train step, the Podracer outer loop taken
        all the way (SURVEY.md §7 hard part (d): per-step host↔device
        sync at zero). Under the TPU tunnel every dispatch is a remote
        RPC, so the per-iteration host loop of :meth:`run` bounds
        sustained throughput by RPC latency, not chip time; one fused
        dispatch removes that bound (and is how ``bench.py`` measures the
        chip rather than the tunnel). No logging / eval / checkpoint /
        window-streaming hooks run inside — use :meth:`run` when you need
        them. Returns the LAST iteration's metrics.

        RNG: ONE split of ``self.key`` is fanned out into ``iterations``
        subkeys up front, whereas :meth:`run`'s per-step loop splits
        ``self.key`` sequentially every iteration — the two derive
        DIFFERENT key streams. A fused (or ``fused_chunk > 1``) run is
        therefore deterministic and reproducible, but NOT bit-identical
        to the same-seed per-step run."""
        if self._fused_jit is None:
            step = self.train_step_raw
            if step is None:
                raise ValueError("run_fused needs the raw step "
                                 "(Experiment.build stores it)")
            if self.train_step is self.train_step_raw:
                # built with jit=False — the shard_map/axis_name path
                # (build() directs that path to dp.shard_map_train);
                # jitting the raw step here would hit an unbound
                # collective axis at trace time with an opaque error
                raise ValueError(
                    "run_fused supports the plain jitted single-program "
                    "build; a jit=False/axis_name experiment runs its "
                    "step under parallel.dp.shard_map_train instead")

            def many(state, carry, traces, keys, faults):
                def body(c, sk):
                    s, ca = c
                    s, ca, _ = step(s, ca, traces, sk, faults)
                    return (s, ca), None

                (state, carry), _ = jax.lax.scan(
                    body, (state, carry), keys[:-1])
                # final step outside the scan returns its metrics without
                # stacking [k] metric arrays for the whole run
                state, carry, metrics = step(state, carry, traces,
                                             keys[-1], faults)
                return state, carry, metrics

            # one wrapper; jax.jit itself caches one compile per distinct
            # keys length — no second cache layer needed
            if self.mesh is not None:
                # fused-under-mesh rides the SAME partition-rule table
                # as the per-step build (not input-inferred shardings,
                # which would silently fall back to whatever layout the
                # donated buffers happened to carry): params/opt-state
                # by the model family's rules, env batch over data,
                # fanned-out keys replicated — one sharding authority
                # for both step cadences (ROADMAP residual from PR 9)
                from .parallel import sharding as shardlib
                from .parallel.dp import carry_sharding_prefix
                from .parallel.mesh import env_sharded, replicated
                rules = shardlib.rules_for(self.cfg)
                state_sh = shardlib.tree_shardings(self.train_state,
                                                   rules, self.mesh)
                env = env_sharded(self.mesh)
                rep = replicated(self.mesh)
                carry_sh = carry_sharding_prefix(self.mesh)
                self._fused_jit = jax.jit(
                    shardlib.bind_mesh(many, self.mesh),
                    in_shardings=(state_sh, carry_sh, env, rep, env),
                    out_shardings=(state_sh, carry_sh, rep),
                    donate_argnums=(0, 1))
            else:
                self._fused_jit = jax.jit(many, donate_argnums=(0, 1))
        self.key, sub = jax.random.split(self.key)
        keys = jax.random.split(sub, iterations)
        self.train_state, self.carry, metrics = self._fused_jit(
            self.train_state, self.carry, self.traces, keys, self.faults)
        return metrics

    def _cut_windows(self, cursor: int) -> None:
        """Re-cut the env windows at tiling position ``cursor`` (same
        shapes → NO recompilation; the jitted step takes traces as an
        argument). Sharding of the previous traces is preserved so DP runs
        stay sharded."""
        self.window_cursor = cursor
        windows = (make_domain_windows(self.cfg, self.domains, cursor)
                   if self.domains is not None
                   else make_env_windows(self.cfg, self.source, cursor))
        sim_params = (self.env_params.sim
                      if isinstance(self.env_params, EnvParams)
                      else self.env_params.pod_sim)
        traces = stack_traces(windows, sim_params)
        self.traces = jax.tree.map(
            lambda new, old: jax.device_put(new, old.sharding),
            traces, self.traces)
        self.windows = windows

    def advance_windows(self) -> None:
        """Rotate every env onto the next ``n_envs`` windows of the source
        tiling and reset episodes (window streaming — a long run covers
        the whole trace, VERDICT r1 missing #3). Fault schedules are
        window-independent (episode-relative times) and stay fixed: a
        streaming run sees every window under its env's draw of the
        fault distribution."""
        self._cut_windows(self.window_cursor + self.cfg.n_envs)
        self.key, carry_key = jax.random.split(self.key)
        carry = init_carry(self.env_params, self.traces, carry_key,
                           self.faults)
        self.carry = jax.tree.map(
            lambda new, old: jax.device_put(new, old.sharding),
            carry, self.carry)

    def save_checkpoint(self, ckpt, step: int | None = None,
                        meta: dict | None = None, force: bool = False) -> bool:
        """Persist train state + rollout PRNG key + rollout carry
        (``checkpoint.Checkpointer``). Pass ``force=True`` to overwrite an
        existing checkpoint at the same step (e.g. a PBT exploit that copies
        weights without advancing the optimizer)."""
        step = int(self.train_state.step) if step is None else step
        meta = dict(meta or {}, window_cursor=self.window_cursor)
        return ckpt.save(step, self.train_state, key=self.key,
                         extra=self.carry, meta=meta, force=force)

    def restore_checkpoint(self, ckpt, step: int | None = None) -> dict:
        """Restore train state + key + rollout carry in place; returns the
        checkpoint meta. With the carry (and, for streaming runs, the
        window cursor) restored, a resumed ``run()`` reproduces the
        uninterrupted run exactly. The experiment must be built from the
        same config (shapes must match)."""
        self.train_state, key, carry, meta = ckpt.restore(
            self.train_state, self.key, self.carry, step)
        if key is not None:
            self.key = key
        if carry is not None:
            self.carry = carry
        cursor = int((meta or {}).get("window_cursor", 0))
        if cursor != self.window_cursor:
            self._cut_windows(cursor)
        return meta

    def scale_lr(self, scale: float) -> None:
        """Swap the optimizer for one at ``scale`` × the config LR (the
        watchdog's deterministic rollback decay). Rebinding ``tx`` changes
        the TrainState's static treedef, so the next step re-traces — an
        acceptable cost bounded by ``max_rollbacks``. Adam's moment state
        is LR-independent, so the restored opt_state carries over."""
        algo_cfg = self.cfg.ppo if self.cfg.algo == "ppo" else self.cfg.a2c
        scaled = dataclasses.replace(algo_cfg, lr=algo_cfg.lr * scale)
        if self.cfg.algo == "ppo":
            tx = make_optimizer(scaled)
        else:
            from .algos.a2c import make_optimizer as a2c_opt
            tx = a2c_opt(scaled)
        self.train_state = self.train_state.replace(tx=tx)

    def fold_key(self, n: int) -> None:
        """Deterministically diverge the rollout RNG stream (watchdog
        retry: replaying the restored key bit-exactly would re-sample the
        trajectory that just diverged)."""
        self.key = jax.random.fold_in(self.key, n)

    def run(self, iterations: int | None = None, log_every: int = 0,
            logger: Callable[[int, dict], None] | None = None,
            ckpt=None, ckpt_every: int = 0,
            eval_every: int = 0,
            eval_fn: "Callable[[int], dict] | None" = None,
            eval_logger: Callable[[int, dict], None] | None = None,
            fused_chunk: int = 1, watchdog=None, injector=None,
            telemetry=None) -> dict:
        """Run the host training loop; returns summary metrics. Pass a
        ``checkpoint.Checkpointer`` + cadence to persist while training.

        ``telemetry`` (:class:`obs.RunTelemetry`) span-traces the loop:
        per-iteration phase breakdown (step dispatch / sync / eval /
        ckpt / resample via its ``SectionTimer``), an ``iteration``
        event at every LOGGED iteration carrying the metrics dict this
        loop already materialized — telemetry adds ZERO host syncs of
        its own — and, when its alarms are armed, the jitted dispatch
        runs under the recompile/transfer production alarms (a rollback
        retry's LR-rescale re-trace is granted amnesty).

        ``eval_fn(i) -> dict`` runs every ``eval_every`` iterations (and at
        the last one) — the in-training quality probe (e.g. a held-out JCT
        replay); its rows go to ``eval_logger`` (NOT ``logger``: eval rows
        have a different schema than train rows and MetricsLogger pins one
        schema per stream) and into the summary's ``eval_history``.

        ``fused_chunk > 1`` dispatches that many train steps as ONE
        on-device :meth:`run_fused` program between hook boundaries
        (under the TPU tunnel each dispatch is a remote RPC — the chunk
        amortizes it). Every log/eval/ckpt/resample cadence must be a
        multiple of the chunk, so hooks fire exactly as in the per-step
        loop; metrics logged at a boundary are the boundary ITERATION's.
        NOTE: chunked and per-step runs derive their rollout RNG keys
        DIFFERENTLY (see :meth:`run_fused`), so a ``fused_chunk > 1`` run
        is deterministic but NOT bit-identical to the same-seed per-step
        run.

        ``watchdog`` (:class:`resilience.DivergenceWatchdog`, requires
        ``ckpt``) checks each materialized iteration's metrics and rolls
        back to the last good checkpoint on divergence — after a rollback
        the replayed iterations are re-logged, so the history/CSV shows
        the retry honestly. With ``fused_chunk > 1`` only chunk-boundary
        metrics exist to check. ``injector``
        (:class:`resilience.FaultInjector`) drives the fault-injection
        hooks (``nan-grad`` after the matching iteration's step,
        ``corrupt-ckpt`` after the matching iteration's save). A
        :class:`resilience.DivergenceError` propagates to the caller once
        the watchdog's rollback budget is exhausted."""
        iterations = iterations or self.cfg.iterations
        if watchdog is not None and ckpt is None:
            raise ValueError(
                "watchdog rollback needs a checkpoint store; pass ckpt= "
                "(and a ckpt_every cadence so rollbacks stay short)")
        if fused_chunk > 1:
            cadences = {"log_every": log_every,
                        # ckpt_every is only a live cadence when a
                        # checkpointer is attached (the CLI default is 50
                        # even without --ckpt-dir)
                        "ckpt_every": ckpt_every if ckpt is not None else 0,
                        "eval_every": eval_every if eval_fn is not None
                        else 0,
                        "resample_every": self.cfg.resample_every,
                        "iterations": iterations}
            bad = {k: v for k, v in cadences.items()
                   if v and v % fused_chunk}
            if bad:
                raise ValueError(
                    f"fused_chunk={fused_chunk} must divide every active "
                    f"cadence and the iteration count; offending: {bad}")
        history = []
        eval_history = []
        t0 = time.monotonic()
        stride = fused_chunk if fused_chunk > 1 else 1
        # telemetry spans: with no telemetry attached, a throwaway timer
        # keeps the section sites branch-free (its cost is two
        # perf_counter reads per section — noise next to a dispatch)
        from .obs.trace import tracer_of
        from .utils.profiling import SectionTimer
        sections = (telemetry.sections if telemetry is not None
                    else SectionTimer())
        tracer = tracer_of(telemetry)
        if telemetry is not None:
            telemetry.run_start(
                loop="experiment", config=self.cfg.name,
                algo=self.cfg.algo, iterations=iterations,
                n_envs=self.cfg.n_envs,
                steps_per_iteration=self.steps_per_iteration,
                fused_chunk=fused_chunk)
        if watchdog is not None and ckpt.latest_step() is None:
            # guarantee a rollback target before the first periodic save
            self.save_checkpoint(ckpt, meta={"iteration": -1})
        # under a mesh build the step pins its key argument to the
        # replicated sharding; a freshly split subkey is committed to the
        # default device, so the jit would replicate it with an implicit
        # device-to-device copy INSIDE the guarded dispatch (a transfer
        # alarm). Place it explicitly here, outside the guard, like every
        # other input placed at build time.
        key_rep = None
        if self.mesh is not None:
            from .parallel.mesh import replicated
            key_rep = replicated(self.mesh)
        i = 0
        while i < iterations:
            # hooks see the chunk's last iteration (== i when unchunked);
            # chunked boundaries sit at b = k*chunk - 1, so the phase-0
            # cadence form (b % L == 0) would never fire there; the (b+1)
            # form is the same cadence shifted to boundary-aligned phase
            b = i + stride - 1
            if telemetry is not None:
                telemetry.begin_iteration(b)
            guard = (telemetry.dispatch(b) if telemetry is not None
                     else contextlib.nullcontext())
            # "step" is the async dispatch only — the device work it
            # enqueues materializes in the "sync" span's device_get
            if fused_chunk > 1:
                with sections("step"), tracer.span("step"), guard:
                    metrics = self.run_fused(fused_chunk)
            else:
                self.key, sub = jax.random.split(self.key)
                if key_rep is not None:
                    sub = jax.device_put(sub, key_rep)
                with sections("step"), tracer.span("step"), guard:
                    self.train_state, self.carry, metrics = self.train_step(
                        self.train_state, self.carry, self.traces, sub,
                        self.faults)
            if injector is not None:
                metrics = injector.poison_nan(self, b, metrics)
            log_hit = log_every and (
                (b + 1) % log_every == 0 if fused_chunk > 1
                else b % log_every == 0)
            want_log = bool(log_every) and (log_hit or b == iterations - 1)
            # host consumers (watchdog + logger + telemetry) share ONE
            # batched device_get: per-field float() is a separate
            # blocking transfer each, and the watchdog path pays it every
            # iteration (jsan host-sync review, PR 3)
            m = None
            if watchdog is not None or want_log:
                with sections("sync"), tracer.span("sync"):
                    m = {k: float(v) for k, v in
                         jax.device_get(metrics)._asdict().items()}
            if watchdog is not None:
                reason = watchdog.check(m)
                if reason is not None:
                    event = watchdog.rollback(self, ckpt, b, reason)
                    if telemetry is not None:
                        # the retry's LR rescale rebinds tx and re-traces
                        # the step — a legitimate compile, not an alarm
                        telemetry.iteration_aborted(
                            b, f"rollback: {reason}")
                    i = event.resume_iteration
                    continue
            if want_log:
                history.append({"iteration": b, **m})
                if logger is not None:
                    logger(b, m)
            if eval_fn is not None and eval_every and \
                    ((b + 1) % eval_every == 0 or b == iterations - 1):
                with sections("eval"), tracer.span("eval"):
                    em = dict(eval_fn(b))
                eval_history.append({"iteration": b, **em})
                if eval_logger is not None:
                    eval_logger(b, em)
            if ckpt is not None and ckpt_every and \
                    ((b + 1) % ckpt_every == 0 or b == iterations - 1):
                with sections("ckpt"), tracer.span("ckpt"):
                    self.save_checkpoint(ckpt, meta={"iteration": b})
                if injector is not None:
                    injector.corrupt_after_save(ckpt, b)
            if self.cfg.resample_every and \
                    (b + 1) % self.cfg.resample_every == 0 and \
                    b != iterations - 1:
                with sections("resample"), tracer.span("resample"):
                    self.advance_windows()
            if telemetry is not None:
                telemetry.end_iteration(
                    b, m if want_log else None,
                    stride * self.steps_per_iteration)
            i += stride
        jax.block_until_ready(self.train_state.params)
        wall = time.monotonic() - t0
        total_env_steps = iterations * self.steps_per_iteration
        out = {"wall_s": wall, "iterations": iterations,
               "env_steps": total_env_steps,
               "env_steps_per_sec": total_env_steps / wall,
               "window_cursor": self.window_cursor,
               "history": history}
        if watchdog is not None:
            out["rollbacks"] = watchdog.n_rollbacks
            out["rollback_events"] = [e.as_dict() for e in watchdog.events]
        if eval_history:
            out["eval_history"] = eval_history
        if telemetry is not None:
            telemetry.run_end(
                iterations=iterations, wall_s=round(wall, 6),
                env_steps=total_env_steps,
                env_steps_per_sec=round(out["env_steps_per_sec"], 3),
                rollbacks=(watchdog.n_rollbacks
                           if watchdog is not None else 0))
        return out

    def run_async(self, iterations: int | None = None, *,
                  groups=None, staleness_bound: int = 1,
                  queue_capacity: int = 2, log_every: int = 0,
                  logger: Callable[[int, dict], None] | None = None,
                  ckpt=None, ckpt_every: int = 0, eval_every: int = 0,
                  eval_fn: "Callable[[int], dict] | None" = None,
                  eval_logger: Callable[[int, dict], None] | None = None,
                  telemetry=None) -> dict:
        """Opt-in async actor–learner loop (:mod:`.async_engine`):
        rollout collection on the actor device group overlaps the
        minibatch update on the learner group, coupled by a bounded
        device-side trajectory queue under an explicit staleness bound
        (``staleness_bound=0`` reproduces :meth:`run` bit-identically).
        The hook surface matches :meth:`run`; checkpoints and window
        resamples run at drained-queue barriers so :meth:`restore_checkpoint`
        + a resumed ``run_async`` stays deterministic. ``groups`` is a
        :class:`~.parallel.groups.DeviceGroups` (default: split the
        visible devices). NOTE: construction moves this experiment's
        state onto the group meshes; reuse the runner (or rebuild) rather
        than mixing with :meth:`run` afterwards. Watchdog/injector
        resilience hooks and ``fused_chunk`` are sync-path-only."""
        from .async_engine import AsyncRunner
        runner = AsyncRunner(self, groups=groups,
                             staleness_bound=staleness_bound,
                             queue_capacity=queue_capacity)
        return runner.run(iterations, log_every=log_every, logger=logger,
                          ckpt=ckpt, ckpt_every=ckpt_every,
                          eval_every=eval_every, eval_fn=eval_fn,
                          eval_logger=eval_logger, telemetry=telemetry)


@dataclasses.dataclass
class PopulationExperiment:
    """Config 5 assembly: a population of PPO members trained as one
    vmapped+pop-sharded program, with host-side PBT exploit/explore
    (SURVEY.md §3.5). Each member runs the per-member config ``cfg`` (for
    the driver's config 5 that is the hierarchical 4-pod agent,
    ``configs.HIER_PBT_MEMBER``)."""
    cfg: ExperimentConfig
    n_pop: int
    env_params: EnvParams
    traces: Any              # [E, ...] batched device Trace (shared)
    apply_fn: Callable
    states: Any              # stacked MemberState [P, ...]
    carries: Any             # stacked RolloutCarry [P, ...]
    hparams: Any             # HParams stacked [P]
    keys: jax.Array          # [P, 2] per-member rollout keys
    pop_step: Callable       # jitted
    controller: Any          # PBTController
    windows: list = None     # host ArrayTrace windows (shared; eval reuse)
    mesh: Any = None         # unified Mesh when members ride the pop axis
    state_sharding: Any = None    # rule-resolved member-stack layout
    hparam_sharding: Any = None   # [P] hparam layout (pop axis)
    # batched per-member per-env FaultSchedule [P, E, ...] (cfg.faults),
    # or None: each member draws its own seeded (seed, member, env)
    # schedules, so the population covers the regime P×E-wide. Not
    # checkpointed — deterministically regenerated from cfg at build
    faults: Any = None

    @staticmethod
    def build(cfg: ExperimentConfig, n_pop: int = 4, mesh=None,
              pbt_cfg=None) -> "PopulationExperiment":
        from .parallel.pbt import PBTConfig, PBTController
        from .parallel.population import (init_member, jit_population_step,
                                          make_population_step,
                                          sample_hparams, stack_members)
        if cfg.algo != "ppo":
            raise ValueError(
                f"PopulationExperiment trains PPO members (PBT explores "
                f"PPO hyperparameters); config {cfg.name!r} has "
                f"algo={cfg.algo!r}")
        if cfg.domains:
            # configs.MODE_REFUSALS carries the pbt×domains row for the
            # CLI; programmatic builders must refuse just as loudly
            raise ValueError(
                "PopulationExperiment does not thread domain schedules: "
                "per-member domain draws would need member-indexed trace "
                "windows through the population stack (cfg.domains=None; "
                "cfg.faults is supported)")
        pbt_cfg = pbt_cfg or PBTConfig(seed=cfg.seed)
        resolve_geometry(cfg.ppo.n_epochs, cfg.ppo.n_minibatches,
                         cfg.ppo.minibatch_size,
                         cfg.ppo.n_steps * cfg.n_envs)
        env_params, windows, traces, net, apply_fn, extra, _source = \
            build_stack(cfg)
        # traces stay unstacked [E, ...]: every member trains on the same
        # env windows (PBT fitness comparability) and the vmapped step
        # broadcasts them (in_axes=None) instead of holding n_pop copies

        # per-member per-env fault schedules [P, E, ...]: member p's env e
        # draws from (seed, p, e), so the population covers the regime
        # P×E-wide while every member trains on the SAME trace windows
        # (fitness stays comparable in expectation — same regime,
        # independent draws)
        member_faults = None
        fp = getattr(env_params, "fault_process", None)
        if fp is not None:
            from .sim.faults import stack_fault_schedules
            horizon_s = fault_horizon(windows)
            member_faults = [
                stack_fault_schedules(
                    [sample_fault_schedule(cfg.n_nodes, fp,
                                           (cfg.seed, p, e), horizon_s)
                     for e in range(cfg.n_envs)])
                for p in range(n_pop)]

        key = jax.random.PRNGKey(cfg.seed)
        member_keys = jax.random.split(key, n_pop * 3).reshape(n_pop, 3, 2)
        members, carries = [], []
        for p in range(n_pop):
            carry = init_carry(env_params, traces, member_keys[p, 1],
                               member_faults[p] if member_faults is not None
                               else None)
            ex_obs, ex_mask = jax.tree.map(lambda x: x[:1],
                                           (carry.obs, carry.mask))
            members.append(init_member(net, member_keys[p, 0], ex_obs,
                                       ex_mask, cfg.ppo, extra))
            carries.append(carry)
        states = stack_members(members)
        stacked_carries = stack_members(carries)
        hparams = sample_hparams(cfg.ppo, n_pop, cfg.seed)
        keys = member_keys[:, 2]
        faults = (stack_members(member_faults)
                  if member_faults is not None else None)

        pop_step = make_population_step(apply_fn, env_params, cfg.ppo,
                                        with_faults=faults is not None)
        if mesh is not None:
            if n_pop % mesh.shape["pop"] != 0:
                raise ValueError(f"n_pop={n_pop} not divisible by pop axis "
                                 f"size {mesh.shape['pop']}")
            if cfg.n_envs % mesh.shape["data"] != 0:
                raise ValueError(f"n_envs={cfg.n_envs} not divisible by "
                                 f"data axis size {mesh.shape['data']}")
            # member-state layout resolved per-leaf from the same
            # partition-rule table the single-run path uses: pop axis on
            # the member stack, model axis on kernels within each member
            from .parallel import sharding as shardlib
            from .parallel.population import population_shardings
            rules = shardlib.rules_for(cfg)
            jitted = jit_population_step(mesh, pop_step, states=states,
                                         rules=rules,
                                         with_faults=faults is not None)
            st_sh, ca_sh, tr_sh, key_sh, hp_sh = population_shardings(
                mesh, states=states, rules=rules)
            states = jax.device_put(states, st_sh)
            stacked_carries = jax.device_put(stacked_carries, ca_sh)
            traces = jax.device_put(traces, tr_sh)
            keys = jax.device_put(keys, key_sh)
            hparams = jax.device_put(hparams, hp_sh)
            if faults is not None:
                from .parallel.mesh import pop_env_sharded
                faults = jax.device_put(faults, pop_env_sharded(mesh))
            return PopulationExperiment(
                cfg=cfg, n_pop=n_pop, env_params=env_params,
                traces=traces, apply_fn=apply_fn, states=states,
                carries=stacked_carries, hparams=hparams, keys=keys,
                pop_step=jitted,
                controller=PBTController(n_pop, pbt_cfg),
                windows=windows, mesh=mesh, state_sharding=st_sh,
                hparam_sharding=hp_sh, faults=faults)
        jitted = jax.jit(pop_step, donate_argnums=(0, 1))
        return PopulationExperiment(
            cfg=cfg, n_pop=n_pop, env_params=env_params, traces=traces,
            apply_fn=apply_fn, states=states, carries=stacked_carries,
            hparams=hparams, keys=keys, pop_step=jitted,
            controller=PBTController(n_pop, pbt_cfg), windows=windows,
            faults=faults)

    @property
    def steps_per_iteration(self) -> int:
        return self.cfg.ppo.n_steps * self.cfg.n_envs * self.n_pop

    def best_member(self) -> int:
        """Index of the fittest member by windowed mean fitness (NaN ranks
        worst — same ordering PBT exploit uses). Raises when the controller
        holds no recorded fitness (e.g. a population checkpoint saved
        before controller state was persisted): argmax over the all-zero
        default would silently crown member 0."""
        import numpy as np
        if self.controller._fitness_n == 0 and not self.controller.history:
            raise ValueError(
                "population has no recorded fitness (pre-controller-state "
                "checkpoint, or no training iterations ran); pass an "
                "explicit member index instead")
        f = np.asarray(self.controller.mean_fitness, np.float64)
        return int(np.nanargmax(np.where(np.isnan(f), -np.inf, f)))

    def member_eval_view(self, m: int | None = None):
        """Experiment-like view of one population member for the eval
        harness (``eval.jct_report(pop.member_eval_view())``): the member's
        params indexed out of the stacked MemberState (materialized on the
        default device — the eval replay is unsharded), sharing the
        population's windows/traces/env_params. Default: fittest member."""
        import types
        m = self.best_member() if m is None else m
        if not 0 <= m < self.n_pop:
            raise ValueError(f"member {m} out of range [0, {self.n_pop})")
        params = jax.tree.map(
            lambda x: jax.device_put(x[m], jax.devices()[0]),
            self.states.params)
        return types.SimpleNamespace(
            cfg=self.cfg, env_params=self.env_params, windows=self.windows,
            traces=self.traces, apply_fn=self.apply_fn,
            train_state=types.SimpleNamespace(params=params), member=m)

    def save_checkpoint(self, ckpt, step: int | None = None,
                        meta: dict | None = None, force: bool = False) -> bool:
        """Persist the whole population (member stack + carries + hparams +
        rollout keys) in one checkpoint, plus the full PBT controller state
        (RNG, fitness window, decision history) in meta — so a resumed run
        reproduces the uninterrupted run's exploit decisions bit-for-bit
        (VERDICT r2 weak #2)."""
        import numpy as np
        extra = {"carries": self.carries, "keys": self.keys,
                 "hparams": self.hparams}
        step = (int(np.max(np.asarray(self.states.step)))
                if step is None else step)
        meta = dict(meta or {}, pbt_events=len(self.controller.history),
                    pbt_controller=self.controller.state_dict())
        return ckpt.save(step, self.states, extra=extra, meta=meta,
                         force=force)

    def restore_checkpoint(self, ckpt, step: int | None = None) -> dict:
        extra_t = {"carries": self.carries, "keys": self.keys,
                   "hparams": self.hparams}
        self.states, _key, extra, meta = ckpt.restore(
            self.states, None, extra_t, step)
        if extra is not None:
            # structures restore into the template's treedefs, so these are
            # already RolloutCarry / HParams
            self.carries = extra["carries"]
            self.keys = extra["keys"]
            self.hparams = extra["hparams"]
        self.controller.load_state_dict((meta or {}).get("pbt_controller"))
        return meta

    def scale_lr(self, scale: float) -> None:
        """Watchdog rollback decay for the population: per-member LRs live
        in the traced :class:`~parallel.population.HParams` (not the
        optimizer), so the decay is one array multiply — no re-trace."""
        self.hparams = self.hparams._replace(lr=self.hparams.lr * scale)

    def run_async(self, iterations: int | None = None, *,
                  groups=None, staleness_bound: int = 1,
                  queue_capacity: int = 2, log_every: int = 0,
                  logger: Callable[[int, dict], None] | None = None,
                  ckpt=None, ckpt_every: int = 0, eval_every: int = 0,
                  eval_fn: "Callable[[int], dict] | None" = None,
                  eval_logger: Callable[[int, dict], None] | None = None,
                  telemetry=None) -> dict:
        """Opt-in async actor–learner loop over the whole population
        (:class:`~.async_engine.AsyncPopulationRunner`): the vmapped
        member rollout overlaps the vmapped member update, PBT
        exploit/explore fires at drained-queue barriers, and
        ``staleness_bound=0`` reproduces :meth:`run` bit-identically
        (non-mesh build — construction requires ``mesh=None`` and places
        member stacks on the group meshes itself). Deep bounds want
        ``cfg.ppo.correction="vtrace"`` so stale batches do not skew the
        cross-member fitness ranking. Watchdog/injector chaos drills are
        sync-path-only."""
        from .async_engine import AsyncPopulationRunner
        runner = AsyncPopulationRunner(self, groups=groups,
                                       staleness_bound=staleness_bound,
                                       queue_capacity=queue_capacity)
        return runner.run(iterations, log_every=log_every, logger=logger,
                          ckpt=ckpt, ckpt_every=ckpt_every,
                          eval_every=eval_every, eval_fn=eval_fn,
                          eval_logger=eval_logger, telemetry=telemetry)

    def fold_key(self, n: int) -> None:
        """Deterministically diverge every member's rollout RNG stream
        (watchdog retry — same contract as :meth:`Experiment.fold_key`)."""
        self.keys = jax.vmap(lambda k: jax.random.fold_in(k, n))(self.keys)

    def run(self, iterations: int | None = None, log_every: int = 0,
            logger: Callable[[int, dict], None] | None = None,
            ckpt=None, ckpt_every: int = 0,
            eval_every: int = 0,
            eval_fn: "Callable[[int], dict] | None" = None,
            eval_logger: Callable[[int, dict], None] | None = None,
            watchdog=None, injector=None, telemetry=None) -> dict:
        """Train the population; PBT exploit/explore fires every
        ``controller.cfg.ready_iters`` iterations. Returns summary metrics
        including per-member final fitness and the PBT event log.

        ``eval_fn(i) -> dict`` runs every ``eval_every`` iterations (and
        at the last one), AFTER the iteration's fitness is recorded — so
        a probe may rank members via :meth:`best_member` (the in-training
        quality probe behind the PBT ``--keep-best`` path: the
        population-drift failure mode has cost a best-population twice,
        VERDICT r5 weak #2). Rows go to ``eval_logger`` and the summary's
        ``eval_history`` — same contract as :meth:`Experiment.run`.

        ``watchdog`` (requires ``ckpt``) handles only the CATASTROPHIC
        divergence case — every member non-finite, nobody left to re-seed
        from — by rolling the whole population back to the last good
        checkpoint; a single diverged member is PBT's job (exploit treats
        non-finite fitness as dead and re-seeds it from the best member).
        ``injector`` drives ``nan-grad`` (member poisoning; spec
        ``rank`` = member index) and ``corrupt-ckpt`` faults."""
        iterations = iterations or self.cfg.iterations
        if watchdog is not None and ckpt is None:
            raise ValueError(
                "watchdog rollback needs a checkpoint store; pass ckpt= "
                "(and a ckpt_every cadence so rollbacks stay short)")
        split_all = jax.jit(jax.vmap(lambda k: jax.random.split(k)))
        history = []
        eval_history = []
        t0 = time.monotonic()
        from .obs.trace import tracer_of
        from .utils.profiling import SectionTimer
        sections = (telemetry.sections if telemetry is not None
                    else SectionTimer())
        tracer = tracer_of(telemetry)
        if telemetry is not None:
            telemetry.run_start(
                loop="population", config=self.cfg.name,
                n_pop=self.n_pop, iterations=iterations,
                n_envs=self.cfg.n_envs,
                steps_per_iteration=self.steps_per_iteration)
        if watchdog is not None and ckpt.latest_step() is None:
            self.save_checkpoint(ckpt, meta={"iteration": -1})
        i = 0
        while i < iterations:
            if telemetry is not None:
                telemetry.begin_iteration(i)
            guard = (telemetry.dispatch(i) if telemetry is not None
                     else contextlib.nullcontext())
            both = split_all(self.keys)
            self.keys, subs = both[:, 0], both[:, 1]
            step_args = (self.states, self.carries, self.traces, subs,
                         self.hparams)
            if self.faults is not None:
                step_args = step_args + (self.faults,)
            with sections("step"), tracer.span("step"), guard:
                self.states, self.carries, metrics = self.pop_step(
                    *step_args)
            if injector is not None:
                metrics = injector.poison_nan_member(self, i, metrics)
            fitness = metrics.mean_reward
            if watchdog is not None:
                reason = watchdog.check_population(fitness)
                if reason is not None:
                    event = watchdog.rollback(self, ckpt, i, reason)
                    if telemetry is not None:
                        telemetry.iteration_aborted(
                            i, f"rollback: {reason}")
                    i = event.resume_iteration
                    continue
            self.controller.record(fitness)
            out = self.controller.maybe_update(i, self.states, self.hparams)
            if out is not None:
                self.states, self.hparams, decision = out
                if self.mesh is not None:
                    # the exploit gather + host-side explore hand back
                    # arrays without the pop-axis commitment; re-pin them
                    # HERE — outside the next dispatch's transfer guard —
                    # or the jit replicates them with an implicit
                    # device-to-device copy (transfer alarm)
                    self.states = jax.device_put(self.states,
                                                 self.state_sharding)
                    self.hparams = jax.device_put(self.hparams,
                                                  self.hparam_sharding)
                if telemetry is not None:
                    telemetry.emit(
                        "pbt_exploit", iteration=i,
                        exploited=int(decision.exploited.sum()),
                        src=[int(s) for s in decision.src])
            m = None
            if log_every and (i % log_every == 0 or i == iterations - 1):
                # flatten per-member values to suffixed scalar columns so
                # the CSV stays pandas/TensorBoard-ingestible (ADVICE r1).
                # ONE batched device_get for the whole [P]-metrics tuple:
                # per-element float() was n_fields x P separate blocking
                # transfers per logged iteration (jsan host-sync review)
                m = {}
                with sections("sync"), tracer.span("sync"):
                    got = jax.device_get(metrics)._asdict()
                for k, v in got.items():
                    vals = [float(x) for x in v]
                    m.update({f"{k}_{p}": x for p, x in enumerate(vals)})
                    m[f"{k}_mean"] = sum(vals) / len(vals)
                history.append({"iteration": i, **m})
                if logger is not None:
                    logger(i, m)
            if eval_fn is not None and eval_every and \
                    ((i + 1) % eval_every == 0 or i == iterations - 1):
                with sections("eval"), tracer.span("eval"):
                    em = dict(eval_fn(i))
                eval_history.append({"iteration": i, **em})
                if eval_logger is not None:
                    eval_logger(i, em)
            if ckpt is not None and ckpt_every and \
                    ((i + 1) % ckpt_every == 0 or i == iterations - 1):
                with sections("ckpt"), tracer.span("ckpt"):
                    self.save_checkpoint(ckpt, meta={"iteration": i})
                if injector is not None:
                    injector.corrupt_after_save(ckpt, i)
            if telemetry is not None:
                telemetry.end_iteration(i, m, self.steps_per_iteration)
            i += 1
        jax.block_until_ready(self.states.params)
        wall = time.monotonic() - t0
        total_env_steps = iterations * self.steps_per_iteration
        out = {"wall_s": wall, "iterations": iterations,
               "env_steps": total_env_steps,
               "env_steps_per_sec": total_env_steps / wall,
               "final_fitness": [float(f) for f in
                                 self.controller.mean_fitness],
               "pbt_events": len(self.controller.history),
               "history": history}
        if watchdog is not None:
            out["rollbacks"] = watchdog.n_rollbacks
            out["rollback_events"] = [e.as_dict() for e in watchdog.events]
        if eval_history:
            out["eval_history"] = eval_history
        if telemetry is not None:
            telemetry.run_end(
                iterations=iterations, wall_s=round(wall, 6),
                env_steps=total_env_steps,
                env_steps_per_sec=round(out["env_steps_per_sec"], 3),
                pbt_events=len(self.controller.history),
                rollbacks=(watchdog.n_rollbacks
                           if watchdog is not None else 0))
        return out
